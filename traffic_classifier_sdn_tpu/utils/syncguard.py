"""syncguard: a runtime witness for the host↔device boundary.

The static side (``analysis_static/graftsync.py``) proves the serve
hot paths sync-clean over the calls it can SEE and exports the
expected-sync ledger (``docs/artifacts/hot_path_sync_budget.json``);
this module is the dynamic cross-check, in the locktrace mold. Opt-in
(``TCSDN_SYNCGUARD=1``, or the tier-1 fixture over the
pipeline/incremental/degrade/drift/openset suites): while installed,
the process-wide conversion seams are wrapped in site-keyed counting
shims —

- ``np.asarray`` / ``np.array`` of a ``jax.Array``  → a device→host
  sync (``kind="np.asarray"``),
- ``jax.device_get``                                → the batched
  device→host sync (``kind="device_get"``),
- ``jnp.asarray`` / ``jnp.array`` of a host value   → a host→device
  upload (``kind="upload"``),
- ``jax.device_put``                                → an explicit
  upload (``kind="device_put"``),

each attributed to its nearest in-scope CALL SITE (``file:line`` — the
same key the static pass stamps into the budget's ``allowed_syncs``).
A sync observed inside a static hot span whose site is not on the
allowlist is a violation: either a hot path regressed, or the static
resolver has a hole (exactly locktrace's unknown-edge contract).
Violations land in the flight recorder as ``syncguard.violation``
events, recorded strictly AFTER the witness's own bookkeeping lock
(``_meta``, a leaf — never a graftlock lock class) is released.

``jax.transfer_guard`` is armed best-effort on top of the shims when
``TCSDN_SYNCGUARD_TG`` names a level (``log``/``disallow``): on the
CPU backend every jnp-of-host op is formally a transfer, so the guard
is too loud to arm unconditionally under tier-1, but on a real chip
window ``tools/tpu_day.sh`` can turn it on for free corroboration.

Known blind spot, by construction: C-level scalarization
(``.item()``, ``int()``/``float()``/``bool()`` via the dunders,
truthiness, iteration) never routes through a patchable Python
callable — those seams are covered by the STATIC half only, which is
why the two halves cross-check by site instead of trusting either
alone.
"""

from __future__ import annotations

import json
import os
import sys
import threading

from .locktrace import _PKG_NAME, _REPO_ROOT, _site_key

DEFAULT_BUDGET_PATH = os.path.join(
    _REPO_ROOT, "docs", "artifacts", "hot_path_sync_budget.json"
)
ENV_FLAG = "TCSDN_SYNCGUARD"
ENV_TRANSFER_GUARD = "TCSDN_SYNCGUARD_TG"

# device→host kinds vs host→device kinds (report bookkeeping only —
# the allowlist check keys on site, not kind: a site the static pass
# blessed for one boundary direction is its seam either way)
D2H_KINDS = ("np.asarray", "device_get")
H2D_KINDS = ("upload", "device_put")


def _record_violation(recorder, violation: dict) -> None:
    """Ring-event form of a violation: the recorder's first positional
    is the EVENT kind, so the sync kind rides as ``sync_kind``."""
    fields = dict(violation)
    fields["sync_kind"] = fields.pop("kind")
    recorder.record("syncguard.violation", **fields)


def _default_scope(filename: str) -> bool:
    norm = filename.replace(os.sep, "/")
    if norm.endswith("utils/syncguard.py"):
        return False
    return f"/{_PKG_NAME}/" in norm or norm.startswith(
        _PKG_NAME + "/"
    )


class SyncWitness:
    """Site-keyed sync counts + the live allowlist check."""

    def __init__(self, budget: dict | None = None, recorder=None,
                 scope=None):
        self.active = True
        self.recorder = recorder  # obs.FlightRecorder, attached late
        self.scope = scope if scope is not None else _default_scope
        self._meta = threading.Lock()  # leaf: guards the counts only
        self._local = threading.local()
        self._counts: dict[str, dict[str, int]] = {}
        self._violations: list[dict] = []
        self._flagged: set[str] = set()
        # parsed budget: hot spans by path + the allowed site set
        self._spans: dict[str, list[tuple[int, int]]] = {}
        self._allowed: set[str] = set()
        if budget is not None:
            for path, spans in budget.get("hot_spans", {}).items():
                self._spans[path] = [(int(a), int(b)) for a, b in spans]
            for entry in budget.get("allowed_syncs", ()):
                self._allowed.add(entry["site"])

    # -- reentrancy: a shim calling into numpy/jax must not re-count ----
    def _enter(self) -> bool:
        if getattr(self._local, "in_shim", False):
            return False
        self._local.in_shim = True
        return True

    def _exit(self) -> None:
        self._local.in_shim = False

    # -- site attribution ------------------------------------------------
    def _find_site(self, depth: int = 2) -> str | None:
        """The IMMEDIATE caller of the patched seam — the syntactic
        call site the static pass keys. A conversion reached through
        stdlib, jax-internal, or test frames is deliberately not
        walked up to the package frame above it: an implicit
        jit-boundary conversion of a host input is the workload
        crossing the boundary (transfer-discipline's fresh-data
        doctrine), not a seam the package wrote — attributing it to
        the enclosing package line would charge every legitimate
        dispatch against a site the static pass never keyed."""
        try:
            f = sys._getframe(depth)
        except ValueError:
            return None
        fn = f.f_code.co_filename
        if self.scope(fn):
            return _site_key(fn, f.f_lineno)
        return None

    def _split(self, site: str) -> tuple[str, int]:
        path, _, line = site.rpartition(":")
        return path, int(line)

    def _in_hot_span(self, path: str, line: int) -> bool:
        # path-suffix tolerant: the package witness normalizes to
        # pkg-relative paths (matching a pkg-anchored budget exactly);
        # a tmp-dir fixture budget keys bare filenames the observed
        # absolute path must still find
        for bp, spans in self._spans.items():
            if path == bp or path.endswith("/" + bp):
                if any(lo <= line <= hi for lo, hi in spans):
                    return True
        return False

    def _site_allowed(self, path: str, line: int) -> bool:
        for site in self._allowed:
            ap, al = self._split(site)
            if al == line and (path == ap or path.endswith("/" + ap)):
                return True
        return False

    def note_sync(self, kind: str, site: str | None) -> None:
        if not self.active or site is None:
            return
        path, line = self._split(site)
        fresh = None
        with self._meta:
            per = self._counts.setdefault(site, {})
            per[kind] = per.get(kind, 0) + 1
            if (
                self._spans
                and site not in self._flagged
                and self._in_hot_span(path, line)
                and not self._site_allowed(path, line)
            ):
                self._flagged.add(site)
                fresh = {
                    "site": site, "kind": kind,
                    "thread": threading.current_thread().name,
                }
                self._violations.append(fresh)
        recorder = self.recorder
        if fresh is not None and recorder is not None:
            # strictly AFTER _meta is released: the recorder's ring
            # lock is traced project state — the witness stays a leaf
            _record_violation(recorder, fresh)

    # -- results -----------------------------------------------------------
    @property
    def violations(self) -> list[dict]:
        with self._meta:
            return list(self._violations)

    def counts(self) -> dict[str, dict[str, int]]:
        with self._meta:
            return {s: dict(k) for s, k in self._counts.items()}

    def report(self) -> dict:
        with self._meta:
            return {
                "counts": {
                    s: dict(k) for s, k in sorted(self._counts.items())
                },
                "violations": list(self._violations),
            }

    def check_against(self, budget: dict | None) -> dict:
        """Re-run the allowlist check over everything observed —
        the post-hoc form of the live check, for a budget loaded
        after the fact (mirrors locktrace ``check_against``)."""
        if budget is None:
            return {"unknown_syncs": [], "checked": False}
        probe = SyncWitness(budget=budget)
        unknown = []
        for site, kinds in self.counts().items():
            path, line = probe._split(site)
            if probe._in_hot_span(path, line) and not (
                probe._site_allowed(path, line)
            ):
                unknown.append({
                    "site": site, "kinds": dict(kinds),
                })
        return {
            "unknown_syncs": sorted(unknown, key=lambda u: u["site"]),
            "checked": True,
        }


# ---------------------------------------------------------------------------
# installation: patch the conversion seams
# ---------------------------------------------------------------------------

_installed: SyncWitness | None = None
_saved: dict[str, object] = {}
_tg_prev: object | None = None


def _jax_bits():
    import jax

    try:
        from jax.core import Tracer
    except ImportError:  # pragma: no cover - jax layout drift
        from jax._src.core import Tracer
    return jax, Tracer


def install(witness: SyncWitness) -> None:
    """Monkeypatch the conversion seams with counting shims. The
    patched functions behave identically (same return, same raise) —
    the witness only observes."""
    global _installed
    if _installed is not None:
        raise RuntimeError("syncguard already installed")
    import numpy
    import jax
    import jax.numpy as jnp

    _, tracer_cls = _jax_bits()
    real_np_asarray = numpy.asarray
    real_np_array = numpy.array
    real_device_get = jax.device_get
    real_device_put = jax.device_put
    real_jnp_asarray = jnp.asarray
    real_jnp_array = jnp.array
    _saved.update({
        "np.asarray": real_np_asarray, "np.array": real_np_array,
        "device_get": real_device_get, "device_put": real_device_put,
        "jnp.asarray": real_jnp_asarray, "jnp.array": real_jnp_array,
    })

    def _note(kind: str) -> None:
        witness.note_sync(kind, witness._find_site(depth=3))

    def np_asarray(a, *args, **kwargs):
        if witness._enter():
            try:
                if isinstance(a, jax.Array) and not isinstance(
                    a, tracer_cls
                ):
                    _note("np.asarray")
                return real_np_asarray(a, *args, **kwargs)
            finally:
                witness._exit()
        return real_np_asarray(a, *args, **kwargs)

    def np_array(a, *args, **kwargs):
        if witness._enter():
            try:
                if isinstance(a, jax.Array) and not isinstance(
                    a, tracer_cls
                ):
                    _note("np.asarray")
                return real_np_array(a, *args, **kwargs)
            finally:
                witness._exit()
        return real_np_array(a, *args, **kwargs)

    def device_get(x):
        if witness._enter():
            try:
                leaves = jax.tree_util.tree_leaves(x)
                if any(
                    isinstance(v, jax.Array)
                    and not isinstance(v, tracer_cls)
                    for v in leaves
                ):
                    _note("device_get")
                return real_device_get(x)
            finally:
                witness._exit()
        return real_device_get(x)

    def device_put(x, *args, **kwargs):
        if witness._enter():
            try:
                _note("device_put")
                return real_device_put(x, *args, **kwargs)
            finally:
                witness._exit()
        return real_device_put(x, *args, **kwargs)

    def _upload_shim(real):
        def shim(a, *args, **kwargs):
            if witness._enter():
                try:
                    if not isinstance(a, (jax.Array, tracer_cls)):
                        _note("upload")
                    return real(a, *args, **kwargs)
                finally:
                    witness._exit()
            return real(a, *args, **kwargs)
        return shim

    numpy.asarray = np_asarray
    numpy.array = np_array
    jax.device_get = device_get
    jax.device_put = device_put
    jnp.asarray = _upload_shim(real_jnp_asarray)
    jnp.array = _upload_shim(real_jnp_array)
    _installed = witness
    _arm_transfer_guard()


def uninstall() -> None:
    """Restore the real seams; the witness goes inactive so any shim
    reference still held (a bound import) stops counting."""
    global _installed
    if _saved:
        import numpy
        import jax
        import jax.numpy as jnp

        numpy.asarray = _saved["np.asarray"]
        numpy.array = _saved["np.array"]
        jax.device_get = _saved["device_get"]
        jax.device_put = _saved["device_put"]
        jnp.asarray = _saved["jnp.asarray"]
        jnp.array = _saved["jnp.array"]
        _saved.clear()
    _disarm_transfer_guard()
    if _installed is not None:
        _installed.active = False
    _installed = None


def _arm_transfer_guard() -> None:
    global _tg_prev
    level = os.environ.get(ENV_TRANSFER_GUARD)
    if level not in ("log", "disallow"):
        return
    try:  # best-effort: config name is jax-version-dependent
        import jax

        _tg_prev = jax.config.jax_transfer_guard
        jax.config.update("jax_transfer_guard", level)
    except Exception:  # noqa: BLE001 — corroboration only, never fatal
        _tg_prev = None


def _disarm_transfer_guard() -> None:
    global _tg_prev
    if _tg_prev is None:
        return
    try:
        import jax

        jax.config.update("jax_transfer_guard", _tg_prev)
    except Exception:  # noqa: BLE001
        pass
    _tg_prev = None


class guarding:
    """``with guarding(budget) as witness:`` — scoped
    install/uninstall, the test-fixture idiom."""

    def __init__(self, budget: dict | None = None, recorder=None,
                 scope=None):
        self.witness = SyncWitness(budget=budget, recorder=recorder,
                                   scope=scope)

    def __enter__(self) -> SyncWitness:
        install(self.witness)
        return self.witness

    def __exit__(self, *exc) -> bool:
        uninstall()
        return False


# ---------------------------------------------------------------------------
# budget loading + the CLI env hook
# ---------------------------------------------------------------------------


def load_budget(path: str | None = None) -> dict | None:
    """The exported hot-path sync budget, or None when absent (an
    installed package without the repo's docs tree)."""
    candidate = path or os.environ.get(
        "TCSDN_SYNC_BUDGET", DEFAULT_BUDGET_PATH
    )
    try:
        with open(candidate, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def maybe_guard_from_env() -> SyncWitness | None:
    """CLI hook: install the witness when ``TCSDN_SYNCGUARD=1`` (the
    chaos-matrix / operator opt-in). Returns the witness, or None when
    the flag is off or a witness is already installed."""
    if os.environ.get(ENV_FLAG) != "1" or _installed is not None:
        return None
    witness = SyncWitness(budget=load_budget())
    install(witness)
    return witness


def append_report(witness: SyncWitness, path: str) -> dict:
    """Accumulate this witness's observations into a JSON report file.

    The chip-day sweep (``tools/tpu_day.sh``) runs the serve suites
    with one witness per test; this merges them all into one artifact
    (``hot_path_sync_budget_tpu.json``) — per-site counts summed,
    violations concatenated, platform stamped from the live backend —
    so the window lands the OBSERVED sync economy beside the static
    budget's promised one. Returns the merged report."""
    from .atomicio import atomic_write_bytes

    merged: dict = {"platform": None, "counts": {}, "violations": []}
    try:
        with open(path, encoding="utf-8") as f:
            prev = json.load(f)
        merged["counts"] = {
            s: dict(k) for s, k in prev.get("counts", {}).items()
        }
        merged["violations"] = list(prev.get("violations", ()))
        merged["platform"] = prev.get("platform")
    except (OSError, ValueError):
        pass
    for site, kinds in witness.counts().items():
        per = merged["counts"].setdefault(site, {})
        for kind, n in kinds.items():
            per[kind] = per.get(kind, 0) + n
    merged["violations"].extend(witness.violations)
    try:  # stamp the backend the counts were observed on
        import jax

        merged["platform"] = jax.default_backend()
    except Exception:  # noqa: BLE001 — report is evidence, never fatal
        pass
    atomic_write_bytes(
        path,
        (json.dumps(merged, indent=2, sort_keys=True) + "\n").encode(),
    )
    return merged


def finish(witness: SyncWitness | None, recorder=None) -> dict | None:
    """CLI teardown: uninstall, surface violations (stderr + the
    flight recorder) and the budget cross-check. Returns the report."""
    if witness is None:
        return None
    if _installed is witness:
        uninstall()
    report = witness.report()
    report["cross_check"] = witness.check_against(load_budget())
    for v in report["violations"]:
        print(
            f"SYNCGUARD VIOLATION: {v['kind']} at {v['site']} is "
            "inside a static hot span but not on the allowed-sync "
            f"ledger (thread {v['thread']})",
            file=sys.stderr, flush=True,
        )
        # live-recorded violations (witness.recorder attached) are
        # already in the ring — re-recording would duplicate the event
        if recorder is not None and recorder is not witness.recorder:
            _record_violation(recorder, v)
    return report
