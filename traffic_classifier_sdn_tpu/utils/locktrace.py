"""locktrace: a lockdep-style runtime witness for the project's locks.

The static side (``analysis_static/graftlock.py``) proves lock-order
acyclicity over the edges it can SEE; this module is the dynamic
cross-check. Opt-in (``TCSDN_LOCKTRACE=1``, or the tier-1 fixture over
the chaos/degrade/drift/pipeline suites): while installed, every
``threading.Lock()`` / ``threading.Condition()`` constructed from
package code is wrapped in a tracing shim that

- records the actual acquisition order per thread (a thread-local held
  stack),
- asserts acyclicity ONLINE, lockdep-style: when thread T acquires B
  while holding A, the edge A→B joins a global order graph; if a path
  B→…→A already exists, the AB/BA deadlock is reported *the first time
  the two orders are both observed* — no actual deadlock (no
  unfortunate interleaving) has to manifest, which is what makes the
  tier-1 schedules the chaos/degrade/drift/pipeline suites already
  drive usable as ordering evidence, and
- cross-checks observed edges against the static lock-order graph
  (``docs/artifacts/lock_order_graph.json``): locks are identified by
  CONSTRUCTION SITE (file:line), the same lockdep "lock class" keying
  the static graph exports in each node's ``constructed_at`` — an
  observed edge absent from the static graph is a hole in the static
  analysis worth closing (typically an attribute the resolver could
  not type).

The TSan phase of ``tools/native_sanitize.sh`` covers the C++ spine's
ordering at runtime; this is its Python-side counterpart.

The witness itself must never deadlock the host: its only lock
(``_meta``) is a leaf — no traced lock is ever acquired while holding
it, and violation hooks (the flight recorder) run strictly after it is
released. Stdlib-internal locks (queue.Queue's mutex, Condition's
default RLock, http.server plumbing) are constructed from stdlib files
and therefore never wrapped — the scope filter keys on the
construction frame's filename.
"""

from __future__ import annotations

import json
import os
import sys
import threading

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG_NAME = os.path.basename(_PKG_DIR)
_REPO_ROOT = os.path.dirname(_PKG_DIR)
DEFAULT_GRAPH_PATH = os.path.join(
    _REPO_ROOT, "docs", "artifacts", "lock_order_graph.json"
)
ENV_FLAG = "TCSDN_LOCKTRACE"


def _site_key(filename: str, lineno: int) -> str:
    """Normalize a construction frame to the repo-relative form the
    static graph uses (``traffic_classifier_sdn_tpu/...py:line``)."""
    norm = filename.replace(os.sep, "/")
    marker = "/" + _PKG_NAME + "/"
    idx = norm.rfind(marker)
    if idx >= 0:
        norm = _PKG_NAME + "/" + norm[idx + len(marker):]
    return f"{norm}:{lineno}"


class LockWitness:
    """The order graph + per-thread held stacks + violation log."""

    def __init__(self, recorder=None):
        self.active = True
        self.recorder = recorder  # obs.FlightRecorder, attached late
        self._meta = threading.Lock()  # leaf: guards the graph only
        self._local = threading.local()
        self._edges: dict[tuple[str, str], dict] = {}
        self._violations: list[dict] = []
        self._sites: set[str] = set()
        # id()s of violation dicts already sent to self.recorder, so
        # finish() never duplicates a live-recorded event in the ring
        self._logged: set[int] = set()

    # -- the per-acquisition hooks ------------------------------------------
    def _stack(self) -> list[str]:
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
        return s

    def note_acquire(self, site: str) -> None:
        if not self.active:
            return
        stack = self._stack()
        held = [s for s in stack if s != site]
        fresh: list[dict] = []
        with self._meta:
            self._sites.add(site)
            for a in held:
                v = self._add_edge_locked(a, site)
                if v is not None:
                    fresh.append(v)
        stack.append(site)
        recorder = self.recorder
        if fresh and recorder is not None:
            # strictly AFTER _meta is released: the recorder's ring
            # lock is itself traced, and the witness must stay a leaf
            for v in fresh:
                recorder.record("locktrace.violation", **v)
            with self._meta:
                self._logged.update(id(v) for v in fresh)

    def note_release(self, site: str) -> None:
        stack = getattr(self._local, "stack", None)
        if not stack:
            return
        # last occurrence: re-entrant wrappers (Condition re-acquire
        # after wait) release in LIFO order
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                return

    # -- the order graph (callers hold _meta) -------------------------------
    def _add_edge_locked(self, a: str, b: str) -> dict | None:
        if (a, b) in self._edges:
            return None
        back = self._path_locked(b, a)
        self._edges[(a, b)] = {
            "thread": threading.current_thread().name,
        }
        if back is None:
            return None
        violation = {
            "edge": [a, b],
            "conflict_path": back,
            "thread": threading.current_thread().name,
        }
        key = frozenset([a, b, *back])
        if not any(
            frozenset([*v["edge"], *v["conflict_path"]]) == key
            for v in self._violations
        ):
            self._violations.append(violation)
            return violation
        return None

    def _path_locked(self, src: str, dst: str) -> list[str] | None:
        adj: dict[str, list[str]] = {}
        for x, y in self._edges:
            adj.setdefault(x, []).append(y)
        prev: dict[str, str] = {}
        frontier, visited = [src], {src}
        while frontier:
            nxt = []
            for n in frontier:
                for m in adj.get(n, ()):
                    if m in visited:
                        continue
                    visited.add(m)
                    prev[m] = n
                    if m == dst:
                        path = [dst]
                        while path[-1] != src:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    nxt.append(m)
            frontier = nxt
        return None

    # -- results ------------------------------------------------------------
    @property
    def violations(self) -> list[dict]:
        with self._meta:
            return list(self._violations)

    def edges(self) -> list[tuple[str, str]]:
        with self._meta:
            return sorted(self._edges)

    def report(self) -> dict:
        with self._meta:
            return {
                "edges": [list(e) for e in sorted(self._edges)],
                "violations": list(self._violations),
                "sites": sorted(self._sites),
            }

    def check_against(self, graph: dict | None) -> dict:
        """Cross-check observed edges against the static lock-order
        graph export. Returns ``{"unknown_edges": [...],
        "unmapped_sites": [...]}`` — an unknown edge is one the static
        pass missed (both endpoints map to static nodes but the edge is
        absent); an unmapped site is a lock the static pass never keyed
        at all."""
        if graph is None:
            return {"unknown_edges": [], "unmapped_sites": [],
                    "checked": False}
        site_to_node: dict[str, str] = {}
        for node in graph.get("nodes", ()):
            for site in node.get("constructed_at", ()):
                site_to_node[site] = node["id"]
        static_edges = {
            (e["from"], e["to"]) for e in graph.get("edges", ())
        }
        unknown, unmapped = [], set()
        for a, b in self.edges():
            na, nb = site_to_node.get(a), site_to_node.get(b)
            if na is None:
                unmapped.add(a)
            if nb is None:
                unmapped.add(b)
            if na is None or nb is None:
                continue
            if na != nb and (na, nb) not in static_edges:
                unknown.append({"from": na, "to": nb,
                                "observed": [a, b]})
        return {"unknown_edges": unknown,
                "unmapped_sites": sorted(unmapped), "checked": True}


# ---------------------------------------------------------------------------
# traced wrappers
# ---------------------------------------------------------------------------


class TracedLock:
    """threading.Lock shim: same surface, every transition witnessed."""

    def __init__(self, inner, site: str, witness: LockWitness):
        self._inner = inner
        self._site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness.note_acquire(self._site)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._witness.note_release(self._site)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<TracedLock {self._site} {self._inner!r}>"


class TracedCondition(TracedLock):
    """threading.Condition shim. ``wait``/``wait_for`` release the
    condition's own lock while waiting — the witness pops the site for
    the duration so a parked waiter is not "holding" its condition."""

    def wait(self, timeout: float | None = None):
        self._witness.note_release(self._site)
        try:
            return self._inner.wait(timeout)
        finally:
            self._witness.note_acquire(self._site)

    def wait_for(self, predicate, timeout: float | None = None):
        self._witness.note_release(self._site)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._witness.note_acquire(self._site)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


# ---------------------------------------------------------------------------
# installation
# ---------------------------------------------------------------------------

_REAL_LOCK = threading.Lock
_REAL_CONDITION = threading.Condition
_installed: LockWitness | None = None


def _default_scope(filename: str) -> bool:
    norm = filename.replace(os.sep, "/")
    if norm.endswith("utils/locktrace.py"):
        return False
    return f"/{_PKG_NAME}/" in norm or norm.startswith(
        _PKG_NAME + "/"
    )


def install(witness: LockWitness, scope=None) -> None:
    """Monkeypatch ``threading.Lock``/``threading.Condition`` with
    site-keyed tracing factories. ``scope(filename) -> bool`` bounds
    which construction sites are wrapped (default: package files only —
    stdlib and third-party locks stay real)."""
    global _installed
    if _installed is not None:
        raise RuntimeError("locktrace already installed")
    in_scope = scope if scope is not None else _default_scope

    def lock_factory():
        frame = sys._getframe(1)
        if in_scope(frame.f_code.co_filename):
            site = _site_key(frame.f_code.co_filename, frame.f_lineno)
            return TracedLock(_REAL_LOCK(), site, witness)
        return _REAL_LOCK()

    def condition_factory(lock=None):
        frame = sys._getframe(1)
        if lock is None and in_scope(frame.f_code.co_filename):
            site = _site_key(frame.f_code.co_filename, frame.f_lineno)
            return TracedCondition(_REAL_CONDITION(), site, witness)
        if isinstance(lock, TracedLock):
            lock = lock._inner
        return (
            _REAL_CONDITION(lock) if lock is not None
            else _REAL_CONDITION()
        )

    threading.Lock = lock_factory  # type: ignore[misc]
    threading.Condition = condition_factory  # type: ignore[misc,assignment]
    _installed = witness


def uninstall() -> None:
    """Restore the real factories. Wrappers already handed out keep
    working (their witness goes inactive so late acquisitions are
    ignored, releases stay tolerated)."""
    global _installed
    threading.Lock = _REAL_LOCK  # type: ignore[misc]
    threading.Condition = _REAL_CONDITION  # type: ignore[misc]
    if _installed is not None:
        _installed.active = False
    _installed = None


class tracing:
    """``with tracing() as witness:`` — scoped install/uninstall, the
    test-fixture idiom."""

    def __init__(self, recorder=None, scope=None):
        self.witness = LockWitness(recorder=recorder)
        self._scope = scope

    def __enter__(self) -> LockWitness:
        install(self.witness, scope=self._scope)
        return self.witness

    def __exit__(self, *exc) -> bool:
        uninstall()
        return False


# ---------------------------------------------------------------------------
# static-graph loading + the CLI env hook
# ---------------------------------------------------------------------------


def load_static_graph(path: str | None = None) -> dict | None:
    """The exported static lock-order graph, or None when absent (an
    installed package without the repo's docs tree)."""
    candidate = path or os.environ.get(
        "TCSDN_LOCK_GRAPH", DEFAULT_GRAPH_PATH
    )
    try:
        with open(candidate, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def maybe_trace_from_env() -> LockWitness | None:
    """CLI hook: install the witness when ``TCSDN_LOCKTRACE=1`` (the
    chaos-matrix / operator opt-in). Returns the witness, or None when
    the flag is off or a witness is already installed."""
    if os.environ.get(ENV_FLAG) != "1" or _installed is not None:
        return None
    witness = LockWitness()
    install(witness)
    return witness


def finish(witness: LockWitness | None, recorder=None) -> dict | None:
    """CLI teardown: uninstall, surface violations (stderr + the flight
    recorder) and the static cross-check. Returns the report dict."""
    if witness is None:
        return None
    if _installed is witness:
        uninstall()
    report = witness.report()
    report["cross_check"] = witness.check_against(load_static_graph())
    with witness._meta:
        logged = set(witness._logged)
    for v in report["violations"]:
        print(
            f"LOCKTRACE VIOLATION: edge {v['edge'][0]} -> "
            f"{v['edge'][1]} closes cycle via "
            f"{' -> '.join(v['conflict_path'])} (thread {v['thread']})",
            file=sys.stderr, flush=True,
        )
        # live-recorded violations (witness.recorder attached) are
        # already in the ring — re-recording would duplicate the event
        # and could evict a real earlier one from the bounded ring
        if recorder is not None and not (
            id(v) in logged and recorder is witness.recorder
        ):
            recorder.record("locktrace.violation", **v)
    return report
