"""Lightweight metrics registry: counters, gauges, and latency
histograms with percentile snapshots.

The reference's only observability is the TSV line protocol itself plus
PrettyTable output (SURVEY.md §5 — "the TSV line protocol *is* the
metrics system"). This module gives the framework real counters for the
ingest spine (records parsed/dropped, batches scattered, evictions) and
latency distributions for the device predict path, renderable as a
single-line report or a dict for programmatic scraping.

Deliberately dependency-free and cheap: increments are plain float adds;
histograms keep a bounded ring of recent samples (exact percentiles over
the window, no binning error).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class Histogram:
    """Bounded ring of recent samples; exact percentiles over the window."""

    window: int = 1024
    _samples: list = field(default_factory=list)
    _pos: int = 0
    count: int = 0
    total: float = 0.0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if len(self._samples) < self.window:
            self._samples.append(value)
        else:
            self._samples[self._pos] = value
            self._pos = (self._pos + 1) % self.window

    def percentile(self, q: float) -> float:
        """q in [0, 100]; nearest-rank over the current window."""
        return self.quantiles((q,))[0]

    def quantiles(self, qs) -> list[float]:
        """Batch percentiles over ONE snapshot of the window — the
        exposition path's form: a concurrent ``observe`` between two
        ``percentile`` calls cannot make the reported quantiles cross
        (q50 > q99) because all of them rank the same sorted copy."""
        if not self._samples:
            return [0.0 for _ in qs]
        s = sorted(self._samples)
        out = []
        for q in qs:
            idx = min(
                len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1))))
            )
            out.append(s[idx])
        return out

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    @property
    def last(self) -> float | None:
        """The newest observed sample (None before the first) — the
        per-tick read the black-box perf recorder persists; quantiles
        remain the exposition surface."""
        if not self._samples:
            return None
        if len(self._samples) < self.window:
            return self._samples[-1]
        # ring full: _pos is the next overwrite slot, so the newest
        # sample sits just behind it (negative index wraps at 0)
        return self._samples[self._pos - 1]


class Metrics:
    """Flat namespace of counters / gauges / histograms."""

    def __init__(self):
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, Histogram] = {}
        self._t0 = time.time()

    def reset(self) -> None:
        """Zero everything (start of a CLI run — the global registry must
        not leak state between runs in one process)."""
        self.counters.clear()
        self.gauges.clear()
        self.histograms.clear()
        self._t0 = time.time()

    # -- write -------------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + value

    def set(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram()
        h.observe(value)

    def time(self, name: str):
        """Context manager: record elapsed seconds into histogram ``name``."""
        return _TimerCtx(self, name)

    # -- read --------------------------------------------------------------
    @property
    def started_at(self) -> float:
        """Wall-clock time of the last reset (uptime epoch) — the
        public face of ``_t0`` for the exposition renderer."""
        return self._t0

    def snapshot(self) -> dict:
        out: dict = {"uptime_s": time.time() - self._t0}
        out.update({k: v for k, v in self.counters.items()})
        out.update({k: v for k, v in self.gauges.items()})
        for name, h in self.histograms.items():
            out[f"{name}_count"] = h.count
            out[f"{name}_mean"] = h.mean
            out[f"{name}_p50"] = h.percentile(50)
            out[f"{name}_p99"] = h.percentile(99)
        return out

    def report(self) -> str:
        """One human line, stable key order — greppable from stderr."""
        snap = self.snapshot()
        parts = []
        for k in sorted(snap):
            v = snap[k]
            parts.append(f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}")
        return "metrics " + " ".join(parts)


class _TimerCtx:
    def __init__(self, m: Metrics, name: str):
        self.m, self.name = m, name

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.m.observe(self.name, time.perf_counter() - self._t)
        return False


# process-global default registry (import-cheap, test-resettable)
global_metrics = Metrics()
