"""Deterministic fault injection for the durability seams.

The recovery story (atomic serving checkpoints, supervisor restarts,
native-engine fallback) is only as good as the failures it has actually
survived. This module threads named *fault sites* through those seams —
checkpoint write/rename/restore, collector reads, supervisor restart,
native engine load — and lets a test install a seeded ``FaultPlan`` that
fires scripted failures at exact hit counts (or seeded probabilities).
``tests/test_chaos.py`` and ``tools/chaos_matrix.sh`` drive the matrix.

Design constraints, in order:

1. **Inert by default.** With no plan installed every site is one module
   attribute load and an ``is None`` branch — no allocation, no locking,
   no string work. The serve loop's sites are per-tick / per-chunk (never
   per-record), so the uninstalled cost is unmeasurable in
   ``tools/bench_serve.py`` (acceptance-gated).
2. **Deterministic.** A plan is seeded; probability schedules draw from a
   private ``random.Random`` so a (plan, seed, call sequence) triple
   always yields the same fires. Count schedules (``after``/``times``)
   don't touch the RNG at all.
3. **Scripted, not ambient.** Plans install explicitly (``install`` /
   ``installed``) and tests always clear them; a leaked plan would make
   unrelated tests fail loudly with ``FaultInjected`` rather than
   silently corrupt state.

The canonical site table is ``SITES`` below — the single source of
truth the static analyzer's ``fault-site-registry`` rule enforces: every
site string used at an injection seam must be registered here, every
registered site must be threaded through at least one seam, and every
registered site must have a chaos test (tests/test_chaos.py) referencing
it. ``tools/chaos_matrix.sh`` sweeps the same table, so a seam can
neither be added without coverage nor silently lose it.
"""

from __future__ import annotations

import contextlib
import random
from dataclasses import dataclass, field

# The canonical fault-site registry. Keys are the exact strings passed to
# fault_point()/fault_bytes() (or the *_site kwargs of atomicio's
# atomic_write_bytes); values say where the seam lives and what a fire
# simulates. Enforced by graftlint's fault-site-registry rule (see
# docs/STATIC_ANALYSIS.md): unregistered use, registered-but-unthreaded,
# and registered-but-chaos-untested are all tier-1 lint failures.
SITES: dict[str, str] = {
    "serving_ckpt.write": (
        "io/serving_checkpoint.save — temp file half-written (a fire == "
        "crash mid-checkpoint: the temp is torn away, the previous "
        "checkpoint survives)"
    ),
    "serving_ckpt.rename": (
        "io/serving_checkpoint.save — complete fsynced temp, crash at "
        "the atomic rename itself (durability without visibility)"
    ),
    "serving_ckpt.restore": "io/serving_checkpoint.restore entry",
    "train_ckpt.write": (
        "io/checkpoint manifest commit (model and train-state saves)"
    ),
    "collector.read": (
        "ingest/collector raw reader, per pipe chunk; 'truncate' drops "
        "the chunk tail mid-record (framing must poison the seam), "
        "'raise' kills the monitor mid-stream"
    ),
    "supervisor.restart": (
        "ingest/supervisor — the restart attempt itself fails (spawn "
        "failure); consumes one restart-budget slot and re-enters "
        "backoff"
    ),
    "ingest.fanin_put": (
        "ingest/fanin.FanInQueue.put — the MPSC enqueue from a source "
        "pump fails (a fire == a queue-full drop burst); ABSORBED: the "
        "batch is dropped and counted against ITS source only — the "
        "producer is never blocked, the serve loop never sees the "
        "failure, and every other source's telemetry flows untouched"
    ),
    "ingest.source_dead": (
        "ingest/fanin.SourceWorker pump — one telemetry source dies "
        "mid-stream; ABSORBED by the fan-in tier: the source goes DEAD "
        "(unclean), its namespace quarantines and after the quarantine "
        "window exactly its own slots are evicted, while every other "
        "source keeps serving fresh labels every tick"
    ),
    "ingest.native_parse": (
        "native/engine.NativeBatcher.feed — one line of a native-ingest "
        "poll batch is corrupt (a fire == a torn/garbled wire line at "
        "the C++ parse seam); ABSORBED exactly like a real malformed "
        "line: counted against ITS source (parse_errors) and skipped, "
        "the rest of the batch parses normally — never a crash, never "
        "a torn row, and every other source's telemetry is untouched"
    ),
    "obs.perf_ring": (
        "obs/perf_recorder.PerfRecorder segment commit — the black-box "
        "ring's atomic segment write fails (ENOSPC, dead disk, torn "
        "rename); ABSORBED inside the recorder: that segment's samples "
        "are dropped and counted (perf_ring_dropped_segments), the next "
        "segment starts clean, and the serve tick never sees the "
        "failure — the black box must not stall the plane it records"
    ),
    "obs.profiler": (
        "obs/device.ProfilerCapture.capture — the on-demand "
        "jax.profiler trace capture fails mid-start; ABSORBED at the "
        "/profile endpoint: the request 500s with the error, the "
        "failure is counted (profiler_capture_failures) and recorded, "
        "the busy guard releases, and the serve loop never sees it"
    ),
    "obs.stamp": (
        "ingest/protocol.stamp_records — the latency-provenance emit "
        "stamp itself fails; ABSORBED at the stamping seam: the batch "
        "is delivered unstamped (counted in latency_unstamped_batches, "
        "skipped by the e2e fold) and telemetry is NEVER dropped — a "
        "broken observability plane must not cost a single record"
    ),
    "native.load": (
        "native/engine.available() — the C++ engine is unavailable "
        "(build/dlopen failure)"
    ),
    "pipeline.handoff": (
        "serving/pipeline.Handoff.put — the host→device stage handoff "
        "itself fails mid-tick (a fire == the staging seam dies while "
        "the serve loop is pipelined; the host stage must surface it, "
        "not wedge behind a dead device stage)"
    ),
    "pipeline.coalesce": (
        "serving/pipeline.Handoff.put, coalesce branch — fires only "
        "under backpressure, when a full queue merges the new tick into "
        "the staged one (chaos must cover the overload path, not just "
        "the steady-state handoff)"
    ),
    "degrade.dispatch_stall": (
        "serving/degrade.DegradeLadder device path — a fire simulates a "
        "WEDGED device dispatch (the r04 chip-day failure mode): the "
        "ladder converts it into a watchdog deadline trip, so unlike "
        "the other sites the FaultInjected never escapes — the ladder "
        "must absorb it and demote to the fallback rung"
    ),
    "degrade.dispatch_error": (
        "serving/degrade.DegradeLadder device path — a fire simulates "
        "an ERRORING device dispatch (XLA runtime error mid-kernel); "
        "absorbed by the ladder like dispatch_stall, driving the "
        "error-trip edge of HEALTHY→DEGRADED instead of the deadline "
        "edge"
    ),
    "degrade.probe": (
        "serving/degrade.DegradeLadder probe path — the shadow-batch "
        "re-probe itself fails: consumes the probe attempt, resets the "
        "consecutive-success counter, and grows the full-jitter "
        "backoff (chaos must cover the failed-recovery path, not just "
        "the clean re-promotion)"
    ),
    "serve.dirty_mask": (
        "serving/incremental.IncrementalLabels dirty-mask consult — the "
        "per-slot dirty bookkeeping behind incremental prediction is "
        "suspect this tick; ABSORBED: the tick degrades to a direct "
        "full-table re-predict (served fresh, cache and mask untouched "
        "on the fault path) and the mask/cache pair is rebuilt from "
        "scratch at the next render — a stale label is never served as "
        "fresh"
    ),
    "serve.label_cache": (
        "serving/incremental.IncrementalLabels cache-merge seam — the "
        "device-resident label cache cannot accept this tick's dirty-"
        "row labels; ABSORBED: the tick degrades to a direct full-table "
        "re-predict served fresh, the cache and dirty mask are left "
        "untouched, and the dirty rows re-predict at the next render"
    ),
    "drift.window": (
        "serving/drift.DriftController window observation — the "
        "off-hot-path materialization/stats update for one observed "
        "batch fails; ABSORBED: the observation is dropped (counted in "
        "drift_window_errors) and the serve tick's output is unaffected"
    ),
    "retrain.fit": (
        "serving/retrain.fit_family entry — the background refit "
        "itself dies mid-fit; ABSORBED by the drift controller: the "
        "retrain run is marked failed, the serve keeps the old model, "
        "and a still-drifting stream re-trips later"
    ),
    "promote.swap": (
        "serving/drift.DriftController promotion — the hot swap of the "
        "candidate into the live predict path fails; ABSORBED: the "
        "controller rolls back via serving/retrain.resolve_latest and "
        "the old model keeps serving every tick"
    ),
    "promote.rollback": (
        "serving/drift.DriftController rollback — the rollback reload "
        "itself fails; ABSORBED: the gate keeps the pair it already "
        "holds (the old model), so serving continues regardless"
    ),
    "openset.score": (
        "serving/openset.OpenSetGate scoring — the per-tick open-set "
        "rejection scoring fails; ABSORBED: that tick serves the inner "
        "closed-world labels FRESH (the predict already ran) — never a "
        "fabricated 'unknown', never a stale label, and the serve "
        "never sees the failure"
    ),
    "openset.calibrate": (
        "serving/openset.OpenSetGate calibration/rebase — a "
        "calibration sample fold or a promotion-time rebase fails; "
        "ABSORBED: the sample is dropped (calibration just takes "
        "longer; a failed rebase keeps the previous stats) and labels "
        "are never touched — the gate stays byte-transparent until a "
        "calibration actually lands"
    ),
    "actuation.send": (
        "serving/actuation.ActuationPlane flow-mod send — the switch "
        "socket wedges or refuses a mod mid-write; ABSORBED: the plane "
        "degrades itself to dry-run (in-flight ops resolve as refused, "
        "accounting stays exact) and re-probes the switch on an "
        "exponential backoff while classify serves every tick "
        "byte-identically to --actuation off"
    ),
    "actuation.barrier": (
        "serving/actuation.ActuationPlane barrier collection — the "
        "barrier reply confirming a pushed batch is lost or the read "
        "fails; ABSORBED: the batch's unresolved ops are counted "
        "refused (never silently 'installed'), the plane degrades to "
        "dry-run and re-probes on backoff; the serve cadence never "
        "blocks on the dead barrier"
    ),
    "actuation.retract": (
        "serving/actuation.ActuationPlane retraction push — the DELETE "
        "undoing an installed rule cannot be sent (quarantine, "
        "rollback-demotion, or label-change retract); ABSORBED: the op "
        "resolves refused, the rule is dropped from the installed view "
        "(the switch may hold it until re-probe reconciles), and the "
        "plane degrades to dry-run with backoff re-probe"
    ),
}


class FaultInjected(RuntimeError):
    """Raised by a firing fault site (``kind="raise"``)."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at site {site!r} (hit #{hit})")
        self.site = site
        self.hit = hit


@dataclass
class FaultRule:
    """One scheduled failure at one site.

    ``after`` eligible hits are skipped, then the rule fires up to
    ``times`` times (None = every subsequent hit). ``p`` gates each
    otherwise-eligible hit on a seeded coin flip — with count scheduling
    alone (``p=1.0``) the RNG is never consulted, so count plans are
    exactly reproducible regardless of seed.
    """

    site: str
    after: int = 0
    times: int | None = 1
    p: float = 1.0
    kind: str = "raise"  # or "truncate" (byte sites only)
    fired: int = field(default=0, compare=False)


class FaultPlan:
    """Seeded schedule of FaultRules, keyed by site name."""

    def __init__(self, rules, seed: int = 0):
        self.rules: dict[str, list[FaultRule]] = {}
        for r in rules:
            self.rules.setdefault(r.site, []).append(r)
        self.seed = seed
        self._rng = random.Random(seed)
        self.hits: dict[str, int] = {}  # site → eligible-hit count
        self.fires: list[tuple[str, int]] = []  # (site, hit) audit log

    def check(self, site: str) -> FaultRule | None:
        """Record one hit at ``site``; the firing rule, or None."""
        hit = self.hits.get(site, 0) + 1
        self.hits[site] = hit
        for r in self.rules.get(site, ()):
            if hit <= r.after:
                continue
            if r.times is not None and r.fired >= r.times:
                continue
            if r.p < 1.0 and self._rng.random() >= r.p:
                continue
            r.fired += 1
            self.fires.append((site, hit))
            return r
        return None


# The active plan. ``None`` means every site is inert; sites guard on this
# before doing any other work.
_plan: FaultPlan | None = None

# Fire observers: called as fn(site, hit, kind) AFTER a rule fires but
# BEFORE the failure manifests (raise/truncate), so crash forensics (the
# obs flight recorder) capture the firing even when the fire kills the
# process path that would have reported it. Consulted only on a fire —
# the inert-by-default cost of a site is unchanged.
_observers: list = []


def add_observer(fn) -> None:
    """Register ``fn(site, hit, kind)`` to be called on every fire."""
    if fn not in _observers:
        _observers.append(fn)


def remove_observer(fn) -> None:
    if fn in _observers:
        _observers.remove(fn)


@contextlib.contextmanager
def observing(fn):
    """Scoped observer registration — always detaches (the registry is
    process-global; a leaked observer would haunt later runs)."""
    add_observer(fn)
    try:
        yield fn
    finally:
        remove_observer(fn)


def _notify(site: str, hit: int, kind: str) -> None:
    # observation must never alter injection semantics: a broken
    # observer is reported to stderr, not allowed to mask the fire
    for fn in list(_observers):
        try:
            fn(site, hit, kind)
        except Exception as e:  # noqa: BLE001 — forensics must not inject
            import sys

            print(f"WARNING: fault observer {fn!r} failed: {e}",
                  file=sys.stderr)


def install(plan: FaultPlan | None) -> None:
    global _plan
    _plan = plan


def clear() -> None:
    install(None)


def active() -> FaultPlan | None:
    return _plan


@contextlib.contextmanager
def installed(plan: FaultPlan):
    """Scoped install — the chaos tests' idiom; always clears."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


def fault_point(site: str) -> None:
    """Raise ``FaultInjected`` if a rule fires at ``site``; else no-op."""
    if _plan is None:
        return
    r = _plan.check(site)
    if r is not None:
        _notify(site, _plan.hits[site], r.kind)
        raise FaultInjected(site, _plan.hits[site])


def fault_bytes(site: str, data: bytes) -> bytes:
    """Byte-stream site: pass ``data`` through, truncated to its first
    half on a ``truncate`` fire (a torn read — the tail of the chunk,
    usually mid-record, is lost), or raise on a ``raise`` fire."""
    if _plan is None:
        return data
    r = _plan.check(site)
    if r is None:
        return data
    _notify(site, _plan.hits[site], r.kind)
    if r.kind == "truncate":
        return data[: len(data) // 2]
    raise FaultInjected(site, _plan.hits[site])
