"""Command-line interface — subcommand-compatible with the reference
(traffic_classifier.py:174-246), with its defects fixed:

- ``knearest`` actually dispatches (the reference advertises it but checks
  ``kneighbors`` — NameError; SURVEY.md §2 defects)
- unknown subcommands get a real error instead of an unbound-variable crash
- the print cadence is per poll tick, not "every 10 ingested lines
  mislabeled as seconds" (reference :167)
- flow keys are stable hashes, not per-process ``hash()``

Sources: ``ryu`` (the real monitor subprocess — the reference's mode),
``replay`` (recorded capture file), ``synthetic`` (generated flow
population; no Mininet/OVS needed).

The classify path runs the full TPU pipeline: ingest → device flow table →
batched predict over the whole table → label decode → table render.
"""

from __future__ import annotations

import argparse
import contextlib
import os
import signal
import sys
import threading
import time

import numpy as np

SUBCOMMANDS = (
    "train",
    "retrain",
    "analyze",
    "logistic",
    "kmeans",
    "knearest",
    "kneighbors",
    "svm",
    "Randomforest",
    "randomforest",
    "gaussiannb",
)

# Checkpoint-dir resolution (traffic_classifier.py:230-240 hardcodes
# relative "models/" paths; we resolve: --checkpoint-dir > config file >
# $TCSDN_MODELS_DIR > ./models). Read at call time so tests/conftest can
# point the env at the reference tree before invoking main().


def _default_ckpt_dir() -> str:
    return os.environ.get("TCSDN_MODELS_DIR", "models")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="traffic_classifier_sdn_tpu",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("subcommand", choices=SUBCOMMANDS)
    p.add_argument(
        "traffic_type",
        nargs="?",
        help="traffic label to collect (train), or model family (retrain)",
    )
    p.add_argument(
        "--config", default=None, help="JSON config file (config.py schema)"
    )
    p.add_argument(
        "--native-checkpoint",
        default=None,
        help="load from an io/checkpoint.py directory instead of a "
        "reference pickle (classify), or save target (retrain)",
    )
    p.add_argument(
        "--data-dir",
        default=os.environ.get("TCSDN_DATA_DIR", "datasets"),
        help="training CSV directory (retrain subcommand and "
        "--source workload; default $TCSDN_DATA_DIR or ./datasets, "
        "the reference repo's own layout)",
    )
    p.add_argument(
        "--source",
        choices=("ryu", "controller", "replay", "synthetic", "workload"),
        default="ryu",
        help="telemetry source: 'ryu' spawns the reference's monitor "
        "command, 'controller' spawns our own OpenFlow 1.3 controller "
        "(controller/switch.py — no Ryu needed; switches connect to "
        "--of-port), 'replay' reads --capture, 'synthetic' generates "
        "flows, 'workload' generates class-conditional flows sampled "
        "from the reference datasets (the D-ITG stand-in)",
    )
    p.add_argument(
        "--of-port", type=int, default=6653,
        help="OpenFlow listen port for --source controller",
    )
    p.add_argument("--capture", help="capture file for --source replay")
    p.add_argument(
        "--sources", type=int, default=0, metavar="N",
        help="fan-in ingest tier (ingest/fanin.py): serve N "
        "independently supervised telemetry sources of the base "
        "--source kind through one bounded MPSC queue, each in its own "
        "flow-table namespace (source id folded into the flow key). A "
        "dead source quarantines and evicts only its own namespace; "
        "every other source keeps serving. 0 (default) = the direct "
        "single-collector path",
    )
    p.add_argument(
        "--source-spec", action="append", metavar="KIND:ARG",
        help="explicit fan-in source (repeatable; implies the fan-in "
        "tier, source ids by position): cmd:<monitor command>, "
        "capture:<path>, or synthetic:<n_flows> — mix live and replay "
        "sources in one serve",
    )
    p.add_argument(
        "--source-quarantine", type=float, default=5.0, metavar="SECS",
        help="grace window between a source's unclean death and the "
        "eviction of its namespace (default 5.0): a source restarted "
        "within it re-registers into its old namespace with its flows "
        "intact",
    )
    p.add_argument(
        "--source-interval", type=float, default=1.0, metavar="SECS",
        help="emission pacing for pull-paced fan-in sources "
        "(capture/synthetic): one poll tick per SECS (default 1.0, "
        "the reference monitor's cadence; 0 = flat out)",
    )
    p.add_argument(
        "--source-lockstep", action="store_true",
        help="pace pull-paced fan-in sources by consumer credit (one "
        "emission per serve tick) instead of wall clock — "
        "deterministic multi-source runs (tests, identity checks)",
    )
    p.add_argument(
        "--scenario", default=None, metavar="ID",
        help="replay one adversarial scenario from the campaign "
        "library (scenarios/library.py) through the real serve "
        "composition and print its SLO scorecard instead of serving "
        "live traffic — the post-incident replay hook (e.g. "
        "--scenario source_flap_storm; 'list' prints the matrix)",
    )
    p.add_argument(
        "--scenario-profile", choices=("t1", "cpu"), default="cpu",
        help="scenario scale for --scenario replay (default cpu, "
        "the committed-artifact shape)",
    )
    p.add_argument(
        "--scenario-obs-dir", default="scenario-postmortem",
        metavar="DIR",
        help="--scenario gate failures dump their post-mortem bundle "
        "(flight JSONL + metrics snapshot + timeline manifest) here",
    )
    p.add_argument(
        "--monitor-cmd",
        default=None,
        help="override the spawned monitor command (--source ryu or controller; for controller this replaces the built-in OpenFlow controller and --of-port is ignored)",
    )
    # None defaults are sentinels: a --config file fills them, then
    # main() applies the built-in defaults (see main()).
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="directory with reference-format model checkpoints "
        f"(default {_default_ckpt_dir()})",
    )
    p.add_argument(
        "--checkpoint-every",
        type=int,
        default=None,
        help="retrain: save train state every N SGD steps (logreg; "
        "0 = off, default from config train.checkpoint_every). With "
        "--train-state-dir, an interrupted retrain resumes from the last "
        "saved step and converges bit-identically.",
    )
    p.add_argument(
        "--train-state-dir",
        default=None,
        help="retrain: directory for resumable train-state checkpoints",
    )
    p.add_argument("--capacity", type=int, default=None)
    p.add_argument(
        "--shards", type=int, default=0,
        help="partition the flow table over an N-device mesh "
        "(parallel/table_sharded.py) — serving capacity beyond one "
        "chip's table; requires N visible devices. 1 is an EXPLICIT "
        "single-shard mesh (the sharded engine and programs on one "
        "device); 0 (default) is the single-device engine. Composes "
        "with --sources, --incremental, --native-ingest, serving "
        "checkpoints, and --drift (the region serve)",
    )
    p.add_argument(
        "--save-serve-state", default=None, metavar="FILE",
        help="on exit, checkpoint the live serving state (flow table + "
        "index) for a warm restart (io/serving_checkpoint.py)",
    )
    p.add_argument(
        "--restore-serve-state", default=None, metavar="FILE_OR_DIR",
        help="start from a serving-state checkpoint: every tracked flow "
        "resumes with its counters, rates, and slot intact. A directory "
        "resolves to its newest checkpoint that passes validation "
        "(torn/corrupt newest files roll back to the previous one)",
    )
    p.add_argument(
        "--serve-checkpoint-every", type=int, default=0, metavar="N",
        help="snapshot the live serving state between ticks every N poll "
        "ticks (0 disables) into --serve-checkpoint-dir — bounded-loss "
        "recovery for long-running serves, not just clean exits",
    )
    p.add_argument(
        "--serve-checkpoint-dir", default=None, metavar="DIR",
        help="rotation directory for periodic serving snapshots "
        "(ckpt-<tick>.npz, atomic writes, keep-N); restart with "
        "--restore-serve-state DIR to resume from the newest valid one",
    )
    p.add_argument(
        "--serve-checkpoint-keep", type=int, default=3,
        help="keep the newest N periodic snapshots (default 3)",
    )
    p.add_argument(
        "--serve-checkpoint-budget", type=float, default=0.2,
        metavar="FRAC",
        help="wall-clock budget guard: skip a due snapshot when "
        "checkpointing has already consumed more than FRAC of the serve "
        "loop's elapsed time (default 0.2; 0 disables the guard; skips "
        "are counted in the checkpoint_skipped metric)",
    )
    p.add_argument(
        "--idle-timeout",
        type=int,
        default=None,
        help="evict flows idle for N seconds (0 disables; default 60)",
    )
    p.add_argument(
        "--print-every", type=int, default=None,
        help="render every N poll ticks (default 10)",
    )
    p.add_argument(
        "--duration",
        type=float,
        default=None,
        help="train collection seconds (reference TIMEOUT, :27; "
        "default 900)",
    )
    p.add_argument(
        "--max-ticks", type=int, default=0, help="stop after N ticks (0=∞)"
    )
    p.add_argument(
        "--table-rows", type=int, default=64,
        help="max flows rendered per table (0 = all; classification "
        "always covers the whole table on device)",
    )
    p.add_argument(
        "--synthetic-flows", type=int, default=1024, help="synthetic source size"
    )
    p.add_argument(
        "--out", default=None,
        help="output path: training CSV (train) or figure directory "
        "(analyze)",
    )
    p.add_argument(
        "--native-ingest",
        choices=("auto", "on", "off"),
        default="auto",
        help="use the C++ ingest engine (native/flow_engine.cpp); auto "
        "falls back to the pure-Python batcher if g++ is unavailable",
    )
    p.add_argument(
        "--monitor-restarts", type=int, default=5,
        help="restart a dead monitor up to N times with exponential "
        "backoff (0 disables supervision; the reference just exits)",
    )
    p.add_argument(
        "--metrics-every", type=int, default=0,
        help="print an ingest/predict metrics line to stderr every N "
        "poll ticks (0 disables)",
    )
    p.add_argument(
        "--obs-port", type=int, default=None, metavar="PORT",
        help="serve the observability plane on this port (omit to "
        "disable; 0 binds an EPHEMERAL port — parallel runs never "
        "collide — reported in the startup line, the obs_port gauge, "
        "and the /healthz obs_port self-reference): /metrics "
        "(Prometheus text with per-stage stage_* latency series), "
        "/healthz (collector alive, last-tick age, checkpoint "
        "freshness, latency budget), /events (flight-recorder tail)",
    )
    p.add_argument(
        "--obs-dir", default=None, metavar="DIR",
        help="flight-recorder post-mortem directory: on an unhandled "
        "serve-loop exception, supervisor terminal failure, or SIGTERM "
        "the recent-event ring is dumped there as JSONL "
        "(obs/flight_recorder.py)",
    )
    p.add_argument(
        "--obs-dump-on-exit", action="store_true",
        help="also dump the flight-recorder ring into --obs-dir on a "
        "clean exit (the on-demand post-mortem)",
    )
    p.add_argument(
        "--obs-host", default="127.0.0.1", metavar="ADDR",
        help="bind address for --obs-port (default 127.0.0.1 — the "
        "events/metrics surface carries paths and failure detail, so "
        "exposing it beyond loopback is an explicit choice: pass "
        "0.0.0.0 for a real scrape target)",
    )
    p.add_argument(
        "--obs-stale-after", type=float, default=30.0, metavar="SECS",
        help="/healthz reports unhealthy (503) once the last poll tick "
        "is older than this many seconds (default 30)",
    )
    p.add_argument(
        "--obs-checkpoint-stale-after", type=float, default=0.0,
        metavar="SECS",
        help="/healthz also reports unhealthy once the last committed "
        "serving snapshot (or, before the first one, the serve start) "
        "is older than this many seconds (0 disables; pair with "
        "--serve-checkpoint-every so silent checkpoint failure pages "
        "instead of rotting)",
    )
    p.add_argument(
        "--latency-provenance", choices=("auto", "on", "off"),
        default="auto",
        help="record-level latency provenance (obs/latency.py): "
        "emit-stamp every telemetry batch host-side at its pump-read "
        "moment and fold per-hop boundaries (fan-in queue wait, parse, "
        "scatter dispatch, device completion, render visibility) into "
        "the e2e_emit_to_render_s / queue_wait_s / batch_wait_s / "
        "wf_* waterfall histograms and the /healthz latency block. "
        "Stamps never touch the wire format or the rendered output "
        "(byte-identical on vs off) and add zero traced ops. 'auto' "
        "enables it for single-device serves (the sharded read side "
        "has no single render-visibility point yet); 'off' disables "
        "stamping entirely",
    )
    p.add_argument(
        "--latency-slo", type=float, default=0.0, metavar="SECS",
        help="end-to-end latency SLO: when the running "
        "e2e_emit_to_render_s p99 crosses this, the breach transition "
        "is recorded to the flight recorder (latency.slo_breach, with "
        "the dominant stage) and the latency_slo_breached gauge flips "
        "(0 disables — the default)",
    )
    p.add_argument(
        "--device-obs", choices=("auto", "off"), default="auto",
        help="device-runtime telemetry (obs/device.py): subscribe to "
        "the jax.monitoring compile events (jit_compiles / "
        "jit_compile_s / compilation_cache_hits, device.compile and "
        "post-warmup device.retrace flight-recorder events), poll HBM "
        "gauges per tick, reconcile donation effectiveness on the "
        "double-buffered stages, and report the /healthz device block. "
        "'auto' arms it whenever any obs surface is on (--obs-port or "
        "--obs-dir); with --obs-dir it also runs the black-box perf "
        "ring (obs/perf_recorder.py, <obs-dir>/perf/) and the "
        "/profile endpoint. Byte-transparent: renders are identical "
        "on vs off",
    )
    p.add_argument(
        "--perf-ring-ticks", type=int, default=64, metavar="N",
        help="black-box perf ring: per-tick samples per committed "
        "segment (default 64; needs --obs-dir and --device-obs auto)",
    )
    p.add_argument(
        "--perf-ring-keep", type=int, default=16, metavar="N",
        help="black-box perf ring: committed segments retained on disk "
        "— older ones are pruned, bounding the ring at "
        "keep×ticks-per-segment ticks of evidence (default 16)",
    )
    p.add_argument(
        "--incremental", choices=("auto", "off"), default="auto",
        help="incremental active-set serving (serving/incremental.py): "
        "track which table rows each ingest scatter touched and "
        "re-predict ONLY those, merging fresh labels into a persistent "
        "device-resident label cache — prediction cost scales with "
        "per-tick churn instead of table capacity. Output is "
        "byte-identical to the full re-predict at every churn level "
        "(the cache invalidates wholesale on model promotions and "
        "degrade-rung changes); 'off' restores the full-table "
        "re-predict every render tick",
    )
    p.add_argument(
        "--pipeline", choices=("auto", "on", "off"), default="auto",
        help="pipelined serving (serving/pipeline.py): overlap host "
        "poll/parse/scatter with device predict/render through a "
        "bounded two-deep handoff (auto = on). When the device stage "
        "falls behind, render ticks coalesce (ticks_coalesced counter) "
        "instead of queueing unboundedly; 'off' restores the serial "
        "poll → parse → scatter → predict → render chain",
    )
    p.add_argument(
        "--degrade", choices=("auto", "off"), default="auto",
        help="degradation ladder (serving/degrade.py): wrap the device "
        "predict in a watchdog and demote to a host fallback (native "
        "C++ forest/KNN, eager-CPU jax otherwise) instead of wedging "
        "when the device stalls or errors; a shadow-batch probe path "
        "re-promotes after recovery. 'auto' enables it for device "
        "kernels on the single-device serve (sharded and host-native "
        "serves have no device rung to demote from); 'off' restores "
        "the bare predict path",
    )
    p.add_argument(
        "--device-deadline", type=float, default=2.0, metavar="SECS",
        help="watchdog deadline per device-stage dispatch (default 2.0; "
        "0 disables the deadline — erroring dispatches still demote, "
        "wedged ones block). The first dispatch gets 10x (min 60 s): "
        "it legitimately carries jit compile time",
    )
    p.add_argument(
        "--probe-every", type=float, default=5.0, metavar="SECS",
        help="base interval between recovery probes while degraded "
        "(default 5.0); failed probes back off exponentially from this "
        "base with full jitter",
    )
    p.add_argument(
        "--probe-successes", type=int, default=3, metavar="N",
        help="consecutive clean shadow-batch probes required to "
        "re-promote the device kernel (default 3); any failed probe "
        "resets the chain",
    )
    p.add_argument(
        "--openset", choices=("auto", "off"), default="off",
        help="open-set rejection tier (serving/openset.py): wrap the "
        "serving predict in an OpenSetGate that calibrates per-class "
        "feature statistics from the live stream's first windows, then "
        "serves an explicit 'unknown' label for rows whose features "
        "sit further than the calibrated threshold from EVERY known "
        "class — wrong-but-confident never serves. Byte-transparent "
        "until calibration completes and on closed-world traffic "
        "(output identical to 'off' — pinned serial+pipelined); "
        "composes with --drift (promotions re-base the gate on the "
        "retrain window; rejected rows never become training signal). "
        "'auto' enables it for single-device serves (sharded serves "
        "bind their predict at construction and are skipped)",
    )
    p.add_argument(
        "--openset-margin", type=float, default=3.0, metavar="M",
        help="open-set threshold margin: the rejection threshold is M "
        "times the worst (max) calibration-window score, so traffic "
        "from the calibration distribution is not rejected by "
        "construction (default 3.0; larger = more conservative)",
    )
    p.add_argument(
        "--openset-calibration-rows", type=int, default=4096,
        metavar="N",
        help="active labeled rows the open-set gate accumulates before "
        "freezing its per-class statistics and arming (default 4096); "
        "the gate is byte-transparent until then",
    )
    p.add_argument(
        "--drift", choices=("auto", "off"), default="off",
        help="online drift loop (serving/drift.py): monitor the live "
        "feature stream against a training-time reference, retrain in "
        "the background on sustained divergence, and hot-promote the "
        "fresh checkpoint through a parity-gated probe — wrong-but-"
        "fresh never promotes, a bad promotion rolls back. Works on "
        "both spines: single-device serves hot-swap through the "
        "DriftGate, sharded serves install through the engine's "
        "install_predict (per-shard read programs rebuilt, label "
        "caches reset); with no drift the output is byte-identical "
        "to 'off'. Requires --drift-dir",
    )
    p.add_argument(
        "--drift-follow", action="store_true",
        help="fleet mode (serving/fleet.py): adopt newer rotation "
        "members that PEER serves sharing this --drift-dir stage, as "
        "this serve's own candidates — each adoption still earns its "
        "own parity probes against this serve's live labels before "
        "installing, and a rejected adoption never discards the "
        "peer's member. Requires --drift auto",
    )
    p.add_argument(
        "--drift-dir", default=None, metavar="DIR",
        help="candidate checkpoint rotation for the drift loop: the "
        "boot model is seeded here (staged-commit save), retrained "
        "candidates land as model-<seq> members, and rollback resolves "
        "the newest member that still loads",
    )
    p.add_argument(
        "--drift-window", type=int, default=8, metavar="N",
        help="observations (render ticks) per drift window (default 8)",
    )
    p.add_argument(
        "--drift-threshold", type=float, default=4.0, metavar="Z",
        help="drift score a window must exceed to count as divergent: "
        "max over features of the EWMA z-shift vs the reference "
        "(default 4.0)",
    )
    p.add_argument(
        "--drift-trips", type=int, default=3, metavar="K",
        help="consecutive over-threshold windows before the retrain "
        "trips (default 3; one noisy window never retrains)",
    )
    p.add_argument(
        "--drift-class-tolerance", type=float, default=0.2,
        metavar="FRAC",
        help="class-mix sensitivity: a window's max per-class "
        "frequency delta vs the reference is divided by this before "
        "comparing to --drift-threshold (default 0.2, so a full "
        "label-mix inversion scores 5.0 — above the default "
        "threshold; values >= 1/threshold make class-mix drift "
        "undetectable)",
    )
    p.add_argument(
        "--drift-probe-successes", type=int, default=3, metavar="N",
        help="consecutive clean parity probes a candidate checkpoint "
        "needs before hot promotion (default 3)",
    )
    p.add_argument(
        "--drift-parity", type=float, default=1.0, metavar="FRAC",
        help="minimum probe agreement between the candidate's labels "
        "and the live model's on the shadow batch for a probe to "
        "count as clean (default 1.0 — exact parity; loosen for "
        "families whose refit legitimately disagrees near decision "
        "boundaries). kmeans compares mode-matched (cluster ids are a "
        "permutation), so the default applies there too",
    )
    p.add_argument(
        "--retrain-deadline", type=float, default=300.0, metavar="SECS",
        help="abandon a background retrain that outlives this many "
        "seconds (default 300; the serve keeps the old model and the "
        "loop resumes watching)",
    )
    p.add_argument(
        "--actuation", choices=("off", "dry-run", "push"), default="off",
        help="actuation tier (serving/actuation.py): compile per-class "
        "--policy actions into OF1.3 flow-mods, hysteresis-gated so a "
        "label must hold for --actuation-k-install consecutive render "
        "ticks before its rule installs (an open-set 'unknown' blip or "
        "single-tick flip never touches the switch), and retracted "
        "only after --actuation-k-retract deviating ticks. 'dry-run' "
        "renders intended mods to stderr + ring events without a "
        "socket; 'push' programs the switch at --actuation-switch and "
        "degrades itself to dry-run with backoff re-probe on ANY "
        "actuation failure. 'off' (default) is byte-transparent: "
        "stdout is identical with the tier absent. Single-device "
        "serves only (the sharded render has no per-row label surface "
        "to gate on)",
    )
    p.add_argument(
        "--policy", default=None, metavar="SPEC",
        help="declarative per-class actions for --actuation: comma-"
        "separated CLASS=ACTION clauses where ACTION is queue:N (QoS "
        "queue), meter:N (rate limit), drop, or mirror:P (copy to "
        "port P, forward normally). Classes without a clause are "
        "observe-only; 'unknown' may never carry one",
    )
    p.add_argument(
        "--actuation-switch", default=None, metavar="HOST:PORT",
        help="switch address for --actuation push (the OF1.3 peer the "
        "actuation plane dials; tools/fake_switch.py AccountingSwitch "
        "speaks the server side for replay tests)",
    )
    p.add_argument(
        "--actuation-k-install", type=int, default=3, metavar="K",
        help="consecutive stable-label render ticks before a rule "
        "installs (default 3)",
    )
    p.add_argument(
        "--actuation-k-retract", type=int, default=3, metavar="K",
        help="consecutive deviating render ticks before an installed "
        "rule retracts (default 3); a deviation episode that ends "
        "sooner is a suppressed flap",
    )
    p.add_argument(
        "--actuation-span", default=None, metavar="SIDS",
        help="comma-separated source ids this serve may actuate "
        "(fleet blast radius: members only program rules for slots "
        "their own span owns; default: every source)",
    )
    p.add_argument(
        "--warmup", action="store_true",
        help="AOT-compile the serving programs at startup "
        "(serving/warmup.py: donated scatter per batch bucket, feature "
        "projection, predict, render gather) so the first tick runs "
        "hot instead of paying a multi-second compile stall",
    )
    p.add_argument(
        "--compilation-cache-dir", default=None, metavar="DIR",
        help="JAX persistent compilation cache: compiles (including "
        "--warmup's) land here and restarts — including "
        "checkpoint-rollback restarts — reuse them instead of "
        "recompiling",
    )
    p.add_argument(
        "--knn-topk", default=None, metavar="IMPL",
        help="KNN serving top-k implementation (models/__init__.py "
        "resolve_knn_topk): sort (default), argmax, hier[<group>], "
        "screened[<group>], pallas (TPU-only), native (exact-f64 C++ "
        "host evaluator — single-device host serving), or ivf[<nprobe>] "
        "(the APPROXIMATE cluster-probed tier, ops/knn_ivf.py — "
        "explicit opt-in with a measured recall artifact). The flag "
        "wins over the TCSDN_KNN_TOPK env var (kept as fallback); "
        "unknown values are a usage error",
    )
    p.add_argument(
        "--profile-dir", default=None,
        help="capture a jax.profiler trace of the run into this directory",
    )
    return p


def _use_native(args) -> bool:
    if args.native_ingest == "off":
        return False
    from .native import engine as native_engine

    ok = native_engine.available()
    if args.native_ingest == "on" and not ok:
        sys.exit("ERROR: --native-ingest on, but the C++ engine won't build")
    return ok


def _fanin_active(args) -> bool:
    """The fan-in ingest tier engages on --sources N or any
    --source-spec entry."""
    return getattr(args, "sources", 0) > 0 or bool(
        getattr(args, "source_spec", None)
    )


def _provenance_on(args, sharded: bool = False) -> bool:
    """--latency-provenance resolution: 'auto' arms the latency plane
    for single-device serves (the sharded read side has no single
    render-visibility point to close an e2e measurement at)."""
    mode = getattr(args, "latency_provenance", "off")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return not sharded


def _resolved_monitor_cmd(args) -> str:
    """The monitor command a subprocess source spawns (--monitor-cmd
    override, the built-in controller, or the reference's Ryu line)."""
    from .ingest.collector import DEFAULT_MONITOR_CMD

    if args.source == "controller":
        return args.monitor_cmd or (
            f"{sys.executable} -m traffic_classifier_sdn_tpu.controller "
            f"--port {args.of_port}"
        )
    return args.monitor_cmd or DEFAULT_MONITOR_CMD


def _stamped_ticks(gen):
    """Emit-stamp each pull-paced direct-source batch as it is
    generated — the unpumped counterpart of the fan-in pump's
    ``_deliver`` stamp (replay injection / synthetic generation), and
    like it stamps only the batch's LEAD record: one generation moment
    per batch. An absorbed ``obs.stamp`` fire leaves that batch
    unstamped; the batch still flows."""
    from .ingest.protocol import stamp_records

    for batch in gen:
        stamp_records(batch[:1])
        yield batch


def _tick_source(args, raw: bool = False, recorder=None, probe_out=None,
                 stamp: bool = False):
    """Yield one batch of telemetry per poll tick: a list of
    TelemetryRecords, or raw pipe bytes when ``raw`` (the native-engine
    fast path — no per-line Python anywhere between the pipe and C++).

    ``recorder`` threads the obs flight recorder into the collector/
    supervisor stack; ``probe_out`` (a dict) receives a ``"probe"``
    callable reporting collector liveness once a subprocess source
    starts — the /healthz collector-alive feed (replay/synthetic
    sources set nothing: there is no collector to be dead). With the
    fan-in tier (--sources/--source-spec) it also receives the
    ``"fanin"`` tier object: the serve loop polls it for expired
    quarantines and /healthz reads its per-source roster.

    ``stamp`` arms latency-provenance emit stamping (obs/latency.py):
    fan-in pumps stamp at ``_deliver`` (raw-mode pumps carry the
    pump-read stamp on the queue entry itself — the provenance seam
    survives byte delivery), subprocess collectors at pipe parse on the
    reader thread, pull-paced direct sources at generation; DIRECT raw
    byte sources cannot stamp (no records host-side) and the serve
    loop degrades them to arrival-time provenance."""
    if _fanin_active(args):
        from .ingest import fanin
        from .utils.metrics import global_metrics

        try:
            specs = fanin.specs_from_cli(
                args.source, max(1, args.sources), args.source_spec,
                capture=args.capture,
                monitor_cmd=_resolved_monitor_cmd(args),
                synthetic_flows=args.synthetic_flows,
                max_restarts=args.monitor_restarts or 0,
                interval=args.source_interval,
                lockstep=args.source_lockstep,
            )
        except ValueError as e:
            sys.exit(f"ERROR: {e}")
        tier = fanin.FanInIngest(
            specs, quarantine_s=args.source_quarantine,
            metrics=global_metrics, recorder=recorder, stamp=stamp,
            # native ingest rides the raw wire end to end: pumps
            # deliver bytes, ticks() yields RawTick batches, and the
            # C++ keyer namespaces per (sid, payload) pair
            raw=raw,
        )
        if probe_out is not None:
            probe_out["probe"] = tier.alive
            probe_out["fanin"] = tier
        yield from tier.ticks()
        return
    if args.source == "replay":
        if not args.capture:
            sys.exit("--source replay requires --capture FILE")
        from .ingest.replay import iter_capture

        gen = iter_capture(args.capture)
        yield from (_stamped_ticks(gen) if stamp else gen)
    elif args.source == "synthetic":
        from .ingest.replay import SyntheticFlows

        syn = SyntheticFlows(n_flows=args.synthetic_flows)

        def _syn():
            while True:
                yield syn.tick()

        yield from (_stamped_ticks(_syn()) if stamp else _syn())
    elif args.source == "workload":
        from .ingest.workload import ClassWorkload, class_delta_pools

        pools = class_delta_pools(args.data_dir)
        wl = ClassWorkload(
            pools,
            flows_per_class=max(1, args.synthetic_flows // len(pools)),
        )

        def _wl():
            while True:
                yield wl.tick()

        yield from (_stamped_ticks(_wl()) if stamp else _wl())
    else:
        from .ingest.collector import SubprocessCollector

        cmd = _resolved_monitor_cmd(args)
        if args.monitor_restarts:
            from .ingest.supervisor import SupervisedCollector
            from .utils.metrics import global_metrics

            coll = SupervisedCollector(
                cmd, raw=raw, max_restarts=args.monitor_restarts,
                metrics=global_metrics, recorder=recorder, stamp=stamp,
            )
        else:
            coll = SubprocessCollector(cmd, raw=raw, recorder=recorder,
                                       stamp=stamp)
        if probe_out is not None:
            probe_out["probe"] = lambda: coll.running
        coll.start()
        try:
            while True:
                first = coll.wait_record(timeout=2.0)
                if first is None:
                    if not coll.running:
                        break  # monitor exited and the queue is drained
                    continue
                time.sleep(0.05)  # let the 1 Hz burst of lines arrive
                rest = coll.poll_records()
                if raw:
                    yield first + b"".join(rest)
                else:
                    yield [first] + rest
        finally:
            coll.stop()


def _run_classify(args) -> None:
    # lockdep witness (utils/locktrace.py): TCSDN_LOCKTRACE=1 wraps
    # every project lock constructed from here on, so the serve's real
    # schedules become lock-ordering evidence. Armed in this thin
    # wrapper so the monkeypatched factories can NEVER leak: any exit
    # out of the serve body — the flag-validation sys.exit guards, a
    # failed restore, an exception before the serve loop's own
    # try/finally — lands in this finally, which uninstalls and
    # reports iff the serve body's finish didn't already run
    from .utils import locktrace, syncguard

    lock_witness = locktrace.maybe_trace_from_env()
    # device-boundary witness (utils/syncguard.py): TCSDN_SYNCGUARD=1
    # site-keys every host↔device conversion from here on and checks
    # it live against the static hot-path sync budget — same
    # leak-proofing shape as the lock witness above
    sync_witness = syncguard.maybe_guard_from_env()
    try:
        _run_classify_armed(args, lock_witness, sync_witness)
    finally:
        if (lock_witness is not None
                and locktrace._installed is lock_witness):
            locktrace.finish(lock_witness)
        if (sync_witness is not None
                and syncguard._installed is sync_witness):
            syncguard.finish(sync_witness)


def _run_classify_armed(args, lock_witness, sync_witness=None) -> None:
    from .ingest.batcher import FlowStateEngine
    from .models import (
        SUBCOMMAND_ALIASES,
        jit_serving_fn,
        load_reference_model,
    )
    from .io.sklearn_import import REFERENCE_CHECKPOINTS

    # serve-durability flag validation runs before any model/device work
    # so misuse fails fast (and identically with or without checkpoints)
    #
    # --shards >= 1 is the sharded spine; 1 is an EXPLICIT single-shard
    # mesh (same wire scatter, same shard_mapped read programs, one
    # device) — it used to silently mean "un-sharded", which made
    # "--shards 1" lie about which engine served. Serving checkpoints,
    # the fan-in tier, and the drift loop all compose with the sharded
    # spine now; the region serve is their fusion.
    sharded = args.shards >= 1
    if args.serve_checkpoint_every and not args.serve_checkpoint_dir:
        sys.exit("--serve-checkpoint-every needs --serve-checkpoint-dir")
    if args.obs_dump_on_exit and not args.obs_dir:
        sys.exit("--obs-dump-on-exit needs --obs-dir (the dump target)")
    if args.latency_provenance == "on" and sharded:
        sys.exit(
            "--latency-provenance on is single-device: the sharded "
            "read side has no single render-visibility point to close "
            "an end-to-end measurement at (auto skips it)"
        )
    if args.drift != "off" and not args.drift_dir:
        sys.exit(
            "--drift auto needs --drift-dir (the candidate checkpoint "
            "rotation and rollback target)"
        )
    if args.drift_follow and args.drift == "off":
        sys.exit(
            "--drift-follow needs --drift auto (the follower IS the "
            "drift loop, adopting peers' rotation members)"
        )
    if args.actuation != "off" and not args.policy:
        sys.exit("--actuation needs --policy (the per-class action spec)")
    if args.policy and args.actuation == "off":
        sys.exit(
            "--policy without --actuation does nothing — pass "
            "--actuation dry-run|push (off is the byte-transparent "
            "default)"
        )
    if args.actuation == "push" and not args.actuation_switch:
        sys.exit("--actuation push needs --actuation-switch HOST:PORT")
    if args.actuation != "off" and sharded:
        sys.exit(
            "--actuation is single-device: the hysteresis tier rides "
            "the per-row label render plus the open-set/drift gates, "
            "which the sharded spine's fused read programs don't expose"
        )

    name = SUBCOMMAND_ALIASES[args.subcommand]
    if args.native_checkpoint:
        from .io.checkpoint import load_model

        model = load_model(args.native_checkpoint)
    else:
        ckpt = f"{args.checkpoint_dir}/{REFERENCE_CHECKPOINTS[name]}"
        model = load_reference_model(args.subcommand, ckpt)
    # the serving-optimized (predict_fn, params) pair, resolved as one
    # unit (GEMM-form forest, chunked KNN/SVC; canonical otherwise),
    # jitted unless host-native (models.jit_serving_fn owns that rule)
    serve_fn, serve_params = model.serving_path()
    predict = jit_serving_fn(serve_fn)

    from .utils.metrics import global_metrics as m
    from .obs import FlightRecorder, Tracer
    from .utils import locktrace

    # the obs plane: the flight recorder exists whenever any obs surface
    # is on (it feeds both /events and the post-mortem dump); the tracer
    # is ALWAYS on — per-tick spans cost microseconds and give
    # --metrics-every its stage_* latency attribution unconditionally
    recorder = (
        FlightRecorder()
        if (args.obs_port is not None or args.obs_dir) else None
    )
    if lock_witness is not None and recorder is not None:
        # live attachment: a violation lands in the ring the moment the
        # offending edge is observed, so post-mortem dumps carry it
        lock_witness.recorder = recorder
    tracer = Tracer(metrics=m, recorder=recorder)

    # Latency provenance (obs/latency.py): the record-level end-to-end
    # budget plane. Like the tracer it is always on (auto) for
    # single-device serves — stamps are host-side only, add zero
    # traced ops, and the fold costs microseconds per render tick; the
    # rendered output is byte-identical on vs off (pinned in
    # tests/test_latency.py) and the bench A/B bounds stamping under
    # 3% of tick p50 (tools/bench_e2e_live.py).
    lat = None
    if _provenance_on(args, sharded):
        from .obs import LatencyProvenance

        lat = LatencyProvenance(
            metrics=m, recorder=recorder, slo_s=args.latency_slo,
        )

    # Device-runtime telemetry (obs/device.py): armed with the rest of
    # the obs plane ('auto' + any obs surface on). Attached BEFORE the
    # engine is built so table-construction and restore compiles are
    # counted too; the retrace edge arms only after warmup. With
    # --obs-dir the black-box perf ring rides along — per-tick samples
    # committed to <obs-dir>/perf/ as atomic segments, so a kill -9 or
    # a wedged device leaves on-disk evidence with no dump cooperation.
    dev = None
    perf = None
    if args.device_obs != "off" and recorder is not None:
        from .obs import DeviceTelemetry

        dev = DeviceTelemetry(metrics=m, recorder=recorder)
        dev.attach()
        if args.obs_dir:
            from .obs import PerfRecorder

            perf = PerfRecorder(
                os.path.join(args.obs_dir, "perf"),
                ticks_per_segment=args.perf_ring_ticks,
                keep_segments=args.perf_ring_keep,
                metrics=m,
            )

    # --native-ingest composes with --sources N: the C++ engine keys
    # per-source namespaces (tck_feed_lines folds the source id) and
    # owns the per-slot source map behind namespace eviction, so
    # multi-source fan-in rides the raw wire path end to end
    use_native = _use_native(args)
    if sharded:
        from .parallel import mesh as meshlib
        from .parallel import table_sharded as tsh

        if getattr(serve_fn, "host_native", False):
            # the sharded engine jits + shard_maps predict_fn — the one
            # thing the host_native contract forbids (models/__init__)
            sys.exit(
                "host-native kernels (TCSDN_FOREST_KERNEL=native, "
                "TCSDN_KNN_TOPK=native) are single-device host serving; "
                "use a device kernel with --shards"
            )
        if args.table_rows <= 0:
            # the sharded render merges bounded per-shard candidates; an
            # unbounded ("0 = all") table would be an O(capacity) fetch
            sys.exit(
                "--shards requires a bounded --table-rows "
                "(the sharded render merges per-shard top-k candidates)"
            )
        import jax as _jax

        _devs = _jax.devices()
        if args.shards > len(_devs):
            sys.exit(
                f"--shards {args.shards} needs {args.shards} visible "
                f"devices (have {len(_devs)})"
            )
        # an explicit sub-mesh (--shards 1 included) takes the leading
        # devices; make_mesh's all-devices default stays for the tools
        mesh = meshlib.make_mesh(
            n_data=args.shards, n_state=1, devices=_devs[:args.shards],
        )
        if args.restore_serve_state:
            from .io import serving_checkpoint as _sc

            # composed-spine restore: the checkpoint's GLOBAL leaf
            # layout scatters across the mesh (restore_sharded) — the
            # format is spine-agnostic, so a single-device checkpoint
            # restores into a sharded serve and vice versa
            try:
                engine = _sc.restore_sharded(
                    args.restore_serve_state, mesh,
                    predict_fn=serve_fn, params=serve_params,
                    table_rows=args.table_rows,
                    incremental=args.incremental != "off",
                    recorder=recorder,
                )
            except ValueError as e:
                sys.exit(str(e))
            if engine.capacity != args.capacity:
                print(
                    f"WARNING: --capacity {args.capacity} ignored — "
                    f"the checkpoint fixes capacity at "
                    f"{engine.capacity}",
                    file=sys.stderr,
                )
                args.capacity = engine.capacity
            print(
                f"restored {engine.num_flows()} tracked flows from "
                f"{args.restore_serve_state}",
                file=sys.stderr,
            )
        else:
            engine = tsh.ShardedFlowEngine(
                mesh,
                args.capacity, predict_fn=serve_fn, params=serve_params,
                table_rows=args.table_rows,
                native=use_native,
                incremental=args.incremental != "off",
            )
    elif args.restore_serve_state:
        from .io import serving_checkpoint as _sc

        engine = _sc.restore(args.restore_serve_state, recorder=recorder)
        if args.incremental != "off":
            # restored rows predate the label cache: everything starts
            # dirty, so the first render re-predicts the whole table
            engine.enable_dirty_tracking()
        if engine.table.capacity != args.capacity:
            print(
                f"WARNING: --capacity {args.capacity} ignored — the "
                f"checkpoint fixes capacity at {engine.table.capacity}",
                file=sys.stderr,
            )
            args.capacity = engine.table.capacity
        print(
            f"restored {engine.num_flows()} tracked flows from "
            f"{args.restore_serve_state}",
            file=sys.stderr,
        )
    else:
        engine = FlowStateEngine(
            args.capacity, native=use_native,
            track_dirty=args.incremental != "off",
        )
    if dev is not None and hasattr(engine, "donation_probe"):
        # donation-effectiveness ledger on the donated wire scatter
        # (the sharded engine has no single donated table to probe)
        engine.donation_probe = dev.note_donation

    # Degradation ladder (serving/degrade.py): wraps the device predict
    # so a wedged/erroring dispatch demotes to a host fallback instead
    # of taking the serve loop down. Built BEFORE warmup so warmup
    # routes through it (the ladder is host_native → warmup also primes
    # top_active_flags, the ranked-read program its serving path uses,
    # and the first device call's compile consumes the ladder's
    # first-call grace deadline, not a serving tick's budget). 'auto'
    # skips the serves with no device rung to demote from: sharded
    # (the sharded engine owns its predict dispatch) and already
    # host-native kernels.
    degrade = None
    if (args.degrade != "off" and not sharded
            and not getattr(predict, "host_native", False)):
        from .models import resolve_fallback
        from .serving.degrade import DegradeLadder

        fallback = resolve_fallback(name, model.params)
        degrade = DegradeLadder(
            predict, fallback,
            deadline=args.device_deadline,
            probe_every=args.probe_every,
            probe_successes=args.probe_successes,
            metrics=m, recorder=recorder,
        )
        predict = degrade

    # persistent-cache wiring must precede warmup so its compiles land
    # on disk; it also helps un-warmed serves — lazy compiles persist,
    # and the NEXT restart (including a checkpoint-rollback restart)
    # starts hot
    if args.compilation_cache_dir:
        from .serving.warmup import enable_compilation_cache

        enable_compilation_cache(args.compilation_cache_dir)
    if args.warmup:
        from .serving.warmup import warmup_serving

        wstats = warmup_serving(
            engine, predict, serve_params,
            table_rows=args.table_rows,
            idle_timeout=args.idle_timeout,
            incremental=args.incremental != "off",
        )
        print(
            f"warmup: compiled {len(wstats['warmed'])} serving "
            f"programs in {wstats['seconds']:.2f}s "
            f"({', '.join(wstats['warmed'])})",
            file=sys.stderr,
        )
        if dev is not None:
            # arm the retrace edge: every compile from here on is a
            # device.retrace event + retraces_after_warmup count. A
            # surface warmup does not cover (an --openset calibration
            # fold, a drift parity probe) registers honestly — it IS a
            # compile the warmup contract missed.
            dev.mark_warmup_complete()

    # Drift loop (serving/drift.py): on the single-device spine it
    # wraps the (possibly ladder-guarded) predict in a DriftGate — a
    # transparent passthrough until the first promotion, the hot-swap
    # point after it. The SHARDED spine compiles its predict INTO the
    # per-shard read programs, so there is no call site to wrap:
    # ShardedDriftGate routes install through engine.install_predict
    # (rebuilds the read programs, resets the per-shard label caches)
    # and the serve loop hands it per-render (features, labels)
    # captures explicitly. Built AFTER warmup so warmup primes the
    # BOOT model's programs (a candidate's serving program compiles
    # during its parity probes — the exact serving shape — so the
    # first post-swap tick is already warm).
    drift = None
    drift_feed = None  # sharded capture hand-off (fed per render tick)
    degrade_surface = degrade  # what the render/healthz paths consult
    if args.drift != "off":
        from .serving.drift import (
            DriftController,
            DriftGate,
            GateLadderView,
            ShardedDriftGate,
        )

        from .serving.drift import default_build_serving

        _build_bare = default_build_serving(
            name, tuple(model.classes.names)
        )
        if sharded:
            gate = ShardedDriftGate(engine)
            drift_feed = gate

            def _build_promoted(params):
                """Candidate params → the serving pair a promotion
                installs on the sharded spine. A host-native candidate
                can never install here (its predict would have to
                compile into shard_map) — raising makes it a counted
                retrain failure instead of a mid-promotion crash."""
                pred, p = _build_bare(params)
                if getattr(pred, "host_native", False):
                    raise RuntimeError(
                        "host-native candidate kernels cannot install "
                        "on the sharded spine"
                    )
                return pred, p
        else:
            gate = DriftGate(predict)

            def _build_promoted(params):
                """Candidate params → the serving pair a promotion
                installs: the default resolution (models.serving_path +
                jit rule), PLUS the degradation ladder when --degrade
                engaged — a promoted checkpoint must keep the
                watchdog/fallback guarantees, not silently shed them at
                the first swap."""
                pred, p = _build_bare(params)
                if degrade is None or getattr(pred, "host_native", False):
                    return pred, p
                from .models import resolve_fallback
                from .serving.degrade import DegradeLadder

                return DegradeLadder(
                    pred, resolve_fallback(name, params),
                    deadline=args.device_deadline,
                    probe_every=args.probe_every,
                    probe_successes=args.probe_successes,
                    metrics=m, recorder=recorder,
                ), p

        drift = DriftController(
            gate,
            family=name,
            classes=tuple(model.classes.names),
            directory=args.drift_dir,
            window=args.drift_window,
            threshold=args.drift_threshold,
            trips=args.drift_trips,
            class_tolerance=args.drift_class_tolerance,
            probe_successes=args.drift_probe_successes,
            parity_min=args.drift_parity,
            # a refit clustering orders its centroids arbitrarily —
            # raw kmeans cluster ids are a permutation of the live
            # model's, so parity must mode-match before comparing
            parity_mode=(
                "mode-matched" if name == "kmeans" else "exact"
            ),
            retrain_deadline=args.retrain_deadline,
            reference=getattr(engine, "feature_reference", None),
            build_serving=_build_promoted,
            boot_params=model.params,
            metrics=m,
            recorder=recorder,
            # fleet mode: adopt newer rotation members staged by peer
            # serves sharing --drift-dir (each adoption still earns its
            # own parity probes before installing here)
            follow_rotation=args.drift_follow,
        )
        if not sharded:
            predict = gate
            if degrade is not None:
                # promotions rebuild the ladder around the new kernel,
                # so the render STALE column and /healthz must follow
                # the gate's CURRENT ladder, not the boot object
                degrade_surface = GateLadderView(gate, degrade)

    # Open-set rejection tier (serving/openset.py): the OUTERMOST
    # predict wrapper — drift promotions hot-swap INSIDE it, so a
    # promoted model is gated exactly like the boot model. Rows
    # further than the calibrated threshold from every known class
    # serve an explicit 'unknown' label; the model's class list is
    # extended so every render path decodes the unknown index to
    # "unknown" (never "?" and never a fabricated known class).
    # 'auto' skips sharded serves: unlike --drift (whose sharded
    # adapter swaps whole models through install_predict), per-row
    # rejection would need the unknown index threaded through every
    # per-shard read program — a deliberate remaining carve-out.
    openset = None
    if args.openset != "off" and not sharded:
        import dataclasses

        from .models.base import ClassList
        from .serving.openset import OpenSetGate

        # a restored serving checkpoint carries the gate's armed
        # reference (stats + threshold): the gate boots ARMED against
        # what it served with — a restart mid-novel-episode must not
        # re-calibrate ON the novel traffic and unlearn its rejection
        restored_ref = getattr(engine, "feature_reference", None) or {}
        os_keys = (
            "openset_mean", "openset_inv_std", "openset_threshold",
        )
        openset = OpenSetGate(
            predict, n_classes=len(model.classes.names),
            margin=args.openset_margin,
            calibration_rows=args.openset_calibration_rows,
            metrics=m, recorder=recorder,
            reference=(
                {
                    k: restored_ref[k]
                    for k in (*os_keys, "openset_calibrated_rows")
                    if k in restored_ref
                }
                if all(k in restored_ref for k in os_keys) else None
            ),
        )
        predict = openset
        model = dataclasses.replace(
            model,
            classes=ClassList(tuple(model.classes.names) + ("unknown",)),
        )
        if drift is not None:
            # promotions re-base the gate on the retrain window, and
            # the monitor observes the gate's labels (the unknown
            # fraction becomes the class-mix drift signal)
            drift.set_openset(openset)

    # Incremental active-set serving (serving/incremental.py): wraps
    # the FINAL predict composition (ladder- and gate-wrapped) so its
    # label cache watches the composed label_epoch — a promotion
    # hot-swap or degrade rung change invalidates the whole cache.
    # Built AFTER warmup primed the boot model and AFTER the drift
    # gate exists; the single-device serial and pipelined loops both
    # read their labels from it.
    inc = None
    if args.incremental != "off" and not sharded:
        from .serving.incremental import IncrementalLabels

        inc = IncrementalLabels(
            engine, predict, serve_params, degrade=degrade_surface,
            metrics=m, recorder=recorder, tracer=tracer,
        )

    # Actuation tier (serving/actuation.py): built AFTER the open-set
    # gate extended the class list, so --policy validates against the
    # same names every render decodes (and 'unknown' is rejectable by
    # name). The plane only ever *observes* rendered rows — stdout is
    # byte-identical to --actuation off by construction, and every
    # actuation failure is absorbed into dry-run + backoff re-probe.
    actuation = None
    if args.actuation != "off":
        from .controller.policy import parse_policy
        from .serving.actuation import ActuationPlane, SwitchLink

        try:
            policy = parse_policy(args.policy, tuple(model.classes.names))
        except ValueError as e:
            sys.exit(str(e))
        link_factory = None
        if args.actuation == "push":
            sw_host, _, sw_port = args.actuation_switch.rpartition(":")
            if not sw_host or not sw_port.isdigit():
                sys.exit("--actuation-switch wants HOST:PORT")

            def link_factory(host=sw_host, port=int(sw_port)):
                return SwitchLink(host, port)

        span = None
        if args.actuation_span:
            try:
                span = frozenset(
                    int(s) for s in args.actuation_span.split(",")
                    if s.strip()
                )
            except ValueError:
                sys.exit(
                    "--actuation-span wants comma-separated integer "
                    "source ids"
                )
        actuation = ActuationPlane(
            policy, mode=args.actuation,
            k_install=args.actuation_k_install,
            k_retract=args.actuation_k_retract,
            link_factory=link_factory,
            span=span,
            slots_for_source=(
                engine.slots_for_source if span is not None else None
            ),
            metrics=m, recorder=recorder,
        )

    server = None
    health = None
    probe_out: dict = {}
    if args.obs_port is not None:
        from .obs import ExpositionServer, HealthState

        health = HealthState(
            max_tick_age_s=args.obs_stale_after,
            max_checkpoint_age_s=(
                args.obs_checkpoint_stale_after or None
            ),
        )
        health.model_loaded()  # the model_age_s staleness anchor
        if degrade_surface is not None:
            # /healthz reports 200-but-degraded with the ladder rung —
            # a degraded serve still answers every tick (the surface
            # follows promotions when the drift loop is on)
            health.set_degrade(degrade_surface.status)
        if drift is not None:
            # the drift loop's self-report + promotion timestamps: an
            # operator can tell "healthy but ancient" from "freshly
            # promoted" by model_age_s alone
            health.set_drift(drift.status)
            drift.set_health(health)
        if inc is not None:
            # label-cache coverage: how much of the table the last
            # render served from cache vs re-predicted
            health.set_label_cache(inc.status)
        if openset is not None:
            # the rejection tier's self-report: state, calibrated
            # threshold, rejection counters
            health.set_openset(openset.status)
        if actuation is not None:
            # the actuation block: live state (push/dry-run/degraded/
            # demoted), rule FSM census, the exact ledger, flap counts
            health.set_actuation(actuation.status)
        if lat is not None:
            # the live e2e budget: p50/p99 since emit + dominant stage
            health.set_latency(lat.status)
        if dev is not None:
            # compile/retrace counters, HBM watermark, last-dispatch
            # age, donation effectiveness — the device block
            health.set_device(dev.status)
    profiler = None
    if dev is not None and args.obs_dir:
        from .obs import ProfilerCapture

        profiler = ProfilerCapture(
            os.path.join(args.obs_dir, "profile"),
            metrics=m, recorder=recorder,
        )
    if args.obs_port is not None:
        from .obs import ExpositionServer

        server = ExpositionServer(
            m, recorder=recorder, health=health, port=args.obs_port,
            host=args.obs_host, profiler=profiler,
        )
        server.start()
        # --obs-port 0 binds ephemerally: report the ACTUAL port on
        # every self-describing surface — the startup line, the
        # obs_port gauge (scrapable and readable in-process before any
        # stderr parsing), and the /healthz self-reference
        health.set_obs_port(server.port)
        m.set("obs_port", server.port)
        print(
            f"observability plane on port {server.port} "
            f"(/metrics /healthz /events"
            f"{' /profile' if profiler is not None else ''})",
            file=sys.stderr,
        )
    # SIGTERM (the orchestrator's shutdown signal) must leave a
    # post-mortem before dying. The handler itself does the MINIMUM —
    # flag + raise: it runs on the main thread between bytecodes, and
    # touching the recorder there would deadlock if the interrupted
    # frame already holds the (non-reentrant) ring lock mid-record. The
    # actual record+dump happens in the except path below, after stack
    # unwinding has released every lock. Signal handlers install only
    # from the main thread (the CPython rule); embedded callers on
    # other threads simply skip the hook.
    prev_sigterm = None
    sigterm_hooked = False
    sigterm_seen = False
    # SIGUSR1: live flight-recorder + metrics-snapshot dump WITHOUT
    # exiting — the on-demand mid-incident snapshot. Same flag+deferred
    # discipline as SIGTERM: the handler only flips a dict flag (it
    # must never touch the non-reentrant ring lock from a signal
    # frame); the serve loop performs the dump between ticks.
    usr1 = {"due": False}
    prev_sigusr1 = None
    sigusr1_hooked = False
    if (recorder is not None and args.obs_dir
            and threading.current_thread() is threading.main_thread()):
        def _on_sigterm(signum, frame):
            nonlocal sigterm_seen
            sigterm_seen = True
            raise SystemExit(143)

        prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        sigterm_hooked = True
        if hasattr(signal, "SIGUSR1"):
            def _on_sigusr1(signum, frame):
                usr1["due"] = True  # flag only — dump deferred to loop

            prev_sigusr1 = signal.signal(signal.SIGUSR1, _on_sigusr1)
            sigusr1_hooked = True
    obs_faults = (
        recorder.observing_faults() if recorder is not None
        else contextlib.nullcontext()
    )
    try:
        with obs_faults:
            _serve_loop(args, engine, model, predict, serve_params, m,
                        sharded, use_native, dropped_seen=0,
                        tracer=tracer, recorder=recorder, health=health,
                        probe_out=probe_out, degrade=degrade_surface,
                        drift=drift, drift_feed=drift_feed, inc=inc,
                        lat=lat, usr1=usr1, openset=openset, dev=dev,
                        perf=perf, actuation=actuation)
    except BaseException as e:
        # the crash-forensics moment: record the terminal exception and
        # freeze the ring — safely outside any signal-handler frame.
        # SystemExit is a dump only when the SIGTERM hook raised it
        # (argparse/sys.exit paths are deliberate, not crashes).
        if recorder is not None:
            if sigterm_seen and isinstance(e, SystemExit):
                recorder.record("signal.sigterm")
                _dump_flight(recorder, args.obs_dir, "sigterm")
                _dump_device(dev, perf, args.obs_dir, "sigterm")
            elif not isinstance(e, SystemExit):
                recorder.record(
                    "serve.exception", error=type(e).__name__,
                    detail=str(e),
                )
                reason = (
                    "keyboard-interrupt"
                    if isinstance(e, KeyboardInterrupt)
                    else "serve-exception"
                )
                _dump_flight(recorder, args.obs_dir, reason)
                _dump_device(dev, perf, args.obs_dir, reason)
        raise
    else:
        if recorder is not None:
            if recorder.count("supervisor.terminal"):
                # the monitor died for good and the source drained — the
                # loop ends "cleanly" but an operator needs the trail
                _dump_flight(recorder, args.obs_dir, "supervisor-terminal")
                _dump_device(dev, perf, args.obs_dir,
                             "supervisor-terminal")
            elif args.obs_dump_on_exit:
                _dump_flight(recorder, args.obs_dir, "on-demand")
                _dump_device(dev, perf, args.obs_dir, "on-demand")
    finally:
        if lock_witness is not None:
            # surface ordering violations + the static-graph
            # cross-check before the recorder goes away (violations
            # also land in the ring as locktrace.violation events)
            locktrace.finish(lock_witness, recorder=recorder)
        if sync_witness is not None:
            # same for the device-boundary witness: unknown hot-span
            # syncs land as syncguard.violation events + stderr
            from .utils import syncguard

            syncguard.finish(sync_witness, recorder=recorder)
        if server is not None:
            server.stop()
        if perf is not None:
            # commit the partial segment: every recorded tick is on
            # disk before the process goes away (best-effort — the
            # commit path absorbs its own failures)
            perf.flush()
        if dev is not None:
            # unregister the monitoring listeners + restore the
            # dispatch logger — a finished run must not haunt the next
            dev.detach()
        if actuation is not None:
            # the plane only closes its switch link — installed rules
            # stay (a serve restart must not blackhole live traffic by
            # retracting QoS rules it will re-earn in seconds)
            actuation.close()
        if degrade_surface is not None:
            # the view closes both the live (possibly promoted) ladder
            # and the boot one; without drift it IS the boot ladder
            degrade_surface.close()
        if drift is not None:
            drift.close()
        if sigterm_hooked:
            signal.signal(signal.SIGTERM, prev_sigterm)
        if sigusr1_hooked:
            signal.signal(signal.SIGUSR1, prev_sigusr1)
        # the checkpoint must survive EVERY exit, including Ctrl-C on a
        # long-running serve — the state is consistent between ticks
        # (save() flushes pending rows first)
        if args.save_serve_state:
            from .io import serving_checkpoint as _sc

            _sc.save(
                engine, args.save_serve_state,
                feature_reference=_serving_reference(drift, openset),
            )
            print(
                f"saved serving state ({engine.num_flows()} tracked "
                f"flows) to {args.save_serve_state}",
                file=sys.stderr,
            )


def _serving_reference(drift, openset) -> dict | None:
    """The serving checkpoint's ``feature_reference`` block: the drift
    monitor's reference and the open-set gate's armed stats+threshold
    ride together (either may be absent — each loop restores only its
    own keys)."""
    ref: dict = {}
    if drift is not None:
        ref.update(drift.reference_arrays() or {})
    if openset is not None:
        ref.update(openset.reference_arrays() or {})
    return ref or None


def _dump_flight(recorder, obs_dir, reason: str) -> None:
    """Best-effort post-mortem dump — the forensics path must never turn
    a serve-loop failure into a different failure."""
    if recorder is None or not obs_dir:
        return
    try:
        path = recorder.dump(obs_dir, reason)
    except OSError as e:
        print(f"WARNING: flight-recorder dump failed: {e}",
              file=sys.stderr)
        return
    print(f"flight recorder dumped to {path} ({reason})", file=sys.stderr)


def _dump_device(dev, perf, obs_dir, reason: str) -> None:
    """Best-effort device-plane dump: the /healthz device block plus the
    black-box perf-ring tail, frozen as one JSON bundle beside the
    flight-recorder post-mortem — gate-breach forensics carry device
    state (compiles, retraces, HBM watermark, last-dispatch age) and
    the last ticks' stage timings without needing the obs port up."""
    if (dev is None and perf is None) or not obs_dir:
        return
    import json

    from .utils.atomicio import atomic_write_bytes

    payload: dict = {"kind": "device", "reason": reason}
    if dev is not None:
        payload["device"] = dev.status()
    if perf is not None:
        # commit the partial segment first so the on-disk ring and the
        # reported tail agree about the final ticks
        perf.flush()
        payload["perf"] = perf.status()
        payload["perf_tail"] = perf.tail(64)
    try:
        os.makedirs(obs_dir, exist_ok=True)
        path = os.path.join(
            obs_dir,
            f"device-{os.getpid()}-{time.monotonic_ns()}-{reason}.json",
        )
        atomic_write_bytes(
            path,
            json.dumps(payload, sort_keys=True, default=repr).encode(),
        )
    except OSError as e:
        print(f"WARNING: device dump failed: {e}", file=sys.stderr)
        return
    print(f"device telemetry dumped to {path} ({reason})",
          file=sys.stderr)


def _snapshot_if_due(args, engine, m, ticks: int, loop_t0: float,
                     recorder=None, health=None, drift=None,
                     openset=None) -> None:
    """Periodic in-loop serving snapshot (between ticks, state flushed).

    The wall-clock budget guard keeps checkpointing from starving the
    serve loop: when cumulative save time exceeds
    ``--serve-checkpoint-budget`` of the loop's elapsed time, the due
    snapshot is skipped (counted, so operators see the deferral) and
    retried at the next due tick. Bounded loss either way: the rotation's
    newest valid member is at most a few due-intervals old.

    A failed save (disk full, permission, unreachable dir) must not kill
    a serve whose live state is healthy — it's warned, counted in
    ``checkpoint_errors``, and retried at the next due tick. Injected
    faults (chaos runs) DO propagate: they simulate process death."""
    from .io import serving_checkpoint as _sc
    from .utils.faults import FaultInjected

    h = m.histograms.get("checkpoint_save_s")
    elapsed = time.monotonic() - loop_t0
    # budget <= 0 disables the guard (like --serve-checkpoint-every 0
    # disables snapshots) — otherwise any recorded save makes
    # total/elapsed > 0 true forever and the rotation silently freezes
    if (args.serve_checkpoint_budget > 0 and h is not None
            and elapsed > 0
            and h.total / elapsed > args.serve_checkpoint_budget):
        m.inc("checkpoint_skipped")
        if recorder is not None:
            recorder.record(
                "checkpoint.skip", tick=ticks, reason="budget",
            )
        return
    try:
        with m.time("checkpoint_save_s"):
            _, nbytes = _sc.save_rotating(
                engine, args.serve_checkpoint_dir, tick=ticks,
                keep=args.serve_checkpoint_keep,
                # the drift reference AND the open-set gate's armed
                # stats ride in the snapshot (format v3) so a restored
                # serve resumes detection against the same
                # training-time distribution and keeps rejecting at
                # the same calibrated threshold
                feature_reference=_serving_reference(drift, openset),
            )
    except FaultInjected:
        raise
    except OSError as e:
        m.inc("checkpoint_errors")
        if recorder is not None:
            recorder.record(
                "checkpoint.error", tick=ticks,
                error=type(e).__name__, detail=str(e),
            )
        print(
            f"WARNING: serving snapshot failed (tick {ticks}): {e} — "
            f"will retry at the next due tick",
            file=sys.stderr,
        )
        return
    m.inc("checkpoint_saves")
    m.inc("checkpoint_bytes", nbytes)
    if recorder is not None:
        recorder.record("checkpoint.save", tick=ticks, bytes=nbytes)
    if health is not None:
        health.checkpoint()


def _serve_loop(args, engine, model, predict, serve_params, m, sharded,
                use_native, dropped_seen, tracer, recorder=None,
                health=None, probe_out=None, degrade=None,
                drift=None, drift_feed=None, inc=None, lat=None,
                usr1=None, openset=None, dev=None, perf=None,
                actuation=None) -> None:
    from .ingest.fanin import RawTick
    from .utils.profiling import trace

    # Pipelined serving (serving/pipeline.py): the host stage (this
    # thread) polls/parses/scatters and DISPATCHES each render tick's
    # read side; the device stage (one worker thread) absorbs the sync
    # and renders. The handoff is bounded (depth 2) with coalescing
    # backpressure; 'off' keeps the serial chain byte-for-byte.
    pipe = None
    feature_stage = None
    host_busy = host_span = contextlib.nullcontext
    if getattr(args, "pipeline", "off") != "off":
        import functools

        from .serving.pipeline import FeatureStage, ServePipeline

        pipe = ServePipeline(
            consume=lambda job: job(), depth=2, metrics=m,
        ).start()
        host_busy = pipe.host_stage
        host_span = functools.partial(tracer.span, "stage.host")
        if (not sharded and args.table_rows > 0 and inc is None
                and not getattr(predict, "host_native", False)):
            # donated double-buffers pin the per-render feature matrix
            # (full re-predict only: the incremental path gathers
            # per-bucket dirty rows instead of projecting the table)
            feature_stage = FeatureStage(
                engine.table.capacity, telemetry=dev,
            )

    ticks = 0
    # A restarted serve must keep numbering ABOVE the rotation's existing
    # members: ticks restart at 0 here, and lower-numbered snapshots
    # would be treated as oldest by keep-N pruning and resolve_latest —
    # post-restart progress silently losing to pre-crash checkpoints.
    tick_base = 0
    if args.serve_checkpoint_every and args.serve_checkpoint_dir:
        from .io import serving_checkpoint as _sc

        existing = _sc.list_checkpoints(args.serve_checkpoint_dir)
        if existing:
            tick_base = existing[0][0]
    loop_t0 = time.monotonic()
    probe_wired = False
    end = object()  # next() sentinel: a batch is never None-able
    source = _tick_source(
        args,
        # raw wherever the native engine can consume bytes directly:
        # pipe-fed direct sources, and EVERY fan-in kind (the tier's
        # pumps render capture/synthetic ticks to the wire themselves)
        raw=use_native and (
            args.source in ("ryu", "controller") or _fanin_active(args)
        ),
        recorder=recorder, probe_out=probe_out, stamp=lat is not None,
    )
    try:
        with trace(args.profile_dir):
            while True:
                # poll is its own root span (not a child of tick): it
                # measures waiting on EXTERNAL telemetry, and folding it
                # into tick would drown the pipeline's own latency
                with tracer.span("poll"):
                    batch = next(source, end)
                if batch is end:
                    break
                if usr1 is not None and usr1["due"]:
                    # deferred half of the SIGUSR1 hook: safely outside
                    # the signal frame, between ticks — record the
                    # signal, freeze the ring, snapshot the counters,
                    # and KEEP SERVING
                    usr1["due"] = False
                    recorder.record("signal.sigusr1")
                    _dump_flight(recorder, args.obs_dir, "sigusr1")
                    _dump_metrics(m, args.obs_dir, "sigusr1")
                    _dump_device(dev, perf, args.obs_dir, "sigusr1")
                if pipe is not None:
                    # a dead device stage must kill the serve (and leave
                    # a post-mortem), not let the host spin silently
                    pipe.raise_if_failed()
                if lat is not None:
                    _begin_tick_provenance(lat, batch, probe_out)
                if health is not None:
                    health.tick()
                    if (not probe_wired and probe_out is not None
                            and "probe" in probe_out):
                        # the subprocess collector exists only once the
                        # source generator has started — wire the
                        # /healthz liveness probe at first arrival
                        health.set_collector_probe(probe_out["probe"])
                        if probe_out.get("fanin") is not None:
                            # per-source roster rides alongside the
                            # single collector_alive boolean
                            health.set_source_roster(
                                probe_out["fanin"].roster
                            )
                        probe_wired = True
                with tracer.span("tick"), host_busy(), host_span():
                    engine.mark_tick()  # freshness floor for the render
                    with m.time("ingest_s"):
                        with tracer.span("parse"):
                            if isinstance(batch, bytes):
                                n_rec = engine.ingest_bytes(batch)
                            elif isinstance(batch, RawTick):
                                # native fan-in: one tck_feed_lines
                                # call per (source, poll batch) — no
                                # per-flow string ever touches Python
                                n_rec = sum(
                                    engine.ingest_bytes(data, sid)
                                    for sid, data in batch
                                )
                            else:
                                n_rec = engine.ingest(batch)
                        m.inc("records", n_rec)
                        # malformed wire lines, counted + skipped at
                        # the parse seam — the accessor is spine-
                        # agnostic (C++ per-source counters, or the
                        # Python fallback's mirror), so the gauge
                        # reads the same on either path instead of
                        # vanishing when --native-ingest is off
                        m.set(
                            "native_parse_errors",
                            engine.parse_errors(),
                        )
                        if lat is not None:
                            lat.mark_parse()
                        with tracer.span("scatter"):
                            engine.step()
                        if lat is not None:
                            lat.mark_scatter()
                    if (probe_out is not None
                            and probe_out.get("fanin") is not None):
                        _evict_dead_namespaces(
                            probe_out["fanin"], engine, m, pipe,
                            recorder, lat=lat, actuation=actuation,
                        )
                    ticks += 1
                    m.inc("ticks")
                    # every tick, not just render ticks: a /metrics
                    # scrape between renders must not read a drop count
                    # up to print_every ticks stale
                    m.set("flows_dropped", engine.dropped)
                    if ticks % args.print_every == 0:
                        if engine.dropped > dropped_seen:
                            print(
                                f"WARNING: flow table full — "
                                f"{engine.dropped - dropped_seen} new "
                                f"flows dropped since last report "
                                f"(capacity {args.capacity}, "
                                f"idle-timeout {args.idle_timeout}s)",
                                file=sys.stderr,
                            )
                            dropped_seen = engine.dropped
                        if dev is not None:
                            # render dispatch == device work this tick:
                            # feeds the /healthz last-dispatch age
                            dev.mark_dispatch()
                        if pipe is not None:
                            _dispatch_render(
                                args, engine, model, predict,
                                serve_params, m, tracer, pipe,
                                feature_stage, sharded,
                                degrade=degrade, drift=drift,
                                drift_feed=drift_feed, inc=inc,
                                lat=lat, actuation=actuation,
                            )
                        elif sharded:
                            # the sharded tick's whole read side
                            # (per-shard predict + render candidates +
                            # stale masks) is one dispatch, with
                            # eviction folded in
                            with m.time("predict_s"), \
                                    tracer.span("predict"):
                                rows, evicted = engine.tick_render(
                                    now=engine.last_time,
                                    idle_seconds=(
                                        args.idle_timeout or None
                                    ),
                                )
                            m.inc("evicted", evicted)
                            with tracer.span("render"):
                                _print_ranked(
                                    engine, model, rows,
                                    engine.num_flows(),
                                )
                            if drift is not None:
                                # off the hot path: the tick's frame
                                # is already printed. The observation
                                # is exact — serial loop, no ingest
                                # between render and capture.
                                if drift_feed is not None and rows:
                                    _feed_sharded_capture(
                                        engine, drift_feed, rows,
                                    )
                                drift.poll()
                        else:
                            if args.idle_timeout and engine.last_time:
                                m.inc(
                                    "evicted",
                                    engine.evict_idle(
                                        engine.last_time,
                                        args.idle_timeout,
                                    ),
                                )
                            with m.time("predict_s"):
                                _print_table(
                                    engine, model, predict,
                                    serve_params, args, tracer,
                                    degrade=degrade, inc=inc, lat=lat,
                                    drift=drift, actuation=actuation,
                                )
                            if drift is not None:
                                # off the hot path: the tick's labels
                                # are already rendered
                                drift.poll()
                    if (args.serve_checkpoint_every
                            and ticks % args.serve_checkpoint_every == 0):
                        with tracer.span("snapshot"):
                            _snapshot_if_due(
                                args, engine, m, tick_base + ticks,
                                loop_t0, recorder=recorder,
                                health=health, drift=drift,
                                openset=openset,
                            )
                if dev is not None or perf is not None:
                    # after the tick span closes, so every stage
                    # histogram's newest sample is THIS tick's
                    _record_perf_tick(m, dev, perf, ticks,
                                      degrade=degrade, drift=drift)
                if args.metrics_every and ticks % args.metrics_every == 0:
                    print(m.report(), file=sys.stderr, flush=True)
                if args.max_ticks and ticks >= args.max_ticks:
                    break
        if pipe is not None:
            # end of stream: staged renders finish before the loop
            # returns (save-serve-state and capsys-style capture both
            # rely on it), and a device-stage failure surfaces here
            pipe.shutdown(drain=True)
            pipe.raise_if_failed()
    finally:
        if pipe is not None:
            pipe.shutdown(drain=False)  # idempotent; error paths drop
        # deterministic teardown (the generator's finally stops the
        # collector) BEFORE the obs server goes down, so /healthz can
        # never observe a half-stopped source
        source.close()


def _record_perf_tick(m, dev, perf, ticks, degrade=None, drift=None) -> None:
    """One black-box sample per poll tick: refresh the HBM gauges
    (``dev.sample``) and persist the tick's stage timings, queue/dirty
    state, and degrade/drift positions into the on-disk perf ring.
    Host-side dict reads only — the write path never touches jax."""
    devs = dev.sample() if dev is not None else None
    if perf is None:
        return
    sample: dict = {"tick": ticks}
    # newest sample per latency surface — the same underlying readings
    # the latency plane folds into its quantiles, so a ring segment's
    # per-stage p50s reconcile against /healthz by construction
    for name in ("stage_tick_s", "stage_parse_s", "stage_scatter_s",
                 "stage_predict_s", "stage_render_s", "ingest_s",
                 "predict_s"):
        h = m.histograms.get(name)
        if h is not None and h.last is not None:
            sample[name] = round(h.last, 6)
    for gauge in ("queue_depth", "dirty_rows", "flows_dropped"):
        if gauge in m.gauges:
            sample[gauge] = m.gauges[gauge]
    if degrade is not None:
        try:
            sample["degrade_state"] = degrade.status().get("state")
        except Exception:  # noqa: BLE001 — the black box must not inject
            pass
    if drift is not None:
        try:
            sample["drift_state"] = drift.status().get("state")
        except Exception:  # noqa: BLE001 — the black box must not inject
            pass
    if devs is not None:
        sample["jit_compiles"] = devs["jit_compiles"]
        sample["retraces_after_warmup"] = devs["retraces_after_warmup"]
        if devs["hbm_bytes"] is not None:
            sample["hbm_bytes"] = devs["hbm_bytes"]
    perf.record(sample)


def _dump_metrics(m, obs_dir, reason: str) -> None:
    """Best-effort metrics-snapshot dump (the SIGUSR1 pair of
    ``_dump_flight``) — forensics must never become a new failure."""
    from .obs import dump_metrics_snapshot

    try:
        path = dump_metrics_snapshot(m, obs_dir, reason)
    except OSError as e:
        print(f"WARNING: metrics snapshot dump failed: {e}",
              file=sys.stderr)
        return
    print(f"metrics snapshot dumped to {path} ({reason})",
          file=sys.stderr)


def _begin_tick_provenance(lat, batch, probe_out) -> None:
    """Register this tick's arrived batches with the latency plane:
    the fan-in tier hands over its per-batch (sid, emit, enq, deq, n)
    entries; a direct source becomes one sid-0 entry stamped at its
    pump/parse moment. Raw byte batches degrade BY DESIGN to an
    arrival-time emit (the native fast path has no records host-side
    to carry a stamp); a RECORD batch arriving unstamped means the
    stamp itself failed (an absorbed ``obs.stamp`` fire) — it keeps
    the fault-site contract: counted in ``latency_unstamped_batches``
    and excluded from the e2e fold, never fabricated from arrival
    time (which would inject an understated sample into the headline
    quantiles)."""
    from .ingest.batcher import batch_emit_ts

    tier = probe_out.get("fanin") if probe_out is not None else None
    if tier is not None:
        entries = tier.pop_provenance()
        if entries:
            lat.begin_tick(entries)
        return
    if isinstance(batch, (bytes, bytearray)):
        emit, n = lat.clock(), 0
    else:
        emit, n = batch_emit_ts(batch), len(batch)
    lat.begin_tick([(0, emit, None, None, n)])


def _evict_dead_namespaces(tier, engine, m, pipe, recorder,
                           lat=None, actuation=None) -> None:
    """Evict namespaces whose source-death quarantine expired (fan-in
    tier, ingest/fanin.py). Deferred while a pipelined render is in
    flight — a released slot's metadata must outlive its render, the
    same ordering idle eviction enforces — and the tier re-offers the
    pending sids next tick, so 'defer' never becomes 'never' while
    ticks keep flowing."""
    if pipe is not None and not pipe.idle():
        return
    for sid in tier.take_evictions():
        # surgical namespace clear on EITHER spine: the Python index
        # walks its sparse slot_source map, the C++ engine its per-slot
        # namespace tags (tck_slots_for_source) — the old native
        # degrade-to-idle-timeout fallback (and its
        # source_evictions_skipped counter) is gone
        if actuation is not None:
            # blast radius: the dead namespace's flow rules retract
            # with its slots — captured BEFORE evict_source releases
            # them (a released slot could be reused next tick and the
            # retraction would name the wrong flow)
            actuation.retract_source(sid, engine.slots_for_source(sid))
        n = engine.evict_source(sid)
        if lat is not None:
            # the namespace's rows are gone: pending latency entries
            # would fold against labels nobody will ever serve — the
            # per-source e2e series stops accumulating here (its queue
            # backlog was already purged by take_evictions)
            lat.drop_source(sid)
        m.inc("evicted", n)
        m.inc("source_evictions")
        if recorder is not None:
            recorder.record(
                "fanin.namespace_evicted", source=sid, flows=n,
            )
        print(
            f"WARNING: telemetry source {sid} dead past quarantine — "
            f"evicted {n} flows from its namespace",
            file=sys.stderr,
        )


def _feed_sharded_capture(engine, gate, rows) -> None:
    """Hand the sharded drift gate one render's (features, labels)
    observation — the stand-in for ``DriftGate.__call__``'s
    by-reference capture. The ranked rows' labels were produced by the
    per-shard predict this render; ``feature_sample`` re-reads the same
    slots through one gathered shard_map fetch."""
    X = engine.feature_sample([s for s, *_ in rows])
    gate.feed_capture(
        X, np.asarray([c for _, c, *_ in rows], dtype=np.int64)
    )


def _dispatch_render(args, engine, model, predict, serve_params, m,
                     tracer, pipe, feature_stage, sharded,
                     degrade=None, drift=None, drift_feed=None,
                     inc=None, lat=None, actuation=None) -> None:
    """Host-stage half of one pipelined render tick: dispatch the read
    side against THIS tick's table and stage the device-stage job.
    Output is byte-identical to the serial render of the same tick —
    n_flows is captured at dispatch, the dispatched arrays are fixed
    against tick-N state, and idle eviction only runs while no render
    is in flight (a released slot's metadata must outlive its render)."""
    from .serving.pipeline import dispatch_read

    idle = args.idle_timeout or None
    if sharded:
        if idle is not None and engine.last_time:
            # the sharded read side fuses eviction into the render
            # dispatch and releasing slots needs the synced stale bits
            # on the host stage: run the fused tick here and hand only
            # the formatting to the device stage (the no-eviction
            # sharded serve overlaps fully — docs/ARCHITECTURE.md)
            with m.time("predict_s"), tracer.span("predict"):
                rows, evicted = engine.tick_render(
                    now=engine.last_time, idle_seconds=idle,
                )
            m.inc("evicted", evicted)
            n_flows = engine.num_flows()
            # resolve slot metadata HERE, before returning to ingest: a
            # slot this tick just released could be reused by the next
            # tick's ingest, and a deferred lookup on the worker would
            # print the NEW flow's addresses under the OLD flow's label
            sample = engine.slot_metadata([s for s, *_ in rows])
            if drift is not None and drift_feed is not None and rows:
                # exact pairing: still the host stage, before ingest
                # resumes — the sampled features are this render's
                _feed_sharded_capture(engine, drift_feed, rows)

            def render_only(rows=rows, n_flows=n_flows, sample=sample):
                with tracer.span("stage.device"), tracer.span("render"):
                    _print_ranked_resolved(model, rows, sample, n_flows)
                if drift is not None:
                    # the device-stage worker's idle time, same as the
                    # single-device pipelined job
                    drift.poll()

            pipe.submit(render_only)
            return
        with tracer.span("dispatch"):
            outs = engine.tick_read_dispatch(now=engine.last_time)
            n_flows = engine.num_flows()

        def sharded_job(outs=outs, n_flows=n_flows):
            with tracer.span("stage.device"):
                with m.time("predict_s"), tracer.span("predict"):
                    rows = engine.tick_read_finish(outs)
                with tracer.span("render"):
                    _print_ranked(engine, model, rows, n_flows)
            if drift is not None:
                if drift_feed is not None and rows:
                    # worker-side capture: feature_sample re-reads the
                    # LIVE table, which the overlapped host stage may
                    # already be advancing — a slightly torn
                    # observation is acceptable drift signal, and a
                    # torn parity probe only defers promotion by one
                    # window (probes demand fresh captures anyway)
                    _feed_sharded_capture(engine, drift_feed, rows)
                drift.poll()

        pipe.submit(sharded_job)
        return
    if idle is not None and engine.last_time:
        # Whether an eviction is due is decided from DATA time alone
        # (table state + the capture's last_time), so the stale set is
        # byte-identical across runs; only WHEN the pipe happens to be
        # busy is wall-clock. Deciding first and draining only on ticks
        # that actually evict keeps pipelined output deterministic under
        # host load — gating the whole pass on pipe.idle() (as this loop
        # once did) deferred eviction by a tick whenever the render
        # worker lagged, shifting slot reuse between otherwise identical
        # runs.
        stale = engine.stale_slots(engine.last_time, idle)
        if stale.size:
            if not pipe.idle():
                # a released slot's metadata must outlive any render
                # already in flight — wait it out, then reclaim; the
                # drain is counted so overlap loss is observable
                m.inc("evict_deferred")
                pipe.drain(timeout=10.0)
            if pipe.idle():
                m.inc("evicted", engine.evict_slots(stale))
    with tracer.span("dispatch"):
        read = dispatch_read(
            engine, predict, serve_params, args.table_rows,
            feature_stage, inc=inc,
        )
    # seal at dispatch, ON the host stage: the read side was dispatched
    # against THIS tick's table, so exactly the batches scattered so
    # far become visible when this render prints — later ticks' batches
    # wait for their own render, like their rows. A coalesced
    # (superseded) render's generation folds at the render that
    # actually prints (render_visible folds every generation <= seal).
    seal = lat.seal() if lat is not None else None

    def job(read=read, seal=seal):
        with tracer.span("stage.device"):
            with m.time("predict_s"), tracer.span("predict"):
                rows = read.rows()
            if lat is not None:
                lat.mark_device(seal)
            # the stale verdict must postdate the predict attempt: a
            # ladder trip DURING rows() marks THIS tick's render
            stale = degrade is not None and degrade.render_stale
            with tracer.span("render"):
                if args.table_rows > 0:
                    _print_ranked(engine, model, rows, read.n_flows,
                                  stale=stale, actuation=actuation,
                                  drift=drift)
                else:
                    _print_full(model, rows, stale=stale,
                                actuation=actuation, drift=drift)
            if lat is not None:
                lat.render_visible(seal)
        if drift is not None:
            # the device-stage worker's idle time: the tick's frame is
            # already printed, the next render is not yet staged
            drift.poll()

    pipe.submit(job)


def _stale_fields(fields, rows, stale):
    """Append the explicit ``Label State = STALE`` column when the
    degrade ladder is serving last-known-good labels (BROKEN rung) —
    the no-fault table stays byte-identical because the column only
    exists while labels actually are stale."""
    if not stale:
        return fields, rows
    return (tuple(fields) + ("Label State",),
            [tuple(r) + ("STALE",) for r in rows])


def _observe_actuation(actuation, rows, stale, drift) -> None:
    """Feed one rendered tick's ``(slot, src, dst, label)`` rows to the
    actuation plane, with the freshness verdict (STALE render) and the
    drift loop's current state riding along — the three signals the
    hysteresis tier gates on. A no-op without the tier; never raises
    and never touches stdout, so every render stays byte-identical to
    ``--actuation off``."""
    if actuation is None:
        return
    actuation.observe(
        rows, stale=stale,
        drift_state=drift.state if drift is not None else None,
    )


def _print_full(model, rows, stale=False, actuation=None,
                drift=None) -> None:
    """Render the unbounded (``--table-rows 0``) table from a
    ``serving.pipeline.FullRead`` row list — the device-stage
    counterpart of ``_print_table``'s full branch."""
    from .utils.table import CLASSIFIER_FIELDS, render_table, status_str

    names = model.classes.names
    out = [
        (
            slot, src, dst,
            names[c] if c < len(names) else "?",
            status_str(f), status_str(r),
        )
        for slot, src, dst, c, f, r in rows
    ]
    fields, out = _stale_fields(CLASSIFIER_FIELDS, out, stale)
    print(render_table(fields, out), flush=True)
    _observe_actuation(
        actuation,
        [(slot, src, dst, names[c] if c < len(names) else "?")
         for slot, src, dst, c, _f, _r in rows],
        stale, drift,
    )


def _print_table(engine, model, predict, serve_params, args,
                 tracer, degrade=None, inc=None, lat=None,
                 drift=None, actuation=None) -> None:
    import jax

    from .utils.table import CLASSIFIER_FIELDS, render_table, status_str

    # serial render: everything scattered so far becomes visible when
    # this frame prints — seal, sync, fold (the pipelined counterpart
    # lives in _dispatch_render)
    seal = lat.seal() if lat is not None else None
    # The device flow table produces float32 features natively, so the
    # SVC/KNN hi/lo precise mode is moot here (lo would be identically
    # zero); it applies to float64 feature sources like the CSV pipeline.
    if inc is not None:
        # incremental path: labels come from the persistent cache, with
        # only this tick's dirty rows re-predicted (the compact span
        # inside carries the count/compact/gather cost)
        with tracer.span("predict"):
            labels = inc.labels()
            jax.block_until_ready(labels)
    else:
        with tracer.span("feature"):
            X = engine.features()
        with tracer.span("predict"):
            labels = predict(serve_params, X)  # stays device-resident
            # the dispatch is async; block here so the predict span
            # carries the device compute instead of smearing it into
            # render (the degrade ladder returns host arrays — a no-op
            # pass-through)
            jax.block_until_ready(labels)
    if lat is not None:
        lat.mark_device(seal)
    # the stale verdict postdates the predict attempt: a ladder trip
    # during THIS call marks this tick's render
    stale = degrade is not None and degrade.render_stale
    # Classification is batched over the WHOLE table on device; the table
    # *render* samples at most --table-rows flows (the reference prints
    # everything because it tracks dozens, traffic_classifier.py:99-118 —
    # at the 2²⁰-flow target a full render would be O(N) Python per tick,
    # and a full label/active fetch ~6 MB per tick over the device link).
    limit = args.table_rows if args.table_rows > 0 else None
    n_flows = engine.num_flows()

    def name(c: int) -> str:
        return (
            model.classes.names[c] if c < len(model.classes.names) else "?"
        )

    if limit is not None:
        # activity-ranked sample: the rendered rows track live traffic
        # (device top_k over this tick's byte deltas), most active first;
        # labels + active flags gathered device-side, O(limit) fetched
        with tracer.span("render"):
            _print_ranked(
                engine, model, engine.render_sample(labels, limit),
                n_flows, stale=stale, actuation=actuation, drift=drift,
            )
        if lat is not None:
            lat.render_visible(seal)
        return
    with tracer.span("render"):
        rows = []
        # one batched device→host fetch where three serial np.asarray
        # round trips used to block the render one after another
        idx, fwd_active, rev_active = jax.device_get(
            (labels, engine.table.fwd.active, engine.table.rev.active)
        )  # graftlint: disable=implicit-sync -- render-sync: the tick's one batched fetch
        fwd_active = fwd_active[:-1]
        rev_active = rev_active[:-1]
        for slot, (src, dst) in sorted(engine.slot_metadata().items()):
            rows.append(
                (
                    slot,
                    src,
                    dst,
                    name(int(idx[slot])),
                    status_str(bool(fwd_active[slot])),
                    status_str(bool(rev_active[slot])),
                )
            )
        fields, rows = _stale_fields(CLASSIFIER_FIELDS, rows, stale)
        print(render_table(fields, rows), flush=True)
    if lat is not None:
        lat.render_visible(seal)
    _observe_actuation(
        actuation,
        [(slot, src, dst, label) for slot, src, dst, label, *_ in rows],
        stale, drift,
    )


def _print_ranked(engine, model, ranked, n_flows, stale=False,
                  actuation=None, drift=None) -> None:
    """Render activity-ranked ``(slot, label, fwd, rev)`` rows — the shared
    table surface for the single-device and mesh-sharded serve loops."""
    sample = engine.slot_metadata(slots=[s for s, *_ in ranked])
    _print_ranked_resolved(model, ranked, sample, n_flows, stale=stale,
                           actuation=actuation, drift=drift)


def _print_ranked_resolved(model, ranked, sample, n_flows,
                           stale=False, actuation=None,
                           drift=None) -> None:
    """``_print_ranked`` with the slot→(src, dst) sample already
    resolved — the pipelined sharded eviction path resolves it on the
    host stage (the lookup must precede any slot reuse)."""
    from .utils.table import CLASSIFIER_FIELDS, render_table, status_str

    names = model.classes.names
    rows = []
    for slot, c, fa, ra in ranked:
        if slot not in sample:
            continue
        src, dst = sample[slot]
        rows.append((
            slot, src, dst,
            names[c] if c < len(names) else "?",
            status_str(fa), status_str(ra),
        ))
    fields, rows = _stale_fields(CLASSIFIER_FIELDS, rows, stale)
    print(render_table(fields, rows), flush=True)
    if n_flows > len(rows):
        print(f"... showing {len(rows)} of {n_flows} tracked flows",
              flush=True)
    _observe_actuation(
        actuation,
        [(slot, src, dst, label) for slot, src, dst, label, *_ in rows],
        stale, drift,
    )


def _run_train(args) -> None:
    from .core.features import CSV_COLUMNS_16, LABEL_COLUMN
    from .core.flow_table import features16
    from .ingest.batcher import FlowStateEngine

    if not args.traffic_type:
        sys.exit("ERROR: specify traffic type.")  # reference :225
    out_path = args.out or f"{args.traffic_type}_training_data.csv"
    # --native-ingest is legal with --sources N here too: the fan-in
    # tier delivers raw byte batches per source and the C++ keyer folds
    # the source id into every flow key (tck_feed_lines), so N sources'
    # identical flow tuples land in N disjoint slots — the old
    # collapse-into-one-slot hazard is gone
    use_native = _use_native(args)
    engine = FlowStateEngine(args.capacity, native=use_native)
    deadline = time.time() + args.duration
    ticks = 0
    with open(out_path, "w") as f:
        f.write("\t".join(list(CSV_COLUMNS_16) + [LABEL_COLUMN]) + "\n")
        from .ingest.fanin import RawTick

        for batch in _tick_source(
            args,
            raw=engine.native and (
                args.source in ("ryu", "controller")
                or _fanin_active(args)
            ),
        ):
            if isinstance(batch, bytes):
                engine.ingest_bytes(batch)
            elif isinstance(batch, RawTick):
                for sid, data in batch:
                    engine.ingest_bytes(data, sid)
            else:
                engine.ingest(batch)
            engine.step()
            ticks += 1
            X16 = np.asarray(features16(engine.table))
            in_use = np.asarray(engine.table.in_use)[:-1]
            slots = np.nonzero(in_use)[0]
            if slots.size:
                # Bulk row write: one C-level format per row instead of 16
                # str() + join per flow, so the tick cost stays flat as the
                # tracked-flow count grows. ``newline`` carries the label
                # column (savetxt appends it after each formatted row).
                np.savetxt(
                    f, X16[slots].astype(np.float64), fmt="%s",
                    delimiter="\t", newline=f"\t{args.traffic_type}\n",
                )
            if time.time() >= deadline:
                print("Finished collecting data.")  # reference :185
                break
            if args.max_ticks and ticks >= args.max_ticks:
                break
    print(f"wrote {out_path}")


def _run_analyze(args) -> None:
    """C13 analysis extras: the reference notebook's scaler/PCA numbers
    AND its figures (1_log_Kmeans.ipynb cells 70-129), rendered by
    analysis/figures.py from the on-device kernels. PNGs land in --out
    (default ./analysis_out)."""
    from .analysis import figures
    from .io.datasets import load_reference_datasets

    out_dir = args.out or "analysis_out"
    ds = load_reference_datasets(args.data_dir)
    res = figures.save_all(ds, out_dir)
    print(
        f"PCA-2 explained variance: "
        f"{res['pca2_explained_variance'] * 100:.2f}%"
    )
    print(
        f"PCA-space logreg accuracy (70/30): "
        f"{res['pca_logreg_accuracy'] * 100:.2f}%"
    )
    print(f"cluster accuracy (mode-matched): "
          f"{res['cluster_accuracy'] * 100:.2f}%")
    for name, path in res["paths"].items():
        print(f"wrote {name}: {path}")


def _run_retrain(args) -> None:
    """On-device retraining from the training CSVs (the C12 notebook
    pipeline, SURVEY.md §3.4) + native checkpoint save."""
    import jax.numpy as jnp

    from .io.datasets import load_reference_datasets, train_test_split
    from .models import MODEL_MODULES, SUBCOMMAND_ALIASES

    family = SUBCOMMAND_ALIASES.get(args.traffic_type, args.traffic_type)
    if family not in MODEL_MODULES:
        sys.exit(
            f"ERROR: retrain needs a model family "
            f"({', '.join(MODEL_MODULES)}), got {args.traffic_type!r}"
        )
    ds = load_reference_datasets(args.data_dir)
    tr, te = train_test_split(ds, test_size=0.5, seed=101)
    n_classes = len(tr.classes)
    mod = MODEL_MODULES[family]

    ckpt_every = getattr(args, "checkpoint_every", 0) or 0
    if ckpt_every > 0 and family != "logreg":
        print(
            f"WARNING: --checkpoint-every only applies to the logreg SGD "
            f"trainer; ignored for {family}", file=sys.stderr,
        )
    if family == "logreg":
        from .train import logreg as t

        if ckpt_every > 0 and not args.train_state_dir:
            sys.exit(
                "ERROR: --checkpoint-every needs --train-state-dir (flag "
                "or config train.train_state_dir) — the resumable SGD "
                "path has nowhere to save state"
            )
        if ckpt_every > 0:
            # Resumable streaming path: consumes train.checkpoint_every;
            # a killed run re-invoked with the same --train-state-dir
            # resumes from the last saved step (train/logreg.fit_sgd).
            params = t.fit_sgd(
                tr.X,
                tr.y,
                n_classes,
                checkpoint_dir=args.train_state_dir,
                checkpoint_every=ckpt_every,
            )
        else:
            params = t.fit(tr.X, tr.y, n_classes)
    elif family == "gnb":
        from .train import gnb as t

        params = t.fit(tr.X, tr.y, n_classes)
    elif family == "kmeans":
        from .train import kmeans as t

        params, inertia = t.fit(tr.X, k=n_classes)
        print(f"kmeans inertia: {inertia:.4g}")
    elif family == "knn":
        from .train import knn as t

        params = t.fit(tr.X, tr.y, n_neighbors=5, n_classes=n_classes)
    elif family == "forest":
        from .train import forest as t

        params = t.fit(tr.X, tr.y, n_classes)
    else:  # svc
        from .train import svc as t

        params = t.fit(tr.X, tr.y, n_classes)

    if family != "kmeans":
        from .analysis import accuracy, confusion_matrix

        pred = np.asarray(
            mod.predict(params, jnp.asarray(te.X, jnp.float32))
        )
        acc = float(accuracy(jnp.asarray(te.y), jnp.asarray(pred)))
        print(f"{family} held-out accuracy: {acc:.4f} "
              f"({len(te.y)} rows, classes={list(tr.classes)})")
        cm = np.asarray(
            confusion_matrix(
                jnp.asarray(te.y), jnp.asarray(pred), n_classes
            )
        )
        width = max(8, max(len(c) for c in tr.classes) + 1)
        print("confusion matrix (rows=true, cols=predicted):")
        print(" " * width + "".join(f"{c:>{width}}" for c in tr.classes))
        for i, c in enumerate(tr.classes):
            print(f"{c:>{width}}" + "".join(
                f"{v:>{width}}" for v in cm[i]
            ))
    else:
        from .analysis.eval import clustering_accuracy

        cids = np.asarray(
            mod.predict(params, jnp.asarray(te.X, jnp.float32))
        )
        acc = float(
            clustering_accuracy(
                jnp.asarray(cids), jnp.asarray(te.y),
                k=int(params.centers.shape[0]), n_classes=n_classes,
            )
        )
        print(f"kmeans mode-matched clustering accuracy: {acc:.4f} "
              f"({len(te.y)} rows)")
    if args.native_checkpoint:
        from .io.checkpoint import save_model

        save_model(args.native_checkpoint, family, params, tr.classes)
        print(f"saved native checkpoint to {args.native_checkpoint}")


def main(argv=None) -> None:
    from .utils.metrics import global_metrics

    global_metrics.reset()  # per-run metrics, even for embedded reuse
    parser = _build_parser()
    args = parser.parse_args(argv)
    if args.knn_topk is not None:
        # validate at parse time (clean usage error, exit 2 — never a
        # traceback) and publish through the env var so EVERY serving-
        # path resolution — boot, degrade-rung rebuilds, drift
        # promotions — sees the same choice (flag wins; env kept as
        # fallback when the flag is absent)
        from .models import resolve_knn_topk

        try:
            resolve_knn_topk(args.knn_topk)
        except ValueError as e:
            parser.error(f"--knn-topk: {e}")
        os.environ["TCSDN_KNN_TOPK"] = args.knn_topk
    if args.config:
        from . import config as config_mod

        cfg = config_mod.load(args.config)
        # config supplies defaults; explicit flags win (argparse defaults
        # are sentinels where config can override)
        if args.capacity is None:
            args.capacity = cfg.ingest.capacity
        if args.shards == 0 and cfg.ingest.shards:
            args.shards = cfg.ingest.shards
        if args.idle_timeout is None:
            args.idle_timeout = cfg.ingest.idle_timeout_s
        if args.print_every is None:
            args.print_every = cfg.print_every
        if args.monitor_cmd is None:
            args.monitor_cmd = cfg.ingest.monitor_cmd
        if args.duration is None:
            args.duration = cfg.train.collect_duration_s
        if args.checkpoint_dir is None:
            args.checkpoint_dir = cfg.model.checkpoint_dir
        if args.native_checkpoint is None:
            args.native_checkpoint = cfg.model.native_checkpoint
        if args.checkpoint_every is None:
            args.checkpoint_every = cfg.train.checkpoint_every
        if args.train_state_dir is None:
            args.train_state_dir = cfg.train.train_state_dir
    # unset sentinels → built-in defaults
    if args.capacity is None:
        args.capacity = 65536
    if args.idle_timeout is None:
        args.idle_timeout = 60
    if args.print_every is None:
        args.print_every = 10
    if args.duration is None:
        args.duration = 15 * 60
    if args.checkpoint_dir is None:
        args.checkpoint_dir = _default_ckpt_dir()

    if args.scenario is not None:
        _run_scenario_replay(args, parser)
    elif args.subcommand == "train":
        _run_train(args)
    elif args.subcommand == "retrain":
        _run_retrain(args)
    elif args.subcommand == "analyze":
        _run_analyze(args)
    else:
        _run_classify(args)


def _run_scenario_replay(args, parser) -> None:
    """The --scenario replay hook: run one campaign scenario through
    the real serve composition (scenarios/runner.py) and print its
    scorecard — same gates, same post-mortem contract as
    tools/bench_scenarios.py, but addressable from the serving CLI
    for post-incident replay. Exits nonzero on gate failure."""
    import json

    from .scenarios import SCENARIOS, build, run_scenario

    if args.scenario == "list":
        for name, builder in SCENARIOS.items():
            print(f"{name}: {builder('t1').title}")
        return
    if args.scenario not in SCENARIOS:
        parser.error(
            f"--scenario: unknown scenario {args.scenario!r} "
            f"(known: {', '.join(sorted(SCENARIOS))}; "
            f"'list' prints them)"
        )
    card = run_scenario(
        build(args.scenario, args.scenario_profile),
        obs_dir=args.scenario_obs_dir,
    )
    print(json.dumps(card, indent=1, default=repr))
    if not card["passed"]:
        failed = ", ".join(
            g["id"] for g in card["gates"] if not g["passed"]
        )
        sys.exit(f"scenario {args.scenario} FAILED gates: {failed} "
                 f"(post-mortem under {args.scenario_obs_dir}/)")


if __name__ == "__main__":
    main()
