"""State-sharded RBF-SVC: support vectors split across chips, partial ovo
decisions psum-reduced over ICI.

libsvm walks all 2281 support vectors sequentially on one CPU (SURVEY.md
§2.3). Here the (S, F) support-vector matrix and the (P, S) dual
coefficients shard on the mesh's state axis: each chip computes the RBF
kernel block against its local SVs and the *partial* pair decision
``K_local @ coef_localᵀ`` — an (N, P) matrix whose sum over chips is the
full ovo decision. One ``psum`` merges them (communication O(N·P),
independent of S, so the SV set scales with the mesh), then votes and
argmax run replicated.

Same numerical contract as models/svc.py: hi/lo split support vectors,
difference-form distances, highest-precision matmuls.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models import svc
from .mesh import STATE_AXIS, shard_map

_HI = lax.Precision.HIGHEST


def pad_support(d: dict, n_shards: int) -> dict:
    """Pad the SV count to a multiple of the state-axis size. Padding rows
    duplicate SV 0 with all-zero dual coefficients, so their kernel values
    are finite and their decision contribution is exactly zero."""
    S = np.asarray(d["support_vectors"]).shape[0]
    pad = (-S) % n_shards
    if pad == 0:
        return d
    out = dict(d)
    sv = np.asarray(d["support_vectors"], np.float64)
    out["support_vectors"] = np.concatenate(
        [sv, np.repeat(sv[:1], pad, axis=0)], axis=0
    )
    dual = np.asarray(d["dual_coef"], np.float64)
    out["dual_coef"] = np.concatenate(
        [dual, np.zeros((dual.shape[0], pad), np.float64)], axis=1
    )
    return out


def _ovo_vote_argmax(D, vote_i, vote_j, n_classes: int):
    """(N,) class labels from full ovo decisions — libsvm tie-break
    (lowest class index among maxima; argmax does exactly that, matching
    models/svc.predict). One home for the vote: both local stages (XLA
    and fused Pallas) end here."""
    pos = D > 0
    votes_i = jax.nn.one_hot(vote_i, n_classes, dtype=D.dtype)
    votes_j = jax.nn.one_hot(vote_j, n_classes, dtype=D.dtype)
    votes = jnp.where(pos[:, :, None], votes_i, votes_j).sum(axis=1)
    return jnp.argmax(votes, axis=-1).astype(jnp.int32)


def sharded_predict(mesh, params: svc.Params, precise: bool = False):
    """Build a jit-compiled sharded predict: queries replicated on the
    state axis, SV state sharded. Returns ``fn(X[, X_lo]) -> (N,) int32``.

    ``precise=True`` accepts the hi/lo query split (svc.split_hilo) for
    float64-parity on raw-counter-scale features."""
    n_classes = params.n_classes
    vote_i, vote_j = params.vote_i, params.vote_j
    intercept, gamma = params.intercept, params.gamma

    in_specs = (
        P(STATE_AXIS),  # sv_hi rows
        P(STATE_AXIS),  # sv_lo rows
        P(None, STATE_AXIS),  # pair_coef columns
        P(),  # X replicated
        P(),  # X_lo replicated
    )

    def local_decision(sv_hi, sv_lo, pair_coef, X, X_lo):
        diff = X[:, None, :] - sv_hi[None, :, :]
        diff = diff + (X_lo[:, None, :] - sv_lo[None, :, :])
        K = jnp.exp(-gamma * jnp.sum(diff * diff, axis=-1))
        part = jnp.matmul(K, pair_coef.T, precision=_HI)  # (N, P) partial
        D = lax.psum(part, STATE_AXIS) + intercept[None, :]
        return _ovo_vote_argmax(D, vote_i, vote_j, n_classes)

    shmapped = shard_map(
        local_decision,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_vma=False,
    )

    @jax.jit
    def fn(X, X_lo=None):
        if X_lo is None:
            X_lo = jnp.zeros_like(X)
        return shmapped(
            params.sv_hi, params.sv_lo, params.pair_coef, X, X_lo
        )

    if precise:
        return fn
    return lambda X: fn(X)


def fused_predict(
    mesh, params: svc.Params, *,
    row_tile: int = 512, sv_chunk: int = 512, interpret: bool = False,
):
    """SV-sharded predict with the FUSED local stage: each chip runs the
    Pallas RBF kernel (ops/pallas_rbf.py ``partial_decision``) over its
    support-vector shard — the per-shard (N, S/D) kernel matrix never
    touches HBM — then one ``psum`` merges the partial ovo decisions and
    the intercept is added once, exactly as ``sharded_predict``.

    Same per-element math as the single-device fused kernel (two-float
    difference distances, highest-precision vote matmul) — but the f32
    ACCUMULATION ORDER differs with sharding and chunking (sv_chunk here
    defaults to 512 vs compile_svc's 1024, and psum ordering is the
    mesh's), so decision values can differ in the last ulp across
    shard/chunk configurations; label parity is verified on the reference
    data but is not guaranteed at exact vote boundaries. Padding SVs
    carry zero dual coefficients so their contribution is exactly zero
    (the ``compile_svc`` trick, per shard). TPU-only compiled (Mosaic);
    CPU-mesh tests pass ``interpret=True``.

    Returns ``fn(X[, X_lo]) -> (N,) int32``.
    """
    from ..ops import pallas_rbf

    n_classes = params.n_classes
    vote_i, vote_j = params.vote_i, params.vote_j
    intercept, gamma = params.intercept, params.gamma
    D = mesh.shape[STATE_AXIS]

    # per-shard chunk-aligned global layout (numpy, outside shard_map):
    # every shard holds the same number of whole chunks of transposed
    # SVs; padding slots carry zero coefficients (pallas_rbf.sv_layout
    # owns that invariant)
    S = np.asarray(params.sv_hi).shape[0]
    per = -(-S // D)
    per = -(-per // sv_chunk) * sv_chunk
    sv_t_hi, sv_t_lo, coef_t = pallas_rbf.sv_layout(params, per * D)

    def local_fused(svt_hi_l, svt_lo_l, coef_l, X, X_lo):
        part = pallas_rbf.partial_decision(
            X, X_lo, gamma, svt_hi_l, svt_lo_l, coef_l,
            row_tile=row_tile, sv_chunk=sv_chunk, interpret=interpret,
        )
        Dv = lax.psum(part, STATE_AXIS) + intercept[None, :]
        return _ovo_vote_argmax(Dv, vote_i, vote_j, n_classes)

    shmapped = shard_map(
        local_fused,
        mesh=mesh,
        in_specs=(
            P(None, STATE_AXIS),  # sv_t_hi columns = SV rows
            P(None, STATE_AXIS),
            P(STATE_AXIS),  # coef_t rows = SV rows
            P(),  # X replicated
            P(),  # X_lo replicated
        ),
        out_specs=P(),
        check_vma=False,
    )

    @jax.jit
    def fn(X, X_lo=None):
        if X_lo is None:
            X_lo = jnp.zeros_like(X)
        return shmapped(sv_t_hi, sv_t_lo, coef_t, X, X_lo)

    return fn
