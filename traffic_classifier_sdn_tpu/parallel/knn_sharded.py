"""State-sharded k-NN: the training corpus split across chips, global top-k
merged over ICI.

The reference's KNN walks one KDTree on one CPU (SURVEY.md §2.3). The
TPU-scale design (SURVEY.md §2.4): shard the (S, F) training matrix on the
mesh's state axis; each chip computes distances to its local shard and takes
a *local* top-k; the (devices × k) candidates are then ``all_gather``-merged
and reduced to the global top-k. Communication is O(devices · k) per query —
independent of corpus size S — so the corpus can grow with the mesh.

Built on ``shard_map`` with explicit collectives, per the scaling-book
recipe: pick the mesh, shard the state, let the collectives ride ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models import knn
from .mesh import STATE_AXIS, axis_size, shard_map


def pad_corpus(d: dict, n_shards: int) -> dict:
    """Pad corpus length to a multiple of the state-axis size — and to at
    least ``n_neighbors`` rows per shard, so the local ``top_k`` is always
    well-formed — with +inf-distance sentinels (zero rows never win because
    their half-norm is replaced by +inf)."""
    import numpy as np

    S = d["fit_X"].shape[0]
    k = int(d.get("n_neighbors", 1))
    target = max(S + (-S) % n_shards, n_shards * k)
    pad = target - S
    if pad == 0:
        return d
    out = dict(d)
    out["fit_X"] = np.concatenate(
        [d["fit_X"], np.zeros((pad, d["fit_X"].shape[1]),
                              d["fit_X"].dtype)], axis=0
    )
    out["y"] = np.concatenate([d["y"], np.zeros(pad, d["y"].dtype)])
    out["pad_mask"] = np.concatenate(
        [np.zeros(S, bool), np.ones(pad, bool)]
    )
    return out


def _mask_half_norms(params: knn.Params, pad_mask):
    half = params.half_sq_norms
    if pad_mask is not None:
        half = jnp.where(jnp.asarray(pad_mask), jnp.inf, half)
    return half


def _check_real_rows(params: knn.Params, pad_mask) -> None:
    """Every sharded path's correctness rests on >= k REAL corpus rows
    GLOBALLY (padded/masked rows carry -inf candidates that lose every
    merge — but only if enough real candidates exist to beat them).
    With fewer, padded candidates reach the vote carrying label 0 and
    bias it silently, where single-device ``lax.top_k`` fails loudly —
    so enforce the invariant at build time, in the scaffolding every
    entry point shares."""
    import numpy as np

    S = np.asarray(params.fit_X).shape[0]
    k = int(params.n_neighbors)
    real = S if pad_mask is None else int(S - np.asarray(pad_mask).sum())
    if real < k:
        raise ValueError(
            f"corpus has {real} real rows < n_neighbors={k}"
        )


def _local_topk(fit_X, fit_y, half_norms, X, k):
    """Per-chip candidates: (val, label, global corpus index), each (N, k).

    Similarity is the half-norm trick ``x·s − ‖s‖²/2`` (argmax-equivalent
    to −‖x−s‖²/2); +inf half-norms exclude padding rows. The global index
    is the tie-break key: single-device ``top_k`` prefers the lowest
    corpus index among equal distances (the data has duplicate rows, so
    ties are real), and every merge strategy must reproduce that."""
    me = lax.axis_index(STATE_AXIS)
    sim = knn._dot_expansion_sim(X, fit_X, half_norms)
    val, idx = lax.top_k(sim, k)
    lab = fit_y[idx].astype(jnp.int32)
    gidx = (idx + me * fit_X.shape[0]).astype(jnp.int32)
    return val, lab, gidx


def _vote(lab, n_classes):
    votes = jnp.sum(jax.nn.one_hot(lab, n_classes, dtype=jnp.int32), axis=1)
    return jnp.argmax(votes, axis=-1).astype(jnp.int32)


def _gather_merge_vote(val, lab, k: int, n_classes: int):
    """all_gather every shard's (N, k) candidates and vote the global
    top-k. Gathered column order is (shard, rank) == global corpus
    order — shards are contiguous ascending index ranges and each
    shard's candidates are already (similarity desc, index asc) — so
    plain ``top_k`` keeps the single-device tie-break."""
    all_val = lax.all_gather(val, STATE_AXIS, axis=0)  # (D, N, k)
    all_lab = lax.all_gather(lab, STATE_AXIS, axis=0)
    D, N = all_val.shape[0], all_val.shape[1]
    merged_val = jnp.moveaxis(all_val, 0, 1).reshape(N, D * k)
    merged_lab = jnp.moveaxis(all_lab, 0, 1).reshape(N, D * k)
    _, gsel = lax.top_k(merged_val, k)
    glab = jnp.take_along_axis(merged_lab, gsel, axis=1)
    return _vote(glab, n_classes)


def _build(mesh, params: knn.Params, pad_mask, local_fn):
    """Common scaffolding: shard the corpus on the state axis, replicate
    the queries, jit the shard_mapped kernel."""
    _check_real_rows(params, pad_mask)
    in_specs = (
        P(STATE_AXIS),  # fit_X rows
        P(STATE_AXIS),  # fit_y
        P(STATE_AXIS),  # half_sq_norms (+inf at padding)
        P(),  # X replicated
    )
    shmapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_vma=False,
    )
    half = _mask_half_norms(params, pad_mask)

    @jax.jit
    def fn(X):
        return shmapped(params.fit_X, params.fit_y, half, X)

    return fn


def sharded_predict(mesh, params: knn.Params, pad_mask=None):
    """all_gather merge: every chip gathers all candidates and reduces.
    Communication O(devices·k) per query; one collective per predict.

    Returns ``fn(X) -> (N,) int32``.
    """
    n_classes = params.n_classes
    k = params.n_neighbors

    def local_topk(fit_X, fit_y, half_norms, X):
        val, lab, _ = _local_topk(fit_X, fit_y, half_norms, X, k)
        return _gather_merge_vote(val, lab, k, n_classes)

    return _build(mesh, params, pad_mask, local_topk)


def _packable(params: knn.Params) -> bool:
    """True when ``gidx · C + label`` fits int32 — the common case; huge
    corpora fall back to carrying labels as a separate payload."""
    return params.fit_X.shape[0] * params.n_classes < 2**31


def _pack(lab, gidx, n_classes: int):
    """One int32 payload per candidate: ``gidx · C + label``. Monotone in
    gidx (labels occupy the low ``C`` residues), so ordering packed values
    ascending == ordering global indices ascending — the tie-break key
    survives packing, and every hop moves one int array instead of two."""
    return gidx * jnp.int32(n_classes) + lab


def _merge_topk(av, ai, bv, bi, k: int, extra_a=None, extra_b=None):
    """Sort-free merge by rank (merge-path) of two (N, k) candidate blocks,
    each already ordered by (similarity desc, index asc).

    Each candidate's output rank is its own position plus the count of
    strictly-preceding candidates in the OTHER block. Indices are unique
    across shards, so precedence is a total order and the ranks are a
    permutation — bit-identical to a lexicographic 2-key sort, without
    the variadic ``lax.sort`` whose generic comparator dominated the ring's
    runtime on the scaling canary (2.1× all_gather at 8 shards before this
    rewrite). Cost: k² vectorized compares + two (2k → k) one-hot
    contractions; k = 5 for the reference checkpoint.

    ``extra_a``/``extra_b`` is an optional int payload (labels, when the
    packed form would overflow) routed through the same selection."""
    b_pre_a = (bv[:, None, :] > av[:, :, None]) | (
        (bv[:, None, :] == av[:, :, None])
        & (bi[:, None, :] < ai[:, :, None])
    )  # (N, i, j): does B[j] precede A[i]
    pos = jnp.arange(k, dtype=jnp.int32)[None, :]
    rank_a = pos + jnp.sum(b_pre_a, axis=2, dtype=jnp.int32)
    rank_b = pos + jnp.sum(~b_pre_a, axis=1, dtype=jnp.int32)
    # rank ≥ k one-hots to a zero row → candidate dropped; ranks are
    # unique, so each kept position gets exactly one writer
    sel_a = jax.nn.one_hot(rank_a, k, dtype=jnp.int32)  # (N, k, k)
    sel_b = jax.nn.one_hot(rank_b, k, dtype=jnp.int32)
    # values route through where-select, NOT a one-hot matmul: padding
    # candidates carry −inf similarity, and 0 · (−inf) = NaN would poison
    # the whole merged row (a shard with fewer than k real corpus rows
    # emits −inf candidates legitimately)
    mv = jnp.sum(
        jnp.where(sel_a.astype(bool), av[:, :, None], 0.0), axis=1
    ) + jnp.sum(jnp.where(sel_b.astype(bool), bv[:, :, None], 0.0), axis=1)
    mi = jnp.einsum("nik,ni->nk", sel_a, ai) + jnp.einsum(
        "nik,ni->nk", sel_b, bi
    )
    if extra_a is None:
        return mv, mi, None
    me = jnp.einsum("nik,ni->nk", sel_a, extra_a) + jnp.einsum(
        "nik,ni->nk", sel_b, extra_b
    )
    return mv, mi, me


# A candidate block in flight is a "held" tuple shared by the ring and
# tournament merges: (val, packed) when the corpus packs into int32, else
# (val, gidx, lab) with labels as their own payload.


def _make_held(val, lab, gidx, n_classes: int, packable: bool):
    if packable:
        # one int payload per hop: label rides the low residues of the
        # packed index and is recovered by mod C at the end
        return (val, _pack(lab, gidx, n_classes))
    # corpus too large to pack: labels travel as their own payload
    return (val, gidx, lab)


def _merge_held(a, b, k: int, packable: bool):
    ea, eb = (a[2], b[2]) if not packable else (None, None)
    mv, mi, me = _merge_topk(a[0], a[1], b[0], b[1], k, ea, eb)
    return (mv, mi) if me is None else (mv, mi, me)


def _held_labels(held, n_classes: int, packable: bool):
    return held[1] % jnp.int32(n_classes) if packable else held[2]


def _ring_merge(held, k: int, packable: bool):
    """The ring schedule over a held block: circulate with ``ppermute``,
    software-pipelined (merge the previous hop's block while the next
    transfer flies). One home for the loop — the XLA local stage
    (``ring_predict``) and the fused local stage share it."""
    n_dev = axis_size(STATE_AXIS)
    if n_dev == 1:
        return held
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def rotate(arrs):
        return tuple(lax.ppermute(a, STATE_AXIS, perm) for a in arrs)

    # prologue: issue hop 1
    incoming = rotate(held)

    def body(_, carry):
        acc, prev = carry
        nxt = rotate(prev)  # forward the held block
        # merge while the transfer flies
        return _merge_held(acc, prev, k, packable), nxt

    acc, last = lax.fori_loop(0, n_dev - 2, body, (held, incoming))
    return _merge_held(acc, last, k, packable)  # last in-flight block


def _require_pow2_state(n_dev: int) -> None:
    if n_dev & (n_dev - 1):
        raise ValueError(
            f"tournament merge needs a power-of-two state axis, got {n_dev}"
        )


def _tournament_merge(held, k: int, packable: bool, n_dev: int):
    """Recursive-doubling schedule over a held block: round r exchanges
    with the XOR-2^r partner. Requires power-of-two ``n_dev`` (validated
    by callers). Shared by ``tournament_predict`` and the fused path."""
    d = 1
    while d < n_dev:
        perm = [(i, i ^ d) for i in range(n_dev)]
        other = tuple(lax.ppermute(a, STATE_AXIS, perm) for a in held)
        held = _merge_held(held, other, k, packable)
        d <<= 1
    return held


def ring_predict(mesh, params: knn.Params, pad_mask=None):
    """Ring merge: the candidate block circulates around the state axis
    with ``ppermute`` — the ring-attention neighbor-passing schedule
    applied to top-k merge. Live state per chip is O(N·k), independent of
    device count, and the schedule is software-pipelined: each iteration
    forwards the block it holds and merges the block received on the
    *previous* hop, so the merge compute has no data dependence on the
    in-flight collective and XLA can overlap the two.

    Exactly equivalent to ``sharded_predict`` (same candidates, same
    tie-break); preferable on large meshes where the gathered (D, N, k)
    buffer would dominate memory. On small meshes ``tournament_predict``
    needs only ⌈log₂ D⌉ rounds to the ring's D−1.
    """
    n_classes = params.n_classes
    k = params.n_neighbors
    packable = _packable(params)

    def local_ring(fit_X, fit_y, half_norms, X):
        val, lab, gidx = _local_topk(fit_X, fit_y, half_norms, X, k)
        if axis_size(STATE_AXIS) == 1:
            return _vote(lab, n_classes)
        held = _make_held(val, lab, gidx, n_classes, packable)
        final = _ring_merge(held, k, packable)
        return _vote(_held_labels(final, n_classes, packable), n_classes)

    return _build(mesh, params, pad_mask, local_ring)


def fused_predict(
    mesh, params: knn.Params, pad_mask=None, *,
    merge: str = "all_gather",
    row_tile: int = 512, corpus_chunk: int = 512, interpret: bool = False,
):
    """FUSED local stage × any merge schedule: each chip runs the Pallas
    distance+top-k kernel (ops/pallas_knn.py) over its corpus shard —
    the per-shard (N, S/D) similarity matrix never touches HBM — then
    the (D·k) candidates merge by ``merge`` ∈ ``all_gather`` (one
    collective, as ``sharded_predict``) | ``ring`` (ppermute circulation,
    as ``ring_predict``) | ``tournament`` (recursive doubling, as
    ``tournament_predict`` — power-of-two state axis only). The local
    stage and the merge schedules are orthogonal layers; the loops are
    the same shared helpers the XLA paths use.

    Same candidates, same tie-break, bit-identical output to the XLA
    paths: shards are contiguous ascending corpus ranges, the kernel's
    in-shard order is bitwise ``lax.top_k``, and every merge ranks by
    (value desc, global index asc). TPU-only compiled (Mosaic); CPU-mesh
    tests pass ``interpret=True``.

    Returns ``fn(X) -> (N,) int32``.
    """
    import numpy as np

    from ..ops import pallas_knn

    n_classes = params.n_classes
    k = params.n_neighbors
    D = mesh.shape[STATE_AXIS]
    if k > corpus_chunk or k > 128:
        raise ValueError(f"n_neighbors={k} exceeds kernel limits")
    if merge not in ("all_gather", "ring", "tournament"):
        raise ValueError(f"unknown merge {merge!r}")
    if merge == "tournament":
        _require_pow2_state(D)

    # chunk-aligned global layout (numpy, outside shard_map): every shard
    # spans the same number of whole chunks, but the padding itself is
    # TAIL-CONCENTRATED — corpus_layout pads only after row S, before the
    # contiguous split, so e.g. S=900, D=8 gives shards 0-6 fully real and
    # shard 7 with 4 real + 124 pad rows. A shard may hold fewer than k
    # (or zero) real rows; that is legal because padded slots carry +inf
    # half-norms (pallas_knn.corpus_layout owns that invariant) and zero
    # labels, so their -inf candidates lose every merge — correctness
    # rests on the GLOBAL S >= k invariant, not per-shard balance.
    _check_real_rows(params, pad_mask)
    S = np.asarray(params.fit_X).shape[0]
    per = max(-(-S // D), k)
    per = -(-per // corpus_chunk) * corpus_chunk
    fit_t, half_sq = pallas_knn.corpus_layout(
        params.fit_X, _mask_half_norms(params, pad_mask), per * D
    )
    fity = np.zeros((per * D,), np.int32)
    fity[:S] = np.asarray(params.fit_y, np.int32)
    fit_y = jnp.asarray(fity)

    # packability of gidx·C+lab against the PADDED corpus length: gidx
    # runs over per-shard-padded global indices, up to per·D
    packable = per * D * n_classes < 2**31

    def local_fused(fit_t_l, half_l, fity_l, X):
        val, idx = pallas_knn.topk_sim_idx(
            X, fit_t_l, half_l, k,
            row_tile=row_tile, corpus_chunk=corpus_chunk,
            interpret=interpret,
        )
        lab = fity_l[idx].astype(jnp.int32)
        if merge == "all_gather":
            return _gather_merge_vote(val, lab, k, n_classes)
        if axis_size(STATE_AXIS) == 1:
            return _vote(lab, n_classes)
        me = lax.axis_index(STATE_AXIS)
        gidx = (idx + me * per).astype(jnp.int32)
        held = _make_held(val, lab, gidx, n_classes, packable)
        if merge == "ring":
            held = _ring_merge(held, k, packable)
        else:
            held = _tournament_merge(held, k, packable, D)
        return _vote(_held_labels(held, n_classes, packable), n_classes)

    shmapped = shard_map(
        local_fused,
        mesh=mesh,
        in_specs=(
            P(None, STATE_AXIS),  # fit_t columns = corpus rows
            P(None, STATE_AXIS),  # half norms
            P(STATE_AXIS),  # labels
            P(),  # X replicated
        ),
        out_specs=P(),
        check_vma=False,
    )

    @jax.jit
    def fn(X):
        return shmapped(fit_t, half_sq, fit_y, X)

    return fn


def tournament_predict(mesh, params: knn.Params, pad_mask=None):
    """Recursive-doubling merge: round r exchanges candidate blocks with
    the XOR-2^r partner and rank-merges, so every chip holds the global
    top-k after ⌈log₂ D⌉ rounds — against the ring's D−1 — while live
    state stays O(N·k) like the ring (the all_gather path buffers
    (D, N, k)). XOR partners at distances 1/2/4 are torus neighbors on a
    TPU ICI mesh, so each round's exchange stays local. Same candidates,
    same tie-break, bit-identical output to both other merges.

    Requires a power-of-two state axis (XOR partnering); ``sharded_predict``
    covers the general case.
    """
    n_classes = params.n_classes
    k = params.n_neighbors
    n_dev = mesh.shape[STATE_AXIS]
    _require_pow2_state(n_dev)
    packable = _packable(params)

    def local_tournament(fit_X, fit_y, half_norms, X):
        val, lab, gidx = _local_topk(fit_X, fit_y, half_norms, X, k)
        if n_dev == 1:
            return _vote(lab, n_classes)
        held = _make_held(val, lab, gidx, n_classes, packable)
        held = _tournament_merge(held, k, packable, n_dev)
        return _vote(_held_labels(held, n_classes, packable), n_classes)

    return _build(mesh, params, pad_mask, local_tournament)
