"""State-sharded k-NN: the training corpus split across chips, global top-k
merged over ICI.

The reference's KNN walks one KDTree on one CPU (SURVEY.md §2.3). The
TPU-scale design (SURVEY.md §2.4): shard the (S, F) training matrix on the
mesh's state axis; each chip computes distances to its local shard and takes
a *local* top-k; the (devices × k) candidates are then ``all_gather``-merged
and reduced to the global top-k. Communication is O(devices · k) per query —
independent of corpus size S — so the corpus can grow with the mesh.

Built on ``shard_map`` with explicit collectives, per the scaling-book
recipe: pick the mesh, shard the state, let the collectives ride ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models import knn
from .mesh import STATE_AXIS


def pad_corpus(d: dict, n_shards: int) -> dict:
    """Pad corpus length to a multiple of the state-axis size — and to at
    least ``n_neighbors`` rows per shard, so the local ``top_k`` is always
    well-formed — with +inf-distance sentinels (zero rows never win because
    their half-norm is replaced by +inf)."""
    import numpy as np

    S = d["fit_X"].shape[0]
    k = int(d.get("n_neighbors", 1))
    target = max(S + (-S) % n_shards, n_shards * k)
    pad = target - S
    if pad == 0:
        return d
    out = dict(d)
    out["fit_X"] = np.concatenate(
        [d["fit_X"], np.zeros((pad, d["fit_X"].shape[1]))], axis=0
    )
    out["y"] = np.concatenate([d["y"], np.zeros(pad, d["y"].dtype)])
    out["pad_mask"] = np.concatenate(
        [np.zeros(S, bool), np.ones(pad, bool)]
    )
    return out


def _mask_half_norms(params: knn.Params, pad_mask):
    half = params.half_sq_norms
    if pad_mask is not None:
        half = jnp.where(jnp.asarray(pad_mask), jnp.inf, half)
    return half


def _local_topk(fit_X, fit_y, half_norms, X, k):
    """Per-chip candidates: (val, label, global corpus index), each (N, k).

    Similarity is the half-norm trick ``x·s − ‖s‖²/2`` (argmax-equivalent
    to −‖x−s‖²/2); +inf half-norms exclude padding rows. The global index
    is the tie-break key: single-device ``top_k`` prefers the lowest
    corpus index among equal distances (the data has duplicate rows, so
    ties are real), and every merge strategy must reproduce that."""
    me = lax.axis_index(STATE_AXIS)
    sim = (
        jnp.matmul(X, fit_X.T, precision=lax.Precision.HIGHEST)
        - half_norms[None, :]
    )
    val, idx = lax.top_k(sim, k)
    lab = fit_y[idx].astype(jnp.int32)
    gidx = (idx + me * fit_X.shape[0]).astype(jnp.int32)
    return val, lab, gidx


def _vote(lab, n_classes):
    votes = jnp.sum(jax.nn.one_hot(lab, n_classes, dtype=jnp.int32), axis=1)
    return jnp.argmax(votes, axis=-1).astype(jnp.int32)


def _build(mesh, params: knn.Params, pad_mask, local_fn):
    """Common scaffolding: shard the corpus on the state axis, replicate
    the queries, jit the shard_mapped kernel."""
    in_specs = (
        P(STATE_AXIS),  # fit_X rows
        P(STATE_AXIS),  # fit_y
        P(STATE_AXIS),  # half_sq_norms (+inf at padding)
        P(),  # X replicated
    )
    shmapped = jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_vma=False,
    )
    half = _mask_half_norms(params, pad_mask)

    @jax.jit
    def fn(X):
        return shmapped(params.fit_X, params.fit_y, half, X)

    return fn


def sharded_predict(mesh, params: knn.Params, pad_mask=None):
    """all_gather merge: every chip gathers all candidates and reduces.
    Communication O(devices·k) per query; one collective per predict.

    Returns ``fn(X) -> (N,) int32``.
    """
    n_classes = params.n_classes
    k = params.n_neighbors

    def local_topk(fit_X, fit_y, half_norms, X):
        val, lab, _ = _local_topk(fit_X, fit_y, half_norms, X, k)
        all_val = lax.all_gather(val, STATE_AXIS, axis=0)  # (D, N, k)
        all_lab = lax.all_gather(lab, STATE_AXIS, axis=0)
        D, N = all_val.shape[0], all_val.shape[1]
        # gathered column order == global corpus order, so plain top_k
        # keeps the single-device tie-break
        merged_val = jnp.moveaxis(all_val, 0, 1).reshape(N, D * k)
        merged_lab = jnp.moveaxis(all_lab, 0, 1).reshape(N, D * k)
        _, gsel = lax.top_k(merged_val, k)
        glab = jnp.take_along_axis(merged_lab, gsel, axis=1)
        return _vote(glab, n_classes)

    return _build(mesh, params, pad_mask, local_topk)


def ring_predict(mesh, params: knn.Params, pad_mask=None):
    """Ring merge: the candidate block circulates around the state axis
    with ``ppermute`` — the ring-attention neighbor-passing schedule
    applied to top-k merge. Live state per chip is O(N·k), independent of
    device count, and the schedule is software-pipelined: each iteration
    forwards the block it holds and merges the block received on the
    *previous* hop, so the merge compute has no data dependence on the
    in-flight collective and XLA can overlap the two.

    Exactly equivalent to ``sharded_predict`` (same candidates, same
    tie-break); preferable on large meshes where the gathered (D, N, k)
    buffer would dominate memory.
    """
    n_classes = params.n_classes
    k = params.n_neighbors

    def local_ring(fit_X, fit_y, half_norms, X):
        n_dev = lax.axis_size(STATE_AXIS)
        val, lab, gidx = _local_topk(fit_X, fit_y, half_norms, X, k)
        if n_dev == 1:
            return _vote(lab, n_classes)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

        def rotate(v, ints):
            # one f32 + one packed int32 payload per hop (labels and
            # indices ride together: fewer collective launches)
            return (
                lax.ppermute(v, STATE_AXIS, perm),
                lax.ppermute(ints, STATE_AXIS, perm),
            )

        def merge(av, al, ai, bv, bl, bi):
            neg = jnp.concatenate([-av, -bv], axis=1)  # (N, 2k)
            mi = jnp.concatenate([ai, bi], axis=1)
            ml = jnp.concatenate([al, bl], axis=1)
            # lexicographic: similarity desc, then global index asc —
            # bit-identical to top_k over the corpus-ordered row
            sneg, si, sl = lax.sort((neg, mi, ml), num_keys=2)
            return -sneg[:, :k], sl[:, :k], si[:, :k]

        ints0 = jnp.concatenate([lab, gidx], axis=1)  # (N, 2k) packed
        # prologue: issue hop 1
        in_v, in_ints = rotate(val, ints0)

        def body(_, carry):
            av, al, ai, pv, pints = carry
            nv, nints = rotate(pv, pints)  # forward the held block
            av, al, ai = merge(  # merge it while the transfer flies
                av, al, ai, pv, pints[:, :k], pints[:, k:]
            )
            return av, al, ai, nv, nints

        av, al, ai, lv, lints = lax.fori_loop(
            0, n_dev - 2, body, (val, lab, gidx, in_v, in_ints)
        )
        # epilogue: merge the final in-flight block
        av, al, ai = merge(av, al, ai, lv, lints[:, :k], lints[:, k:])
        return _vote(al, n_classes)

    return _build(mesh, params, pad_mask, local_ring)
