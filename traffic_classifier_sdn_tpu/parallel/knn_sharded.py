"""State-sharded k-NN: the training corpus split across chips, global top-k
merged over ICI.

The reference's KNN walks one KDTree on one CPU (SURVEY.md §2.3). The
TPU-scale design (SURVEY.md §2.4): shard the (S, F) training matrix on the
mesh's state axis; each chip computes distances to its local shard and takes
a *local* top-k; the (devices × k) candidates are then ``all_gather``-merged
and reduced to the global top-k. Communication is O(devices · k) per query —
independent of corpus size S — so the corpus can grow with the mesh.

Built on ``shard_map`` with explicit collectives, per the scaling-book
recipe: pick the mesh, shard the state, let the collectives ride ICI.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models import knn
from .mesh import STATE_AXIS


def pad_corpus(d: dict, n_shards: int) -> dict:
    """Pad corpus length to a multiple of the state-axis size — and to at
    least ``n_neighbors`` rows per shard, so the local ``top_k`` is always
    well-formed — with +inf-distance sentinels (zero rows never win because
    their half-norm is replaced by +inf)."""
    import numpy as np

    S = d["fit_X"].shape[0]
    k = int(d.get("n_neighbors", 1))
    target = max(S + (-S) % n_shards, n_shards * k)
    pad = target - S
    if pad == 0:
        return d
    out = dict(d)
    out["fit_X"] = np.concatenate(
        [d["fit_X"], np.zeros((pad, d["fit_X"].shape[1]))], axis=0
    )
    out["y"] = np.concatenate([d["y"], np.zeros(pad, d["y"].dtype)])
    out["pad_mask"] = np.concatenate(
        [np.zeros(S, bool), np.ones(pad, bool)]
    )
    return out


def sharded_predict(mesh, params: knn.Params, pad_mask=None):
    """Build a jit-compiled sharded predict: X replicated per-chip on the
    state axis (each chip sees the full query batch), corpus sharded.

    Returns ``fn(X) -> (N,) int32``.
    """
    n_classes = params.n_classes
    k = params.n_neighbors

    in_specs = (
        P(STATE_AXIS),  # fit_X rows
        P(STATE_AXIS),  # fit_y
        P(STATE_AXIS),  # half_sq_norms (+inf at padding)
        P(),  # X replicated
    )

    def local_topk(fit_X, fit_y, half_norms, X):
        sim = (
            jnp.matmul(X, fit_X.T, precision=lax.Precision.HIGHEST)
            - half_norms[None, :]
        )
        val, idx = lax.top_k(sim, k)  # local (N, k)
        lab = fit_y[idx]
        # merge across the state axis: gather every chip's candidates
        all_val = lax.all_gather(val, STATE_AXIS, axis=0)  # (D, N, k)
        all_lab = lax.all_gather(lab, STATE_AXIS, axis=0)
        D = all_val.shape[0]
        N = all_val.shape[1]
        merged_val = jnp.moveaxis(all_val, 0, 1).reshape(N, D * k)
        merged_lab = jnp.moveaxis(all_lab, 0, 1).reshape(N, D * k)
        gval, gidx = lax.top_k(merged_val, k)  # global top-k
        glab = jnp.take_along_axis(merged_lab, gidx, axis=1)
        votes = jnp.sum(
            jax.nn.one_hot(glab, n_classes, dtype=jnp.int32), axis=1
        )
        return jnp.argmax(votes, axis=-1).astype(jnp.int32)

    shmapped = jax.shard_map(
        local_topk,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_vma=False,
    )

    half = params.half_sq_norms
    if pad_mask is not None:
        half = jnp.where(jnp.asarray(pad_mask), jnp.inf, half)

    @jax.jit
    def fn(X):
        return shmapped(params.fit_X, params.fit_y, half, X)

    return fn
