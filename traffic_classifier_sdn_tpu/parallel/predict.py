"""Batch-sharded (data-parallel) prediction for every model family.

The reference classifies one flow per ``model.predict`` call in a Python
loop (traffic_classifier.py:103-106). Here the (N, 12) feature matrix is
sharded row-wise over the mesh's data axis and the *same* pure predict
function runs on every chip over its shard — XLA inserts no collectives at
all for the embarrassingly-parallel models (logreg/gnb/kmeans/forest/svc),
and the output keeps the batch sharding so downstream consumers (label
decode, the flow table) can stay distributed.
"""

from __future__ import annotations

from functools import partial
from collections.abc import Callable
from typing import Any

import jax

from .mesh import batch_sharded, replicated


def shard_params(mesh, params: Any):
    """Replicate a param pytree onto every device of the mesh."""
    return jax.device_put(params, replicated(mesh))


def shard_batch(mesh, X):
    """Split an (N, …) batch row-wise across the data axis. N must divide
    by the data-axis size (the ingest batcher's buckets are powers of two,
    so this holds by construction)."""
    return jax.device_put(X, batch_sharded(mesh))


def data_parallel(mesh, fn: Callable) -> Callable:
    """jit ``fn(params, X, *rest)`` with params replicated and X (plus any
    extra batch-like args, e.g. the hi/lo split) batch-sharded."""

    @partial(jax.jit, static_argnums=())
    def wrapped(params, X, *rest):
        return fn(params, X, *rest)

    def call(params, X, *rest):
        params = shard_params(mesh, params)
        X = shard_batch(mesh, X)
        rest = tuple(shard_batch(mesh, r) for r in rest)
        return wrapped(params, X, *rest)

    return call
