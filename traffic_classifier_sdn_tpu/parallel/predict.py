"""Batch-sharded (data-parallel) prediction for every model family.

The reference classifies one flow per ``model.predict`` call in a Python
loop (traffic_classifier.py:103-106). Here the (N, 12) feature matrix is
sharded row-wise over the mesh's data axis and the *same* pure predict
function runs on every chip over its shard — XLA inserts no collectives at
all for the embarrassingly-parallel models (logreg/gnb/kmeans/forest/svc),
and the output keeps the batch sharding so downstream consumers (label
decode, the flow table) can stay distributed.
"""

from __future__ import annotations

import warnings
from functools import partial
from collections.abc import Callable
from typing import Any

import jax

from .mesh import batch_sharded, replicated


def shard_params(mesh, params: Any):
    """Replicate a param pytree onto every device of the mesh."""
    return jax.device_put(params, replicated(mesh))


def shard_batch(mesh, X):
    """Split an (N, …) batch row-wise across the data axis. N must divide
    by the data-axis size (the ingest batcher's buckets are powers of two,
    so this holds by construction)."""
    return jax.device_put(X, batch_sharded(mesh))


def data_parallel(mesh, fn: Callable) -> Callable:
    """jit ``fn(params, X, *rest)`` with params replicated and X (plus any
    extra batch-like args, e.g. the hi/lo split) batch-sharded.

    ``X`` is donated: every call site passes the fresh ``device_put``
    copy made in ``call`` below (never a caller-held array), so the
    donation can only ever reclaim the staging copy — pinning the
    per-tick batch in rotating donated buffers instead of allocating
    fresh HBM per predict (the serving loop's allocation churn)."""

    @partial(jax.jit, donate_argnums=(1,))
    def wrapped(params, X, *rest):
        return fn(params, X, *rest)

    compiled_once = False

    def call(params, X, *rest):
        nonlocal compiled_once
        params = shard_params(mesh, params)
        staged = shard_batch(mesh, X)
        if staged is X:
            # device_put aliases when the sharding already matches
            # (1-device meshes, repeated calls): copy so the donation
            # below can never invalidate the caller's array
            staged = jax.numpy.array(staged, copy=True)
        rest = tuple(shard_batch(mesh, r) for r in rest)
        if not compiled_once:
            # models whose outputs carry no f32 batch-shaped result
            # (argmax label vectors) give XLA nothing to alias the
            # donated X onto and it says so at lowering — expected
            # here, not actionable; suppress around THIS compile only
            # (a process-global filter would hide genuinely missed
            # donations in unrelated user code)
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable",
                )
                out = wrapped(params, staged, *rest)
            compiled_once = True
            return out
        return wrapped(params, staged, *rest)

    return call
