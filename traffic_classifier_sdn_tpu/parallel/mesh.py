"""Device mesh construction — the framework's single parallelism substrate.

The reference has no parallelism at all (SURVEY.md §2.4); every scaling axis
here is expressed over one ``jax.sharding.Mesh``:

- ``data``  — the flow batch N (the reference's per-flow Python loop axis)
- ``state`` — model state: the KNN corpus, the forest's trees, SVC's support
  vectors (the axes sklearn's Cython loops walk sequentially)

Multi-host: call ``init_distributed`` first (jax.distributed handles the
DCN rendezvous); the mesh then spans all hosts' devices and XLA routes
collectives over ICI within a slice and DCN across slices.

Tests exercise the same code on a virtual CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4c).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
STATE_AXIS = "state"


# -- jax version compat ------------------------------------------------------
# ``shard_map`` reached the top-level jax namespace (with ``check_vma``)
# only in newer jax; the 0.4.x line in this image ships it as
# ``jax.experimental.shard_map.shard_map`` with the older ``check_rep``
# spelling of the same knob. One resolver here so every sharded module
# (table/knn/forest/svc_sharded, train/distributed) runs on both.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        """``jax.shard_map``-compatible wrapper over the experimental
        module: ``check_vma`` (new name) maps onto ``check_rep``."""
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


def donate_argnums_if_safe(*argnums: int) -> dict:
    """``{"donate_argnums": argnums}`` when buffer donation through
    shard_map is trustworthy on this jax, ``{}`` otherwise.

    On the 0.4.x line (the experimental-shard_map fallback above),
    donating a shard_map operand intermittently corrupts the process
    heap — glibc ``corrupted double-linked list`` aborts once allocator
    state is complex enough, reproduced under the full test suite and
    gone with donation disabled; single-run tests pass either way,
    which is exactly what a double-free looks like. The jax line that
    ships ``jax.shard_map`` natively donates fine. Callers splat this
    into ``jax.jit`` so the old-jax path trades the in-place HBM
    update for a heap that stays intact."""
    if hasattr(jax, "shard_map"):
        return {"donate_argnums": argnums}
    return {}


def axis_size(name: str) -> int:
    """Static size of mesh axis ``name`` inside a shard_map body.

    Newer jax spells this ``jax.lax.axis_size``; on the 0.4.x line the
    axis environment's frame lookup returns the same static int — both
    are trace-time constants, so ``if axis_size(...) == 1`` branches
    stay Python-level."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(name))
    import jax.core as _core

    return int(_core.axis_frame(name))


def make_mesh(
    n_data: int | None = None, n_state: int = 1, devices=None
) -> Mesh:
    """A (data, state) mesh. Default: all devices on the data axis."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = devices.size // n_state
    if n_data * n_state != devices.size:
        raise ValueError(
            f"mesh {n_data}x{n_state} != {devices.size} devices"
        )
    return Mesh(devices.reshape(n_data, n_state), (DATA_AXIS, STATE_AXIS))


def init_distributed(coordinator: str | None = None, **kw) -> None:
    """Multi-host bring-up (the reference's closest analogue is the
    OpenFlow TCP session at simple_monitor_13.py:43-47; ours is the XLA
    runtime's DCN rendezvous)."""
    if coordinator is not None:
        kw["coordinator_address"] = coordinator
    jax.distributed.initialize(**kw)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh) -> NamedSharding:
    """Rows of an (N, …) batch split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def state_sharded(mesh: Mesh) -> NamedSharding:
    """Leading axis of model state split over the state axis."""
    return NamedSharding(mesh, P(STATE_AXIS))
