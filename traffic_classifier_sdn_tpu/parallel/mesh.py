"""Device mesh construction — the framework's single parallelism substrate.

The reference has no parallelism at all (SURVEY.md §2.4); every scaling axis
here is expressed over one ``jax.sharding.Mesh``:

- ``data``  — the flow batch N (the reference's per-flow Python loop axis)
- ``state`` — model state: the KNN corpus, the forest's trees, SVC's support
  vectors (the axes sklearn's Cython loops walk sequentially)

Multi-host: call ``init_distributed`` first (jax.distributed handles the
DCN rendezvous); the mesh then spans all hosts' devices and XLA routes
collectives over ICI within a slice and DCN across slices.

Tests exercise the same code on a virtual CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4c).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
STATE_AXIS = "state"


def make_mesh(
    n_data: int | None = None, n_state: int = 1, devices=None
) -> Mesh:
    """A (data, state) mesh. Default: all devices on the data axis."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = devices.size // n_state
    if n_data * n_state != devices.size:
        raise ValueError(
            f"mesh {n_data}x{n_state} != {devices.size} devices"
        )
    return Mesh(devices.reshape(n_data, n_state), (DATA_AXIS, STATE_AXIS))


def init_distributed(coordinator: str | None = None, **kw) -> None:
    """Multi-host bring-up (the reference's closest analogue is the
    OpenFlow TCP session at simple_monitor_13.py:43-47; ours is the XLA
    runtime's DCN rendezvous)."""
    if coordinator is not None:
        kw["coordinator_address"] = coordinator
    jax.distributed.initialize(**kw)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh) -> NamedSharding:
    """Rows of an (N, …) batch split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def state_sharded(mesh: Mesh) -> NamedSharding:
    """Leading axis of model state split over the state axis."""
    return NamedSharding(mesh, P(STATE_AXIS))
