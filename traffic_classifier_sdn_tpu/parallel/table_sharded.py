"""Flow-state table sharded across the device mesh — serving capacity
beyond one chip's table.

The reference tracks flows in one Python dict (traffic_classifier.py:24);
the single-device replacement is ``core/flow_table.FlowTable``. This module
scales that serving state across the mesh's data axis: each device owns an
independent ``(local_capacity+1,)`` SoA shard, the host routes update
records to shards by global slot range, and every device op runs under one
``shard_map`` (no cross-device traffic in the steady state — flows are
partitioned, not replicated; only the O(rows) render candidates and the
bit-packed stale masks come home, where the tiny cross-shard merges happen
on host).

Scaling shape: capacity_total = n_shards × local_capacity, one scatter +
one full-shard predict per shard per tick, all shards in parallel — an
8-device mesh serves 2²³ concurrent flows at the same per-device cost the
single-chip spine pays for 2²⁰.

Device axis layout: every leaf carries a leading ``(n_shards, …)`` axis
sharded over ``mesh``'s data axis; ``shard_map`` peels it to the local
``[0]`` table inside each shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import flow_table as ft
from ..ingest.batcher import DEFAULT_BUCKETS, FlowIndex, Batcher, bucket_size
from .mesh import DATA_AXIS


def _n_shards(mesh) -> int:
    return mesh.shape[DATA_AXIS]


def make_sharded_table(mesh, capacity_total: int) -> ft.FlowTable:
    """A FlowTable pytree with leaves of shape (n_shards, local_cap+1),
    dim 0 sharded over the mesh's data axis."""
    n = _n_shards(mesh)
    if capacity_total % n:
        raise ValueError(f"capacity {capacity_total} not divisible by {n}")
    local = ft.make_table(capacity_total // n)
    stacked = jax.tree.map(lambda a: jnp.broadcast_to(a, (n,) + a.shape), local)
    return jax.device_put(
        stacked, NamedSharding(mesh, P(DATA_AXIS))
    )


def make_apply(mesh):
    """jit'd (tables, wire) → tables: per-shard ``apply_wire`` under one
    shard_map. ``wire`` is (n_shards, B, 6) uint32 — the host router pads
    every shard's sub-batch to one common bucket size."""

    @functools.partial(jax.jit, donate_argnums=0)
    def apply(tables, wire):
        def local(t, w):
            t1 = jax.tree.map(lambda a: a[0], t)
            out = ft.apply_wire(t1, w[0])
            return jax.tree.map(lambda a: a[None], out)

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )(tables, wire)

    return apply


def make_tick_outputs(mesh, predict_fn, n_rows: int):
    """jit'd (tables, params, floor, now, idle_seconds) → per-shard render
    candidates + stale bits, ONE dispatch for the whole tick's read side:
    full-shard predict, scored local top-n (labels + active flags
    gathered device-side), and the bit-packed eviction mask. Everything
    that crosses to host is O(n_rows + capacity/8) per shard."""

    @jax.jit
    def tick(tables, params, floor, now, idle_seconds):
        def local(t, p, fl, nw, idl):
            t1 = jax.tree.map(lambda a: a[0], t)
            labels = predict_fn(p, ft.features12(t1))
            outs = ft.top_active_scored(t1, labels, n_rows, fl[0, 0])
            bits = ft.stale_bits(t1, nw[0, 0], idl[0, 0])
            return tuple(o[None] for o in outs) + (bits[None],)

        scalar = lambda v: jnp.broadcast_to(  # noqa: E731
            jnp.int32(v), (_n_shards(mesh), 1)
        )
        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )(tables, params, scalar(floor), scalar(now), scalar(idle_seconds))

    return tick


def make_clear(mesh):
    """jit'd (tables, slots) → tables: per-shard ``clear_slots``; ``slots``
    is (n_shards, E) LOCAL slot ids padded with local_capacity."""

    @functools.partial(jax.jit, donate_argnums=0)
    def clear(tables, slots):
        def local(t, s):
            t1 = jax.tree.map(lambda a: a[0], t)
            out = ft.clear_slots(t1, s[0])
            return jax.tree.map(lambda a: a[None], out)

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )(tables, slots)

    return clear


class ShardedFlowEngine:
    """Host spine for the sharded table: ONE global flow index (slots
    [0, capacity_total)), shard routing by slot range, shard_map device
    ops. The single-device ``FlowStateEngine`` API shape, scaled across
    the mesh.

    Shard s owns global slots [s·local_cap, (s+1)·local_cap); the host
    splits every flushed batch by that range, so a flow's whole lifetime
    stays on one shard and no device ever sees another shard's state.
    """

    def __init__(self, mesh, capacity_total: int, buckets=DEFAULT_BUCKETS,
                 predict_fn=None, params=None, table_rows: int = 64):
        self.mesh = mesh
        self.n_shards = _n_shards(mesh)
        if capacity_total % self.n_shards:
            raise ValueError("capacity must divide evenly across shards")
        self.local_capacity = capacity_total // self.n_shards
        self.capacity = capacity_total
        self.index = FlowIndex(capacity_total)
        self.batcher = Batcher(self.index, buckets)
        self.buckets = buckets
        self.tables = make_sharded_table(mesh, capacity_total)
        self._apply = make_apply(mesh)
        self._clear = make_clear(mesh)
        self._tick_outputs = (
            make_tick_outputs(mesh, predict_fn, table_rows)
            if predict_fn is not None else None
        )
        self.params = params
        self.table_rows = table_rows
        self._tick_floor = 0
        self._last_time = 0

    # -- ingest (host) -----------------------------------------------------
    def ingest(self, records) -> int:
        n = 0
        for r in records:
            self._last_time = max(self._last_time, r.time)
            if not self.batcher.add(r):
                self.step()
                self.batcher.add(r)
            n += 1
        return n

    @property
    def last_time(self) -> int:
        return self._last_time

    def mark_tick(self) -> None:
        self._tick_floor = self._last_time

    def num_flows(self) -> int:
        return len(self.index.slot_meta)

    # -- device ops --------------------------------------------------------
    def _route(self, batch) -> np.ndarray:
        """(n_shards, B, 6) uint32: the flushed batch split by owning
        shard, each sub-batch rebased to local slots and padded (local
        scratch = local_capacity) to one shared bucket size."""
        w = ft.pack_wire(batch)
        gslot = w[:, 0] & np.uint32(0x3FFFFFFF)
        real = gslot < self.capacity
        shard = np.minimum(
            gslot // self.local_capacity, self.n_shards - 1
        ).astype(np.int64)
        counts = np.bincount(shard[real], minlength=self.n_shards)
        B = bucket_size(int(counts.max()) if counts.size else 1, self.buckets)
        out = np.empty((self.n_shards, B, 6), np.uint32)
        # padding rows: local scratch slot, no flags
        out[:, :, 0] = np.uint32(self.local_capacity)
        out[:, :, 1:] = 0
        for s in range(self.n_shards):
            rows = w[real & (shard == s)]
            rows[:, 0] -= np.uint32(s * self.local_capacity)
            out[s, : rows.shape[0]] = rows
        return out

    def step(self) -> bool:
        applied = False
        while (batch := self.batcher.flush()) is not None:
            self.tables = self._apply(self.tables, jnp.asarray(self._route(batch)))
            applied = True
        return applied

    def tick_render(self, now: int, idle_seconds: int):
        """One fused read-side dispatch for the whole mesh: returns
        ``(rows, evicted)`` where rows are the global top table_rows
        ``(global_slot, label, fwd_active, rev_active)`` merged across
        shards by activity score, and evicted is the count of idle flows
        released everywhere."""
        if self._tick_outputs is None:
            raise ValueError("engine built without a predict_fn")
        self.step()
        idx, valid, score, lab, fa, ra, bits = (
            np.asarray(o)
            for o in self._tick_outputs(
                self.tables, self.params, self._tick_floor, now, idle_seconds
            )
        )
        # global render merge: best table_rows of n_shards×table_rows
        # candidates (tiny, host-side)
        cand = []
        for s in range(self.n_shards):
            for j in range(idx.shape[1]):
                if valid[s, j]:
                    cand.append((
                        float(score[s, j]),
                        int(s * self.local_capacity + idx[s, j]),
                        int(lab[s, j]), bool(fa[s, j]), bool(ra[s, j]),
                    ))
        cand.sort(key=lambda c: (-c[0], c[1]))
        rows = [(g, c, f, r) for _sc, g, c, f, r in cand[: self.table_rows]]

        # eviction: unpack each shard's bits, release + clear
        evicted = 0
        local_cap = self.local_capacity
        clear_batches = []
        for s in range(self.n_shards):
            stale = np.unpackbits(bits[s], count=local_cap + 1)[:-1]
            slots = np.nonzero(stale)[0]
            evicted += slots.size
            clear_batches.append(slots)
            self.index.release_slots(slots + s * local_cap)
        E = max((b.size for b in clear_batches), default=0)
        if E:
            E = bucket_size(E, self.buckets)
            padded = np.full((self.n_shards, E), local_cap, np.int32)
            for s, b in enumerate(clear_batches):
                padded[s, : b.size] = b
            self.tables = self._clear(self.tables, jnp.asarray(padded))
        return rows, evicted

    def slot_metadata(self, slots):
        return {
            int(s): self.index.slot_meta[s]
            for s in slots
            if s in self.index.slot_meta
        }
