"""Flow-state table sharded across the device mesh — serving capacity
beyond one chip's table.

The reference tracks flows in one Python dict (traffic_classifier.py:24);
the single-device replacement is ``core/flow_table.FlowTable``. This module
scales that serving state across the mesh's data axis: each device owns an
independent ``(local_capacity+1,)`` SoA shard, the host routes update
records to shards round-robin by slot, and every device op runs under one
``shard_map``. Flows are partitioned, never replicated; the write path has
zero cross-device traffic, and the read path's only collective is one
all_gather per tick of the render candidates (O(rows)) plus the
bit-packed stale masks (capacity/8 bytes per shard — ~1 MiB/tick fleet-
wide at the 2²³ target), so every process can run the host-side merge.

Scaling shape: capacity_total = n_shards × local_capacity, one scatter +
one full-shard predict per shard per tick, all shards in parallel — an
8-device mesh serves 2²³ concurrent flows at the same per-device cost the
single-chip spine pays for 2²⁰.

Device axis layout: every leaf carries a leading ``(n_shards, …)`` axis
sharded over ``mesh``'s data axis; ``shard_map`` peels it to the local
``[0]`` table inside each shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import flow_table as ft
from ..ingest.batcher import DEFAULT_BUCKETS, HostSpine, bucket_size
from .mesh import DATA_AXIS, donate_argnums_if_safe, shard_map


def _n_shards(mesh) -> int:
    return mesh.shape[DATA_AXIS]


def make_sharded_table(mesh, capacity_total: int) -> ft.FlowTable:
    """A FlowTable pytree with leaves of shape (n_shards, local_cap+1),
    dim 0 sharded over the mesh's data axis. Built from host numpy (every
    leaf starts zeroed) so the device_put also works on a multi-host mesh
    — each process materializes only its addressable shards."""
    n = _n_shards(mesh)
    if capacity_total % n:
        raise ValueError(f"capacity {capacity_total} not divisible by {n}")
    local = ft.make_table(capacity_total // n)
    stacked = jax.tree.map(
        lambda a: np.zeros((n,) + a.shape, a.dtype), local
    )
    return jax.device_put(
        stacked, NamedSharding(mesh, P(DATA_AXIS))
    )


def make_apply(mesh):
    """jit'd (tables, wire) → tables: per-shard ``apply_wire`` under one
    shard_map. ``wire`` is (n_shards, B, ncols) uint32 with ncols = 4
    (compact) or 6 (full) — see ``flow_table.pack_wire``; the host
    router pads every shard's sub-batch to one common bucket size (jit
    compiles one variant per width)."""

    @functools.partial(jax.jit, **donate_argnums_if_safe(0))
    def apply(tables, wire):
        def local(t, w):
            t1 = jax.tree.map(lambda a: a[0], t)
            out = ft.apply_wire(t1, w[0])
            return jax.tree.map(lambda a: a[None], out)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )(tables, wire)

    return apply


def make_tick_outputs(mesh, predict_fn, n_rows: int):
    """jit'd (tables, params, floor, now, idle_seconds) → per-shard render
    candidates + stale bits, ONE dispatch for the whole tick's read side:
    full-shard predict, scored local top-n (labels + active flags
    gathered device-side), and the bit-packed eviction mask. Everything
    that crosses to host is O(n_rows + capacity/8) per shard."""

    @jax.jit
    def tick(tables, params, floor, now, idle_seconds):
        def local(t, p, fl, nw, idl):
            t1 = jax.tree.map(lambda a: a[0], t)
            labels = predict_fn(p, ft.features12(t1))
            outs = ft.top_active_scored(t1, labels, n_rows, fl[0, 0])
            bits = ft.stale_bits(t1, nw[0, 0], idl[0, 0])
            # all_gather the per-shard outputs (O(rows) candidates plus
            # capacity/8 stale-mask bytes per shard) so every device — and
            # on a multi-host mesh every PROCESS — holds the full
            # candidate set; the host-side merge can then run anywhere
            return tuple(
                jax.lax.all_gather(o, DATA_AXIS) for o in (*outs, bits)
            )

        scalar = lambda v: jnp.broadcast_to(  # noqa: E731
            jnp.int32(v), (_n_shards(mesh), 1)
        )
        # check_vma off: the varying-axis checker cannot see that an
        # all_gather over the only mesh axis leaves every output replicated
        return shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=P(),
            check_vma=False,
        )(tables, params, scalar(floor), scalar(now), scalar(idle_seconds))

    return tick


def make_apply_dirty(mesh):
    """``make_apply`` fused with the per-slot dirty-bit scatter
    (incremental serving): jit'd (tables, dirty, wire) →
    (tables, dirty), both sharded leaves donated where safe."""

    @functools.partial(jax.jit, **donate_argnums_if_safe(0, 1))
    def apply(tables, dirty, wire):
        def local(t, d, w):
            t1 = jax.tree.map(lambda a: a[0], t)
            out, d1 = ft.apply_wire_dirty(t1, d[0], w[0])
            return jax.tree.map(lambda a: a[None], out), d1[None]

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        )(tables, dirty, wire)

    return apply


def make_dirty_counts(mesh):
    """jit'd (dirty) → (n_shards,) int32 per-shard dirty-row counts,
    replicated — the one small fetch the host needs to pick this
    tick's compaction bucket (the max across shards, because one
    shard_map dispatch compiles one static bucket for every shard)."""

    @jax.jit
    def counts(dirty):
        def local(d):
            c = ft.dirty_count(d[0])[None]
            return jax.lax.all_gather(c, DATA_AXIS)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS),),
            out_specs=P(),
            check_vma=False,
        )(dirty)

    return counts


def make_tick_outputs_inc(mesh, predict_fn, n_rows: int):
    """The incremental read side (serving/incremental.py's dirty-set
    discipline, per shard): compact each shard's dirty rows to one
    STATIC ``bucket`` shape, gather exactly those rows' features,
    predict the subset, scatter the fresh labels into the shard's
    persistent label cache, and render the candidates from the CACHE —
    byte-identical to the full-shard predict because unchanged rows
    project unchanged features (flow_table.features12_at). Returns the
    same gathered 7-tuple as ``make_tick_outputs`` plus the updated
    (donated) caches and cleared dirty masks. ``bucket`` may equal
    ``local_capacity + 1``'s row count minus one (the rebuild bucket):
    that variant re-predicts whole shards and is what primes the cache
    on the first tick and at over-bucket churn."""

    @functools.partial(
        jax.jit, static_argnames=("bucket",),
        **donate_argnums_if_safe(1, 2),
    )
    def tick(tables, caches, dirty, params, floor, now, idle_seconds,
             bucket: int):
        def local(t, c, d, p, fl, nw, idl):
            t1 = jax.tree.map(lambda a: a[0], t)
            d1 = d[0]
            idx = ft.compact_dirty(d1, bucket)
            labels = predict_fn(p, ft.features12_at(t1, idx))
            c1 = ft.merge_labels(c[0], idx, labels)
            outs = ft.top_active_scored(t1, c1, n_rows, fl[0, 0])
            bits = ft.stale_bits(t1, nw[0, 0], idl[0, 0])
            gathered = tuple(
                jax.lax.all_gather(o, DATA_AXIS) for o in (*outs, bits)
            )
            return gathered + (c1[None], jnp.zeros_like(d1)[None])

        scalar = lambda v: jnp.broadcast_to(  # noqa: E731
            jnp.int32(v), (_n_shards(mesh), 1)
        )
        outs = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(),
                      P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(
                (P(),) * 7 + (P(DATA_AXIS), P(DATA_AXIS))
            ),
            check_vma=False,
        )(tables, caches, dirty, params, scalar(floor), scalar(now),
          scalar(idle_seconds))
        return outs

    return tick


def make_feature_sample(mesh):
    """jit'd (tables, slots) → (n_shards, k, 12) float32 feature rows,
    replicated: per-shard ``features12_at`` over (n_shards, k) LOCAL
    slot ids padded with local_capacity (scratch — never in use, so
    padding rows project zeros), all_gathered so the host can reassemble
    the sample anywhere. O(k) across the wire; the drift monitor's
    observation tap on the composed spine."""

    @jax.jit
    def sample(tables, slots):
        def local(t, s):
            t1 = jax.tree.map(lambda a: a[0], t)
            X = ft.features12_at(t1, s[0])
            return jax.lax.all_gather(X, DATA_AXIS)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(),
            check_vma=False,
        )(tables, slots)

    return sample


def make_clear(mesh):
    """jit'd (tables, slots) → tables: per-shard ``clear_slots``; ``slots``
    is (n_shards, E) LOCAL slot ids padded with local_capacity."""

    @functools.partial(jax.jit, **donate_argnums_if_safe(0))
    def clear(tables, slots):
        def local(t, s):
            t1 = jax.tree.map(lambda a: a[0], t)
            out = ft.clear_slots(t1, s[0])
            return jax.tree.map(lambda a: a[None], out)

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )(tables, slots)

    return clear


def make_clear_dirty(mesh):
    """``make_clear`` fused with label-cache invalidation: evicted
    slots' features drop to zero, so their cached labels must be
    re-predicted (flow_table.clear_slots_dirty, per shard)."""

    @functools.partial(jax.jit, **donate_argnums_if_safe(1))
    def clear(tables, dirty, slots):
        def local(t, d, s):
            t1 = jax.tree.map(lambda a: a[0], t)
            out, d1 = ft.clear_slots_dirty(t1, d[0], s[0])
            return jax.tree.map(lambda a: a[None], out), d1[None]

        return shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        )(tables, dirty, slots)

    return clear


class ShardedFlowEngine(HostSpine):
    """Host spine for the sharded table: ONE global flow index (slots
    [0, capacity_total)), shard routing by slot, shard_map device ops —
    the single-device ``FlowStateEngine`` API shape, scaled across the
    mesh (the host half is the shared ``HostSpine``).

    Global slot g lives on shard g % n_shards at local slot g // n_shards
    — round-robin, NOT range partitioning, because the index assigns
    slots sequentially: ranges would pile every new flow onto one shard
    (and pad every other shard's sub-batch to the hot shard's bucket),
    while interleaving keeps any allocation pattern balanced. A flow's
    whole lifetime stays on one shard; no device sees another's state.
    """

    def __init__(self, mesh, capacity_total: int, buckets=DEFAULT_BUCKETS,
                 predict_fn=None, params=None, table_rows: int = 64,
                 native: bool = False, incremental: bool = False):
        self.mesh = mesh
        self.n_shards = _n_shards(mesh)
        if capacity_total % self.n_shards:
            raise ValueError("capacity must divide evenly across shards")
        self.local_capacity = capacity_total // self.n_shards
        self.capacity = capacity_total
        self._init_spine(capacity_total, buckets, native)
        self.tables = make_sharded_table(mesh, capacity_total)
        self._apply = make_apply(mesh)
        self._clear = make_clear(mesh)
        # a shard's top_k cannot ask for more rows than it holds — but a
        # shard also cannot CONTRIBUTE more than it holds, so clamping the
        # per-shard k keeps the global top-table_rows merge exact
        self.table_rows = table_rows
        self._predict_fn = predict_fn
        self._feature_sample = None
        self._tick_outputs = (
            make_tick_outputs(
                mesh, predict_fn, min(table_rows, self.local_capacity)
            )
            if predict_fn is not None else None
        )
        self.params = params
        # incremental active-set serving (serving/incremental.py's
        # dirty-set discipline, applied per shard): a sharded dirty
        # mask fed by the apply scatter, a sharded persistent label
        # cache, and a bucketed compact-predict-merge read side. The
        # rebuild bucket (== local_capacity) doubles as the full-table
        # path, so the cache primes on the first tick.
        self.incremental = bool(incremental and predict_fn is not None)
        self.dirty = None
        self.caches = None
        if self.incremental:
            sharding = NamedSharding(mesh, P(DATA_AXIS))
            self.dirty = jax.device_put(
                np.ones(
                    (self.n_shards, self.local_capacity + 1), bool
                ),
                sharding,
            )
            label_dtype = jax.eval_shape(
                predict_fn, params,
                jax.ShapeDtypeStruct((1, 12), jnp.float32),
            ).dtype
            self.caches = jax.device_put(
                np.zeros(
                    (self.n_shards, self.local_capacity), label_dtype
                ),
                sharding,
            )
            self._apply_dirty = make_apply_dirty(mesh)
            self._clear_dirty = make_clear_dirty(mesh)
            self._dirty_counts = make_dirty_counts(mesh)
            self._tick_outputs_inc = make_tick_outputs_inc(
                mesh, predict_fn, min(table_rows, self.local_capacity)
            )
            from ..serving.incremental import dirty_buckets

            self.dirty_buckets = dirty_buckets(self.local_capacity) + (
                self.local_capacity,
            )

    # -- device ops --------------------------------------------------------
    def _route_chunks(self, w: np.ndarray):
        """Yield (n_shards, B, ncols) uint32 wire chunks (ncols = 4
        compact or 6 full, preserved from ``w`` — see
        ``flow_table.pack_wire``) covering every row of
        the concatenated packed batch ``w``: rows split by owning shard
        (order-preserving, so a slot's create still precedes its update),
        rebased to local slots, and cut into ≤ buckets[-1]-row per-shard
        chunks padded (local scratch = local_capacity) to one shared
        bucket size per chunk."""
        gslot = w[:, 0] & np.uint32(0x3FFFFFFF)
        real = np.nonzero(gslot < self.capacity)[0]
        shard = (gslot[real] % np.uint32(self.n_shards)).astype(np.int64)
        # ONE stable (radix) sort by shard replaces n_shards boolean-mask
        # passes + fancy-index copies over the whole batch — the routing
        # was an O(n_shards * rows) host cost at 2^23 scale. Stability
        # preserves per-slot create-before-update order within a shard.
        order = np.argsort(shard, kind="stable")
        sorted_idx = real[order]
        rows_all = w[sorted_idx]
        rows_all[:, 0] = (
            (gslot[sorted_idx] // np.uint32(self.n_shards))
            | (w[sorted_idx, 0] & np.uint32(0xC0000000))
        )
        counts = np.bincount(shard, minlength=self.n_shards)
        bounds = np.concatenate(([0], np.cumsum(counts)))
        per_shard = [
            rows_all[bounds[s] : bounds[s + 1]]
            for s in range(self.n_shards)
        ]
        cap = self.buckets[-1]
        ncols = w.shape[1]  # compact (4) or full (6) wire, preserved
        widest_total = max(r.shape[0] for r in per_shard)
        for off in range(0, max(widest_total, 1), cap):
            chunks = [r[off : off + cap] for r in per_shard]
            widest = max(c.shape[0] for c in chunks)
            B = bucket_size(max(widest, 1), self.buckets)
            out = np.empty((self.n_shards, B, ncols), np.uint32)
            # padding rows: local scratch slot, no flags
            out[:, :, 0] = np.uint32(self.local_capacity)
            out[:, :, 1:] = 0
            for s, c in enumerate(chunks):
                out[s, : c.shape[0]] = c
            yield out

    def step(self) -> bool:
        """Coalesced apply: drain EVERY pending flush batch first, then
        route + dispatch the union in as few shard_map calls as possible.

        Why not apply per flush batch (the single-device pattern): the
        gather-apply merge costs O(local_capacity) per dispatch on every
        shard regardless of batch size, so applying each ≤ buckets[-1]
        GLOBAL-row flush separately pays the full-mesh merge once per
        2²⁰ global rows — at 2²³ capacity that was 8+ full-table merges
        per tick (measured 10.9 s step p50 on the 8-way CPU mesh,
        VERDICT r3 weak item 3). Coalescing fills each dispatch with up
        to buckets[-1] rows PER SHARD, restoring the design invariant
        that a shard's per-tick merge work matches the single-device
        spine at equal local fill.

        Correctness of the concatenation: batches are grouped at
        CONFLICT boundaries — ``batcher.last_flush_was_conflict()`` marks
        a flushed batch that repeats a (slot, direction, create/update)
        key of its predecessor (the native engine's conflict-started
        generations; a third same-direction record in one tick). Within a
        group each key therefore holds at most one create and one update
        row, create first — exactly the uniqueness precondition
        flow_table._inverse_index needs — and groups are applied in
        separate scatters, in order, reproducing the reference's
        sequential per-line semantics. The Python batcher never conflicts
        within a drain; the native engine's size-rollover generations
        (the common case at scale) coalesce freely. Order-preserving
        routing and sequential chunk cuts keep any split create/update
        pair in create-then-update order.

        Native drain: ``tck_flush_wire`` stages packed wire in TWO pinned
        buffers (flush k reuses flush k-2's), so before each next flush
        every held view but the newest is materialized into host memory
        the C++ side can never overwrite. Unlike the single-device spine
        no ``block_until_ready`` is needed here: ``_route_chunks`` copies
        every row host-side (the stable-sort fancy index plus the padded
        per-shard chunks) before any dispatch consumes it, so a staged
        view is never handed to an async device op."""
        groups: list[list[np.ndarray]] = []
        if self.native:
            pending: list[tuple[int, int]] = []  # uncopied staged views
            while len(self.batcher):
                while len(pending) > 1:
                    g, i = pending.pop(0)
                    groups[g][i] = np.array(groups[g][i])
                w = self.batcher.flush_wire()
                if w is None:
                    break
                conflict = self.batcher.last_flush_was_conflict()
                if not groups or (conflict and groups[-1]):
                    groups.append([])
                groups[-1].append(w)
                pending.append((len(groups) - 1, len(groups[-1]) - 1))
        else:
            while (batch := self.batcher.flush()) is not None:
                conflict = self.batcher.last_flush_was_conflict()
                if not groups or (conflict and groups[-1]):
                    groups.append([])
                groups[-1].append(ft.pack_wire(batch))
        if not groups:
            return False
        for packed in groups:
            if len(packed) > 1 and len({p.shape[1] for p in packed}) > 1:
                # rare mixed widths (a >2^31-counter batch among compact
                # ones): widen so the concatenation is well-formed
                packed = [ft.widen_wire(p) for p in packed]
            w = packed[0] if len(packed) == 1 else np.concatenate(packed)
            for chunk in self._route_chunks(w):
                self.wire_bytes += chunk.nbytes
                # chunk passes as host numpy (uncommitted): identical on
                # every process, so jit treats it as replicated —
                # multi-host safe
                if self.incremental:
                    self.tables, self.dirty = self._apply_dirty(
                        self.tables, self.dirty, chunk
                    )
                else:
                    self.tables = self._apply(self.tables, chunk)
        return True

    def tick_read_dispatch(self, now: int,
                           idle_seconds: int | None = None):
        """Flush pending updates and DISPATCH the tick's whole read side
        (one shard_map call); returns the un-synced device outputs.
        The pipelined serve loop's host stage calls this so the device
        stage can absorb the sync (``tick_read_finish``) off the poll
        path; ``tick_render`` composes both for the serial loop.

        ``idle_seconds=None`` compiles the same shape with an inert
        2^30 s horizon — callers that skip eviction must not act on the
        returned stale bits (see tick_render)."""
        if self._tick_outputs is None:
            raise ValueError("engine built without a predict_fn")
        self.step()
        horizon = idle_seconds if idle_seconds is not None else (1 << 30)
        if self.incremental:
            outs = self._dispatch_incremental(now, horizon)
            if outs is not None:
                return outs
        return self._tick_outputs(
            self.tables, self.params, self._tick_floor, now, horizon,
        )

    def _dispatch_incremental(self, now: int, horizon: int):
        """The incremental read dispatch: pick this tick's compaction
        bucket from the per-shard dirty counts (the max — one shard_map
        compiles one static shape for every shard) and run the
        compact-predict-merge-render program; the updated cache/dirty
        pair is committed at dispatch (host thread), so the pipelined
        and serial callers share the path. Returns None to fall back to
        the plain full-shard read (the ABSORBED fault sites: that tick
        re-predicts everything directly and the cache/mask pair is
        rebuilt at the next render — never a stale label as fresh)."""
        from ..utils import faults as _faults

        try:
            _faults.fault_point("serve.dirty_mask")
            _faults.fault_point("serve.label_cache")
        except _faults.FaultInjected:
            self.dirty = jax.device_put(
                np.ones((self.n_shards, self.local_capacity + 1), bool),
                NamedSharding(self.mesh, P(DATA_AXIS)),
            )
            return None
        n = int(np.asarray(self._dirty_counts(self.dirty)).max())
        bucket = next(b for b in self.dirty_buckets if n <= b)
        outs = self._tick_outputs_inc(
            self.tables, self.caches, self.dirty, self.params,
            self._tick_floor, now, horizon, bucket=bucket,
        )
        self.caches, self.dirty = outs[-2], outs[-1]
        return tuple(outs[:-2])

    def tick_read_finish(self, outs) -> list[tuple]:
        """Sync the dispatched read side and merge the per-shard
        candidates into the global top-``table_rows`` render rows —
        the device-stage half of a pipelined sharded render (no
        eviction: that stays on the host stage, which owns the index)."""
        idx, valid, score, lab, fa, ra, _bits = (
            np.asarray(o) for o in outs
        )
        return self._merge_candidates(idx, valid, score, lab, fa, ra)

    def _merge_candidates(self, idx, valid, score, lab, fa, ra):
        """Global render merge: best table_rows of n_shards×table_rows
        candidates (tiny, host-side)."""
        cand = []
        for s in range(self.n_shards):
            for j in range(idx.shape[1]):
                if valid[s, j]:
                    cand.append((
                        float(score[s, j]),
                        int(idx[s, j]) * self.n_shards + s,
                        int(lab[s, j]), bool(fa[s, j]), bool(ra[s, j]),
                    ))
        cand.sort(key=lambda c: (-c[0], c[1]))
        return [(g, c, f, r) for _sc, g, c, f, r in cand[: self.table_rows]]

    def tick_render(self, now: int, idle_seconds: int | None):
        """One fused read-side dispatch for the whole mesh: returns
        ``(rows, evicted)`` where rows are the global top table_rows
        ``(global_slot, label, fwd_active, rev_active)`` merged across
        shards by activity score, and evicted is the count of idle flows
        released everywhere.

        ``idle_seconds=None`` disables eviction: the device call still
        runs (same compiled shape, with a 2^30 s horizon — note the
        device may still mark long-idle/empty slots stale when ``now``
        is epoch seconds), but the host discards the stale bits: the
        unpack / release / clear loop is skipped entirely and evicted
        is 0. Do not act on ``bits`` when ``evict`` is False."""
        if self._tick_outputs is None:
            raise ValueError("engine built without a predict_fn")
        evict = idle_seconds is not None
        outs = self.tick_read_dispatch(
            now, idle_seconds if evict else None
        )
        idx, valid, score, lab, fa, ra, bits = (
            np.asarray(o) for o in outs
        )
        rows = self._merge_candidates(idx, valid, score, lab, fa, ra)

        # eviction: unpack each shard's bits, release + clear
        evicted = 0
        if not evict:
            return rows, evicted
        local_cap = self.local_capacity
        clear_batches = []
        for s in range(self.n_shards):
            stale = np.unpackbits(bits[s], count=local_cap + 1)[:-1]
            slots = np.nonzero(stale)[0]
            evicted += slots.size
            clear_batches.append(slots)
            (self.batcher if self.native else self.index).release_slots(
                slots * self.n_shards + s
            )
        self._clear_sharded(clear_batches)
        return rows, evicted

    def _clear_sharded(self, clear_batches) -> None:
        """Clear per-shard LOCAL slot batches in largest-bucket chunks:
        an idle storm — or a dead source's whole namespace — can mark
        more slots than the biggest padded shape admits (same chunking
        as FlowStateEngine.evict_idle). When incremental, the fused
        clear also invalidates the per-shard label cache rows."""
        local_cap = self.local_capacity
        E_max = max((b.size for b in clear_batches), default=0)
        step = self.buckets[-1]
        for off in range(0, E_max, step):
            chunks = [b[off : off + step] for b in clear_batches]
            widest = max(c.size for c in chunks)
            if not widest:
                break
            E = bucket_size(widest, self.buckets)
            padded = np.full((self.n_shards, E), local_cap, np.int32)
            for s, c in enumerate(chunks):
                padded[s, : c.size] = c
            if self.incremental:
                self.tables, self.dirty = self._clear_dirty(
                    self.tables, self.dirty, padded
                )
            else:
                self.tables = self._clear(self.tables, padded)

    def evict_source(self, source: int) -> int:
        """Release every flow owned by ``source`` across ALL shards —
        the per-source blast radius (quarantine evict, flap escalation)
        preserved over shard boundaries; the composed-spine twin of
        ``FlowStateEngine.evict_source``. Flushes pending updates first
        so no in-flight record re-creates a slot being evicted, drops
        the source's reassembly tail, releases the GLOBAL slots in one
        bulk index call, then clears the state rows per shard through
        the bucket-padded chunk shapes tick_render already compiles."""
        self.step()
        self._tails.pop(source, None)
        if self.native:
            self.batcher.reset_tail(source)
            slots = self.batcher.slots_for_source(source).astype(np.int64)
        else:
            slots = np.asarray(
                sorted(self.index.slots_for_source(source)), np.int64
            )
        if slots.size:
            (self.batcher if self.native else self.index).release_slots(
                slots
            )
            shard = (slots % self.n_shards).astype(np.int64)
            local = (slots // self.n_shards).astype(np.int64)
            self._clear_sharded(
                [local[shard == s] for s in range(self.n_shards)]
            )
        return int(slots.size)

    def install_predict(self, predict_fn, params):
        """Hot-swap the serving model (drift promotion/rollback on the
        composed spine): rebuild the read-side programs around the new
        fn and reset the incremental cache/dirty pair all-dirty, so no
        label cached under the OLD model ever renders as fresh under
        the new one — the sharded twin of the label-epoch invalidation
        the single-device gate drives. Returns the previous
        ``(predict_fn, params)`` pair so the caller can retire it."""
        prev = (self._predict_fn, self.params)
        self._predict_fn = predict_fn
        self.params = params
        n_rows = min(self.table_rows, self.local_capacity)
        self._tick_outputs = make_tick_outputs(
            self.mesh, predict_fn, n_rows
        )
        if self.incremental:
            sharding = NamedSharding(self.mesh, P(DATA_AXIS))
            self._tick_outputs_inc = make_tick_outputs_inc(
                self.mesh, predict_fn, n_rows
            )
            label_dtype = jax.eval_shape(
                predict_fn, params,
                jax.ShapeDtypeStruct((1, 12), jnp.float32),
            ).dtype
            self.dirty = jax.device_put(
                np.ones((self.n_shards, self.local_capacity + 1), bool),
                sharding,
            )
            self.caches = jax.device_put(
                np.zeros(
                    (self.n_shards, self.local_capacity), label_dtype
                ),
                sharding,
            )
        return prev

    def feature_sample(self, gslots) -> np.ndarray:
        """(len(gslots), 12) float32 feature rows for the given GLOBAL
        slots, in input order — the drift monitor's per-render
        observation tap. One fixed-shape shard_map gather (k = the
        render-row clamp, so exactly one compile); slots route to their
        owning shard, padding entries read each shard's scratch row and
        are dropped on reassembly. Rows evicted between render and
        sample read as zeros, which the monitor's any-feature mask
        already discards."""
        g = np.asarray(gslots, np.int64)
        k = min(self.table_rows, self.local_capacity)
        if g.size == 0:
            return np.zeros((0, 12), np.float32)
        if self._feature_sample is None:
            self._feature_sample = make_feature_sample(self.mesh)
        shard = (g % self.n_shards).astype(np.int64)
        local = (g // self.n_shards).astype(np.int64)
        padded = np.full((self.n_shards, k), self.local_capacity, np.int32)
        pos = np.full((self.n_shards, k), -1, np.int64)
        counts = np.zeros(self.n_shards, np.int64)
        for i in range(g.size):
            s = shard[i]
            if counts[s] >= k:
                raise ValueError(
                    f"feature_sample: >{k} slots routed to shard {s}"
                )
            padded[s, counts[s]] = local[i]
            pos[s, counts[s]] = i
            counts[s] += 1
        X = np.asarray(self._feature_sample(self.tables, padded))
        out = np.zeros((g.size, 12), np.float32)
        for s in range(self.n_shards):
            m = int(counts[s])
            if m:
                out[pos[s, :m]] = X[s, :m]
        return out

    def warmup_incremental(self) -> list[str]:
        """AOT-compile the incremental read program for EVERY dirty
        bucket (serving/warmup.py's sharded branch): one
        ``tick_read_dispatch`` only exercises the bucket the current
        dirty counts select, so the other shapes would compile at their
        first serving hit. Scratch state throughout — on jax lines
        where shard_map donation is live the priming calls consume
        their operands, and the real cache/dirty must never be warmup
        fodder."""
        if not self.incremental:
            return []
        warmed = []
        sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        scratch_t = make_sharded_table(self.mesh, self.capacity)
        scratch_c = jax.device_put(
            np.zeros(
                (self.n_shards, self.local_capacity),
                self.caches.dtype,
            ),
            sharding,
        )
        scratch_d = jax.device_put(
            np.ones((self.n_shards, self.local_capacity + 1), bool),
            sharding,
        )
        jax.block_until_ready(self._dirty_counts(scratch_d))
        for b in self.dirty_buckets:
            self._tick_outputs_inc.lower(
                scratch_t, scratch_c, scratch_d, self.params,
                0, 0, 1 << 30, bucket=b,
            ).compile()
            outs = self._tick_outputs_inc(
                scratch_t, scratch_c, scratch_d, self.params,
                0, 0, 1 << 30, bucket=b,
            )
            # donated on native-shard_map jax lines: chain the returned
            # cache so one allocation covers every bucket; the dirty
            # mask comes back cleared, so re-seed it all-dirty (the
            # next bucket's priming must compact a real population)
            scratch_c = outs[-2]
            scratch_d = jax.device_put(
                np.ones((self.n_shards, self.local_capacity + 1), bool),
                sharding,
            )
            warmed.append(f"sharded.dirty[{b}]")
        jax.block_until_ready(scratch_c)
        return warmed

    def warmup_scatter(self) -> list[str]:
        """AOT-compile the write-side scatter for EVERY wire bucket a
        tick can plausibly fill (≤ two records per tracked flow per
        shard). The apply program's shape is (n_shards, B, 4) and B
        follows the widest per-shard sub-batch of each routed chunk,
        so a serve whose batch sizes vary tick to tick pays a compile
        at the first hit of every new bucket — inside a live tick's
        latency budget — unless they are all primed here. All-padding
        chunks (slot == local_capacity) are a clean no-op; scratch
        state absorbs the donation, never the live table. The rare
        full-width (B, 6) wire still compiles lazily, matching the
        single-device warm."""
        warmed = []
        limit = bucket_size(
            min(2 * self.local_capacity, self.buckets[-1]), self.buckets
        )
        scratch_t = make_sharded_table(self.mesh, self.capacity)
        scratch_d = None
        if self.incremental:
            scratch_d = jax.device_put(
                np.ones((self.n_shards, self.local_capacity + 1), bool),
                NamedSharding(self.mesh, P(DATA_AXIS)),
            )
        for b in self.buckets:
            if b > limit:
                break
            chunk = np.empty((self.n_shards, b, 4), np.uint32)
            chunk[:, :, 0] = np.uint32(self.local_capacity)
            chunk[:, :, 1:] = 0
            if self.incremental:
                scratch_t, scratch_d = self._apply_dirty(
                    scratch_t, scratch_d, chunk
                )
                warmed.append(f"sharded.apply_dirty[{b}]")
            else:
                scratch_t = self._apply(scratch_t, chunk)
                warmed.append(f"sharded.apply[{b}]")
        jax.block_until_ready(scratch_t)
        return warmed

    def slot_metadata(self, slots):
        return self._slot_meta_for(slots)
