"""Tree-sharded random forest: the ensemble split across chips, class
distributions psum-merged over ICI.

The reference evaluates 100 Cython trees sequentially on one CPU
(SURVEY.md §2.3). Here each chip holds T/D trees (the dense padded node
arrays shard cleanly on their leading axis), evaluates its sub-ensemble with
the same lockstep traversal as the single-chip path (ops/tree_eval.py), and
one ``psum`` of the (N, C) per-chip probability sums produces the exact
ensemble average — bitwise-equal reduction order aside, the same math as
sklearn's ``predict_proba`` mean.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models import forest
from ..ops import tree_eval
from .mesh import STATE_AXIS, shard_map


def pad_trees(d: dict, n_shards: int) -> dict:
    """Pad the ensemble to a multiple of the state-axis size with inert
    single-leaf trees whose value rows are all-zero (they contribute zero
    probability mass; the divisor uses the true tree count)."""
    import numpy as np

    T = d["left"].shape[0]
    pad = (-T) % n_shards
    if pad == 0:
        return d
    out = dict(d)
    for name in ("left", "right"):
        out[name] = np.concatenate(
            [d[name], np.full((pad,) + d[name].shape[1:], -1, d[name].dtype)]
        )
    for name in ("feature", "threshold", "values"):
        out[name] = np.concatenate(
            [d[name], np.zeros((pad,) + d[name].shape[1:], d[name].dtype)]
        )
    out["n_real_trees"] = T
    return out


def sharded_predict(mesh, params: forest.Params, n_real_trees: int | None = None):
    """Build a jit-compiled tree-sharded predict: ``fn(X) -> (N,) int32``."""
    T = params.left.shape[0]
    n_real = n_real_trees if n_real_trees is not None else T
    max_depth = params.max_depth

    def local_eval(left, right, feature, threshold, values, X):
        leaf = tree_eval.traverse_gather(
            left, right, feature, threshold, X, max_depth
        )
        tree_ar = jnp.arange(left.shape[0])[None, :]
        leaf_vals = values[tree_ar, leaf]  # (N, T_local, C)
        norm = jnp.sum(leaf_vals, axis=-1, keepdims=True)
        # Padding trees have all-zero values → 0/max(0,eps) = 0 contribution.
        probs = leaf_vals / jnp.maximum(norm, 1e-30)
        local_sum = jnp.sum(probs, axis=1)  # (N, C)
        total = lax.psum(local_sum, STATE_AXIS)
        return jnp.argmax(total / n_real, axis=-1).astype(jnp.int32)

    shmapped = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(
            P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS),
            P(STATE_AXIS), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )

    @jax.jit
    def fn(X):
        return shmapped(
            params.left, params.right, params.feature, params.threshold,
            params.values, X,
        )

    return fn


def gemm_sharded_predict(
    mesh, d: dict, n_features: int | None = None, row_chunk: int = 65536
):
    """Tree-sharded predict with the MXU GEMM local stage: each chip
    evaluates its sub-ensemble through the same three-GEMM formulation
    the serving path promotes (ops/tree_gemm — the gather traversal of
    ``sharded_predict`` is the TPU-slow path it replaced), then one
    ``psum`` of the per-chip (N, C) distribution sums yields the exact
    ensemble mean.

    Layout: ONE single-group operand build over the whole (padded)
    ensemble — uniform (D_max, L_max) padding, leaf values pre-divided
    by the REAL tree count — then the tree-leading arrays shard on the
    state axis, so every chip holds identically-shaped blocks of T/D
    trees. ``d`` is the node-array dict (``pad_trees`` output; inert
    padded trees carry zero values and contribute nothing).

    Returns ``fn(X) -> (N,) int32``.
    """
    from ..ops import tree_gemm

    D_mesh = mesh.shape[STATE_AXIS]
    T = d["left"].shape[0]
    if T % D_mesh:
        raise ValueError(
            f"{T} trees not divisible by {D_mesh} shards — pad_trees first"
        )
    n_real = int(d.get("n_real_trees", T))
    ops = tree_gemm.build_gemm_operands(
        d, n_features=n_features, n_trees_total=n_real
    )
    F = ops["feat_onehot"].shape[0]
    Dm, n_classes = ops["n_internal"], ops["n_classes"]

    def local_gemm(feat3_l, thr2_l, path_l, depth_l, vals_l, X):
        T_l = path_l.shape[0]
        g = tree_gemm.ForestGemm(
            feat_onehot=feat3_l.reshape(F, T_l * Dm),
            thresholds=thr2_l.reshape(T_l * Dm),
            path=path_l,
            leaf_depth=depth_l,
            leaf_values=vals_l,
            n_classes=n_classes,
            row_chunk=row_chunk,
        )
        local_sum = tree_gemm.forest_proba_gemm(g, X)  # (N, C)
        total = lax.psum(local_sum, STATE_AXIS)
        return jnp.argmax(total, axis=-1).astype(jnp.int32)

    shmapped = shard_map(
        local_gemm,
        mesh=mesh,
        in_specs=(
            P(None, STATE_AXIS, None),  # feat_onehot as (F, T, D)
            P(STATE_AXIS, None),  # thresholds as (T, D)
            P(STATE_AXIS),  # path (T, D, L)
            P(STATE_AXIS),  # leaf_depth (T, L)
            P(STATE_AXIS),  # leaf_values (T, L, C)
            P(),  # X replicated
        ),
        out_specs=P(),
        check_vma=False,
    )

    # canonical dtypes come from tree_gemm's one policy; this layer only
    # reshapes to tree-leading shard form
    da = tree_gemm.dtyped_operands(ops)
    feat3 = da["feat_onehot"].reshape(F, T, Dm)
    thr2 = da["thresholds"].reshape(T, Dm)
    path, depth, vals = da["path"], da["leaf_depth"], da["leaf_values"]

    @jax.jit
    def fn(X):
        return shmapped(feat3, thr2, path, depth, vals, X)

    return fn
