"""Tree-sharded random forest: the ensemble split across chips, class
distributions psum-merged over ICI.

The reference evaluates 100 Cython trees sequentially on one CPU
(SURVEY.md §2.3). Here each chip holds T/D trees (the dense padded node
arrays shard cleanly on their leading axis), evaluates its sub-ensemble with
the same lockstep traversal as the single-chip path (ops/tree_eval.py), and
one ``psum`` of the (N, C) per-chip probability sums produces the exact
ensemble average — bitwise-equal reduction order aside, the same math as
sklearn's ``predict_proba`` mean.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models import forest
from ..ops import tree_eval
from .mesh import STATE_AXIS


def pad_trees(d: dict, n_shards: int) -> dict:
    """Pad the ensemble to a multiple of the state-axis size with inert
    single-leaf trees whose value rows are all-zero (they contribute zero
    probability mass; the divisor uses the true tree count)."""
    import numpy as np

    T = d["left"].shape[0]
    pad = (-T) % n_shards
    if pad == 0:
        return d
    out = dict(d)
    for name in ("left", "right"):
        out[name] = np.concatenate(
            [d[name], np.full((pad,) + d[name].shape[1:], -1, d[name].dtype)]
        )
    for name in ("feature", "threshold", "values"):
        out[name] = np.concatenate(
            [d[name], np.zeros((pad,) + d[name].shape[1:], d[name].dtype)]
        )
    out["n_real_trees"] = T
    return out


def sharded_predict(mesh, params: forest.Params, n_real_trees: int | None = None):
    """Build a jit-compiled tree-sharded predict: ``fn(X) -> (N,) int32``."""
    T = params.left.shape[0]
    n_real = n_real_trees if n_real_trees is not None else T
    max_depth = params.max_depth

    def local_eval(left, right, feature, threshold, values, X):
        leaf = tree_eval.traverse_gather(
            left, right, feature, threshold, X, max_depth
        )
        tree_ar = jnp.arange(left.shape[0])[None, :]
        leaf_vals = values[tree_ar, leaf]  # (N, T_local, C)
        norm = jnp.sum(leaf_vals, axis=-1, keepdims=True)
        # Padding trees have all-zero values → 0/max(0,eps) = 0 contribution.
        probs = leaf_vals / jnp.maximum(norm, 1e-30)
        local_sum = jnp.sum(probs, axis=1)  # (N, C)
        total = lax.psum(local_sum, STATE_AXIS)
        return jnp.argmax(total / n_real, axis=-1).astype(jnp.int32)

    shmapped = jax.shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(
            P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS), P(STATE_AXIS),
            P(STATE_AXIS), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )

    @jax.jit
    def fn(X):
        return shmapped(
            params.left, params.right, params.feature, params.threshold,
            params.values, X,
        )

    return fn
