"""Native (C++) host-runtime components. See engine.py."""

from .engine import NativeBatcher, available  # noqa: F401
