"""Shared lazy g++ build + dlopen for the native host-spine libraries.

One home for the build machinery engine.py and forest.py both need (no
pybind11 in this image; plain C ABI + ctypes): compile on first use to a
temp path and atomically rename into place (concurrent processes never
dlopen a half-written .so), rebuild when the source is newer, cache the
CDLL and any build failure per process.
"""

from __future__ import annotations

import ctypes as ct
import os
import subprocess
import threading


class LazyLib:
    def __init__(self, src: str, lib: str, name: str,
                 flags: tuple[str, ...] = ("-O3",)):
        self._src = src
        self._lib_path = lib
        self._name = name
        self._flags = flags
        self._lock = threading.Lock()
        self._lib: ct.CDLL | None = None
        self._error: str | None = None

    def _build(self) -> None:
        tmp = f"{self._lib_path}.{os.getpid()}.tmp"
        try:
            subprocess.run(
                ["g++", *self._flags, "-std=c++17", "-fPIC", "-shared",
                 "-o", tmp, self._src],
                check=True,
                capture_output=True,
                text=True,
            )
            os.replace(tmp, self._lib_path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load(self) -> ct.CDLL:
        """The CDLL, building/rebuilding first if needed. Raises
        RuntimeError (cached) when no build is possible."""
        with self._lock:
            if self._lib is not None:
                return self._lib
            if self._error is not None:
                raise RuntimeError(self._error)
            try:
                if (not os.path.exists(self._lib_path)
                        or os.path.getmtime(self._lib_path)
                        < os.path.getmtime(self._src)):
                    self._build()  # graftlint: disable=blocking-under-lock -- the first caller pays the one-time g++ build under the lock BY DESIGN (build-once guarantee: concurrent loaders must wait, not race a second compile); every later acquisition is a cached-handle hit
                self._lib = ct.CDLL(self._lib_path)
            except (OSError, subprocess.CalledProcessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                self._error = f"{self._name} unavailable: {detail}"
                raise RuntimeError(self._error) from e
            return self._lib

    def available(self) -> bool:
        try:
            self.load()
            return True
        except RuntimeError:
            return False
