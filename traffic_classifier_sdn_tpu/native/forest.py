"""ctypes bindings for the native (C++) random-forest evaluator.

The host-spine predict path: the reference's production compute is
sklearn's Cython ``Tree.predict`` on CPU, one flow per call
(``/root/reference/traffic_classifier.py:103-106``); this evaluator is the
framework's native equivalent for accelerator-less deployments, and the
honest CPU entrant ``bench.py`` races against that exact sklearn path on
outage rounds. The TPU kernels (ops/tree_gemm.py, ops/pallas_forest.py)
remain the production path on chip.

Exactness: the caller hands over the checkpoint's raw (T, M) node arrays
plus float64 normalized leaf distributions computed in numpy — the same
addends, added in the same tree order, as the level-synchronous oracle in
``bench._numpy_forest_labels`` — so argmax parity is bitwise, not
approximate (asserted in tests/test_native_forest.py).

Built lazily with g++ on first use, same pattern as engine.py (no
pybind11 in this image; plain C ABI + ctypes). ``available()`` reports
whether a build is possible so callers can gate to other paths.
"""

from __future__ import annotations

import ctypes as ct
import os
import threading

import numpy as np

from ..io.sklearn_import import f32_safe_thresholds
from .loader import LazyLib

_DIR = os.path.dirname(os.path.abspath(__file__))
_lazy = LazyLib(
    os.path.join(_DIR, "forest_eval.cpp"),
    os.path.join(_DIR, "_forest_eval.so"),
    "native forest evaluator",
)
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = _lazy.load()  # graftlint: disable=blocking-under-lock -- one-time g++ build serialized under the module lock by design (build-once; shared machinery with engine.py); later calls are cache hits
        lib.tcf_create.restype = ct.c_void_p
        lib.tcf_create.argtypes = [
            ct.c_uint32, ct.c_uint32, ct.c_uint32,
            ct.c_void_p, ct.c_void_p, ct.c_void_p, ct.c_void_p, ct.c_void_p,
        ]
        lib.tcf_destroy.restype = None
        lib.tcf_destroy.argtypes = [ct.c_void_p]
        lib.tcf_predict.restype = None
        lib.tcf_predict.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        lib.tcf_proba.restype = None
        lib.tcf_proba.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


class NativeForest:
    """A compiled forest handle. Arrays are copied into the library at
    construction; the handle is freed on GC or explicit ``close()``."""

    def __init__(self, d: dict):
        lib = _load()
        feature = np.ascontiguousarray(d["feature"], np.int32)
        # f32-safe cast, NOT a plain round-to-nearest: sklearn stores f64
        # midpoints of adjacent f32 feature values and compares
        # f32(x) <= f64(thr); a midpoint that rounds UP under f32 flips
        # the decision for a sample sitting exactly at the upper value
        # (ADVICE r5 high). Same routing as models/forest.from_numpy and
        # ops/tree_gemm.compile_forest. Leaf slots are overwritten with
        # the NaN sentinel in tcf_create, so applying it everywhere is
        # safe.
        threshold = np.ascontiguousarray(
            f32_safe_thresholds(np.asarray(d["threshold"], np.float64)),
            np.float32,
        )
        left = np.ascontiguousarray(d["left"], np.int32)
        right = np.ascontiguousarray(d["right"], np.int32)
        values = np.asarray(d["values"], np.float64)  # (T, M, C)
        T, M = left.shape
        if M > 32767:
            raise ValueError(f"nodes per tree {M} exceeds int16 layout")
        # the oracle's addends, precomputed: v / v.sum() in float64;
        # padded slots (zero rows) are unreachable — zero their dists so
        # no NaN can exist in the library even in principle
        sums = values.sum(axis=2, keepdims=True)
        with np.errstate(invalid="ignore", divide="ignore"):
            leaf = np.where(sums > 0, values / sums, 0.0)
        leaf = np.ascontiguousarray(leaf)
        self._lib = lib
        self.n_classes = int(values.shape[2])
        # narrower X would make the walk read across row boundaries
        # silently — record the minimum width and refuse at call time
        interior = left != -1
        self.min_features = (
            int(feature[interior].max()) + 1 if interior.any() else 1
        )
        self.n_features = int(d.get("n_features", self.min_features))
        self._h = lib.tcf_create(
            T, M, self.n_classes,
            feature.ctypes.data_as(ct.c_void_p),
            threshold.ctypes.data_as(ct.c_void_p),
            left.ctypes.data_as(ct.c_void_p),
            right.ctypes.data_as(ct.c_void_p),
            leaf.ctypes.data_as(ct.c_void_p),
        )
        if not self._h:
            raise RuntimeError("tcf_create rejected the forest layout")

    def _check_width(self, X: np.ndarray) -> None:
        if not self._h:
            # a NULL handle would segfault in C++, not raise
            raise RuntimeError("NativeForest handle is closed")
        if X.ndim != 2 or X.shape[1] < self.min_features:
            raise ValueError(
                f"X shape {X.shape} too narrow: forest reads feature "
                f"indices up to {self.min_features - 1}"
            )

    def predict(self, X: np.ndarray) -> np.ndarray:
        """(N, F) float32 features -> (N,) int32 class indices."""
        X = np.ascontiguousarray(X, np.float32)
        self._check_width(X)
        out = np.empty(X.shape[0], np.int32)
        self._lib.tcf_predict(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        """(N, F) float32 -> (N, C) float64 mean class distributions."""
        X = np.ascontiguousarray(X, np.float32)
        self._check_width(X)
        out = np.empty((X.shape[0], self.n_classes), np.float64)
        self._lib.tcf_proba(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def close(self) -> None:
        if self._h:
            self._lib.tcf_destroy(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
