// Native (host-spine) random-forest evaluator.
//
// The reference's ENTIRE production compute path is a per-flow CPU predict
// through sklearn's Cython Tree.predict (traffic_classifier.py:103-106);
// this is the TPU framework's host-side equivalent for deployments where
// no accelerator is attached (and the honest CPU entrant the fallback
// bench races against that exact sklearn path). One core, cache-tight,
// and structured around the walk being LATENCY-bound, not FLOP-bound:
//
//   - nodes repacked per tree into 8-byte DFS-preorder records (float
//     threshold, uint16 feature, uint16 right; the left child is
//     implicitly node+1) — the whole 100-tree forest is ~80 KB, near-L1-
//     resident, and the common left-descent walks forward through memory;
//   - leaves become SELF-LOOPS (thr = NaN, right = self) at load time
//     and stepping is an arithmetic select (no cmov-vs-branch codegen
//     gamble): the only branch in the walk is the group exit, taken once
//     per group when all WIDE rows have stabilized at their leaves —
//     the group's true max depth (~8 empirically), not the worst case;
//   - rows walk in blocks of 256, WIDE rows interleaved in registers
//     inside each tree: WIDE independent load chains in flight per
//     iteration, hiding the ~L1-latency per step (the Cython path walks
//     one row at a time through every tree, serializing on each chain).
//     Measured on the 1-core bench host: 774k rows/s vs sklearn's 367k
//     (same forest, same host) — interleave width swept 4/8/12/16/24,
//     WIDE=8 won;
//   - leaf class distributions are the caller's float64 values
//     (values/sum computed in numpy), accumulated in tree order per row —
//     bitwise the same sums as the numpy level-synchronous oracle in
//     bench._numpy_forest_labels, so argmax parity is exact, not
//     approximate. Argmax takes the FIRST maximum (strict >), matching
//     np.argmax tie semantics.
//
// Plain C ABI for ctypes (no pybind11 in this image) — same pattern as
// flow_engine.cpp.

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <vector>

namespace {

// 8-byte node in DFS-preorder layout: the left child is implicitly
// node+1 (preorder, left first), so only the right index is stored —
// half the bytes per node, and the common left-descent walks FORWARD
// through memory (cacheline + prefetcher friendly). Leaves carry
// thr = NaN (the `x <= thr` test is false for every x, including -inf
// and NaN) and right = self, forming the self-loop the group exit
// detects.
struct Node {
    float thr;
    uint16_t feat;
    uint16_t right;
};
static_assert(sizeof(Node) == 8, "walk layout relies on 8-byte nodes");

struct Forest {
    uint32_t n_trees;
    uint32_t stride;    // padded nodes per tree
    uint32_t n_classes;
    std::vector<Node> nodes;       // (T * stride), DFS-preorder per tree
    std::vector<double> leaf;      // (T * stride * C) normalized dists
};

constexpr uint32_t kBlock = 256;

// interleave width: independent walk chains in flight per group (tuned
// empirically on the 1-core bench host; see tools note in forest.py)
#ifndef WIDE
#define WIDE 8
#endif


}  // namespace

extern "C" {

void *tcf_create(uint32_t n_trees, uint32_t stride, uint32_t n_classes,
                 const int32_t *feature, const float *threshold,
                 const int32_t *left, const int32_t *right,
                 const double *leaf_dist) {
    if (n_trees == 0 || stride == 0 || n_classes == 0 || stride > 65535)
        return nullptr;
    Forest *f = new Forest();
    f->n_trees = n_trees;
    f->stride = stride;
    f->n_classes = n_classes;
    f->nodes.resize(size_t(n_trees) * stride);
    f->leaf.assign(leaf_dist,
                   leaf_dist + size_t(n_trees) * stride * n_classes);
    std::vector<uint16_t> remap(stride);
    std::vector<int32_t> dfs;
    for (uint32_t t = 0; t < n_trees; ++t) {
        const size_t off = size_t(t) * stride;
        // DFS preorder (left first): the left child lands at parent+1 in
        // the new numbering; unreachable padded slots are never visited
        // (their node/leaf slots simply stay unused)
        dfs.assign(1, 0);
        uint32_t next_id = 0;
        while (!dfs.empty()) {
            const int32_t m = dfs.back();
            dfs.pop_back();
            remap[m] = uint16_t(next_id++);
            if (left[off + m] != -1) {
                dfs.push_back(right[off + m]);  // right visited after the
                dfs.push_back(left[off + m]);   // whole left subtree
            }
        }
        // second pass: write nodes/leaf dists at their new ids
        dfs.assign(1, 0);
        while (!dfs.empty()) {
            const int32_t m = dfs.back();
            dfs.pop_back();
            const uint16_t nid = remap[m];
            Node &n = f->nodes[off + nid];
            if (left[off + m] == -1) {
                // leaf sentinel: x <= NaN is false for EVERY x — finite,
                // -inf, or NaN — so the select always takes 'right',
                // the self-loop (a -inf threshold would break for
                // x == -inf and march the walk off the node array)
                n.thr = std::numeric_limits<float>::quiet_NaN();
                n.feat = 0;
                n.right = nid;      // self-loop
            } else {
                n.thr = threshold[off + m];
                n.feat = uint16_t(feature[off + m]);
                n.right = remap[right[off + m]];
                dfs.push_back(right[off + m]);
                dfs.push_back(left[off + m]);
            }
            std::memcpy(f->leaf.data() + (off + nid) * n_classes,
                        leaf_dist + (off + m) * n_classes,
                        n_classes * sizeof(double));
        }
    }
    return f;
}

void tcf_destroy(void *h) { delete static_cast<Forest *>(h); }

// X: (N, F) float32 row-major; out: (N,) int32 class indices.
void tcf_predict(void *h, const float *X, uint64_t N, uint32_t F,
                 int32_t *out) {
    const Forest *f = static_cast<const Forest *>(h);
    const uint32_t C = f->n_classes;
    const uint32_t T = f->n_trees;
    const uint32_t S = f->stride;
    std::vector<double> acc(size_t(kBlock) * C);
    std::vector<uint16_t> leaf_idx(kBlock);
    for (uint64_t r0 = 0; r0 < N; r0 += kBlock) {
        const uint32_t B = uint32_t(N - r0 < kBlock ? N - r0 : kBlock);
        std::memset(acc.data(), 0, size_t(B) * C * sizeof(double));
        const float *Xb = X + r0 * F;
        for (uint32_t t = 0; t < T; ++t) {
            const Node *tree = f->nodes.data() + size_t(t) * S;
            uint32_t r = 0;
            for (; r + WIDE <= B; r += WIDE) {
                // branch-free stepping (arithmetic select — no cmov-vs-
                // branch codegen gamble), eight independent chains in
                // flight; the ONLY branch is the group exit, not-taken
                // until all eight rows stabilize at their leaf self-loops
                // (the group's true max depth — empirically ~8 of the
                // worst-case 14 on the reference forest). The fixed-size
                // arrays fully unroll into registers at -O3.
                const float *xp[WIDE];
                uint32_t n[WIDE];
                for (uint32_t i = 0; i < WIDE; ++i) {
                    xp[i] = Xb + size_t(r + i) * F;
                    n[i] = 0;
                }
                for (;;) {
                    uint32_t same = 1;
#pragma GCC unroll 16
                    for (uint32_t i = 0; i < WIDE; ++i) {
                        const Node &A = tree[n[i]];
                        const uint32_t m =
                            -uint32_t(xp[i][A.feat] <= A.thr);
                        const uint32_t q =
                            ((n[i] + 1) & m) | (A.right & ~m);
                        same &= uint32_t(q == n[i]);
                        n[i] = q;
                    }
                    if (same) break;
                }
                for (uint32_t i = 0; i < WIDE; ++i)
                    leaf_idx[r + i] = uint16_t(n[i]);
            }
            for (; r < B; ++r) {
                const float *x = Xb + size_t(r) * F;
                uint32_t n = 0;
                for (;;) {
                    const Node &nd_ = tree[n];
                    const uint32_t m = -uint32_t(x[nd_.feat] <= nd_.thr);
                    const uint32_t q = ((n + 1) & m) | (nd_.right & ~m);
                    if (q == n) break;
                    n = q;
                }
                leaf_idx[r] = uint16_t(n);
            }
            // accumulate this tree's leaf distributions (tree order ==
            // the numpy oracle's addition order, float64: bitwise-equal)
            const double *ld = f->leaf.data() + size_t(t) * S * C;
            for (uint32_t rr = 0; rr < B; ++rr) {
                const double *dd = ld + size_t(leaf_idx[rr]) * C;
                double *a = acc.data() + size_t(rr) * C;
                for (uint32_t c = 0; c < C; ++c) a[c] += dd[c];
            }
        }
        for (uint32_t r = 0; r < B; ++r) {
            const double *a = acc.data() + size_t(r) * C;
            uint32_t best = 0;
            double bv = a[0];
            for (uint32_t c = 1; c < C; ++c)
                if (a[c] > bv) { bv = a[c]; best = c; }  // first max wins
            out[r0 + r] = int32_t(best);
        }
    }
}

// Mean class distribution per row (the predict_proba analogue), mostly
// for tests: probs (N, C) float64.
void tcf_proba(void *h, const float *X, uint64_t N, uint32_t F,
               double *probs) {
    const Forest *f = static_cast<const Forest *>(h);
    const uint32_t C = f->n_classes;
    const uint32_t T = f->n_trees;
    const uint32_t S = f->stride;
    std::memset(probs, 0, size_t(N) * C * sizeof(double));
    for (uint64_t r = 0; r < N; ++r) {
        const float *x = X + r * F;
        double *a = probs + r * C;
        for (uint32_t t = 0; t < T; ++t) {
            const Node *tree = f->nodes.data() + size_t(t) * S;
            uint32_t n = 0;
            for (;;) {
                const Node &nd = tree[n];
                const uint32_t m = -uint32_t(x[nd.feat] <= nd.thr);
                const uint32_t q = ((n + 1) & m) | (nd.right & ~m);
                if (q == n) break;
                n = q;
            }
            const double *dd = f->leaf.data() + (size_t(t) * S + n) * C;
            for (uint32_t c = 0; c < C; ++c) a[c] += dd[c];
        }
        for (uint32_t c = 0; c < C; ++c) a[c] /= T;
    }
}

}  // extern "C"
