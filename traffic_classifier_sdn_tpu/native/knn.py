"""ctypes bindings for the native (C++) KNN evaluator.

The accelerator-less host path for the KNeighbors checkpoint (the
reference walks one KDTree per query on one CPU,
``/root/reference/traffic_classifier.py:234-236``): exact float64
squared distances with the lax.top_k tie order. The default
``predict``/``votes`` run the PRUNED engine (cluster-chunked
triangle-inequality screening + f32 SIMD screen + partial-distance
early abandon, vote-for-vote identical to the full scan — see
native/knn_eval.cpp);
``predict_unpruned``/``votes_unpruned`` keep the original blocked full
scan callable as the same-run A/B baseline
(docs/artifacts/knn_prune_cpu.json) and parity oracle, and
``build_ivf``/``predict_ivf``/``votes_ivf`` expose the approximate
cluster-probed tier (coarse quantizer fit in Python by ops/knn_ivf.py;
nprobe >= n_lists degenerates to the exact search bit-for-bit). The
XLA/Pallas kernels in models/knn.py and ops/pallas_knn.py remain the
device paths; ``bench.py`` races this entrant on the CPU fallback under
the same same-run parity gate as every other raced kernel. Serving
divergence: this path's exact-f64 ranking can disagree with the default
f32 dot-expansion ranking on near-ties — ``TCSDN_KNN_TOPK=native`` is a
documented opt-in and models.resolve_knn_topk logs a one-line warning
when it is selected.

Built lazily with g++ ``-march=native`` on first use (the distance
loops need the host's widest SIMD; the .so never leaves the machine it
was built on). ``available()`` reports whether a build is possible.
"""

from __future__ import annotations

import ctypes as ct
import os
import threading

import numpy as np

from .loader import LazyLib

_DIR = os.path.dirname(os.path.abspath(__file__))
_lazy = LazyLib(
    os.path.join(_DIR, "knn_eval.cpp"),
    os.path.join(_DIR, "_knn_eval.so"),
    "native knn evaluator",
    flags=("-O3", "-march=native"),
)
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = _lazy.load()  # graftlint: disable=blocking-under-lock -- one-time g++ build serialized under the module lock by design (build-once); later calls are cache hits
        lib.tck_create.restype = ct.c_void_p
        lib.tck_create.argtypes = [
            ct.c_uint32, ct.c_uint32, ct.c_uint32, ct.c_uint32,
            ct.c_void_p, ct.c_void_p,
        ]
        lib.tck_destroy.restype = None
        lib.tck_destroy.argtypes = [ct.c_void_p]
        lib.tck_predict.restype = None
        lib.tck_predict.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_votes.restype = None
        lib.tck_votes.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_predict_unpruned.restype = None
        lib.tck_predict_unpruned.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_votes_unpruned.restype = None
        lib.tck_votes_unpruned.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_ivf_build.restype = ct.c_int32
        lib.tck_ivf_build.argtypes = [
            ct.c_void_p, ct.c_uint32, ct.c_void_p, ct.c_void_p,
        ]
        lib.tck_predict_ivf.restype = None
        lib.tck_predict_ivf.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32,
            ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_votes_ivf.restype = None
        lib.tck_votes_ivf.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32,
            ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_screen_stats.restype = None
        lib.tck_screen_stats.argtypes = [ct.c_void_p, ct.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


class NativeKnn:
    """A compiled corpus handle (arrays copied in at construction).

    ``d`` is the importer dict (``fit_X`` (S, F) float, ``y`` (S,) int,
    ``n_neighbors``, ``classes``) — the same dict models/knn.from_numpy
    consumes, so the two paths load identical corpora."""

    def __init__(self, d: dict):
        lib = _load()
        fit_X = np.ascontiguousarray(d["fit_X"], np.float32)
        classes = np.asarray(d["classes"])
        # y is already class INDICES (knn.from_numpy casts it straight
        # to int32 — the importer resolves raw labels)
        fit_y = np.ascontiguousarray(d["y"], np.int32)
        S, F = fit_X.shape
        k = int(d["n_neighbors"])
        self.n_classes = int(classes.shape[0])
        self.n_features = F
        self.n_neighbors = k
        if S < k:
            raise ValueError(f"corpus has {S} rows < n_neighbors={k}")
        if k > 64:
            raise ValueError(f"n_neighbors={k} exceeds the 64-cand cap")
        self.n_rows = S
        self.n_lists = 0  # set by build_ivf
        self._lib = lib
        self._h = lib.tck_create(
            S, F, self.n_classes, k,
            fit_X.ctypes.data_as(ct.c_void_p),
            fit_y.ctypes.data_as(ct.c_void_p),
        )
        if not self._h:
            raise RuntimeError("tck_create rejected the corpus layout")

    def _check_X(self, X: np.ndarray) -> np.ndarray:
        if not self._h:
            raise RuntimeError("NativeKnn handle is closed")
        X = np.ascontiguousarray(X, np.float32)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"X shape {X.shape} != (N, {self.n_features})"
            )
        return X

    def predict(self, X: np.ndarray) -> np.ndarray:
        """(N, F) float32 features -> (N,) int32 class indices, through
        the PRUNED exact engine (triangle/f32 screens + early abandon —
        vote-for-vote identical to ``predict_unpruned``, pinned in
        tests/test_native_knn.py)."""
        X = self._check_X(X)
        out = np.empty(X.shape[0], np.int32)
        self._lib.tck_predict(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def votes(self, X: np.ndarray) -> np.ndarray:
        """(N, F) float32 features -> (N, C) int32 neighbor vote counts
        — the score surface for the open-set / degrade-rung paths
        (``argmax(votes) == predict``, first-max tie order, asserted in
        tests/test_native_knn.py). Pruned engine, same guarantee."""
        X = self._check_X(X)
        out = np.empty((X.shape[0], self.n_classes), np.int32)
        self._lib.tck_votes(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def predict_unpruned(self, X: np.ndarray) -> np.ndarray:
        """The original blocked full-scan predict — the same-run A/B
        baseline (docs/artifacts/knn_prune_cpu.json) and parity
        oracle for the pruned engine."""
        X = self._check_X(X)
        out = np.empty(X.shape[0], np.int32)
        self._lib.tck_predict_unpruned(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def votes_unpruned(self, X: np.ndarray) -> np.ndarray:
        X = self._check_X(X)
        out = np.empty((X.shape[0], self.n_classes), np.int32)
        self._lib.tck_votes_unpruned(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def build_ivf(self, centers: np.ndarray,
                  assignments: np.ndarray) -> None:
        """Install the IVF coarse index: ``centers`` (K, F) float,
        ``assignments`` (S,) int in [0, K) — the quantizer fit by
        ops/knn_ivf.py (train/kmeans). Build once, then serve: the
        C++ side is not guarded against concurrent predicts during a
        rebuild."""
        if not self._h:
            raise RuntimeError("NativeKnn handle is closed")
        centers = np.ascontiguousarray(centers, np.float32)
        assignments = np.ascontiguousarray(assignments, np.int32)
        if centers.ndim != 2 or centers.shape[1] != self.n_features:
            raise ValueError(
                f"centers shape {centers.shape} != (K, {self.n_features})"
            )
        if assignments.shape != (self.n_rows,):
            # the C++ side reads exactly S entries — a short or
            # reshaped buffer would be an out-of-bounds read
            raise ValueError(
                f"assignments shape {assignments.shape} != "
                f"({self.n_rows},)"
            )
        rc = self._lib.tck_ivf_build(
            self._h, centers.shape[0],
            centers.ctypes.data_as(ct.c_void_p),
            assignments.ctypes.data_as(ct.c_void_p),
        )
        if rc:
            raise ValueError(
                f"tck_ivf_build rejected the index (rc={rc}: "
                "bad K or out-of-range assignment)"
            )
        self.n_lists = int(centers.shape[0])

    def _ivf_ready(self) -> None:
        if not getattr(self, "n_lists", 0):
            raise RuntimeError("no IVF index — call build_ivf first")

    def predict_ivf(self, X: np.ndarray, nprobe: int) -> np.ndarray:
        """Approximate predict over the ``nprobe`` nearest coarse lists
        (clamped to K; ``nprobe >= n_lists`` equals ``predict``
        bit-for-bit — the tests/test_knn_ivf.py anchor)."""
        self._ivf_ready()
        X = self._check_X(X)
        if nprobe < 1:
            raise ValueError(f"nprobe={nprobe} must be >= 1")
        out = np.empty(X.shape[0], np.int32)
        self._lib.tck_predict_ivf(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1], nprobe,
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def votes_ivf(self, X: np.ndarray, nprobe: int) -> np.ndarray:
        self._ivf_ready()
        X = self._check_X(X)
        if nprobe < 1:
            raise ValueError(f"nprobe={nprobe} must be >= 1")
        out = np.empty((X.shape[0], self.n_classes), np.int32)
        self._lib.tck_votes_ivf(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1], nprobe,
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def screen_stats(self) -> tuple[int, int, int]:
        """Cumulative (screened, abandoned, queries) counters: norm-bound
        skips, partial-distance early exits, and queries answered —
        the serving layer diffs these into the
        ``knn_candidates_screened`` / ``knn_candidates_abandoned``
        metrics (docs/OBSERVABILITY.md)."""
        if not self._h:
            raise RuntimeError("NativeKnn handle is closed")
        out = np.zeros(3, np.uint64)
        self._lib.tck_screen_stats(
            self._h, out.ctypes.data_as(ct.c_void_p)
        )
        return int(out[0]), int(out[1]), int(out[2])

    def close(self) -> None:
        if self._h:
            self._lib.tck_destroy(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
