"""ctypes bindings for the native (C++) brute-force KNN evaluator.

The accelerator-less host path for the KNeighbors checkpoint (the
reference walks one KDTree per query on one CPU,
``/root/reference/traffic_classifier.py:234-236``): exact float64
squared distances with the lax.top_k tie order, SIMD-blocked so the
corpus streams from cache once per 8-query block (see
native/knn_eval.cpp). The XLA/Pallas kernels in models/knn.py and
ops/pallas_knn.py remain the device paths; ``bench.py`` races this
entrant on the CPU fallback under the same same-run parity gate as
every other raced kernel. Serving divergence: this path's exact-f64
ranking can disagree with the default f32 dot-expansion ranking on
near-ties — ``TCSDN_KNN_TOPK=native`` is a documented opt-in and
models/__init__ logs a one-line warning when it is selected.

Built lazily with g++ ``-march=native`` on first use (the distance
loops need the host's widest SIMD; the .so never leaves the machine it
was built on). ``available()`` reports whether a build is possible.
"""

from __future__ import annotations

import ctypes as ct
import os
import threading

import numpy as np

from .loader import LazyLib

_DIR = os.path.dirname(os.path.abspath(__file__))
_lazy = LazyLib(
    os.path.join(_DIR, "knn_eval.cpp"),
    os.path.join(_DIR, "_knn_eval.so"),
    "native knn evaluator",
    flags=("-O3", "-march=native"),
)
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = _lazy.load()  # graftlint: disable=blocking-under-lock -- one-time g++ build serialized under the module lock by design (build-once); later calls are cache hits
        lib.tck_create.restype = ct.c_void_p
        lib.tck_create.argtypes = [
            ct.c_uint32, ct.c_uint32, ct.c_uint32, ct.c_uint32,
            ct.c_void_p, ct.c_void_p,
        ]
        lib.tck_destroy.restype = None
        lib.tck_destroy.argtypes = [ct.c_void_p]
        lib.tck_predict.restype = None
        lib.tck_predict.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_votes.restype = None
        lib.tck_votes.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint64, ct.c_uint32, ct.c_void_p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


class NativeKnn:
    """A compiled corpus handle (arrays copied in at construction).

    ``d`` is the importer dict (``fit_X`` (S, F) float, ``y`` (S,) int,
    ``n_neighbors``, ``classes``) — the same dict models/knn.from_numpy
    consumes, so the two paths load identical corpora."""

    def __init__(self, d: dict):
        lib = _load()
        fit_X = np.ascontiguousarray(d["fit_X"], np.float32)
        classes = np.asarray(d["classes"])
        # y is already class INDICES (knn.from_numpy casts it straight
        # to int32 — the importer resolves raw labels)
        fit_y = np.ascontiguousarray(d["y"], np.int32)
        S, F = fit_X.shape
        k = int(d["n_neighbors"])
        self.n_classes = int(classes.shape[0])
        self.n_features = F
        self.n_neighbors = k
        if S < k:
            raise ValueError(f"corpus has {S} rows < n_neighbors={k}")
        if k > 64:
            raise ValueError(f"n_neighbors={k} exceeds the 64-cand cap")
        self._lib = lib
        self._h = lib.tck_create(
            S, F, self.n_classes, k,
            fit_X.ctypes.data_as(ct.c_void_p),
            fit_y.ctypes.data_as(ct.c_void_p),
        )
        if not self._h:
            raise RuntimeError("tck_create rejected the corpus layout")

    def predict(self, X: np.ndarray) -> np.ndarray:
        """(N, F) float32 features -> (N,) int32 class indices."""
        if not self._h:
            raise RuntimeError("NativeKnn handle is closed")
        X = np.ascontiguousarray(X, np.float32)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"X shape {X.shape} != (N, {self.n_features})"
            )
        out = np.empty(X.shape[0], np.int32)
        self._lib.tck_predict(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def votes(self, X: np.ndarray) -> np.ndarray:
        """(N, F) float32 features -> (N, C) int32 neighbor vote counts
        — the score surface for the open-set / degrade-rung paths
        (``argmax(votes) == predict``, first-max tie order, asserted in
        tests/test_native_knn.py)."""
        if not self._h:
            raise RuntimeError("NativeKnn handle is closed")
        X = np.ascontiguousarray(X, np.float32)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"X shape {X.shape} != (N, {self.n_features})"
            )
        out = np.empty((X.shape[0], self.n_classes), np.int32)
        self._lib.tck_votes(
            self._h,
            X.ctypes.data_as(ct.c_void_p),
            X.shape[0], X.shape[1],
            out.ctypes.data_as(ct.c_void_p),
        )
        return out

    def close(self) -> None:
        if self._h:
            self._lib.tck_destroy(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
