"""ctypes bindings for the native (C++) ingest engine.

``NativeBatcher`` is a drop-in for the pure-Python ``FlowIndex`` +
``Batcher`` pair in ingest/batcher.py: raw monitor bytes in, padded
``flow_table.UpdateBatch`` out. The Python pair remains the behavioral
oracle (tests/test_native_engine.py asserts record-for-record parity);
this path exists because line splitting + dict routing is the host-side
hot loop once the counter math lives on device (SURVEY.md §2.3 — the
reference's equivalent work runs in eventlet/CPython, one line at a time).

The shared library is built lazily with g++ on first use (no pybind11 in
this image; plain C ABI + ctypes). ``available()`` reports whether a
build is possible so callers can gate to the Python fallback.
"""

from __future__ import annotations

import ctypes as ct
import os
import threading

import numpy as np

from ..core import flow_table as ft
from ..ingest.protocol import TelemetryRecord, format_line
from .loader import LazyLib

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "flow_engine.cpp")
_LIB = os.path.join(_DIR, "_flow_engine.so")
_lazy = LazyLib(_SRC, _LIB, "native flow engine",
                flags=("-O3", "-pthread"))
_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = _lazy.load()  # graftlint: disable=blocking-under-lock -- one-time g++ build serialized under the module lock by design (build-once; shared machinery with forest.py); later calls are cache hits
        lib.tc_engine_create.restype = ct.c_void_p
        lib.tc_engine_create.argtypes = [ct.c_uint32, ct.c_uint32]
        lib.tc_engine_destroy.restype = None
        lib.tc_engine_destroy.argtypes = [ct.c_void_p]
        lib.tc_engine_feed.restype = ct.c_uint64
        lib.tc_engine_feed.argtypes = [ct.c_void_p, ct.c_char_p, ct.c_uint64]
        lib.tck_feed_lines.restype = ct.c_uint64
        lib.tck_feed_lines.argtypes = [
            ct.c_void_p, ct.c_char_p, ct.c_uint64, ct.c_uint32,
        ]
        lib.tck_flush_wire.restype = ct.c_uint64
        lib.tck_flush_wire.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_void_p, ct.c_uint32,
            ct.c_uint32,
        ]
        lib.tck_reset_tail.restype = None
        lib.tck_reset_tail.argtypes = [ct.c_void_p, ct.c_uint32]
        lib.tck_slots_for_source.restype = ct.c_uint32
        lib.tck_slots_for_source.argtypes = [
            ct.c_void_p, ct.c_uint32, ct.c_void_p,
        ]
        lib.tck_parse_errors_total.restype = ct.c_uint64
        lib.tck_parse_errors_total.argtypes = [ct.c_void_p]
        lib.tck_parse_errors.restype = ct.c_uint64
        lib.tck_parse_errors.argtypes = [ct.c_void_p, ct.c_uint32]
        lib.tck_source_parsed.restype = ct.c_uint64
        lib.tck_source_parsed.argtypes = [ct.c_void_p, ct.c_uint32]
        lib.tc_engine_pending.restype = ct.c_uint64
        lib.tc_engine_pending.argtypes = [ct.c_void_p]
        lib.tc_engine_flush.restype = ct.c_uint32
        lib.tc_engine_flush.argtypes = [ct.c_void_p] + [ct.c_void_p] * 8
        lib.tc_engine_last_flush_conflict.restype = ct.c_int
        lib.tc_engine_last_flush_conflict.argtypes = [ct.c_void_p]
        lib.tc_engine_dropped.restype = ct.c_uint64
        lib.tc_engine_dropped.argtypes = [ct.c_void_p]
        lib.tc_engine_parsed.restype = ct.c_uint64
        lib.tc_engine_parsed.argtypes = [ct.c_void_p]
        lib.tc_engine_last_time.restype = ct.c_int32
        lib.tc_engine_last_time.argtypes = [ct.c_void_p]
        lib.tc_engine_num_flows.restype = ct.c_uint32
        lib.tc_engine_num_flows.argtypes = [ct.c_void_p]
        lib.tc_engine_slot_meta.restype = ct.c_int
        lib.tc_engine_slot_meta.argtypes = [
            ct.c_void_p, ct.c_uint32, ct.c_char_p, ct.c_char_p, ct.c_uint32,
        ]
        lib.tc_engine_release_slot.restype = None
        lib.tc_engine_release_slot.argtypes = [ct.c_void_p, ct.c_uint32]
        lib.tc_engine_release_slots.restype = None
        lib.tc_engine_release_slots.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint32,
        ]
        lib.tc_engine_export_index.restype = ct.c_uint32
        lib.tc_engine_export_index.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_void_p,
        ]
        lib.tc_engine_export_free.restype = ct.c_uint32
        lib.tc_engine_export_free.argtypes = [ct.c_void_p, ct.c_void_p]
        lib.tc_engine_import_slots.restype = None
        lib.tc_engine_import_slots.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_void_p, ct.c_void_p,
            ct.c_void_p, ct.c_uint32,
        ]
        lib.tc_engine_import_finish.restype = None
        lib.tc_engine_import_finish.argtypes = [
            ct.c_void_p, ct.c_uint32, ct.c_int32, ct.c_void_p, ct.c_uint32,
        ]
        lib.tc_engine_export_meta.restype = None
        lib.tc_engine_export_meta.argtypes = [
            ct.c_void_p, ct.c_void_p, ct.c_uint32, ct.c_void_p, ct.c_void_p,
        ]
        _lib = lib
        return lib


def available() -> bool:
    """True when the native engine can be built/loaded on this host.

    The ``native.load`` fault site (utils/faults.py) simulates a
    build/dlopen failure here — uncached, unlike LazyLib's real-error
    cache, so one injected outage doesn't poison later calls — letting
    the chaos suite prove both the Python-fallback gate (cli._use_native)
    and serving_checkpoint.restore's native-unavailable error."""
    from ..utils.faults import FaultInjected, fault_point

    try:
        fault_point("native.load")
        _load()
        return True
    except (RuntimeError, FaultInjected):
        return False


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ct.c_void_p)


class NativeBatcher:
    """Raw telemetry bytes → padded UpdateBatch, all routing in C++.

    API-compatible with the batcher.FlowIndex + batcher.Batcher pair where
    FlowStateEngine touches them (add/flush/dropped/release_slot/slot_meta),
    plus a bulk ``feed(bytes)`` fast path the Python pair doesn't have.
    """

    def __init__(self, capacity: int, buckets=None):
        from ..ingest.batcher import DEFAULT_BUCKETS

        if buckets is None:
            buckets = DEFAULT_BUCKETS
        lib = _load()
        self._lib = lib
        self.capacity = capacity
        self.buckets = tuple(buckets)
        self._max = self.buckets[-1]
        self._h = lib.tc_engine_create(capacity, self._max)
        if not self._h:
            raise RuntimeError(
                "tc_engine_create failed (capacity must be 1..2^30-1 — "
                "the wire layout packs slot|flags in 32 bits, the same "
                "bound pack_wire enforces — and max_batch nonzero)"
            )
        # Reused flush staging buffers (C fills the first n rows; the
        # padded tail is re-zeroed per flush below).
        m = self._max
        self._slot = np.empty(m, np.int32)
        self._time = np.empty(m, np.int32)
        self._pkts_lo = np.empty(m, np.uint32)
        self._pkts_f = np.empty(m, np.float32)
        self._bytes_lo = np.empty(m, np.uint32)
        self._bytes_f = np.empty(m, np.float32)
        self._is_fwd = np.empty(m, np.uint8)
        self._is_create = np.empty(m, np.uint8)
        # Pinned double-buffered wire staging (flush_wire): C++ writes
        # the packed (B, 4|6) uint32 matrix straight into these pages —
        # no per-flush numpy allocation, no Python column work.
        self._wire_stage = ft.WireStage(self._max)
        self._buckets_u32 = np.asarray(self.buckets, np.uint32)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.tc_engine_destroy(h)
            self._h = None

    # -- ingest ------------------------------------------------------------
    def feed(self, data: bytes, source: int = 0) -> int:
        """Bulk byte ingest (the fast path): one ``tck_feed_lines``
        call per poll batch, routed entirely in C++ under ``source``'s
        flow-table namespace (0 = the legacy/default namespace — the
        exact pre-fan-in key). Returns records parsed.

        Fault site ``ingest.native_parse`` (ABSORBED): a fire simulates
        one corrupt line at the head of the batch — counted against
        this source like any real malformed line and skipped; the rest
        of the batch parses normally and the serve never sees the
        failure."""
        from ..utils.faults import FaultInjected, fault_point

        try:
            fault_point("ingest.native_parse")
        except FaultInjected:
            # SUBSTITUTE the batch's lead line with a data-prefixed-
            # but-invalid one rather than deleting it: raw chunks can
            # end mid-line, and deleting the head would also delete the
            # completion of the previous chunk's carried tail — the
            # engine would then splice that stale tail onto the NEXT
            # line, a torn frame this site's contract forbids. The
            # substituted line flows through the real parser and is
            # counted per source like any wire-born malformed line
            # (\xff fails both the numeric and the UTF-8 field rules,
            # so the substitute — or a tail it completes — can never
            # parse as a valid record). A newline-LESS chunk is a pure
            # mid-line fragment: corrupt the spanning line IN PLACE by
            # splicing a bogus \t\xff field a few bytes in (past a
            # line-starting 'data' prefix, so the corrupt line still
            # counts) — the extra field breaks the EXACT 9-column rule
            # both parsers enforce, wherever in the spanning line it
            # lands. Deleting the fragment and fabricating a terminator
            # would tear the very framing this branch exists to
            # preserve.
            nl = data.find(b"\n")
            if nl >= 0:
                data = b"data\t\xff\n" + data[nl + 1:]
            else:
                data = data[:4] + b"\t\xff" + data[4:]
        return int(
            self._lib.tck_feed_lines(self._h, data, len(data), source)
        )

    def add(self, r: TelemetryRecord) -> bool:
        """Record-object compatibility shim (tests, mixed pipelines).
        The record's ``source`` rides into the namespaced keyer — the
        wire format itself has no source field."""
        self.feed(format_line(r), r.source)
        return True

    def __len__(self) -> int:
        return int(self._lib.tc_engine_pending(self._h))

    # -- flush -------------------------------------------------------------
    def last_flush_was_conflict(self) -> bool:
        """True iff the batch returned by the most recent ``flush()`` was
        a generation started by a same-(slot, direction, kind) conflict —
        it must not be coalesced into the same device scatter as the
        batch flushed before it. Size-rollover generations return False
        (see flow_engine.cpp push_row)."""
        return bool(self._lib.tc_engine_last_flush_conflict(self._h))

    def flush(self) -> ft.UpdateBatch | None:
        """Pop the oldest pending generation as a padded UpdateBatch
        (None when idle) — same contract as batcher.Batcher.flush."""
        n = int(
            self._lib.tc_engine_flush(
                self._h, _ptr(self._slot), _ptr(self._time),
                _ptr(self._pkts_lo), _ptr(self._pkts_f),
                _ptr(self._bytes_lo), _ptr(self._bytes_f),
                _ptr(self._is_fwd), _ptr(self._is_create),
            )
        )
        if n == 0:
            return None
        size = next(b for b in self.buckets if n <= b)
        slot = np.full(size, self.capacity, np.int32)  # scratch-row padding
        slot[:n] = self._slot[:n]
        time = np.zeros(size, np.int32)
        time[:n] = self._time[:n]
        pkts_lo = np.zeros(size, np.uint32)
        pkts_lo[:n] = self._pkts_lo[:n]
        pkts_f = np.zeros(size, np.float32)
        pkts_f[:n] = self._pkts_f[:n]
        bytes_lo = np.zeros(size, np.uint32)
        bytes_lo[:n] = self._bytes_lo[:n]
        bytes_f = np.zeros(size, np.float32)
        bytes_f[:n] = self._bytes_f[:n]
        is_fwd = np.ones(size, bool)
        is_fwd[:n] = self._is_fwd[:n].astype(bool)
        is_create = np.zeros(size, bool)
        is_create[:n] = self._is_create[:n].astype(bool)
        return ft.UpdateBatch(
            slot=slot, time=time, pkts_lo=pkts_lo, pkts_f=pkts_f,
            bytes_lo=bytes_lo, bytes_f=bytes_f, is_fwd=is_fwd,
            is_create=is_create,
        )

    def flush_wire(self) -> "np.ndarray | None":
        """Pop the oldest pending generation DIRECTLY as a packed wire
        matrix (flow_table.pack_wire layout) — the zero-copy serving
        path: C++ writes the padded (B, 4|6) uint32 rows into this
        batcher's pinned staging pages and the returned view goes
        straight to the device scatter. None when idle. The staging is
        double-buffered, so the previous flush's view stays intact
        while its transfer may still be in flight."""
        buf = self._wire_stage.buffer()
        r = int(
            self._lib.tck_flush_wire(
                self._h, _ptr(buf), _ptr(self._buckets_u32),
                len(self._buckets_u32), self.capacity,
            )
        )
        if r == 0:
            return None
        return self._wire_stage.view(r & 0xFFFFFFFF, r >> 32)

    def warm_stage(self) -> None:
        """Touch every wire-staging page (AOT warmup): the first serve
        tick must not pay the staging buffers' page faults."""
        self._wire_stage.touch()

    # -- bookkeeping -------------------------------------------------------
    @property
    def dropped(self) -> int:
        return int(self._lib.tc_engine_dropped(self._h))

    @property
    def parsed(self) -> int:
        return int(self._lib.tc_engine_parsed(self._h))

    @property
    def last_time(self) -> int:
        """Max telemetry timestamp parsed — the idle-eviction clock."""
        return int(self._lib.tc_engine_last_time(self._h))

    def num_flows(self) -> int:
        return int(self._lib.tc_engine_num_flows(self._h))

    def slot_meta(self, slot: int) -> tuple[str, str] | None:
        """(eth_src, eth_dst) for an in-use slot, for the UI table."""
        src = ct.create_string_buffer(64)
        dst = ct.create_string_buffer(64)
        if self._lib.tc_engine_slot_meta(self._h, slot, src, dst, 64):
            # errors="replace" is belt-and-braces: ingest_line rejects
            # non-UTF-8 fields, so this should never trigger
            return (
                src.value.decode(errors="replace"),
                dst.value.decode(errors="replace"),
            )
        return None

    def reset_tail(self, source: int) -> None:
        """Drop ``source``'s carried partial line (namespace eviction:
        a dead incarnation's dangling fragment must never be completed
        by the restarted stream's first chunk)."""
        self._lib.tck_reset_tail(self._h, source)

    def slots_for_source(self, source: int) -> np.ndarray:
        """Every live slot in ``source``'s namespace, ascending — the
        native eviction set behind ``FlowStateEngine.evict_source``
        (one ctypes crossing; O(capacity) scan, walked only on a
        source-death event)."""
        out = np.empty(self.capacity, np.uint32)
        n = int(self._lib.tck_slots_for_source(self._h, source, _ptr(out)))
        return out[:n].copy()

    def parse_errors(self, source: int | None = None) -> int:
        """Malformed telemetry lines ('data'-prefixed, invalid body)
        counted and skipped — total, or for one source. Absorbed
        ``ingest.native_parse`` fires count here too: the fault seam
        substitutes a genuinely malformed line that the C++ parser
        rejects and accounts like any wire-born one."""
        if source is None:
            return int(self._lib.tck_parse_errors_total(self._h))
        return int(self._lib.tck_parse_errors(self._h, source))

    def source_parsed(self, source: int) -> int:
        """Records parsed under ``source``'s namespace (per-source
        accounting for the fan-in roster)."""
        return int(self._lib.tck_source_parsed(self._h, source))

    def release_slot(self, slot: int) -> None:
        self._lib.tc_engine_release_slot(self._h, slot)

    def release_slots(self, slots) -> None:
        """Bulk release: one ctypes crossing for the whole eviction batch
        (``slots`` is any uint32-convertible array)."""
        a = np.ascontiguousarray(slots, np.uint32)
        self._lib.tc_engine_release_slots(self._h, _ptr(a), a.size)

    def export_index(self):
        """Serving-checkpoint export, all bulk crossings:
        ``(fp, used, next_slot, free)`` — per-slot fingerprints,
        occupancy, the sequential-assignment frontier, and the free-slot
        stack VERBATIM (LIFO order decides future assignments)."""
        fp = np.zeros(self.capacity, np.uint64)
        used = np.zeros(self.capacity, np.uint8)
        next_slot = self._lib.tc_engine_export_index(
            self._h, _ptr(fp), _ptr(used)
        )
        free = np.zeros(self.capacity, np.uint32)
        n_free = self._lib.tc_engine_export_free(self._h, _ptr(free))
        return fp, used, int(next_slot), free[:n_free].copy()

    def export_meta(self, slots):
        """(src, dst) fixed-width byte arrays for the given slots — one
        ctypes crossing for the whole table."""
        slots = np.ascontiguousarray(slots, np.uint32)
        src = np.zeros(slots.size, "S64")
        dst = np.zeros(slots.size, "S64")
        self._lib.tc_engine_export_meta(
            self._h, _ptr(slots), slots.size, _ptr(src), _ptr(dst)
        )
        return src, dst

    def import_index(self, slots, fps, src, dst, next_slot: int,
                     last_time: int, free) -> None:
        """Rebuild a FRESH engine's index from an export (same capacity):
        one bulk crossing for the slots, one for the finish."""
        slots = np.ascontiguousarray(slots, np.uint32)
        fps = np.ascontiguousarray(fps, np.uint64)
        src = np.ascontiguousarray(src, "S64")
        dst = np.ascontiguousarray(dst, "S64")
        self._lib.tc_engine_import_slots(
            self._h, _ptr(slots), _ptr(fps), _ptr(src), _ptr(dst),
            slots.size,
        )
        free = np.ascontiguousarray(free, np.uint32)
        self._lib.tc_engine_import_finish(
            self._h, next_slot, last_time, _ptr(free), free.size
        )
