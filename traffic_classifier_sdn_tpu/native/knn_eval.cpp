// Native (host-spine) KNN evaluator: cluster-pruned exact search + IVF.
//
// The reference's KNN walks one KDTree per query on one CPU
// (models/KNeighbors checkpoint, loaded at traffic_classifier.py:234-236);
// the framework's XLA paths (models/knn.py) rank by an f32 dot-expansion
// similarity on device. This evaluator is the accelerator-less host
// entrant: exact float64 squared distances with the lax.top_k total
// order ((distance asc, corpus index asc) — ties to the earlier index),
// votes as class counts over the k nearest with first-maximum argmax,
// mirroring models/knn.neighbor_votes → argmax.
//
// PRUNED exact engine (tck_predict / tck_votes — the default). At build
// time the corpus is coarse-clustered (a fixed-seed Lloyd pass in here —
// deterministic: fixed init, fixed iteration count, fixed summation
// order), lists are laid out consecutively and split into uniform
// kEChunk-wide chunks (sentinel-padded), each anchored on the rounded
// f32 mean of its REAL members. Queries run in 8-wide blocks:
//
//   1. exact f64 squared distances to every chunk anchor;
//   2. each query seeds its running top-k exactly from its nearest
//      chunk (blocked f64 refine — FMA latency hidden across members);
//   3. one sweep over the remaining chunks: per (query, chunk), an
//      Elkan-style triangle screen in squared space
//      (‖x−t‖ ≥ ‖x−ã‖ − cmax and, inside the hull,
//      ‖x−t‖ ≥ cmin − ‖x−ã‖ for every member t of the chunk) skips
//      the whole chunk for that query without touching a member;
//      chunks that survive for ANY query in the block pay ONE
//      f-streamed f32 distance screen shared across the block's
//      surviving queries — the same vectorization shape as the
//      unpruned kernel, restricted to the (query, chunk) pairs the
//      triangle bound cannot clear. A member whose f32 distance
//      exceeds the query's bound inflated by kScreenMargin32 is
//      screened out; the few survivors pay the exact f64 accumulation
//      (ascending-f — bitwise-identical addend order to the unpruned
//      path) with a per-feature early-abandon against the LIVE k-th
//      best distance.
//
// Every pruning step is provably lossless. The f32 screen consumes the
// SAME f32 inputs the f64 path widens, so its 12-term accumulation is
// within ~2e-6 relative of the exact sum — a 1e-5 threshold margin
// makes a screened candidate's f64 distance strictly above the
// incumbent worst, ties included. The triangle tests compare against a
// bound radius inflated by the deflation reserve (1e-9 ≫ the f64
// sqrt/sub/mul rounding); the early-abandon is exact (a partial sum of
// nonnegative addends only grows, and only STRICTLY-greater partials
// abandon). Candidate order is scan-order-independent: insertion
// compares (distance, corpus index) lexicographically, so any visiting
// order produces the exact ascending-index-scan top-k. The anchor of
// every triangle bound is the ROUNDED chunk mean — a concrete point,
// so the inequality is exact regardless of how it was derived.
// Non-finite queries (and corpora with non-finite values, where
// cluster geometry is meaningless) fall back to the ascending full
// scan — parity with the unpruned path holds on every input. Cluster
// QUALITY only affects speed, never results. Measured on this class of
// flow corpora the exact pruned tier gains ~1.2-1.8× over the blocked
// full scan at k=5 (docs/artifacts/knn_prune_cpu.json records the
// same-run A/B); the order-of-magnitude rescue lives in the IVF tier
// below and the XLA screened path (models/knn.py).
//
// UNPRUNED baseline (tck_predict_unpruned / tck_votes_unpruned): the
// original GEMM-style blocked evaluator — 8-query blocks × 256-row
// corpus chunks, per-feature streaming accumulation that autovectorizes
// without -ffast-math. Kept callable so tools/bench_knn.py can race
// pruned vs unpruned in ONE process on identical inputs
// (docs/artifacts/knn_prune_cpu.json) and the parity suite can pin
// vote-for-vote equality.
//
// IVF tier (tck_ivf_build + tck_predict_ivf / tck_votes_ivf): the
// approximate tier behind the explicit `--knn-topk ivf` opt-in. The
// coarse quantizer (KMeans centers + assignments) is fit in Python by
// the already-device-resident kernel (train/kmeans.py via
// ops/knn_ivf.py) and handed over; queries rank the centroids exactly
// (f64 over the same rounded centers, (distance, centroid index)
// order), probe only the nprobe nearest lists, and run the bounded
// exact member scan within them. nprobe >= K degenerates to the exact
// search bit-for-bit (every list scanned; candidate order is
// comparator-defined, not scan-defined) — the anchor
// tests/test_knn_ivf.py pins. tck_ivf_build is NOT thread-safe against
// in-flight predicts (build once, then serve — the same discipline as
// tck_create).
//
// Screen accounting: per-handle atomic totals (candidates screened out
// by the triangle/f32 bounds, early-abandoned partial distances,
// queries) read by tck_screen_stats — the serving layer surfaces them
// as the knn_candidates_screened / knn_candidates_abandoned counters.
//
// Plain C ABI for ctypes (no pybind11 in this image) — same pattern as
// flow_engine.cpp / forest_eval.cpp.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

namespace {

constexpr uint32_t kQueryBlock = 8;
constexpr uint32_t kChunk = 256;
// Exact-tier chunk width: small enough that whole-chunk triangle skips
// fire (radius shrinks with the chunk), large enough to amortize the
// shared screen's loop constants.
constexpr uint32_t kEChunk = 32;
constexpr uint32_t kMaxK = 64;
constexpr uint32_t kMaxIvfLists = 65536;
constexpr uint32_t kLloydIters = 8;
// Deflation absorbing the f64 rounding of the sqrt/sub/mul bound chain
// (relative error ≤ ~1e-13) so a triangle bound can never reject a
// candidate whose computed distance would have been inserted.
constexpr double kScreenDeflate = 1.0 - 1e-9;
// Threshold inflation for the f32 SIMD distance screen: a 12-term f32
// accumulation of the SAME f32 inputs the f64 path widens differs from
// the exact sum by ≤ ~(F+2)·2⁻²⁴ ≈ 2e-6 relative, so a candidate whose
// f32 distance exceeds bound×(1+1e-5) provably has f64 distance
// strictly above the bound — it could never be inserted, ties included.
constexpr double kScreenMargin32 = 1.0 + 1e-5;
// Sentinel member index padding each list-aligned chunk to the uniform
// width: sentinel columns hold kSentinelVal, so every screen and exact
// distance sees +inf-scale values and rejects them — they can never
// enter a top-k (S >= k real members always exist).
constexpr uint32_t kSentinel = 0xffffffffu;
constexpr double kSentinelVal = 1e300;

// The exact tier's index: the corpus permuted into spatial locality
// order (a fixed-seed Lloyd pass — lists laid out consecutively), then
// cut into UNIFORM kChunk-member chunks aligned with the streaming
// layout. Each chunk carries its own anchor (the ROUNDED f32 mean of
// its members — a concrete point, so the triangle bound anchored on it
// is exact no matter how it was derived) and the min/max member-anchor
// distances the whole-chunk skip tests compare against.
struct Chunks {
    uint32_t nchunk = 0;
    uint32_t spad = 0;              // padded member count (NC * kEChunk)
    std::vector<uint32_t> nreal;    // (NC,) real members per chunk
    std::vector<double> anch_cols;  // (F, NC) column-major, f64 of the
                                    // rounded f32 anchors
    std::vector<double> cmin;       // (NC,) min member-anchor distance
    std::vector<double> cmax;       // (NC,) max member-anchor distance
    std::vector<uint32_t> orig;     // (S,) original corpus idx, scan order
    std::vector<float> cols32;      // (F, S) f32 columns, scan order —
                                    // the SIMD screen's operand (the
                                    // corpus IS f32 input: lossless)
    std::vector<double> cols64;     // (F, S) f64 columns, scan order —
                                    // the blocked exact refine operand
};

// The IVF tier's index: list-contiguous permuted corpus + per-list
// geometry, installed from the Python-fit quantizer (centers rounded
// to f32 anchors, mirrored to f64 for the probe ranking).
struct Coarse {
    uint32_t K = 0;
    uint32_t max_list = 0;          // longest list (scratch sizing)
    std::vector<double> cent_cols;  // (F, K) column-major, f64 mirror
    std::vector<uint32_t> off;      // (K+1,) list offsets
    std::vector<double> cmin;       // (K,) min member-anchor distance
    std::vector<double> cmax;       // (K,) max member-anchor distance
    std::vector<uint32_t> orig;     // (S,) original corpus idx, scan order
    std::vector<double> cols64;     // (F, S) f64 columns, scan order —
                                    // the blocked probe-refine operand
};

struct Knn {
    uint32_t S, F, C, k;
    std::vector<double> cols;  // (F, S) column-major corpus (unpruned)
    std::vector<double> rows;  // (S, F) row-major corpus (scalar scans)
    std::vector<int32_t> y;    // (S,)
    bool prunable = false;     // finite corpus → cluster geometry valid
    Chunks exact;              // built at tck_create
    Coarse ivf;                // built at tck_ivf_build (K==0 until then)
    // screen accounting (relaxed: counters, not synchronization)
    std::atomic<uint64_t> screened{0};
    std::atomic<uint64_t> abandoned{0};
    std::atomic<uint64_t> queries{0};
};

struct Cand {
    double d;
    uint32_t idx;
};

// (distance asc, corpus index asc) — the lax.top_k total order, as an
// explicit comparator so candidate insertion is independent of scan
// order (the cluster scans rely on this; the ascending-scan unpruned
// path produces the same order by construction).
inline bool cand_better(double d, uint32_t idx, const Cand &w) {
    return d < w.d || (d == w.d && idx < w.idx);
}

inline double b_worst(const Cand *b, uint32_t k) { return b[k - 1].d; }

inline void push_cand(Cand *b, uint32_t &n, uint32_t k, double d,
                      uint32_t idx) {
    if (n == k && !cand_better(d, idx, b[k - 1])) return;
    uint32_t pos = (n < k) ? n : k - 1;
    while (pos > 0 && cand_better(d, idx, b[pos - 1])) {
        b[pos] = b[pos - 1];
        --pos;
    }
    b[pos] = Cand{d, idx};
    if (n < k) ++n;
}

inline void stage_query(const Knn *h, const float *X, uint64_t q,
                        uint32_t F, double *xq, double *qsq) {
    double s = 0.0;
    for (uint32_t f = 0; f < h->F; ++f) {
        xq[f] = double(X[q * F + f]);
        s += xq[f] * xq[f];
    }
    *qsq = s;
}

// Exact f64 member scan over a permuted-column range [m0, m1): the
// ascending-f accumulation (bitwise-identical addend order to the
// unpruned path) with the per-feature early abandon against the LIVE
// k-th best distance. The workhorse of the seed lists, the n<k phase,
// and every screen survivor.
inline void refine_range(const Knn *h, const uint32_t *orig,
                         const double *xq, uint32_t m0, uint32_t m1,
                         Cand *b, uint32_t &n, uint64_t *aband) {
    const uint32_t F = h->F, k = h->k;
    for (uint32_t m = m0; m < m1; ++m) {
        // row-major access: one member = one contiguous 96-byte row
        // (the column layout would cost a cache line PER FEATURE here)
        const uint32_t si = orig[m];
        const double *row = h->rows.data() + size_t(si) * F;
        double d = 0.0;
        bool dead = false;
        for (uint32_t f = 0; f < F; ++f) {
            const double diff = xq[f] - row[f];
            d += diff * diff;
            if (n == k && d > b[k - 1].d && f + 1 < F) {
                dead = true;  // early abandon: nonneg addends only grow
                break;        // the partial sum
            }
        }
        if (dead) {
            ++*aband;
            continue;
        }
        push_cand(b, n, k, d, si);
    }
}

// One screen survivor's exact distance — the scalar ascending-f chain
// with the per-feature early abandon against the LIVE bound.
inline void refine_member(const Knn *h, const Chunks &C,
                          const double *xq, uint32_t m, Cand *b,
                          uint32_t &n, uint64_t *aband) {
    refine_range(h, C.orig.data(), xq, m, m + 1, b, n, aband);
}

// Exact f64 distances for a WHOLE chunk, f-streamed over the
// list-contiguous f64 columns — elementwise ascending-f accumulation,
// so every sum is bitwise-identical to the scalar chain (and to the
// unpruned path), with the FMA latency hidden across the chunk's
// members. Used by the seed chunks and the still-filling phase.
inline void refine_chunk_blocked(const Knn *h, const Chunks &C,
                                 const double *xq, uint32_t c, Cand *b,
                                 uint32_t &n, double *accd) {
    const uint32_t F = h->F, k = h->k, SP = C.spad;
    const uint32_t m0 = c * kEChunk;
    const uint32_t L = kEChunk;
    std::memset(accd, 0, L * sizeof(double));
    for (uint32_t f = 0; f < F; ++f) {
        const double x = xq[f];
        const double *col = C.cols64.data() + size_t(f) * SP + m0;
        for (uint32_t j = 0; j < L; ++j) {
            const double diff = x - col[j];
            accd[j] += diff * diff;
        }
    }
    for (uint32_t j = 0; j < L; ++j)
        if (C.orig[m0 + j] != kSentinel)
            push_cand(b, n, k, accd[j], C.orig[m0 + j]);
}

// Ascending full scan, no pruning — the fallback for non-finite
// queries / non-prunable corpora, and the exactness reference the
// comparator-ordered scans must (and do) reproduce.
void knn_topk_full(const Knn *h, const double *xq, Cand *b, uint32_t &n) {
    const uint32_t F = h->F, S = h->S, k = h->k;
    for (uint32_t i = 0; i < S; ++i) {
        double d = 0.0;
        for (uint32_t f = 0; f < F; ++f) {
            const double d0 = xq[f] - h->rows[size_t(i) * F + f];
            d += d0 * d0;
        }
        push_cand(b, n, k, d, i);
    }
}

// Per-call scratch (allocated once per C call, shared across that
// call's query blocks; each call owns its own — no cross-thread state,
// so concurrent predicts stay race-free).
struct Scratch {
    std::vector<double> ad2;     // (QB, NC) f64 anchor distances
    std::vector<double> accd;    // (kChunk,) blocked-refine sums
    std::vector<float> acc32;    // (QB, kChunk) f32 screen distances
    std::vector<double> cd2;     // (K,) f64 anchor distances (IVF)
    std::vector<uint32_t> cord;  // (K,) probe order (IVF)
    Scratch(uint32_t nchunk, uint32_t K_ivf, uint32_t ivf_maxlist = 0)
        : ad2(size_t(kQueryBlock) * (nchunk ? nchunk : 1)),
          accd(std::max(kEChunk, ivf_maxlist ? ivf_maxlist : 1)),
          acc32(size_t(kQueryBlock) * kEChunk),
          cd2(K_ivf ? K_ivf : 1), cord(K_ivf ? K_ivf : 1) {}
};

inline void votes_from_best(const Knn *h, const Cand *b, uint32_t n,
                            uint32_t *v) {
    const uint32_t C = h->C;
    std::memset(v, 0, C * sizeof(uint32_t));
    for (uint32_t j = 0; j < n; ++j) {
        const int32_t lab = h->y[b[j].idx];
        if (lab >= 0 && uint32_t(lab) < C) ++v[lab];
    }
}

inline int32_t argmax_votes(const uint32_t *v, uint32_t C) {
    uint32_t argc = 0, bv = v[0];
    for (uint32_t c = 1; c < C; ++c)
        if (v[c] > bv) { bv = v[c]; argc = c; }  // first max wins
    return int32_t(argc);
}

// The 8-query blocked pruned exact engine (see the file header for the
// stages and the losslessness argument). votes: (QB, C).
void knn_votes_block(const Knn *h, const float *X, uint64_t q0,
                     uint32_t QB, uint32_t F, uint32_t *votes,
                     Scratch &s, uint64_t *scr, uint64_t *aband) {
    const Chunks &C = h->exact;
    const uint32_t NC = C.nchunk, k = h->k, SP = C.spad, Fh = h->F;
    double xq[kQueryBlock][32];
    float xf[kQueryBlock][32];
    Cand best[kQueryBlock][kMaxK];
    uint32_t n[kQueryBlock];
    bool blk[kQueryBlock];  // query runs through the block engine
    uint32_t nblk = 0;
    for (uint32_t q = 0; q < QB; ++q) {
        n[q] = 0;
        double qsq;
        stage_query(h, X, q0 + q, F, xq[q], &qsq);
        for (uint32_t f = 0; f < Fh; ++f)
            xf[q][f] = X[(q0 + q) * F + f];
        blk[q] = h->prunable && std::isfinite(qsq);
        if (blk[q]) {
            ++nblk;
        } else {
            knn_topk_full(h, xq[q], best[q], n[q]);
        }
    }
    if (nblk) {
        // --- stage 1: exact f64 anchor distances (NC is small) ----------
        double *ad2 = s.ad2.data();
        for (uint32_t q = 0; q < QB; ++q) {
            if (!blk[q]) continue;
            double *a = ad2 + size_t(q) * NC;
            std::memset(a, 0, NC * sizeof(double));
            for (uint32_t f = 0; f < Fh; ++f) {
                const double x = xq[q][f];
                const double *ac = C.anch_cols.data() + size_t(f) * NC;
                for (uint32_t c = 0; c < NC; ++c) {
                    const double diff = x - ac[c];
                    a[c] += diff * diff;
                }
            }
        }
        // --- stage 2: seed each query from its nearest chunk ------------
        uint32_t seed[kQueryBlock];
        for (uint32_t q = 0; q < QB; ++q) {
            if (!blk[q]) continue;
            const double *a = ad2 + size_t(q) * NC;
            uint32_t c0 = 0;
            for (uint32_t c = 1; c < NC; ++c)
                if (a[c] < a[c0]) c0 = c;
            seed[q] = c0;
            refine_chunk_blocked(h, C, xq[q], c0, best[q], n[q],
                                 s.accd.data());
        }
        // --- stage 3: one sweep, shared f32 screen ----------------------
        double sb[kQueryBlock], sb_at[kQueryBlock];
        for (uint32_t q = 0; q < QB; ++q) {
            sb[q] = 0.0;
            sb_at[q] = -1.0;  // cache invalid
        }
        uint32_t needs[kQueryBlock];
        for (uint32_t c = 0; c < NC; ++c) {
            const uint32_t m0 = c * kEChunk;
            const uint32_t L = kEChunk;
            const uint32_t nreal = C.nreal[c];
            uint32_t nneed = 0, nscreen = 0;
            for (uint32_t q = 0; q < QB; ++q) {
                if (!blk[q] || c == seed[q]) continue;
                if (n[q] == k) {
                    const double worst = b_worst(best[q], k);
                    if (worst != sb_at[q]) {
                        // inflate the radius so |dist| > sb implies
                        // dist²·deflate > bound even after fp rounding
                        sb_at[q] = worst;
                        sb[q] = std::sqrt(worst / kScreenDeflate);
                    }
                    const double cmin = C.cmin[c], cmax = C.cmax[c];
                    const double hi_edge = cmax + sb[q];
                    const double d2 = ad2[size_t(q) * NC + c];
                    if (d2 > hi_edge * hi_edge
                        || (cmin > sb[q]
                            && d2 < (cmin - sb[q]) * (cmin - sb[q]))) {
                        *scr += nreal;  // whole chunk provably rejected
                        continue;
                    }
                    ++nscreen;
                }
                needs[nneed++] = q;
            }
            if (!nneed) continue;
            // shared f32 screen for the bound-holding queries (skipped
            // for still-filling queries — they refine every member)
            if (nscreen) {
                float *acc = s.acc32.data();
                for (uint32_t t = 0; t < nneed; ++t)
                    if (n[needs[t]] == k)
                        std::memset(acc + size_t(needs[t]) * kEChunk, 0,
                                    L * sizeof(float));
                for (uint32_t f = 0; f < Fh; ++f) {
                    const float *col =
                        C.cols32.data() + size_t(f) * SP + m0;
                    for (uint32_t t = 0; t < nneed; ++t) {
                        const uint32_t q = needs[t];
                        if (n[q] != k) continue;
                        const float x = xf[q][f];
                        float *a = acc + size_t(q) * kEChunk;
                        for (uint32_t j = 0; j < L; ++j) {
                            const float diff = x - col[j];
                            a[j] += diff * diff;
                        }
                    }
                }
            }
            for (uint32_t t = 0; t < nneed; ++t) {
                const uint32_t q = needs[t];
                if (n[q] != k) {  // still filling: exact, no screen
                    refine_chunk_blocked(h, C, xq[q], c, best[q], n[q],
                                         s.accd.data());
                    continue;
                }
                const float *a = s.acc32.data() + size_t(q) * kEChunk;
                const float thr =
                    float(b_worst(best[q], k) * kScreenMargin32);
                float mn = a[0];  // vectorizable min-reduce: most
                for (uint32_t j = 1; j < L; ++j)  // chunks have no
                    mn = std::min(mn, a[j]);      // survivor at all
                if (mn > thr) {
                    *scr += nreal;
                    continue;
                }
                uint32_t kept = 0;
                for (uint32_t j = 0; j < L; ++j) {
                    if (a[j] > thr) continue;  // rare, predictable
                    ++kept;
                    refine_member(h, C, xq[q], m0 + j, best[q], n[q],
                                  aband);
                }
                *scr += nreal - kept;
            }
        }
    }
    for (uint32_t q = 0; q < QB; ++q)
        votes_from_best(h, best[q], n[q], votes + size_t(q) * h->C);
}


// IVF probe: one query's votes over its nprobe nearest lists. Centroid
// ranking is exact f64 over the rounded anchors with (distance,
// centroid index) order; members pay the triangle screen + exact
// refine. nprobe >= K is the exact search (comparator order, every
// list scanned once — the corpus is a partition of the lists).
void knn_votes_ivf_one(const Knn *h, const float *X, uint64_t q,
                       uint32_t F, uint32_t nprobe, uint32_t *v,
                       Scratch &s, uint64_t *scr, uint64_t *aband) {
    (void)aband;  // the blocked probe refine has no scalar abandon
    const Coarse &C = h->ivf;
    const uint32_t K = C.K, k = h->k, Fh = h->F;
    double xq[32];
    double qsq;
    stage_query(h, X, q, F, xq, &qsq);
    Cand best[kMaxK];
    uint32_t n = 0;
    if (!h->prunable || !std::isfinite(qsq)) {
        // geometry is meaningless — serve the exact full scan (a
        // superset of any probe set, so still deterministic)
        knn_topk_full(h, xq, best, n);
        votes_from_best(h, best, n, v);
        return;
    }
    double *cd2 = s.cd2.data();
    std::memset(cd2, 0, K * sizeof(double));
    for (uint32_t f = 0; f < Fh; ++f) {
        const double x = xq[f];
        const double *cc = C.cent_cols.data() + size_t(f) * K;
        for (uint32_t c = 0; c < K; ++c) {
            const double diff = x - cc[c];
            cd2[c] += diff * diff;
        }
    }
    uint32_t *cord = s.cord.data();
    std::iota(cord, cord + K, 0u);
    const uint32_t visit = nprobe < K ? nprobe : K;
    std::partial_sort(
        cord, cord + visit, cord + K, [&](uint32_t a, uint32_t bb) {
            return cd2[a] < cd2[bb] || (cd2[a] == cd2[bb] && a < bb);
        });
    double sb = 0.0, sb_at = -1.0;
    for (uint32_t i = 0; i < visit; ++i) {
        const uint32_t c = cord[i];
        const uint32_t m0 = C.off[c], m1 = C.off[c + 1];
        if (m0 == m1) continue;
        if (n == k) {
            if (best[k - 1].d != sb_at) {
                sb_at = best[k - 1].d;
                sb = std::sqrt(sb_at / kScreenDeflate);
            }
            const double cmin = C.cmin[c], cmax = C.cmax[c];
            const double hi_edge = cmax + sb;
            if (cd2[c] > hi_edge * hi_edge
                || (cmin > sb && cd2[c] < (cmin - sb) * (cmin - sb))) {
                *scr += m1 - m0;
                continue;
            }
        }
        // blocked exact refine of the probed list: f-streamed f64
        // accumulation (elementwise ascending-f — bitwise-identical to
        // the scalar chain), FMA latency hidden across members
        double *accd = s.accd.data();
        const uint32_t L = m1 - m0;
        std::memset(accd, 0, L * sizeof(double));
        for (uint32_t f = 0; f < Fh; ++f) {
            const double x = xq[f];
            const double *col = C.cols64.data() + size_t(f) * h->S + m0;
            for (uint32_t j = 0; j < L; ++j) {
                const double diff = x - col[j];
                accd[j] += diff * diff;
            }
        }
        for (uint32_t j = 0; j < L; ++j)
            push_cand(best, n, k, accd[j], C.orig[m0 + j]);
    }
    votes_from_best(h, best, n, v);
}

// Populate a Coarse index from centroids (f64 (K, F) row-major, rounded
// to the f32 anchors in here) and per-point assignments. Lists are
// contiguous, members in ascending original-index order — a
// deterministic layout the result order never depends on (the candidate
// comparator owns tie order).
void build_coarse(const Knn *h, Coarse &C, uint32_t K,
                  const std::vector<double> &centers,
                  const std::vector<uint32_t> &assign) {
    const uint32_t S = h->S, F = h->F;
    C.K = K;
    C.cent_cols.assign(size_t(F) * K, 0.0);
    for (uint32_t c = 0; c < K; ++c)
        for (uint32_t f = 0; f < F; ++f)
            C.cent_cols[size_t(f) * K + c] =
                double(float(centers[size_t(c) * F + f]));
    C.off.assign(K + 1, 0);
    for (uint32_t s = 0; s < S; ++s) ++C.off[assign[s] + 1];
    for (uint32_t c = 0; c < K; ++c) C.off[c + 1] += C.off[c];
    C.orig.resize(S);
    std::vector<uint32_t> cursor(C.off.begin(), C.off.end() - 1);
    for (uint32_t s = 0; s < S; ++s)  // ascending s → ascending per list
        C.orig[cursor[assign[s]]++] = s;
    C.cmin.assign(K, 0.0);
    C.cmax.assign(K, 0.0);
    C.cols64.resize(size_t(F) * S);
    C.max_list = 0;
    for (uint32_t c = 0; c < K; ++c) {
        C.max_list = std::max(C.max_list, C.off[c + 1] - C.off[c]);
        for (uint32_t m = C.off[c]; m < C.off[c + 1]; ++m) {
            const uint32_t s = C.orig[m];
            double sq = 0.0;
            for (uint32_t f = 0; f < F; ++f) {
                const double v = h->rows[size_t(s) * F + f];
                C.cols64[size_t(f) * S + m] = v;
                // member-anchor distances measure to the ROUNDED
                // centroid — the point the triangle bounds anchor on
                const double diff = v - C.cent_cols[size_t(f) * K + c];
                sq += diff * diff;
            }
            const double d = std::sqrt(sq);
            if (m == C.off[c] || d < C.cmin[c]) C.cmin[c] = d;
            if (m == C.off[c] || d > C.cmax[c]) C.cmax[c] = d;
        }
    }
}

// Fixed-seed Lloyd clustering for the exact tier's internal index:
// deterministic (spread init over the corpus order, kLloydIters
// iterations, fixed summation order). Quality only affects pruning
// power, never results.
void build_exact_index(Knn *h) {
    const uint32_t S = h->S, F = h->F;
    uint32_t K = S / 16;  // small lists: strong whole-list skips, cheap
                          // shared screens (tuned on the bench corpus)
    if (K < 1) K = 1;
    if (K > S) K = S;
    std::vector<double> centers(size_t(K) * F);
    for (uint32_t c = 0; c < K; ++c) {
        const uint32_t s = uint32_t((uint64_t(c) * S) / K);
        for (uint32_t f = 0; f < F; ++f)
            centers[size_t(c) * F + f] = h->rows[size_t(s) * F + f];
    }
    std::vector<uint32_t> assign(S, 0);
    std::vector<double> sums(size_t(K) * F);
    std::vector<uint32_t> counts(K);
    for (uint32_t it = 0; it < kLloydIters; ++it) {
        for (uint32_t s = 0; s < S; ++s) {
            const double *row = h->rows.data() + size_t(s) * F;
            double bd = 0.0;
            uint32_t bc = 0;
            for (uint32_t c = 0; c < K; ++c) {
                double d = 0.0;
                const double *ce = centers.data() + size_t(c) * F;
                for (uint32_t f = 0; f < F; ++f) {
                    const double diff = row[f] - ce[f];
                    d += diff * diff;
                }
                if (c == 0 || d < bd) { bd = d; bc = c; }
            }
            assign[s] = bc;
        }
        std::fill(sums.begin(), sums.end(), 0.0);
        std::fill(counts.begin(), counts.end(), 0u);
        for (uint32_t s = 0; s < S; ++s) {
            double *acc = sums.data() + size_t(assign[s]) * F;
            const double *row = h->rows.data() + size_t(s) * F;
            for (uint32_t f = 0; f < F; ++f) acc[f] += row[f];
            ++counts[assign[s]];
        }
        for (uint32_t c = 0; c < K; ++c)
            if (counts[c])  // empty cluster: keep the previous center
                for (uint32_t f = 0; f < F; ++f)
                    centers[size_t(c) * F + f] =
                        sums[size_t(c) * F + f] / double(counts[c]);
    }
    // lay the corpus out in cluster order, each list split into
    // kEChunk-wide chunks padded with sentinel members (kSentinelVal
    // columns — rejected by every screen and every exact compare), so
    // chunk geometry is LIST geometry: tight anchors, firing skips
    std::vector<uint32_t> off(K + 1, 0);
    for (uint32_t s = 0; s < S; ++s) ++off[assign[s] + 1];
    for (uint32_t c = 0; c < K; ++c) off[c + 1] += off[c];
    std::vector<uint32_t> order(S);
    {
        std::vector<uint32_t> cursor(off.begin(), off.end() - 1);
        for (uint32_t s = 0; s < S; ++s)
            order[cursor[assign[s]]++] = s;
    }
    uint32_t NC = 0;
    for (uint32_t c = 0; c < K; ++c)
        NC += (off[c + 1] - off[c] + kEChunk - 1) / kEChunk;
    Chunks &C = h->exact;
    C.nchunk = NC;
    C.spad = NC * kEChunk;
    C.orig.assign(C.spad, kSentinel);
    C.nreal.assign(NC, 0);
    C.cols32.assign(size_t(F) * C.spad, float(kSentinelVal));
    C.cols64.assign(size_t(F) * C.spad, kSentinelVal);
    C.anch_cols.assign(size_t(F) * NC, 0.0);
    C.cmin.assign(NC, 0.0);
    C.cmax.assign(NC, 0.0);
    std::vector<double> mean(F);
    uint32_t chunk = 0;
    for (uint32_t c = 0; c < K; ++c) {
        for (uint32_t base = off[c]; base < off[c + 1];
             base += kEChunk, ++chunk) {
            const uint32_t nreal =
                std::min(kEChunk, off[c + 1] - base);
            C.nreal[chunk] = nreal;
            const uint32_t m0 = chunk * kEChunk;
            std::fill(mean.begin(), mean.end(), 0.0);
            for (uint32_t j = 0; j < nreal; ++j) {
                const uint32_t s = order[base + j];
                C.orig[m0 + j] = s;
                for (uint32_t f = 0; f < F; ++f) {
                    const double v = h->rows[size_t(s) * F + f];
                    C.cols64[size_t(f) * C.spad + m0 + j] = v;
                    C.cols32[size_t(f) * C.spad + m0 + j] = float(v);
                    mean[f] += v;
                }
            }
            // anchor: the rounded f32 mean of the REAL members — a
            // concrete point, so the triangle bound is exact
            for (uint32_t f = 0; f < F; ++f)
                C.anch_cols[size_t(f) * NC + chunk] =
                    double(float(mean[f] / double(nreal)));
            for (uint32_t j = 0; j < nreal; ++j) {
                double sq = 0.0;
                for (uint32_t f = 0; f < F; ++f) {
                    const double diff =
                        C.cols64[size_t(f) * C.spad + m0 + j]
                        - C.anch_cols[size_t(f) * NC + chunk];
                    sq += diff * diff;
                }
                const double d = std::sqrt(sq);
                if (j == 0 || d < C.cmin[chunk]) C.cmin[chunk] = d;
                if (j == 0 || d > C.cmax[chunk]) C.cmax[chunk] = d;
            }
        }
    }
}

// ---- unpruned baseline (the original blocked evaluator) -------------------

// One query block's k-nearest vote counts — 8-query blocks × 256-row
// corpus chunks, per-feature streaming accumulation (prefetch-friendly;
// a register-blocked 12-stream variant measured 3× SLOWER here).
// Elementwise, no cross-lane reduction — vectorizes exactly without
// -ffast-math, f-order fixed per element. Candidate fold: ascending
// corpus index; a candidate EQUAL to the incumbent worst is rejected,
// so earlier indices win ties — the lax.top_k total order.
void knn_votes_range_unpruned(const Knn *h, const float *X, uint64_t q0,
                              uint32_t QB, uint32_t F, uint32_t *votes) {
    const uint32_t S = h->S, C = h->C, k = h->k;
    double acc[kQueryBlock][kChunk];
    double xq[kQueryBlock][32];
    Cand best[kQueryBlock][kMaxK];
    uint32_t nbest[kQueryBlock];
    for (uint32_t q = 0; q < QB; ++q) nbest[q] = 0;
    for (uint32_t q = 0; q < QB; ++q)
        for (uint32_t f = 0; f < h->F; ++f)
            xq[q][f] = double(X[(q0 + q) * F + f]);
    for (uint32_t c0 = 0; c0 < S; c0 += kChunk) {
        const uint32_t CH = (S - c0 < kChunk) ? (S - c0) : kChunk;
        for (uint32_t q = 0; q < QB; ++q)
            std::memset(acc[q], 0, CH * sizeof(double));
        for (uint32_t f = 0; f < h->F; ++f) {
            const double *col = h->cols.data() + size_t(f) * S + c0;
            for (uint32_t q = 0; q < QB; ++q) {
                const double x = xq[q][f];
                double *a = acc[q];
                for (uint32_t i = 0; i < CH; ++i) {
                    const double diff = x - col[i];
                    a[i] += diff * diff;
                }
            }
        }
        for (uint32_t q = 0; q < QB; ++q) {
            Cand *b = best[q];
            uint32_t n = nbest[q];
            const double *a = acc[q];
            for (uint32_t i = 0; i < CH; ++i) {
                const double d = a[i];
                if (n == k && !(d < b[k - 1].d)) continue;
                uint32_t pos = (n < k) ? n : k - 1;
                while (pos > 0 && b[pos - 1].d > d) {
                    b[pos] = b[pos - 1];
                    --pos;
                }
                b[pos] = Cand{d, c0 + i};
                if (n < k) nbest[q] = ++n;
            }
        }
    }
    for (uint32_t q = 0; q < QB; ++q)
        votes_from_best(h, best[q], k, votes + size_t(q) * C);
}

}  // namespace

extern "C" {

void *tck_create(uint32_t S, uint32_t F, uint32_t C, uint32_t k,
                 const float *fit_X, const int32_t *fit_y) {
    if (S == 0 || F == 0 || F > 32 || C == 0 || k == 0 || k > kMaxK
        || S < k)
        return nullptr;  // F cap matches the query staging buffer
    Knn *h = new Knn();
    h->S = S;
    h->F = F;
    h->C = C;
    h->k = k;
    h->cols.resize(size_t(F) * S);
    h->rows.resize(size_t(S) * F);
    bool finite = true;
    for (uint32_t s = 0; s < S; ++s) {
        for (uint32_t f = 0; f < F; ++f) {
            const double v = double(fit_X[size_t(s) * F + f]);
            h->cols[size_t(f) * S + s] = v;
            h->rows[size_t(s) * F + f] = v;
            if (!std::isfinite(v)) finite = false;
        }
    }
    h->y.assign(fit_y, fit_y + S);
    // cluster geometry (and the triangle bounds built on it) is only
    // meaningful over a finite corpus; otherwise every query takes the
    // ascending full-scan fallback
    h->prunable = finite;
    if (h->prunable) build_exact_index(h);
    return h;
}

void tck_destroy(void *h) { delete static_cast<Knn *>(h); }

// X: (N, F) float32 row-major; out: (N,) int32 class indices — the
// PRUNED exact path (vote-for-vote identical to tck_predict_unpruned).
void tck_predict(void *hp, const float *X, uint64_t N, uint32_t F,
                 int32_t *out) {
    Knn *h = static_cast<Knn *>(hp);
    const uint32_t C = h->C;
    std::vector<uint32_t> votes(size_t(kQueryBlock) * C);
    Scratch s(h->exact.nchunk, 0);
    uint64_t scr = 0, aband = 0;
    for (uint64_t q0 = 0; q0 < N; q0 += kQueryBlock) {
        const uint32_t QB =
            uint32_t(N - q0 < kQueryBlock ? N - q0 : kQueryBlock);
        knn_votes_block(h, X, q0, QB, F, votes.data(), s, &scr, &aband);
        for (uint32_t q = 0; q < QB; ++q)
            out[q0 + q] = argmax_votes(votes.data() + size_t(q) * C, C);
    }
    h->screened.fetch_add(scr, std::memory_order_relaxed);
    h->abandoned.fetch_add(aband, std::memory_order_relaxed);
    h->queries.fetch_add(N, std::memory_order_relaxed);
}

// X: (N, F) float32 row-major; out: (N, C) int32 neighbor vote counts
// — the score surface (argmax with first-max ties == tck_predict).
void tck_votes(void *hp, const float *X, uint64_t N, uint32_t F,
               int32_t *out) {
    Knn *h = static_cast<Knn *>(hp);
    const uint32_t C = h->C;
    std::vector<uint32_t> votes(size_t(kQueryBlock) * C);
    Scratch s(h->exact.nchunk, 0);
    uint64_t scr = 0, aband = 0;
    for (uint64_t q0 = 0; q0 < N; q0 += kQueryBlock) {
        const uint32_t QB =
            uint32_t(N - q0 < kQueryBlock ? N - q0 : kQueryBlock);
        knn_votes_block(h, X, q0, QB, F, votes.data(), s, &scr, &aband);
        for (uint32_t q = 0; q < QB; ++q)
            for (uint32_t c = 0; c < C; ++c)
                out[(q0 + q) * C + c] =
                    int32_t(votes[size_t(q) * C + c]);
    }
    h->screened.fetch_add(scr, std::memory_order_relaxed);
    h->abandoned.fetch_add(aband, std::memory_order_relaxed);
    h->queries.fetch_add(N, std::memory_order_relaxed);
}

// The original blocked full-scan evaluator — the same-run A/B baseline
// (docs/artifacts/knn_prune_cpu.json) and the parity oracle.
void tck_predict_unpruned(void *hp, const float *X, uint64_t N,
                          uint32_t F, int32_t *out) {
    const Knn *h = static_cast<const Knn *>(hp);
    const uint32_t C = h->C;
    std::vector<uint32_t> votes(size_t(kQueryBlock) * C);
    for (uint64_t q0 = 0; q0 < N; q0 += kQueryBlock) {
        const uint32_t QB =
            uint32_t(N - q0 < kQueryBlock ? N - q0 : kQueryBlock);
        knn_votes_range_unpruned(h, X, q0, QB, F, votes.data());
        for (uint32_t q = 0; q < QB; ++q)
            out[q0 + q] = argmax_votes(votes.data() + size_t(q) * C, C);
    }
}

void tck_votes_unpruned(void *hp, const float *X, uint64_t N, uint32_t F,
                        int32_t *out) {
    const Knn *h = static_cast<const Knn *>(hp);
    const uint32_t C = h->C;
    std::vector<uint32_t> votes(size_t(kQueryBlock) * C);
    for (uint64_t q0 = 0; q0 < N; q0 += kQueryBlock) {
        const uint32_t QB =
            uint32_t(N - q0 < kQueryBlock ? N - q0 : kQueryBlock);
        knn_votes_range_unpruned(h, X, q0, QB, F, votes.data());
        for (uint32_t q = 0; q < QB; ++q)
            for (uint32_t c = 0; c < C; ++c)
                out[(q0 + q) * C + c] =
                    int32_t(votes[size_t(q) * C + c]);
    }
}

// Install the IVF coarse index: centers (K, F) float32 row-major,
// assign (S,) int32 in [0, K). Returns 0 on success. NOT thread-safe
// against concurrent predicts — build before serving.
int32_t tck_ivf_build(void *hp, uint32_t K, const float *centers,
                      const int32_t *assign) {
    Knn *h = static_cast<Knn *>(hp);
    if (K == 0 || K > kMaxIvfLists) return 1;
    for (uint32_t s = 0; s < h->S; ++s)
        if (assign[s] < 0 || uint32_t(assign[s]) >= K) return 2;
    std::vector<double> cents(size_t(K) * h->F);
    for (size_t i = 0; i < cents.size(); ++i)
        cents[i] = double(centers[i]);
    std::vector<uint32_t> a(h->S);
    for (uint32_t s = 0; s < h->S; ++s) a[s] = uint32_t(assign[s]);
    build_coarse(h, h->ivf, K, cents, a);
    return 0;
}

// IVF predict/votes: nprobe nearest lists only (clamped to K). Returns
// without writing when no index is built — callers gate on
// tck_ivf_build's 0 return.
void tck_predict_ivf(void *hp, const float *X, uint64_t N, uint32_t F,
                     uint32_t nprobe, int32_t *out) {
    Knn *h = static_cast<Knn *>(hp);
    if (h->ivf.K == 0 || nprobe == 0) return;
    const uint32_t C = h->C;
    std::vector<uint32_t> v(C);
    Scratch s(0, h->ivf.K, h->ivf.max_list);
    uint64_t scr = 0, aband = 0;
    for (uint64_t q = 0; q < N; ++q) {
        knn_votes_ivf_one(h, X, q, F, nprobe, v.data(), s, &scr,
                          &aband);
        out[q] = argmax_votes(v.data(), C);
    }
    h->screened.fetch_add(scr, std::memory_order_relaxed);
    h->abandoned.fetch_add(aband, std::memory_order_relaxed);
    h->queries.fetch_add(N, std::memory_order_relaxed);
}

void tck_votes_ivf(void *hp, const float *X, uint64_t N, uint32_t F,
                   uint32_t nprobe, int32_t *out) {
    Knn *h = static_cast<Knn *>(hp);
    if (h->ivf.K == 0 || nprobe == 0) return;
    const uint32_t C = h->C;
    std::vector<uint32_t> v(C);
    Scratch s(0, h->ivf.K, h->ivf.max_list);
    uint64_t scr = 0, aband = 0;
    for (uint64_t q = 0; q < N; ++q) {
        knn_votes_ivf_one(h, X, q, F, nprobe, v.data(), s, &scr,
                          &aband);
        for (uint32_t c = 0; c < C; ++c)
            out[q * C + c] = int32_t(v[c]);
    }
    h->screened.fetch_add(scr, std::memory_order_relaxed);
    h->abandoned.fetch_add(aband, std::memory_order_relaxed);
    h->queries.fetch_add(N, std::memory_order_relaxed);
}

// Cumulative screen accounting: out[0]=screened (triangle/f32-bound
// skips), out[1]=abandoned (partial-distance early exits),
// out[2]=queries.
void tck_screen_stats(void *hp, uint64_t *out) {
    const Knn *h = static_cast<const Knn *>(hp);
    out[0] = h->screened.load(std::memory_order_relaxed);
    out[1] = h->abandoned.load(std::memory_order_relaxed);
    out[2] = h->queries.load(std::memory_order_relaxed);
}

}  // extern "C"
