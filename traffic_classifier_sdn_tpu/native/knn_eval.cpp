// Native (host-spine) brute-force KNN evaluator.
//
// The reference's KNN walks one KDTree per query on one CPU
// (models/KNeighbors checkpoint, loaded at traffic_classifier.py:234-236);
// the framework's XLA paths (models/knn.py) rank by an f32 dot-expansion
// similarity on device. This evaluator is the accelerator-less host
// entrant: exact float64 squared distances, GEMM-style blocking so the
// corpus streams from cache once per QUERY BLOCK instead of once per
// query, and the per-element loops autovectorize (AVX2/AVX512 on the
// bench host — built with -march=native) without -ffast-math, keeping
// the accumulation order fixed and deterministic:
//
//   for each query block (8 rows) × corpus chunk (256 rows):
//       acc[q][i] += (x[q][f] - col[f][i])²   for f = 0..F-1 in order
//
// Candidate order is (distance asc, corpus index asc) — the same total
// order lax.top_k produces over the similarity row — maintained by a
// k-element insertion list that rejects ties with the incumbent (the
// earlier corpus index wins, scanned in ascending index order). The vote
// is class counts over the k neighbors with first-maximum argmax,
// mirroring models/knn.neighbor_votes → argmax.
//
// Numerics vs the XLA fast path: f64 diff-square is strictly more
// accurate than the f32 dot-expansion; orderings agree everywhere the
// f32 rounding does not create or break a near-tie (exact on the
// integer-valued adversarial tie suites, and label parity is gated on
// the full reference corpus before any promotion — the same bar every
// raced kernel passes).
//
// Plain C ABI for ctypes (no pybind11 in this image) — same pattern as
// flow_engine.cpp / forest_eval.cpp.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t kQueryBlock = 8;
constexpr uint32_t kChunk = 256;
constexpr uint32_t kMaxK = 64;

struct Knn {
    uint32_t S, F, C, k;
    std::vector<double> cols;   // (F, S) column-major corpus, f64
    std::vector<int32_t> y;     // (S,)
};

struct Cand {
    double d;
    uint32_t idx;
};

}  // namespace

namespace {

// One query block's k-nearest vote counts — the shared core of
// tck_predict (argmax tail) and tck_votes (raw (N, C) exposure for the
// open-set / degrade-rung score surface). Vote semantics unchanged:
// class counts over the k nearest, candidate order (distance asc,
// corpus index asc).
void knn_votes_range(const Knn *h, const float *X, uint64_t q0,
                     uint32_t QB, uint32_t F, uint32_t *votes) {
    const uint32_t S = h->S, C = h->C, k = h->k;
    double acc[kQueryBlock][kChunk];
    double xq[kQueryBlock][32];
    Cand best[kQueryBlock][kMaxK];
    uint32_t nbest[kQueryBlock];
    for (uint32_t q = 0; q < QB; ++q) nbest[q] = 0;
    for (uint32_t q = 0; q < QB; ++q)
        for (uint32_t f = 0; f < h->F; ++f)
            xq[q][f] = double(X[(q0 + q) * F + f]);
    for (uint32_t c0 = 0; c0 < S; c0 += kChunk) {
        const uint32_t CH = (S - c0 < kChunk) ? (S - c0) : kChunk;
        for (uint32_t q = 0; q < QB; ++q)
            std::memset(acc[q], 0, CH * sizeof(double));
        // per-feature streaming accumulation: each column chunk is
        // one contiguous run (prefetch-friendly; a register-blocked
        // 12-stream variant measured 3× SLOWER here). Elementwise,
        // no cross-lane reduction — vectorizes exactly without
        // -ffast-math, f-order fixed per element.
        for (uint32_t f = 0; f < h->F; ++f) {
            const double *col = h->cols.data() + size_t(f) * S + c0;
            for (uint32_t q = 0; q < QB; ++q) {
                const double x = xq[q][f];
                double *a = acc[q];
                for (uint32_t i = 0; i < CH; ++i) {
                    const double diff = x - col[i];
                    a[i] += diff * diff;
                }
            }
        }
        // per query: fold this chunk into the running top-k.
        // Ascending corpus index; a candidate EQUAL to the incumbent
        // worst is rejected, so earlier indices win ties — the
        // lax.top_k total order (value desc == distance asc, then
        // index asc)
        for (uint32_t q = 0; q < QB; ++q) {
            Cand *b = best[q];
            uint32_t n = nbest[q];
            const double *a = acc[q];
            for (uint32_t i = 0; i < CH; ++i) {
                const double d = a[i];
                if (n == k && !(d < b[k - 1].d)) continue;
                // insert (d, c0+i) keeping (d asc, idx asc); equal
                // distances: the new (larger) index goes AFTER
                uint32_t pos = (n < k) ? n : k - 1;
                while (pos > 0 && b[pos - 1].d > d) {
                    b[pos] = b[pos - 1];
                    --pos;
                }
                b[pos] = {d, c0 + i};
                if (n < k) nbest[q] = ++n;
            }
        }
    }
    for (uint32_t q = 0; q < QB; ++q) {
        uint32_t *v = votes + size_t(q) * C;
        std::memset(v, 0, C * sizeof(uint32_t));
        for (uint32_t j = 0; j < k; ++j) {
            const int32_t lab = h->y[best[q][j].idx];
            if (lab >= 0 && uint32_t(lab) < C) ++v[lab];
        }
    }
}

}  // namespace

extern "C" {

void *tck_create(uint32_t S, uint32_t F, uint32_t C, uint32_t k,
                 const float *fit_X, const int32_t *fit_y) {
    if (S == 0 || F == 0 || F > 32 || C == 0 || k == 0 || k > kMaxK
        || S < k)
        return nullptr;  // F cap matches the query staging buffer
    Knn *h = new Knn();
    h->S = S;
    h->F = F;
    h->C = C;
    h->k = k;
    h->cols.resize(size_t(F) * S);
    for (uint32_t f = 0; f < F; ++f)
        for (uint32_t s = 0; s < S; ++s)
            h->cols[size_t(f) * S + s] = double(fit_X[size_t(s) * F + f]);
    h->y.assign(fit_y, fit_y + S);
    return h;
}

void tck_destroy(void *h) { delete static_cast<Knn *>(h); }

// X: (N, F) float32 row-major; out: (N,) int32 class indices.
void tck_predict(void *hp, const float *X, uint64_t N, uint32_t F,
                 int32_t *out) {
    const Knn *h = static_cast<const Knn *>(hp);
    const uint32_t C = h->C;
    std::vector<uint32_t> votes(size_t(kQueryBlock) * C);
    for (uint64_t q0 = 0; q0 < N; q0 += kQueryBlock) {
        const uint32_t QB =
            uint32_t(N - q0 < kQueryBlock ? N - q0 : kQueryBlock);
        knn_votes_range(h, X, q0, QB, F, votes.data());
        for (uint32_t q = 0; q < QB; ++q) {
            const uint32_t *v = votes.data() + size_t(q) * C;
            uint32_t argc = 0, bv = v[0];
            for (uint32_t c = 1; c < C; ++c)
                if (v[c] > bv) { bv = v[c]; argc = c; }  // first max wins
            out[q0 + q] = int32_t(argc);
        }
    }
}

// X: (N, F) float32 row-major; out: (N, C) int32 neighbor vote counts
// — the score surface (argmax with first-max ties == tck_predict).
void tck_votes(void *hp, const float *X, uint64_t N, uint32_t F,
               int32_t *out) {
    const Knn *h = static_cast<const Knn *>(hp);
    const uint32_t C = h->C;
    std::vector<uint32_t> votes(size_t(kQueryBlock) * C);
    for (uint64_t q0 = 0; q0 < N; q0 += kQueryBlock) {
        const uint32_t QB =
            uint32_t(N - q0 < kQueryBlock ? N - q0 : kQueryBlock);
        knn_votes_range(h, X, q0, QB, F, votes.data());
        for (uint32_t q = 0; q < QB; ++q)
            for (uint32_t c = 0; c < C; ++c)
                out[(q0 + q) * C + c] =
                    int32_t(votes[size_t(q) * C + c]);
    }
}

}  // extern "C"
