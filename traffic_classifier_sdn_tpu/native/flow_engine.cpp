// Native host-side ingest engine: telemetry line parsing, flow indexing
// with direction folding, and packed update-batch assembly.
//
// This is the C++ replacement for the host-bound half of the reference's
// ingest loop (traffic_classifier.py:144-171): where the reference splits
// strings and mutates per-flow Python objects one line at a time, this
// engine consumes raw pipe bytes in bulk and emits packed arrays that the
// JAX layer scatters into the device-resident flow table
// (core/flow_table.py). All counter math stays on device; this code only
// decides where each record goes (slot, direction, create flag) — the
// same contract as ingest/batcher.py's FlowIndex + Batcher, which remain
// as the pure-Python fallback and behavioral oracle.
//
// Hot-path design (the serving loop budget is the monitor's 1 Hz poll
// cadence, simple_monitor_13.py:36, at 2^20 tracked flows ≈ 1M records
// per tick):
//   - flow keys are deterministic 64-bit fingerprints of
//     (datapath\0src\0dst) — same keying rule as the Python oracle's
//     protocol.stable_flow_key, different (much faster) mix; see the
//     fingerprint section below for the collision-equivalence argument —
//     held in an open-addressing table: no per-record string allocation,
//     no chained-bucket pointer chases
//   - parsing (tokenize, int parse, UTF-8 validate, fingerprint) is
//     side-effect-free per line, so large chunks are split at line
//     boundaries and parsed on worker threads when the host has cores to
//     spare; ROUTING stays sequential in original record order, so slot
//     assignment is identical to the single-threaded oracle
//   - on a single-core host the threaded path auto-degrades to inline
//     parsing (no thread overhead)
//
// Semantics mirrored from the Python batcher (and ultimately from the
// reference's key folding at traffic_classifier.py:157-165):
//   - a record keys on (datapath, eth_src, eth_dst); if that key is new
//     but the reversed key exists, the record is the reverse direction of
//     the existing flow
//   - per (slot, direction) a batch generation holds at most one create
//     row and one update row; a second same-direction update starts a new
//     generation (conflict_start=true), so flushing generations in order
//     reproduces the reference's sequential per-line semantics exactly.
//     Uniqueness is enforced per RUN (all generations between conflicts /
//     drains), so consumers may concatenate a whole run into one scatter
//   - table-full records are dropped and counted
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// 64-bit flow fingerprint — a wyhash-style 128-bit-multiply mix over
// dp\0src\0dst. Deterministic (fixed seed, stable across processes and
// runs — the property the reference's per-process-randomized ``hash()``
// lacks, SURVEY.md §2 defect list) and well-mixed, at ~10 ns per key where
// a cryptographic digest costs ~220 ns — fingerprinting is the ingest hot
// loop's largest single cost at 1M records/tick.
//
// The Python control plane (ingest/protocol.stable_flow_key) uses
// BLAKE2b-64 for the same key. The two paths never share a table, and
// routing behavior depends only on fingerprint hit/miss patterns, so
// native and Python routing agree except when either function collides:
// birthday probability ~(2^20)²/2 / 2^64 = 2^-25 at 2^20 live flows —
// the same order as the Python path's own BLAKE2b-64 collision
// acceptance (both are 64-bit fingerprints; only the mixing function
// differs). A collision merges two flows' counters — the identical
// failure mode the oracle already accepts.
// ---------------------------------------------------------------------------

inline uint64_t mum_mix(uint64_t a, uint64_t b) {
  __uint128_t r = static_cast<__uint128_t>(a) * b;
  return static_cast<uint64_t>(r) ^ static_cast<uint64_t>(r >> 64);
}

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

inline uint64_t load_partial(const uint8_t* p, size_t n) {
  uint64_t v = 0;
  std::memcpy(&v, p, n);  // little-endian host assumed (x86/ARM LE)
  return v;
}

constexpr uint64_t kSeed0 = 0xa0761d6478bd642fULL;
constexpr uint64_t kSeed1 = 0xe7037ed1a0b428dbULL;
constexpr uint64_t kSeed2 = 0x8ebc6af09c88c6e3ULL;

uint64_t hash_bytes(const uint8_t* s, size_t len) {
  uint64_t h = kSeed0 ^ mum_mix(len, kSeed1);
  size_t i = 0;
  for (; i + 16 <= len; i += 16) {
    h = mum_mix(load64(s + i) ^ kSeed1, load64(s + i + 8) ^ h);
  }
  uint64_t a = 0, b = 0;
  size_t rem = len - i;
  if (rem > 8) {
    a = load64(s + i);
    b = load_partial(s + i + 8, rem - 8);
  } else if (rem > 0) {
    a = load_partial(s + i, rem);
  }
  return mum_mix(kSeed2 ^ a, h ^ b);
}

// Fingerprint of dp\0src\0dst (the \0 separators carry the same
// anti-ambiguity rule as protocol.stable_flow_key: 'ab'+'c' must not
// collide with 'a'+'bc'). A nonzero ``source`` appends \0 + the 4-byte
// little-endian source id — the fan-in tier's per-source namespace,
// mirroring stable_flow_key(source=): source 0 hashes the exact legacy
// byte string, so pre-fan-in checkpoints restore into the default
// namespace unchanged, and N sources reporting the same flow tuple
// occupy N disjoint slots.
uint64_t flow_fingerprint(const char* dp, size_t dpl, const char* src,
                          size_t sl, const char* dst, size_t dl,
                          uint32_t source) {
  const size_t total = dpl + sl + dl + 2 + (source != 0 ? 5 : 0);
  uint8_t stackbuf[512];
  std::vector<uint8_t> heapbuf;
  uint8_t* buf = stackbuf;
  if (total > sizeof(stackbuf)) {
    heapbuf.resize(total);
    buf = heapbuf.data();
  }
  std::memcpy(buf, dp, dpl);
  buf[dpl] = 0;
  std::memcpy(buf + dpl + 1, src, sl);
  buf[dpl + 1 + sl] = 0;
  std::memcpy(buf + dpl + 2 + sl, dst, dl);
  if (source != 0) {
    size_t o = dpl + 2 + sl + dl;
    buf[o] = 0;
    std::memcpy(buf + o + 1, &source, 4);  // little-endian host assumed
  }
  return hash_bytes(buf, total);
}

// ---------------------------------------------------------------------------
// Open-addressing fingerprint → slot map (linear probing, tombstones).
// The mum_mix fingerprint above is well-mixed across all 64 bits, so the
// fingerprint itself serves as the probe hash (no re-hash).
// ---------------------------------------------------------------------------

constexpr uint32_t kEmpty = 0xFFFFFFFFu;
constexpr uint32_t kTomb = 0xFFFFFFFEu;

struct FpMap {
  // Parallel keys[]/vals[] arrays, NOT interleaved 16-byte entries: an
  // interleave was tried (round 4) and measured ~10% SLOWER — probing
  // scans vals only (16 per line vs 4 entries per line), and the
  // route_block prefetch already covers both arrays' lines.
  std::vector<uint64_t> keys;
  std::vector<uint32_t> vals;
  size_t mask = 0;
  size_t used = 0;    // live entries
  size_t filled = 0;  // live + tombstones

  explicit FpMap(size_t initial = 1024) { reset(initial); }

  void reset(size_t cap) {
    size_t n = 16;
    while (n < cap) n <<= 1;
    keys.assign(n, 0);
    vals.assign(n, kEmpty);
    mask = n - 1;
    used = filled = 0;
  }

  uint32_t* find(uint64_t k) {
    size_t i = k & mask;
    while (true) {
      uint32_t v = vals[i];
      if (v == kEmpty) return nullptr;
      if (v != kTomb && keys[i] == k) return &vals[i];
      i = (i + 1) & mask;
    }
  }

  void grow() {
    std::vector<uint64_t> ok = std::move(keys);
    std::vector<uint32_t> ov = std::move(vals);
    size_t n = (used * 4 >= (mask + 1)) ? (mask + 1) * 2 : (mask + 1);
    keys.assign(n, 0);
    vals.assign(n, kEmpty);
    mask = n - 1;
    filled = used;
    for (size_t j = 0; j < ov.size(); j++) {
      if (ov[j] == kEmpty || ov[j] == kTomb) continue;
      size_t i = ok[j] & mask;
      while (vals[i] != kEmpty) i = (i + 1) & mask;
      keys[i] = ok[j];
      vals[i] = ov[j];
    }
  }

  void insert(uint64_t k, uint32_t v) {
    if ((filled + 1) * 2 >= mask + 1) grow();  // ≤50% load incl tombstones
    size_t i = k & mask;
    while (vals[i] != kEmpty && vals[i] != kTomb) i = (i + 1) & mask;
    if (vals[i] == kEmpty) filled++;
    keys[i] = k;
    vals[i] = v;
    used++;
  }

  void erase(uint64_t k) {
    uint32_t* p = find(k);
    if (p != nullptr) {
      *p = kTomb;
      used--;
    }
  }
};

// ---------------------------------------------------------------------------
// Engine
// ---------------------------------------------------------------------------

struct Row {
  uint32_t slot;
  int32_t time;
  uint64_t pkts;
  uint64_t bytes;
  uint8_t is_fwd;
  uint8_t is_create;
};

// One flush unit. The per-(slot,dir) occupancy that enforces the
// one-create-plus-one-update-per-direction limit lives in the Engine as
// an epoch-stamped flat array (occ_epoch/occ_bits) scoped to the RUN
// (see Engine) — only the newest generation ever accepts rows, and a
// bump of run_seq invalidates the whole array in O(1) instead of
// clearing.
struct Generation {
  std::vector<Row> rows;
  // True iff this generation was STARTED because a (slot, direction,
  // create/update) key already occupied the previous generation — the
  // flush consumer must then apply it in a separate scatter (duplicate
  // target rows in one scatter are undefined). Size-rollover generations
  // (rows reached max_batch) carry no such conflict and may be coalesced
  // with their predecessor by the sharded spine's batched apply.
  bool conflict_start = false;
};

// A parsed-but-not-yet-routed telemetry record. String views point into
// the feed buffer (or the tail scratch), valid for the duration of the
// feed() call — routing happens before feed() returns.
struct ParsedRec {
  uint64_t fp;    // fingerprint of (dp, src, dst)
  uint64_t rfp;   // fingerprint of (dp, dst, src); valid iff has_rfp
  const char* src;
  const char* dst;
  uint32_t src_len;
  uint32_t dst_len;
  const char* dp;
  uint32_t dp_len;
  int32_t time;
  uint64_t pkts;
  uint64_t bytes;
  uint8_t has_rfp;
};

struct Engine {
  uint32_t capacity;
  uint32_t max_batch;
  FpMap key_to_slot;
  std::vector<uint64_t> slot_fp;
  std::vector<uint8_t> slot_used;
  std::vector<std::string> slot_src;
  std::vector<std::string> slot_dst;
  // Per-slot telemetry-source namespace (0 = the default/legacy
  // namespace) — the reverse map behind tck_slots_for_source, i.e. the
  // native counterpart of FlowIndex.slot_source: a dead source's
  // quarantine eviction clears exactly its own slots. A flat vector,
  // not a sparse map: one uint32 per slot is 4 MB at 2^20 capacity and
  // the write is free inside the create path's cache lines.
  std::vector<uint32_t> slot_source;
  std::vector<uint32_t> free_slots;
  uint32_t next_slot = 0;
  uint64_t dropped = 0;
  uint64_t parsed = 0;
  // Malformed telemetry: lines that carry the 'data' prefix but fail
  // the parse (bad int, non-UTF8 field, too few fields). Noise lines
  // (Ryu logs, headers) are NOT errors — the reference's own stdout
  // interleaves them by design. Keyed per source so the fan-in tier
  // can attribute a corrupt feed to the switch that sent it.
  uint64_t parse_errors = 0;
  std::unordered_map<uint32_t, uint64_t> src_parse_errors;
  std::unordered_map<uint32_t, uint64_t> src_parsed;
  int32_t last_time = 0;  // max telemetry timestamp seen (eviction clock)
  std::deque<Generation> gens;
  // A RUN is a maximal sequence of coalescible generations: it ends at a
  // key conflict (a generation with conflict_start) or when the deque
  // drains empty (everything popped has been applied by then). Key
  // occupancy is tracked per RUN — not per generation — so a consumer
  // may concatenate every generation of a run into ONE device scatter:
  // (slot << 1 | is_fwd) bits valid iff occ_epoch[k] == run_seq
  // (bit0=create, bit1=update).
  uint32_t run_seq = 0;
  std::vector<uint32_t> occ_epoch;
  std::vector<uint8_t> occ_bits;
  // Per-source partial-line carry across feed calls: N sources deliver
  // interleaved byte chunks, and source A's half line must never be
  // completed by source B's next chunk. Source 0 is the legacy single
  // feed's tail.
  std::unordered_map<uint32_t, std::string> tails;
  int last_flush_conflict = 0;  // conflict_start of the last popped gen
  // Serializes every public entry point (see the extern "C" contract
  // below): ctypes releases the GIL for the duration of a foreign
  // call, so a Python reader thread feeding while the classify loop
  // flushes is REAL C++-level concurrency. One uncontended lock per
  // feed/flush (per chunk / per generation, never per record) is noise
  // against the 1 Hz poll cadence; tools/native_sanitize.sh's TSan
  // phase drives concurrent feed/flush to prove the discipline holds.
  std::mutex mu;

  explicit Engine(uint32_t cap, uint32_t mb)
      : capacity(cap), max_batch(mb), slot_fp(cap, 0), slot_used(cap, 0),
        slot_src(cap), slot_dst(cap), slot_source(cap, 0),
        occ_epoch(static_cast<size_t>(cap) * 2, 0),
        occ_bits(static_cast<size_t>(cap) * 2, 0) {}
};

// Python-int-compatible enough for the wire format: optional surrounding
// spaces, optional sign, then digits. Returns false on anything else
// (mirrors the parse_line() int() guard in ingest/protocol.py).
bool parse_i64(const char* s, size_t len, int64_t* out) {
  size_t i = 0, j = len;
  while (i < j && (s[i] == ' ' || s[i] == '\r')) i++;
  while (j > i && (s[j - 1] == ' ' || s[j - 1] == '\r')) j--;
  if (i >= j) return false;
  bool neg = false;
  if (s[i] == '+' || s[i] == '-') {
    neg = s[i] == '-';
    i++;
  }
  if (i >= j) return false;
  int64_t v = 0;
  for (; i < j; i++) {
    if (s[i] < '0' || s[i] > '9') return false;
    int d = s[i] - '0';
    // overflow guard: >19-digit fields would hit signed-overflow UB where
    // Python's arbitrary-precision int parses them; both sides now reject
    if (v > (INT64_MAX - d) / 10) return false;
    v = v * 10 + d;
  }
  *out = neg ? -v : v;
  return true;
}

// Strict UTF-8 validity — the Python oracle's parse_line rejects lines
// whose string fields fail .decode() (ingest/protocol.py), so we must too
// or slot metadata could carry bytes Python can't decode. ASCII fast path
// first: telemetry fields are MACs/ports/datapath ids, almost always pure
// ASCII.
bool utf8_valid(const char* s, size_t len) {
  size_t i = 0;
  // ASCII fast path, 8 bytes at a time: telemetry fields are MACs /
  // datapath ids / port numbers — pure ASCII in practice, so this skim
  // is the whole check. memcpy keeps the load alignment-safe.
  while (i + 8 <= len) {
    uint64_t w;
    std::memcpy(&w, s + i, 8);
    if (w & 0x8080808080808080ULL) break;
    i += 8;
  }
  while (i < len && static_cast<unsigned char>(s[i]) < 0x80) i++;
  while (i < len) {
    unsigned char c = s[i];
    size_t n;
    if (c < 0x80) n = 0;
    else if ((c & 0xE0) == 0xC0) n = 1;
    else if ((c & 0xF0) == 0xE0) n = 2;
    else if ((c & 0xF8) == 0xF0) n = 3;
    else return false;
    if (i + n >= len) return false;  // truncated sequence
    for (size_t k = 1; k <= n; k++) {
      if ((static_cast<unsigned char>(s[i + k]) & 0xC0) != 0x80) return false;
    }
    // reject overlong/surrogate/out-of-range forms
    if (n == 1 && c < 0xC2) return false;
    if (n == 2 && c == 0xE0 && static_cast<unsigned char>(s[i + 1]) < 0xA0)
      return false;
    if (n == 2 && c == 0xED && static_cast<unsigned char>(s[i + 1]) >= 0xA0)
      return false;
    if (n == 3 && c == 0xF0 && static_cast<unsigned char>(s[i + 1]) < 0x90)
      return false;
    if (n == 3 && (c > 0xF4 ||
                   (c == 0xF4 && static_cast<unsigned char>(s[i + 1]) > 0x8F)))
      return false;
    i += n + 1;
  }
  return true;
}

Generation& current_gen(Engine* e) {
  if (e->gens.empty()) {
    // everything previously flushed has been applied by now — the run
    // (the coalescible-uniqueness domain) starts over
    ++e->run_seq;
    e->gens.emplace_back();
  }
  return e->gens.back();
}

void push_row(Engine* e, uint32_t slot, uint8_t is_fwd, uint8_t is_create,
              int32_t time, uint64_t pkts, uint64_t bytes) {
  size_t k = (static_cast<size_t>(slot) << 1) | is_fwd;
  uint8_t bit = is_create ? 1 : 2;
  Generation* g = &current_gen(e);
  uint8_t occ = e->occ_epoch[k] == e->run_seq ? e->occ_bits[k] : 0;
  if ((occ & bit) || g->rows.size() >= e->max_batch) {
    bool conflict = (occ & bit) != 0;
    e->gens.emplace_back();
    g = &e->gens.back();
    g->conflict_start = conflict;
    if (conflict) {
      // new run: this key (and every other) may appear once more
      ++e->run_seq;
      occ = 0;
    }
    // size rollover: SAME run — occupancy stays valid, so a key that
    // already appeared anywhere in the run still conflicts later,
    // keeping whole-run concatenation scatter-safe
  }
  e->occ_epoch[k] = e->run_seq;
  e->occ_bits[k] = occ | bit;
  g->rows.push_back(Row{slot, time, pkts, bytes, is_fwd, is_create});
}

// parse_rec outcomes: noise (no 'data' prefix — Ryu logs/headers, not
// an error), a valid record, or a malformed telemetry line (counted per
// source and skipped — never a crash, never a torn row).
enum ParseResult { kNoise = 0, kValid = 1, kMalformed = 2 };

// Parse one complete line (no trailing \n) without touching engine state.
int parse_rec(const char* line, size_t len, bool eager_rfp, uint32_t source,
              ParsedRec* out) {
  // prefix match, like the reference's line.startswith('data')
  // (traffic_classifier.py:152)
  if (len < 4 || std::memcmp(line, "data", 4) != 0) return kNoise;
  // split on \t, drop field 0, need EXACTLY 8 remaining — the wire
  // format emits exactly 9 columns, so trailing junk fields are a
  // corrupt line, not slop to ignore (and the Python parser rejects
  // identically). memchr (SIMD in libc) instead of a per-byte scan —
  // the split was ~a third of the single-thread parse cost at
  // 56 B/line.
  const char* f[16];
  size_t fl[16];
  int nf = 0;
  size_t start = 0;
  while (nf < 16) {
    const char* t = static_cast<const char*>(
        std::memchr(line + start, '\t', len - start));
    f[nf] = line + start;
    if (t == nullptr) {
      fl[nf] = len - start;
      nf++;
      break;
    }
    fl[nf] = static_cast<size_t>(t - line) - start;
    nf++;
    start = static_cast<size_t>(t - line) + 1;
  }
  if (nf != 9) return kMalformed;
  int64_t time, pkts, bytes;
  if (!parse_i64(f[1], fl[1], &time)) return kMalformed;
  if (!parse_i64(f[7], fl[7], &pkts)) return kMalformed;
  if (!parse_i64(f[8], fl[8], &bytes)) return kMalformed;
  // Cumulative counters can't be negative; a signed value here is a
  // corrupt line (and would otherwise wrap to ~1.8e19 via the uint64_t
  // cast below, diverging from the Python parser, which also rejects).
  if (pkts < 0 || bytes < 0) return kMalformed;
  // the Python oracle decodes datapath/ports/MACs as UTF-8 and rejects
  // the line on failure; match it (fields 2..6 are the string fields)
  for (int k = 2; k <= 6; k++) {
    if (!utf8_valid(f[k], fl[k])) return kMalformed;
  }
  // f[2]=datapath f[4]=eth_src f[5]=eth_dst (f[3]=in_port f[6]=out_port
  // are carried by the wire format but unused for keying, same as the
  // reference)
  out->dp = f[2];
  out->dp_len = static_cast<uint32_t>(fl[2]);
  out->src = f[4];
  out->src_len = static_cast<uint32_t>(fl[4]);
  out->dst = f[5];
  out->dst_len = static_cast<uint32_t>(fl[5]);
  out->time = static_cast<int32_t>(time);
  out->pkts = static_cast<uint64_t>(pkts);
  out->bytes = static_cast<uint64_t>(bytes);
  out->fp = flow_fingerprint(f[2], fl[2], f[4], fl[4], f[5], fl[5], source);
  if (eager_rfp) {
    // worker threads pre-hash the reverse key too: the sequential router
    // then never hashes, only probes
    out->rfp =
        flow_fingerprint(f[2], fl[2], f[5], fl[5], f[4], fl[4], source);
    out->has_rfp = 1;
  } else {
    out->has_rfp = 0;
  }
  return kValid;
}

// Route one parsed record (the FlowIndex.assign logic). MUST run in
// original record order — slot assignment is order-dependent and the
// Python oracle is sequential. ``source`` tags a newly created slot's
// namespace; hits already carry the source in their fingerprint.
void route_rec(Engine* e, const ParsedRec& r, uint32_t source) {
  uint32_t* hit = e->key_to_slot.find(r.fp);
  if (hit != nullptr) {
    push_row(e, *hit, 1, 0, r.time, r.pkts, r.bytes);
  } else {
    uint64_t rfp = r.has_rfp
                       ? r.rfp
                       : flow_fingerprint(r.dp, r.dp_len, r.dst, r.dst_len,
                                          r.src, r.src_len, source);
    hit = e->key_to_slot.find(rfp);
    if (hit != nullptr) {
      push_row(e, *hit, 0, 0, r.time, r.pkts, r.bytes);
    } else {
      uint32_t slot;
      if (!e->free_slots.empty()) {
        slot = e->free_slots.back();
        e->free_slots.pop_back();
      } else if (e->next_slot < e->capacity) {
        slot = e->next_slot++;
      } else {
        e->dropped++;
        e->parsed++;
        if (r.time > e->last_time) e->last_time = r.time;
        return;
      }
      e->key_to_slot.insert(r.fp, slot);
      e->slot_fp[slot] = r.fp;
      e->slot_used[slot] = 1;
      e->slot_src[slot].assign(r.src, r.src_len);
      e->slot_dst[slot].assign(r.dst, r.dst_len);
      e->slot_source[slot] = source;
      push_row(e, slot, 1, 1, r.time, r.pkts, r.bytes);
    }
  }
  e->parsed++;
  if (r.time > e->last_time) e->last_time = r.time;
}

inline void parse_and_route(Engine* e, const char* line, size_t len,
                            uint32_t source, uint64_t* errors) {
  ParsedRec r;
  int res = parse_rec(line, len, /*eager_rfp=*/false, source, &r);
  if (res == kValid) {
    route_rec(e, r, source);
  } else if (res == kMalformed) {
    ++*errors;
  }
}

// Route a parsed block with the key-map probe lines prefetched: at ~1M
// live flows the map (16+ MB) misses cache on nearly every probe, and
// those serialized misses — not parsing — bound the single-thread feed
// (measured: prefix-reject framing runs 57 M lines/s, full routing
// 2.4 M/s). Records carry eager reverse fingerprints so both probe
// targets prefetch; the block is small enough that all its lines stay
// resident in L1/L2 until routed. Routing order stays strictly
// sequential — identical assignment to the unprefetched path. A grow()
// during the block only wastes prefetches (correctness unaffected).
// Shared block size for both feed paths: small enough that every
// prefetched map line stays L1/L2-resident until its record routes.
constexpr size_t kRouteBlock = 64;

inline void route_block(Engine* e, const ParsedRec* recs, size_t n,
                        uint32_t source) {
  const FpMap& m = e->key_to_slot;
  for (size_t i = 0; i < n; i++) {
    size_t b = recs[i].fp & m.mask;
    __builtin_prefetch(&m.vals[b]);
    __builtin_prefetch(&m.keys[b]);
    size_t rb = recs[i].rfp & m.mask;
    __builtin_prefetch(&m.vals[rb]);
    __builtin_prefetch(&m.keys[rb]);
  }
  for (size_t i = 0; i < n; i++) route_rec(e, recs[i], source);
}

// Parse every line in [buf+begin, buf+end) into out (telemetry lines
// only; malformed lines counted into *errors). begin must sit at a line
// start; end at a line end (past '\n'). Runs on worker threads WITHOUT
// the engine lock — it touches no engine state, only its own outputs.
void parse_region(const char* buf, size_t begin, size_t end,
                  uint32_t source, std::vector<ParsedRec>* out,
                  uint64_t* errors) {
  size_t start = begin;
  while (start < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(buf + start, '\n', end - start));
    if (nl == nullptr) break;  // caller guarantees end is past a '\n'
    size_t i = static_cast<size_t>(nl - buf);
    ParsedRec r;
    int res = parse_rec(buf + start, i - start, /*eager_rfp=*/true,
                        source, &r);
    if (res == kValid) {
      out->push_back(r);
    } else if (res == kMalformed) {
      ++*errors;
    }
    start = i + 1;
  }
}

// Threaded feed: split [begin, end) at line boundaries, parse in
// parallel, route sequentially. Only called when end-begin is large and
// the host has >1 core. Returns the malformed-line count.
uint64_t feed_threaded(Engine* e, const char* buf, size_t begin, size_t end,
                       size_t nthreads, uint32_t source) {
  std::vector<size_t> cut(nthreads + 1, begin);
  cut[nthreads] = end;
  size_t span = (end - begin) / nthreads;
  for (size_t t = 1; t < nthreads; t++) {
    size_t c = begin + t * span;
    // never inspect buf[begin-1]: with a tiny forced-thread region span
    // can be 0 and begin can be 0 (late cuts then collapse to empty)
    if (c < begin + 1) c = begin + 1;
    while (c < end && buf[c - 1] != '\n') c++;  // advance to a line start
    cut[t] = c < cut[t - 1] ? cut[t - 1] : c;
  }
  std::vector<std::vector<ParsedRec>> outs(nthreads);
  std::vector<uint64_t> errs(nthreads, 0);
  std::vector<std::thread> workers;
  workers.reserve(nthreads - 1);
  for (size_t t = 1; t < nthreads; t++) {
    workers.emplace_back(parse_region, buf, cut[t], cut[t + 1], source,
                         &outs[t], &errs[t]);
  }
  parse_region(buf, cut[0], cut[1], source, &outs[0], &errs[0]);
  for (auto& w : workers) w.join();
  uint64_t errors = 0;
  for (size_t t = 0; t < nthreads; t++) {
    errors += errs[t];
    const std::vector<ParsedRec>& rs = outs[t];
    for (size_t i = 0; i < rs.size(); i += kRouteBlock) {
      size_t n = rs.size() - i < kRouteBlock ? rs.size() - i : kRouteBlock;
      route_block(e, rs.data() + i, n, source);
    }
  }
  return errors;
}

// Free one slot back to the allocator. Callers hold e->mu.
void release_slot_locked(Engine* e, uint32_t slot) {
  if (slot >= e->capacity || !e->slot_used[slot]) return;
  e->key_to_slot.erase(e->slot_fp[slot]);
  e->slot_used[slot] = 0;
  e->slot_src[slot].clear();
  e->slot_dst[slot].clear();
  // reset the namespace tag: a reused slot must never inherit a dead
  // source's namespace (the next create stamps its own)
  e->slot_source[slot] = 0;
  e->free_slots.push_back(slot);
}

// Feed raw bytes in arbitrary chunks (partial lines are carried over
// per source). Returns the number of telemetry records parsed from this
// chunk. Callers hold e->mu.
uint64_t feed_locked(Engine* e, const char* buf, uint64_t len,
                     uint32_t source) {
  uint64_t before = e->parsed;
  uint64_t errors = 0;
  std::string& tail = e->tails[source];
  size_t begin = 0;
  if (!tail.empty()) {
    // complete the carried partial line first (routes before anything
    // parsed from this chunk — order preserved)
    const char* p = static_cast<const char*>(std::memchr(buf, '\n', len));
    if (p == nullptr) {
      tail.append(buf, len);
      return 0;
    }
    size_t nl = static_cast<size_t>(p - buf);
    tail.append(buf, nl);
    parse_and_route(e, tail.data(), tail.size(), source, &errors);
    tail.clear();
    begin = nl + 1;
  }
  size_t last_nl = len;  // one past the final '\n'
  while (last_nl > begin && buf[last_nl - 1] != '\n') last_nl--;
  if (last_nl > begin) {
    // TC_ENGINE_THREADS overrides both the thread count and the size
    // threshold (testing: forces the threaded path on single-core CI
    // hosts, where it would otherwise never execute).
    static const long forced = [] {
      const char* v = std::getenv("TC_ENGINE_THREADS");
      long n = v != nullptr ? std::atol(v) : 0L;
      return n > 16 ? 16L : n;  // clamp: typo'd values must not fork
                                // thousands of threads in the hot path
    }();
    static const size_t hw = std::thread::hardware_concurrency();
    const size_t nthreads =
        forced > 0 ? static_cast<size_t>(forced) : (hw > 8 ? 8 : hw);
    const size_t threshold = forced > 0 ? 1 : (1u << 21);
    if (nthreads >= 2 && last_nl - begin >= threshold) {
      errors += feed_threaded(e, buf, begin, last_nl, nthreads, source);
    } else {
      // block-parse then route-with-prefetch (see route_block)
      ParsedRec recs[kRouteBlock];
      size_t nr = 0;
      size_t start = begin;
      while (start < last_nl) {
        const char* nl = static_cast<const char*>(
            std::memchr(buf + start, '\n', last_nl - start));
        if (nl == nullptr) break;
        size_t i = static_cast<size_t>(nl - buf);
        int res = parse_rec(buf + start, i - start, /*eager_rfp=*/true,
                            source, &recs[nr]);
        if (res == kValid) {
          if (++nr == kRouteBlock) {
            route_block(e, recs, nr, source);
            nr = 0;
          }
        } else if (res == kMalformed) {
          errors++;
        }
        start = i + 1;
      }
      route_block(e, recs, nr, source);
    }
  }
  if (last_nl < len) tail.append(buf + last_nl, len - last_nl);
  uint64_t n = e->parsed - before;
  // per-source accounting amortized to one map touch per CALL, never
  // per record — the per-record hot loop stays map-free
  if (n) e->src_parsed[source] += n;
  if (errors) {
    e->parse_errors += errors;
    e->src_parse_errors[source] += errors;
  }
  return n;
}

}  // namespace

// Concurrency contract: every function below except tc_engine_create /
// tc_engine_destroy takes the engine mutex, so feed, flush, and the
// bookkeeping queries may be called from different threads
// concurrently. Destruction is the caller's ordering problem (as with
// any handle API): no call may race tc_engine_destroy.
extern "C" {

void* tc_engine_create(uint32_t capacity, uint32_t max_batch) {
  // capacity is bounded below the FpMap sentinel slot values AND below
  // the wire layout's flag bits: tck_flush_wire packs slot | fwd<<31 |
  // create<<30 (and pads with slot == capacity), so any slot touching
  // bit 30 would silently corrupt direction/create semantics. pack_wire
  // raises for the same bound on the Python path — fail loudly here too.
  if (capacity == 0 || max_batch == 0 || capacity >= (1u << 30)) {
    return nullptr;
  }
  return new Engine(capacity, max_batch);
}

void tc_engine_destroy(void* h) { delete static_cast<Engine*>(h); }

// Feed raw bytes in arbitrary chunks (partial lines are carried over).
// Returns the number of telemetry records parsed from this chunk.
// Legacy single-source entry: the default namespace (source 0) —
// bit-for-bit the pre-fan-in behavior.
uint64_t tc_engine_feed(void* h, const char* buf, uint64_t len) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return feed_locked(e, buf, len, 0);
}

// THE fan-in wire entry: one call per (source, poll batch) — raw pipe /
// capture / synthetic bytes routed entirely in C++ under the source's
// namespace (fingerprints fold the source id; new slots are tagged for
// tck_slots_for_source). Per-source partial-line tails keep framing
// correct across interleaved multi-source chunks. Malformed telemetry
// lines ('data' prefix, invalid body) are counted per source and
// skipped — never a crash, never a torn row.
uint64_t tck_feed_lines(void* h, const char* buf, uint64_t len,
                        uint32_t source) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return feed_locked(e, buf, len, source);
}

uint64_t tc_engine_pending(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> guard(e->mu);
  uint64_t n = 0;
  for (const auto& g : e->gens) n += g.rows.size();
  return n;
}

// Pop the oldest generation into caller-provided arrays (each sized >=
// max_batch). Returns the row count, 0 when nothing is pending. pkts/bytes
// are split into low-32-bits + float32 lanes, matching the device table's
// uint32+f32 counter representation (core/flow_table.py).
uint32_t tc_engine_flush(void* h, int32_t* slot, int32_t* time,
                         uint32_t* pkts_lo, float* pkts_f, uint32_t* bytes_lo,
                         float* bytes_f, uint8_t* is_fwd, uint8_t* is_create) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> guard(e->mu);
  while (!e->gens.empty() && e->gens.front().rows.empty()) {
    e->gens.pop_front();
  }
  if (e->gens.empty()) return 0;
  const Generation& g = e->gens.front();
  e->last_flush_conflict = g.conflict_start ? 1 : 0;
  uint32_t n = static_cast<uint32_t>(g.rows.size());
  for (uint32_t i = 0; i < n; i++) {
    const Row& r = g.rows[i];
    slot[i] = static_cast<int32_t>(r.slot);
    time[i] = r.time;
    pkts_lo[i] = static_cast<uint32_t>(r.pkts & 0xFFFFFFFFu);
    pkts_f[i] = static_cast<float>(r.pkts);
    bytes_lo[i] = static_cast<uint32_t>(r.bytes & 0xFFFFFFFFu);
    bytes_f[i] = static_cast<float>(r.bytes);
    is_fwd[i] = r.is_fwd;
    is_create[i] = r.is_create;
  }
  e->gens.pop_front();
  return n;
}

// 1 iff the generation most recently popped by tc_engine_flush was
// started by a same-(slot, direction, kind) conflict with its
// predecessor — i.e. it must NOT be coalesced into the same device
// scatter as the batch flushed before it. 0 for size-rollover
// generations and the first generation of a drain.
int tc_engine_last_flush_conflict(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return e->last_flush_conflict;
}

// Pop the oldest generation DIRECTLY into the packed uint32 wire layout
// (core/flow_table.pack_wire): one pass from the C++ rows into the
// caller's pinned staging buffer, zero per-flush numpy allocation or
// Python column work. ``wire`` must hold >= max_batch*6 uint32; rows
// are written TIGHT at the chosen width (4 compact / 6 full), padded
// with pad_slot rows (is_fwd set, everything else zero — exactly
// pack_wire's padding) up to the smallest admitting bucket from
// ``buckets`` (ascending, last entry >= max_batch). Returns
// (width << 32) | padded_rows, or 0 when nothing is pending. The width
// rule matches pack_wire bit-for-bit: compact whenever every counter's
// float32 image is < 2^31, so the device-side unpack reconstructs
// identical f32 lanes.
uint64_t tck_flush_wire(void* h, uint32_t* wire, const uint32_t* buckets,
                        uint32_t n_buckets, uint32_t pad_slot) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> guard(e->mu);
  while (!e->gens.empty() && e->gens.front().rows.empty()) {
    e->gens.pop_front();
  }
  if (e->gens.empty() || n_buckets == 0) return 0;
  const Generation& g = e->gens.front();
  e->last_flush_conflict = g.conflict_start ? 1 : 0;
  const uint32_t n = static_cast<uint32_t>(g.rows.size());
  constexpr float kLim = 2147483648.0f;  // 2^31 as float32
  bool compact = true;
  for (uint32_t i = 0; i < n; i++) {
    const Row& r = g.rows[i];
    if (static_cast<float>(r.pkts) >= kLim ||
        static_cast<float>(r.bytes) >= kLim) {
      compact = false;
      break;
    }
  }
  uint32_t padded = buckets[n_buckets - 1];
  for (uint32_t b = 0; b < n_buckets; b++) {
    if (n <= buckets[b]) {
      padded = buckets[b];
      break;
    }
  }
  const uint32_t w = compact ? 4 : 6;
  for (uint32_t i = 0; i < n; i++) {
    const Row& r = g.rows[i];
    uint32_t* row = wire + static_cast<size_t>(i) * w;
    row[0] = r.slot | (static_cast<uint32_t>(r.is_fwd) << 31) |
             (static_cast<uint32_t>(r.is_create) << 30);
    row[1] = static_cast<uint32_t>(r.time);
    row[2] = static_cast<uint32_t>(r.pkts & 0xFFFFFFFFu);
    if (compact) {
      row[3] = static_cast<uint32_t>(r.bytes & 0xFFFFFFFFu);
    } else {
      float pf = static_cast<float>(r.pkts);
      float bf = static_cast<float>(r.bytes);
      std::memcpy(&row[3], &pf, 4);
      row[4] = static_cast<uint32_t>(r.bytes & 0xFFFFFFFFu);
      std::memcpy(&row[5], &bf, 4);
    }
  }
  // padding rows: scratch slot with the fwd flag, zeros elsewhere — a
  // clean no-op under apply_wire, bit-identical to pack_wire's pad
  const uint32_t pad0 = pad_slot | (1u << 31);
  for (uint32_t i = n; i < padded; i++) {
    uint32_t* row = wire + static_cast<size_t>(i) * w;
    row[0] = pad0;
    std::memset(row + 1, 0, (w - 1) * sizeof(uint32_t));
  }
  e->gens.pop_front();
  return (static_cast<uint64_t>(w) << 32) | padded;
}

// Every in-use slot in ``source``'s namespace, ascending — the native
// half of FlowStateEngine.evict_source (the caller clears the device
// rows, then releases these slots in bulk). O(capacity) scan, but only
// walked on a source-death event, never per tick — the same contract
// as FlowIndex.slots_for_source. ``out`` must hold >= capacity slots.
uint32_t tck_slots_for_source(void* h, uint32_t source, uint32_t* out) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  uint32_t n = 0;
  for (uint32_t s = 0; s < e->capacity; s++) {
    if (e->slot_used[s] && e->slot_source[s] == source) out[n++] = s;
  }
  return n;
}

// Drop ``source``'s carried partial line — the native half of
// FlowStateEngine.evict_source's framing reset. The dead incarnation's
// dangling fragment must not be completed by a restarted stream's
// first chunk (the fan-in queue's \x00\n poison seam guards the same
// boundary from the delivery side; this guards direct engine callers).
void tck_reset_tail(void* h, uint32_t source) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  e->tails.erase(source);
}

// Malformed-telemetry accounting ('data'-prefixed lines that failed the
// parse — noise lines are not errors), total and per source.
uint64_t tck_parse_errors_total(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return e->parse_errors;
}

uint64_t tck_parse_errors(void* h, uint32_t source) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->src_parse_errors.find(source);
  return it == e->src_parse_errors.end() ? 0 : it->second;
}

uint64_t tck_source_parsed(void* h, uint32_t source) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->src_parsed.find(source);
  return it == e->src_parsed.end() ? 0 : it->second;
}

uint64_t tc_engine_dropped(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return e->dropped;
}
uint64_t tc_engine_parsed(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return e->parsed;
}
int32_t tc_engine_last_time(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return e->last_time;
}

uint32_t tc_engine_num_flows(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  return static_cast<uint32_t>(e->key_to_slot.used);
}

// Copy the (src, dst) MAC strings for a slot into caller buffers of size
// cap (NUL-terminated, truncated if needed). Returns 1 if the slot is in
// use, 0 otherwise.
int tc_engine_slot_meta(void* h, uint32_t slot, char* src_out, char* dst_out,
                        uint32_t cap) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  if (slot >= e->capacity || !e->slot_used[slot] || cap == 0) return 0;
  std::snprintf(src_out, cap, "%s", e->slot_src[slot].c_str());
  std::snprintf(dst_out, cap, "%s", e->slot_dst[slot].c_str());
  return 1;
}

// Free a slot (idle eviction). The caller must drain flush() first so no
// pending row can scatter into a reassigned slot — same contract as
// FlowStateEngine.evict_idle.
void tc_engine_release_slot(void* h, uint32_t slot) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  release_slot_locked(e, slot);
}

// Bulk release: one ctypes crossing for an eviction batch instead of one
// per slot — an idle-storm at the 2^20-flow scale releases hundreds of
// thousands of slots in one tick.
void tc_engine_release_slots(void* h, const uint32_t* slots, uint32_t n) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  for (uint32_t i = 0; i < n; ++i) release_slot_locked(e, slots[i]);
}

// --- serving-state checkpoint support --------------------------------------
// Export the index for a warm-restart checkpoint: per-slot fingerprints +
// occupancy (metadata strings travel via tc_engine_slot_meta). Returns
// next_slot — the sequential-assignment frontier a restore must resume.
uint32_t tc_engine_export_index(void* h, uint64_t* fp_out, uint8_t* used_out) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  std::memcpy(fp_out, e->slot_fp.data(),
              static_cast<size_t>(e->capacity) * sizeof(uint64_t));
  std::memcpy(used_out, e->slot_used.data(), e->capacity);
  return e->next_slot;
}

// Export the free-slot stack VERBATIM (bottom to top): allocation order
// is LIFO, so a warm restart must preserve the exact stack for the
// restored engine's future slot assignments to match a never-stopped one.
uint32_t tc_engine_export_free(void* h, uint32_t* out) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  std::memcpy(out, e->free_slots.data(),
              e->free_slots.size() * sizeof(uint32_t));
  return static_cast<uint32_t>(e->free_slots.size());
}

// Bulk import into a FRESH engine of the same capacity: slots +
// fingerprints + fixed 64-byte src/dst cells, ONE ctypes crossing for
// the whole table (per-slot crossings would stall a 2^20-flow restart).
void tc_engine_import_slots(void* h, const uint32_t* slots,
                            const uint64_t* fps, const char* src,
                            const char* dst, uint32_t n) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t s = slots[i];
    if (s >= e->capacity || e->slot_used[s]) continue;
    e->slot_fp[s] = fps[i];
    e->slot_used[s] = 1;
    // Cells are fixed 64-byte numpy 'S64' fields with NO guaranteed NUL
    // terminator when the string fills the cell — bound the read.
    const char* sp = src + static_cast<size_t>(i) * 64;
    const char* dp = dst + static_cast<size_t>(i) * 64;
    e->slot_src[s].assign(sp, strnlen(sp, 64));
    e->slot_dst[s].assign(dp, strnlen(dp, 64));
    e->key_to_slot.insert(fps[i], s);
  }
}

// Finish an import: restore the assignment frontier, the eviction clock,
// and the free stack verbatim.
void tc_engine_import_finish(void* h, uint32_t next_slot, int32_t last_time,
                             const uint32_t* free_list, uint32_t n_free) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  e->next_slot = next_slot;
  e->last_time = last_time;
  e->free_slots.assign(free_list, free_list + n_free);
}

// Bulk metadata export: fixed 64-byte NUL-terminated cells per string —
// the one-crossing counterpart of tc_engine_slot_meta for checkpoints.
void tc_engine_export_meta(void* h, const uint32_t* slots, uint32_t n,
                           char* src_out, char* dst_out) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->mu);
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t s = slots[i];
    char* so = src_out + static_cast<size_t>(i) * 64;
    char* to = dst_out + static_cast<size_t>(i) * 64;
    if (s < e->capacity && e->slot_used[s]) {
      std::snprintf(so, 64, "%s", e->slot_src[s].c_str());
      std::snprintf(to, 64, "%s", e->slot_dst[s].c_str());
    } else {
      so[0] = to[0] = '\0';
    }
  }
}

}  // extern "C"
