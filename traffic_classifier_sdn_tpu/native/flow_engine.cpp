// Native host-side ingest engine: telemetry line parsing, flow indexing
// with direction folding, and packed update-batch assembly.
//
// This is the C++ replacement for the host-bound half of the reference's
// ingest loop (traffic_classifier.py:144-171): where the reference splits
// strings and mutates per-flow Python objects one line at a time, this
// engine consumes raw pipe bytes in bulk and emits packed arrays that the
// JAX layer scatters into the device-resident flow table
// (core/flow_table.py). All counter math stays on device; this code only
// decides where each record goes (slot, direction, create flag) — the
// same contract as ingest/batcher.py's FlowIndex + Batcher, which remain
// as the pure-Python fallback and behavioral oracle.
//
// Semantics mirrored from the Python batcher (and ultimately from the
// reference's key folding at traffic_classifier.py:157-165):
//   - a record keys on (datapath, eth_src, eth_dst); if that key is new
//     but the reversed key exists, the record is the reverse direction of
//     the existing flow
//   - per (slot, direction) a batch generation holds at most one create
//     row and one update row; a second same-direction update starts a new
//     generation, so flushing generations in order reproduces the
//     reference's sequential per-line semantics exactly
//   - table-full records are dropped and counted
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Row {
  uint32_t slot;
  int32_t time;
  uint64_t pkts;
  uint64_t bytes;
  uint8_t is_fwd;
  uint8_t is_create;
};

// One flush unit: rows plus the per-(slot,dir) occupancy needed to detect
// the one-create-plus-one-update-per-direction limit.
struct Generation {
  std::vector<Row> rows;
  // (slot << 1 | is_fwd) -> flags bit0=create present, bit1=update present
  std::unordered_map<uint64_t, uint8_t> occ;
};

struct Engine {
  uint32_t capacity;
  uint32_t max_batch;
  std::unordered_map<std::string, uint32_t> key_to_slot;
  std::vector<std::string> slot_key;  // "" when free
  std::vector<std::string> slot_src;
  std::vector<std::string> slot_dst;
  std::vector<uint32_t> free_slots;
  uint32_t next_slot = 0;
  uint64_t dropped = 0;
  uint64_t parsed = 0;
  int32_t last_time = 0;  // max telemetry timestamp seen (eviction clock)
  std::deque<Generation> gens;
  std::string tail;  // partial line carried across feed() calls

  explicit Engine(uint32_t cap, uint32_t mb)
      : capacity(cap), max_batch(mb), slot_key(cap), slot_src(cap),
        slot_dst(cap) {}
};

// Python-int-compatible enough for the wire format: optional surrounding
// spaces, optional sign, then digits. Returns false on anything else
// (mirrors the parse_line() int() guard in ingest/protocol.py).
bool parse_i64(const char* s, size_t len, int64_t* out) {
  size_t i = 0, j = len;
  while (i < j && (s[i] == ' ' || s[i] == '\r')) i++;
  while (j > i && (s[j - 1] == ' ' || s[j - 1] == '\r')) j--;
  if (i >= j) return false;
  bool neg = false;
  if (s[i] == '+' || s[i] == '-') {
    neg = s[i] == '-';
    i++;
  }
  if (i >= j) return false;
  int64_t v = 0;
  for (; i < j; i++) {
    if (s[i] < '0' || s[i] > '9') return false;
    int d = s[i] - '0';
    // overflow guard: >19-digit fields would hit signed-overflow UB where
    // Python's arbitrary-precision int parses them; both sides now reject
    if (v > (INT64_MAX - d) / 10) return false;
    v = v * 10 + d;
  }
  *out = neg ? -v : v;
  return true;
}

// Strict UTF-8 validity — the Python oracle's parse_line rejects lines
// whose string fields fail .decode() (ingest/protocol.py), so we must too
// or slot metadata could carry bytes Python can't decode.
bool utf8_valid(const char* s, size_t len) {
  size_t i = 0;
  while (i < len) {
    unsigned char c = s[i];
    size_t n;
    if (c < 0x80) n = 0;
    else if ((c & 0xE0) == 0xC0) n = 1;
    else if ((c & 0xF0) == 0xE0) n = 2;
    else if ((c & 0xF8) == 0xF0) n = 3;
    else return false;
    if (i + n >= len) return false;  // truncated sequence
    for (size_t k = 1; k <= n; k++) {
      if ((static_cast<unsigned char>(s[i + k]) & 0xC0) != 0x80) return false;
    }
    // reject overlong/surrogate/out-of-range forms
    if (n == 1 && c < 0xC2) return false;
    if (n == 2 && c == 0xE0 && static_cast<unsigned char>(s[i + 1]) < 0xA0)
      return false;
    if (n == 2 && c == 0xED && static_cast<unsigned char>(s[i + 1]) >= 0xA0)
      return false;
    if (n == 3 && c == 0xF0 && static_cast<unsigned char>(s[i + 1]) < 0x90)
      return false;
    if (n == 3 && (c > 0xF4 ||
                   (c == 0xF4 && static_cast<unsigned char>(s[i + 1]) > 0x8F)))
      return false;
    i += n + 1;
  }
  return true;
}

std::string make_key(const char* dp, size_t dpl, const char* src, size_t sl,
                     const char* dst, size_t dl) {
  // \x00 separators, same anti-ambiguity rule as protocol.stable_flow_key.
  std::string k;
  k.reserve(dpl + sl + dl + 2);
  k.append(dp, dpl);
  k.push_back('\0');
  k.append(src, sl);
  k.push_back('\0');
  k.append(dst, dl);
  return k;
}

Generation& current_gen(Engine* e) {
  if (e->gens.empty()) e->gens.emplace_back();
  return e->gens.back();
}

void push_row(Engine* e, uint32_t slot, uint8_t is_fwd, uint8_t is_create,
              int32_t time, uint64_t pkts, uint64_t bytes) {
  uint64_t k = (static_cast<uint64_t>(slot) << 1) | is_fwd;
  uint8_t bit = is_create ? 1 : 2;
  Generation* g = &current_gen(e);
  uint8_t* occ = &g->occ[k];
  if ((*occ & bit) || g->rows.size() >= e->max_batch) {
    e->gens.emplace_back();
    g = &e->gens.back();
    occ = &g->occ[k];
  }
  *occ |= bit;
  g->rows.push_back(Row{slot, time, pkts, bytes, is_fwd, is_create});
}

// Route one parsed record (the FlowIndex.assign logic).
void route(Engine* e, const char* dp, size_t dpl, const char* src, size_t sl,
           const char* dst, size_t dl, int32_t time, uint64_t pkts,
           uint64_t bytes) {
  std::string key = make_key(dp, dpl, src, sl, dst, dl);
  auto it = e->key_to_slot.find(key);
  if (it != e->key_to_slot.end()) {
    push_row(e, it->second, 1, 0, time, pkts, bytes);
    return;
  }
  std::string rkey = make_key(dp, dpl, dst, dl, src, sl);
  it = e->key_to_slot.find(rkey);
  if (it != e->key_to_slot.end()) {
    push_row(e, it->second, 0, 0, time, pkts, bytes);
    return;
  }
  uint32_t slot;
  if (!e->free_slots.empty()) {
    slot = e->free_slots.back();
    e->free_slots.pop_back();
  } else if (e->next_slot < e->capacity) {
    slot = e->next_slot++;
  } else {
    e->dropped++;
    return;
  }
  e->key_to_slot.emplace(key, slot);
  e->slot_key[slot] = std::move(key);
  e->slot_src[slot].assign(src, sl);
  e->slot_dst[slot].assign(dst, dl);
  push_row(e, slot, 1, 1, time, pkts, bytes);
}

// Parse one complete line (no trailing \n). Returns true if it was a
// telemetry record (counted), false for headers / controller logs.
bool ingest_line(Engine* e, const char* line, size_t len) {
  // prefix match, like the reference's line.startswith('data')
  // (traffic_classifier.py:152)
  if (len < 4 || std::memcmp(line, "data", 4) != 0) return false;
  // split on \t, drop field 0, need >= 8 remaining
  const char* f[16];
  size_t fl[16];
  int nf = 0;
  size_t start = 0;
  for (size_t i = 0; i <= len && nf < 16; i++) {
    if (i == len || line[i] == '\t') {
      f[nf] = line + start;
      fl[nf] = i - start;
      nf++;
      start = i + 1;
    }
  }
  if (nf < 9) return false;
  int64_t time, pkts, bytes;
  if (!parse_i64(f[1], fl[1], &time)) return false;
  if (!parse_i64(f[7], fl[7], &pkts)) return false;
  if (!parse_i64(f[8], fl[8], &bytes)) return false;
  // Cumulative counters can't be negative; a signed value here is a
  // corrupt line (and would otherwise wrap to ~1.8e19 via the uint64_t
  // cast below, diverging from the Python parser, which also rejects).
  if (pkts < 0 || bytes < 0) return false;
  // the Python oracle decodes datapath/ports/MACs as UTF-8 and rejects
  // the line on failure; match it (fields 2..6 are the string fields)
  for (int k = 2; k <= 6; k++) {
    if (!utf8_valid(f[k], fl[k])) return false;
  }
  // f[2]=datapath f[4]=eth_src f[5]=eth_dst (f[3]=in_port f[6]=out_port
  // are carried by the wire format but unused for keying, same as the
  // reference)
  route(e, f[2], fl[2], f[4], fl[4], f[5], fl[5],
        static_cast<int32_t>(time), static_cast<uint64_t>(pkts),
        static_cast<uint64_t>(bytes));
  e->parsed++;
  if (static_cast<int32_t>(time) > e->last_time)
    e->last_time = static_cast<int32_t>(time);
  return true;
}

}  // namespace

extern "C" {

void* tc_engine_create(uint32_t capacity, uint32_t max_batch) {
  if (capacity == 0 || max_batch == 0) return nullptr;
  return new Engine(capacity, max_batch);
}

void tc_engine_destroy(void* h) { delete static_cast<Engine*>(h); }

// Feed raw bytes in arbitrary chunks (partial lines are carried over).
// Returns the number of telemetry records parsed from this chunk.
uint64_t tc_engine_feed(void* h, const char* buf, uint64_t len) {
  Engine* e = static_cast<Engine*>(h);
  uint64_t before = e->parsed;
  size_t start = 0;
  for (size_t i = 0; i < len; i++) {
    if (buf[i] != '\n') continue;
    if (e->tail.empty()) {
      ingest_line(e, buf + start, i - start);
    } else {
      e->tail.append(buf + start, i - start);
      ingest_line(e, e->tail.data(), e->tail.size());
      e->tail.clear();
    }
    start = i + 1;
  }
  if (start < len) e->tail.append(buf + start, len - start);
  return e->parsed - before;
}

uint64_t tc_engine_pending(void* h) {
  Engine* e = static_cast<Engine*>(h);
  uint64_t n = 0;
  for (const auto& g : e->gens) n += g.rows.size();
  return n;
}

// Pop the oldest generation into caller-provided arrays (each sized >=
// max_batch). Returns the row count, 0 when nothing is pending. pkts/bytes
// are split into low-32-bits + float32 lanes, matching the device table's
// uint32+f32 counter representation (core/flow_table.py).
uint32_t tc_engine_flush(void* h, int32_t* slot, int32_t* time,
                         uint32_t* pkts_lo, float* pkts_f, uint32_t* bytes_lo,
                         float* bytes_f, uint8_t* is_fwd, uint8_t* is_create) {
  Engine* e = static_cast<Engine*>(h);
  while (!e->gens.empty() && e->gens.front().rows.empty()) {
    e->gens.pop_front();
  }
  if (e->gens.empty()) return 0;
  const Generation& g = e->gens.front();
  uint32_t n = static_cast<uint32_t>(g.rows.size());
  for (uint32_t i = 0; i < n; i++) {
    const Row& r = g.rows[i];
    slot[i] = static_cast<int32_t>(r.slot);
    time[i] = r.time;
    pkts_lo[i] = static_cast<uint32_t>(r.pkts & 0xFFFFFFFFu);
    pkts_f[i] = static_cast<float>(r.pkts);
    bytes_lo[i] = static_cast<uint32_t>(r.bytes & 0xFFFFFFFFu);
    bytes_f[i] = static_cast<float>(r.bytes);
    is_fwd[i] = r.is_fwd;
    is_create[i] = r.is_create;
  }
  e->gens.pop_front();
  return n;
}

uint64_t tc_engine_dropped(void* h) { return static_cast<Engine*>(h)->dropped; }
uint64_t tc_engine_parsed(void* h) { return static_cast<Engine*>(h)->parsed; }
int32_t tc_engine_last_time(void* h) {
  return static_cast<Engine*>(h)->last_time;
}

uint32_t tc_engine_num_flows(void* h) {
  Engine* e = static_cast<Engine*>(h);
  return static_cast<uint32_t>(e->key_to_slot.size());
}

// Copy the (src, dst) MAC strings for a slot into caller buffers of size
// cap (NUL-terminated, truncated if needed). Returns 1 if the slot is in
// use, 0 otherwise.
int tc_engine_slot_meta(void* h, uint32_t slot, char* src_out, char* dst_out,
                        uint32_t cap) {
  Engine* e = static_cast<Engine*>(h);
  if (slot >= e->capacity || e->slot_key[slot].empty() || cap == 0) return 0;
  std::snprintf(src_out, cap, "%s", e->slot_src[slot].c_str());
  std::snprintf(dst_out, cap, "%s", e->slot_dst[slot].c_str());
  return 1;
}

// Free a slot (idle eviction). The caller must drain flush() first so no
// pending row can scatter into a reassigned slot — same contract as
// FlowStateEngine.evict_idle.
void tc_engine_release_slot(void* h, uint32_t slot) {
  Engine* e = static_cast<Engine*>(h);
  if (slot >= e->capacity || e->slot_key[slot].empty()) return;
  e->key_to_slot.erase(e->slot_key[slot]);
  e->slot_key[slot].clear();
  e->slot_src[slot].clear();
  e->slot_dst[slot].clear();
  e->free_slots.push_back(slot);
}

}  // extern "C"
