"""Feature schema for per-flow traffic classification.

The reference engineers 16 per-flow columns (8 per direction) and trains on a
12-feature subset that drops the 4 cumulative counters. Column names and order
come from the training-CSV header written at traffic_classifier.py:217 and the
online feature vector assembled at traffic_classifier.py:104; the notebooks
drop the cumulative columns before fitting (SURVEY.md §3.4/§3.5).

Order matters: the online 12-vector must match the training column order
exactly (no scaling is applied in the reference, and none is applied here).
"""

from __future__ import annotations

# The 17-column training-CSV schema (16 features + label), exactly as the
# reference's training-data writer emits it (traffic_classifier.py:217).
CSV_COLUMNS_16 = (
    "Forward Packets",
    "Forward Bytes",
    "Delta Forward Packets",
    "Delta Forward Bytes",
    "Forward Instantaneous Packets per Second",
    "Forward Average Packets per second",
    "Forward Instantaneous Bytes per Second",
    "Forward Average Bytes per second",
    "Reverse Packets",
    "Reverse Bytes",
    "Delta Reverse Packets",
    "Delta Reverse Bytes",
    "DeltaReverse Instantaneous Packets per Second",
    "Reverse Average Packets per second",
    "Reverse Instantaneous Bytes per Second",
    "Reverse Average Bytes per second",
)
LABEL_COLUMN = "Traffic Type"

# The 4 cumulative columns dropped before training (notebook cell 4 of every
# training notebook; SURVEY.md §3.4).
CUMULATIVE_COLUMNS = (
    "Forward Packets",
    "Forward Bytes",
    "Reverse Packets",
    "Reverse Bytes",
)

# The 12 model-input features, in training column order — which the online
# vector at traffic_classifier.py:104 matches exactly.
FEATURE_COLUMNS_12 = tuple(
    c for c in CSV_COLUMNS_16 if c not in CUMULATIVE_COLUMNS
)

NUM_FEATURES = 12
assert len(FEATURE_COLUMNS_12) == NUM_FEATURES

# Indices of the 12 model features within the 16-column row.
FEATURE_INDICES_IN_16 = tuple(
    i for i, c in enumerate(CSV_COLUMNS_16) if c not in CUMULATIVE_COLUMNS
)

# Canonical 6-class label set, alphabetical — pandas categorical coding used
# by every notebook (dns=0, game=1, ping=2, quake=3, telnet=4, voice=5;
# SURVEY.md §3.4), which the reference's online remap at
# traffic_classifier.py:109-114 mirrors.
CLASSES_6 = ("dns", "game", "ping", "quake", "telnet", "voice")
