"""Golden pure-Python bidirectional flow tracker — the behavioral oracle.

Reimplements (not copies) the exact update semantics of the reference's
``Flow`` class (traffic_classifier.py:29-96), used as the ground truth the
vectorized device flow table (core/flow_table.py) is property-tested against:

- a conversation is tracked once; the reverse direction folds into the same
  record (reference key folding at traffic_classifier.py:157-165)
- per direction: cumulative packets/bytes, deltas since last poll,
  instantaneous rates (delta / poll gap), average rates (cumulative / flow
  age), and an ACTIVE/INACTIVE status that is INACTIVE iff the latest delta
  of packets *or* bytes is zero (traffic_classifier.py:75-78, 93-96)
- rate guards: average rates only update when curr_time != time_start;
  instantaneous rates only when curr_time != last_time (reference :66-67)
- on creation the forward side starts ACTIVE with the initial counters and
  the reverse side starts INACTIVE at zero (reference :38-60)
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class DirectionState:
    packets: int = 0
    bytes: int = 0
    delta_packets: int = 0
    delta_bytes: int = 0
    inst_pps: float = 0.0
    avg_pps: float = 0.0
    inst_bps: float = 0.0
    avg_bps: float = 0.0
    active: bool = False
    last_time: int = 0


@dataclass
class GoldenFlow:
    """One bidirectional conversation, updated once per telemetry poll."""

    time_start: int
    datapath: str
    ethsrc: str
    ethdst: str
    inport: int = 0
    outport: int = 0
    forward: DirectionState = field(default_factory=DirectionState)
    reverse: DirectionState = field(default_factory=DirectionState)

    def __post_init__(self):
        self.forward.last_time = self.time_start
        self.reverse.last_time = self.time_start

    @classmethod
    def create(cls, time_start, datapath, ethsrc, ethdst, packets, bytes_,
               inport=0, outport=0) -> "GoldenFlow":
        f = cls(time_start, datapath, ethsrc, ethdst, inport, outport)
        f.forward.packets = packets
        f.forward.bytes = bytes_
        f.forward.active = True  # reference :47
        return f

    def _update(self, d: DirectionState, packets, bytes_, curr_time):
        d.delta_packets = packets - d.packets
        d.packets = packets
        if curr_time != self.time_start:
            d.avg_pps = packets / float(curr_time - self.time_start)
        if curr_time != d.last_time:
            d.inst_pps = d.delta_packets / float(curr_time - d.last_time)
        d.delta_bytes = bytes_ - d.bytes
        d.bytes = bytes_
        if curr_time != self.time_start:
            d.avg_bps = bytes_ / float(curr_time - self.time_start)
        if curr_time != d.last_time:
            d.inst_bps = d.delta_bytes / float(curr_time - d.last_time)
        d.last_time = curr_time
        d.active = not (d.delta_bytes == 0 or d.delta_packets == 0)

    def update_forward(self, packets, bytes_, curr_time):
        self._update(self.forward, packets, bytes_, curr_time)

    def update_reverse(self, packets, bytes_, curr_time):
        self._update(self.reverse, packets, bytes_, curr_time)

    def features12(self) -> list:
        """The online feature vector, exact order of
        traffic_classifier.py:104."""
        f, r = self.forward, self.reverse
        return [
            f.delta_packets, f.delta_bytes, f.inst_pps, f.avg_pps,
            f.inst_bps, f.avg_bps,
            r.delta_packets, r.delta_bytes, r.inst_pps, r.avg_pps,
            r.inst_bps, r.avg_bps,
        ]

    def features16(self) -> list:
        """The training-CSV row, exact order of traffic_classifier.py:124-141
        (and the datasets/*.csv column order)."""
        f, r = self.forward, self.reverse
        return [
            f.packets, f.bytes, f.delta_packets, f.delta_bytes,
            f.inst_pps, f.avg_pps, f.inst_bps, f.avg_bps,
            r.packets, r.bytes, r.delta_packets, r.delta_bytes,
            r.inst_pps, r.avg_pps, r.inst_bps, r.avg_bps,
        ]
