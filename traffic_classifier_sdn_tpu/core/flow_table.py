"""Device-resident flow table: the reference's ``flows = {}`` dict as a
fixed-capacity structure-of-arrays updated by one jit-compiled scatter step.

The reference keeps per-flow Python objects in a global dict and mutates them
one telemetry line at a time (traffic_classifier.py:24,157-165). Inverted for
TPU: all counters live in device arrays; each poll tick applies a *batch* of
updates in one ``jit`` call (donated state, pure scatter/gather — no
host↔device ping-pong), and the 12-feature matrix for the classifiers is a
pure projection of the state.

Numerical design — exact semantics without int64/float64 (neither is fast on
TPU):

- ``*_lo`` cumulative counters are uint32, i.e. the true counter mod 2^32.
  A delta is ``int32(new_lo - old_lo)`` in wraparound arithmetic, which is
  *exact* whenever the true per-poll delta is < 2^31 — so delta features and
  the ACTIVE/INACTIVE zero-test match the reference's arbitrary-precision
  Python ints exactly, even after the 4 GiB counter wrap.
- ``*_f`` cumulative counters are float32 approximations of the full 64-bit
  value (supplied by the host, which parses the telemetry as int64). Only the
  average-rate features divide these, so their error is ≤1 ulp relative —
  the same rounding the f32 feature matrix incurs anyway.
- Slot assignment (key → row) is host-side control plane: a dict keyed by a
  *stable* 64-bit hash (ingest/protocol.py) — deliberately not Python's
  ``hash()``, whose per-process randomization the reference depends on
  (defect list, SURVEY.md §2).

Row ``capacity`` is reserved as a scratch row so fixed-shape update batches
can pad harmlessly (no recompilation across variable batch sizes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from .features import NUM_FEATURES


class DirState(struct.PyTreeNode):
    """Per-direction counters for every slot, shape (capacity+1,)."""

    pkts_lo: jax.Array  # uint32, true packet count mod 2^32
    pkts_f: jax.Array  # float32 ≈ true packet count
    bytes_lo: jax.Array  # uint32
    bytes_f: jax.Array  # float32
    delta_pkts: jax.Array  # int32, exact
    delta_bytes: jax.Array  # int32, exact
    inst_pps: jax.Array  # float32
    avg_pps: jax.Array  # float32
    inst_bps: jax.Array  # float32
    avg_bps: jax.Array  # float32
    last_time: jax.Array  # int32
    active: jax.Array  # bool


class FlowTable(struct.PyTreeNode):
    time_start: jax.Array  # int32 (capacity+1,)
    in_use: jax.Array  # bool (capacity+1,)
    fwd: DirState
    rev: DirState

    @property
    def capacity(self) -> int:
        return self.time_start.shape[0] - 1


class UpdateBatch(struct.PyTreeNode):
    """One poll tick's worth of telemetry, padded to a fixed length.

    Padding rows use ``slot == capacity`` (the scratch row) with
    ``is_create=False, is_fwd=True``. Duplicate (slot, direction) pairs
    within one batch are not allowed (the host batcher deduplicates
    last-wins), matching the reference's per-line sequential dict updates.
    """

    slot: jax.Array  # int32 (B,)
    time: jax.Array  # int32 (B,) poll timestamp, seconds
    pkts_lo: jax.Array  # uint32 (B,)
    pkts_f: jax.Array  # float32 (B,)
    bytes_lo: jax.Array  # uint32 (B,)
    bytes_f: jax.Array  # float32 (B,)
    is_fwd: jax.Array  # bool (B,)
    is_create: jax.Array  # bool (B,)


def _zeros_dir(n: int) -> DirState:
    return DirState(
        pkts_lo=jnp.zeros(n, jnp.uint32),
        pkts_f=jnp.zeros(n, jnp.float32),
        bytes_lo=jnp.zeros(n, jnp.uint32),
        bytes_f=jnp.zeros(n, jnp.float32),
        delta_pkts=jnp.zeros(n, jnp.int32),
        delta_bytes=jnp.zeros(n, jnp.int32),
        inst_pps=jnp.zeros(n, jnp.float32),
        avg_pps=jnp.zeros(n, jnp.float32),
        inst_bps=jnp.zeros(n, jnp.float32),
        avg_bps=jnp.zeros(n, jnp.float32),
        last_time=jnp.zeros(n, jnp.int32),
        active=jnp.zeros(n, bool),
    )


def make_table(capacity: int) -> FlowTable:
    n = capacity + 1  # last row is the padding scratch slot
    return FlowTable(
        time_start=jnp.zeros(n, jnp.int32),
        in_use=jnp.zeros(n, bool),
        fwd=_zeros_dir(n),
        rev=_zeros_dir(n),
    )


def _updated_dir(
    d: DirState, slot, time, pkts_lo, pkts_f, bytes_lo, bytes_f, time_start, apply_mask
) -> DirState:
    """Compute the reference's updateforward/updatereverse math
    (traffic_classifier.py:63-96) for a batch of rows, then scatter."""
    old_pkts_lo = d.pkts_lo[slot]
    old_bytes_lo = d.bytes_lo[slot]
    old_last = d.last_time[slot]

    # Exact deltas via mod-2^32 wraparound (see module docstring).
    delta_pkts = (pkts_lo - old_pkts_lo).astype(jnp.int32)
    delta_bytes = (bytes_lo - old_bytes_lo).astype(jnp.int32)

    age = (time - time_start).astype(jnp.float32)
    gap = (time - old_last).astype(jnp.float32)
    # Guards replicate reference :66-67: keep the old value when the
    # denominator would be zero.
    avg_pps = jnp.where(age != 0, pkts_f / age, d.avg_pps[slot])
    avg_bps = jnp.where(age != 0, bytes_f / age, d.avg_bps[slot])
    inst_pps = jnp.where(
        gap != 0, delta_pkts.astype(jnp.float32) / gap, d.inst_pps[slot]
    )
    inst_bps = jnp.where(
        gap != 0, delta_bytes.astype(jnp.float32) / gap, d.inst_bps[slot]
    )
    active = (delta_bytes != 0) & (delta_pkts != 0)  # reference :75-78

    # Masked scatter: rows not applying to this direction are routed to the
    # scratch row (last index). Never write identity values at the real slot —
    # the same slot can appear in the batch for the *other* direction, and
    # duplicate-index scatter order is undefined, so an identity write could
    # clobber the real one.
    scratch = d.pkts_lo.shape[0] - 1
    eff_slot = jnp.where(apply_mask, slot, scratch)

    def put(arr, new):
        return arr.at[eff_slot].set(new, mode="drop")

    return DirState(
        pkts_lo=put(d.pkts_lo, pkts_lo),
        pkts_f=put(d.pkts_f, pkts_f),
        bytes_lo=put(d.bytes_lo, bytes_lo),
        bytes_f=put(d.bytes_f, bytes_f),
        delta_pkts=put(d.delta_pkts, delta_pkts),
        delta_bytes=put(d.delta_bytes, delta_bytes),
        inst_pps=put(d.inst_pps, inst_pps),
        avg_pps=put(d.avg_pps, avg_pps),
        inst_bps=put(d.inst_bps, inst_bps),
        avg_bps=put(d.avg_bps, avg_bps),
        last_time=put(d.last_time, time),
        active=put(d.active, active),
    )


def _created_dir(
    d: DirState, b: UpdateBatch, counters_from_batch: bool, active_init: bool
) -> DirState:
    """Initialize rows for newly created flows (reference :38-60): the
    forward side gets the first counters and starts ACTIVE
    (``counters_from_batch=True, active_init=True``), the reverse side
    starts at zero INACTIVE. Both sides' last_time starts at time_start."""
    # Route non-create rows to the scratch row (see _updated_dir on why
    # identity writes at the real slot are unsafe).
    scratch = d.pkts_lo.shape[0] - 1
    eff_slot = jnp.where(b.is_create, b.slot, scratch)

    def put(arr, new):
        return arr.at[eff_slot].set(new, mode="drop")

    if counters_from_batch:
        pk_lo, pk_f, by_lo, by_f = b.pkts_lo, b.pkts_f, b.bytes_lo, b.bytes_f
    else:
        pk_lo = jnp.zeros_like(b.pkts_lo)
        pk_f = jnp.zeros_like(b.pkts_f)
        by_lo = jnp.zeros_like(b.bytes_lo)
        by_f = jnp.zeros_like(b.bytes_f)
    zero_i = jnp.zeros_like(b.slot)
    zero_f = jnp.zeros_like(b.pkts_f)
    return DirState(
        pkts_lo=put(d.pkts_lo, pk_lo),
        pkts_f=put(d.pkts_f, pk_f),
        bytes_lo=put(d.bytes_lo, by_lo),
        bytes_f=put(d.bytes_f, by_f),
        delta_pkts=put(d.delta_pkts, zero_i),
        delta_bytes=put(d.delta_bytes, zero_i),
        inst_pps=put(d.inst_pps, zero_f),
        avg_pps=put(d.avg_pps, zero_f),
        inst_bps=put(d.inst_bps, zero_f),
        avg_bps=put(d.avg_bps, zero_f),
        last_time=put(d.last_time, b.time),
        active=put(d.active, jnp.full_like(b.is_create, active_init)),
    )


@jax.jit
def apply_batch(table: FlowTable, b: UpdateBatch) -> FlowTable:
    """Apply one padded update batch. Donate ``table`` at the call site
    (``jax.jit(apply_batch).lower`` …) or rely on XLA aliasing via the
    wrapper in ingest/batcher.py for true in-place updates."""
    slot = b.slot
    create = b.is_create
    upd_fwd = ~create & b.is_fwd
    upd_rev = ~create & ~b.is_fwd

    # Creation: shared fields. Non-create rows route to the scratch row
    # (duplicate-slot safety — see _updated_dir).
    scratch = table.time_start.shape[0] - 1
    create_slot = jnp.where(create, slot, scratch)
    time_start = table.time_start.at[create_slot].set(b.time, mode="drop")
    in_use = table.in_use.at[create_slot].set(True, mode="drop")

    # Creates BEFORE updates: a batch may contain both a flow's create row
    # and a same-tick update row for either direction (the monitor reports
    # both directions per poll). Updates must then read the freshly
    # initialized counters, exactly like the reference's sequential
    # per-line processing (create → updatereverse within one poll).
    fwd = _created_dir(table.fwd, b, counters_from_batch=True, active_init=True)
    rev = _created_dir(table.rev, b, counters_from_batch=False, active_init=False)

    ts_for_rows = time_start[slot]
    fwd = _updated_dir(
        fwd, slot, b.time, b.pkts_lo, b.pkts_f, b.bytes_lo, b.bytes_f,
        ts_for_rows, upd_fwd,
    )
    rev = _updated_dir(
        rev, slot, b.time, b.pkts_lo, b.pkts_f, b.bytes_lo, b.bytes_f,
        ts_for_rows, upd_rev,
    )

    return FlowTable(time_start=time_start, in_use=in_use, fwd=fwd, rev=rev)


def _cleared_dir(d: DirState, slot) -> DirState:
    def put(arr):
        return arr.at[slot].set(jnp.zeros((), arr.dtype), mode="drop")

    return DirState(
        pkts_lo=put(d.pkts_lo), pkts_f=put(d.pkts_f),
        bytes_lo=put(d.bytes_lo), bytes_f=put(d.bytes_f),
        delta_pkts=put(d.delta_pkts), delta_bytes=put(d.delta_bytes),
        inst_pps=put(d.inst_pps), avg_pps=put(d.avg_pps),
        inst_bps=put(d.inst_bps), avg_bps=put(d.avg_bps),
        last_time=put(d.last_time), active=put(d.active),
    )


@jax.jit
def clear_slots(table: FlowTable, slot: jax.Array) -> FlowTable:
    """Reset the given slots to the empty state (eviction). ``slot`` is a
    fixed-length int32 batch padded with ``capacity`` (the scratch row)."""
    return FlowTable(
        time_start=table.time_start.at[slot].set(0, mode="drop"),
        in_use=table.in_use.at[slot].set(False, mode="drop"),
        fwd=_cleared_dir(table.fwd, slot),
        rev=_cleared_dir(table.rev, slot),
    )


@jax.jit
def stale_mask(table: FlowTable, now, idle_seconds) -> jax.Array:
    """(capacity+1,) bool: in-use slots with no telemetry in either
    direction for ``idle_seconds``. Computed on device so eviction scans
    transfer one bool array instead of three int arrays — the incremental
    evict path that keeps the 2²⁰-flow serving loop off the host
    (VERDICT r1 item 4)."""
    last = jnp.maximum(table.fwd.last_time, table.rev.last_time)
    return table.in_use & (now - last >= idle_seconds)


@functools.partial(jax.jit, static_argnames=("n",))
def top_active_slots(table: FlowTable, n: int, floor):
    """Indices of the ≤n most active in-use slots this tick, ranked by
    |Δbytes| summed over both directions (desc), ties to the lowest slot.

    Deltas persist in the table until a flow's next telemetry record, so
    activity is gated to slots with telemetry strictly newer than
    ``floor`` (the max timestamp of all previous ticks — see
    FlowStateEngine.mark_tick): a flow that moved gigabytes and then
    vanished from telemetry must not dominate the render forever, while
    timestamp skew between datapaths reporting within one tick cannot
    demote a busy flow. Stale in-use slots score 0 — below any
    currently-active flow, above nothing — so they still fill the sample
    on an idle network.

    Device-side ``top_k`` over the whole table, so the host sees O(n) data
    — the render sample tracks live traffic instead of insertion order
    (the reference prints every flow it knows, traffic_classifier.py:99-118;
    at 2²⁰ tracked flows a host-side scan would dominate the tick).
    Returns ``(idx, valid)``: unused slots score −inf and are masked out
    via ``valid``.
    """
    act = (
        jnp.abs(table.fwd.delta_bytes.astype(jnp.float32))
        + jnp.abs(table.rev.delta_bytes.astype(jnp.float32))
    )[:-1]
    fresh = (
        jnp.maximum(table.fwd.last_time, table.rev.last_time)[:-1] > floor
    )
    score = jnp.where(
        table.in_use[:-1], jnp.where(fresh, act, 0.0), -jnp.inf
    )
    _, idx = jax.lax.top_k(score, n)
    return idx, jnp.take(table.in_use[:-1], idx)


def features12(table: FlowTable) -> jax.Array:
    """(capacity, 12) online feature matrix, order of
    traffic_classifier.py:104 — rows for unused slots are zero."""
    f, r = table.fwd, table.rev
    cols = [
        f.delta_pkts.astype(jnp.float32), f.delta_bytes.astype(jnp.float32),
        f.inst_pps, f.avg_pps, f.inst_bps, f.avg_bps,
        r.delta_pkts.astype(jnp.float32), r.delta_bytes.astype(jnp.float32),
        r.inst_pps, r.avg_pps, r.inst_bps, r.avg_bps,
    ]
    X = jnp.stack(cols, axis=1)[:-1]  # drop the scratch row
    X = jnp.where(table.in_use[:-1, None], X, 0.0)
    assert X.shape[1] == NUM_FEATURES
    return X


def features16(table: FlowTable) -> jax.Array:
    """(capacity, 16) training-row matrix, order of
    traffic_classifier.py:124-141 / the CSV header at :217."""
    f, r = table.fwd, table.rev
    cols = [
        f.pkts_f, f.bytes_f,
        f.delta_pkts.astype(jnp.float32), f.delta_bytes.astype(jnp.float32),
        f.inst_pps, f.avg_pps, f.inst_bps, f.avg_bps,
        r.pkts_f, r.bytes_f,
        r.delta_pkts.astype(jnp.float32), r.delta_bytes.astype(jnp.float32),
        r.inst_pps, r.avg_pps, r.inst_bps, r.avg_bps,
    ]
    X = jnp.stack(cols, axis=1)[:-1]
    return jnp.where(table.in_use[:-1, None], X, 0.0)
