"""Device-resident flow table: the reference's ``flows = {}`` dict as a
fixed-capacity structure-of-arrays updated by one jit-compiled scatter step.

The reference keeps per-flow Python objects in a global dict and mutates them
one telemetry line at a time (traffic_classifier.py:24,157-165). Inverted for
TPU: all counters live in device arrays; each poll tick applies a *batch* of
updates in one ``jit`` call (donated state, pure scatter/gather — no
host↔device ping-pong), and the 12-feature matrix for the classifiers is a
pure projection of the state.

Numerical design — exact semantics without int64/float64 (neither is fast on
TPU):

- ``*_lo`` cumulative counters are uint32, i.e. the true counter mod 2^32.
  A delta is ``int32(new_lo - old_lo)`` in wraparound arithmetic, which is
  *exact* whenever the true per-poll delta is < 2^31 — so delta features and
  the ACTIVE/INACTIVE zero-test match the reference's arbitrary-precision
  Python ints exactly, even after the 4 GiB counter wrap.
- ``*_f`` cumulative counters are float32 approximations of the full 64-bit
  value (supplied by the host, which parses the telemetry as int64). Only the
  average-rate features divide these, so their error is ≤1 ulp relative —
  the same rounding the f32 feature matrix incurs anyway.
- Slot assignment (key → row) is host-side control plane: a dict keyed by a
  *stable* 64-bit hash (ingest/protocol.py) — deliberately not Python's
  ``hash()``, whose per-process randomization the reference depends on
  (defect list, SURVEY.md §2).

Row ``capacity`` is reserved as a scratch row so fixed-shape update batches
can pad harmlessly (no recompilation across variable batch sizes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from .features import NUM_FEATURES


class DirState(struct.PyTreeNode):
    """Per-direction counters for every slot, shape (capacity+1,)."""

    pkts_lo: jax.Array  # uint32, true packet count mod 2^32
    pkts_f: jax.Array  # float32 ≈ true packet count
    bytes_lo: jax.Array  # uint32
    bytes_f: jax.Array  # float32
    delta_pkts: jax.Array  # int32, exact
    delta_bytes: jax.Array  # int32, exact
    inst_pps: jax.Array  # float32
    avg_pps: jax.Array  # float32
    inst_bps: jax.Array  # float32
    avg_bps: jax.Array  # float32
    last_time: jax.Array  # int32
    active: jax.Array  # bool


class FlowTable(struct.PyTreeNode):
    time_start: jax.Array  # int32 (capacity+1,)
    in_use: jax.Array  # bool (capacity+1,)
    fwd: DirState
    rev: DirState

    @property
    def capacity(self) -> int:
        return self.time_start.shape[0] - 1


class UpdateBatch(struct.PyTreeNode):
    """One poll tick's worth of telemetry, padded to a fixed length.

    Padding rows use ``slot == capacity`` (the scratch row) with
    ``is_create=False, is_fwd=True``. Duplicate (slot, direction) pairs
    within one batch are not allowed (the host batcher deduplicates
    last-wins), matching the reference's per-line sequential dict updates.
    """

    slot: jax.Array  # int32 (B,)
    time: jax.Array  # int32 (B,) poll timestamp, seconds
    pkts_lo: jax.Array  # uint32 (B,)
    pkts_f: jax.Array  # float32 (B,)
    bytes_lo: jax.Array  # uint32 (B,)
    bytes_f: jax.Array  # float32 (B,)
    is_fwd: jax.Array  # bool (B,)
    is_create: jax.Array  # bool (B,)


def _zeros_dir(n: int) -> DirState:
    return DirState(
        pkts_lo=jnp.zeros(n, jnp.uint32),
        pkts_f=jnp.zeros(n, jnp.float32),
        bytes_lo=jnp.zeros(n, jnp.uint32),
        bytes_f=jnp.zeros(n, jnp.float32),
        delta_pkts=jnp.zeros(n, jnp.int32),
        delta_bytes=jnp.zeros(n, jnp.int32),
        inst_pps=jnp.zeros(n, jnp.float32),
        avg_pps=jnp.zeros(n, jnp.float32),
        inst_bps=jnp.zeros(n, jnp.float32),
        avg_bps=jnp.zeros(n, jnp.float32),
        last_time=jnp.zeros(n, jnp.int32),
        active=jnp.zeros(n, bool),
    )


def make_table(capacity: int) -> FlowTable:
    n = capacity + 1  # last row is the padding scratch slot
    return FlowTable(
        time_start=jnp.zeros(n, jnp.int32),
        in_use=jnp.zeros(n, bool),
        fwd=_zeros_dir(n),
        rev=_zeros_dir(n),
    )


def pack_wire(b: UpdateBatch) -> "np.ndarray":
    """Host-side: one contiguous uint32 wire matrix per batch. Column 0
    carries the slot with the two direction/create flags in bits 31/30
    (slot ≤ capacity < 2³⁰).

    Two widths, chosen per batch:

    - **(B, 4) compact** — slot+flags, time, pkts_lo, bytes_lo — when
      every counter in the batch is < 2³¹: the device reconstructs the
      f32 counter lanes exactly (``float32(lo)`` == the host's
      ``float32(u64)`` whenever the u64 equals its low 32 bits, and
      < 2³¹ keeps a safety margin below f32-uint rounding at the 2³²
      boundary). 16 B/record instead of 24 — the wire is the serving
      tick's dominant cost on a slow device link (measured 35.9 MB/s
      tunnel: 25.2 MB → 16.8 MB per 2²⁰ tick saves ~230 ms).
    - **(B, 6) full** — adds bit-cast pkts_f/bytes_f — whenever any
      counter reaches 2³¹ (a >2-billion-packet flow), preserving exact
      f32 lanes for arbitrary u64 counters.

    ``unpack_wire`` dispatches on the column count; both round-trip
    exactly (property-tested in tests/test_flow_state.py)."""
    import numpy as np

    if b.slot.size and int(b.slot.max()) >= (1 << 30):
        raise ValueError(
            "pack_wire: slot >= 2^30 collides with the flag bits — "
            "table capacity must stay below 2^30"
        )
    col0 = (
        b.slot.astype(np.uint32)
        | (b.is_fwd.astype(np.uint32) << 31)
        | (b.is_create.astype(np.uint32) << 30)
    )
    lim = np.float32(1 << 31)
    compact = bool((b.pkts_f < lim).all() and (b.bytes_f < lim).all())
    w = np.empty((b.slot.shape[0], 4 if compact else 6), np.uint32)
    w[:, 0] = col0
    w[:, 1] = b.time.view(np.uint32)
    w[:, 2] = b.pkts_lo
    if compact:
        w[:, 3] = b.bytes_lo
        return w
    w[:, 3] = b.pkts_f.view(np.uint32)
    w[:, 4] = b.bytes_lo
    w[:, 5] = b.bytes_f.view(np.uint32)
    return w


class WireStage:
    """Pinned, reusable host staging for packed wire batches — the
    zero-copy half of native ingest (native/engine.NativeBatcher
    .flush_wire): the C++ engine writes each flushed generation straight
    into one of these buffers in the ``pack_wire`` layout, and the view
    handed back goes to ``apply_wire`` untouched. Two rotating buffers
    (the ``FeatureStage`` double-buffer discipline from the pipelined
    serve, serving/pipeline.py): the previous flush's view — possibly
    still being consumed by an in-flight transfer — is never overwritten
    by the next flush. Buffers are flat uint32 so one allocation serves
    both wire widths: a (rows, 4) compact view and a (rows, 6) full view
    are reshapes of the same pages.
    """

    def __init__(self, max_rows: int):
        import numpy as np

        self._bufs = (
            np.empty(max_rows * 6, np.uint32),
            np.empty(max_rows * 6, np.uint32),
        )
        self._i = 0

    def buffer(self):
        """The buffer the NEXT flush writes into (flat uint32)."""
        return self._bufs[self._i]

    def view(self, rows: int, width: int):
        """Consume the current buffer as a (rows, width) wire matrix and
        rotate — the caller owns the view until the flush after next."""
        buf = self._bufs[self._i]
        self._i ^= 1
        return buf[: rows * width].reshape(rows, width)

    def touch(self) -> None:
        """Fault every page in (warmup): first-tick latency must not pay
        the staging buffers' lazy page allocation."""
        for b in self._bufs:
            b.fill(0)


def widen_wire(w: "np.ndarray") -> "np.ndarray":
    """Host-side (B, 4) compact → (B, 6) full wire: rebuilds the f32
    lanes as ``float32(lo)`` (exact under the compact form's < 2³¹
    guarantee). Lets a consumer concatenate mixed-width batches — e.g.
    the sharded spine coalescing a compact batch with a rare full one."""
    import numpy as np

    if w.shape[1] == 6:
        return w
    out = np.empty((w.shape[0], 6), np.uint32)
    out[:, 0] = w[:, 0]
    out[:, 1] = w[:, 1]
    out[:, 2] = w[:, 2]
    out[:, 3] = w[:, 2].astype(np.float32).view(np.uint32)
    out[:, 4] = w[:, 3]
    out[:, 5] = w[:, 3].astype(np.float32).view(np.uint32)
    return out


def unpack_wire(w: jax.Array) -> UpdateBatch:
    """Device-side inverse of ``pack_wire`` (elementwise, fuses into the
    scatter that follows). Dispatches on the static column count: the
    compact (B, 4) form rebuilds the f32 counter lanes as
    ``float32(lo)`` — exact under the packer's < 2³¹ guarantee."""
    col0 = w[:, 0]
    bitcast = jax.lax.bitcast_convert_type
    compact = w.shape[1] == 4
    pkts_lo = w[:, 2]
    bytes_lo = w[:, 3] if compact else w[:, 4]
    return UpdateBatch(
        slot=(col0 & jnp.uint32(0x3FFFFFFF)).astype(jnp.int32),
        time=bitcast(w[:, 1], jnp.int32),
        pkts_lo=pkts_lo,
        pkts_f=pkts_lo.astype(jnp.float32) if compact
        else bitcast(w[:, 3], jnp.float32),
        bytes_lo=bytes_lo,
        bytes_f=bytes_lo.astype(jnp.float32) if compact
        else bitcast(w[:, 5], jnp.float32),
        is_fwd=(col0 >> 31) != 0,
        is_create=((col0 >> 30) & jnp.uint32(1)) != 0,
    )


def apply_wire(table: FlowTable, w: jax.Array) -> FlowTable:
    """``apply_batch`` over the packed wire format — the serving spine's
    per-flush entry point: one host→device buffer per batch."""
    return apply_batch(table, unpack_wire(w))


def mark_dirty_wire(dirty: jax.Array, w: jax.Array) -> jax.Array:
    """Set the dirty bit for every slot a packed wire batch touches.

    ``dirty`` is the per-slot (capacity+1,) bool mask behind incremental
    prediction (serving/incremental.py): the ingest scatter is the ONLY
    thing that changes a row's 12 serving features, so the slots in the
    wire are exactly the rows whose cached labels went stale. Padding
    rows carry the scratch slot and land on the scratch bit, which no
    reader consults."""
    slot = (w[:, 0] & jnp.uint32(0x3FFFFFFF)).astype(jnp.int32)
    return dirty.at[slot].set(True, mode="drop")


def apply_wire_dirty(
    table: FlowTable, dirty: jax.Array, w: jax.Array
) -> tuple[FlowTable, jax.Array]:
    """``apply_wire`` fused with the dirty-bit scatter: ONE wire
    transfer and one dispatch cover both the table update and the
    staleness bookkeeping (a separate jit would ship the packed batch
    across the link twice)."""
    return apply_batch(table, unpack_wire(w)), mark_dirty_wire(dirty, w)


def _inverse_index(mask, slot, n: int):
    """(n,) int32 map: table row → index of the batch row addressing it
    under ``mask``, or B (sentinel) for rows no batch row addresses.

    ONE int32 scatter replaces a per-field scatter: masked-out rows are
    routed past the end of the table (n + i, unique per row) and dropped,
    so every remaining index is unique and ``unique_indices=True`` lets
    XLA emit the vectorized lowering. TPU scatters without it serialize —
    measured ~1.5 s of device time for one 2²⁰-row batch applied through
    per-field scatters, vs ~ms for this inverse + gathers formulation.

    Uniqueness precondition: the batcher guarantees at most one batch row
    per (slot, direction) and per-slot create (ingest/batcher.Batcher
    docstring); padding rows carry slot == scratch and are masked out by
    the caller."""
    B = slot.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)
    tgt = jnp.where(mask, slot, n + rows)
    inv = jnp.full(n, B, jnp.int32)
    return inv.at[tgt].set(rows, mode="drop", unique_indices=True)


def _merged_dir(
    d: DirState, b: UpdateBatch, gather, time_start,
    inv_create, inv_update, counters_from_batch: bool, active_init: bool,
) -> DirState:
    """One direction's create-then-update merge, all in table-row space.

    ``gather(arr)`` pulls batch columns to table rows through the
    direction's inverse index; old per-row values are already table-space
    so the reference's read-modify-write update math
    (traffic_classifier.py:63-96) becomes elementwise. Create first, then
    update — a batch may hold a flow's create row and a same-tick update
    row, and the update must read the freshly initialized counters,
    exactly like the reference's sequential per-line processing."""
    hit_c = inv_create != b.slot.shape[0]
    time_c = gather(b.time, inv_create)

    def init(old, batch_col, init_val):
        created = gather(batch_col, inv_create) if counters_from_batch \
            else jnp.full_like(old, init_val)
        return jnp.where(hit_c, created, old)

    zero = 0
    pkts_lo = init(d.pkts_lo, b.pkts_lo, zero)
    pkts_f = init(d.pkts_f, b.pkts_f, zero)
    bytes_lo = init(d.bytes_lo, b.bytes_lo, zero)
    bytes_f = init(d.bytes_f, b.bytes_f, zero)
    delta_pkts = jnp.where(hit_c, 0, d.delta_pkts)
    delta_bytes = jnp.where(hit_c, 0, d.delta_bytes)
    inst_pps = jnp.where(hit_c, 0.0, d.inst_pps)
    avg_pps = jnp.where(hit_c, 0.0, d.avg_pps)
    inst_bps = jnp.where(hit_c, 0.0, d.inst_bps)
    avg_bps = jnp.where(hit_c, 0.0, d.avg_bps)
    last_time = jnp.where(hit_c, time_c, d.last_time)
    active = jnp.where(hit_c, active_init, d.active)

    # --- update pass (reference updateforward/updatereverse math) ---------
    hit = inv_update != b.slot.shape[0]
    time_u = gather(b.time, inv_update)
    pkts_lo_u = gather(b.pkts_lo, inv_update)
    pkts_f_u = gather(b.pkts_f, inv_update)
    bytes_lo_u = gather(b.bytes_lo, inv_update)
    bytes_f_u = gather(b.bytes_f, inv_update)

    # Exact deltas via mod-2^32 wraparound (see module docstring).
    d_pkts = (pkts_lo_u - pkts_lo).astype(jnp.int32)
    d_bytes = (bytes_lo_u - bytes_lo).astype(jnp.int32)
    age = (time_u - time_start).astype(jnp.float32)
    gap = (time_u - last_time).astype(jnp.float32)
    # Guards replicate reference :66-67: keep the old value when the
    # denominator would be zero.
    n_avg_pps = jnp.where(age != 0, pkts_f_u / age, avg_pps)
    n_avg_bps = jnp.where(age != 0, bytes_f_u / age, avg_bps)
    n_inst_pps = jnp.where(
        gap != 0, d_pkts.astype(jnp.float32) / gap, inst_pps
    )
    n_inst_bps = jnp.where(
        gap != 0, d_bytes.astype(jnp.float32) / gap, inst_bps
    )
    n_active = (d_bytes != 0) & (d_pkts != 0)  # reference :75-78

    def upd(old, new):
        return jnp.where(hit, new, old)

    return DirState(
        pkts_lo=upd(pkts_lo, pkts_lo_u),
        pkts_f=upd(pkts_f, pkts_f_u),
        bytes_lo=upd(bytes_lo, bytes_lo_u),
        bytes_f=upd(bytes_f, bytes_f_u),
        delta_pkts=upd(delta_pkts, d_pkts),
        delta_bytes=upd(delta_bytes, d_bytes),
        inst_pps=upd(inst_pps, n_inst_pps),
        avg_pps=upd(avg_pps, n_avg_pps),
        inst_bps=upd(inst_bps, n_inst_bps),
        avg_bps=upd(avg_bps, n_avg_bps),
        last_time=upd(last_time, time_u),
        active=upd(active, n_active),
    )


@jax.jit
def apply_batch(table: FlowTable, b: UpdateBatch) -> FlowTable:
    """Apply one padded update batch. Donate ``table`` at the call site
    (``jax.jit(apply_batch).lower`` …) or rely on XLA aliasing via the
    wrapper in ingest/batcher.py for true in-place updates.

    Formulated as three inverse-index builds (one int32 scatter each)
    plus vectorized gathers and elementwise merges over the whole table —
    never a per-field scatter (see _inverse_index on why)."""
    n = table.time_start.shape[0]
    scratch = n - 1
    B = b.slot.shape[0]
    real = b.slot < scratch  # padding rows carry slot == scratch
    create = b.is_create & real
    upd_fwd = ~b.is_create & b.is_fwd & real
    upd_rev = ~b.is_create & ~b.is_fwd & real

    # The barrier pins each inverse to ONE materialization: without it XLA
    # clones the scatter into every consumer fusion (~12 consumers × 3
    # inverses = 36 scatters in the optimized HLO, ~66 GB modeled traffic,
    # ~0.5 s/batch measured on TPU; barriered it is 3 scatters and ~ms).
    inv_c, inv_f, inv_r = jax.lax.optimization_barrier((
        _inverse_index(create, b.slot, n),
        _inverse_index(upd_fwd, b.slot, n),
        _inverse_index(upd_rev, b.slot, n),
    ))
    hit_c = inv_c != B

    def gather(col, inv):
        # sentinel row B appended so inv == B reads an inert value. The
        # barrier keeps XLA from fusing the gather into its elementwise
        # consumers — fused gathers serialize on TPU (measured ~130 ms per
        # direction at 2²⁰ rows; barriered, the whole apply is ~ms).
        return jax.lax.optimization_barrier(
            jnp.concatenate([col, jnp.zeros((1,), col.dtype)])[inv]
        )

    time_start = jnp.where(hit_c, gather(b.time, inv_c), table.time_start)
    in_use = table.in_use | hit_c

    fwd = _merged_dir(
        table.fwd, b, gather, time_start, inv_c, inv_f,
        counters_from_batch=True, active_init=True,
    )
    rev = _merged_dir(
        table.rev, b, gather, time_start, inv_c, inv_r,
        counters_from_batch=False, active_init=False,
    )

    return FlowTable(time_start=time_start, in_use=in_use, fwd=fwd, rev=rev)


def _cleared_dir(d: DirState, keep) -> DirState:
    def put(arr):
        return jnp.where(keep, arr, jnp.zeros((), arr.dtype))

    return DirState(
        pkts_lo=put(d.pkts_lo), pkts_f=put(d.pkts_f),
        bytes_lo=put(d.bytes_lo), bytes_f=put(d.bytes_f),
        delta_pkts=put(d.delta_pkts), delta_bytes=put(d.delta_bytes),
        inst_pps=put(d.inst_pps), avg_pps=put(d.avg_pps),
        inst_bps=put(d.inst_bps), avg_bps=put(d.avg_bps),
        last_time=put(d.last_time), active=put(d.active),
    )


@jax.jit
def clear_slots(table: FlowTable, slot: jax.Array) -> FlowTable:
    """Reset the given slots to the empty state (eviction). ``slot`` is a
    fixed-length int32 batch padded with ``capacity`` (the scratch row).

    One boolean-mask scatter (barriered — see apply_batch) followed by
    elementwise clears: the former 26 per-field scatters serialize on TPU
    and would cost ~seconds in a 2²⁰-slot eviction storm."""
    n = table.time_start.shape[0]
    cleared = jnp.zeros(n, bool).at[slot].set(True, mode="drop")
    keep = jax.lax.optimization_barrier(~cleared)
    return FlowTable(
        time_start=jnp.where(keep, table.time_start, 0),
        in_use=table.in_use & keep,
        fwd=_cleared_dir(table.fwd, keep),
        rev=_cleared_dir(table.rev, keep),
    )


def clear_slots_dirty(
    table: FlowTable, dirty: jax.Array, slot: jax.Array
) -> tuple[FlowTable, jax.Array]:
    """``clear_slots`` fused with cache invalidation: an evicted slot's
    features drop to zero, so its cached label is stale — the dirty bit
    comes up with the clear in one dispatch (one slot-batch transfer).
    A reassigned slot would be marked by its create scatter anyway; this
    covers the window where the slot sits empty."""
    return clear_slots(table, slot), dirty.at[slot].set(True, mode="drop")


def mark_dirty_slots(dirty: jax.Array, slot: jax.Array) -> jax.Array:
    """Set the dirty bit for an explicit slot batch (padded with the
    scratch slot) — the re-invalidation path: rows whose subset predict
    was discarded (a degrade trip served stale labels mid-flight) must
    be re-predicted once the ladder recovers."""
    return dirty.at[slot].set(True, mode="drop")


def dirty_count(dirty: jax.Array) -> jax.Array:
    """Number of set dirty bits outside the scratch row — the one
    scalar the host fetches per render tick to pick a compaction
    bucket."""
    return jnp.sum(dirty[:-1].astype(jnp.int32))


def compact_dirty(dirty: jax.Array, bucket: int) -> jax.Array:
    """(bucket,) int32 indices of the dirty rows (scratch excluded),
    padded with ``capacity`` — the static-shape compaction step.
    ``bucket`` is static: serving picks the smallest warmed bucket that
    admits this tick's dirty count (serving/incremental.dirty_buckets),
    so retrace hazard stays one compile per bucket, exactly the
    ingest-scatter discipline."""
    n = dirty.shape[0] - 1
    return jnp.nonzero(
        dirty[:-1], size=bucket, fill_value=n
    )[0].astype(jnp.int32)


def features12_at(table: FlowTable, idx: jax.Array) -> jax.Array:
    """(len(idx), 12) feature rows for exactly the given slots — the
    dirty-set gather. Elementwise-identical to ``features12(table)[idx]``
    (the SAME ``_feature12_cols`` list, same per-element ops: int32→f32
    casts, in_use zeroing), which is what keeps dirty-set prediction
    byte-identical to a full-table re-predict. Padding entries
    (``idx == capacity``) read the scratch row: never in use, so they
    project to zeros and their (garbage) labels are dropped by the
    ``mode="drop"`` cache scatter."""
    X = jnp.stack([c[idx] for c in _feature12_cols(table)], axis=1)
    return jnp.where(table.in_use[idx, None], X, 0.0)


def merge_labels(cache, idx: jax.Array, labels) -> jax.Array:
    """Scatter the dirty rows' fresh labels into the (capacity,) label
    cache. Padding entries carry ``idx == capacity`` — out of bounds
    for the cache, dropped. Jitted with the cache donated by the caller
    (serving/incremental.py) so the persistent device-resident cache
    updates in place."""
    return cache.at[idx].set(labels, mode="drop")


@jax.jit
def stale_mask(table: FlowTable, now, idle_seconds) -> jax.Array:
    """(capacity+1,) bool: in-use slots with no telemetry in either
    direction for ``idle_seconds``. Computed on device so eviction scans
    transfer one bool array instead of three int arrays — the incremental
    evict path that keeps the 2²⁰-flow serving loop off the host
    (VERDICT r1 item 4)."""
    last = jnp.maximum(table.fwd.last_time, table.rev.last_time)
    return table.in_use & (now - last >= idle_seconds)


@jax.jit
def stale_bits(table: FlowTable, now, idle_seconds):
    """Bit-packed ``stale_mask`` — the eviction scan's one device→host
    transfer shrinks 8× (1 MB → 128 KB at capacity 2²⁰; material on this
    rig's ~12 MB/s tunnel). Host side unpacks with ``np.unpackbits``."""
    return jnp.packbits(stale_mask(table, now, idle_seconds))


@functools.partial(jax.jit, static_argnames=("n",))
def top_active_slots(table: FlowTable, n: int, floor):
    """Indices of the ≤n most active in-use slots this tick, ranked by
    |Δbytes| summed over both directions (desc), ties to the lowest slot.

    Deltas persist in the table until a flow's next telemetry record, so
    activity is gated to slots with telemetry strictly newer than
    ``floor`` (the max timestamp of all previous ticks — see
    FlowStateEngine.mark_tick): a flow that moved gigabytes and then
    vanished from telemetry must not dominate the render forever, while
    timestamp skew between datapaths reporting within one tick cannot
    demote a busy flow. Stale in-use slots score 0 — below any
    currently-active flow, above nothing — so they still fill the sample
    on an idle network.

    Device-side ``top_k`` over the whole table, so the host sees O(n) data
    — the render sample tracks live traffic instead of insertion order
    (the reference prints every flow it knows, traffic_classifier.py:99-118;
    at 2²⁰ tracked flows a host-side scan would dominate the tick).
    Returns ``(idx, valid)``: unused slots score −inf and are masked out
    via ``valid``.
    """
    _, idx = jax.lax.top_k(_activity_score(table, floor), n)
    return idx, jnp.take(table.in_use[:-1], idx)


def _activity_score(table: FlowTable, floor):
    """(capacity,) ranking score: |Δbytes| for slots with telemetry newer
    than ``floor``, 0 for stale in-use slots, −inf for unused — THE
    activity definition every ranked surface shares (single-table render,
    per-shard candidates, cross-shard merge ordering)."""
    act = (
        jnp.abs(table.fwd.delta_bytes.astype(jnp.float32))
        + jnp.abs(table.rev.delta_bytes.astype(jnp.float32))
    )[:-1]
    fresh = (
        jnp.maximum(table.fwd.last_time, table.rev.last_time)[:-1] > floor
    )
    return jnp.where(
        table.in_use[:-1], jnp.where(fresh, act, 0.0), -jnp.inf
    )


@functools.partial(jax.jit, static_argnames=("n",))
def top_active_scored(table: FlowTable, labels, n: int, floor):
    """``top_active_render`` plus the activity scores — the per-shard half
    of a cross-shard render merge (parallel/table_sharded.py): each shard
    returns its local top-n with scores; the global top-n is the best n
    of the concatenated candidates, exact because per-shard top-n sets
    contain every global winner and the merge sorts by the same score."""
    vals, idx = jax.lax.top_k(_activity_score(table, floor), n)
    return (
        idx,
        jnp.take(table.in_use[:-1], idx),
        vals,
        jnp.take(labels, idx),
        jnp.take(table.fwd.active[:-1], idx),
        jnp.take(table.rev.active[:-1], idx),
    )


@functools.partial(jax.jit, static_argnames=("n",))
def top_active_flags(table: FlowTable, n: int, floor):
    """``top_active_render`` minus the label gather:
    ``(idx, valid, fwd_active[idx], rev_active[idx])`` for the ≤n most
    active slots. The host-native pipelined serve path dispatches this
    at tick N (fixing the ranked set against tick N's table) while the
    full-table labels are computed later on the device-stage worker by
    the C++ predict — which needs no (capacity,) dummy label vector
    crossing the link just to satisfy ``top_active_render``'s
    signature."""
    idx, valid = top_active_slots(table, n, floor)
    return (
        idx,
        valid,
        jnp.take(table.fwd.active[:-1], idx),
        jnp.take(table.rev.active[:-1], idx),
    )


@functools.partial(jax.jit, static_argnames=("n",))
def top_active_render(table: FlowTable, labels, n: int, floor):
    """Everything one rendered table row needs, gathered on device in one
    dispatch: ``(idx, valid, labels[idx], fwd_active[idx], rev_active[idx])``
    for the ≤n most active slots (ranking of ``top_active_slots``).

    ``labels`` is the (capacity,) vector from a full-table predict and
    stays device-resident — only O(n) scalars cross to the host. A serving
    tick that instead fetched the label and active vectors whole would
    move ~6 MB per tick at capacity 2²⁰, which on this rig's ~12 MB/s
    device tunnel costs more than the 2²⁰-row device predict itself."""
    idx, valid = top_active_slots(table, n, floor)
    return (
        idx,
        valid,
        jnp.take(labels, idx),
        jnp.take(table.fwd.active[:-1], idx),
        jnp.take(table.rev.active[:-1], idx),
    )


def _feature12_cols(table: FlowTable) -> list:
    """The 12 serving-feature columns, (capacity+1,) each, order of
    traffic_classifier.py:104 — THE single source for both the
    full-table projection (``features12``) and the dirty-set gather
    (``features12_at``): incremental serving's byte-identity guarantee
    is exactly that the two consume the same column list with the same
    per-element ops."""
    f, r = table.fwd, table.rev
    return [
        f.delta_pkts.astype(jnp.float32), f.delta_bytes.astype(jnp.float32),
        f.inst_pps, f.avg_pps, f.inst_bps, f.avg_bps,
        r.delta_pkts.astype(jnp.float32), r.delta_bytes.astype(jnp.float32),
        r.inst_pps, r.avg_pps, r.inst_bps, r.avg_bps,
    ]


def features12(table: FlowTable) -> jax.Array:
    """(capacity, 12) online feature matrix, order of
    traffic_classifier.py:104 — rows for unused slots are zero."""
    X = jnp.stack(_feature12_cols(table), axis=1)[:-1]  # drop scratch row
    X = jnp.where(table.in_use[:-1, None], X, 0.0)
    assert X.shape[1] == NUM_FEATURES
    return X


def features16(table: FlowTable) -> jax.Array:
    """(capacity, 16) training-row matrix, order of
    traffic_classifier.py:124-141 / the CSV header at :217."""
    f, r = table.fwd, table.rev
    cols = [
        f.pkts_f, f.bytes_f,
        f.delta_pkts.astype(jnp.float32), f.delta_bytes.astype(jnp.float32),
        f.inst_pps, f.avg_pps, f.inst_bps, f.avg_bps,
        r.pkts_f, r.bytes_f,
        r.delta_pkts.astype(jnp.float32), r.delta_bytes.astype(jnp.float32),
        r.inst_pps, r.avg_pps, r.inst_bps, r.avg_bps,
    ]
    X = jnp.stack(cols, axis=1)[:-1]
    return jnp.where(table.in_use[:-1, None], X, 0.0)
