"""traffic_classifier_sdn_tpu — a TPU-native (JAX/XLA/Pallas/pjit) framework
with the capabilities of ashwinn-v/Traffic-classifier-SDN.

The reference system classifies live SDN network flows into six traffic
classes (dns, game, ping, quake, telnet, voice) by polling Open vSwitch flow
statistics through a Ryu OpenFlow-1.3 controller, engineering 12 per-flow
rate/delta features, and calling pickled scikit-learn estimators one flow at a
time (reference: traffic_classifier.py:98-170, simple_monitor_13.py:31-66).

This framework inverts that shape for TPU hardware: flow state lives in a
device-resident structure-of-arrays, the six classifiers are pure jit/vmap
functions over explicit parameter pytrees, batches are sharded over a
`jax.sharding.Mesh` with XLA collectives (psum/all_gather) doing the
cross-chip merges, and the host side is a thin async ingest shell speaking
the reference's `data\t` line protocol.

Layout:
  core/      flow state + feature engineering as arrays (+ golden Python port)
  models/    six predictors as pure functions over param pytrees
  io/        sklearn-pickle importer, dataset pipeline, checkpointing
  parallel/  mesh, batch-sharded predict, state-sharded KNN/forest
  train/     on-device (re)training for all six model families
  ingest/    line-protocol parsing, replay + live collectors, batching
  ops/       Pallas kernels and tensorized tree evaluation
  utils/     table rendering, config, logging/metrics
"""

__version__ = "0.1.0"

from .core.features import CLASSES_6 as TRAFFIC_CLASSES  # noqa: E402
