#!/bin/sh
# Land every TPU-bound measurement in one pass (run when the chip is up):
#   1. quick liveness probe (exits 1 fast if the worker is wedged)
#   2. bench.py            -> docs/artifacts/bench_tpu_r03.{json,log}
#   3. tools/tpu_proof.py  -> docs/artifacts/tpu_proof.json
#   4. serve bench on TPU  -> docs/artifacts/serve_2m_tpu.json
# Artifacts are only overwritten by runs that actually produced output.
set -e
cd "$(dirname "$0")/.."

timeout 90 python -c "
import jax, numpy as np, jax.numpy as jnp
jax.devices()
print(float(np.asarray(jax.jit(lambda: jnp.sum(jnp.ones((128,128))))())))
" >/dev/null 2>&1 || { echo "TPU worker down"; exit 1; }
echo "TPU up — running the measurement suite"

python bench.py 2>&1 | tee /tmp/tpu_day_bench.log
if grep -q '"platform": "tpu"' /tmp/tpu_day_bench.log; then
  cp /tmp/tpu_day_bench.log docs/artifacts/bench_tpu_r03.log
  grep '^{' /tmp/tpu_day_bench.log | tail -1 \
    > docs/artifacts/bench_tpu_r03.json
fi

python tools/tpu_proof.py

python tools/bench_serve.py --platform default --model forest --ticks 6 \
  2>&1 | tee /tmp/tpu_day_serve.log
if grep '^{' /tmp/tpu_day_serve.log | tail -1 \
    | grep -q '"platform": "tpu"'; then
  grep '^{' /tmp/tpu_day_serve.log | tail -1 \
    > docs/artifacts/serve_2m_tpu.json
fi

echo "tpu_day: all artifacts written"
