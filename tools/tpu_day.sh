#!/bin/sh
# Land every TPU-bound measurement in one pass (run when the chip is up):
#   1. quick liveness probe (exits 1 fast if the worker is wedged)
#   2. serve bench on TPU   -> docs/artifacts/serve_2m_tpu.json
#   3. tools/bench_e2e.py   -> docs/artifacts/e2e_budget_tpu.json
#   4. bench.py             -> docs/artifacts/bench_tpu_r04.{json,log}
#   5. tools/tpu_proof.py   -> docs/artifacts/tpu_proof.json
# Order is risk-ascending: the serve tick and e2e budget use short
# kernels and land the scarcest artifacts first; the bench ladder's
# 1M-row kernels and the Mosaic compiles in the proof have wedged the
# worker before, so they go last — a wedge then costs nothing already
# landed. Artifacts are only overwritten by runs that actually produced
# output. Each step redirects to a log and checks the exit status
# directly — piping through tee would report tee's status and mask
# failures.
set -e
cd "$(dirname "$0")/.."

sh tools/tpu_probe.sh || { echo "TPU worker down"; exit 1; }
echo "TPU up — running the measurement suite"

run_step() {
  # run_step <secs> <log> <cmd...>: fail loudly, always show the log.
  # The timeout bounds a mid-step worker wedge (all JAX calls hang, not
  # fail, on a wedged worker) so one stuck step cannot eat the window;
  # -k escalates to KILL for a python that ignores TERM. (A true
  # D-state hang would outlive even KILL — the observed wedges are
  # interruptible RPC waits, which TERM/KILL do stop.)
  secs="$1"; log="$2"; shift 2
  if timeout -k 30 "$secs" "$@" > "$log" 2>&1; then cat "$log"; else
    cat "$log"; echo "tpu_day: FAILED: $*"; exit 1
  fi
}

run_step 1200 /tmp/tpu_day_serve.log python tools/bench_serve.py \
  --platform default --model forest --ticks 6
if grep '^{' /tmp/tpu_day_serve.log | tail -1 \
    | grep -q '"platform": "tpu"'; then
  grep '^{' /tmp/tpu_day_serve.log | tail -1 \
    > docs/artifacts/serve_2m_tpu.json
fi

if [ -f tools/bench_e2e.py ]; then
  run_step 1200 /tmp/tpu_day_e2e.log python tools/bench_e2e.py
  if grep '^{' /tmp/tpu_day_e2e.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_day_e2e.log | tail -1 \
      > docs/artifacts/e2e_budget_tpu.json
  fi
fi

# chip-day allowance: one warm process gets time for every race stage
# (the driver's own end-of-round run keeps bench.py's 560 s default)
TCSDN_BENCH_BUDGET=1500
export TCSDN_BENCH_BUDGET
run_step 1900 /tmp/tpu_day_bench.log python bench.py
if grep -q '"platform": "tpu"' /tmp/tpu_day_bench.log; then
  cp /tmp/tpu_day_bench.log docs/artifacts/bench_tpu_r04.log
  grep '^{' /tmp/tpu_day_bench.log | tail -1 \
    > docs/artifacts/bench_tpu_r04.json
fi

run_step 1500 /tmp/tpu_day_proof.log python tools/tpu_proof.py

echo "tpu_day: all artifacts written"
