#!/bin/sh
# Land every TPU-bound measurement in one pass (run when the chip is up):
#   1. quick liveness probe (exits 1 fast if the worker is wedged)
#   2. tools/tpu_doctor.py  -> docs/artifacts/tpu_doctor_tpu.json
#   3. serve bench on TPU   -> docs/artifacts/serve_2m_tpu.json
#   4. tools/bench_e2e.py   -> docs/artifacts/e2e_budget_tpu.json
#   5. bench.py             -> docs/artifacts/bench_tpu_r05.{json,log}
#   6. tools/tpu_proof.py   -> docs/artifacts/tpu_proof.json
# Order is risk-ascending: the serve tick and e2e budget use short
# kernels and land the scarcest artifacts first; the bench ladder's
# 1M-row kernels and the Mosaic compiles in the proof have wedged the
# worker before, so they go last — a wedge then costs nothing already
# landed. Artifacts are only overwritten by runs that actually produced
# output. Each step redirects to a log and checks the exit status
# directly — piping through tee would report tee's status and mask
# failures.
set -e
cd "$(dirname "$0")/.."

sh tools/tpu_probe.sh || { echo "TPU worker down"; exit 1; }
echo "TPU up — running the measurement suite"

FAILED_STEPS=""
run_step() {
  # run_step <secs> <log> <cmd...>: run EVERY step, fail loudly at the
  # END (one bad step must not cost the window's remaining artifacts).
  # STEP_OK gates each landing block below: a failed/timed-out step's
  # partial output must never overwrite a complete artifact from a
  # prior run (a healthy-but-budget-stopped bench still exits 0, so its
  # best-so-far line lands). The timeout bounds a mid-step worker wedge
  # (all JAX calls hang, not fail, on a wedged worker); -k escalates to
  # KILL for a python that ignores TERM. After a timeout, re-probe: if
  # the worker is wedged, the remaining steps would serially burn their
  # whole timeouts against a dead worker — bail out instead.
  secs="$1"; log="$2"; shift 2
  if timeout -k 30 "$secs" "$@" > "$log" 2>&1; then
    cat "$log"; STEP_OK=1
  else
    rc=$?
    cat "$log"; echo "tpu_day: FAILED (rc=$rc): $*"
    FAILED_STEPS="$FAILED_STEPS [$*]"
    STEP_OK=0
    if [ "$rc" -ge 124 ] && ! sh tools/tpu_probe.sh; then
      echo "tpu_day: worker wedged mid-suite — aborting remaining steps"
      exit 1
    fi
  fi
}

# preflight doctor FIRST: ~30 s of instrumented micro-serve answering
# "is this window worth spending?" — platform identity, compile-time
# budget, zero-retrace hygiene, HBM headroom, transfer counts vs the
# static sync ledger, tick cadence. The doctor writes its own bundle
# atomically via --out, so the pass/fail evidence lands even when a
# later stage wedges the worker and the suite aborts.
run_step 300 /tmp/tpu_day_doctor.log python tools/tpu_doctor.py \
  --platform default --expect tpu \
  --out docs/artifacts/tpu_doctor_tpu.json

run_step 1200 /tmp/tpu_day_serve.log python tools/bench_serve.py \
  --platform default --model forest --ticks 6
if [ "$STEP_OK" = 1 ] && grep '^{' /tmp/tpu_day_serve.log | tail -1 \
    | grep -q '"platform": "tpu"'; then
  grep '^{' /tmp/tpu_day_serve.log | tail -1 \
    > docs/artifacts/serve_2m_tpu.json
fi

if [ -f tools/bench_e2e.py ]; then
  run_step 1200 /tmp/tpu_day_e2e.log python tools/bench_e2e.py
  if [ "$STEP_OK" = 1 ] && grep '^{' /tmp/tpu_day_e2e.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_day_e2e.log | tail -1 \
      > docs/artifacts/e2e_budget_tpu.json
  fi
fi

# native-ingest serving budget: the whole wire path (tck_feed_lines →
# pinned tck_flush_wire staging → scatter → predict → render) at batch
# 16k, native-vs-Python A/B with the render-identity gate — the chip
# twin of docs/artifacts/e2e_budget_native_cpu.json
if [ -f tools/bench_e2e.py ]; then
  run_step 1200 /tmp/tpu_day_e2e_native.log python tools/bench_e2e.py \
    --serve-budget
  if [ "$STEP_OK" = 1 ] && grep '^{' /tmp/tpu_day_e2e_native.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_day_e2e_native.log | tail -1 \
      > docs/artifacts/e2e_budget_native_tpu.json
  fi
fi

# the live counterpart: the latency-provenance waterfall through the
# REAL fan-in serve path (short kernels, ~1 min) — lands beside the
# microbench budget so the chip window carries both views
if [ -f tools/bench_e2e_live.py ]; then
  run_step 1200 /tmp/tpu_day_e2e_live.log python tools/bench_e2e_live.py \
    --platform default
  if [ "$STEP_OK" = 1 ] && grep '^{' /tmp/tpu_day_e2e_live.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_day_e2e_live.log | tail -1 \
      > docs/artifacts/e2e_budget_live_tpu.json
  fi
fi

# region composition on chip: fan-in × sharded mesh × incremental ×
# native ingest in one serve, swept over (sources × shards × churn)
# with the byte-identity phase and the zero-compiles-in-measured-ticks
# gate — the TPU twin of serve_region_cpu.json. Runs behind the doctor
# preflight above like everything else; short per-level kernels, but
# the grid is 12 levels, so it gets the full step budget.
run_step 1200 /tmp/tpu_day_region.log python tools/bench_serve.py \
  --region-sweep --platform default \
  --capacity 262144 --flows-per-tick 131072 --ticks 6 --table-rows 64
if [ "$STEP_OK" = 1 ] && grep '^{' /tmp/tpu_day_region.log | tail -1 \
    | grep -q '"platform": "tpu"'; then
  grep '^{' /tmp/tpu_day_region.log | tail -1 \
    > docs/artifacts/serve_region_tpu.json
fi

# open-set eval on chip: the six-family fit + score sweep is short
# kernels only (~2 min) — the TPU twin of openset_eval_cpu.json
if [ -f tools/bench_openset.py ]; then
  run_step 1200 /tmp/tpu_day_openset.log python tools/bench_openset.py \
    --platform default
  if [ "$STEP_OK" = 1 ] && grep '^{' /tmp/tpu_day_openset.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_day_openset.log | tail -1 \
      > docs/artifacts/openset_eval_tpu.json
  fi
fi

# adversarial scenario matrix on chip: the campaign timelines scored
# against their SLO gates with the device in the loop — the TPU twin
# of scenario_matrix_cpu.json. bench_scenarios.py writes the artifact
# itself (platform-keyed filename) and exits nonzero on gate failure,
# so the platform guard rides the artifact name, not a grep.
if [ -f tools/bench_scenarios.py ]; then
  run_step 1200 /tmp/tpu_day_scenarios.log python tools/bench_scenarios.py \
    --platform default --profile cpu \
    --obs-dir /tmp/tpu_day_scenario_postmortem
fi

# KNN kernel evidence on chip: the pruned-exact A/B + the IVF recall
# sweep (tools/bench_knn.py; short kernels — the sweep reuses one warm
# process). Writes *_cpu.json paths by default; land the chip twins
# explicitly so the CPU evidence is never overwritten by a chip run.
if [ -f tools/bench_knn.py ]; then
  run_step 1200 /tmp/tpu_day_knn.log python tools/bench_knn.py \
    --platform default \
    --out-prune /tmp/knn_prune_chip.json \
    --out-recall /tmp/knn_ivf_recall_chip.json
  if [ "$STEP_OK" = 1 ] \
      && grep -q '"platform": "tpu"' /tmp/knn_prune_chip.json; then
    cp /tmp/knn_prune_chip.json docs/artifacts/knn_prune_tpu.json
    cp /tmp/knn_ivf_recall_chip.json \
      docs/artifacts/knn_ivf_recall_tpu.json
    echo "tpu_day: knn prune + ivf recall landed"
  fi
fi

# syncguard on chip: the five serve suites with the runtime transfer
# witness armed (utils/syncguard.py) and jax.transfer_guard=log for
# corroboration — on TPU a host↔device crossing is a REAL wire
# transfer, so this is the strongest form of the hot-path sync-budget
# check. Each test's observed per-site counts accumulate into the
# report; a pass means every hot-span sync matched the static budget
# (docs/artifacts/hot_path_sync_budget.json) on real hardware, and the
# observed economy lands as the artifact's chip twin.
rm -f /tmp/tpu_day_syncguard.json
run_step 1200 /tmp/tpu_day_sync.log env TCSDN_SYNCGUARD=1 \
  TCSDN_SYNCGUARD_TG=log \
  TCSDN_SYNCGUARD_REPORT=/tmp/tpu_day_syncguard.json \
  python -m pytest tests/test_pipeline.py tests/test_incremental.py \
    tests/test_degrade.py tests/test_drift.py tests/test_openset.py \
    -q -m "not slow" -p no:cacheprovider
if [ "$STEP_OK" = 1 ] && [ -f /tmp/tpu_day_syncguard.json ]; then
  cp /tmp/tpu_day_syncguard.json \
    docs/artifacts/hot_path_sync_budget_tpu.json
  echo "tpu_day: observed sync budget landed"
fi

# chip-day allowance: one warm process gets time for every race stage —
# including the 4-way+ KNN top-k chip race (sort/argmax/hier*/screened*
# now race inside bench.py stage 4b; the parity-gated winner promotes)
# (the driver's own end-of-round run keeps bench.py's 560 s default)
TCSDN_BENCH_BUDGET=1500
export TCSDN_BENCH_BUDGET
run_step 1900 /tmp/tpu_day_bench.log python bench.py
if [ "$STEP_OK" = 1 ] \
    && grep -q '"platform": "tpu"' /tmp/tpu_day_bench.log; then
  cp /tmp/tpu_day_bench.log docs/artifacts/bench_tpu_r05.log
  grep '^{' /tmp/tpu_day_bench.log | tail -1 \
    > docs/artifacts/bench_tpu_r05.json
fi

run_step 1500 /tmp/tpu_day_proof.log python tools/tpu_proof.py

if [ -n "$FAILED_STEPS" ]; then
  echo "tpu_day: FAILED steps:$FAILED_STEPS"
  exit 1
fi
echo "tpu_day: all artifacts written"
