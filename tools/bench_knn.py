#!/usr/bin/env python
"""KNN kernel bench: the pruned-exact A/B and the IVF recall sweep.

Two artifacts, one same-run process (the same-run discipline every
raced kernel rides — identical corpus, identical query batch, identical
host):

- ``docs/artifacts/knn_prune_cpu.json`` — the EXACT tier. Native C++:
  pruned (cluster-screened, f32-screen + early-abandon;
  native/knn_eval.cpp) vs unpruned (the original blocked full scan) on
  the same handle, with vote-for-vote parity ENFORCED (the bench exits
  nonzero on any divergence) plus label parity vs the XLA sort oracle.
  XLA: ``screened`` (bound-screened group selection, models/knn.py) vs
  ``sort`` (``lax.top_k``) at the serving batch, with bitwise
  neighbor-index parity enforced.

- ``docs/artifacts/knn_ivf_recall_cpu.json`` — the APPROXIMATE tier
  (ops/knn_ivf.py, ``--knn-topk ivf``). nprobe sweep with measured
  recall@1 (IVF top-1 neighbor == exact top-1), label agreement vs the
  exact sort path, and speedup columns for both the XLA and native
  mirrors; the nprobe == n_lists anchor is asserted bit-for-bit equal
  to the exact search, and the shipped DEFAULT_NPROBE must clear the
  >= 0.99 recall@1 gate (exit nonzero otherwise — the opt-in's evidence
  must exist before the opt-in is honest).

Corpus: the reference KNeighbors checkpoint when the image carries it,
else a conversation-structured synthetic at reference scale (S=4448,
k=5, 6 classes — cumulative snapshot rows per flow, the geometry the
serving path actually sees; an i.i.d. gamma cloud is the documented
WORST case for metric pruning and is reported as a secondary line).

Usage: python tools/bench_knn.py [--batch 16384] [--repeat 3]
       [--out-prune PATH] [--out-recall PATH] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _flow_corpus(rng, S, n_cls=6, rows_per_conv=8):
    """Conversation-structured corpus: per-flow cumulative snapshots."""
    import numpy as np

    theta = rng.gamma(2.0, 100.0, (n_cls, 12))
    conv = max(1, S // rows_per_conv)
    ccls = rng.randint(0, n_cls, conv)
    base = rng.gamma(2.0, 1.0, (conv, 12)) * theta[ccls]
    rows, ys = [], []
    for i in range(conv):
        t = np.sort(rng.uniform(0.1, 1.0, rows_per_conv))[:, None]
        rows.append(np.abs(
            base[i] * t * (1 + rng.normal(0, 0.02, (rows_per_conv, 12)))
        ))
        ys += [int(ccls[i])] * rows_per_conv
    X = np.concatenate(rows)[:S].astype(np.float64)
    return X, np.asarray(ys[:S], np.int32)


def _median_rate(fn, n_rows, repeat):
    best = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        fn()
        best.append(time.perf_counter() - t0)
    best.sort()
    return n_rows / best[len(best) // 2]


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument(
        "--out-prune", default="docs/artifacts/knn_prune_cpu.json"
    )
    ap.add_argument(
        "--out-recall", default="docs/artifacts/knn_ivf_recall_cpu.json"
    )
    ap.add_argument(
        "--platform", choices=("cpu", "default"), default="cpu",
        help="cpu (safe anywhere) or default (real TPU when healthy)",
    )
    args = ap.parse_args()
    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.models import knn
    from traffic_classifier_sdn_tpu.native import knn as native_knn
    from traffic_classifier_sdn_tpu.ops import knn_ivf

    platform = jax.devices()[0].platform
    rng = np.random.RandomState(7)
    models_dir = os.environ.get(
        "TCSDN_MODELS_DIR", "/root/reference/models"
    )
    ref = os.path.join(models_dir, "KNeighbors")
    if os.path.exists(ref):
        from traffic_classifier_sdn_tpu.io import sklearn_import as ski

        d = ski.import_knn(ref)
        corpus_kind = "reference"
    else:
        X, y = _flow_corpus(rng, 4448)
        d = {"fit_X": X, "y": y, "n_neighbors": 5,
             "classes": np.arange(6)}
        corpus_kind = "flow-synthetic"
    S = int(np.asarray(d["fit_X"]).shape[0])
    params = knn.from_numpy(d, dtype=jnp.float32)
    # serving-like queries: corpus points under churn-scale jitter
    sel = rng.choice(S, args.batch)
    Xq = np.abs(
        np.asarray(d["fit_X"], np.float64)[sel]
        * (1 + rng.normal(0, 0.05, (args.batch, 12)))
    ).astype(np.float32)
    Xd = jnp.asarray(Xq)

    # ---- exact tier: native pruned vs unpruned --------------------------
    if not native_knn.available():
        sys.exit("bench_knn: g++ unavailable — no native evaluator")
    hk = native_knn.NativeKnn(d)
    hk.predict(Xq[:256])
    hk.predict_unpruned(Xq[:256])  # warm both paths
    got_p = hk.predict(Xq)
    got_u = hk.predict_unpruned(Xq)
    if not (got_p == got_u).all():
        sys.exit("bench_knn: PRUNED/UNPRUNED PARITY FAILED")
    votes_ok = bool((hk.votes(Xq[:2048])
                     == hk.votes_unpruned(Xq[:2048])).all())
    if not votes_ok:
        sys.exit("bench_knn: PRUNED/UNPRUNED VOTE PARITY FAILED")
    want_sort = np.asarray(jax.jit(knn.predict)(params, Xd))
    native_sort_parity = float((got_p == want_sort).mean() * 100.0)
    pruned_rate = _median_rate(
        lambda: hk.predict(Xq), args.batch, args.repeat
    )
    unpruned_rate = _median_rate(
        lambda: hk.predict_unpruned(Xq), args.batch, args.repeat
    )
    scr, ab, qn = hk.screen_stats()

    # ---- exact tier: XLA screened vs sort -------------------------------
    sort_fn = jax.jit(knn.predict)
    scr_fn = jax.jit(
        lambda p, x: knn.predict(p, x, top_k_impl="screened")
    )
    jax.block_until_ready(sort_fn(params, Xd))
    jax.block_until_ready(scr_fn(params, Xd))
    # bitwise neighbor-index parity, not just labels
    sim = knn._neighbor_sim(params, Xd)
    idx_sort = np.asarray(
        jax.jit(lambda s: jax.lax.top_k(s, params.n_neighbors)[1])(sim)
    )
    idx_scr = np.asarray(jax.jit(
        lambda s: knn._topk_screened_idx(s, params.n_neighbors)
    )(sim))
    if not (idx_sort == idx_scr).all():
        sys.exit("bench_knn: SCREENED/SORT BITWISE PARITY FAILED")
    sort_rate = _median_rate(
        lambda: jax.block_until_ready(sort_fn(params, Xd)),
        args.batch, args.repeat,
    )
    screened_rate = _median_rate(
        lambda: jax.block_until_ready(scr_fn(params, Xd)),
        args.batch, args.repeat,
    )

    prune_line = {
        "artifact": "knn_prune",
        "platform": platform,
        "corpus": corpus_kind,
        "corpus_rows": S,
        "n_neighbors": int(params.n_neighbors),
        "batch": args.batch,
        "repeat": args.repeat,
        "knn_native_topk_flows_per_sec": round(pruned_rate, 1),
        "knn_native_unpruned_topk_flows_per_sec": round(
            unpruned_rate, 1
        ),
        "native_prune_speedup": round(pruned_rate / unpruned_rate, 3),
        "native_parity_pruned_vs_unpruned_pct": 100.0,  # enforced above
        "native_votes_parity": votes_ok,
        "native_label_parity_vs_sort_pct": round(
            native_sort_parity, 3
        ),
        "native_candidates_screened_per_query": round(scr / qn, 1),
        "native_candidates_abandoned_per_query": round(ab / qn, 1),
        "knn_sort_topk_flows_per_sec": round(sort_rate, 1),
        "knn_screened_topk_flows_per_sec": round(screened_rate, 1),
        "screened_vs_sort_speedup": round(screened_rate / sort_rate, 3),
        "screened_bitwise_parity": True,  # enforced above
        "screened_beats_sort": bool(screened_rate > sort_rate),
    }
    print(json.dumps(prune_line), flush=True)

    # ---- approximate tier: IVF recall sweep -----------------------------
    ivf = knn_ivf.build(params)
    K = ivf.n_lists
    assign = knn_ivf.assignments(
        np.asarray(params.fit_X), np.asarray(ivf.centers)
    )
    hk.build_ivf(np.asarray(ivf.centers), assign)
    # the nprobe == K anchor: bit-for-bit the exact search, both tiers
    full_x = np.asarray(jax.jit(
        lambda p, x: knn_ivf.predict(p, x, nprobe=K)
    )(ivf, Xd))
    if not (full_x == want_sort).all():
        sys.exit("bench_knn: IVF nprobe=K != EXACT (XLA)")
    if not (hk.predict_ivf(Xq, K) == got_p).all():
        sys.exit("bench_knn: IVF nprobe=K != EXACT (native)")
    sweep = []
    nprobes = sorted({1, 2, 4, 8, 16, 32, K} & set(range(1, K + 1)))
    exact1 = np.asarray(knn_ivf.exact_top1(params, Xd))
    for npb in nprobes:
        fn = jax.jit(lambda p, x, _n=npb: knn_ivf.predict(p, x, _n))
        jax.block_until_ready(fn(ivf, Xd))
        xla_rate = _median_rate(
            lambda: jax.block_until_ready(fn(ivf, Xd)),
            args.batch, args.repeat,
        )
        nat_rate = _median_rate(
            lambda: hk.predict_ivf(Xq, npb), args.batch, args.repeat
        )
        top1 = np.asarray(knn_ivf.ivf_top1(ivf, Xd, npb))
        labels = np.asarray(fn(ivf, Xd))
        sweep.append({
            "nprobe": int(npb),
            "recall_at_1": round(float((top1 == exact1).mean()), 5),
            "label_agreement_vs_sort": round(
                float((labels == want_sort).mean()), 5
            ),
            "xla_flows_per_sec": round(xla_rate, 1),
            "native_flows_per_sec": round(nat_rate, 1),
            "xla_speedup_vs_sort": round(xla_rate / sort_rate, 3),
            "native_speedup_vs_unpruned": round(
                nat_rate / unpruned_rate, 3
            ),
        })
        print(f"# nprobe={npb}: recall@1={sweep[-1]['recall_at_1']} "
              f"native {nat_rate:,.0f}/s xla {xla_rate:,.0f}/s",
              flush=True)
    default_row = next(
        r for r in sweep
        if r["nprobe"] == min(knn_ivf.DEFAULT_NPROBE, K)
    )
    recall_line = {
        "artifact": "knn_ivf_recall",
        "platform": platform,
        "corpus": corpus_kind,
        "corpus_rows": S,
        "n_lists": K,
        "batch": args.batch,
        "default_nprobe": int(min(knn_ivf.DEFAULT_NPROBE, K)),
        "default_nprobe_recall_at_1": default_row["recall_at_1"],
        "default_nprobe_recall_ok": bool(
            default_row["recall_at_1"] >= 0.99
        ),
        "nprobe_equals_K_bitwise_exact": True,  # enforced above
        "sweep": sweep,
        "knn_sort_topk_flows_per_sec": round(sort_rate, 1),
        "knn_native_unpruned_topk_flows_per_sec": round(
            unpruned_rate, 1
        ),
    }
    print(json.dumps(recall_line), flush=True)
    if not recall_line["default_nprobe_recall_ok"]:
        sys.exit(
            "bench_knn: shipped DEFAULT_NPROBE misses the 0.99 "
            "recall@1 gate — the ivf opt-in's evidence is not honest"
        )
    for path, line in ((args.out_prune, prune_line),
                       (args.out_recall, recall_line)):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            json.dump(line, fh, indent=1)
            fh.write("\n")
        print(f"# wrote {path}", flush=True)


if __name__ == "__main__":
    main()
