#!/usr/bin/env python
"""Fleet launcher: N region serves + one roster-of-rosters scrape target.

Takes the composed region serve (fan-in × sharded × incremental ×
native ingest) HORIZONTAL: launches ``--members`` serve processes, each
owning a contiguous partition of ``--total-sources`` telemetry sources
(serving/fleet.partition_sources), all sharing ONE model-checkpoint
rotation directory (``--drift-dir``). Member 0 is the leader; every
other member runs ``--drift-follow``, so a promotion staged by any
member propagates fleet-wide through each follower's OWN parity-gated
probes (the wrong-but-fresh gate is never bypassed — see
serving/drift.py and tests/test_fleet.py for the e2e proof on a
virtual clock).

Each member binds an ephemeral observability plane (``--obs-port 0``);
the launcher parses the bound port off the member's startup line and
raises serving/fleet.FleetAggregator over the member ``/healthz``
URLs — one scrape answers the whole region: member health conjunction,
every fan-in source annotated with its member, drift state per member,
``promotions_total`` to watch a promotion sweep the fleet.

Emits one JSON roster line once the fleet is up, then a fleet summary
line per ``--poll-s`` until the members exit (``--max-ticks``) or
SIGINT. Exit status 0 iff every member exited 0.

Usage:
  tools/fleet_serve.py gaussiannb --native-checkpoint CKPT \
      --members 2 --total-sources 8 --shards 8 \
      --drift-dir /tmp/fleet-rotation --max-ticks 30

(CPU-safe: forces the host platform unless --platform default; with
--shards N it also forces an N-device host mesh per member.)
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from traffic_classifier_sdn_tpu.serving import fleet  # noqa: E402

_OBS_LINE = re.compile(r"observability plane on port (\d+)")


def _member_argv(args: argparse.Namespace, count: int,
                 follower: bool) -> list[str]:
    argv = [
        sys.executable, "-m", "traffic_classifier_sdn_tpu.cli",
        args.model,
        "--source", "synthetic",
        "--synthetic-flows", str(args.synthetic_flows),
        "--sources", str(count),
        "--capacity", str(args.capacity),
        "--table-rows", str(args.table_rows),
        "--print-every", str(args.print_every),
        "--max-ticks", str(args.max_ticks),
        "--obs-port", "0",
    ]
    if args.native_checkpoint:
        argv += ["--native-checkpoint", args.native_checkpoint]
    if args.shards:
        argv += ["--shards", str(args.shards)]
    if args.drift_dir:
        argv += ["--drift", "auto", "--drift-dir", args.drift_dir]
        if follower:
            argv.append("--drift-follow")
    if args.lockstep:
        argv.append("--source-lockstep")
    argv += args.member_arg
    return argv


class _Member:
    """One serve process + the stderr pump that finds its obs port."""

    def __init__(self, idx: int, span: tuple[int, int],
                 argv: list[str], env: dict, log_path: str | None):
        self.idx = idx
        self.span = span
        self.port: int | None = None
        self._port_found = threading.Event()
        self._log = open(log_path, "wb") if log_path else None
        self.proc = subprocess.Popen(
            argv, stdout=self._log or subprocess.DEVNULL,
            stderr=subprocess.PIPE, env=env,
        )
        # drain stderr forever (a full pipe would wedge the member);
        # the first obs line carries the ephemeral port
        self._pump = threading.Thread(
            target=self._drain, name=f"fleet-member-{idx}-stderr",
            daemon=True,
        )
        self._pump.start()

    def _drain(self) -> None:
        for raw in self.proc.stderr:
            if self._log is not None:
                self._log.write(raw)
                self._log.flush()
            if self.port is None:
                m = _OBS_LINE.search(raw.decode(errors="replace"))
                if m:
                    self.port = int(m.group(1))
                    self._port_found.set()
        self._port_found.set()  # EOF: stop any waiter either way

    def wait_port(self, timeout: float) -> int | None:
        self._port_found.wait(timeout)
        return self.port

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        if self._log is not None:
            self._log.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0], prog="fleet_serve.py"
    )
    ap.add_argument("model", help="model family for every member "
                    "(e.g. gaussiannb)")
    ap.add_argument("--native-checkpoint", default=None)
    ap.add_argument("--members", type=int, default=2)
    ap.add_argument("--total-sources", type=int, default=4,
                    help="region-wide telemetry sources, partitioned "
                    "contiguously across members")
    ap.add_argument("--shards", type=int, default=0,
                    help="per-member device shards (0 = single-device "
                    "spine)")
    ap.add_argument("--drift-dir", default=None, metavar="DIR",
                    help="SHARED rotation directory — what makes the "
                    "fleet one system; member 0 leads, the rest follow")
    ap.add_argument("--synthetic-flows", type=int, default=16)
    ap.add_argument("--capacity", type=int, default=1024)
    ap.add_argument("--table-rows", type=int, default=8)
    ap.add_argument("--print-every", type=int, default=5,
                    help="member render cadence in ticks (renders also "
                    "feed the drift capture, so keep it > 0; member "
                    "stdout goes to --log-dir or is discarded)")
    ap.add_argument("--max-ticks", type=int, default=30)
    ap.add_argument("--lockstep", action="store_true",
                    help="lockstep fan-in pumps (deterministic demo)")
    ap.add_argument("--port", type=int, default=0,
                    help="aggregator bind port (0 = ephemeral)")
    ap.add_argument("--poll-s", type=float, default=2.0)
    ap.add_argument("--log-dir", default=None,
                    help="per-member stdout+stderr logs "
                    "(member-<i>.log); default discards stdout")
    ap.add_argument("--platform", choices=("cpu", "default"),
                    default="cpu")
    ap.add_argument("--member-arg", action="append", default=[],
                    metavar="ARG", help="extra argv appended to every "
                    "member (repeatable)")
    args = ap.parse_args(argv)

    if args.members < 1:
        ap.error("--members must be >= 1")
    spans = fleet.partition_sources(args.total_sources, args.members)
    if any(n == 0 for _, n in spans):
        ap.error(
            f"--total-sources {args.total_sources} leaves an idle "
            f"member at --members {args.members}"
        )

    env = dict(os.environ)
    if args.platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        if args.shards:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={args.shards}"
            ).strip()
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    members: list[_Member] = []
    rc = 0
    try:
        for i, span in enumerate(spans):
            log = (os.path.join(args.log_dir, f"member-{i}.log")
                   if args.log_dir else None)
            members.append(_Member(
                i, span,
                _member_argv(args, span[1], follower=i > 0),
                env, log,
            ))
        urls = []
        for m in members:
            port = m.wait_port(timeout=120.0)
            if port is None:
                print(
                    f"ERROR: member {m.idx} died before binding its "
                    f"observability plane (rc={m.proc.poll()})",
                    file=sys.stderr,
                )
                return 1
            urls.append(f"http://127.0.0.1:{port}/healthz")

        with fleet.FleetAggregator(urls, port=args.port) as agg:
            print(json.dumps({
                "fleet_healthz": f"http://127.0.0.1:{agg.port}/healthz",
                "members": [
                    {"member": m.idx, "pid": m.proc.pid,
                     "obs_port": m.port,
                     "sources": {"first": m.span[0], "count": m.span[1]}}
                    for m in members
                ],
                "drift_dir": args.drift_dir,
            }, sort_keys=True), flush=True)
            while any(m.proc.poll() is None for m in members):
                time.sleep(args.poll_s)
                healthy, report = agg.check()
                print(json.dumps({
                    "healthy": healthy,
                    "members_reachable": report["members_reachable"],
                    "members_healthy": report["members_healthy"],
                    "drift_states": report["drift_states"],
                    "swapped": report["swapped"],
                    "promotions_total": report["promotions_total"],
                }, sort_keys=True), flush=True)
        rc = max(
            (m.proc.returncode or 0 for m in members), default=0
        )
    except KeyboardInterrupt:
        rc = 130
    finally:
        for m in members:
            m.stop()
    return rc


if __name__ == "__main__":
    signal.signal(signal.SIGINT, signal.default_int_handler)
    sys.exit(main())
