#!/usr/bin/env python
"""Fake OpenFlow monitor: emits the reference's telemetry line protocol
(simple_monitor_13.py:49-66 format) for a synthetic flow population —
an end-to-end stand-in for `sudo ryu run simple_monitor_13.py` that needs
no Mininet/OVS/Ryu (the test seam SURVEY.md §4b calls for).

Usage: python tools/fake_monitor.py [n_flows] [n_ticks] [period_sec]
"""

import sys
import time

sys.path.insert(0, ".")

from traffic_classifier_sdn_tpu.ingest.protocol import format_line
from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows


def main() -> None:
    n_flows = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    n_ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    period = float(sys.argv[3]) if len(sys.argv) > 3 else 1.0

    out = sys.stdout.buffer
    # the human header line the reference's monitor logs first
    # (simple_monitor_13.py:32) — consumers must ignore it
    out.write(b"datapath         in-port eth-dst           out-port packets  bytes\n")
    out.flush()
    syn = SyntheticFlows(n_flows=n_flows)
    for _ in range(n_ticks):
        for r in syn.tick():
            out.write(format_line(r))
        out.flush()
        if period > 0:
            time.sleep(period)


if __name__ == "__main__":
    main()
