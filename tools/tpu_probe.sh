#!/bin/sh
# Shared TPU liveness probe for the chip-day scripts (tpu_day.sh,
# tpu_extras.sh): exits 0 iff jax initializes AND the default platform
# is a real TPU (a CPU-only host must not pass) AND a tiny jit executes.
# A wedged worker hangs in init, so the timeout converts the hang into a
# fast failure.
timeout 90 python -c "
import jax, numpy as np, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu', jax.devices()
print(float(np.asarray(jax.jit(lambda: jnp.sum(jnp.ones((128,128))))())))
" >/dev/null 2>&1
