#!/usr/bin/env python
"""Compiled-on-TPU proof artifact for the two Pallas kernels.

Runs ``ops/pallas_forest.py`` and ``ops/pallas_rbf.py`` COMPILED (never
interpret mode) on the default platform, asserts argmax parity against
independent oracles (vectorized NumPy node-walk of the checkpoint trees;
sklearn's own ``SVC.predict``) and against the XLA production paths
(``ops/tree_gemm``, ``models/svc``), races both pairs at two batch sizes,
and writes one JSON artifact to ``docs/artifacts/`` — the evidence VERDICT
round 2 found missing (the kernels had only ever run interpreted on CPU).

Usage: tools/tpu_proof.py [--out docs/artifacts/tpu_proof.json]
                          [--batches 16384,131072]

The kernels' HBM-traffic claims live in their module docstrings
(ops/pallas_forest.py, ops/pallas_rbf.py); the reference hot loop they
replace is sklearn's fused Cython predict at traffic_classifier.py:103-106.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/artifacts/tpu_proof.json")
    ap.add_argument("--batches", default="16384,131072")
    ap.add_argument("--models-dir", default="/root/reference/models")
    ap.add_argument("--data-dir", default="/root/reference/datasets")
    args = ap.parse_args()
    batches = [int(b) for b in args.batches.split(",")]

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax
    import jax.numpy as jnp

    import bench
    from traffic_classifier_sdn_tpu.io import sklearn_import as ski
    from traffic_classifier_sdn_tpu.io.datasets import load_reference_datasets
    from traffic_classifier_sdn_tpu.models import svc as svc_mod
    from traffic_classifier_sdn_tpu.ops import pallas_forest, pallas_rbf, tree_gemm

    t0 = time.time()
    # stderr liveness markers: device init and Mosaic compiles over the
    # tunnel can take minutes each, and a silent run is indistinguishable
    # from a wedged worker (the r04 chip day lost 20+ min to exactly that
    # ambiguity) — so mark BEFORE the first blocking call
    def mark(msg: str) -> None:
        print(f"# {msg}", file=sys.stderr, flush=True)

    mark("initializing devices")
    platform = jax.devices()[0].platform
    mark(f"devices: {jax.devices()}")
    out: dict = {
        "metric": "pallas_compiled_proof",
        "platform": platform,
        "interpret_mode": False,
        "batches": batches,
    }
    if platform != "tpu":
        out["warning"] = (
            "not running on TPU — Pallas compiles are Mosaic/TPU-only; "
            "this artifact only proves the claim on platform=tpu"
        )

    ds = load_reference_datasets(args.data_dir)
    rng = np.random.RandomState(0)
    X_big = np.abs(
        rng.gamma(1.5, 200.0, (max(batches), 12))
    ).astype(np.float32)

    # ---- forest: fused Pallas vs XLA GEMM form vs NumPy node-walk -------
    forest_raw = ski.import_forest(f"{args.models_dir}/RandomForestClassifier")
    g_gemm = tree_gemm.compile_forest(forest_raw)  # bucketed by default
    mark("compiling pallas forest (1 bucket)")
    g_pal = pallas_forest.compile_forest(forest_raw)
    mark("compiling pallas forest (8 buckets)")
    g_pal_b = pallas_forest.compile_forest(forest_raw, n_buckets=8)
    mark("running forest parity predicts")
    Xd = jnp.asarray(ds.X, jnp.float32)
    want = bench._numpy_forest_labels(forest_raw, ds.X)
    got_pal = np.asarray(jax.jit(pallas_forest.predict)(g_pal, Xd))
    got_pal_b = np.asarray(jax.jit(pallas_forest.predict)(g_pal_b, Xd))
    got_gemm = np.asarray(jax.jit(tree_gemm.predict)(g_gemm, Xd))
    out["forest"] = {
        "parity_rows": int(ds.X.shape[0]),
        "pallas_vs_oracle_pct": round(
            float((got_pal == want).mean() * 100.0), 3
        ),
        "pallas_bucketed_vs_oracle_pct": round(
            float((got_pal_b == want).mean() * 100.0), 3
        ),
        "xla_vs_oracle_pct": round(
            float((got_gemm == want).mean() * 100.0), 3
        ),
        "pallas_vs_xla_pct": round(
            float((got_pal == got_gemm).mean() * 100.0), 3
        ),
        "timings_device_ms": {},
    }
    # fast-stage variant (bf16x3 stage-1 + int8 stage-2): guarded so a
    # Mosaic rejection of the int8 dot never costs the baseline proof
    g_pal_f = None
    try:
        mark("compiling pallas forest (fast stages)")
        g_pal_f = pallas_forest.compile_forest(
            forest_raw, n_buckets=8, fast_stages=True
        )
        got_pal_f = np.asarray(jax.jit(pallas_forest.predict)(g_pal_f, Xd))
        out["forest"]["pallas_fast_vs_oracle_pct"] = round(
            float((got_pal_f == want).mean() * 100.0), 3
        )
    except Exception as e:  # noqa: BLE001
        out["forest"]["pallas_fast_error"] = f"{type(e).__name__}: {e}"[:120]
        g_pal_f = None

    def forest_sum(g, X):
        return jnp.sum(tree_gemm.predict(g, X)).astype(jnp.float32)

    def pallas_fsum(g, X):
        return jnp.sum(pallas_forest.predict(g, X)).astype(jnp.float32)

    for b in batches:
        mark(f"timing forest variants at batch {b}")
        X = jnp.asarray(X_big[:b])
        it = bench._loop_iters(b)
        row = {
            "pallas": round(bench._timed_loop(pallas_fsum, g_pal, X, it) * 1e3, 3),
            "pallas_bucketed": round(
                bench._timed_loop(pallas_fsum, g_pal_b, X, it) * 1e3, 3
            ),
            "xla_gemm_bucketed": round(
                bench._timed_loop(forest_sum, g_gemm, X, it) * 1e3, 3
            ),
        }
        if g_pal_f is not None:
            try:
                row["pallas_fast"] = round(
                    bench._timed_loop(pallas_fsum, g_pal_f, X, it) * 1e3, 3
                )
            except Exception as e:  # noqa: BLE001 — keep the baselines
                row["pallas_fast_error"] = f"{type(e).__name__}: {e}"[:120]
        out["forest"]["timings_device_ms"][str(b)] = row
    print(json.dumps({"forest": out["forest"]}), flush=True)

    # ---- SVC: fused Pallas RBF vs XLA path vs sklearn -------------------
    import pickle
    import warnings

    warnings.filterwarnings("ignore")
    mark("compiling pallas rbf svc")
    svc_raw = ski.import_svc(f"{args.models_dir}/SVC")
    svc_params = svc_mod.from_numpy(svc_raw, dtype=jnp.float32)
    g_rbf = pallas_rbf.compile_svc(svc_params)
    with open(f"{args.models_dir}/SVC", "rb") as fh:
        est = pickle.load(fh)
    lut = {str(c): i for i, c in enumerate(svc_raw["classes"])}
    want_svc = np.array([lut[str(v)] for v in est.predict(ds.X)])
    X_hi, X_lo = svc_mod.split_hilo(ds.X)
    got_rbf = np.asarray(jax.jit(pallas_rbf.predict)(g_rbf, X_hi, X_lo))
    got_xla = np.asarray(jax.jit(svc_mod.predict)(svc_params, X_hi, X_lo))
    out["svc"] = {
        "parity_rows": int(ds.X.shape[0]),
        "pallas_vs_sklearn_pct": round(
            float((got_rbf == want_svc).mean() * 100.0), 3
        ),
        "xla_vs_sklearn_pct": round(
            float((got_xla == want_svc).mean() * 100.0), 3
        ),
        "timings_device_ms": {},
    }

    def svc_sum(p, X):
        return jnp.sum(svc_mod.predict(p, X)).astype(jnp.float32)

    def rbf_sum(g, X):
        return jnp.sum(pallas_rbf.predict(g, X)).astype(jnp.float32)

    for b in batches:
        b = min(b, 1 << 16)  # the (N, S) kernel matrix bounds the XLA path
        mark(f"timing svc variants at batch {b}")
        X = jnp.asarray(X_big[:b])
        it = bench._loop_iters(b)
        out["svc"]["timings_device_ms"][str(b)] = {
            "pallas": round(bench._timed_loop(rbf_sum, g_rbf, X, it) * 1e3, 3),
            "xla": round(bench._timed_loop(svc_sum, svc_params, X, it) * 1e3, 3),
        }

    out["elapsed_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fh:
        fh.write(json.dumps(out) + "\n")
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
