#!/usr/bin/env python
"""End-to-end latency budget for one 16k classification slice — the
artifact VERDICT r3 item 8 asked for: decompose the per-batch p50 into
device compute / transfers / control-path round trip / host spine, so
the BASELINE.md north star ("<1 ms p50") can be restated with an explicit
boundary of what is and is not under 1 ms on this rig.

Why the decomposition matters: round 3 measured e2e_p50_batch_ms = 62.9
at a 4k batch vs 0.18 ms device compute — a ~350x gap. This rig reaches
its TPU through an axon tunnel (~12 MB/s payload, 7-15 ms control RTT
spikes), so the naive e2e number mostly measures the tunnel, not the
framework. A production deployment is co-located (PCIe/ICI: >10 GB/s,
<100 us dispatch), so the honest claim splits into:
  - device compute per 16k slice          (what the TPU design owns)
  - payload bytes moved per slice          (what co-located PCIe would pay)
  - control round trip                     (tunnel tax on this rig)
  - host spine: parse+route+pack per slice (CPU work any deployment pays)

Methodology per stage (tunnel-safe, see bench.py for the rationale):
  rtt      — empty-kernel dispatch + scalar fetch, median of 15
  device   — K dependent predicts in one jitted fori_loop, minus rtt, / K
  h2d      — device_put of the (16384, 12) f32 slice + sync, minus rtt
  d2h      — fetch of the (16384,) int32 labels, minus rtt
  e2e      — full numpy -> device -> predict -> numpy cycle, median of 15
  host     — C++ ingest of one 16k-record tick (parse + route + pack)

Prints ONE JSON line; tools/tpu_day.sh lands it as
docs/artifacts/e2e_budget_tpu.json when platform == "tpu".

--serve-budget instead measures the SERVING wire path end to end: the
full ingest→scatter→predict→render tick at batch 16k records through
the real FlowStateEngine, A/B'd native (C++ tck_feed_lines + pinned
tck_flush_wire staging) vs the Python batcher over IDENTICAL payloads,
with a render-identity gate and the e2e-vs-device-side ratio the
ROADMAP's "<1 ms p50 at the device boundary" claim needs an honest
boundary for. One JSON line → docs/artifacts/e2e_budget_native_cpu
.json (tools/tpu_day.sh lands the tpu variant). Runs without the
reference checkpoints (synthetic GNB — the cheapest full-table
predict, so the ingest path under test dominates the host side).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

SLICE = 16384
FEATURES = 12
REPEATS = 15


def _serve_budget(args) -> None:
    """Per-stage e2e serving budget at batch 16k: native-vs-Python
    ingest A/B + render identity + the device-side ratio gate."""
    import numpy as np

    import jax

    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows
    from traffic_classifier_sdn_tpu.models import gnb, jit_serving_fn
    from traffic_classifier_sdn_tpu.native import engine as native_engine
    from traffic_classifier_sdn_tpu.obs.device import DeviceTelemetry
    from traffic_classifier_sdn_tpu.serving.warmup import warmup_serving

    print("# initializing devices", file=sys.stderr, flush=True)
    platform = jax.devices()[0].platform
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)
    if not native_engine.available():
        sys.exit("--serve-budget needs the C++ engine (g++)")

    # Compile hygiene: this path warms explicitly, so a compile inside
    # either measured loop means the budget timed XLA — hard-gated
    # below (the tail still lands first).
    dev = DeviceTelemetry()
    dev.attach()
    warm_marked = False

    rng = np.random.RandomState(0)
    params = gnb.from_numpy({
        "theta": rng.gamma(2.0, 100.0, (6, FEATURES)),
        "var": rng.gamma(2.0, 50.0, (6, FEATURES)) + 1.0,
        "class_prior": np.full(6, 1 / 6),
    })
    predict = jit_serving_fn(gnb.predict)

    conversations = args.flows_per_tick  # 2 records (directions) each
    syn = SyntheticFlows(n_flows=conversations, seed=0)
    fill = syn.tick_bytes()
    payloads = [syn.tick_bytes() for _ in range(args.ticks)]
    records_per_tick = payloads[0].count(b"\n")

    modes = {}
    rendered = {}
    for name, native in (("native", True), ("python", False)):
        eng = FlowStateEngine(capacity=args.capacity, native=native)
        warmup_serving(eng, predict, params, table_rows=args.table_rows)
        eng.mark_tick()
        eng.ingest_bytes(fill)
        eng.step()
        jax.block_until_ready(eng.table)
        if not warm_marked:
            # the python mode reuses the native mode's jit caches, so
            # one mark covers both measured loops
            dev.mark_warmup_complete()
            warm_marked = True
        timings = {k: [] for k in ("ingest", "step", "predict",
                                   "render", "tick")}
        rows_per_tick = []
        for payload in payloads:
            eng.mark_tick()
            t0 = time.perf_counter()
            eng.ingest_bytes(payload)
            t1 = time.perf_counter()
            eng.step()
            # attribution honesty: the scatter dispatch is async — sync
            # here so its cost lands in "step", not whichever later
            # stage first touches device data
            jax.block_until_ready(eng.table)
            t2 = time.perf_counter()
            labels = predict(params, eng.features())
            jax.block_until_ready(labels)
            t3 = time.perf_counter()
            ranked = eng.render_sample(labels, args.table_rows)
            sample = eng.slot_metadata(slots=[s for s, *_ in ranked])
            rows = [
                (s, *sample[s], int(c))
                for s, c, _fa, _ra in ranked if s in sample
            ]
            t4 = time.perf_counter()
            timings["ingest"].append(t1 - t0)
            timings["step"].append(t2 - t1)
            timings["predict"].append(t3 - t2)
            timings["render"].append(t4 - t3)
            timings["tick"].append(t4 - t0)
            rows_per_tick.append(rows)
        rendered[name] = rows_per_tick
        modes[name] = {
            "stage_p50_ms": {
                k: round(float(np.median(v)) * 1e3, 3)
                for k, v in timings.items()
            },
            "records_per_sec": round(
                records_per_tick
                / float(np.median(timings["ingest"])), 1
            ),
        }
        del eng

    render_identical = rendered["native"] == rendered["python"]
    nat = modes["native"]["stage_p50_ms"]
    # device-side p50 = the whole-table predict, synced — the device
    # boundary the "<1 ms p50" claim measures; e2e = the full tick
    # from raw wire bytes to rendered rows
    device_ms = nat["predict"]
    e2e_ms = nat["tick"]
    ratio = round(e2e_ms / device_ms, 2) if device_ms else None
    ingest_speedup = (
        round(
            modes["python"]["stage_p50_ms"]["ingest"]
            / nat["ingest"], 2
        )
        if nat["ingest"] else None
    )
    out = {
        "metric": "e2e_serve_budget_16k",
        "value": e2e_ms,
        "unit": "ms",
        "platform": platform,
        "capacity": args.capacity,
        "records_per_tick": records_per_tick,
        "ticks": args.ticks,
        "table_rows_rendered": args.table_rows,
        "predict_model": "gnb-synth",
        "native": modes["native"],
        "python": modes["python"],
        "ingest_speedup_native_vs_python": ingest_speedup,
        "device_side_p50_ms": device_ms,
        "e2e_p50_ms": e2e_ms,
        "e2e_over_device_ratio": ratio,
        "e2e_within_5x_device": bool(
            ratio is not None and ratio <= 5.0
        ),
        "render_identical": render_identical,
        "jit_compiles": dev.status()["jit_compiles"],
        "retraces_after_warmup": dev.status()["retraces_after_warmup"],
    }
    print(json.dumps(out), flush=True)
    if not render_identical:
        sys.exit("FAIL: native vs python rendered rows diverged")
    retraces = dev.status()["retraces_after_warmup"]
    if retraces:
        sys.exit(
            f"FAIL: {retraces} compile(s) fired inside the measured "
            "region after warmup — the budget timed XLA, not the "
            "serve path (program: "
            f"{dev.status()['last_compile_program']})"
        )


def _sync_scalar(x) -> float:
    import numpy as np

    return float(np.asarray(x))


def _median_time(fn, repeats: int = REPEATS) -> float:
    import numpy as np

    fn()  # warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--serve-budget", action="store_true",
        help="measure the serving wire path (ingest→scatter→predict→"
        "render) at batch 16k with a native-vs-Python ingest A/B and a "
        "render-identity gate, instead of the predict-slice budget",
    )
    ap.add_argument("--capacity", type=int, default=65536)
    ap.add_argument(
        "--flows-per-tick", type=int, default=SLICE // 2,
        help="conversations per tick (2 records each; default fills "
        "the 16k-record batch the acceptance gate names)",
    )
    ap.add_argument("--ticks", type=int, default=9)
    ap.add_argument("--table-rows", type=int, default=64)
    ap.add_argument(
        "--platform", choices=("cpu", "default"), default="default",
        help="cpu forces the host platform (safe anywhere)",
    )
    args = ap.parse_args()
    if args.platform == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if args.serve_budget:
        _serve_budget(args)
        return
    import numpy as np

    import jax
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.io import sklearn_import as ski
    from traffic_classifier_sdn_tpu.ops import tree_gemm

    # init-first liveness: a wedged worker hangs in jax.devices(), and a
    # silent run is indistinguishable from a slow compile without this
    print("# initializing devices", file=sys.stderr, flush=True)
    platform = jax.devices()[0].platform
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)

    # totals only here: the slice budget's stages each warm themselves
    # inline (_median_time), so there is no single warm boundary to
    # gate on — the count still lands in the artifact
    from traffic_classifier_sdn_tpu.obs.device import DeviceTelemetry

    dev = DeviceTelemetry()
    dev.attach()

    models_dir = os.environ.get("TCSDN_MODELS_DIR", "/root/reference/models")
    g = tree_gemm.compile_forest(
        ski.import_forest(f"{models_dir}/RandomForestClassifier")
    )
    predict = jax.jit(tree_gemm.predict)

    rng = np.random.RandomState(0)
    X_np = np.abs(rng.gamma(1.5, 200.0, (SLICE, FEATURES))).astype(np.float32)

    # --- control-path round trip (empty kernel) --------------------------
    trivial = jax.jit(lambda a: jnp.sum(a) * 0.0)
    small = jnp.ones((8,), jnp.float32)
    rtt = _median_time(lambda: _sync_scalar(trivial(small)))

    # --- device compute: K dependent predicts in one jit, minus rtt -----
    from jax import lax

    K = 32

    @jax.jit
    def loop(g, X):
        def body(i, acc):
            Xi = X.at[0, 0].set(acc * 1e-9 + jnp.float32(i))
            return acc + jnp.sum(tree_gemm.predict(g, Xi)).astype(jnp.float32)

        return lax.fori_loop(0, K, body, jnp.float32(0.0))

    Xd = jnp.asarray(X_np)
    device_s = max(
        _median_time(lambda: _sync_scalar(loop(g, Xd)), repeats=7) - rtt,
        1e-12,
    ) / K

    # --- h2d: move the slice payload (16384x12 f32 = 786 kB) -------------
    # jnp.asarray + a sum fetch forces the bytes across; subtract rtt to
    # isolate payload time. (block_until_ready lies on the tunnel.)
    h2d_bytes = X_np.nbytes

    def h2d():
        _sync_scalar(jnp.sum(jnp.asarray(X_np)))

    h2d_s = max(_median_time(h2d) - rtt, 1e-12)

    # --- d2h: fetch the (16384,) int32 labels (64 kB) --------------------
    # jax.Array caches its numpy value after the first np.asarray, so a
    # repeated fetch of ONE array times a host cache read (~0), not the
    # transfer. Instead: one distinct device array per repetition, each
    # synced device-side via an independent scalar reduction (which does
    # NOT populate the source array's host cache), fetched exactly once.
    labels_dev = predict(g, Xd)
    labels_np = np.asarray(labels_dev)
    d2h_bytes = int(labels_np.nbytes)
    arrs = [jax.device_put(labels_np) for _ in range(REPEATS + 1)]
    for a in arrs:
        _sync_scalar(jnp.sum(a))  # transfer + compute done; host cache cold
    np.asarray(arrs[0])  # warm the fetch path once
    d2h_times = []
    for a in arrs[1:]:
        t0 = time.perf_counter()
        np.asarray(a)
        d2h_times.append(time.perf_counter() - t0)
    d2h_s = max(float(np.median(d2h_times)) - rtt, 1e-12)

    # --- full e2e cycle: numpy in -> labels in numpy out -----------------
    def e2e():
        np.asarray(predict(g, jnp.asarray(X_np)))

    e2e_s = _median_time(e2e)

    # --- host spine: parse + route + pack one 16k-record tick ------------
    # The CPU work any deployment pays per slice before the device sees
    # it. Uses the C++ ingest engine when built (the serving default).
    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows
    from traffic_classifier_sdn_tpu.native import engine as native_engine

    native = native_engine.available()
    eng = FlowStateEngine(capacity=1 << 15, native=native)
    payload = SyntheticFlows(n_flows=SLICE // 2, seed=0).tick_bytes()
    host_times = []
    for _ in range(5):
        eng.mark_tick()
        t0 = time.perf_counter()
        eng.ingest_bytes(payload)
        host_times.append(time.perf_counter() - t0)
    host_s = float(np.median(host_times))

    # --- the budget, restated --------------------------------------------
    # Co-located projection: same payload over PCIe gen3 x16 (~12 GB/s
    # effective) + ~50 us dispatch, instead of this rig's tunnel.
    pcie_bps = 12e9
    colocated_ms = (
        device_s + (h2d_bytes + d2h_bytes) / pcie_bps + 100e-6 + host_s
    ) * 1e3

    line = {
        "metric": "e2e_latency_budget_16k_slice",
        "value": round(e2e_s * 1e3, 3),
        "unit": "ms",
        "platform": platform,
        "slice_rows": SLICE,
        "model": "random_forest_100x6class",
        "budget_p50_ms": {
            "device_compute": round(device_s * 1e3, 3),
            "h2d_payload": round(h2d_s * 1e3, 3),
            "d2h_payload": round(d2h_s * 1e3, 3),
            "control_rtt": round(rtt * 1e3, 3),
            "host_spine_ingest": round(host_s * 1e3, 3),
            "e2e_measured": round(e2e_s * 1e3, 3),
        },
        "payload_bytes": {"h2d": int(h2d_bytes), "d2h": int(d2h_bytes)},
        "h2d_mb_per_sec": round(h2d_bytes / h2d_s / 1e6, 1),
        "residual_ms": round(
            (e2e_s - device_s - h2d_s - d2h_s - rtt) * 1e3, 3
        ),
        "colocated_projection_ms": round(colocated_ms, 3),
        "north_star_boundary": (
            f"device compute per 16k slice measured "
            f"{device_s * 1e3:.3f} ms on platform={platform}; the gap to "
            f"e2e_measured is control RTT + payload transfer (on this "
            f"rig, tunnel tax — not framework cost); a co-located "
            f"deployment pays device + PCIe + host spine = "
            f"~{colocated_ms:.2f} ms per 16k slice"
        ),
        "native_ingest": native,
        "jit_compiles": dev.status()["jit_compiles"],
    }
    print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
