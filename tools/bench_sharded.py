#!/usr/bin/env python
"""Sharded-path scaling bench on a virtual CPU mesh (VERDICT r1 item 9).

Times every distributed predict path at shard counts 1 / 2 / 8 on the
8-virtual-device CPU mesh the tests use (SURVEY.md §4c). Absolute numbers
on virtual CPU devices are meaningless; the *relative* shape catches
collective-layout regressions (a psum/all_gather whose operand suddenly
scales with the full state, a ring step that stops overlapping, padding
that stops dividing) before they reach hardware. Prints one JSON line.

Paths (state axis unless noted):
  knn_allgather — local top-k + all_gather merge (parallel/knn_sharded.py)
  knn_ring      — software-pipelined ppermute ring merge
  forest        — tree-sharded, psum of class distributions
  svc           — SV-sharded, psum of partial ovo decisions
  forest_dp     — batch-sharded forest (data axis; no collectives)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--models-dir", default=os.environ.get(
        "TCSDN_MODELS_DIR", "/root/reference/models"))
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.io import sklearn_import as ski
    from traffic_classifier_sdn_tpu.models import forest, knn, svc
    from traffic_classifier_sdn_tpu.parallel import (
        forest_sharded,
        knn_sharded,
        mesh as meshlib,
        predict as dp,
        svc_sharded,
    )

    rng = np.random.RandomState(0)
    X = jnp.asarray(
        np.abs(rng.gamma(1.5, 200.0, (args.batch, 12))), jnp.float32
    )

    def timed(fn, *a) -> float:
        out = jax.block_until_ready(fn(*a))  # compile + warm
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*a))
            times.append(time.perf_counter() - t0)
        del out
        return float(np.median(times))

    knn_raw = ski.import_knn(os.path.join(args.models_dir, "KNeighbors"))
    svc_raw = ski.import_svc(os.path.join(args.models_dir, "SVC"))
    forest_raw = ski.import_forest(
        os.path.join(args.models_dir, "RandomForestClassifier")
    )

    results: dict = {}
    devices = jax.devices()
    for n_state in (1, 2, 8):
        mesh = meshlib.make_mesh(
            n_data=1, n_state=n_state, devices=devices[:n_state]
        )
        r: dict = {}

        kr = knn_sharded.pad_corpus(dict(knn_raw), n_state)
        kp = knn.from_numpy(kr, dtype=jnp.float32)
        r["knn_allgather_ms"] = timed(
            knn_sharded.sharded_predict(
                mesh, kp, pad_mask=kr.get("pad_mask")
            ), X,
        ) * 1e3
        r["knn_ring_ms"] = timed(
            knn_sharded.ring_predict(mesh, kp, pad_mask=kr.get("pad_mask")),
            X,
        ) * 1e3
        r["knn_tournament_ms"] = timed(
            knn_sharded.tournament_predict(
                mesh, kp, pad_mask=kr.get("pad_mask")
            ),
            X,
        ) * 1e3

        fr = forest_sharded.pad_trees(dict(forest_raw), n_state)
        fp = forest.from_numpy(fr)
        r["forest_ms"] = timed(
            forest_sharded.sharded_predict(
                mesh, fp, n_real_trees=fr.get(
                    "n_real_trees", fr["left"].shape[0]
                )
            ), X,
        ) * 1e3

        sr = svc_sharded.pad_support(dict(svc_raw), n_state)
        sp = svc.from_numpy(sr, dtype=jnp.float32)
        r["svc_ms"] = timed(svc_sharded.sharded_predict(mesh, sp), X) * 1e3

        results[f"state_{n_state}"] = {
            k: round(v, 2) for k, v in r.items()
        }

    for n_data in (1, 8):
        mesh = meshlib.make_mesh(
            n_data=n_data, n_state=1, devices=devices[:n_data]
        )
        fp = forest.from_numpy(forest_raw)
        call = dp.data_parallel(mesh, forest.predict)
        results[f"data_{n_data}"] = {
            "forest_dp_ms": round(timed(call, fp, X) * 1e3, 2)
        }

    # Distributed TRAINING canaries (cold, one call: each fit builds its
    # own shard_map closure, so compile time is included — the row exists
    # to catch collective-layout regressions, e.g. a histogram psum that
    # suddenly scales with the full corpus, not to be a precise timer).
    from traffic_classifier_sdn_tpu.io.datasets import load_reference_datasets
    from traffic_classifier_sdn_tpu.train.distributed import (
        fit_forest,
        fit_svc,
    )

    ds = load_reference_datasets(
        os.environ.get("TCSDN_DATA_DIR", "/root/reference/datasets")
    )
    Xt, yt = ds.X[:1024], ds.y[:1024]
    C = len(ds.classes)
    for n_data in (1, 8):
        mesh = meshlib.make_mesh(
            n_data=n_data, n_state=1, devices=devices[:n_data]
        )
        t0 = time.perf_counter()
        fit_forest(mesh, Xt, yt, C, n_trees=4, max_depth=6, n_bins=32)
        results.setdefault(f"data_{n_data}", {})["forest_fit_cold_ms"] = (
            round((time.perf_counter() - t0) * 1e3, 1)
        )
    for n_state in (1, 8):
        mesh = meshlib.make_mesh(
            n_data=1, n_state=n_state, devices=devices[:n_state]
        )
        t0 = time.perf_counter()
        fit_svc(mesh, Xt, yt, C, n_iters=100, power_iters=10)
        results.setdefault(f"state_{n_state}", {})["svc_fit_cold_ms"] = (
            round((time.perf_counter() - t0) * 1e3, 1)
        )

    print(
        json.dumps(
            {
                "metric": "sharded_scaling_cpu_mesh",
                "batch": args.batch,
                "platform": "cpu_x8_virtual",
                "results": results,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
