#!/bin/sh
# OPTIONAL chip-day extras — run AFTER tools/tpu_day.sh has landed the
# official artifacts, if the worker window is still healthy:
#   1. serve-tick A/B with the v2 GEMM kernels (TCSDN_FOREST_KERNEL)
#      -> docs/artifacts/serve_2m_tpu_v2_dot.json / serve_2m_tpu_v2_gather.json
#   2. single-chip big-corpus KNN rate (2^18-row corpus streamed in
#      16k slices) -> docs/artifacts/knn_big_corpus_tpu.json
#   3. KNN serve-tick A/B across raced top-k kernels (TCSDN_KNN_TOPK)
#      -> docs/artifacts/serve_2m_knn_tpu_<impl>.json
#   4. fused KNN + SVC kernels compiled inside shard_map, parity-asserted
#      -> docs/artifacts/fused_knn_shmap_tpu.json / fused_svc_shmap_tpu.json
#   5. forest GEMM bucket-count sweep (VERDICT r3 item 5)
#      -> docs/artifacts/forest_buckets_tpu.json
# Each step is independently guarded; a failure skips only that step.
set -e
# one home for the per-step wedge bound (see tpu_day.sh run_step)
TMO="timeout -k 30"
cd "$(dirname "$0")/.."

sh tools/tpu_probe.sh || { echo "TPU worker down"; exit 1; }
echo "TPU up — extras"

for K in gemm_v2_dot gemm_v2_gather; do
  if TCSDN_FOREST_KERNEL=$K $TMO 900 python tools/bench_serve.py \
       --platform default --model forest --ticks 4 \
       > /tmp/tpu_serve_$K.log 2>&1; then
    if grep '^{' /tmp/tpu_serve_$K.log | tail -1 \
        | grep -q '"platform": "tpu"'; then
      grep '^{' /tmp/tpu_serve_$K.log | tail -1 \
        > "docs/artifacts/serve_2m_tpu_${K#gemm_}.json"
      echo "extras: serve A/B $K landed"
    fi
  else
    cat /tmp/tpu_serve_$K.log; echo "extras: serve A/B $K FAILED (skipped)"
  fi
done

$TMO 900 python - > /tmp/tpu_knn_big.log 2>&1 <<'EOF' || \
  echo "extras: big-corpus KNN exited nonzero (landing completed lines)"
import json, time
import numpy as np
import jax, jax.numpy as jnp
import sys, os
sys.path.insert(0, os.getcwd())
import bench
from traffic_classifier_sdn_tpu.models import knn

platform = jax.devices()[0].platform
rng = np.random.RandomState(0)
S, B = 1 << 18, 65536
d = {"fit_X": np.abs(rng.gamma(1.5, 200.0, (S, 12))),
     "y": rng.randint(0, 6, S), "n_neighbors": 5, "classes": np.arange(6)}
p = knn.from_numpy(d, dtype=jnp.float32)
X = jnp.asarray(np.abs(rng.gamma(1.5, 200.0, (B, 12))).astype(np.float32))

def big_sum(p, X):
    return jnp.sum(
        knn.predict_big_corpus(p, X, corpus_chunk=16384)
    ).astype(jnp.float32)

sec = bench._timed_loop(big_sum, p, X, 4)
out = {
    "metric": "knn_big_corpus_flows_per_sec", "value": round(B / sec, 1),
    "unit": "flows/s", "platform": platform, "corpus_rows": S,
    "batch": B, "winner": "xla_scan", "scan_corpus_chunk": 16384,
    "scan_flows_per_sec": round(B / sec, 1),
    "scan_device_batch_ms": round(sec * 1e3, 3),
    "device_batch_ms": round(sec * 1e3, 3),
}
print(json.dumps(out))
# race the fused kernel at the same corpus: its HBM saving GROWS with S
# (the scan path writes/reads an (N, chunk) slice per step; the kernel
# keeps every similarity in VMEM). Guarded: a Mosaic failure must not
# cost the scan data point above. Parity-gated before promotion.
try:
    from traffic_classifier_sdn_tpu.ops import pallas_knn

    g = pallas_knn.compile_knn(p, corpus_chunk=2048)
    out["pallas_corpus_chunk"] = 2048
    Xs = X[:4096]
    a = np.asarray(jax.jit(pallas_knn.predict)(g, Xs))
    b = np.asarray(jax.jit(
        lambda p, X: knn.predict_big_corpus(p, X, corpus_chunk=16384)
    )(p, Xs))
    out["pallas_parity_pct"] = round(float((a == b).mean() * 100.0), 3)

    def pk_sum(g, X):
        return jnp.sum(pallas_knn.predict(g, X)).astype(jnp.float32)

    sec_pk = bench._timed_loop(pk_sum, g, X, 4)
    out["pallas_flows_per_sec"] = round(B / sec_pk, 1)
    out["pallas_device_batch_ms"] = round(sec_pk * 1e3, 3)
    if out["pallas_parity_pct"] == 100.0 and sec_pk < sec:
        # scan numbers stay under their scan_* keys either way
        out["value"] = out["pallas_flows_per_sec"]
        out["device_batch_ms"] = out["pallas_device_batch_ms"]
        out["winner"] = "pallas_fused"
except Exception as e:
    out["pallas_error"] = f"{type(e).__name__}: {e}"[:120]
print(json.dumps(out))
EOF
# land the freshest completed line REGARDLESS of exit status: a Mosaic
# crash in the pallas race must not cost the scan data point already
# printed (the last line supersedes — it carries the scan_* keys always)
if grep '^{' /tmp/tpu_knn_big.log | tail -1 \
    | grep -q '"platform": "tpu"'; then
  grep '^{' /tmp/tpu_knn_big.log | tail -1 \
    > docs/artifacts/knn_big_corpus_tpu.json
  echo "extras: big-corpus KNN landed"
else
  cat /tmp/tpu_knn_big.log; echo "extras: big-corpus KNN FAILED (skipped)"
fi

for K in sort hier512 pallas; do
  if TCSDN_KNN_TOPK=$K $TMO 900 python tools/bench_serve.py \
       --platform default --model knn --ticks 3 \
       > /tmp/tpu_serve_knn_$K.log 2>&1; then
    if grep '^{' /tmp/tpu_serve_knn_$K.log | tail -1 \
        | grep -q '"platform": "tpu"'; then
      grep '^{' /tmp/tpu_serve_knn_$K.log | tail -1 \
        > "docs/artifacts/serve_2m_knn_tpu_$K.json"
      echo "extras: knn serve A/B $K landed"
    fi
  else
    cat /tmp/tpu_serve_knn_$K.log
    echo "extras: knn serve A/B $K FAILED (skipped)"
  fi
done

if $TMO 600 python - > /tmp/tpu_fused_shmap.log 2>&1 <<'EOF'
# compiled proof: the fused KNN kernel inside shard_map on the real
# chip (1-device state mesh — the manual-sharding compile path the
# plain bench race does not exercise)
import json
import numpy as np
import jax, jax.numpy as jnp
import sys, os
sys.path.insert(0, os.getcwd())
from traffic_classifier_sdn_tpu.io import sklearn_import as ski
from traffic_classifier_sdn_tpu.io.datasets import load_reference_datasets
from traffic_classifier_sdn_tpu.models import knn
from traffic_classifier_sdn_tpu.parallel import knn_sharded, mesh as meshlib

platform = jax.devices()[0].platform
ds = load_reference_datasets("/root/reference/datasets")
d = ski.import_knn("/root/reference/models/KNeighbors")
params = knn.from_numpy(d, dtype=jnp.float32)
m = meshlib.make_mesh(n_data=1, n_state=1, devices=jax.devices()[:1])
fn = knn_sharded.fused_predict(m, params)
X = jnp.asarray(ds.X[:4096], jnp.float32)
got = np.asarray(fn(X))
want = np.asarray(jax.jit(knn.predict)(params, X))
parity = float((got == want).mean() * 100.0)
print(json.dumps({
    "metric": "fused_knn_shard_map_compiled",
    "platform": platform, "rows": int(X.shape[0]),
    "parity_pct": round(parity, 3),
}))
# proof semantics: non-parity must fail the step, not land as a proof
assert parity == 100.0, f"fused shard_map parity {parity}"
EOF
then
  if grep '^{' /tmp/tpu_fused_shmap.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_fused_shmap.log | tail -1 \
      > docs/artifacts/fused_knn_shmap_tpu.json
    echo "extras: fused shard_map KNN proof landed"
  fi
else
  cat /tmp/tpu_fused_shmap.log
  echo "extras: fused shard_map KNN proof FAILED (skipped)"
fi

if $TMO 600 python - > /tmp/tpu_fused_svc_shmap.log 2>&1 <<'EOF'
# compiled proof: the fused RBF-SVC kernel inside shard_map on the real
# chip (1-device state mesh), parity-asserted vs the XLA path
import json
import numpy as np
import jax, jax.numpy as jnp
import sys, os
sys.path.insert(0, os.getcwd())
from traffic_classifier_sdn_tpu.io import sklearn_import as ski
from traffic_classifier_sdn_tpu.io.datasets import load_reference_datasets
from traffic_classifier_sdn_tpu.models import svc
from traffic_classifier_sdn_tpu.parallel import svc_sharded, mesh as meshlib

platform = jax.devices()[0].platform
ds = load_reference_datasets("/root/reference/datasets")
params = svc.from_numpy(
    ski.import_svc("/root/reference/models/SVC"), dtype=jnp.float32
)
m = meshlib.make_mesh(n_data=1, n_state=1, devices=jax.devices()[:1])
fn = svc_sharded.fused_predict(m, params)
Xhi, Xlo = svc.split_hilo(ds.X[:4096])
got = np.asarray(fn(Xhi, Xlo))
want = np.asarray(jax.jit(svc.predict)(params, Xhi, Xlo))
parity = float((got == want).mean() * 100.0)
print(json.dumps({
    "metric": "fused_svc_shard_map_compiled",
    "platform": platform, "rows": int(Xhi.shape[0]),
    "parity_pct": round(parity, 3),
}))
# proof semantics: non-parity must fail the step, not land as a proof
assert parity == 100.0, f"fused svc shard_map parity {parity}"
EOF
then
  if grep '^{' /tmp/tpu_fused_svc_shmap.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_fused_svc_shmap.log | tail -1 \
      > docs/artifacts/fused_svc_shmap_tpu.json
    echo "extras: fused shard_map SVC proof landed"
  fi
else
  cat /tmp/tpu_fused_svc_shmap.log
  echo "extras: fused shard_map SVC proof FAILED (skipped)"
fi

if $TMO 1200 python tools/bench_forest_buckets.py > /tmp/tpu_forest_buckets.log 2>&1
then
  if grep '^{' /tmp/tpu_forest_buckets.log | tail -1 \
      | grep -q '"platform": "tpu"'; then
    grep '^{' /tmp/tpu_forest_buckets.log | tail -1 \
      > docs/artifacts/forest_buckets_tpu.json
    echo "extras: forest bucket sweep landed"
  fi
else
  cat /tmp/tpu_forest_buckets.log
  echo "extras: forest bucket sweep FAILED (skipped)"
fi

echo "tpu_extras: done"
