#!/usr/bin/env python
"""Chip-window preflight doctor: a ~30 s instrumented micro-serve that
answers "is this window worth spending?" BEFORE tpu_day.sh burns it.

Five checks, each an independent pass/fail/skip row in one atomically
written JSON bundle (--out — the bundle lands even on a failing
verdict, so a dead chip still leaves evidence of HOW it was dead):

  platform   — the backend jax actually initialized vs --expect
               (a silently-CPU "TPU window" is the classic wasted day)
  compile    — total XLA compile seconds for the full warm serve set
               under --compile-budget-s (a wedged worker compiles
               forever; a cold cache on a short window is a choice the
               operator should make knowingly)
  retrace    — ZERO compiles once the measured ticks start; a retrace
               here means shape instability would poison every bench
               downstream (obs/device.py edge-triggered accounting)
  hbm        — device memory headroom after the table fill vs
               --hbm-headroom (skip-with-note where memory_stats() is
               unavailable, e.g. CPU)
  transfers  — the runtime sync witness (utils/syncguard.py) armed
               over the measured ticks, cross-checked against the
               static ledger docs/artifacts/hot_path_sync_budget.json:
               any hot-span sync off the allowlist fails
  cadence    — measured tick p50 under --cadence-budget-s (the 1 s
               render cadence the serve loop promises)

Exit 0 iff every non-skip check passed. tools/tpu_day.sh runs this
first; docs/artifacts/tpu_doctor_cpu.json is the committed CPU run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _check(checks: list, cid: str, status: str, detail: str,
           **fields) -> None:
    checks.append({"id": cid, "status": status, "detail": detail,
                   **fields})
    print(f"# doctor {cid}: {status} — {detail}",
          file=sys.stderr, flush=True)


def run_doctor(args) -> dict:
    import numpy as np

    import jax

    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows
    from traffic_classifier_sdn_tpu.models import gnb, jit_serving_fn
    from traffic_classifier_sdn_tpu.obs.device import DeviceTelemetry
    from traffic_classifier_sdn_tpu.serving.incremental import (
        IncrementalLabels,
    )
    from traffic_classifier_sdn_tpu.serving.warmup import warmup_serving
    from traffic_classifier_sdn_tpu.utils import syncguard

    checks: list = []
    dev = DeviceTelemetry()
    dev.attach()

    # -- platform ---------------------------------------------------------
    platform = jax.devices()[0].platform
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)
    if args.expect == "any":
        _check(checks, "platform", "pass",
               f"platform={platform} (no expectation set)",
               platform=platform)
    elif platform == args.expect:
        _check(checks, "platform", "pass",
               f"platform={platform} as expected", platform=platform)
    else:
        _check(checks, "platform", "fail",
               f"expected platform={args.expect}, got {platform} — "
               "the window would measure the wrong backend",
               platform=platform, expected=args.expect)

    # -- compile budget: warm the whole serve set, timed ------------------
    rng = np.random.RandomState(0)
    params = gnb.from_numpy({
        "theta": rng.gamma(2.0, 100.0, (6, 12)),
        "var": rng.gamma(2.0, 50.0, (6, 12)) + 1.0,
        "class_prior": np.full(6, 1 / 6),
    })
    predict = jit_serving_fn(gnb.predict)
    eng = FlowStateEngine(capacity=args.capacity, track_dirty=True)
    t0 = time.perf_counter()
    warmup_serving(eng, predict, params, table_rows=args.table_rows,
                   idle_timeout=3600, incremental=True)
    warm_wall = time.perf_counter() - t0
    st = dev.status()
    compile_s = st["jit_compile_s_total"]
    if compile_s <= args.compile_budget_s:
        _check(checks, "compile", "pass",
               f"{st['jit_compiles']} compiles, "
               f"{compile_s:.2f}s XLA time (warm wall "
               f"{warm_wall:.2f}s) within {args.compile_budget_s}s",
               jit_compiles=st["jit_compiles"],
               compile_s=round(compile_s, 3),
               warm_wall_s=round(warm_wall, 3))
    else:
        _check(checks, "compile", "fail",
               f"{compile_s:.2f}s XLA compile time exceeds the "
               f"{args.compile_budget_s}s budget — worker wedge or "
               "pathological cache miss",
               jit_compiles=st["jit_compiles"],
               compile_s=round(compile_s, 3),
               warm_wall_s=round(warm_wall, 3))

    # -- measured micro-serve: retrace + transfers + cadence --------------
    syn = SyntheticFlows(n_flows=args.flows_per_tick, seed=0)
    fill = syn.tick_bytes()
    payloads = [syn.tick_bytes() for _ in range(args.ticks)]
    inc = IncrementalLabels(eng, predict, params)
    eng.mark_tick()
    eng.ingest_bytes(fill)
    eng.step()
    jax.block_until_ready(inc.labels())
    dev.mark_warmup_complete()
    budget = syncguard.load_budget()
    tick_walls = []
    with syncguard.guarding(budget=budget) as witness:
        for payload in payloads:
            t0 = time.perf_counter()
            eng.mark_tick()
            eng.ingest_bytes(payload)
            eng.step()
            labels = inc.labels()
            jax.block_until_ready(labels)
            eng.render_sample(labels, args.table_rows)
            eng.evict_idle(now=eng.last_time, idle_seconds=3600)
            tick_walls.append(time.perf_counter() - t0)
    devs = dev.sample()

    retraces = devs["retraces_after_warmup"]
    if retraces == 0:
        _check(checks, "retrace", "pass",
               f"0 compiles across {args.ticks} measured ticks",
               retraces_after_warmup=0)
    else:
        _check(checks, "retrace", "fail",
               f"{retraces} compile(s) fired inside the measured "
               "ticks (last program: "
               f"{dev.status()['last_compile_program']}) — shape "
               "instability would poison every downstream bench",
               retraces_after_warmup=retraces)

    # -- hbm headroom -----------------------------------------------------
    stats = None
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:
        stats = None
    in_use = (stats or {}).get("bytes_in_use")
    limit = (stats or {}).get("bytes_limit")
    if in_use is None or not limit:
        _check(checks, "hbm", "skip",
               f"memory_stats() unavailable on platform={platform} — "
               "headroom unverifiable here, not a failure",
               hbm_bytes=devs["hbm_bytes"])
    else:
        headroom = 1.0 - in_use / limit
        row = {
            "bytes_in_use": int(in_use), "bytes_limit": int(limit),
            "headroom_fraction": round(headroom, 4),
        }
        if headroom >= args.hbm_headroom:
            _check(checks, "hbm", "pass",
                   f"{headroom:.1%} HBM free after table fill "
                   f"(floor {args.hbm_headroom:.0%})", **row)
        else:
            _check(checks, "hbm", "fail",
                   f"only {headroom:.1%} HBM free after table fill — "
                   "the 2^20 table or a leak would OOM mid-window",
                   **row)

    # -- transfers vs the static ledger -----------------------------------
    if budget is None:
        _check(checks, "transfers", "skip",
               "docs/artifacts/hot_path_sync_budget.json missing — "
               "run `python -m traffic_classifier_sdn_tpu."
               "analysis_static --sync-budget` first")
    else:
        verdict = witness.check_against(budget)
        counts = witness.counts()
        d2h = sum(
            n for kinds in counts.values()
            for kind, n in kinds.items()
            if kind in syncguard.D2H_KINDS
        )
        row = {
            "d2h_syncs_observed": d2h,
            "d2h_syncs_per_tick": round(d2h / args.ticks, 2),
            "unknown_syncs": verdict["unknown_syncs"],
        }
        if verdict["unknown_syncs"]:
            _check(checks, "transfers", "fail",
                   f"{len(verdict['unknown_syncs'])} hot-span sync "
                   "site(s) off the static allowlist — a hot path "
                   "regressed or the resolver has a hole", **row)
        else:
            _check(checks, "transfers", "pass",
                   f"{d2h} device→host syncs over {args.ticks} ticks, "
                   "all hot-span sites on the allowlist", **row)

    # -- cadence ----------------------------------------------------------
    p50 = float(np.median(tick_walls))
    row = {
        "tick_p50_s": round(p50, 4),
        "tick_max_s": round(max(tick_walls), 4),
    }
    if p50 <= args.cadence_budget_s:
        _check(checks, "cadence", "pass",
               f"tick p50 {p50 * 1e3:.1f} ms within the "
               f"{args.cadence_budget_s}s cadence budget", **row)
    else:
        _check(checks, "cadence", "fail",
               f"tick p50 {p50 * 1e3:.1f} ms blows the "
               f"{args.cadence_budget_s}s cadence budget — the serve "
               "loop cannot hold its render cadence here", **row)

    dev.detach()
    failed = [c["id"] for c in checks if c["status"] == "fail"]
    skipped = [c["id"] for c in checks if c["status"] == "skip"]
    return {
        "metric": "tpu_doctor",
        "verdict": "fail" if failed else "pass",
        "platform": platform,
        "failed_checks": failed,
        "skipped_checks": skipped,
        "checks": checks,
        "config": {
            "expect": args.expect,
            "capacity": args.capacity,
            "flows_per_tick": args.flows_per_tick,
            "ticks": args.ticks,
            "table_rows": args.table_rows,
            "compile_budget_s": args.compile_budget_s,
            "hbm_headroom": args.hbm_headroom,
            "cadence_budget_s": args.cadence_budget_s,
        },
        "device": dev.status(),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--platform", choices=("cpu", "default"), default="cpu",
        help="cpu forces the host platform (safe anywhere); default "
        "lets jax pick the real device",
    )
    ap.add_argument(
        "--expect", choices=("any", "cpu", "tpu", "gpu"), default="any",
        help="fail the platform check unless jax initialized this "
        "backend (tpu_day.sh passes tpu)",
    )
    ap.add_argument("--capacity", type=int, default=1 << 14)
    ap.add_argument("--flows-per-tick", type=int, default=2048)
    ap.add_argument("--ticks", type=int, default=8)
    ap.add_argument("--table-rows", type=int, default=64)
    ap.add_argument("--compile-budget-s", type=float, default=120.0)
    ap.add_argument(
        "--hbm-headroom", type=float, default=0.2,
        help="minimum fraction of device memory that must be free "
        "after the table fill (default 0.2)",
    )
    ap.add_argument("--cadence-budget-s", type=float, default=1.0)
    ap.add_argument(
        "--out", default=None, metavar="PATH",
        help="write the bundle here atomically (in addition to "
        "stdout) — written on BOTH verdicts, so a failing preflight "
        "still leaves its evidence",
    )
    args = ap.parse_args()
    if args.platform == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    bundle = run_doctor(args)
    print(json.dumps(bundle), flush=True)
    if args.out:
        from traffic_classifier_sdn_tpu.utils.atomicio import (
            atomic_write_bytes,
        )

        out_dir = os.path.dirname(os.path.abspath(args.out))
        os.makedirs(out_dir, exist_ok=True)
        atomic_write_bytes(
            args.out, (json.dumps(bundle, indent=2) + "\n").encode(),
        )
        print(f"# doctor bundle: {args.out}", file=sys.stderr,
              flush=True)
    if bundle["verdict"] != "pass":
        sys.exit(
            "tpu_doctor: FAIL — " + ", ".join(bundle["failed_checks"])
        )


if __name__ == "__main__":
    main()
