#!/usr/bin/env python
"""Bucket-count sweep for the headline forest GEMM (VERDICT r3 item 5).

The size-bucketed GEMM (ops/tree_gemm.py) pads every tree in a bucket to
the bucket's max (D, L); more buckets mean tighter padding (fewer wasted
matmul columns) but more, smaller MXU dispatches. 8 buckets was chosen in
round 2 without a sweep — this tool races n_buckets over the reference
checkpoint at the bench's large batch, parity-gating each point, and
prints one JSON line for docs/artifacts/.

Usage: python tools/bench_forest_buckets.py [--batch 131072]
       [--buckets 2,4,8,16,32]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=131072)
    ap.add_argument("--buckets", default="2,4,8,16,32")
    ap.add_argument("--models-dir", default="/root/reference/models")
    ap.add_argument("--data-dir", default="/root/reference/datasets")
    args = ap.parse_args()

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax
    import jax.numpy as jnp

    import bench
    from traffic_classifier_sdn_tpu.io import sklearn_import as ski
    from traffic_classifier_sdn_tpu.io.datasets import load_reference_datasets
    from traffic_classifier_sdn_tpu.ops import tree_gemm

    print("# initializing devices", file=sys.stderr, flush=True)
    platform = jax.devices()[0].platform
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)

    t0 = time.time()
    forest_raw = ski.import_forest(
        f"{args.models_dir}/RandomForestClassifier"
    )
    ds = load_reference_datasets(args.data_dir)
    Xd = jnp.asarray(ds.X, jnp.float32)
    want = bench._numpy_forest_labels(forest_raw, ds.X)

    rng = np.random.RandomState(0)
    X = jnp.asarray(
        np.abs(rng.gamma(1.5, 200.0, (args.batch, 12))).astype(np.float32)
    )

    def forest_sum(g, Xb):
        return jnp.sum(tree_gemm.predict(g, Xb)).astype(jnp.float32)

    out: dict = {
        "metric": "forest_bucket_sweep",
        "platform": platform,
        "batch": args.batch,
        "parity_rows": int(ds.X.shape[0]),
        "points": {},
    }
    best = None
    for nb in (int(b) for b in args.buckets.split(",")):
        print(f"# n_buckets={nb}", file=sys.stderr, flush=True)
        g = tree_gemm.compile_forest(forest_raw, n_buckets=nb)
        got = np.asarray(jax.jit(tree_gemm.predict)(g, Xd))
        parity = float((got == want).mean() * 100.0)
        sec = bench._timed_loop(
            forest_sum, g, X, bench._loop_iters(args.batch)
        )
        point = {
            "device_ms": round(sec * 1e3, 3),
            "flows_per_sec": round(args.batch / sec, 1),
            "parity_pct": round(parity, 3),
        }
        out["points"][str(nb)] = point
        print(json.dumps({f"n_buckets_{nb}": point}), flush=True)
        if parity == 100.0 and (best is None or sec < best[1]):
            best = (nb, sec)
    if best is not None:
        out["best_n_buckets"] = best[0]
        out["best_device_ms"] = round(best[1] * 1e3, 3)
        out["best_flows_per_sec"] = round(args.batch / best[1], 1)
    out["elapsed_s"] = round(time.time() - t0, 1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
