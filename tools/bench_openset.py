#!/usr/bin/env python
"""Open-set eval: known-class accuracy + unknown-detection ROC for all
six model families under the serving rejection tier (F12,
serving/openset.py).

The claim this artifact pins: adding the calibrated rejection gate
costs ~zero known-class accuracy while detecting a never-trained
class, per family, at the SHIPPED threshold discipline (per-class
stats calibrated from the family's OWN predicted labels — exactly the
serving regime, where ground truth does not exist — and
``threshold = margin × max(calibration score)``).

Data: class-shaped synthetic traffic (the ``forest-synth`` scheme —
gamma rows scaled by per-class means at distinct rate scales), so the
eval runs on any host; the reference CSV tree is not required. One
class is HELD OUT of training entirely: it is the unknown application
an open-world serve must reject.

Per family, the JSON reports:

- ``closed_accuracy`` / ``gated_accuracy`` / ``accuracy_delta`` —
  known-class accuracy without/with the gate (a rejected known row
  counts as an error, so the delta IS the gate's false-reject cost);
- ``unknown_tpr_at_threshold`` / ``known_fpr_at_threshold`` — the
  operating point at the shipped margin-calibrated threshold;
- ``mahalanobis_auc`` + ``roc`` — threshold-swept detection quality of
  the serving score (min-over-classes diagonal Mahalanobis RMS);
- ``family_score_auc`` — the family's own ``predict_scores`` surface
  (max per-class score as confidence) as a comparison diagnostic.

Writes docs/artifacts/openset_eval_cpu.json (tools/tpu_day.sh arms the
TPU variant). CPU-safe: forces the host platform unless --platform
default.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _make_data(seed: int, n_known: int, rows_per_class: int):
    """(theta, Xtr, ytr, Xte, yte, X_unknown): known classes 0..n-1 at
    distinct rate scales, plus a held-out class at an out-of-family
    scale AND an inverted fwd/rev pattern (the novel application)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    F = 12
    # per-class feature means: rate scale 4^c times a class-specific
    # per-feature shape — separable the way real per-app mixes are
    theta = rng.gamma(2.0, 1.0, (n_known + 1, F)) + 0.5
    for c in range(n_known):
        theta[c] *= 100.0 * (4.0 ** c)
    # the unknown application: beyond every known scale, shuffled shape
    theta[n_known] = (
        theta[n_known][rng.permutation(F)] * 100.0 * (4.0 ** (n_known + 2))
    )

    def rows(c, n):
        return (rng.gamma(2.0, 1.0, (n, F)) * theta[c]).astype(
            np.float32
        )

    Xtr = np.concatenate([rows(c, rows_per_class) for c in range(n_known)])
    ytr = np.repeat(np.arange(n_known), rows_per_class).astype(np.int32)
    Xte = np.concatenate(
        [rows(c, rows_per_class // 2) for c in range(n_known)]
    )
    yte = np.repeat(
        np.arange(n_known), rows_per_class // 2
    ).astype(np.int32)
    Xun = rows(n_known, rows_per_class)
    return Xtr, ytr, Xte, yte, Xun


def _auc(pos, neg):
    """Mann-Whitney AUC: P(score(pos) > score(neg)) with tie credit."""
    import numpy as np

    pos = np.asarray(pos, np.float64)
    neg = np.asarray(neg, np.float64)
    order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
    ranks = np.empty(order.size, np.float64)
    ranks[order] = np.arange(1, order.size + 1)
    # midranks for ties
    allv = np.concatenate([pos, neg])
    for v in np.unique(allv):
        sel = allv == v
        if sel.sum() > 1:
            ranks[sel] = ranks[sel].mean()
    r_pos = ranks[: pos.size].sum()
    u = r_pos - pos.size * (pos.size + 1) / 2.0
    return float(u / (pos.size * neg.size))


def _roc(pos, neg, points: int = 21):
    """[(fpr, tpr)] swept over the pooled score range (pos = unknown
    scores, neg = known scores; higher = more unknown)."""
    import numpy as np

    pool = np.concatenate([pos, neg])
    out = []
    for q in np.linspace(0.0, 1.0, points):
        thr = float(np.quantile(pool, q))
        out.append((
            round(float((neg > thr).mean()), 6),
            round(float((pos > thr).mean()), 6),
        ))
    return out


def _fit(family, Xtr, ytr, n_classes):
    """The canonical per-family trainers (cli.py's retrain path)."""
    import jax.numpy as jnp

    if family == "logreg":
        from traffic_classifier_sdn_tpu.train import logreg as t

        return t.fit(jnp.asarray(Xtr), jnp.asarray(ytr), n_classes)
    if family == "gnb":
        from traffic_classifier_sdn_tpu.train import gnb as t

        return t.fit(Xtr, ytr, n_classes)
    if family == "kmeans":
        from traffic_classifier_sdn_tpu.train import kmeans as t

        params, _inertia = t.fit(Xtr, k=n_classes)
        return params
    if family == "knn":
        from traffic_classifier_sdn_tpu.train import knn as t

        return t.fit(Xtr, ytr, n_neighbors=5, n_classes=n_classes)
    if family == "forest":
        from traffic_classifier_sdn_tpu.train import forest as t

        return t.fit(Xtr, ytr, n_classes)
    from traffic_classifier_sdn_tpu.train import svc as t

    return t.fit(Xtr, ytr, n_classes)


def _eval_family(family, Xtr, ytr, Xte, yte, Xun, margin):
    import numpy as np

    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.models import MODEL_MODULES
    from traffic_classifier_sdn_tpu.serving.openset import (
        class_reference,
        openset_scores,
        reference_matrices,
    )

    n_known = int(ytr.max()) + 1
    mod = MODEL_MODULES[family]
    params = _fit(family, Xtr, ytr, n_known)

    def predict(X):
        return np.asarray(mod.predict(params, jnp.asarray(X)))

    def fam_scores(X):
        _labels, s = mod.predict_scores(params, jnp.asarray(X))
        return np.asarray(s)

    # serving-regime calibration: per-class stats keyed by the
    # family's OWN labels on the training window (kmeans labels are
    # cluster ids — the gate's stats follow whatever label space the
    # family serves, exactly as in the live gate)
    cal_labels = predict(Xtr)
    n_stat_classes = int(cal_labels.max()) + 1
    ref = class_reference(Xtr, cal_labels, n_stat_classes)
    # empty predicted classes are DROPPED, exactly as the serving gate
    # does (reference_matrices) — a phantom class at the origin would
    # accept low-rate novel traffic
    mean, inv_std = reference_matrices(
        ref, np.asarray(Xtr, np.float64).std(axis=0)
    )
    cal_scores = openset_scores(Xtr, mean, inv_std)
    threshold = margin * float(cal_scores.max())

    te_scores = openset_scores(Xte, mean, inv_std)
    un_scores = openset_scores(Xun, mean, inv_std)
    te_pred = predict(Xte)

    if family == "kmeans":
        # cluster ids are a permutation: mode-match before scoring
        # accuracy (analysis.eval's discipline)
        remap = {}
        for cid in np.unique(te_pred):
            vals, counts = np.unique(
                yte[te_pred == cid], return_counts=True
            )
            remap[int(cid)] = int(vals[np.argmax(counts)])
        matched = np.array([remap[int(c)] for c in te_pred])
        closed_acc = float((matched == yte).mean())
        gated_correct = (matched == yte) & (te_scores <= threshold)
    else:
        closed_acc = float((te_pred == yte).mean())
        gated_correct = (te_pred == yte) & (te_scores <= threshold)
    gated_acc = float(gated_correct.mean())

    # family score surface as a confidence diagnostic: LOW max-score =
    # less known (negate so higher = more unknown, like the serving
    # score)
    fam_auc = _auc(-fam_scores(Xun).max(axis=1),
                   -fam_scores(Xte).max(axis=1))

    return {
        "closed_accuracy": round(closed_acc, 6),
        "gated_accuracy": round(gated_acc, 6),
        "accuracy_delta": round(gated_acc - closed_acc, 6),
        "threshold": round(threshold, 6),
        "unknown_tpr_at_threshold": round(
            float((un_scores > threshold).mean()), 6
        ),
        "known_fpr_at_threshold": round(
            float((te_scores > threshold).mean()), 6
        ),
        "mahalanobis_auc": round(_auc(un_scores, te_scores), 6),
        "family_score_auc": round(fam_auc, 6),
        "roc": _roc(un_scores, te_scores),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", choices=("cpu", "default"),
                    default="cpu")
    ap.add_argument("--margin", type=float, default=3.0,
                    help="the shipped --openset-margin (default 3.0)")
    ap.add_argument("--rows-per-class", type=int, default=1024)
    ap.add_argument("--known-classes", type=int, default=5)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--families", default="logreg,gnb,kmeans,knn,svc,forest",
        help="comma-separated family subset (smoke tests trim the "
        "fit cost; the committed artifact carries all six)",
    )
    ap.add_argument("--out", default=None,
                    help="also write the JSON here")
    args = ap.parse_args()
    if args.platform == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import jax

    Xtr, ytr, Xte, yte, Xun = _make_data(
        args.seed, args.known_classes, args.rows_per_class
    )
    families = tuple(
        f.strip() for f in args.families.split(",") if f.strip()
    )
    out = {
        "bench": "openset_eval",
        "platform": jax.devices()[0].platform,
        "margin": args.margin,
        "known_classes": args.known_classes,
        "rows_per_class": args.rows_per_class,
        "seed": args.seed,
        "families": {},
        "notes": (
            "serving-regime calibration: per-class stats from each "
            "family's own predicted labels on the training window; "
            "threshold = margin x max calibration score. A rejected "
            "known-class row counts as an error in gated_accuracy, so "
            "accuracy_delta is the gate's false-reject cost. roc is "
            "[fpr, tpr] over the pooled score quantiles."
        ),
    }
    for family in families:
        print(f"evaluating {family} ...", file=sys.stderr, flush=True)
        out["families"][family] = _eval_family(
            family, Xtr, ytr, Xte, yte, Xun, args.margin
        )
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
