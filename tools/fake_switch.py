"""Fake OpenFlow 1.3 switch: connects to a controller, speaks enough of
the protocol to exercise the learning switch and the flow-stats monitor,
and simulates host traffic so flow counters evolve.

This is the test/demo stand-in for Mininet + Open vSwitch + D-ITG
(reference README.md:26-35): hosts exchange packets (→ PACKET_INs until
flows are installed), installed priority-1 flows accumulate synthetic
per-class packet/byte rates, and MULTIPART flow-stats requests are
answered from the simulated flow table.

Usable as a library (tests/test_controller.py, in-process asyncio) or as
a script:  python tools/fake_switch.py --port 6653 --hosts 4
"""

from __future__ import annotations

import argparse
import asyncio
import os
import random
import socket
import struct
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from traffic_classifier_sdn_tpu.controller import openflow as of  # noqa: E402


def eth_frame(src: str, dst: str, eth_type: int = 0x0800) -> bytes:
    return of.mac_bytes(dst) + of.mac_bytes(src) + struct.pack(
        "!H", eth_type
    ) + b"\x00" * 46


class FakeSwitch:
    """One simulated datapath with ``n_hosts`` hosts on ports 1..n."""

    def __init__(self, dpid: int = 1, n_hosts: int = 4,
                 rates: dict | None = None, seed: int = 0):
        self.dpid = dpid
        self.n_hosts = n_hosts
        self.macs = [f"00:00:00:00:00:{i + 1:02x}" for i in range(n_hosts)]
        self.port_of = {m: i + 1 for i, m in enumerate(self.macs)}
        # installed flows: list of dicts with match/priority/out_port/counters
        self.flows: list[dict] = []
        self.rng = random.Random(seed)
        # per-flow (pkts/s, bytes/s) rate; default: telnet-ish chatter
        self.rates = rates or {}
        self.default_rate = (20, 1200)
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self._mr = of.MessageReader()
        self._xid = 0
        self.packet_outs: list[dict] = []
        self.eof = False  # controller closed the connection

    def next_xid(self) -> int:
        self._xid += 1
        return self._xid

    async def connect(self, host: str, port: int) -> None:
        self.reader, self.writer = await asyncio.open_connection(host, port)

    async def pump(self, duration: float) -> None:
        """Process controller messages for ``duration`` seconds."""
        loop = asyncio.get_event_loop()
        end = loop.time() + duration
        while True:
            timeout = end - loop.time()
            if timeout <= 0:
                break
            try:
                data = await asyncio.wait_for(
                    self.reader.read(1 << 16), timeout=timeout
                )
            except asyncio.TimeoutError:
                break
            if not data:
                self.eof = True
                break
            for mtype, xid, body in self._mr.feed(data):
                self._handle(mtype, xid, body)
            await self.writer.drain()

    def _handle(self, mtype: int, xid: int, body: bytes) -> None:
        if mtype == of.OFPT_HELLO:
            self.writer.write(of.hello(self.next_xid()))
        elif mtype == of.OFPT_FEATURES_REQUEST:
            self.writer.write(of.features_reply(xid, self.dpid))
        elif mtype == of.OFPT_ECHO_REQUEST:
            self.writer.write(of.echo_reply(xid, body))
        elif mtype == of.OFPT_FLOW_MOD:
            fm = of.parse_flow_mod(body)
            if fm["command"] == of.OFPFC_ADD:
                self.flows.append(
                    {
                        "priority": fm["priority"],
                        "match": fm["match"],
                        "out_port": of.decode_output_port(fm["instructions"]),
                        "packets": 0,
                        "bytes": 0,
                    }
                )
        elif mtype == of.OFPT_PACKET_OUT:
            self.packet_outs.append({"xid": xid})
        elif mtype == of.OFPT_MULTIPART_REQUEST:
            mp_type, = struct.unpack_from("!H", body)
            if mp_type == of.OFPMP_FLOW:
                self._advance_counters()
                stats = [
                    of.FlowStat(
                        f["priority"], f["packets"], f["bytes"],
                        f["match"], f["out_port"],
                    )
                    for f in self.flows
                    if f["priority"] == 1
                ]
                self.writer.write(of.flow_stats_reply(xid, stats))
            # port-stats requests: reply with an empty port-stats body
            # (the controller discards it anyway, like the reference)
            elif mp_type == of.OFPMP_PORT_STATS:
                empty = struct.pack("!HH4x", of.OFPMP_PORT_STATS, 0)
                self.writer.write(
                    of.message(of.OFPT_MULTIPART_REPLY, xid, empty)
                )

    def _advance_counters(self) -> None:
        for f in self.flows:
            if f["priority"] != 1:
                continue
            key = (f["match"].get("eth_src"), f["match"].get("eth_dst"))
            pps, bps = self.rates.get(key, self.default_rate)
            f["packets"] += max(0, int(self.rng.gauss(pps, pps * 0.2)))
            f["bytes"] += max(0, int(self.rng.gauss(bps, bps * 0.2)))

    def send_packet(self, src_host: int, dst_host: int) -> None:
        """Host src sends one packet: emit the PACKET_IN the real switch
        would produce for a table miss."""
        src, dst = self.macs[src_host], self.macs[dst_host]
        match = of.encode_match(in_port=self.port_of[src])
        self.writer.write(
            of.packet_in(
                self.next_xid(), of.OFP_NO_BUFFER, 0, match,
                eth_frame(src, dst),
            )
        )

    def converse(self, a: int, b: int) -> None:
        """Two packets a→b then b→a: after the second, the controller has
        learned both MACs and installs the first priority-1 flow; a third
        a→b installs the reverse. Mirrors how OVS+Ryu converges."""
        self.send_packet(a, b)
        self.send_packet(b, a)
        self.send_packet(a, b)


class AccountingSwitch:
    """A listening OF1.3 datapath with a flow-mod accounting surface —
    the replay-test stand-in for OVS on the actuation side (the image
    has no OVS, so the end-to-end loop closes against this).

    Unlike :class:`FakeSwitch` (which dials out to a controller and
    simulates traffic for the telemetry plane), this one *listens* and
    accounts: every FLOW_MOD is decoded (match + structured
    instructions) into ``flow_log``, ADDs/DELETEs maintain the live
    ``rules`` view keyed by cookie, BARRIER_REQUESTs are answered in
    order, and two scriptable knobs break things on purpose:

    * ``script_refuse(n)`` — the next ``n`` flow-mods bounce with an
      OFPT_ERROR embedding the offending message (so the sender can
      recover the refused xid, as the spec intends)
    * ``script_stall_barrier(n)`` — the next ``n`` barrier replies are
      withheld (the lost-barrier failure an actuation plane must
      absorb without stalling its serve cadence)

    Thread-per-connection so a degraded client can reconnect while an
    old socket lingers; start()/stop() or use as a context manager.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 dpid: int = 1):
        self.host = host
        self.dpid = dpid
        self.flow_log: list[dict] = []
        self.rules: dict[int, dict] = {}  # cookie → live rule
        self.barriers = 0
        self.connections = 0
        self._refuse = 0
        self._stall = 0
        self._lock = threading.Lock()
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conn_threads: list[threading.Thread] = []

    # -- scripting ----------------------------------------------------------

    def script_refuse(self, n: int = 1) -> None:
        with self._lock:
            self._refuse += n

    def script_stall_barrier(self, n: int = 1) -> None:
        with self._lock:
            self._stall += n

    # -- accounting views ---------------------------------------------------

    def installs(self) -> list[dict]:
        with self._lock:
            return [e for e in self.flow_log if e["op"] == "install"]

    def deletes(self) -> list[dict]:
        with self._lock:
            return [e for e in self.flow_log if e["op"] == "delete"]

    def refusals(self) -> list[dict]:
        with self._lock:
            return [e for e in self.flow_log if e["refused"]]

    def live_cookies(self) -> set[int]:
        with self._lock:
            return set(self.rules)

    # -- server loop --------------------------------------------------------

    def start(self) -> "AccountingSwitch":
        self._srv.listen(8)
        self._srv.settimeout(0.1)
        t = threading.Thread(
            target=self._accept_loop, name="accounting-switch", daemon=True,
        )
        t.start()
        self._accept_thread = t
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        for t in self._conn_threads:
            t.join(timeout=2.0)

    def __enter__(self) -> "AccountingSwitch":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except (socket.timeout, OSError):
                continue
            with self._lock:
                self.connections += 1
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
            )
            t.start()
            self._conn_threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        mr = of.MessageReader()
        conn.settimeout(0.1)
        xid_out = 1 << 20  # our xids, clear of the client's range
        try:
            while not self._stop.is_set():
                try:
                    data = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not data:
                    break
                for mtype, xid, body in mr.feed(data):
                    xid_out += 1
                    reply = self._handle(mtype, xid, body, xid_out)
                    if reply:
                        conn.sendall(reply)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, mtype: int, xid: int, body: bytes,
                xid_out: int) -> bytes:
        if mtype == of.OFPT_HELLO:
            return of.hello(xid_out)
        if mtype == of.OFPT_ECHO_REQUEST:
            return of.echo_reply(xid, body)
        if mtype == of.OFPT_FEATURES_REQUEST:
            return of.features_reply(xid, self.dpid)
        if mtype == of.OFPT_BARRIER_REQUEST:
            with self._lock:
                self.barriers += 1
                if self._stall > 0:
                    self._stall -= 1
                    return b""  # withheld: the client's barrier is lost
            return of.barrier_reply(xid)
        if mtype == of.OFPT_FLOW_MOD:
            return self._handle_flow_mod(xid, body)
        return b""

    def _handle_flow_mod(self, xid: int, body: bytes) -> bytes:
        fm = of.parse_flow_mod(body)
        entry = {
            "op": "install" if fm["command"] == of.OFPFC_ADD else (
                "delete" if fm["command"] == of.OFPFC_DELETE else "modify"
            ),
            "xid": xid,
            "cookie": fm["cookie"],
            "priority": fm["priority"],
            "match": fm["match"],
            "instructions": of.decode_instructions(fm["instructions"]),
            "refused": False,
        }
        with self._lock:
            if self._refuse > 0:
                self._refuse -= 1
                entry["refused"] = True
                self.flow_log.append(entry)
                return of.error_msg(
                    xid, of.OFPET_FLOW_MOD_FAILED, 0,
                    of.message(of.OFPT_FLOW_MOD, xid, body),
                )
            self.flow_log.append(entry)
            if fm["command"] == of.OFPFC_ADD:
                # OF1.3 ADD semantics: identical match+priority replaces
                # the existing entry (whatever its cookie)
                for ck in [
                    ck for ck, r in self.rules.items()
                    if r["match"] == fm["match"]
                    and r["priority"] == fm["priority"]
                ]:
                    self.rules.pop(ck, None)
                self.rules[fm["cookie"]] = entry
            elif fm["command"] == of.OFPFC_DELETE:
                if fm["cookie_mask"]:
                    self.rules.pop(fm["cookie"], None)
                else:
                    # unmasked delete: match-wide removal
                    for ck in [
                        ck for ck, r in self.rules.items()
                        if r["match"] == fm["match"]
                    ]:
                        self.rules.pop(ck, None)
        return b""


async def run_standalone(port: int, n_hosts: int, host: str = "127.0.0.1",
                         duration: float = 0.0) -> None:
    sw = FakeSwitch(n_hosts=n_hosts)
    # the controller may take a while to come up (it's spawned after the
    # classifier's JAX/model init): retry for up to ~60 s
    for attempt in range(300):
        try:
            await sw.connect(host, port)
            break
        except ConnectionRefusedError:
            if attempt == 299:
                raise
            await asyncio.sleep(0.2)
    await sw.pump(0.5)
    # all host pairs converse so flows get installed
    for a in range(0, n_hosts - 1, 2):
        sw.converse(a, a + 1)
    loop = asyncio.get_event_loop()
    end = loop.time() + duration if duration else None
    while (end is None or loop.time() < end) and not sw.eof:
        await sw.pump(1.0)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=6653)
    p.add_argument("--hosts", type=int, default=4)
    p.add_argument("--duration", type=float, default=0.0, help="0 = forever")
    a = p.parse_args(argv)
    try:
        asyncio.run(run_standalone(a.port, a.hosts, a.host, a.duration))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
