#!/bin/sh
# One-shot static-analysis gate: graftlint + ruff + mypy.
#
#   graftlint  project-native AST rules (jit-purity, retrace-hazard,
#              ctypes-abi, lock-discipline, fault-site-registry,
#              atomic-io, plus the graftlock whole-program concurrency
#              pass: lock-order, blocking-under-lock,
#              thread-lifecycle, plus the graftsync device-boundary
#              pass: implicit-sync, transfer-discipline,
#              donation-hazard, sync-under-lock — 13 rules) — always
#              runs, zero findings required. Also enforced in tier-1
#              via `pytest -m lint`
#              (tests/test_graftlint.py::test_package_is_clean);
#              `--list-rules` prints the full set.
#   ruff       generic baseline, config pinned in [tool.ruff]
#   mypy       typing baseline, config pinned in [tool.mypy]
#
# ruff/mypy are optional in the container image: when absent they are
# reported as "skipped" (visible in the JSON summary below), never
# silently dropped — the gate still fails if an INSTALLED tool finds
# violations. Machine-readable findings land in $LINT_SUMMARY (default:
# a per-run /tmp/lint_summary.<pid>.json, path echoed on exit):
# per-tool status plus graftlint's full --json findings array (the
# schema_version-stamped report) and the path of the SARIF 2.1.0 copy
# ($LINT_SARIF, default /tmp/graftlint_sarif.<pid>.json) CI can feed
# to an inline annotator.
#
# Usage: tools/lint.sh [paths...]   (default: the package only — tests/
# and tools/ are not held to the graftlint bar; pass them explicitly to
# audit them, e.g. `tools/lint.sh traffic_classifier_sdn_tpu tests tools`)
cd "$(dirname "$0")/.." || exit 2

# per-run default so concurrent runs don't overwrite each other's
# summary; set LINT_SUMMARY for a stable consumer-facing location
SUMMARY="${LINT_SUMMARY:-/tmp/lint_summary.$$.json}"
# positional params (not a flattened string) so paths containing
# spaces/globs survive: pass "$@" everywhere
[ "$#" -eq 0 ] && set -- traffic_classifier_sdn_tpu

fail=0

# ---- graftlint -------------------------------------------------------------
echo "=== graftlint ($*)"
# per-run temp file: concurrent lint runs (CI matrix, two worktrees)
# must not clobber each other's findings before the summary step reads
# them back
GRAFT_JSON="$(mktemp /tmp/graftlint_findings.XXXXXX.json)" || exit 2
trap 'rm -f "$GRAFT_JSON"' EXIT
# SARIF copy survives the run (CI uploads it for inline annotations);
# per-run default so concurrent runs never clobber each other
SARIF_OUT="${LINT_SARIF:-/tmp/graftlint_sarif.$$.json}"
if JAX_PLATFORMS=cpu python -m traffic_classifier_sdn_tpu.analysis_static \
     --json --sarif "$SARIF_OUT" "$@" > "$GRAFT_JSON"; then
  graftlint_status=pass
  echo "graftlint: clean"
else
  graftlint_status=fail
  fail=1
  python - "$GRAFT_JSON" <<'EOF'
import json, sys
try:
    with open(sys.argv[1]) as f:
        report = json.load(f)
except (OSError, ValueError):
    # exit 2 (usage error): graftlint wrote its diagnostic to stderr
    # above and no findings report exists
    print("graftlint: usage error (no findings report)")
    sys.exit(0)
for finding in report["findings"]:
    print("{path}:{line}: [{rule}] {message}".format(**finding))
print(f"graftlint: {report['count']} finding(s)")
EOF
fi

# ---- ruff ------------------------------------------------------------------
echo "=== ruff"
if python -m ruff --version >/dev/null 2>&1; then
  if python -m ruff check "$@"; then
    ruff_status=pass
    echo "ruff: clean"
  else
    ruff_status=fail
    fail=1
  fi
else
  ruff_status=skipped
  echo "ruff: skipped (not installed in this image; config pinned in [tool.ruff])"
fi

# ---- mypy ------------------------------------------------------------------
# NB: mypy's scope is FIXED to the files list pinned in [tool.mypy]
# (the package), regardless of the paths passed to this script — the
# typing bar applies to the package only, and a scoped graftlint/ruff
# run should not silently imply those extra paths were type-checked.
echo "=== mypy (scope pinned in [tool.mypy], ignores script paths)"
if python -m mypy --version >/dev/null 2>&1; then
  if python -m mypy; then
    mypy_status=pass
    echo "mypy: clean"
  else
    mypy_status=fail
    fail=1
  fi
else
  mypy_status=skipped
  echo "mypy: skipped (not installed in this image; config pinned in [tool.mypy])"
fi

# ---- summary ---------------------------------------------------------------
python - "$SUMMARY" "$GRAFT_JSON" \
    "$graftlint_status" "$ruff_status" "$mypy_status" "$SARIF_OUT" <<'EOF'
import json, os, sys
out, graft_json, graftlint, ruff, mypy, sarif = sys.argv[1:7]
try:
    with open(graft_json) as f:
        findings = json.load(f)["findings"]
except (OSError, ValueError, KeyError):
    findings = []
# the enabled rule set, read back from the SARIF driver catalog so the
# summary's list can never drift from what actually ran
try:
    with open(sarif) as f:
        rules = [r["id"] for r in
                 json.load(f)["runs"][0]["tool"]["driver"]["rules"]]
except (OSError, ValueError, KeyError, IndexError):
    rules = []
summary = {
    "tools": [
        {"name": "graftlint", "status": graftlint, "findings": findings,
         "rules": rules,
         # the SARIF path is recorded even when clean — CI annotators
         # want the (empty) run object either way; absent only on a
         # usage-error run that never wrote it
         "sarif": sarif if os.path.exists(sarif) else None},
        {"name": "ruff", "status": ruff},
        {"name": "mypy", "status": mypy},
    ],
    "ok": graftlint == "pass" and "fail" not in (ruff, mypy),
}
with open(out, "w") as f:
    json.dump(summary, f, indent=2)
print(json.dumps(summary if findings else {
    k: ([{t["name"]: t["status"]} for t in summary["tools"]]
        if k == "tools" else v)
    for k, v in summary.items()
}))
EOF

if [ "$fail" -eq 0 ]; then
  echo "lint: gate clean (graftlint=$graftlint_status ruff=$ruff_status mypy=$mypy_status; summary: $SUMMARY)"
  exit 0
fi
echo "lint: FAILURES (graftlint=$graftlint_status ruff=$ruff_status mypy=$mypy_status; summary: $SUMMARY)" >&2
exit 1
