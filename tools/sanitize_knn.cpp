// Sanitizer driver for the pruned/IVF KNN evaluator (knn_eval.cpp) —
// the KNN sibling of tools/sanitize_feed_flush.cpp. Build with
// ASan/UBSan or TSan and run via tools/native_sanitize.sh (phases
// knn_asan / knn_tsan). TC_KNN_THREADS > 1 drives CONCURRENT
// tck_predict / tck_votes / tck_predict_unpruned / tck_predict_ivf /
// tck_screen_stats calls over one shared handle — the evaluator's
// read-only-after-build contract, checked for real.
//
// Phases per corpus:
//   1. build + single-thread parity self-check: pruned vs unpruned
//      vote-for-vote over predict AND votes (exit 1 on divergence);
//   2. IVF build (stride-spread assignment — every list nonempty) +
//      nprobe sweep incl. nprobe > K (clamp) and nprobe == K, which
//      must equal the pruned exact predict bit-for-bit;
//   3. TC_KNN_THREADS concurrent mixed-entry-point workers over
//      OVERLAPPING query slices + a stats poller;
//   4. non-finite queries (nan/±inf rows) through every entry point.
//
// Corpora: a gamma-mixture at chunk-straddling sizes, the DEGENERATE
// all-identical-points corpus (every triangle bound ties — the screens
// must stay lossless with zero pruning power), and a k == S corpus
// (the whole corpus IS the top-k: nothing may be screened away).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

extern "C" {
void *tck_create(uint32_t S, uint32_t F, uint32_t C, uint32_t k,
                 const float *fit_X, const int32_t *fit_y);
void tck_destroy(void *h);
void tck_predict(void *h, const float *X, uint64_t N, uint32_t F,
                 int32_t *out);
void tck_votes(void *h, const float *X, uint64_t N, uint32_t F,
               int32_t *out);
void tck_predict_unpruned(void *h, const float *X, uint64_t N,
                          uint32_t F, int32_t *out);
void tck_votes_unpruned(void *h, const float *X, uint64_t N, uint32_t F,
                        int32_t *out);
int32_t tck_ivf_build(void *h, uint32_t K, const float *centers,
                      const int32_t *assign);
void tck_predict_ivf(void *h, const float *X, uint64_t N, uint32_t F,
                     uint32_t nprobe, int32_t *out);
void tck_votes_ivf(void *h, const float *X, uint64_t N, uint32_t F,
                   uint32_t nprobe, int32_t *out);
void tck_screen_stats(void *h, uint64_t *out);
}

namespace {

constexpr uint32_t F = 12;
constexpr uint32_t C = 6;

std::atomic<int> failures{0};

void check(bool ok, const char *what) {
    if (!ok) {
        std::fprintf(stderr, "sanitize_knn: FAIL %s\n", what);
        ++failures;
    }
}

void drive_corpus(const std::vector<float> &fit,
                  const std::vector<int32_t> &y, uint32_t S, uint32_t k,
                  int threads, const char *name) {
    void *h = tck_create(S, F, C, k, fit.data(), y.data());
    if (!h) {
        std::fprintf(stderr, "sanitize_knn: create rejected %s\n", name);
        ++failures;
        return;
    }
    std::mt19937 rng(99);
    std::normal_distribution<double> nj(0.0, 0.05);
    const uint64_t N = 513;  // non-multiple-of-8: query-block tail
    std::vector<float> X(N * F);
    for (uint64_t q = 0; q < N; ++q) {
        const uint32_t src = rng() % S;
        for (uint32_t f = 0; f < F; ++f)
            X[q * F + f] =
                float(std::abs(fit[src * F + f] * (1.0 + nj(rng))));
    }
    // 1. parity self-check, single thread
    std::vector<int32_t> a(N), b(N), va(N * C), vb(N * C);
    tck_predict(h, X.data(), N, F, a.data());
    tck_predict_unpruned(h, X.data(), N, F, b.data());
    check(std::memcmp(a.data(), b.data(), N * 4) == 0, name);
    tck_votes(h, X.data(), N, F, va.data());
    tck_votes_unpruned(h, X.data(), N, F, vb.data());
    check(std::memcmp(va.data(), vb.data(), N * C * 4) == 0, name);
    // 2. IVF: stride assignment (deterministic, every list nonempty)
    const uint32_t K = S < 8 ? 1 : 8;
    std::vector<float> centers(size_t(K) * F, 0.0f);
    std::vector<int32_t> assign(S);
    std::vector<uint32_t> counts(K, 0);
    for (uint32_t s = 0; s < S; ++s) {
        assign[s] = int32_t(s % K);
        ++counts[s % K];
        for (uint32_t f = 0; f < F; ++f)
            centers[(s % K) * F + f] += fit[s * F + f];
    }
    for (uint32_t c = 0; c < K; ++c)
        for (uint32_t f = 0; f < F; ++f)
            centers[c * F + f] /= float(counts[c]);
    check(tck_ivf_build(h, K, centers.data(), assign.data()) == 0,
          "ivf_build");
    std::vector<int32_t> iv(N), ivv(N * C);
    for (uint32_t npb : {1u, 3u, K, K + 7u}) {  // incl. clamp past K
        tck_predict_ivf(h, X.data(), N, F, npb, iv.data());
        tck_votes_ivf(h, X.data(), N, F, npb, ivv.data());
        if (npb >= K)  // every list probed == the exact search
            check(std::memcmp(iv.data(), a.data(), N * 4) == 0,
                  "ivf nprobe>=K exact");
    }
    // 3. concurrent mixed entry points over overlapping slices
    std::vector<std::thread> ts;
    for (int t = 0; t < threads; ++t) {
        ts.emplace_back([&, t] {
            std::vector<int32_t> out(N), vout(N * C);
            for (int it = 0; it < 4; ++it) {
                switch ((t + it) % 4) {
                case 0:
                    tck_predict(h, X.data(), N, F, out.data());
                    check(std::memcmp(out.data(), a.data(),
                                      N * 4) == 0,
                          "concurrent pruned parity");
                    break;
                case 1:
                    tck_votes(h, X.data(), N, F, vout.data());
                    break;
                case 2:
                    tck_predict_unpruned(h, X.data(), N, F,
                                         out.data());
                    break;
                default:
                    tck_predict_ivf(h, X.data(), N, F, 3,
                                    out.data());
                }
                uint64_t st[3];
                tck_screen_stats(h, st);  // live accounting poll
            }
        });
    }
    for (auto &t : ts) t.join();
    // 4. non-finite queries through every entry point (parity incl.)
    std::vector<float> bad(16 * F, 0.0f);
    for (uint32_t f = 0; f < F; ++f) {
        bad[0 * F + f] = std::numeric_limits<float>::quiet_NaN();
        bad[1 * F + f] = std::numeric_limits<float>::infinity();
        bad[2 * F + f] = -std::numeric_limits<float>::infinity();
    }
    bad[3 * F + 5] = std::numeric_limits<float>::quiet_NaN();
    std::vector<int32_t> ba(16), bb(16), bv(16 * C);
    tck_predict(h, bad.data(), 16, F, ba.data());
    tck_predict_unpruned(h, bad.data(), 16, F, bb.data());
    check(std::memcmp(ba.data(), bb.data(), 16 * 4) == 0,
          "nonfinite parity");
    tck_votes(h, bad.data(), 16, F, bv.data());
    tck_predict_ivf(h, bad.data(), 16, F, 2, ba.data());
    tck_destroy(h);
    std::fprintf(stderr, "sanitize_knn: corpus %s ok\n", name);
}

}  // namespace

int main() {
    const char *env = std::getenv("TC_KNN_THREADS");
    const int threads = env ? std::atoi(env) : 1;
    std::mt19937 rng(7);
    std::gamma_distribution<double> g1(2.0, 100.0), g2(2.0, 1.0);

    // gamma mixture at chunk-straddling sizes (kEChunk=32 boundaries)
    for (uint32_t S : {31u, 32u, 33u, 255u, 257u, 900u}) {
        std::vector<float> theta(C * F);
        for (auto &v : theta) v = float(g1(rng));
        std::vector<float> fit(size_t(S) * F);
        std::vector<int32_t> y(S);
        for (uint32_t s = 0; s < S; ++s) {
            y[s] = int32_t(rng() % C);
            for (uint32_t f = 0; f < F; ++f)
                fit[s * F + f] = float(g2(rng)) * theta[y[s] * F + f];
        }
        char name[32];
        std::snprintf(name, sizeof(name), "gamma-S%u", S);
        drive_corpus(fit, y, S, 5, threads, name);
    }

    // DEGENERATE: all points identical — every bound ties, screens
    // must stay lossless with zero pruning power
    {
        const uint32_t S = 300;
        std::vector<float> fit(size_t(S) * F, 41.5f);
        std::vector<int32_t> y(S);
        for (uint32_t s = 0; s < S; ++s) y[s] = int32_t(s % C);
        drive_corpus(fit, y, S, 5, threads, "all-identical");
    }

    // k == S: the whole corpus is the top-k — nothing may screen away
    {
        const uint32_t S = 48;
        std::vector<float> fit(size_t(S) * F);
        std::vector<int32_t> y(S);
        for (uint32_t s = 0; s < S; ++s) {
            y[s] = int32_t(rng() % C);
            for (uint32_t f = 0; f < F; ++f)
                fit[s * F + f] = float(g2(rng)) * 100.0f;
        }
        drive_corpus(fit, y, S, S, threads, "k-equals-S");
    }

    if (failures.load()) {
        std::fprintf(stderr, "sanitize_knn: %d FAILURES\n",
                     failures.load());
        return 1;
    }
    std::fprintf(stderr, "sanitize_knn: all clean (threads=%d)\n",
                 threads);
    return 0;
}
