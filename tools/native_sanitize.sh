#!/bin/sh
# Sanitizer pass over the native C++ evaluators: ASan+UBSan builds of
# forest_eval.cpp and knn_eval.cpp driven across the reference corpus,
# nonfinite/odd-shape inputs (including the exact 8-row query block),
# chunk-boundary corpus sizes, and irregular freshly-fit sklearn forests
# (exercising the DFS-preorder remap). The sanitized builds go through
# the SAME LazyLib machinery the real loaders use — with the sanitizer
# flags on the LazyLib itself, so even a mid-run rebuild stays
# sanitized. Exits 0 iff everything is clean. Not part of the test
# suite (the LD_PRELOAD ASan runtime is too invasive for pytest); run
# standalone: `sh tools/native_sanitize.sh`.
set -e
cd "$(dirname "$0")/.."

PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu ASAN_OPTIONS=detect_leaks=0 \
LD_PRELOAD="$(g++ -print-file-name=libasan.so)" python - <<'EOF'
import numpy as np
import traffic_classifier_sdn_tpu.native.forest as nf
import traffic_classifier_sdn_tpu.native.knn as nk

SAN = ("-O1", "-g", "-fsanitize=address,undefined",
       "-fno-sanitize-recover=all")
nf._lazy = nf.LazyLib(nf._lazy._src, "/tmp/_fe_asan.so",
                      "asan forest", flags=SAN)
nk._lazy = nk.LazyLib(nk._lazy._src, "/tmp/_knn_asan.so",
                      "asan knn", flags=SAN + ("-march=native",))

from traffic_classifier_sdn_tpu.io import sklearn_import as ski
from traffic_classifier_sdn_tpu.io.datasets import load_reference_datasets

d = ski.import_forest('/root/reference/models/RandomForestClassifier')
f = nf.NativeForest(d)
ds = load_reference_datasets('/root/reference/datasets')
X = ds.X.astype(np.float32)
f.predict(X)
f.predict_proba(X[:256])
bad = np.zeros((13, 12), np.float32)
bad[0] = -np.inf; bad[1] = np.nan; bad[2] = np.inf
for Xs in (bad, X[:1], X[:8], X[:255], X[:257]):
    f.predict(Xs)
print('forest: asan/ubsan clean', flush=True)

h = nk.NativeKnn(ski.import_knn('/root/reference/models/KNeighbors'))
# 8 = exactly one query block (kQueryBlock): the no-tail path
for Xs in (X, X[:1], X[:7], X[:8], X[:9], bad):
    h.predict(Xs)
rng = np.random.RandomState(0)
for S in (5, 255, 256, 257, 511, 513):
    hh = nk.NativeKnn({
        'fit_X': rng.rand(S, 12),
        'y': rng.randint(0, 6, S).astype(np.int32),
        'n_neighbors': 5, 'classes': np.arange(6),
    })
    hh.predict(np.asarray(rng.rand(33, 12), np.float32))
    hh.predict(np.asarray(rng.rand(16, 12), np.float32))  # N % 8 == 0
    hh.close()
print('knn: asan/ubsan clean', flush=True)

import warnings
warnings.filterwarnings('ignore')
from sklearn.ensemble import RandomForestClassifier
for t in range(3):
    Xt = rng.randint(0, 5, (300, 12)).astype(np.float64)
    yt = rng.randint(0, 4, 300)
    est = RandomForestClassifier(
        n_estimators=6, max_depth=None if t % 2 else 4, random_state=t,
    ).fit(Xt, yt)
    # the importer's OWN packing (max_depth/n_features derived, never
    # hand-set) — the fuzz exercises exactly the production layout
    ff = nf.NativeForest(ski.forest_dict_from_estimator(est))
    ff.predict(np.asarray(rng.rand(77, 12) * 6, np.float32))
    ff.close()
print('irregular-forest remap: asan/ubsan clean', flush=True)
EOF
echo "native_sanitize: all clean"
