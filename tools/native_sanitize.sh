#!/bin/sh
# Three-sanitizer gate over the native C++ host spine:
#
#   asan   ASan(+UBSan) builds of forest_eval.cpp and knn_eval.cpp driven
#          across nonfinite/odd-shape inputs (including the exact 8-row
#          query block), chunk-boundary corpus sizes, and irregular
#          freshly-fit sklearn forests (the DFS-preorder remap) — plus
#          the reference corpus when /root/reference is present. The
#          sanitized builds go through the SAME LazyLib machinery the
#          real loaders use, so even a mid-run rebuild stays sanitized.
#   ubsan  UBSan-only build of flow_engine.cpp linked against the
#          feed/flush driver (tools/sanitize_feed_flush.cpp): integer/
#          pointer UB under both single- and multi-threaded load. The
#          driver's second phase hammers the namespaced parser —
#          concurrent tck_feed_lines from N sources over OVERLAPPING
#          flow tuples (disjoint namespaces), per-source tail carries
#          split mid-line, deliberate malformed lines (counted, never
#          crashing), the packed tck_flush_wire drain, live per-source
#          accounting polls, and a tck_slots_for_source eviction.
#   asan_engine  ASan(+UBSan) build of the same driver pair — heap
#          errors in the per-source tail map / wire staging / namespace
#          scan that UBSan alone would miss.
#   tsan   ThreadSanitizer build of the same pair, driving concurrent
#          tc_engine_feed / tck_feed_lines / flush / bookkeeping-poll
#          threads — the engine's mutex contract, checked for real (a
#          lock removal fails this phase with TSan exit 66, verified).
#   knn_asan  ASan+UBSan build of the pruned-KNN driver
#          (tools/sanitize_knn.cpp + knn_eval.cpp): pruned-vs-unpruned
#          vote parity self-checks, IVF builds + nprobe clamps, the
#          DEGENERATE all-identical-points corpus (every triangle bound
#          ties), a k == S corpus, non-finite queries, and concurrent
#          mixed-entry-point calls over one shared handle.
#   knn_tsan  TSan build of the same driver — the evaluator's
#          read-only-after-build contract plus the relaxed-atomic
#          screen counters under 4 concurrent predict threads.
#
# Exits 0 iff every phase is clean, and always writes a machine-readable
# per-phase summary (JSON) to $NATIVE_SANITIZE_SUMMARY (default: a
# per-run /tmp/native_sanitize_summary.<pid>.json, path echoed on exit)
# — the chaos/lint tooling reads phase names from there rather than
# scraping logs. Not part of the
# pytest suite (the LD_PRELOAD ASan runtime is too invasive for pytest);
# run standalone: `sh tools/native_sanitize.sh`.
cd "$(dirname "$0")/.." || exit 2

# per-run default so concurrent runs don't overwrite each other's
# summary; set NATIVE_SANITIZE_SUMMARY for a stable location
SUMMARY="${NATIVE_SANITIZE_SUMMARY:-/tmp/native_sanitize_summary.$$.json}"
# per-run scratch dir: concurrent runs (CI matrix, two worktrees) must
# not execute each other's half-rebuilt driver binaries
WORK="$(mktemp -d /tmp/native_sanitize.XXXXXX)" || exit 2
trap 'rm -rf "$WORK"' EXIT
asan_status=fail
ubsan_status=fail
asan_engine_status=fail
tsan_status=fail
knn_asan_status=fail
knn_tsan_status=fail

# ---- phase 1: asan (ASan+UBSan on the ctypes evaluators) -------------------
echo "=== phase asan: forest_eval + knn_eval under ASan+UBSan"
if PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu ASAN_OPTIONS=detect_leaks=0 \
   NATIVE_SANITIZE_WORK="$WORK" \
   LD_PRELOAD="$(g++ -print-file-name=libasan.so)" python - <<'EOF'
import os

WORK = os.environ["NATIVE_SANITIZE_WORK"]

import numpy as np
import traffic_classifier_sdn_tpu.native.forest as nf
import traffic_classifier_sdn_tpu.native.knn as nk

SAN = ("-O1", "-g", "-fsanitize=address,undefined",
       "-fno-sanitize-recover=all")
nf._lazy = nf.LazyLib(nf._lazy._src, WORK + "/fe_asan.so",
                      "asan forest", flags=SAN)
nk._lazy = nk.LazyLib(nk._lazy._src, WORK + "/knn_asan.so",
                      "asan knn", flags=SAN + ("-march=native",))

rng = np.random.RandomState(0)
bad = np.zeros((13, 12), np.float32)
bad[0] = -np.inf; bad[1] = np.nan; bad[2] = np.inf

# Reference checkpoints/datasets when baked into the image; the
# synthetic sweeps below cover the same code paths when they are not.
if os.path.isdir('/root/reference'):
    from traffic_classifier_sdn_tpu.io import sklearn_import as ski
    from traffic_classifier_sdn_tpu.io.datasets import (
        load_reference_datasets,
    )

    d = ski.import_forest('/root/reference/models/RandomForestClassifier')
    f = nf.NativeForest(d)
    ds = load_reference_datasets('/root/reference/datasets')
    X = ds.X.astype(np.float32)
    f.predict(X)
    f.predict_proba(X[:256])
    for Xs in (bad, X[:1], X[:8], X[:255], X[:257]):
        f.predict(Xs)
    h = nk.NativeKnn(ski.import_knn('/root/reference/models/KNeighbors'))
    # 8 = exactly one query block (kQueryBlock): the no-tail path
    for Xs in (X, X[:1], X[:7], X[:8], X[:9], bad):
        h.predict(Xs)
    print('reference corpus: asan/ubsan clean', flush=True)
else:
    print('NOTE: /root/reference absent — synthetic sweeps only',
          flush=True)

# chunk-boundary corpus sizes + the 8-row query block, synthetic
for S in (5, 255, 256, 257, 511, 513):
    hh = nk.NativeKnn({
        'fit_X': rng.rand(S, 12),
        'y': rng.randint(0, 6, S).astype(np.int32),
        'n_neighbors': 5, 'classes': np.arange(6),
    })
    hh.predict(np.asarray(rng.rand(33, 12), np.float32))
    hh.predict(np.asarray(rng.rand(16, 12), np.float32))  # N % 8 == 0
    hh.predict(bad)
    hh.close()
print('knn: asan/ubsan clean', flush=True)

import warnings
warnings.filterwarnings('ignore')
from sklearn.ensemble import RandomForestClassifier
from traffic_classifier_sdn_tpu.io import sklearn_import as ski
for t in range(3):
    Xt = rng.randint(0, 5, (300, 12)).astype(np.float64)
    yt = rng.randint(0, 4, 300)
    est = RandomForestClassifier(
        n_estimators=6, max_depth=None if t % 2 else 4, random_state=t,
    ).fit(Xt, yt)
    # the importer's OWN packing (max_depth/n_features derived, never
    # hand-set) — the fuzz exercises exactly the production layout
    ff = nf.NativeForest(ski.forest_dict_from_estimator(est))
    ff.predict(np.asarray(rng.rand(77, 12) * 6, np.float32))
    ff.predict(bad)
    ff.close()
print('irregular-forest remap: asan/ubsan clean', flush=True)
EOF
then
  asan_status=pass
fi

# ---- phase 2: ubsan (flow_engine + feed/flush driver) ----------------------
echo "=== phase ubsan: flow_engine under UBSan (single + multi thread)"
if g++ -O1 -g -fsanitize=undefined -fno-sanitize-recover=all \
     -std=c++17 -pthread -o "$WORK/tc_ubsan_drv" \
     tools/sanitize_feed_flush.cpp \
     traffic_classifier_sdn_tpu/native/flow_engine.cpp \
   && "$WORK/tc_ubsan_drv" \
   && TC_ENGINE_THREADS=4 "$WORK/tc_ubsan_drv"; then
  ubsan_status=pass
  echo "flow_engine: ubsan clean"
fi

# ---- phase 2b: asan_engine (flow_engine + driver under ASan+UBSan) ---------
echo "=== phase asan_engine: flow_engine driver under ASan+UBSan"
if g++ -O1 -g -fsanitize=address,undefined -fno-sanitize-recover=all \
     -std=c++17 -pthread -o "$WORK/tc_asan_drv" \
     tools/sanitize_feed_flush.cpp \
     traffic_classifier_sdn_tpu/native/flow_engine.cpp \
   && ASAN_OPTIONS=detect_leaks=0 "$WORK/tc_asan_drv" \
   && ASAN_OPTIONS=detect_leaks=0 TC_ENGINE_THREADS=4 "$WORK/tc_asan_drv"
then
  asan_engine_status=pass
  echo "flow_engine: asan clean"
fi

# ---- phase 3: tsan (concurrent feed/flush) ---------------------------------
echo "=== phase tsan: concurrent tc_engine_feed/tc_engine_flush under TSan"
if g++ -O1 -g -fsanitize=thread \
     -std=c++17 -pthread -o "$WORK/tc_tsan_drv" \
     tools/sanitize_feed_flush.cpp \
     traffic_classifier_sdn_tpu/native/flow_engine.cpp \
   && TSAN_OPTIONS=halt_on_error=1 "$WORK/tc_tsan_drv" \
   && TSAN_OPTIONS=halt_on_error=1 TC_ENGINE_THREADS=4 "$WORK/tc_tsan_drv"
then
  tsan_status=pass
  echo "flow_engine: tsan clean"
fi

# ---- phase 4: knn_asan (pruned KNN driver under ASan+UBSan) ----------------
echo "=== phase knn_asan: pruned/IVF knn_eval driver under ASan+UBSan"
if g++ -O1 -g -fsanitize=address,undefined -fno-sanitize-recover=all \
     -std=c++17 -pthread -o "$WORK/knn_asan_drv" \
     tools/sanitize_knn.cpp \
     traffic_classifier_sdn_tpu/native/knn_eval.cpp \
   && ASAN_OPTIONS=detect_leaks=0 "$WORK/knn_asan_drv" \
   && ASAN_OPTIONS=detect_leaks=0 TC_KNN_THREADS=4 "$WORK/knn_asan_drv"
then
  knn_asan_status=pass
  echo "knn_eval: asan clean"
fi

# ---- phase 5: knn_tsan (concurrent pruned/IVF predicts) --------------------
echo "=== phase knn_tsan: concurrent knn_eval predicts under TSan"
if g++ -O1 -g -fsanitize=thread \
     -std=c++17 -pthread -o "$WORK/knn_tsan_drv" \
     tools/sanitize_knn.cpp \
     traffic_classifier_sdn_tpu/native/knn_eval.cpp \
   && TSAN_OPTIONS=halt_on_error=1 TC_KNN_THREADS=4 "$WORK/knn_tsan_drv"
then
  knn_tsan_status=pass
  echo "knn_eval: tsan clean"
fi

# ---- summary ---------------------------------------------------------------
printf '{"phases": [{"name": "asan", "status": "%s"}, {"name": "ubsan", "status": "%s"}, {"name": "asan_engine", "status": "%s"}, {"name": "tsan", "status": "%s"}, {"name": "knn_asan", "status": "%s"}, {"name": "knn_tsan", "status": "%s"}], "ok": %s}\n' \
  "$asan_status" "$ubsan_status" "$asan_engine_status" "$tsan_status" \
  "$knn_asan_status" "$knn_tsan_status" \
  "$([ "$asan_status$ubsan_status$asan_engine_status$tsan_status$knn_asan_status$knn_tsan_status" = passpasspasspasspasspass ] \
     && echo true || echo false)" > "$SUMMARY"
cat "$SUMMARY"

if [ "$asan_status$ubsan_status$asan_engine_status$tsan_status$knn_asan_status$knn_tsan_status" = passpasspasspasspasspass ]; then
  echo "native_sanitize: all clean (summary: $SUMMARY)"
  exit 0
fi
echo "native_sanitize: FAILURES (asan=$asan_status ubsan=$ubsan_status asan_engine=$asan_engine_status tsan=$tsan_status knn_asan=$knn_asan_status knn_tsan=$knn_tsan_status)" >&2
exit 1
