#!/usr/bin/env python
"""Adversarial scenario campaign runner (F13): drive the scenario
matrix through the REAL serve composition and emit the per-scenario
SLO scorecard artifact.

Each scenario (traffic_classifier_sdn_tpu/scenarios/library.py) is a
declarative phase timeline — flash crowd, source flap storm,
cumulative-counter reset storm, novel-class wave + boundary-hugging
evasion, mass-eviction churn spike, queue-saturation flood, device
wedge, label flap storm vs the actuation hysteresis — run through the
fan-in tier × native ingest × incremental serving stack with the
relevant ladders live (the flap storm pushes real flow-mods at an
in-process AccountingSwitch), and scored against its gates: cadence
p50, EXACT per-source drop accounting (zero silent drops), e2e p99
via the latency-provenance waterfall, required state transitions
observed in the flight recorder, open-world ground truth where the
scenario injects novelty, and — where actuation is armed — zero rule
flaps with an exact rule ledger.

Writes docs/artifacts/scenario_matrix_cpu.json (tools/tpu_day.sh arms
the scenario_matrix_tpu.json variant) and EXITS NONZERO on any gate
failure — the matrix is a gate, not a report. A failing scenario also
leaves an atomic post-mortem bundle (flight-recorder JSONL + metrics
snapshot + timeline-position manifest) under --obs-dir, named by
scenario id.

Usage: bench_scenarios.py [--profile cpu] [--scenario id ...]
       [--native auto|on|off] [--out PATH] [--obs-dir DIR]
(CPU-safe: forces the host platform unless --platform default.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser(
        description="run the adversarial scenario matrix"
    )
    ap.add_argument("--profile", choices=("t1", "cpu"), default="cpu",
                    help="scenario scale: t1 (tier-1 test shape) or "
                         "cpu (the committed-artifact shape)")
    ap.add_argument("--scenario", action="append", default=None,
                    metavar="ID",
                    help="run only this scenario (repeatable; "
                         "default: the whole matrix)")
    ap.add_argument("--native", choices=("auto", "on", "off"),
                    default="auto",
                    help="C++ ingest spine: auto uses it when built")
    ap.add_argument("--platform", choices=("cpu", "tpu", "default"),
                    default="cpu",
                    help="cpu pins JAX_PLATFORMS=cpu; default "
                         "inherits the environment (chip runs)")
    ap.add_argument("--out", default=None,
                    help="artifact path (default docs/artifacts/"
                         "scenario_matrix_<platform>.json)")
    ap.add_argument("--obs-dir", default="scenario-postmortem",
                    help="gate-failure post-mortem bundle directory")
    ap.add_argument("--list", action="store_true",
                    help="list scenario ids and exit")
    args = ap.parse_args()

    if args.platform == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    from traffic_classifier_sdn_tpu.scenarios import (
        SCENARIOS,
        build,
        run_scenario,
    )

    if args.list:
        for name, builder in SCENARIOS.items():
            sc = builder("t1")
            print(f"{name}: {sc.title}")
        return

    names = args.scenario or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        sys.exit(f"unknown scenarios: {unknown} "
                 f"(known: {sorted(SCENARIOS)})")

    import jax

    platform = jax.devices()[0].platform
    cards = []
    for name in names:
        print(f"running {name} [{args.profile}] ...",
              file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        card = run_scenario(
            build(name, args.profile),
            native=args.native, obs_dir=args.obs_dir,
        )
        card["wall_s"] = round(time.perf_counter() - t0, 3)
        cards.append(card)
        verdict = "PASS" if card["passed"] else "FAIL"
        print(f"  {verdict} in {card['wall_s']}s "
              f"(dominant stage: "
              f"{card['latency'].get('dominant_stage')})",
              file=sys.stderr, flush=True)

    out = {
        "bench": "scenario_matrix",
        "platform": platform,
        "profile": args.profile,
        "scenarios": cards,
        "passed": all(c["passed"] for c in cards),
        "gate_failures": [
            {"scenario": c["scenario"], "gate": g["id"],
             "value": g["value"], "bound": g["bound"],
             "detail": g["detail"]}
            for c in cards
            for g in c["gates"] if not g["passed"]
        ],
    }
    line = json.dumps(out)
    print(line)
    path = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "artifacts", f"scenario_matrix_{platform}.json",
    )
    with open(path, "w") as f:
        f.write(json.dumps(out, indent=1) + "\n")
    print(f"wrote {path}", file=sys.stderr)
    if not out["passed"]:
        fails = ", ".join(
            f"{f['scenario']}:{f['gate']}" for f in out["gate_failures"]
        )
        sys.exit(f"scenario gates FAILED: {fails} "
                 f"(post-mortems under {args.obs_dir}/)")


if __name__ == "__main__":
    main()
