#!/usr/bin/env python
"""Live end-to-end latency budget through the REAL fan-in serve path —
the continuous counterpart of tools/bench_e2e.py's synthetic microbench
and the artifact ROADMAP items 1 and 5 both name.

bench_e2e.py decomposes one 16k slice offline (device compute vs
transfer vs control RTT); this bench drives the actual ingest tier —
per-source pump threads, emit-stamped batches, the bounded MPSC queue,
the Python batcher, the device scatter/predict/render chain — at the
monitor's 1 Hz cadence and reads the budget off the latency-provenance
plane itself (obs/latency.py): per-batch emit → queue-exit → parse →
scatter-dispatch → device-completion → render-visible stamps, folded
per render tick exactly as a production serve folds them.

Per source count (default 1/16/64, fixed aggregate 16384 records/tick)
it reports:

- the measured e2e_emit_to_render p50/p99 and queue/batch-wait p50s,
- the per-stage waterfall p50 budget (per-batch stage increments, so
  ``sum_of_stages_p50`` is a REAL reconciliation target — summing
  medians of correlated stages approximates, not tautologically
  equals, the e2e median; the artifact gate requires agreement within
  10%),
- serve-side tick processing p50 (the cadence-budget check
  bench_serve.py's fan-in sweep established).

The stamp-overhead A/B runs the same tier in lockstep (deterministic
batch assembly) with provenance on vs off over interleaved repeats:
the artifact records the tick-p50 delta as ``overhead_frac`` (the
acceptance bound is <= 3%) and verifies the rendered rows are
byte-identical — stamps must never leak into output.

Prints one JSON object; lands as docs/artifacts/e2e_budget_live_cpu
.json (CPU) or e2e_budget_live_tpu.json (tools/tpu_day.sh, platform
guard).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _percentile(values, q):
    import numpy as np

    return float(np.percentile(values, q)) if values else 0.0


def _run_level(args, n_sources: int, *, stamp: bool, lockstep: bool,
               ticks: int, interval: float, predict, params,
               collect_entries: bool, pace: float = 0.0):
    """One serve run through the real tier; returns timings, rendered
    rows, and (when collecting) the folded per-batch entries.

    ``pace`` is consumer-side cadence enforcement for lockstep runs:
    the serve loop sleeps out the remainder of each ``pace``-second
    window before granting the next tick's credits, so every tick
    carries a FULL source set (deterministic per-stage budgets) while
    the pumps still emit at the real 1 Hz rhythm — the configuration a
    healthy production serve runs in (processing p50 under the
    cadence, bench_serve.py's fan-in sweep)."""
    import jax

    from traffic_classifier_sdn_tpu.ingest import fanin
    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
    from traffic_classifier_sdn_tpu.obs.latency import LatencyProvenance
    from traffic_classifier_sdn_tpu.utils.metrics import Metrics

    conversations = args.records_per_tick // 2
    per = max(1, conversations // n_sources)
    specs = [
        fanin.SourceSpec(
            kind="synthetic", sid=sid, n_flows=per, seed=sid,
            mac_base=sid * per, max_ticks=ticks, interval=interval,
            lockstep=lockstep,
        )
        for sid in range(n_sources)
    ]
    tier = fanin.FanInIngest(specs, quarantine_s=5.0, stamp=stamp)
    eng = FlowStateEngine(capacity=args.capacity, native=False)
    m = Metrics()
    lat = LatencyProvenance(metrics=m) if stamp else None
    entries: list[dict] = []
    if lat is not None and collect_entries:
        def _tap(e, render_ts):
            entries.append({
                "sid": e.sid, "emit": e.emit, "deq": e.deq,
                "parse": e.parse, "scatter": e.scatter,
                "device": e.device, "render": render_ts,
            })
        lat.on_fold = _tap
    tick_s: list[float] = []
    rendered: list[list] = []
    n_records = 0
    gen = tier.ticks(tick_timeout=max(10.0, 4 * max(interval, 0.1)))
    try:
        for _ in range(ticks * 2):  # headroom: partial source sets
            batch = next(gen, None)
            if batch is None:
                break
            t0 = time.perf_counter()
            if lat is not None:
                lat.begin_tick(tier.pop_provenance())
            eng.mark_tick()
            n_records += eng.ingest(batch)
            if lat is not None:
                lat.mark_parse()
            eng.step()
            if lat is not None:
                lat.mark_scatter()
            for sid in tier.take_evictions():
                eng.evict_source(sid)
                if lat is not None:
                    lat.drop_source(sid)
            seal = lat.seal() if lat is not None else None
            labels = predict(params, eng.features())
            jax.block_until_ready(labels)
            if lat is not None:
                lat.mark_device(seal)
            ranked = eng.render_sample(labels, args.table_rows)
            sample = eng.slot_metadata(slots=[s for s, *_ in ranked])
            rows = [
                (s, *sample[s], c)
                for s, c, _fa, _ra in ranked if s in sample
            ]
            if lat is not None:
                lat.render_visible(seal)
            done = time.perf_counter()
            tick_s.append(done - t0)
            rendered.append(rows)
            if pace > 0:
                time.sleep(max(0.0, pace - (done - t0)))
    finally:
        gen.close()
    return {
        "metrics": m, "entries": entries, "tick_s": tick_s,
        "rendered": rendered, "n_records": n_records,
        "serve_ticks": len(tick_s),
    }


def _batch_increments(e):
    """One folded batch's per-stage durations (seconds); they
    telescope to its e2e exactly."""
    hop_in = e["deq"] if e["deq"] is not None else e["emit"]
    marks = [
        ("queue", e["emit"], hop_in),
        ("parse", hop_in, e["parse"]),
        ("scatter", e["parse"], e["scatter"]),
        ("device", e["scatter"], e["device"]),
        ("render", e["device"], e["render"]),
    ]
    return [
        (name, max(0.0, b - a))
        for name, a, b in marks
        if a is not None and b is not None
    ]


def _stage_budget(entries, n_sources: int):
    """Aggregate stage budget + tick-envelope reconciliation.

    Each batch's increments telescope to its e2e exactly, but pooled
    MEDIANS only nearly add up: across sources within one tick, an
    early-emitting source's longer queue wait trades against its
    neighbors' (the serve consumes one batch per source per tick), so
    the pooled stage medians come from different batches than the e2e
    median, and at single-digit tick counts the discrepancy is noise-
    sized. Reconciliation is therefore checked on the per-TICK
    envelope, whose internal structure is stable: per serve tick,
    anchor at the tick's EARLIEST emit, take queue as
    (last dequeue − earliest emit), and the shared parse/scatter/
    device/render boundaries for the rest — the five increments
    telescope to the tick's envelope e2e (the tick's directly-measured
    worst-batch latency). Sum of per-stage p50s across ticks vs p50 of
    the envelope e2e is the artifact's 10% gate; the pooled per-batch
    stage medians remain the headline budget (what an operator reads
    off /metrics)."""
    incs: dict[str, list[float]] = {}
    e2e = []
    stamped = [e for e in entries if e["emit"] is not None]
    for e in stamped:
        for name, dur in _batch_increments(e):
            incs.setdefault(name, []).append(dur)
        e2e.append(e["render"] - e["emit"])
    stage_p50 = {k: _percentile(v, 50) for k, v in incs.items()}

    # tick envelopes: fold order groups entries per render tick
    # (lockstep = one batch per source per tick)
    env: dict[str, list[float]] = {}
    env_e2e = []
    for i in range(0, len(stamped) - n_sources + 1, n_sources):
        tick = stamped[i:i + n_sources]
        emit0 = min(e["emit"] for e in tick)
        deq_last = max(
            (e["deq"] if e["deq"] is not None else e["emit"])
            for e in tick
        )
        bounds = [
            ("queue", emit0, deq_last),
            ("parse", deq_last, tick[0]["parse"]),
            ("scatter", tick[0]["parse"], tick[0]["scatter"]),
            ("device", tick[0]["scatter"], tick[0]["device"]),
            ("render", tick[0]["device"], tick[0]["render"]),
        ]
        if any(a is None or b is None for _, a, b in bounds):
            continue
        for name, a, b in bounds:
            env.setdefault(name, []).append(max(0.0, b - a))
        env_e2e.append(tick[0]["render"] - emit0)
    env_sum = sum(_percentile(v, 50) for v in env.values())
    env_p50 = _percentile(env_e2e, 50)
    ratio = env_sum / env_p50 if env_p50 else None
    return stage_p50, e2e, {
        "envelope_e2e_p50": env_p50,
        "envelope_sum_of_stage_p50": env_sum,
        "ratio": ratio,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sources", default="1,16,64",
                    help="comma-separated source counts to sweep")
    ap.add_argument("--records-per-tick", type=int, default=16384,
                    help="aggregate records per serve tick (batch 16k "
                    "— the acceptance shape; 2 records/conversation)")
    ap.add_argument("--ticks", type=int, default=12)
    ap.add_argument("--capacity", type=int, default=1 << 16)
    ap.add_argument("--interval", type=float, default=1.0,
                    help="per-source emission cadence (1 Hz default — "
                    "the reference monitor's poll rate)")
    ap.add_argument("--table-rows", type=int, default=64)
    ap.add_argument("--ab-sources", type=int, default=16,
                    help="source count for the stamp-overhead A/B")
    ap.add_argument("--ab-repeat", type=int, default=3,
                    help="interleaved on/off repeats for the A/B")
    ap.add_argument("--platform", choices=("cpu", "default"),
                    default="cpu")
    args = ap.parse_args()

    if args.platform == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from traffic_classifier_sdn_tpu.models import gnb, jit_serving_fn

    print("# initializing devices", file=sys.stderr, flush=True)
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)

    rng = np.random.RandomState(0)
    params = gnb.from_numpy({
        "theta": rng.gamma(2.0, 100.0, (6, 12)),
        "var": rng.gamma(2.0, 50.0, (6, 12)) + 1.0,
        "class_prior": np.full(6, 1 / 6),
    })
    predict = jit_serving_fn(gnb.predict)

    # one throwaway warm run so the sweep's first level doesn't carry
    # the jit compiles inside its waterfall
    _run_level(args, 1, stamp=True, lockstep=True, ticks=2,
               interval=0.0, predict=predict, params=params,
               collect_entries=False)

    levels = []
    for n_sources in [int(x) for x in args.sources.split(",")]:
        print(f"# level: {n_sources} sources", file=sys.stderr,
              flush=True)
        r = _run_level(
            args, n_sources, stamp=True, lockstep=True,
            ticks=args.ticks, interval=args.interval,
            predict=predict, params=params, collect_entries=True,
            pace=args.interval,
        )
        # steady state only: fold order groups entries by render tick
        # (lockstep = one batch per source per tick), so slicing off
        # the first n_sources entries drops exactly serve tick 0 —
        # pump-thread spin-up and first-credit phase jitter
        steady_entries = (
            r["entries"][n_sources:]
            if len(r["entries"]) > n_sources else r["entries"]
        )
        stage_p50, e2e, recon = _stage_budget(steady_entries, n_sources)
        e2e_p50 = _percentile(e2e, 50)
        total = sum(stage_p50.values())
        ratio = recon["ratio"]
        m = r["metrics"]
        qh = m.histograms.get("queue_wait_s")
        bh = m.histograms.get("batch_wait_s")
        steady = r["tick_s"][1:] or r["tick_s"]
        level = {
            "sources": n_sources,
            "flows_per_source": max(
                1, (args.records_per_tick // 2) // n_sources
            ),
            "records_ingested": r["n_records"],
            "serve_ticks": r["serve_ticks"],
            "batches_folded": len(r["entries"]),
            "e2e_p50_ms": round(e2e_p50 * 1e3, 3),
            "e2e_p99_ms": round(_percentile(e2e, 99) * 1e3, 3),
            "queue_wait_p50_ms": round(
                (qh.percentile(50) if qh is not None else 0.0) * 1e3, 3
            ),
            "batch_wait_p50_ms": round(
                (bh.percentile(50) if bh is not None else 0.0) * 1e3, 3
            ),
            "stage_p50_ms": {
                k: round(v * 1e3, 3) for k, v in stage_p50.items()
            },
            "sum_of_stages_p50_ms": round(total * 1e3, 3),
            # tick-envelope reconciliation (see _stage_budget): the
            # 10% acceptance gate compares the sum of per-stage p50s
            # against the directly-measured envelope e2e p50
            "envelope_e2e_p50_ms": round(
                recon["envelope_e2e_p50"] * 1e3, 3
            ),
            "envelope_sum_of_stages_p50_ms": round(
                recon["envelope_sum_of_stage_p50"] * 1e3, 3
            ),
            "reconciliation_ratio": (
                round(ratio, 4) if ratio is not None else None
            ),
            "within_10pct": (
                ratio is not None and abs(ratio - 1.0) <= 0.10
            ),
            "tick_processing_p50_ms": round(
                _percentile(steady, 50) * 1e3, 2
            ),
        }
        levels.append(level)
        print(
            f"#   e2e_p50={level['e2e_p50_ms']} ms "
            f"sum_of_stages={level['sum_of_stages_p50_ms']} ms "
            f"ratio={level['reconciliation_ratio']}",
            file=sys.stderr, flush=True,
        )

    # --- stamp overhead A/B: lockstep (deterministic batch assembly),
    # interleaved repeats, identical payload streams both arms --------
    print(f"# stamp A/B at {args.ab_sources} sources",
          file=sys.stderr, flush=True)
    on_p50s, off_p50s = [], []
    on_rows = off_rows = None
    for _ in range(args.ab_repeat):
        for stamp in (True, False):
            r = _run_level(
                args, args.ab_sources, stamp=stamp, lockstep=True,
                ticks=args.ticks, interval=0.0, predict=predict,
                params=params, collect_entries=False,
            )
            steady = r["tick_s"][1:] or r["tick_s"]
            (on_p50s if stamp else off_p50s).append(
                _percentile(steady, 50)
            )
            if stamp:
                on_rows = r["rendered"]
            else:
                off_rows = r["rendered"]
    tick_on = float(np.median(on_p50s))
    tick_off = float(np.median(off_p50s))
    overhead = (tick_on - tick_off) / tick_off if tick_off else None

    # Direct stamping cost: time exactly what the pump's _deliver adds
    # per batch — one clock read + the lead-record stamp (fan-in
    # batches share one emit moment; ingest/fanin.py). The wall A/B
    # above validates there is no hidden systematic cost but carries
    # the shared host's scheduler noise, so the 3% acceptance bound is
    # pinned on the direct measure against the measured tick p50 — a
    # larger wall-A/B delta would be noise, not stamping.
    from traffic_classifier_sdn_tpu.ingest.protocol import stamp_records
    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows

    syn = SyntheticFlows(n_flows=args.records_per_tick // 2, seed=3)
    stamp_times = []
    for _ in range(5):
        batch = syn.tick()  # fresh records: stamp_records is write-once
        t0 = time.perf_counter()
        stamp_records(batch[:1], time.perf_counter())
        stamp_times.append(time.perf_counter() - t0)
    stamp_s = float(np.median(stamp_times))
    stamp_frac = stamp_s / tick_off if tick_off else None

    ab = {
        "sources": args.ab_sources,
        "ticks": args.ticks,
        "repeats": args.ab_repeat,
        "tick_p50_on_ms": round(tick_on * 1e3, 3),
        "tick_p50_off_ms": round(tick_off * 1e3, 3),
        "overhead_frac_ab": (
            round(overhead, 4) if overhead is not None else None
        ),
        "stamp_cost_ms_per_batch": round(stamp_s * 1e3, 4),
        "stamp_cost_frac_of_tick_p50": (
            round(stamp_frac, 4) if stamp_frac is not None else None
        ),
        "within_3pct": stamp_frac is not None and stamp_frac <= 0.03,
        "render_identical": on_rows == off_rows,
    }

    out = {
        "metric": "e2e_budget_live",
        "platform": jax.devices()[0].platform,
        "records_per_tick": args.records_per_tick,
        "cadence_s": args.interval,
        "ticks_per_source": args.ticks,
        "capacity": args.capacity,
        "predict_model": "gnb",
        "native_ingest": False,
        "levels": levels,
        "stamp_overhead_ab": ab,
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
