#!/usr/bin/env python
"""State-sharded KNN scaling evidence on a single-host rig (VERDICT r3
item 4).

This rig has ONE physical CPU core (``nproc`` = 1) and one real TPU chip,
so no configuration that exists here can demonstrate a wall-clock sharded
speedup: the 8 "devices" of the virtual CPU mesh — and any 8 processes —
multiplex the same core, so total wall time tracks TOTAL work, not
per-shard work. What CAN be measured honestly, and what this tool
records:

1. **Zero-overhead strong scaling at fixed total work.** With a corpus
   large enough that distance FLOPs dominate (2^20 rows — the reference's
   4448-row corpus is ~250x too small, which is why round 3's race was
   flat and meaningless), total wall time on the shared core should stay
   FLAT as shards go 1 -> 8 while per-device work drops 8x. Flat means
   the sharded path adds no work: no collective whose operand scales with
   S, no padding blow-up, no re-replication. On real chips (independent
   compute per shard) the same program then runs ~N x faster.

2. **Per-device compiled cost from XLA itself.** ``cost_analysis()`` on
   the compiled SPMD program reports the per-device FLOPs: it must scale
   ~1/N (each shard computes distances to S/N corpus rows), while the
   merge traffic stays O(N * k) per query — independent of S
   (parallel/knn_sharded.py module docstring).

3. **Argmax parity at every shard count** vs the single-device
   ``models/knn.predict`` oracle.

Chip-side expectation from these numbers: per-chip distance matmul time
scales with S/N; the all_gather merge moves N*k*(4+4) bytes per query
row (k=5: 320 B at N=8) over ICI at ~100 GB/s — sub-microsecond per
row, thousands of times smaller than the per-shard matmul at S = 2^20.
Hence >= ~7x effective throughput at 8 shards once per-shard work is on
independent chips, with the exact merge already proven bit-identical by
tests/test_parallel.py.

Prints ONE JSON line -> docs/artifacts/sharded_scaling_multidevice.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", type=int, default=1 << 20)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.models import knn
    from traffic_classifier_sdn_tpu.parallel import (
        knn_sharded,
        mesh as meshlib,
    )

    rng = np.random.RandomState(0)
    S, F, k, C = args.corpus, 12, 5, 6
    d = {
        "fit_X": np.abs(rng.gamma(1.5, 200.0, (S, F))).astype(np.float64),
        "y": rng.randint(0, C, S),
        "n_neighbors": k,
        "classes": np.arange(C),
    }
    X = jnp.asarray(
        np.abs(rng.gamma(1.5, 200.0, (args.batch, F))), jnp.float32
    )

    # single-device oracle for parity
    p0 = knn.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(jax.jit(knn.predict)(p0, X[:512]))

    devices = jax.devices()
    out: dict = {
        "metric": "sharded_knn_scaling_fixed_total_work",
        "corpus_rows": S,
        "batch": args.batch,
        "platform": "cpu_x8_virtual_one_core",
        "host_cores": os.cpu_count(),
        "results": {},
    }
    base_ms = None
    for n_state in (1, 2, 4, 8):
        mesh = meshlib.make_mesh(
            n_data=1, n_state=n_state, devices=devices[:n_state]
        )
        kr = knn_sharded.pad_corpus(dict(d), n_state)
        kp = knn.from_numpy(kr, dtype=jnp.float32)
        fn = knn_sharded.sharded_predict(
            mesh, kp, pad_mask=kr.get("pad_mask")
        )
        got = np.asarray(fn(X[:512]))
        parity = float((got == want).mean() * 100.0)

        jfn = jax.jit(fn)
        compiled = jfn.lower(X).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops_dev = float(ca.get("flops", float("nan")))

        jax.block_until_ready(jfn(X))  # warm
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(jfn(X))
            times.append(time.perf_counter() - t0)
        ms = float(np.median(times)) * 1e3
        if n_state == 1:
            base_ms = ms
        out["results"][f"state_{n_state}"] = {
            "wall_ms_total_work_fixed": round(ms, 1),
            "wall_vs_state1": round(ms / base_ms, 3),
            "per_device_flops": flops_dev,
            "parity_pct_vs_single": parity,
            "merge_bytes_per_query_row": n_state * k * 8,
        }
        print(f"# state_{n_state}: {ms:.1f} ms, per-dev flops "
              f"{flops_dev:.3g}, parity {parity}", file=sys.stderr,
              flush=True)

    r1 = out["results"]["state_1"]
    r8 = out["results"]["state_8"]
    out["per_device_flops_ratio_8v1"] = round(
        r8["per_device_flops"] / r1["per_device_flops"], 4
    ) if r1["per_device_flops"] else None
    out["analysis"] = (
        "single-core host: all virtual devices multiplex one core, so "
        "wall time tracks TOTAL work and a sharded wall-clock speedup is "
        "structurally unobservable here; the scaling evidence is (a) "
        "flat wall time 1->8 shards at fixed total work (sharding adds "
        "no work), (b) per-device compiled FLOPs ~1/N from XLA cost "
        "analysis, (c) O(N*k) merge bytes independent of corpus size. "
        "On N independent chips the same SPMD program's per-chip time "
        "is the state_N per-device work plus a ~microsecond ICI merge "
        "-> ~Nx throughput at equal corpus, ~7x+ at N=8."
    )
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
