// Sanitizer driver for native/flow_engine.cpp: hammer tc_engine_feed and
// tc_engine_flush from DIFFERENT threads, with a third thread polling the
// bookkeeping surface — the exact concurrency the engine's mutex contract
// promises (ctypes releases the GIL during foreign calls, so a Python
// reader thread feeding while the classify loop flushes is real C++-level
// concurrency). Built twice by tools/native_sanitize.sh: once with
// -fsanitize=undefined (UB under single- and multi-thread load) and once
// with -fsanitize=thread (data races in the feed/flush interleaving).
//
// Also self-checks semantics so a silent lock-ordering bug can't pass as
// "no race": every parsed record must come back out of flush exactly once
// (capacity exceeds the synthetic flow population, so nothing is dropped),
// and chunks are deliberately split mid-line so the tail-carry seam runs
// concurrently with flush too.
//
// Compile: g++ <sanitizer flags> -std=c++17 -pthread \
//     tools/sanitize_feed_flush.cpp traffic_classifier_sdn_tpu/native/flow_engine.cpp

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* tc_engine_create(uint32_t capacity, uint32_t max_batch);
void tc_engine_destroy(void* h);
uint64_t tc_engine_feed(void* h, const char* buf, uint64_t len);
uint64_t tc_engine_pending(void* h);
uint32_t tc_engine_flush(void* h, int32_t* slot, int32_t* time,
                         uint32_t* pkts_lo, float* pkts_f,
                         uint32_t* bytes_lo, float* bytes_f,
                         uint8_t* is_fwd, uint8_t* is_create);
int tc_engine_last_flush_conflict(void* h);
uint64_t tc_engine_dropped(void* h);
uint64_t tc_engine_parsed(void* h);
int32_t tc_engine_last_time(void* h);
uint32_t tc_engine_num_flows(void* h);
int tc_engine_slot_meta(void* h, uint32_t slot, char* src_out,
                        char* dst_out, uint32_t cap);
uint32_t tc_engine_export_index(void* h, uint64_t* fp_out,
                                uint8_t* used_out);
}

namespace {

constexpr uint32_t kCap = 4096;
constexpr uint32_t kMaxBatch = 256;
constexpr int kChunks = 400;
constexpr int kLinesPerChunk = 200;
constexpr int kFlows = 1000;  // < kCap: nothing is ever dropped

}  // namespace

int main() {
  void* eng = tc_engine_create(kCap, kMaxBatch);
  if (eng == nullptr) {
    std::fprintf(stderr, "tc_engine_create failed\n");
    return 1;
  }
  std::atomic<bool> done{false};

  std::thread feeder([&] {
    uint64_t counter = 1;
    for (int c = 0; c < kChunks; ++c) {
      std::string chunk;
      for (int l = 0; l < kLinesPerChunk; ++l) {
        int flow = (c * kLinesPerChunk + l) % kFlows;
        char line[256];
        int n = std::snprintf(
            line, sizeof line,
            "data\t%d\tdp%d\t1\taa:bb:%02x:%02x\tcc:dd:%02x:%02x\t2"
            "\t%llu\t%llu\n",
            c + 1, flow % 7, flow & 0xff, (flow >> 8) & 0xff,
            flow & 0xff, (flow >> 8) & 0xff,
            static_cast<unsigned long long>(counter),
            static_cast<unsigned long long>(counter * 64));
        chunk.append(line, static_cast<size_t>(n));
        ++counter;
      }
      // split mid-line: the partial-line tail carry must be safe
      // against a concurrent flush as well
      size_t half = chunk.size() / 2;
      tc_engine_feed(eng, chunk.data(), half);
      tc_engine_feed(eng, chunk.data() + half, chunk.size() - half);
    }
    done.store(true);
  });

  std::atomic<uint64_t> rows{0};
  std::thread flusher([&] {
    std::vector<int32_t> slot(kMaxBatch), time_(kMaxBatch);
    std::vector<uint32_t> pkts_lo(kMaxBatch), bytes_lo(kMaxBatch);
    std::vector<float> pkts_f(kMaxBatch), bytes_f(kMaxBatch);
    std::vector<uint8_t> is_fwd(kMaxBatch), is_create(kMaxBatch);
    while (true) {
      uint32_t n = tc_engine_flush(
          eng, slot.data(), time_.data(), pkts_lo.data(), pkts_f.data(),
          bytes_lo.data(), bytes_f.data(), is_fwd.data(),
          is_create.data());
      tc_engine_last_flush_conflict(eng);
      if (n == 0) {
        if (done.load() && tc_engine_pending(eng) == 0) break;
        std::this_thread::yield();
        continue;
      }
      rows += n;
    }
  });

  std::thread poller([&] {
    char src[64], dst[64];
    std::vector<uint64_t> fp(kCap);
    std::vector<uint8_t> used(kCap);
    while (!done.load()) {
      tc_engine_parsed(eng);
      tc_engine_dropped(eng);
      tc_engine_num_flows(eng);
      tc_engine_last_time(eng);
      tc_engine_pending(eng);
      tc_engine_slot_meta(eng, 0, src, dst, sizeof src);
      tc_engine_export_index(eng, fp.data(), used.data());
      std::this_thread::yield();
    }
  });

  feeder.join();
  flusher.join();
  poller.join();

  const uint64_t expect =
      static_cast<uint64_t>(kChunks) * kLinesPerChunk;
  uint64_t parsed = tc_engine_parsed(eng);
  uint64_t dropped = tc_engine_dropped(eng);
  int rc = 0;
  if (parsed != expect || dropped != 0 || rows.load() != expect) {
    std::fprintf(stderr,
                 "parity failure: parsed=%llu dropped=%llu rows=%llu "
                 "expected=%llu\n",
                 static_cast<unsigned long long>(parsed),
                 static_cast<unsigned long long>(dropped),
                 static_cast<unsigned long long>(rows.load()),
                 static_cast<unsigned long long>(expect));
    rc = 1;
  }
  tc_engine_destroy(eng);
  if (rc == 0) {
    std::printf("feed/flush driver: %llu records in, %llu rows out, "
                "0 dropped\n",
                static_cast<unsigned long long>(parsed),
                static_cast<unsigned long long>(rows.load()));
  }
  return rc;
}
