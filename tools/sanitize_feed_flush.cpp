// Sanitizer driver for native/flow_engine.cpp: hammer tc_engine_feed and
// tc_engine_flush from DIFFERENT threads, with a third thread polling the
// bookkeeping surface — the exact concurrency the engine's mutex contract
// promises (ctypes releases the GIL during foreign calls, so a Python
// reader thread feeding while the classify loop flushes is real C++-level
// concurrency). Built twice by tools/native_sanitize.sh: once with
// -fsanitize=undefined (UB under single- and multi-thread load) and once
// with -fsanitize=thread (data races in the feed/flush interleaving).
//
// Also self-checks semantics so a silent lock-ordering bug can't pass as
// "no race": every parsed record must come back out of flush exactly once
// (capacity exceeds the synthetic flow population, so nothing is dropped),
// and chunks are deliberately split mid-line so the tail-carry seam runs
// concurrently with flush too.
//
// Compile: g++ <sanitizer flags> -std=c++17 -pthread \
//     tools/sanitize_feed_flush.cpp traffic_classifier_sdn_tpu/native/flow_engine.cpp

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* tc_engine_create(uint32_t capacity, uint32_t max_batch);
void tc_engine_destroy(void* h);
uint64_t tc_engine_feed(void* h, const char* buf, uint64_t len);
uint64_t tck_feed_lines(void* h, const char* buf, uint64_t len,
                        uint32_t source);
uint64_t tc_engine_pending(void* h);
uint32_t tc_engine_flush(void* h, int32_t* slot, int32_t* time,
                         uint32_t* pkts_lo, float* pkts_f,
                         uint32_t* bytes_lo, float* bytes_f,
                         uint8_t* is_fwd, uint8_t* is_create);
uint64_t tck_flush_wire(void* h, uint32_t* wire, const uint32_t* buckets,
                        uint32_t n_buckets, uint32_t pad_slot);
uint32_t tck_slots_for_source(void* h, uint32_t source, uint32_t* out);
void tck_reset_tail(void* h, uint32_t source);
uint64_t tck_parse_errors_total(void* h);
uint64_t tck_parse_errors(void* h, uint32_t source);
uint64_t tck_source_parsed(void* h, uint32_t source);
void tc_engine_release_slots(void* h, const uint32_t* slots, uint32_t n);
int tc_engine_last_flush_conflict(void* h);
uint64_t tc_engine_dropped(void* h);
uint64_t tc_engine_parsed(void* h);
int32_t tc_engine_last_time(void* h);
uint32_t tc_engine_num_flows(void* h);
int tc_engine_slot_meta(void* h, uint32_t slot, char* src_out,
                        char* dst_out, uint32_t cap);
uint32_t tc_engine_export_index(void* h, uint64_t* fp_out,
                                uint8_t* used_out);
}

namespace {

constexpr uint32_t kCap = 4096;
constexpr uint32_t kMaxBatch = 256;
constexpr int kChunks = 400;
constexpr int kLinesPerChunk = 200;
constexpr int kFlows = 1000;  // < kCap: nothing is ever dropped

// multi-source phase: N feeder threads, one per namespace, all emitting
// the SAME flow-tuple population — overlapping tuples, disjoint
// namespaces (the fan-in contract the source-folded fingerprint makes)
constexpr uint32_t kSources = 4;
constexpr int kChunks2 = 150;
constexpr int kLines2 = 120;
constexpr int kFlows2 = 500;        // 4 * 500 < kCap: nothing dropped
constexpr int kBadEvery = 40;       // deliberate malformed line cadence

int run_multisource() {
  void* eng = tc_engine_create(kCap, kMaxBatch);
  if (eng == nullptr) {
    std::fprintf(stderr, "tc_engine_create (multisource) failed\n");
    return 1;
  }
  std::atomic<uint32_t> feeders_done{0};
  std::vector<uint64_t> valid_fed(kSources + 1, 0);
  std::vector<uint64_t> bad_fed(kSources + 1, 0);
  std::vector<std::thread> feeders;
  feeders.reserve(kSources);
  for (uint32_t sid = 1; sid <= kSources; ++sid) {
    feeders.emplace_back([&, sid] {
      uint64_t counter = 1;
      for (int c = 0; c < kChunks2; ++c) {
        std::string chunk;
        for (int l = 0; l < kLines2; ++l) {
          int flow = (c * kLines2 + l) % kFlows2;
          char line[256];
          if ((c * kLines2 + l) % kBadEvery == kBadEvery - 1) {
            // malformed telemetry: 'data' prefix, garbage body — must
            // be counted against THIS source and skipped, never crash
            int n = std::snprintf(line, sizeof line,
                                  "data\t%d\tbroken\n", c + 1);
            chunk.append(line, static_cast<size_t>(n));
            bad_fed[sid]++;
            continue;
          }
          int n = std::snprintf(
              line, sizeof line,
              "data\t%d\tdp%d\t1\taa:bb:%02x:%02x\tcc:dd:%02x:%02x\t2"
              "\t%llu\t%llu\n",
              c + 1, flow % 7, flow & 0xff, (flow >> 8) & 0xff,
              flow & 0xff, (flow >> 8) & 0xff,
              static_cast<unsigned long long>(counter),
              static_cast<unsigned long long>(counter * 64));
          chunk.append(line, static_cast<size_t>(n));
          valid_fed[sid]++;
          ++counter;
        }
        // split mid-line: each source's PRIVATE tail carry runs
        // concurrently with every other source's feed and the flush
        size_t half = chunk.size() / 2;
        tck_feed_lines(eng, chunk.data(), half, sid);
        tck_feed_lines(eng, chunk.data() + half, chunk.size() - half,
                       sid);
      }
      feeders_done.fetch_add(1);
    });
  }

  std::atomic<uint64_t> rows{0};
  std::thread flusher([&] {
    std::vector<uint32_t> wire(static_cast<size_t>(kMaxBatch) * 6);
    const uint32_t buckets[3] = {64, kMaxBatch / 2, kMaxBatch};
    while (true) {
      uint64_t r = tck_flush_wire(eng, wire.data(), buckets, 3, kCap);
      tc_engine_last_flush_conflict(eng);
      if (r == 0) {
        if (feeders_done.load() == kSources &&
            tc_engine_pending(eng) == 0)
          break;
        std::this_thread::yield();
        continue;
      }
      uint32_t padded = static_cast<uint32_t>(r & 0xFFFFFFFFu);
      uint32_t width = static_cast<uint32_t>(r >> 32);
      for (uint32_t i = 0; i < padded; ++i) {
        if ((wire[static_cast<size_t>(i) * width] & 0x3FFFFFFFu) != kCap)
          rows += 1;
      }
    }
  });

  std::thread poller([&] {
    std::vector<uint32_t> slots(kCap);
    char src[64], dst[64];
    while (feeders_done.load() != kSources) {
      tck_parse_errors_total(eng);
      for (uint32_t sid = 1; sid <= kSources; ++sid) {
        tck_parse_errors(eng, sid);
        tck_source_parsed(eng, sid);
        tck_slots_for_source(eng, sid, slots.data());
      }
      tc_engine_num_flows(eng);
      tc_engine_slot_meta(eng, 0, src, dst, sizeof src);
      std::this_thread::yield();
    }
  });

  for (auto& f : feeders) f.join();
  flusher.join();
  poller.join();

  int rc = 0;
  uint64_t total_valid = 0;
  for (uint32_t sid = 1; sid <= kSources; ++sid) {
    total_valid += valid_fed[sid];
    if (tck_source_parsed(eng, sid) != valid_fed[sid] ||
        tck_parse_errors(eng, sid) != bad_fed[sid]) {
      std::fprintf(stderr,
                   "source %u accounting: parsed=%llu/%llu "
                   "errors=%llu/%llu\n",
                   sid,
                   static_cast<unsigned long long>(
                       tck_source_parsed(eng, sid)),
                   static_cast<unsigned long long>(valid_fed[sid]),
                   static_cast<unsigned long long>(
                       tck_parse_errors(eng, sid)),
                   static_cast<unsigned long long>(bad_fed[sid]));
      rc = 1;
    }
  }
  if (tc_engine_parsed(eng) != total_valid ||
      tc_engine_dropped(eng) != 0 || rows.load() != total_valid) {
    std::fprintf(stderr,
                 "multisource parity: parsed=%llu dropped=%llu "
                 "rows=%llu expected=%llu\n",
                 static_cast<unsigned long long>(tc_engine_parsed(eng)),
                 static_cast<unsigned long long>(tc_engine_dropped(eng)),
                 static_cast<unsigned long long>(rows.load()),
                 static_cast<unsigned long long>(total_valid));
    rc = 1;
  }
  // namespace eviction: source 2's slots, exactly, then slot reuse.
  // Leave a dangling partial line first and reset it the way
  // FlowStateEngine.evict_source does — the dead incarnation's
  // fragment must not survive the eviction to splice a later chunk.
  const char frag[] = "data\t9\t1\t1\thalf";
  tck_feed_lines(eng, frag, sizeof(frag) - 1, 2);
  std::vector<uint32_t> slots(kCap);
  uint32_t n2 = tck_slots_for_source(eng, 2, slots.data());
  uint32_t before = tc_engine_num_flows(eng);
  tck_reset_tail(eng, 2);
  tc_engine_release_slots(eng, slots.data(), n2);
  if (n2 != kFlows2 || tc_engine_num_flows(eng) != before - n2 ||
      tck_slots_for_source(eng, 2, slots.data()) != 0) {
    std::fprintf(stderr, "namespace eviction: n2=%u before=%u after=%u\n",
                 n2, before, tc_engine_num_flows(eng));
    rc = 1;
  }
  tc_engine_destroy(eng);
  if (rc == 0) {
    std::printf("multisource driver: %llu records across %u namespaces, "
                "%llu malformed counted, eviction exact\n",
                static_cast<unsigned long long>(total_valid), kSources,
                static_cast<unsigned long long>(
                    bad_fed[1] + bad_fed[2] + bad_fed[3] + bad_fed[4]));
  }
  return rc;
}

}  // namespace

int main() {
  void* eng = tc_engine_create(kCap, kMaxBatch);
  if (eng == nullptr) {
    std::fprintf(stderr, "tc_engine_create failed\n");
    return 1;
  }
  std::atomic<bool> done{false};

  std::thread feeder([&] {
    uint64_t counter = 1;
    for (int c = 0; c < kChunks; ++c) {
      std::string chunk;
      for (int l = 0; l < kLinesPerChunk; ++l) {
        int flow = (c * kLinesPerChunk + l) % kFlows;
        char line[256];
        int n = std::snprintf(
            line, sizeof line,
            "data\t%d\tdp%d\t1\taa:bb:%02x:%02x\tcc:dd:%02x:%02x\t2"
            "\t%llu\t%llu\n",
            c + 1, flow % 7, flow & 0xff, (flow >> 8) & 0xff,
            flow & 0xff, (flow >> 8) & 0xff,
            static_cast<unsigned long long>(counter),
            static_cast<unsigned long long>(counter * 64));
        chunk.append(line, static_cast<size_t>(n));
        ++counter;
      }
      // split mid-line: the partial-line tail carry must be safe
      // against a concurrent flush as well
      size_t half = chunk.size() / 2;
      tc_engine_feed(eng, chunk.data(), half);
      tc_engine_feed(eng, chunk.data() + half, chunk.size() - half);
    }
    done.store(true);
  });

  std::atomic<uint64_t> rows{0};
  std::thread flusher([&] {
    std::vector<int32_t> slot(kMaxBatch), time_(kMaxBatch);
    std::vector<uint32_t> pkts_lo(kMaxBatch), bytes_lo(kMaxBatch);
    std::vector<float> pkts_f(kMaxBatch), bytes_f(kMaxBatch);
    std::vector<uint8_t> is_fwd(kMaxBatch), is_create(kMaxBatch);
    while (true) {
      uint32_t n = tc_engine_flush(
          eng, slot.data(), time_.data(), pkts_lo.data(), pkts_f.data(),
          bytes_lo.data(), bytes_f.data(), is_fwd.data(),
          is_create.data());
      tc_engine_last_flush_conflict(eng);
      if (n == 0) {
        if (done.load() && tc_engine_pending(eng) == 0) break;
        std::this_thread::yield();
        continue;
      }
      rows += n;
    }
  });

  std::thread poller([&] {
    char src[64], dst[64];
    std::vector<uint64_t> fp(kCap);
    std::vector<uint8_t> used(kCap);
    while (!done.load()) {
      tc_engine_parsed(eng);
      tc_engine_dropped(eng);
      tc_engine_num_flows(eng);
      tc_engine_last_time(eng);
      tc_engine_pending(eng);
      tc_engine_slot_meta(eng, 0, src, dst, sizeof src);
      tc_engine_export_index(eng, fp.data(), used.data());
      std::this_thread::yield();
    }
  });

  feeder.join();
  flusher.join();
  poller.join();

  const uint64_t expect =
      static_cast<uint64_t>(kChunks) * kLinesPerChunk;
  uint64_t parsed = tc_engine_parsed(eng);
  uint64_t dropped = tc_engine_dropped(eng);
  int rc = 0;
  if (parsed != expect || dropped != 0 || rows.load() != expect) {
    std::fprintf(stderr,
                 "parity failure: parsed=%llu dropped=%llu rows=%llu "
                 "expected=%llu\n",
                 static_cast<unsigned long long>(parsed),
                 static_cast<unsigned long long>(dropped),
                 static_cast<unsigned long long>(rows.load()),
                 static_cast<unsigned long long>(expect));
    rc = 1;
  }
  tc_engine_destroy(eng);
  if (rc == 0) {
    std::printf("feed/flush driver: %llu records in, %llu rows out, "
                "0 dropped\n",
                static_cast<unsigned long long>(parsed),
                static_cast<unsigned long long>(rows.load()));
  }
  if (rc != 0) return rc;
  // phase 2: concurrent multi-source tck_feed_lines over overlapping
  // flow tuples in disjoint namespaces, flushed through the packed
  // wire path, with live per-source accounting polls and a namespace
  // eviction at the end
  return run_multisource();
}
