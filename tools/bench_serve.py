#!/usr/bin/env python
"""Serving-loop scale bench: drive the FULL ingest→device-table→predict→
render→evict spine at 2²⁰ concurrent flows (the BASELINE.json north star)
and print one JSON line of per-stage timings.

This measures what VERDICT r1 item 4 said was unproven: that the host side
of the serving loop stays O(batch)/O(limit) — not O(capacity) Python — at
1M flows. The reference's equivalent loop is per-flow Python dict + predict
(traffic_classifier.py:99-118,144-171) and its `flows` dict only ever held
dozens of entries.

Stages per tick:
  ingest   — raw wire bytes → C++ engine (or Python fallback) routing
  step     — one scatter of the padded update batch into the device table
  predict  — batched GNB over the whole (capacity, 12) feature matrix
  render   — sorted sample of --table-rows flows + footer (never O(N))
  evict    — device stale-mask + host release of idle slots

--pipeline {off,on,both} A/Bs the serial chain against the pipelined
serve loop (serving/pipeline.py: host poll/parse/scatter overlapped
with device predict/render through the bounded handoff). `both` runs
serial then pipelined over identical payloads and emits one
`serve_pipeline_ab` JSON object with per-mode `serve_flows_per_sec`,
the speedup, and the measured host/device `overlap_ratio`
(overlap_s / device_busy_s). --warmup AOT-compiles the serving
programs first (serving/warmup.py) — pass it for a clean A/B (the
modes share jit caches, so an un-warmed first mode pays every compile)
and to read `first_tick_ms` as the warm first-tick latency.

Usage: bench_serve.py [--capacity 1048576] [--ticks 5] [--no-native]
(CPU-safe: forces the host platform unless --platform default.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _build_model(args):
    """(predict, params, raw_fn) through the serving-path resolution."""
    import numpy as np

    from traffic_classifier_sdn_tpu.models import gnb, jit_serving_fn

    if args.model == "forest-synth":
        # a forest fit on synthetic class-shaped data at bench time —
        # the flagship-predict-cost stand-in for hosts without the
        # reference checkpoint tree; resolves through the same serving
        # path (honors TCSDN_FOREST_KERNEL, e.g. `native` for the C++
        # walk the 1.79 s serve_2m_cpu_native_forest baseline measured)
        from traffic_classifier_sdn_tpu.models import make_loaded_model
        from traffic_classifier_sdn_tpu.models.base import ClassList
        from traffic_classifier_sdn_tpu.train import forest as tforest

        rng = np.random.RandomState(1)
        n_cls = 6
        theta = rng.gamma(2.0, 100.0, (n_cls, 12))
        ytr = rng.randint(0, n_cls, 8192)
        Xtr = (
            rng.gamma(2.0, 1.0, (8192, 12)) * theta[ytr]
        ).astype(np.float32)
        params = tforest.fit(
            Xtr, ytr, n_classes=n_cls, n_trees=args.synth_trees
        )
        m = make_loaded_model(
            "forest", params,
            ClassList(tuple(f"class{i}" for i in range(n_cls))),
        )
        raw_predict, sp = m.serving_path()
        predict = jit_serving_fn(raw_predict)
        if getattr(raw_predict, "host_native", False) and args.shards >= 1:
            sys.exit("host-native kernels are single-device host "
                     "serving; use a device kernel with --shards")
        return predict, sp, raw_predict
    if args.model == "knn-synth":
        # a KNN corpus fit on flow-shaped synthetic data at bench time —
        # the reference-pickle-free KNN serving bench (mirror of
        # forest-synth above) so the serving-regime KNN cost is
        # A/B-able in CI containers; resolves through the same serving
        # path (honors --knn-topk / TCSDN_KNN_TOPK — sort, screened,
        # native, ivf all race on identical corpora). The corpus is
        # conversation-structured (cumulative snapshot rows per flow),
        # the geometry the pruned native tier and the IVF quantizer
        # actually see in serving.
        from traffic_classifier_sdn_tpu.models import make_loaded_model
        from traffic_classifier_sdn_tpu.models.base import ClassList
        from traffic_classifier_sdn_tpu.train import knn as tknn

        rng = np.random.RandomState(1)
        n_cls = 6
        S = args.synth_corpus
        theta = rng.gamma(2.0, 100.0, (n_cls, 12))
        conv = -(-S // 8)  # ceil: rows cover S for ANY size
        ccls = rng.randint(0, n_cls, conv)
        base = rng.gamma(2.0, 1.0, (conv, 12)) * theta[ccls]
        rows, ys = [], []
        for i in range(conv):
            t = np.sort(rng.uniform(0.1, 1.0, 8))[:, None]
            rows.append(np.abs(
                base[i] * t * (1 + rng.normal(0, 0.02, (8, 12)))
            ))
            ys += [int(ccls[i])] * 8
        Xtr = np.concatenate(rows)[:S].astype(np.float32)
        ytr = np.asarray(ys[:S])
        params = tknn.fit(Xtr, ytr, n_neighbors=5, n_classes=n_cls)
        m = make_loaded_model(
            "knn", params,
            ClassList(tuple(f"class{i}" for i in range(n_cls))),
        )
        raw_predict, sp = m.serving_path()
        predict = jit_serving_fn(raw_predict)
        if getattr(raw_predict, "host_native", False) and args.shards >= 1:
            sys.exit("host-native kernels are single-device host "
                     "serving; use a device kernel with --shards")
        return predict, sp, raw_predict
    if args.model in ("forest", "knn"):
        # the reference checkpoint through the serving-path resolution —
        # honors TCSDN_FOREST_KERNEL / TCSDN_KNN_TOPK, so the chip day
        # can A/B the serve tick with whichever raced kernel won
        # (models/__init__.py)
        from traffic_classifier_sdn_tpu.models import load_reference_model

        models_dir = os.environ.get(
            "TCSDN_MODELS_DIR", "/root/reference/models"
        )
        sub, ck = {
            "forest": ("Randomforest", "RandomForestClassifier"),
            "knn": ("knearest", "KNeighbors"),
        }[args.model]
        m = load_reference_model(sub, f"{models_dir}/{ck}")
        raw_predict, params = m.serving_path()
        predict = jit_serving_fn(raw_predict)
        if getattr(raw_predict, "host_native", False) and args.shards >= 1:
            sys.exit("host-native kernels (TCSDN_FOREST_KERNEL="
                     "native, TCSDN_KNN_TOPK=native) are "
                     "single-device host serving; use a device "
                     "kernel with --shards")
        return predict, params, raw_predict
    # 6-class GNB params (synthetic moments — the model family is the
    # cheapest full-table predict; the forest/SVC cost is bench.py's job)
    rng = np.random.RandomState(0)
    params = gnb.from_numpy(
        {
            "theta": rng.gamma(2.0, 100.0, (6, 12)),
            "var": rng.gamma(2.0, 50.0, (6, 12)) + 1.0,
            "class_prior": np.full(6, 1 / 6),
        }
    )
    return jit_serving_fn(gnb.predict), params, gnb.predict


def _make_engine(args, native, raw_fn, params, incremental=False):
    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine

    if args.shards >= 1:
        import jax

        from traffic_classifier_sdn_tpu.parallel import (
            mesh as meshlib,
            table_sharded as tsh,
        )

        # explicit sub-mesh over the leading devices (the region sweep
        # varies shard count under one forced device pool)
        return tsh.ShardedFlowEngine(
            meshlib.make_mesh(
                n_data=args.shards, n_state=1,
                devices=jax.devices()[: args.shards],
            ),
            args.capacity, predict_fn=raw_fn, params=params,
            table_rows=args.table_rows, native=native,
            incremental=incremental,
        )
    return FlowStateEngine(
        capacity=args.capacity, native=native, track_dirty=incremental
    )


def _run_serial(args, eng, predict, params, payloads, inc=None):
    """The serial chain — one tick fully synchronous, per-stage timed.
    ``inc`` (serving/incremental.IncrementalLabels) swaps the
    full-table predict for the dirty-set/label-cache path; the
    rendered rows per tick ride back in the result for A/B identity
    checks."""
    import numpy as np

    import jax

    timings = {k: [] for k in ("ingest", "step", "predict", "render",
                               "evict", "tick")}
    rendered_rows = []
    n_parsed = 0
    t_wall0 = time.perf_counter()
    for ti, payload in enumerate(payloads):
        eng.mark_tick()
        t0 = time.perf_counter()
        n_parsed += eng.ingest_bytes(payload)
        t1 = time.perf_counter()
        eng.step()
        if args.shards >= 1:
            # attribution honesty: apply dispatches are async; without a
            # sync the whole scatter cost lands in whichever later stage
            # first fetches device data (observed: 8.6 s misattributed to
            # "predict" at 2²³). CPU-platform block_until_ready is a real
            # wait (only the tunnel's lies — this path is CPU-mesh only).
            jax.block_until_ready(eng.tables)
        t2 = time.perf_counter()
        if args.shards >= 1:
            # the sharded spine's whole read side (per-shard predict +
            # scored render candidates + stale bits) is ONE dispatch; the
            # "predict" stage carries it, "evict" only the clear/release
            ranked, evicted = eng.tick_render(
                now=eng.last_time, idle_seconds=3600
            )
            t3 = time.perf_counter()
            sample = eng.slot_metadata([s for s, *_ in ranked])
            rows = [
                (s, *sample[s], c)
                for s, c, _fa, _ra in ranked if s in sample
            ]
            footer = f"showing {len(rows)} of {eng.num_flows()}"
            t4 = t5 = time.perf_counter()
        else:
            # full-table predict stays device-resident; the render gather
            # fetches O(table_rows), not the (capacity,) label vector. The
            # render stage's device fetch is the tick's first hard sync,
            # so it also absorbs the (async-dispatched) scatter + predict
            # time — "predict" is dispatch-only, "render" holds the wait.
            # Incremental mode reads the label cache instead: only this
            # tick's dirty rows are re-predicted (its dirty-count fetch
            # is a real sync, so "predict" carries the compact cost).
            if inc is not None:
                labels = inc.labels()
            else:
                labels = predict(params, eng.features())
            t3 = time.perf_counter()
            ranked = eng.render_sample(labels, args.table_rows)
            sample = eng.slot_metadata(slots=[s for s, *_ in ranked])
            rows = [
                (s, *sample[s], c)
                for s, c, _fa, _ra in ranked if s in sample
            ]
            footer = f"showing {len(rows)} of {eng.num_flows()}"
            t4 = time.perf_counter()
            evicted = eng.evict_idle(now=eng.last_time, idle_seconds=3600)
            t5 = time.perf_counter()
        timings["ingest"].append(t1 - t0)
        timings["step"].append(t2 - t1)
        timings["predict"].append(t3 - t2)
        timings["render"].append(t4 - t3)
        timings["evict"].append(t5 - t4)
        timings["tick"].append(t5 - t0)
        rendered_rows.append(rows)
        print(
            f"# tick {ti}: {footer}, evicted {evicted}, "
            f"tick {(t5 - t0) * 1e3:.0f} ms",
            file=sys.stderr, flush=True,
        )
        assert len(rows) <= args.table_rows
    wall = time.perf_counter() - t_wall0
    p50 = {k: float(np.median(v)) for k, v in timings.items()}
    return {"timings": timings, "p50": p50, "wall_s": wall,
            "n_parsed": n_parsed, "pipeline_stats": None,
            "rendered_rows": rendered_rows}


def _run_pipelined(args, eng, predict, params, payloads, inc=None):
    """The pipelined loop: host stage ingests/scatters/dispatches; the
    device stage (worker) syncs and builds the render rows — the same
    shape cli.py serves with (serving/pipeline.py).

    Single-device A/B work parity: this mode runs the same per-tick
    evict pass as the serial mode. The SHARDED pipelined mode does not
    process stale bits (its read dispatch carries an inert horizon), so
    a sharded A/B slightly favors this mode — read its speedup as a
    ceiling, not a measurement of equal work."""
    import numpy as np

    from traffic_classifier_sdn_tpu.serving.pipeline import (
        FeatureStage,
        ServePipeline,
        dispatch_read,
    )

    host_native = getattr(predict, "host_native", False)
    fs = (
        None if (args.shards >= 1 or host_native or inc is not None)
        else FeatureStage(args.capacity)
    )
    rendered = []

    def consume(job):
        job()

    pipe = ServePipeline(consume, depth=2).start()
    timings = {k: [] for k in ("ingest", "step", "dispatch", "tick")}
    n_parsed = 0
    t_wall0 = time.perf_counter()
    try:
        for ti, payload in enumerate(payloads):
            with pipe.host_stage():
                eng.mark_tick()
                t0 = time.perf_counter()
                n_parsed += eng.ingest_bytes(payload)
                t1 = time.perf_counter()
                eng.step()
                t2 = time.perf_counter()
                if args.shards >= 1:
                    outs = eng.tick_read_dispatch(now=eng.last_time)
                    n_flows = eng.num_flows()

                    def job(outs=outs, n_flows=n_flows):
                        ranked = eng.tick_read_finish(outs)
                        sample = eng.slot_metadata(
                            [s for s, *_ in ranked]
                        )
                        rows = [
                            (s, *sample[s], c)
                            for s, c, _fa, _ra in ranked if s in sample
                        ]
                        rendered.append((rows, n_flows))
                else:
                    # every tick, unconditionally — the A/B must pay
                    # identical per-tick work in both modes (the serial
                    # mode's evict stage is O(capacity) host work; an
                    # idle()-gated evict would let the pipelined mode
                    # skip it under load and report overlap it doesn't
                    # have). Safe here unlike cli: the 3600 s horizon
                    # releases nothing, so no render's slot metadata is
                    # ever at stake.
                    eng.evict_idle(now=eng.last_time, idle_seconds=3600)
                    read = dispatch_read(
                        eng, predict, params, args.table_rows, fs,
                        inc=inc,
                    )

                    def job(read=read):
                        ranked = read.rows()
                        # the serial mode's render half: slot metadata
                        # + row assembly, on the device stage like cli
                        sample = eng.slot_metadata(
                            slots=[s for s, *_ in ranked]
                        )
                        rows = [
                            (s, *sample[s], c)
                            for s, c, _fa, _ra in ranked if s in sample
                        ]
                        rendered.append((rows, read.n_flows))
                pipe.submit(job)
                t3 = time.perf_counter()
            timings["ingest"].append(t1 - t0)
            timings["step"].append(t2 - t1)
            timings["dispatch"].append(t3 - t2)
            timings["tick"].append(t3 - t0)
            print(
                f"# tick {ti}: host {(t3 - t0) * 1e3:.0f} ms "
                f"(queue {pipe._handoff.queued})",
                file=sys.stderr, flush=True,
            )
        pipe.shutdown(drain=True)
        pipe.raise_if_failed()
    finally:
        pipe.shutdown(drain=False)
    wall = time.perf_counter() - t_wall0
    for rows, _nf in rendered:
        assert len(rows) <= args.table_rows
    p50 = {k: float(np.median(v)) for k, v in timings.items()}
    return {"timings": timings, "p50": p50, "wall_s": wall,
            "n_parsed": n_parsed, "pipeline_stats": pipe.stats(),
            "ticks_rendered": len(rendered),
            "rendered_rows": [rows for rows, _nf in rendered]}


def _mode_summary(args, runs, n_flows_per_tick):
    """Aggregate one mode's repeats: median-of-repeats throughput (the
    robust center on a noisy shared host), pooled stage medians, and
    first-tick latency from the FIRST repeat (the only cold one)."""
    import numpy as np

    fps = [
        n_flows_per_tick * args.ticks / r["wall_s"] for r in runs
    ]
    pooled = {}
    for r in runs:
        for k, v in r["timings"].items():
            pooled.setdefault(k, []).extend(v)
    t0 = runs[0]["timings"]["tick"]
    steady = t0[1:] or t0
    out = {
        "serve_flows_per_sec": round(float(np.median(fps)), 1),
        "serve_flows_per_sec_per_repeat": [round(f, 1) for f in fps],
        "records_per_sec": round(
            sum(r["n_parsed"] for r in runs)
            / sum(r["wall_s"] for r in runs), 1
        ),
        "wall_s": round(sum(r["wall_s"] for r in runs), 3),
        "first_tick_ms": round(t0[0] * 1e3, 1),
        "steady_tick_p50_ms": round(float(np.median(steady)) * 1e3, 2),
        "stage_p50_ms": {
            k: round(float(np.median(v)) * 1e3, 2)
            for k, v in pooled.items()
        },
    }
    stats = [r["pipeline_stats"] for r in runs if r["pipeline_stats"]]
    if stats:
        host = sum(s["host_busy_s"] for s in stats)
        dev = sum(s["device_busy_s"] for s in stats)
        ov = sum(s["overlap_s"] for s in stats)
        out.update({
            "host_busy_s": round(host, 3),
            "device_busy_s": round(dev, 3),
            "overlap_s": round(ov, 3),
            "overlap_ratio": round(ov / dev, 3) if dev else 0.0,
            "ticks_coalesced": sum(s["ticks_coalesced"] for s in stats),
        })
    return out


def _run_sweep(args, native, predict, params, raw_fn,
               n_flows: int, dev=None) -> None:
    """The dirty sweep (docs/artifacts/serve_dirty_sweep_cpu.json): per
    churn level, A/B incremental vs full re-predict over IDENTICAL
    payloads with the median-of-interleaved-repeats machinery, assert
    render identity, and emit one ``serve_dirty_sweep`` JSON object.
    Engines are rebuilt per level (fresh population, fresh cache) and
    released before the next one; the jit caches persist, so only the
    first level pays compiles (pass --warmup to keep even that out of
    the timed region)."""
    import numpy as np  # noqa: F401 — _mode_summary pulls it lazily

    import jax

    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows

    levels = [float(x) for x in args.churn_sweep.split(",")]
    out_levels = []
    warmed = False
    for lvl in levels:
        # one synthetic feed per level: a full-churn fill tick first
        # (churn is meaningful only against a populated table), then
        # the measured payloads at the level — identical for both modes
        syn = SyntheticFlows(n_flows=n_flows, seed=0, churn=1.0)
        fill = syn.tick_bytes()
        syn.churn = lvl
        chunks = [
            [syn.tick_bytes() for _ in range(args.ticks)]
            for _ in range(args.repeat)
        ]
        engines = {
            "full": _make_engine(args, native, raw_fn, params),
            "incremental": _make_engine(
                args, native, raw_fn, params, incremental=True
            ),
        }
        incs: dict = {"full": None, "incremental": None}
        if args.shards < 1:
            from traffic_classifier_sdn_tpu.serving.incremental import (
                IncrementalLabels,
            )

            incs["incremental"] = IncrementalLabels(
                engines["incremental"], predict, params
            )
        if args.warmup and not warmed:
            from traffic_classifier_sdn_tpu.serving.warmup import (
                warmup_serving,
            )

            t0 = time.perf_counter()
            for name, eng in engines.items():
                warmup_serving(
                    eng, predict, params, table_rows=args.table_rows,
                    idle_timeout=3600 if args.shards < 1 else None,
                    incremental=name == "incremental",
                )
            print(
                f"# warmup in {time.perf_counter() - t0:.2f}s",
                file=sys.stderr, flush=True,
            )
            warmed = True
        for eng in engines.values():
            eng.mark_tick()
            eng.ingest_bytes(fill)
            eng.step()
        runs: dict = {name: [] for name in engines}
        for rep, chunk in enumerate(chunks):
            for name, eng in engines.items():
                print(
                    f"# sweep churn={lvl} repeat {rep} mode {name}",
                    file=sys.stderr, flush=True,
                )
                runs[name].append(
                    _run_serial(args, eng, predict, params, chunk,
                                inc=incs[name])
                )
        ident = all(
            rf == ri
            for runf, runi in zip(runs["full"], runs["incremental"])
            for rf, ri in zip(
                runf["rendered_rows"], runi["rendered_rows"]
            )
        )
        res = {
            name: _mode_summary(args, runs[name], n_flows)
            for name in runs
        }
        f = res["full"]["stage_p50_ms"]["tick"]
        i = res["incremental"]["stage_p50_ms"]["tick"]
        out_levels.append({
            "churn": lvl,
            "full": res["full"],
            "incremental": res["incremental"],
            "tick_p50_speedup": round(f / i, 3) if i else None,
            "render_identical": ident,
        })
        del engines, incs, runs  # free two tables before the next level

    out = {
        "metric": "serve_dirty_sweep",
        "capacity": args.capacity,
        "tracked_flows": n_flows,
        "ticks": args.ticks,
        "repeat": args.repeat,
        "table_rows_rendered": args.table_rows,
        "predict_model": args.model,
        "native_ingest": native,
        **({"shards": args.shards} if args.shards >= 1 else {}),
        "platform": jax.devices()[0].platform,
        "warmup": args.warmup,
        # totals only: levels interleave compiles by design (fresh
        # engines per level share jit caches), so a per-region gate
        # would be noise here — the single-measurement path gates
        **(
            {"jit_compiles": dev.status()["jit_compiles"]}
            if dev is not None else {}
        ),
        "levels": out_levels,
    }
    print(json.dumps(out), flush=True)


def _run_fanin_sweep(args, native, predict, params,
                     n_flows: int, dev=None) -> None:
    """The fan-in source sweep (docs/artifacts/serve_fanin_sources_cpu
    .json): for each source count N, drive the REAL fan-in tier
    (ingest/fanin.py — per-source pump threads, the bounded MPSC queue,
    per-source supervision) with the aggregate flow population split
    into N synthetic sources at a 1 Hz emission cadence, and measure
    whether the serve chain holds the 1 s tick budget: per-tick
    processing p50 (ingest+scatter+predict+render+evict — the work that
    must fit under the cadence) plus per-source drop/lag numbers from
    the tier's roster. A level 'holds' when processing p50 <= 1 s and
    no source dropped records; the knee is the largest holding level.

    With the native engine (the default now that tck_feed_lines keys
    per-source namespaces), pumps deliver raw wire bytes and the serve
    tick feeds each (sid, payload) straight to C++ — the Python-batcher
    capacity ceiling the original sweep hit at 256 sources is the
    per-record routing cost this path deletes; --no-native reproduces
    the historical Python-batcher sweep."""
    import numpy as np

    import jax

    from traffic_classifier_sdn_tpu.ingest import fanin
    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine

    levels = [int(x) for x in args.sources_sweep.split(",")]
    out_levels = []
    warmed = False
    for n_sources in levels:
        per = max(1, n_flows // n_sources)
        specs = [
            fanin.SourceSpec(
                kind="synthetic", sid=sid, n_flows=per, seed=sid,
                mac_base=sid * per, max_ticks=args.ticks,
                interval=args.source_interval,
            )
            for sid in range(n_sources)
        ]
        # queue bound sized to the aggregate record rate (records, not
        # batches): a sweep probing ABOVE the old 24.5k-conversation
        # ceiling must not report self-inflicted bound drops
        tier = fanin.FanInIngest(
            specs, quarantine_s=5.0, raw=native,
            queue_records=max(1 << 16, 4 * 2 * n_flows),
        )
        eng = FlowStateEngine(capacity=args.capacity, native=native)
        if args.warmup and not warmed:
            from traffic_classifier_sdn_tpu.serving.warmup import (
                warmup_serving,
            )

            t0 = time.perf_counter()
            warmup_serving(
                eng, predict, params, table_rows=args.table_rows,
                idle_timeout=3600,
            )
            print(f"# warmup in {time.perf_counter() - t0:.2f}s",
                  file=sys.stderr, flush=True)
            warmed = True
        timings = {k: [] for k in ("drain", "ingest", "step", "predict",
                                   "render", "evict", "tick")}
        n_records = 0
        roster = []
        gen = tier.ticks(tick_timeout=max(10.0,
                                          4 * args.source_interval))
        t_wall0 = time.perf_counter()
        try:
            for ti in range(args.ticks * 2):  # coalesce-split headroom
                t_w = time.perf_counter()
                batch = next(gen, None)
                if batch is None:
                    break
                t0 = time.perf_counter()
                eng.mark_tick()
                if isinstance(batch, fanin.RawTick):
                    n_records += sum(
                        eng.ingest_bytes(data, sid)
                        for sid, data in batch
                    )
                else:
                    n_records += eng.ingest(batch)
                t1 = time.perf_counter()
                eng.step()
                t2 = time.perf_counter()
                for sid in tier.take_evictions():
                    eng.evict_source(sid)
                labels = predict(params, eng.features())
                jax.block_until_ready(labels)
                t3 = time.perf_counter()
                ranked = eng.render_sample(labels, args.table_rows)
                sample = eng.slot_metadata(
                    slots=[s for s, *_ in ranked]
                )
                rows = [
                    (s, *sample[s], c)
                    for s, c, _fa, _ra in ranked if s in sample
                ]
                t4 = time.perf_counter()
                eng.evict_idle(now=eng.last_time, idle_seconds=3600)
                t5 = time.perf_counter()
                assert len(rows) <= args.table_rows
                timings["drain"].append(t0 - t_w)
                timings["ingest"].append(t1 - t0)
                timings["step"].append(t2 - t1)
                timings["predict"].append(t3 - t2)
                timings["render"].append(t4 - t3)
                timings["evict"].append(t5 - t4)
                timings["tick"].append(t5 - t0)
                # refreshed per tick: the artifact's per-source numbers
                # are the last MID-SERVE state, not the post-stream
                # teardown (bounded sources end DEAD-clean by design)
                roster = tier.roster()
        finally:
            gen.close()
        wall = time.perf_counter() - t_wall0
        # steady state: the first serve tick carries thread spin-up (and,
        # un-warmed, the compiles)
        steady = timings["tick"][1:] or timings["tick"]
        p50 = float(np.median(steady))
        total_drops = sum(r["drops"] for r in roster)
        lags = [r["lag_s"] for r in roster if r["lag_s"] is not None]
        holds = p50 <= 1.0 and total_drops == 0
        level = {
            "sources": n_sources,
            "flows_per_source": per,
            "records_ingested": n_records,
            "serve_ticks": len(timings["tick"]),
            "wall_s": round(wall, 3),
            "tick_processing_p50_ms": round(p50 * 1e3, 2),
            "tick_processing_p95_ms": round(
                float(np.percentile(steady, 95)) * 1e3, 2
            ),
            "stage_p50_ms": {
                k: round(float(np.median(v)) * 1e3, 2)
                for k, v in timings.items() if v
            },
            "tracked_flows": eng.num_flows(),
            "total_drops": total_drops,
            "max_lag_s": round(max(lags), 3) if lags else None,
            "holds_1s_cadence": holds,
            "per_source": [
                {k: r[k] for k in
                 ("id", "state", "records", "drops", "lag_s")}
                for r in roster
            ],
        }
        out_levels.append(level)
        print(
            f"# sources={n_sources} tick_p50="
            f"{level['tick_processing_p50_ms']} ms drops={total_drops} "
            f"holds={holds}",
            file=sys.stderr, flush=True,
        )
        del tier, eng
    holding = [lv["sources"] for lv in out_levels
               if lv["holds_1s_cadence"]]
    knee = max(holding) if holding else 0
    out = {
        "metric": "serve_fanin_sources",
        "capacity": args.capacity,
        "aggregate_flows_per_tick": n_flows,
        "ticks_per_source": args.ticks,
        "source_interval_s": args.source_interval,
        "table_rows_rendered": args.table_rows,
        "predict_model": args.model,
        "native_ingest": native,
        "platform": __import__("jax").devices()[0].platform,
        "warmup": args.warmup,
        "max_sources_holding_1s_p50": knee,
        "knee_is_sweep_ceiling": bool(
            out_levels and holding
            and knee == out_levels[-1]["sources"]
        ),
        **(
            {"jit_compiles": dev.status()["jit_compiles"]}
            if dev is not None else {}
        ),
        "levels": out_levels,
    }
    print(json.dumps(out), flush=True)


def _region_identity(max_shards: int) -> dict:
    """Deterministic byte-identity: the composed region serve (fan-in ×
    sharded × incremental × native ingest) vs EACH single-spine path,
    end to end through the real CLI on lockstep synthetic traffic. The
    composed render must be byte-equal to every de-composition — the
    sweep's perf claims only count if the fused spine is literally the
    same serve."""
    import contextlib
    import io
    import tempfile

    import numpy as np

    from traffic_classifier_sdn_tpu import cli as _cli
    from traffic_classifier_sdn_tpu.io import checkpoint as ck
    from traffic_classifier_sdn_tpu.models import gnb as _gnb

    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "gnb")
        ck.save_model(
            ckpt, "gnb",
            _gnb.from_numpy({
                "theta": rng.gamma(2.0, 100.0, (2, 12)),
                "var": rng.gamma(2.0, 50.0, (2, 12)) + 1.0,
                "class_prior": np.full(2, 0.5),
            }),
            classes=("ping", "voice"),
        )
        base = [
            "gaussiannb", "--native-checkpoint", ckpt,
            "--source", "synthetic", "--synthetic-flows", "16",
            "--sources", "2", "--source-lockstep",
            "--capacity", "64", "--print-every", "2",
            "--max-ticks", "6", "--idle-timeout", "0",
            "--table-rows", "8",
        ]

        def run(extra):
            out = io.StringIO()
            with contextlib.redirect_stdout(out), \
                    contextlib.redirect_stderr(io.StringIO()):
                _cli.main(base + extra)
            return out.getvalue()

        s = str(max_shards)
        composed = run(["--shards", s, "--incremental", "auto",
                        "--native-ingest", "auto"])
        spines = {
            "unsharded_fanin": ["--incremental", "auto",
                                "--native-ingest", "auto"],
            "sharded_full_predict": ["--shards", s, "--incremental",
                                     "off", "--native-ingest", "auto"],
            "sharded_python_ingest": ["--shards", s, "--incremental",
                                      "auto", "--native-ingest", "off"],
            "pipelined_composed": ["--shards", s, "--incremental",
                                   "auto", "--native-ingest", "auto",
                                   "--pipeline", "on"],
        }
        verdicts = {name: run(extra) == composed
                    for name, extra in spines.items()}
    return verdicts


# region-sweep warm ticks per level: tick 0 (pump spin-up + first-flush
# bucket compiles) and tick 1 (the steady-churn dirty-bucket compile)
# are excluded from both the timing and the compile-count region
_WARM_TICKS = 2


def _run_region_sweep(args, native, predict, params, raw_fn,
                      n_flows: int, dev=None) -> None:
    """The region sweep (docs/artifacts/serve_region_cpu.json): drive
    the COMPOSED spine — real fan-in tier feeding the mesh-sharded
    table with per-shard dirty masks/label caches and native ingest —
    across (sources × shards × churn), and measure the aggregate churn
    it holds under the 1 s serve cadence.

    shards=0 levels run the single-device fan-in path (the un-sharded
    comparator, full-table predict — the historical sweep); sharded
    levels run the whole composed spine (incremental ON: the per-shard
    dirty-set read is part of what got de-gated). Two comparators must
    both fall: the recorded un-sharded fan-in knee
    (serve_fanin_sources_native_cpu.json) and this sweep's own
    single-source sharded level — otherwise the de-gating bought
    nothing. Byte-identity vs every single-spine path rides in the
    same artifact (``render_identical``), and compiles inside any
    measured region are counted and gated."""
    import numpy as np

    import jax

    from traffic_classifier_sdn_tpu.ingest import fanin
    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
    from traffic_classifier_sdn_tpu.serving.warmup import warmup_serving

    src_levels = [int(x) for x in args.region_sources.split(",")]
    shard_levels = [int(x) for x in args.region_shards.split(",")]
    churn_levels = [float(x) for x in args.region_churn.split(",")]
    max_shards = max([s for s in shard_levels if s >= 1], default=0)
    if max_shards < 1:
        sys.exit("--region-sweep needs at least one sharded level")

    identity = _region_identity(max_shards)

    out_levels = []
    compiles_in_measured = 0
    for shards in shard_levels:
        for churn in churn_levels:
            for n_sources in src_levels:
                per = max(1, n_flows // n_sources)
                specs = [
                    fanin.SourceSpec(
                        kind="synthetic", sid=sid, n_flows=per,
                        seed=sid, mac_base=sid * per, churn=churn,
                        max_ticks=args.ticks,
                        interval=args.source_interval,
                    )
                    for sid in range(n_sources)
                ]
                tier = fanin.FanInIngest(
                    specs, quarantine_s=5.0, raw=native,
                    queue_records=max(1 << 16, 4 * 2 * n_flows),
                )
                args.shards = shards
                eng = _make_engine(args, native, raw_fn, params,
                                   incremental=shards >= 1)
                warmup_serving(
                    eng, predict, params, table_rows=args.table_rows,
                    idle_timeout=None if shards >= 1 else 3600,
                    incremental=shards >= 1,
                )
                timings = {k: [] for k in ("drain", "tick")}
                n_records = 0
                roster = []
                compiles_at_steady = None
                gen = tier.ticks(
                    tick_timeout=max(10.0, 4 * args.source_interval)
                )
                t_wall0 = time.perf_counter()
                try:
                    for ti in range(args.ticks * 2):
                        t_w = time.perf_counter()
                        batch = next(gen, None)
                        if batch is None:
                            break
                        t0 = time.perf_counter()
                        eng.mark_tick()
                        if isinstance(batch, fanin.RawTick):
                            n_records += sum(
                                eng.ingest_bytes(data, sid)
                                for sid, data in batch
                            )
                        else:
                            n_records += eng.ingest(batch)
                        eng.step()
                        for sid in tier.take_evictions():
                            eng.evict_source(sid)
                        if shards >= 1:
                            ranked, _ev = eng.tick_render(
                                now=eng.last_time, idle_seconds=3600
                            )
                        else:
                            labels = predict(params, eng.features())
                            jax.block_until_ready(labels)
                            ranked = eng.render_sample(
                                labels, args.table_rows
                            )
                        sample = eng.slot_metadata(
                            slots=[s for s, *_ in ranked]
                        )
                        rows = [
                            (s, *sample[s], c)
                            for s, c, *_ in ranked if s in sample
                        ]
                        if shards < 1:
                            eng.evict_idle(
                                now=eng.last_time, idle_seconds=3600
                            )
                        t1 = time.perf_counter()
                        assert len(rows) <= args.table_rows
                        timings["drain"].append(t0 - t_w)
                        timings["tick"].append(t1 - t0)
                        if (dev is not None
                                and compiles_at_steady is None
                                and len(timings["tick"]) >= _WARM_TICKS):
                            # measured region = steady ticks: tick 0
                            # carries pump spin-up plus the first-flush
                            # bucket compiles, tick 1 the level's
                            # steady-churn dirty-bucket compile — both
                            # are warmup, not serve work
                            compiles_at_steady = (
                                dev.status()["jit_compiles"]
                            )
                        roster = tier.roster()
                finally:
                    gen.close()
                wall = time.perf_counter() - t_wall0
                if dev is not None and compiles_at_steady is not None:
                    compiles_in_measured += (
                        dev.status()["jit_compiles"] - compiles_at_steady
                    )
                steady = (timings["tick"][_WARM_TICKS:]
                          or timings["tick"])
                p50 = float(np.median(steady))
                total_drops = sum(r["drops"] for r in roster)
                holds = p50 <= 1.0 and total_drops == 0
                serve_ticks = len(timings["tick"])
                level = {
                    "sources": n_sources,
                    "shards": shards,
                    "churn_fraction": churn,
                    "flows_per_source": per,
                    "incremental": shards >= 1,
                    "records_ingested": n_records,
                    "serve_ticks": serve_ticks,
                    "wall_s": round(wall, 3),
                    "aggregate_records_per_tick": (
                        round(n_records / serve_ticks)
                        if serve_ticks else 0
                    ),
                    "records_per_sec": (
                        round(n_records / wall) if wall else 0
                    ),
                    "tick_processing_p50_ms": round(p50 * 1e3, 2),
                    "tick_processing_p95_ms": round(
                        float(np.percentile(steady, 95)) * 1e3, 2
                    ),
                    "tracked_flows": eng.num_flows(),
                    "total_drops": total_drops,
                    "holds_1s_cadence": holds,
                }
                out_levels.append(level)
                print(
                    f"# sources={n_sources} shards={shards} "
                    f"churn={churn} tick_p50="
                    f"{level['tick_processing_p50_ms']} ms "
                    f"drops={total_drops} holds={holds}",
                    file=sys.stderr, flush=True,
                )
                del tier, eng

    # comparator 1: the recorded un-sharded fan-in knee
    knee_rate = None
    try:
        with open(args.baseline_fanin) as f:
            knee_doc = json.load(f)
        knee_n = knee_doc["max_sources_holding_1s_p50"]
        knee_lv = next(
            lv for lv in knee_doc["levels"] if lv["sources"] == knee_n
        )
        knee_rate = round(knee_lv["records_ingested"]
                          / knee_lv["wall_s"])
    except (OSError, KeyError, StopIteration, ValueError) as e:
        print(f"# no un-sharded knee baseline ({e})",
              file=sys.stderr, flush=True)

    # comparator 2: this sweep's own single-source sharded level
    single_sharded = [
        lv for lv in out_levels
        if lv["sources"] == 1 and lv["shards"] >= 1
        and lv["churn_fraction"] == max(churn_levels)
    ]
    single_rate = (max(lv["records_per_sec"] for lv in single_sharded)
                   if single_sharded else None)

    composed = [
        lv for lv in out_levels
        if lv["shards"] >= 1 and lv["sources"] > 1
        and lv["holds_1s_cadence"]
    ]
    best_rate = (max(lv["records_per_sec"] for lv in composed)
                 if composed else 0)
    max_churn = (max(lv["aggregate_records_per_tick"]
                     for lv in composed) if composed else 0)

    out = {
        "metric": "serve_region",
        "capacity": args.capacity,
        "aggregate_flows_per_tick": n_flows,
        "ticks_per_source": args.ticks,
        "source_interval_s": args.source_interval,
        "table_rows_rendered": args.table_rows,
        "predict_model": args.model,
        "native_ingest": native,
        "platform": jax.devices()[0].platform,
        "max_aggregate_records_per_tick_holding_1s": max_churn,
        "best_composed_records_per_sec": best_rate,
        "unsharded_fanin_knee_records_per_sec": knee_rate,
        "exceeds_unsharded_fanin_knee": (
            best_rate > knee_rate if knee_rate is not None else None
        ),
        "single_source_sharded_records_per_sec": single_rate,
        "exceeds_single_source_sharded": (
            best_rate > single_rate if single_rate is not None else None
        ),
        "render_identical": all(identity.values()),
        "identity_paths": identity,
        "compiles_in_measured_region": compiles_in_measured,
        **(
            {"jit_compiles": dev.status()["jit_compiles"]}
            if dev is not None else {}
        ),
        "levels": out_levels,
    }
    print(json.dumps(out), flush=True)
    if compiles_in_measured > 0:
        sys.exit(
            f"FAIL: {compiles_in_measured} compile(s) fired inside "
            "the region sweep's measured ticks — the sweep timed XLA, "
            "not the composed spine"
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--capacity", type=int, default=1 << 20)
    ap.add_argument(
        "--churn-fraction", type=float, default=1.0,
        help="fraction of the synthetic flow population emitting "
        "telemetry each tick (default 1.0 — every flow every tick); "
        "the updated-row knob behind incremental serving: at 0.1 only "
        "10%% of flows change per tick, so the dirty-set predict "
        "touches 10%% of the table",
    )
    ap.add_argument(
        "--sources-sweep", default=None, metavar="N0,N1,...",
        help="run the fan-in source sweep instead of a single "
        "measurement: for each comma-separated source count, split the "
        "aggregate flow population (--flows-per-tick) across N real "
        "fan-in sources (ingest/fanin.py pump threads + MPSC queue) at "
        "--source-interval cadence and report per-tick processing p50, "
        "per-source drops/lag, and the max source count holding the "
        "1 s tick budget — one serve_fanin_sources JSON object "
        "(e.g. 1,2,4,8,16,32)",
    )
    ap.add_argument(
        "--source-interval", type=float, default=1.0, metavar="SECS",
        help="fan-in sweep emission cadence per source (default 1.0, "
        "the reference monitor's poll rate)",
    )
    ap.add_argument(
        "--region-sweep", action="store_true",
        help="run the REGION sweep: the composed spine (fan-in × "
        "sharded × incremental × native ingest) across "
        "(--region-sources × --region-shards × --region-churn), plus "
        "a lockstep byte-identity check of the composed serve vs "
        "every single-spine path through the real CLI — one "
        "serve_region JSON object "
        "(docs/artifacts/serve_region_cpu.json)",
    )
    ap.add_argument(
        "--region-sources", default="1,96,384", metavar="N0,N1,...",
        help="region sweep source-count axis (default 1,96,384 — 1 "
        "anchors the single-source sharded comparator)",
    )
    ap.add_argument(
        "--region-shards", default="0,8", metavar="S0,S1,...",
        help="region sweep shard axis (default 0,8 — 0 anchors the "
        "un-sharded fan-in comparator; sharded levels run the "
        "composed spine with per-shard dirty masks/label caches)",
    )
    ap.add_argument(
        "--region-churn", default="1.0,0.25", metavar="C0,C1,...",
        help="region sweep churn axis: fraction of each source's flow "
        "population emitting per tick (default 1.0,0.25)",
    )
    ap.add_argument(
        "--baseline-fanin",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "docs", "artifacts", "serve_fanin_sources_native_cpu.json",
        ),
        metavar="PATH",
        help="recorded un-sharded fan-in sweep whose knee the region "
        "sweep must beat (default: the committed artifact)",
    )
    ap.add_argument(
        "--churn-sweep", default=None, metavar="L0,L1,...",
        help="run the dirty sweep instead of a single measurement: "
        "for each comma-separated churn level, A/B incremental vs "
        "full re-predict over identical payloads (serial chain, "
        "repeats interleaved) and emit one serve_dirty_sweep JSON "
        "object with per-level per-mode timings, speedups, and a "
        "render-identity verdict (e.g. 0,0.01,0.1,1.0)",
    )
    ap.add_argument(
        "--incremental", choices=("off", "on", "both"), default="off",
        help="label source: off = full-table re-predict every render "
        "(the historical bench), on = dirty-set prediction with the "
        "device-resident label cache (serving/incremental.py), both = "
        "A/B over identical payloads, one serve_incremental_ab JSON "
        "object (requires --pipeline off or on, not both)",
    )
    ap.add_argument(
        "--synth-trees", type=int, default=100,
        help="tree count for --model forest-synth (default 100, the "
        "flagship checkpoint's size)",
    )
    ap.add_argument(
        "--flows-per-tick", type=int, default=0,
        help="synthetic conversations per tick (2 records each); "
        "0 = capacity/2 (the historical fill-the-table default). "
        "Decoupled from --capacity so the A/B can pin the ingest batch "
        "(e.g. 16384) while the full-table predict cost scales with "
        "capacity independently",
    )
    ap.add_argument("--ticks", type=int, default=5)
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument(
        "--platform", choices=("cpu", "default"), default="cpu",
        help="cpu (safe anywhere) or default (real TPU when healthy)",
    )
    ap.add_argument("--table-rows", type=int, default=64)
    ap.add_argument(
        "--model",
        choices=("gnb", "forest", "knn", "forest-synth", "knn-synth"),
        default="gnb",
        help="predict stage: gnb (cheapest full-table predict; the CPU "
        "default), forest (the flagship 100-tree checkpoint), or knn "
        "(the KNeighbors checkpoint) — the latter two resolve through "
        "the serving path and honor TCSDN_FOREST_KERNEL / "
        "TCSDN_KNN_TOPK, so the raced kernels A/B directly in this "
        "bench; forest-synth / knn-synth fit at bench time on "
        "flow-shaped synthetic data (reference-pickle-free — the CI "
        "twins; knn-synth also honors --knn-topk via the env rule)",
    )
    ap.add_argument(
        "--synth-corpus", type=int, default=4448,
        help="corpus rows for --model knn-synth (default 4448, the "
        "reference KNeighbors scale)",
    )
    ap.add_argument(
        "--shards", type=int, default=0,
        help="shard the flow table over an N-device mesh "
        "(parallel/table_sharded.py); on the cpu platform N virtual "
        "devices are forced, so --shards 8 --capacity 8388608 exercises "
        "the 2²³-flow sharded spine on one host",
    )
    ap.add_argument(
        "--pipeline", choices=("off", "on", "both"), default="off",
        help="serve-loop mode: off = serial chain (the historical "
        "bench), on = pipelined (serving/pipeline.py), both = A/B over "
        "identical payloads, one serve_pipeline_ab JSON object",
    )
    ap.add_argument(
        "--repeat", type=int, default=1,
        help="repeat the measurement N times (modes interleaved per "
        "repeat, fresh payload chunk each, engines reused so later "
        "repeats measure the saturated steady state) and report "
        "median-of-repeats throughput — the noisy-neighbor antidote "
        "for shared CI hosts",
    )
    ap.add_argument(
        "--warmup", action="store_true",
        help="AOT-compile the serving programs before timing "
        "(serving/warmup.py) — required for a clean A/B (the modes "
        "share jit caches) and for first_tick_ms to mean warm latency",
    )
    args = ap.parse_args()

    # the region sweep varies shard count per level under ONE device
    # pool: force the pool to the widest sharded level
    forced_devices = args.shards
    if args.region_sweep:
        forced_devices = max(
            [int(x) for x in args.region_shards.split(",")] + [0]
        )
    if args.platform == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        if forced_devices >= 1:
            import re

            flags = re.sub(
                r"--?xla_force_host_platform_device_count=\S*", "",
                os.environ.get("XLA_FLAGS", ""),
            )
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count="
                f"{forced_devices}"
            ).strip()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows
    from traffic_classifier_sdn_tpu.native import engine as native_engine

    native = (not args.no_native) and native_engine.available()
    cap = args.capacity
    # two directions share one slot; the default fills half the table
    n_flows = args.flows_per_tick or cap // 2
    if n_flows > cap:
        sys.exit("--flows-per-tick exceeds --capacity (every "
                 "conversation needs a slot)")
    if args.pipeline == "both" and args.incremental == "both":
        sys.exit("--pipeline both and --incremental both cannot "
                 "combine — A/B one axis at a time")

    # init-first liveness: a wedged worker hangs the first device call,
    # and a silent run is indistinguishable from a slow compile
    print("# initializing devices", file=sys.stderr, flush=True)
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)

    # Compile hygiene: every bench tail carries the jit-compile count so
    # a regression that reintroduces per-tick retraces is visible in the
    # artifact, not just as slower numbers. With --warmup the main path
    # is a hard gate — a compile inside the measured region exits
    # nonzero (the bench measured XLA, not the serve loop).
    from traffic_classifier_sdn_tpu.obs.device import DeviceTelemetry

    dev = DeviceTelemetry()
    dev.attach()

    predict, params, raw_fn = _build_model(args)

    if args.region_sweep:
        _run_region_sweep(args, native, predict, params, raw_fn,
                          n_flows, dev=dev)
        return

    if args.sources_sweep is not None:
        _run_fanin_sweep(args, native, predict, params, n_flows,
                         dev=dev)
        return

    if args.churn_sweep is not None:
        _run_sweep(args, native, predict, params, raw_fn, n_flows,
                   dev=dev)
        return

    syn = SyntheticFlows(
        n_flows=n_flows, seed=0, churn=args.churn_fraction
    )
    fill_payload = None
    if args.churn_fraction < 1.0:
        # populate the table before churn applies: the dirty fraction
        # is only meaningful against a full tracked population
        syn.churn = 1.0
        fill_payload = syn.tick_bytes()
        syn.churn = args.churn_fraction

    print(
        f"# generating {args.repeat} × {args.ticks} ticks × "
        f"~{int(2 * n_flows * args.churn_fraction)} records "
        f"(capacity {cap}, native={native}, "
        f"churn={args.churn_fraction})",
        file=sys.stderr, flush=True,
    )
    payload_chunks = [
        [syn.tick_bytes() for _ in range(args.ticks)]
        for _ in range(args.repeat)
    ]
    total_records = sum(p.count(b"\n") for p in payload_chunks[0])

    # modes: (name, pipelined, incremental) — one A/B axis at a time
    inc_on = args.incremental == "on"
    if args.pipeline == "both":
        modes = [("serial", False, inc_on), ("pipelined", True, inc_on)]
    elif args.incremental == "both":
        pipelined = args.pipeline == "on"
        modes = [
            ("full", pipelined, False),
            ("incremental", pipelined, True),
        ]
    else:
        pipelined = args.pipeline == "on"
        modes = [("pipelined" if pipelined else "serial",
                  pipelined, inc_on)]
    mode_names = [name for name, _, _ in modes]
    if len(modes) > 1 and not args.warmup:
        print(
            "# NOTE: A/B without --warmup — the first mode pays every "
            "cold compile the second mode then inherits; pass --warmup "
            "for a clean comparison",
            file=sys.stderr, flush=True,
        )

    engines = {
        name: _make_engine(args, native, raw_fn, params,
                           incremental=inc_flag)
        for name, _, inc_flag in modes
    }
    incs: dict = {}
    for name, _, inc_flag in modes:
        if inc_flag and args.shards < 1:
            from traffic_classifier_sdn_tpu.serving.incremental import (
                IncrementalLabels,
            )

            incs[name] = IncrementalLabels(
                engines[name], predict, params
            )
        else:
            incs[name] = None
    if args.warmup:
        from traffic_classifier_sdn_tpu.serving.warmup import (
            warmup_serving,
        )

        t0 = time.perf_counter()
        # one warm per engine kind: a dirty-tracking engine scatters
        # through the fused apply+mark program, the plain one doesn't —
        # both must be hot for a clean A/B
        warmed_kinds = set()
        for name, _, inc_flag in modes:
            if inc_flag in warmed_kinds:
                continue
            warmed_kinds.add(inc_flag)
            stats = warmup_serving(
                engines[name], predict, params,
                table_rows=args.table_rows,
                idle_timeout=3600 if args.shards < 1 else None,
                incremental=inc_flag,
            )
        print(
            f"# warmup: {len(stats['warmed'])} programs in "
            f"{time.perf_counter() - t0:.2f}s",
            file=sys.stderr, flush=True,
        )
    if fill_payload is not None:
        for eng in engines.values():
            eng.mark_tick()
            eng.ingest_bytes(fill_payload)
            eng.step()
    if args.warmup:
        dev.mark_warmup_complete()
    compiles_at_measure = dev.status()["jit_compiles"]
    runs: dict = {name: [] for name in mode_names}
    for rep, chunk in enumerate(payload_chunks):
        for name, pipelined, _inc_flag in modes:
            print(f"# repeat {rep} mode: {name}",
                  file=sys.stderr, flush=True)
            run = _run_pipelined if pipelined else _run_serial
            runs[name].append(
                run(args, engines[name], predict, params, chunk,
                    inc=incs[name])
            )
    results = {
        name: _mode_summary(args, runs[name], n_flows)
        for name in mode_names
    }
    dev_status = dev.status()
    compiles_in_measured = (
        dev_status["jit_compiles"] - compiles_at_measure
    )

    eng = engines[mode_names[-1]]
    # Per-tick host->device wire bytes actually moved for the update
    # batches (padded flow_table.pack_wire matrices, counted by the
    # engine) and the measured link bandwidth — on a slow device link the
    # transfer can bound the tick; a local PCIe host moves the same bytes
    # in single-digit ms. The bandwidth probe only means "device link"
    # off the cpu platform, so it is omitted there (a cpu-platform probe
    # would time a host memcpy).
    wire_mb = eng.wire_bytes / (args.ticks * args.repeat) / 1e6
    link_mb_s = None
    if jax.devices()[0].platform != "cpu":
        # sync by scalar fetch: on this rig's tunnel block_until_ready
        # returns without waiting, which would time dispatch, not transfer
        probe_mb = (4 << 20) / 1e6
        blob = np.ones(4 << 20, np.uint8)
        float(np.asarray(jnp.sum(jnp.asarray(blob))))  # warm
        bw = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(np.asarray(jnp.sum(jnp.asarray(blob))))
            bw.append(probe_mb / (time.perf_counter() - t0))
        link_mb_s = float(np.median(bw))

    common = {
        "capacity": cap,
        "tracked_flows": eng.num_flows(),
        "records_per_tick": total_records // args.ticks,
        "update_wire_mb_per_tick": round(wire_mb, 1),
        **(
            {"host_to_device_mb_per_sec": round(link_mb_s, 1)}
            if link_mb_s is not None else {}
        ),
        "native_ingest": native,
        **({"shards": args.shards} if args.shards >= 1 else {}),
        "platform": jax.devices()[0].platform,
        "predict_model": args.model,
        "table_rows_rendered": args.table_rows,
        "churn_fraction": args.churn_fraction,
        "incremental_mode": args.incremental,
        "warmup": args.warmup,
        "jit_compiles": dev_status["jit_compiles"],
        "retraces_after_warmup": dev_status["retraces_after_warmup"],
        "compiles_in_measured_region": compiles_in_measured,
    }

    if args.pipeline == "both":
        s = results["serial"]["serve_flows_per_sec"]
        p = results["pipelined"]["serve_flows_per_sec"]
        out = {
            "metric": "serve_pipeline_ab",
            "serial": results["serial"],
            "pipelined": results["pipelined"],
            "speedup_flows_per_sec": round(p / s, 3) if s else None,
            **common,
        }
    elif args.incremental == "both":
        # identical payloads, identical render expected: the A/B is a
        # correctness gate as much as a perf one
        ident = all(
            rf == ri
            for runf, runi in zip(runs["full"], runs["incremental"])
            for rf, ri in zip(
                runf["rendered_rows"], runi["rendered_rows"]
            )
        )
        f = results["full"]["stage_p50_ms"]["tick"]
        i = results["incremental"]["stage_p50_ms"]["tick"]
        out = {
            "metric": "serve_incremental_ab",
            "full": results["full"],
            "incremental": results["incremental"],
            "tick_p50_speedup": round(f / i, 3) if i else None,
            "render_identical": ident,
            **common,
        }
    else:
        mode = mode_names[0]
        r = results[mode]
        out = {
            "metric": "serve_tick_p50_ms_at_capacity",
            "value": r["stage_p50_ms"]["tick"],
            "unit": "ms",
            "mode": mode,
            **r,
            **common,
        }
    print(json.dumps(out), flush=True)
    if args.warmup and compiles_in_measured > 0:
        sys.exit(
            f"FAIL: {compiles_in_measured} compile(s) fired inside "
            "the measured region despite --warmup — the bench timed "
            "XLA, not the serve loop (program: "
            f"{dev_status['last_compile_program']})"
        )


if __name__ == "__main__":
    main()
