#!/usr/bin/env python
"""Serving-loop scale bench: drive the FULL ingest→device-table→predict→
render→evict spine at 2²⁰ concurrent flows (the BASELINE.json north star)
and print one JSON line of per-stage timings.

This measures what VERDICT r1 item 4 said was unproven: that the host side
of the serving loop stays O(batch)/O(limit) — not O(capacity) Python — at
1M flows. The reference's equivalent loop is per-flow Python dict + predict
(traffic_classifier.py:99-118,144-171) and its `flows` dict only ever held
dozens of entries.

Stages per tick:
  ingest   — raw wire bytes → C++ engine (or Python fallback) routing
  step     — one scatter of the padded update batch into the device table
  predict  — batched GNB over the whole (capacity, 12) feature matrix
  render   — sorted sample of --table-rows flows + footer (never O(N))
  evict    — device stale-mask + host release of idle slots

Usage: bench_serve.py [--capacity 1048576] [--ticks 5] [--no-native]
(CPU-safe: forces the host platform unless --platform default.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--capacity", type=int, default=1 << 20)
    ap.add_argument("--ticks", type=int, default=5)
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument(
        "--platform", choices=("cpu", "default"), default="cpu",
        help="cpu (safe anywhere) or default (real TPU when healthy)",
    )
    ap.add_argument("--table-rows", type=int, default=64)
    ap.add_argument(
        "--model", choices=("gnb", "forest", "knn"), default="gnb",
        help="predict stage: gnb (cheapest full-table predict; the CPU "
        "default), forest (the flagship 100-tree checkpoint), or knn "
        "(the KNeighbors checkpoint) — the latter two resolve through "
        "the serving path and honor TCSDN_FOREST_KERNEL / "
        "TCSDN_KNN_TOPK, so the raced kernels A/B directly in this "
        "bench",
    )
    ap.add_argument(
        "--shards", type=int, default=0,
        help="shard the flow table over an N-device mesh "
        "(parallel/table_sharded.py); on the cpu platform N virtual "
        "devices are forced, so --shards 8 --capacity 8388608 exercises "
        "the 2²³-flow sharded spine on one host",
    )
    args = ap.parse_args()

    if args.platform == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        if args.shards >= 1:
            import re

            flags = re.sub(
                r"--?xla_force_host_platform_device_count=\S*", "",
                os.environ.get("XLA_FLAGS", ""),
            )
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.shards}"
            ).strip()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows
    from traffic_classifier_sdn_tpu.models import gnb
    from traffic_classifier_sdn_tpu.native import engine as native_engine

    native = (not args.no_native) and native_engine.available()
    cap = args.capacity
    n_flows = cap // 2  # two directions share one slot; stay under capacity
    syn = SyntheticFlows(n_flows=n_flows, seed=0)

    # init-first liveness: a wedged worker hangs the first device call,
    # and a silent run is indistinguishable from a slow compile
    print("# initializing devices", file=sys.stderr, flush=True)
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)

    if args.model in ("forest", "knn"):
        # the reference checkpoint through the serving-path resolution —
        # honors TCSDN_FOREST_KERNEL / TCSDN_KNN_TOPK, so the chip day
        # can A/B the serve tick with whichever raced kernel won
        # (models/__init__.py)
        from traffic_classifier_sdn_tpu.models import load_reference_model

        models_dir = os.environ.get(
            "TCSDN_MODELS_DIR", "/root/reference/models"
        )
        sub, ck = {
            "forest": ("Randomforest", "RandomForestClassifier"),
            "knn": ("knearest", "KNeighbors"),
        }[args.model]
        m = load_reference_model(sub, f"{models_dir}/{ck}")
        raw_predict, params = m.serving_path()
        if getattr(raw_predict, "host_native", False):
            # eager by contract (see models/__init__ native branch): a
            # jitted host callback deadlocks pipelined single-core loops
            predict = raw_predict
            if args.shards >= 1:
                sys.exit("host-native kernels (TCSDN_FOREST_KERNEL="
                         "native, TCSDN_KNN_TOPK=native) are "
                         "single-device host serving; use a device "
                         "kernel with --shards")
        else:
            predict = jax.jit(raw_predict)
    else:
        # 6-class GNB params (synthetic moments — the model family is the
        # cheapest full-table predict; the forest/SVC cost is bench.py's job)
        rng = np.random.RandomState(0)
        params = gnb.from_numpy(
            {
                "theta": rng.gamma(2.0, 100.0, (6, 12)),
                "var": rng.gamma(2.0, 50.0, (6, 12)) + 1.0,
                "class_prior": np.full(6, 1 / 6),
            }
        )
        predict = jax.jit(gnb.predict)

    if args.shards >= 1:
        from traffic_classifier_sdn_tpu.parallel import (
            mesh as meshlib,
            table_sharded as tsh,
        )

        # the un-jitted fn paired with params by the serving resolution
        # above — raw_predict/params stay a matched (kernel, operands)
        # unit whatever TCSDN_FOREST_KERNEL selected
        raw_fn = (
            raw_predict if args.model in ("forest", "knn") else gnb.predict
        )
        eng = tsh.ShardedFlowEngine(
            meshlib.make_mesh(n_data=args.shards, n_state=1),
            cap, predict_fn=raw_fn, params=params,
            table_rows=args.table_rows, native=native,
        )
    else:
        eng = FlowStateEngine(capacity=cap, native=native)

    print(
        f"# generating {args.ticks} ticks × {2 * n_flows} records "
        f"(capacity {cap}, native={native})",
        file=sys.stderr, flush=True,
    )
    payloads = [syn.tick_bytes() for _ in range(args.ticks)]
    total_records = sum(p.count(b"\n") for p in payloads)

    classes = None
    timings = {k: [] for k in ("ingest", "step", "predict", "render",
                               "evict", "tick")}
    n_parsed = 0
    for ti, payload in enumerate(payloads):
        eng.mark_tick()
        t0 = time.perf_counter()
        n_parsed += eng.ingest_bytes(payload)
        t1 = time.perf_counter()
        eng.step()
        if args.shards >= 1:
            # attribution honesty: apply dispatches are async; without a
            # sync the whole scatter cost lands in whichever later stage
            # first fetches device data (observed: 8.6 s misattributed to
            # "predict" at 2²³). CPU-platform block_until_ready is a real
            # wait (only the tunnel's lies — this path is CPU-mesh only).
            jax.block_until_ready(eng.tables)
        t2 = time.perf_counter()
        if args.shards >= 1:
            # the sharded spine's whole read side (per-shard predict +
            # scored render candidates + stale bits) is ONE dispatch; the
            # "predict" stage carries it, "evict" only the clear/release
            ranked, evicted = eng.tick_render(
                now=eng.last_time, idle_seconds=3600
            )
            t3 = time.perf_counter()
            sample = eng.slot_metadata([s for s, *_ in ranked])
            rows = [
                (s, *sample[s], c)
                for s, c, _fa, _ra in ranked if s in sample
            ]
            footer = f"showing {len(rows)} of {eng.num_flows()}"
            t4 = t5 = time.perf_counter()
        else:
            # full-table predict stays device-resident; the render gather
            # fetches O(table_rows), not the (capacity,) label vector. The
            # render stage's device fetch is the tick's first hard sync,
            # so it also absorbs the (async-dispatched) scatter + predict
            # time — "predict" is dispatch-only, "render" holds the wait.
            labels = predict(params, eng.features())
            t3 = time.perf_counter()
            ranked = eng.render_sample(labels, args.table_rows)
            sample = eng.slot_metadata(slots=[s for s, *_ in ranked])
            rows = [
                (s, *sample[s], c)
                for s, c, _fa, _ra in ranked if s in sample
            ]
            footer = f"showing {len(rows)} of {eng.num_flows()}"
            t4 = time.perf_counter()
            evicted = eng.evict_idle(now=eng.last_time, idle_seconds=3600)
            t5 = time.perf_counter()
        timings["ingest"].append(t1 - t0)
        timings["step"].append(t2 - t1)
        timings["predict"].append(t3 - t2)
        timings["render"].append(t4 - t3)
        timings["evict"].append(t5 - t4)
        timings["tick"].append(t5 - t0)
        print(
            f"# tick {ti}: {footer}, evicted {evicted}, "
            f"tick {(t5 - t0) * 1e3:.0f} ms",
            file=sys.stderr, flush=True,
        )
        assert len(rows) <= args.table_rows

    p50 = {k: float(np.median(v)) for k, v in timings.items()}
    ingest_rate = (total_records / args.ticks) / p50["ingest"]

    # Per-tick host->device wire bytes actually moved for the update
    # batches (padded flow_table.pack_wire matrices, counted by the
    # engine) and the measured link bandwidth — on a slow device link the
    # transfer can bound the tick; a local PCIe host moves the same bytes
    # in single-digit ms. The bandwidth probe only means "device link"
    # off the cpu platform, so it is omitted there (a cpu-platform probe
    # would time a host memcpy).
    wire_mb = eng.wire_bytes / args.ticks / 1e6
    link_mb_s = None
    if jax.devices()[0].platform != "cpu":
        # sync by scalar fetch: on this rig's tunnel block_until_ready
        # returns without waiting, which would time dispatch, not transfer
        probe_mb = (4 << 20) / 1e6
        blob = np.ones(4 << 20, np.uint8)
        float(np.asarray(jnp.sum(jnp.asarray(blob))))  # warm
        bw = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(np.asarray(jnp.sum(jnp.asarray(blob))))
            bw.append(probe_mb / (time.perf_counter() - t0))
        link_mb_s = float(np.median(bw))
    print(
        json.dumps(
            {
                "metric": "serve_tick_p50_ms_at_capacity",
                "value": round(p50["tick"] * 1e3, 1),
                "unit": "ms",
                "capacity": cap,
                "tracked_flows": eng.num_flows(),
                "records_per_tick": total_records // args.ticks,
                "ingest_records_per_sec": round(ingest_rate, 1),
                "stage_p50_ms": {
                    k: round(v * 1e3, 2) for k, v in p50.items()
                },
                "update_wire_mb_per_tick": round(wire_mb, 1),
                **(
                    {"host_to_device_mb_per_sec": round(link_mb_s, 1)}
                    if link_mb_s is not None else {}
                ),
                "native_ingest": native,
                **({"shards": args.shards} if args.shards >= 1 else {}),
                "platform": jax.devices()[0].platform,
                "predict_model": args.model,
                "table_rows_rendered": args.table_rows,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
