#!/usr/bin/env python
"""Serving-loop scale bench: drive the FULL ingest→device-table→predict→
render→evict spine at 2²⁰ concurrent flows (the BASELINE.json north star)
and print one JSON line of per-stage timings.

This measures what VERDICT r1 item 4 said was unproven: that the host side
of the serving loop stays O(batch)/O(limit) — not O(capacity) Python — at
1M flows. The reference's equivalent loop is per-flow Python dict + predict
(traffic_classifier.py:99-118,144-171) and its `flows` dict only ever held
dozens of entries.

Stages per tick:
  ingest   — raw wire bytes → C++ engine (or Python fallback) routing
  step     — one scatter of the padded update batch into the device table
  predict  — batched GNB over the whole (capacity, 12) feature matrix
  render   — sorted sample of --table-rows flows + footer (never O(N))
  evict    — device stale-mask + host release of idle slots

--pipeline {off,on,both} A/Bs the serial chain against the pipelined
serve loop (serving/pipeline.py: host poll/parse/scatter overlapped
with device predict/render through the bounded handoff). `both` runs
serial then pipelined over identical payloads and emits one
`serve_pipeline_ab` JSON object with per-mode `serve_flows_per_sec`,
the speedup, and the measured host/device `overlap_ratio`
(overlap_s / device_busy_s). --warmup AOT-compiles the serving
programs first (serving/warmup.py) — pass it for a clean A/B (the
modes share jit caches, so an un-warmed first mode pays every compile)
and to read `first_tick_ms` as the warm first-tick latency.

Usage: bench_serve.py [--capacity 1048576] [--ticks 5] [--no-native]
(CPU-safe: forces the host platform unless --platform default.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _build_model(args):
    """(predict, params, raw_fn) through the serving-path resolution."""
    import numpy as np

    from traffic_classifier_sdn_tpu.models import gnb, jit_serving_fn

    if args.model in ("forest", "knn"):
        # the reference checkpoint through the serving-path resolution —
        # honors TCSDN_FOREST_KERNEL / TCSDN_KNN_TOPK, so the chip day
        # can A/B the serve tick with whichever raced kernel won
        # (models/__init__.py)
        from traffic_classifier_sdn_tpu.models import load_reference_model

        models_dir = os.environ.get(
            "TCSDN_MODELS_DIR", "/root/reference/models"
        )
        sub, ck = {
            "forest": ("Randomforest", "RandomForestClassifier"),
            "knn": ("knearest", "KNeighbors"),
        }[args.model]
        m = load_reference_model(sub, f"{models_dir}/{ck}")
        raw_predict, params = m.serving_path()
        predict = jit_serving_fn(raw_predict)
        if getattr(raw_predict, "host_native", False) and args.shards >= 1:
            sys.exit("host-native kernels (TCSDN_FOREST_KERNEL="
                     "native, TCSDN_KNN_TOPK=native) are "
                     "single-device host serving; use a device "
                     "kernel with --shards")
        return predict, params, raw_predict
    # 6-class GNB params (synthetic moments — the model family is the
    # cheapest full-table predict; the forest/SVC cost is bench.py's job)
    rng = np.random.RandomState(0)
    params = gnb.from_numpy(
        {
            "theta": rng.gamma(2.0, 100.0, (6, 12)),
            "var": rng.gamma(2.0, 50.0, (6, 12)) + 1.0,
            "class_prior": np.full(6, 1 / 6),
        }
    )
    return jit_serving_fn(gnb.predict), params, gnb.predict


def _make_engine(args, native, raw_fn, params):
    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine

    if args.shards >= 1:
        from traffic_classifier_sdn_tpu.parallel import (
            mesh as meshlib,
            table_sharded as tsh,
        )

        return tsh.ShardedFlowEngine(
            meshlib.make_mesh(n_data=args.shards, n_state=1),
            args.capacity, predict_fn=raw_fn, params=params,
            table_rows=args.table_rows, native=native,
        )
    return FlowStateEngine(capacity=args.capacity, native=native)


def _run_serial(args, eng, predict, params, payloads):
    """The serial chain — one tick fully synchronous, per-stage timed."""
    import numpy as np

    import jax

    timings = {k: [] for k in ("ingest", "step", "predict", "render",
                               "evict", "tick")}
    n_parsed = 0
    t_wall0 = time.perf_counter()
    for ti, payload in enumerate(payloads):
        eng.mark_tick()
        t0 = time.perf_counter()
        n_parsed += eng.ingest_bytes(payload)
        t1 = time.perf_counter()
        eng.step()
        if args.shards >= 1:
            # attribution honesty: apply dispatches are async; without a
            # sync the whole scatter cost lands in whichever later stage
            # first fetches device data (observed: 8.6 s misattributed to
            # "predict" at 2²³). CPU-platform block_until_ready is a real
            # wait (only the tunnel's lies — this path is CPU-mesh only).
            jax.block_until_ready(eng.tables)
        t2 = time.perf_counter()
        if args.shards >= 1:
            # the sharded spine's whole read side (per-shard predict +
            # scored render candidates + stale bits) is ONE dispatch; the
            # "predict" stage carries it, "evict" only the clear/release
            ranked, evicted = eng.tick_render(
                now=eng.last_time, idle_seconds=3600
            )
            t3 = time.perf_counter()
            sample = eng.slot_metadata([s for s, *_ in ranked])
            rows = [
                (s, *sample[s], c)
                for s, c, _fa, _ra in ranked if s in sample
            ]
            footer = f"showing {len(rows)} of {eng.num_flows()}"
            t4 = t5 = time.perf_counter()
        else:
            # full-table predict stays device-resident; the render gather
            # fetches O(table_rows), not the (capacity,) label vector. The
            # render stage's device fetch is the tick's first hard sync,
            # so it also absorbs the (async-dispatched) scatter + predict
            # time — "predict" is dispatch-only, "render" holds the wait.
            labels = predict(params, eng.features())
            t3 = time.perf_counter()
            ranked = eng.render_sample(labels, args.table_rows)
            sample = eng.slot_metadata(slots=[s for s, *_ in ranked])
            rows = [
                (s, *sample[s], c)
                for s, c, _fa, _ra in ranked if s in sample
            ]
            footer = f"showing {len(rows)} of {eng.num_flows()}"
            t4 = time.perf_counter()
            evicted = eng.evict_idle(now=eng.last_time, idle_seconds=3600)
            t5 = time.perf_counter()
        timings["ingest"].append(t1 - t0)
        timings["step"].append(t2 - t1)
        timings["predict"].append(t3 - t2)
        timings["render"].append(t4 - t3)
        timings["evict"].append(t5 - t4)
        timings["tick"].append(t5 - t0)
        print(
            f"# tick {ti}: {footer}, evicted {evicted}, "
            f"tick {(t5 - t0) * 1e3:.0f} ms",
            file=sys.stderr, flush=True,
        )
        assert len(rows) <= args.table_rows
    wall = time.perf_counter() - t_wall0
    p50 = {k: float(np.median(v)) for k, v in timings.items()}
    return {"timings": timings, "p50": p50, "wall_s": wall,
            "n_parsed": n_parsed, "pipeline_stats": None}


def _run_pipelined(args, eng, predict, params, payloads):
    """The pipelined loop: host stage ingests/scatters/dispatches; the
    device stage (worker) syncs and builds the render rows — the same
    shape cli.py serves with (serving/pipeline.py).

    Single-device A/B work parity: this mode runs the same per-tick
    evict pass as the serial mode. The SHARDED pipelined mode does not
    process stale bits (its read dispatch carries an inert horizon), so
    a sharded A/B slightly favors this mode — read its speedup as a
    ceiling, not a measurement of equal work."""
    import numpy as np

    from traffic_classifier_sdn_tpu.serving.pipeline import (
        FeatureStage,
        ServePipeline,
        dispatch_read,
    )

    host_native = getattr(predict, "host_native", False)
    fs = (
        None if (args.shards >= 1 or host_native)
        else FeatureStage(args.capacity)
    )
    rendered = []

    def consume(job):
        job()

    pipe = ServePipeline(consume, depth=2).start()
    timings = {k: [] for k in ("ingest", "step", "dispatch", "tick")}
    n_parsed = 0
    t_wall0 = time.perf_counter()
    try:
        for ti, payload in enumerate(payloads):
            with pipe.host_stage():
                eng.mark_tick()
                t0 = time.perf_counter()
                n_parsed += eng.ingest_bytes(payload)
                t1 = time.perf_counter()
                eng.step()
                t2 = time.perf_counter()
                if args.shards >= 1:
                    outs = eng.tick_read_dispatch(now=eng.last_time)
                    n_flows = eng.num_flows()

                    def job(outs=outs, n_flows=n_flows):
                        ranked = eng.tick_read_finish(outs)
                        sample = eng.slot_metadata(
                            [s for s, *_ in ranked]
                        )
                        rows = [
                            (s, *sample[s], c)
                            for s, c, _fa, _ra in ranked if s in sample
                        ]
                        rendered.append((len(rows), n_flows))
                else:
                    # every tick, unconditionally — the A/B must pay
                    # identical per-tick work in both modes (the serial
                    # mode's evict stage is O(capacity) host work; an
                    # idle()-gated evict would let the pipelined mode
                    # skip it under load and report overlap it doesn't
                    # have). Safe here unlike cli: the 3600 s horizon
                    # releases nothing, so no render's slot metadata is
                    # ever at stake.
                    eng.evict_idle(now=eng.last_time, idle_seconds=3600)
                    read = dispatch_read(
                        eng, predict, params, args.table_rows, fs
                    )

                    def job(read=read):
                        ranked = read.rows()
                        # the serial mode's render half: slot metadata
                        # + row assembly, on the device stage like cli
                        sample = eng.slot_metadata(
                            slots=[s for s, *_ in ranked]
                        )
                        rows = [
                            (s, *sample[s], c)
                            for s, c, _fa, _ra in ranked if s in sample
                        ]
                        rendered.append((len(rows), read.n_flows))
                pipe.submit(job)
                t3 = time.perf_counter()
            timings["ingest"].append(t1 - t0)
            timings["step"].append(t2 - t1)
            timings["dispatch"].append(t3 - t2)
            timings["tick"].append(t3 - t0)
            print(
                f"# tick {ti}: host {(t3 - t0) * 1e3:.0f} ms "
                f"(queue {pipe._handoff.queued})",
                file=sys.stderr, flush=True,
            )
        pipe.shutdown(drain=True)
        pipe.raise_if_failed()
    finally:
        pipe.shutdown(drain=False)
    wall = time.perf_counter() - t_wall0
    for n_rows, _nf in rendered:
        assert n_rows <= args.table_rows
    p50 = {k: float(np.median(v)) for k, v in timings.items()}
    return {"timings": timings, "p50": p50, "wall_s": wall,
            "n_parsed": n_parsed, "pipeline_stats": pipe.stats(),
            "ticks_rendered": len(rendered)}


def _mode_summary(args, runs, n_flows_per_tick):
    """Aggregate one mode's repeats: median-of-repeats throughput (the
    robust center on a noisy shared host), pooled stage medians, and
    first-tick latency from the FIRST repeat (the only cold one)."""
    import numpy as np

    fps = [
        n_flows_per_tick * args.ticks / r["wall_s"] for r in runs
    ]
    pooled = {}
    for r in runs:
        for k, v in r["timings"].items():
            pooled.setdefault(k, []).extend(v)
    t0 = runs[0]["timings"]["tick"]
    steady = t0[1:] or t0
    out = {
        "serve_flows_per_sec": round(float(np.median(fps)), 1),
        "serve_flows_per_sec_per_repeat": [round(f, 1) for f in fps],
        "records_per_sec": round(
            sum(r["n_parsed"] for r in runs)
            / sum(r["wall_s"] for r in runs), 1
        ),
        "wall_s": round(sum(r["wall_s"] for r in runs), 3),
        "first_tick_ms": round(t0[0] * 1e3, 1),
        "steady_tick_p50_ms": round(float(np.median(steady)) * 1e3, 2),
        "stage_p50_ms": {
            k: round(float(np.median(v)) * 1e3, 2)
            for k, v in pooled.items()
        },
    }
    stats = [r["pipeline_stats"] for r in runs if r["pipeline_stats"]]
    if stats:
        host = sum(s["host_busy_s"] for s in stats)
        dev = sum(s["device_busy_s"] for s in stats)
        ov = sum(s["overlap_s"] for s in stats)
        out.update({
            "host_busy_s": round(host, 3),
            "device_busy_s": round(dev, 3),
            "overlap_s": round(ov, 3),
            "overlap_ratio": round(ov / dev, 3) if dev else 0.0,
            "ticks_coalesced": sum(s["ticks_coalesced"] for s in stats),
        })
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--capacity", type=int, default=1 << 20)
    ap.add_argument(
        "--flows-per-tick", type=int, default=0,
        help="synthetic conversations per tick (2 records each); "
        "0 = capacity/2 (the historical fill-the-table default). "
        "Decoupled from --capacity so the A/B can pin the ingest batch "
        "(e.g. 16384) while the full-table predict cost scales with "
        "capacity independently",
    )
    ap.add_argument("--ticks", type=int, default=5)
    ap.add_argument("--no-native", action="store_true")
    ap.add_argument(
        "--platform", choices=("cpu", "default"), default="cpu",
        help="cpu (safe anywhere) or default (real TPU when healthy)",
    )
    ap.add_argument("--table-rows", type=int, default=64)
    ap.add_argument(
        "--model", choices=("gnb", "forest", "knn"), default="gnb",
        help="predict stage: gnb (cheapest full-table predict; the CPU "
        "default), forest (the flagship 100-tree checkpoint), or knn "
        "(the KNeighbors checkpoint) — the latter two resolve through "
        "the serving path and honor TCSDN_FOREST_KERNEL / "
        "TCSDN_KNN_TOPK, so the raced kernels A/B directly in this "
        "bench",
    )
    ap.add_argument(
        "--shards", type=int, default=0,
        help="shard the flow table over an N-device mesh "
        "(parallel/table_sharded.py); on the cpu platform N virtual "
        "devices are forced, so --shards 8 --capacity 8388608 exercises "
        "the 2²³-flow sharded spine on one host",
    )
    ap.add_argument(
        "--pipeline", choices=("off", "on", "both"), default="off",
        help="serve-loop mode: off = serial chain (the historical "
        "bench), on = pipelined (serving/pipeline.py), both = A/B over "
        "identical payloads, one serve_pipeline_ab JSON object",
    )
    ap.add_argument(
        "--repeat", type=int, default=1,
        help="repeat the measurement N times (modes interleaved per "
        "repeat, fresh payload chunk each, engines reused so later "
        "repeats measure the saturated steady state) and report "
        "median-of-repeats throughput — the noisy-neighbor antidote "
        "for shared CI hosts",
    )
    ap.add_argument(
        "--warmup", action="store_true",
        help="AOT-compile the serving programs before timing "
        "(serving/warmup.py) — required for a clean A/B (the modes "
        "share jit caches) and for first_tick_ms to mean warm latency",
    )
    args = ap.parse_args()

    if args.platform == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        if args.shards >= 1:
            import re

            flags = re.sub(
                r"--?xla_force_host_platform_device_count=\S*", "",
                os.environ.get("XLA_FLAGS", ""),
            )
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.shards}"
            ).strip()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import numpy as np

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows
    from traffic_classifier_sdn_tpu.native import engine as native_engine

    native = (not args.no_native) and native_engine.available()
    cap = args.capacity
    # two directions share one slot; the default fills half the table
    n_flows = args.flows_per_tick or cap // 2
    if n_flows > cap:
        sys.exit("--flows-per-tick exceeds --capacity (every "
                 "conversation needs a slot)")
    syn = SyntheticFlows(n_flows=n_flows, seed=0)

    # init-first liveness: a wedged worker hangs the first device call,
    # and a silent run is indistinguishable from a slow compile
    print("# initializing devices", file=sys.stderr, flush=True)
    print(f"# devices: {jax.devices()}", file=sys.stderr, flush=True)

    predict, params, raw_fn = _build_model(args)

    print(
        f"# generating {args.repeat} × {args.ticks} ticks × "
        f"{2 * n_flows} records (capacity {cap}, native={native})",
        file=sys.stderr, flush=True,
    )
    payload_chunks = [
        [syn.tick_bytes() for _ in range(args.ticks)]
        for _ in range(args.repeat)
    ]
    total_records = sum(p.count(b"\n") for p in payload_chunks[0])

    modes = (
        ("serial", "pipelined") if args.pipeline == "both"
        else (("pipelined",) if args.pipeline == "on" else ("serial",))
    )
    if args.pipeline == "both" and not args.warmup:
        print(
            "# NOTE: A/B without --warmup — the serial mode runs first "
            "and pays every cold compile the pipelined mode then "
            "inherits; pass --warmup for a clean comparison",
            file=sys.stderr, flush=True,
        )

    engines = {
        mode: _make_engine(args, native, raw_fn, params)
        for mode in modes
    }
    if args.warmup:
        from traffic_classifier_sdn_tpu.serving.warmup import (
            warmup_serving,
        )

        t0 = time.perf_counter()
        stats = warmup_serving(
            engines[modes[0]], predict, params,
            table_rows=args.table_rows,
            idle_timeout=3600 if args.shards < 1 else None,
        )
        print(
            f"# warmup: {len(stats['warmed'])} programs in "
            f"{time.perf_counter() - t0:.2f}s",
            file=sys.stderr, flush=True,
        )
    runs: dict = {mode: [] for mode in modes}
    for rep, chunk in enumerate(payload_chunks):
        for mode in modes:
            print(f"# repeat {rep} mode: {mode}",
                  file=sys.stderr, flush=True)
            run = _run_serial if mode == "serial" else _run_pipelined
            runs[mode].append(
                run(args, engines[mode], predict, params, chunk)
            )
    results = {
        mode: _mode_summary(args, runs[mode], n_flows)
        for mode in modes
    }

    eng = engines[modes[-1]]
    # Per-tick host->device wire bytes actually moved for the update
    # batches (padded flow_table.pack_wire matrices, counted by the
    # engine) and the measured link bandwidth — on a slow device link the
    # transfer can bound the tick; a local PCIe host moves the same bytes
    # in single-digit ms. The bandwidth probe only means "device link"
    # off the cpu platform, so it is omitted there (a cpu-platform probe
    # would time a host memcpy).
    wire_mb = eng.wire_bytes / (args.ticks * args.repeat) / 1e6
    link_mb_s = None
    if jax.devices()[0].platform != "cpu":
        # sync by scalar fetch: on this rig's tunnel block_until_ready
        # returns without waiting, which would time dispatch, not transfer
        probe_mb = (4 << 20) / 1e6
        blob = np.ones(4 << 20, np.uint8)
        float(np.asarray(jnp.sum(jnp.asarray(blob))))  # warm
        bw = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(np.asarray(jnp.sum(jnp.asarray(blob))))
            bw.append(probe_mb / (time.perf_counter() - t0))
        link_mb_s = float(np.median(bw))

    common = {
        "capacity": cap,
        "tracked_flows": eng.num_flows(),
        "records_per_tick": total_records // args.ticks,
        "update_wire_mb_per_tick": round(wire_mb, 1),
        **(
            {"host_to_device_mb_per_sec": round(link_mb_s, 1)}
            if link_mb_s is not None else {}
        ),
        "native_ingest": native,
        **({"shards": args.shards} if args.shards >= 1 else {}),
        "platform": jax.devices()[0].platform,
        "predict_model": args.model,
        "table_rows_rendered": args.table_rows,
        "warmup": args.warmup,
    }

    if args.pipeline == "both":
        s = results["serial"]["serve_flows_per_sec"]
        p = results["pipelined"]["serve_flows_per_sec"]
        out = {
            "metric": "serve_pipeline_ab",
            "serial": results["serial"],
            "pipelined": results["pipelined"],
            "speedup_flows_per_sec": round(p / s, 3) if s else None,
            **common,
        }
    else:
        mode = modes[0]
        r = results[mode]
        out = {
            "metric": "serve_tick_p50_ms_at_capacity",
            "value": r["stage_p50_ms"]["tick"],
            "unit": "ms",
            "mode": mode,
            **r,
            **common,
        }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
