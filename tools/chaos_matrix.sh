#!/usr/bin/env bash
# Chaos matrix: sweep the fault-site × schedule matrix with distinct
# seeds and fail on ANY unrecovered scenario.
#
# tests/test_chaos.py is deterministic given TCSDN_CHAOS_SEED: count
# schedules fire identically at every seed, while probability schedules
# (`FaultRule(p=...)`) draw from the plan's seeded RNG — so sweeping the
# seed exercises different crash subsets of the same scenarios. The
# recovery invariants (rollback + replay convergence, no garbage
# records, backoff ladder, fallback gating) must hold for EVERY seed.
#
# Usage: tools/chaos_matrix.sh [seed ...]   (default: 0 1 2 7 1337)
#
# Each seed runs the whole chaos suite once per site group, so a failure
# report names both the seed and the seam that broke. Scenario-level
# `slow` marks keep anything long out of the tier-1 budget; this script
# itself is the full sweep (CI tier-1 runs the suite once at seed 0).
#
# Coverage map: graftlint's `fault-site-registry` rule (see
# docs/STATIC_ANALYSIS.md) statically guarantees that every injection
# seam uses a site registered in utils.faults.SITES, that every
# registered site is live, and that tests/test_chaos.py references it —
# so the site groups below cannot silently drift out of sync with the
# seams this sweep is supposed to cover. If you add a site, the linter
# fails tier-1 until the registry, a chaos test, and (if it is a new
# seam family) a group below all exist.
#
# Lock-order probing: every group runs with TCSDN_LOCKTRACE=1, so the
# locktrace runtime witness (utils/locktrace.py) wraps every project
# lock and asserts acquisition-order acyclicity across EVERY chaos
# schedule this sweep drives — each crash/recovery interleaving doubles
# as ordering evidence cross-checked against the static lock-order
# graph (docs/artifacts/lock_order_graph.json).
#
# Sync-budget probing: every group also runs with TCSDN_SYNCGUARD=1,
# arming the syncguard runtime witness (utils/syncguard.py) in every
# test module (the tier-1 fixture only arms the five serve suites):
# each chaos schedule's host↔device conversions are counted by site
# and checked live against the static hot-path sync budget
# (docs/artifacts/hot_path_sync_budget.json) — a recovery path that
# sneaks an unbudgeted sync into a hot span fails the sweep.

set -euo pipefail
cd "$(dirname "$0")/.."

SEEDS=("$@")
if [ ${#SEEDS[@]} -eq 0 ]; then
  SEEDS=(0 1 2 7 1337)
fi

# site groups: one -k filter per durability seam, so the matrix is
# site × schedule (the tests under each filter carry both count- and
# probability-scheduled plans)
GROUPS_KEYS=(
  "checkpoint:kill_mid_write or rename_fault or probabilistic_save or restore_fault or train_ckpt or train_state"
  "collector:truncated_chunk or monitor_killed"
  "supervisor:spawn_failure"
  "native:native_load or native_checkpoint"
  "pipeline:pipeline_handoff or pipeline_coalesce"
  "degrade:degrade_dispatch or degrade_probe"
  "drift:drift_window or retrain_fit or promote_swap or promote_rollback or drift_loop"
  "dirty:serve_dirty_mask or serve_label_cache"
  "fanin:fanin_put or fanin_source_dead"
  "region:region_source_dead or region_dirty_mask or region_fanin_put"
  "native_ingest:native_parse"
  "obs:obs_stamp or sigusr1"
  "obsdev:perf_ring or profiler"
  "openset:openset_score or openset_calibrate or openset_rebase or openset_probabilistic"
  "actuation:actuation_send or actuation_barrier or actuation_retract or actuation_probabilistic"
)

fail=0
for seed in "${SEEDS[@]}"; do
  for entry in "${GROUPS_KEYS[@]}"; do
    site="${entry%%:*}"
    kexpr="${entry#*:}"
    echo "=== chaos seed=${seed} site=${site}"
    if ! TCSDN_CHAOS_SEED="$seed" TCSDN_LOCKTRACE=1 TCSDN_SYNCGUARD=1 \
        JAX_PLATFORMS=cpu \
        python -m pytest tests/test_chaos.py -q -m chaos -k "$kexpr" \
        -p no:cacheprovider; then
      echo "!!! UNRECOVERED: seed=${seed} site=${site}" >&2
      fail=1
    fi
  done
done

# scenario campaign group: the composed adversarial timelines
# (tests/test_scenarios.py — flash crowd, flap storm, reset storm,
# novel wave, mass eviction, queue flood, device wedge, label flap
# storm vs the actuation hysteresis) under the same
# locktrace witness. Each scenario drives the REAL fan-in pumps ×
# serve loop × ladder threads, so its schedules double as lock-order
# evidence; one sweep suffices — the timelines are deterministic on
# the virtual clock, only thread interleavings vary.
echo "=== chaos site=scenario (campaign timelines)"
if ! TCSDN_LOCKTRACE=1 TCSDN_SYNCGUARD=1 JAX_PLATFORMS=cpu \
    python -m pytest tests/test_scenarios.py -q \
    -p no:cacheprovider; then
  echo "!!! UNRECOVERED: site=scenario" >&2
  fail=1
fi

if [ "$fail" -ne 0 ]; then
  echo "chaos matrix: FAILURES (see above)" >&2
  exit 1
fi
echo "chaos matrix: all scenarios recovered (seeds: ${SEEDS[*]})"
