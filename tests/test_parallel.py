"""Sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4c):
sharded results must equal the single-device reference exactly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from traffic_classifier_sdn_tpu.io import sklearn_import as ski
from traffic_classifier_sdn_tpu.models import forest, gnb, knn, logreg
from traffic_classifier_sdn_tpu.parallel import (
    forest_sharded,
    knn_sharded,
    mesh as meshlib,
    predict as par_predict,
)


@pytest.fixture(scope="module")
def X256(flow_dataset):
    rng = np.random.RandomState(0)
    idx = rng.choice(flow_dataset.n, size=256, replace=False)
    return jnp.asarray(flow_dataset.X[idx], jnp.float32)


def test_device_count():
    assert len(jax.devices()) == 8, "conftest must provision 8 CPU devices"


def test_mesh_shapes():
    m = meshlib.make_mesh()
    assert m.devices.shape == (8, 1)
    m2 = meshlib.make_mesh(n_data=4, n_state=2)
    assert m2.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        meshlib.make_mesh(n_data=3, n_state=2)


@pytest.mark.parametrize("model_name,mod", [("logreg", logreg), ("gnb", gnb)])
def test_data_parallel_predict_matches(
    reference_models_dir, X256, model_name, mod
):
    d = ski.IMPORTERS[model_name](
        f"{reference_models_dir}/{ski.REFERENCE_CHECKPOINTS[model_name]}"
    )
    params = mod.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(mod.predict(params, X256))
    m = meshlib.make_mesh()  # 8-way data parallel
    dp = par_predict.data_parallel(m, mod.predict)
    got = np.asarray(dp(params, X256))
    np.testing.assert_array_equal(got, want)


def test_knn_state_sharded_matches(reference_models_dir, X256):
    d = ski.import_knn(f"{reference_models_dir}/KNeighbors")
    single = knn.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(knn.predict(single, X256))

    m = meshlib.make_mesh(n_data=1, n_state=8)
    dpad = knn_sharded.pad_corpus(d, 8)
    params = knn.from_numpy(dpad, dtype=jnp.float32)
    fn = knn_sharded.sharded_predict(m, params, pad_mask=dpad.get("pad_mask"))
    got = np.asarray(fn(X256))
    np.testing.assert_array_equal(got, want)


def test_forest_tree_sharded_matches(reference_models_dir, X256):
    d = ski.import_forest(f"{reference_models_dir}/RandomForestClassifier")
    single = forest.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(forest.predict(single, X256))

    m = meshlib.make_mesh(n_data=1, n_state=8)
    dpad = forest_sharded.pad_trees(d, 8)
    params = forest.from_numpy(dpad, dtype=jnp.float32)
    fn = forest_sharded.sharded_predict(
        m, params, n_real_trees=dpad.get("n_real_trees", 100)
    )
    got = np.asarray(fn(X256))
    np.testing.assert_array_equal(got, want)


def test_forest_tree_sharded_gemm_matches(reference_models_dir, X256):
    """The MXU GEMM local stage (the serving path's formulation, per
    shard) must predict like the single-device GEMM path and the gather
    traversal on reference rows — tree-leading operand sharding with
    psum'd distribution sums."""
    from traffic_classifier_sdn_tpu.ops import tree_gemm

    d = ski.import_forest(f"{reference_models_dir}/RandomForestClassifier")
    want = np.asarray(
        tree_gemm.predict(tree_gemm.compile_forest(d), X256)
    )
    single = forest.from_numpy(d, dtype=jnp.float32)
    want_gather = np.asarray(forest.predict(single, X256))
    np.testing.assert_array_equal(want, want_gather)

    m = meshlib.make_mesh(n_data=1, n_state=8)
    dpad = forest_sharded.pad_trees(d, 8)
    fn = forest_sharded.gemm_sharded_predict(m, dpad)
    got = np.asarray(fn(X256))
    np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="pad_trees"):
        forest_sharded.gemm_sharded_predict(m, d)  # 100 trees, 8 shards


def test_svc_state_sharded_matches(reference_models_dir, flow_dataset):
    """SV-sharded SVC must reproduce the single-device predict exactly,
    including the hi/lo precise mode on raw-scale features."""
    from traffic_classifier_sdn_tpu.models import svc
    from traffic_classifier_sdn_tpu.parallel import svc_sharded

    d = ski.import_svc(f"{reference_models_dir}/SVC")
    rng = np.random.RandomState(1)
    idx = rng.choice(flow_dataset.n, size=256, replace=False)
    X64 = flow_dataset.X[idx]
    X_hi, X_lo = svc.split_hilo(X64)

    single = svc.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(svc.predict(single, X_hi, X_lo))

    m = meshlib.make_mesh(n_data=1, n_state=8)
    dpad = svc_sharded.pad_support(d, 8)
    params = svc.from_numpy(dpad, dtype=jnp.float32)
    fn = svc_sharded.sharded_predict(m, params, precise=True)
    got = np.asarray(fn(X_hi, X_lo))
    np.testing.assert_array_equal(got, want)

    # plain (non-precise) mode also agrees with its single-device twin
    want_plain = np.asarray(svc.predict(single, X_hi))
    fn_plain = svc_sharded.sharded_predict(m, params)
    np.testing.assert_array_equal(np.asarray(fn_plain(X_hi)), want_plain)


def test_svc_sharded_pad_is_noop_when_aligned(reference_models_dir):
    from traffic_classifier_sdn_tpu.parallel import svc_sharded

    d = ski.import_svc(f"{reference_models_dir}/SVC")
    S = d["support_vectors"].shape[0]
    assert svc_sharded.pad_support(d, 1)["support_vectors"].shape[0] == S
    dpad = svc_sharded.pad_support(d, 8)
    assert dpad["support_vectors"].shape[0] % 8 == 0
    assert np.all(dpad["dual_coef"][:, S:] == 0)


def test_distributed_gnb_fit_matches_single_device(flow_dataset):
    """Batch-sharded GNB moments must reproduce the single-device fit
    (same math, reductions merely distributed)."""
    from traffic_classifier_sdn_tpu.models import gnb as gnb_model
    from traffic_classifier_sdn_tpu.train import gnb as gnb_train
    from traffic_classifier_sdn_tpu.train.distributed import fit_gnb

    n_classes = len(flow_dataset.classes)
    single = gnb_train.fit(flow_dataset.X, flow_dataset.y, n_classes)
    m = meshlib.make_mesh()  # 8-way data parallel
    dist = fit_gnb(m, flow_dataset.X, flow_dataset.y, n_classes)
    np.testing.assert_allclose(
        np.asarray(dist.theta), np.asarray(single.theta), rtol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(dist.inv_var), np.asarray(single.inv_var), rtol=1e-8
    )
    np.testing.assert_allclose(
        np.asarray(dist.log_const), np.asarray(single.log_const), rtol=1e-8
    )
    X = jnp.asarray(flow_dataset.X[:512], jnp.float64)
    np.testing.assert_array_equal(
        np.asarray(gnb_model.predict(dist, X)),
        np.asarray(gnb_model.predict(single, X)),
    )


def test_distributed_kmeans_fit_matches_single_device(flow_dataset):
    from traffic_classifier_sdn_tpu.models import kmeans as kmeans_model
    from traffic_classifier_sdn_tpu.train import kmeans as kmeans_train
    from traffic_classifier_sdn_tpu.train.distributed import fit_kmeans

    X = flow_dataset.X[:2048]
    single, in_single = kmeans_train.fit(X, k=4, n_init=4, n_iter=25, seed=7)
    m = meshlib.make_mesh()
    dist, in_dist = fit_kmeans(m, X, k=4, n_init=4, n_iter=25, seed=7)
    assert in_dist == pytest.approx(in_single, rel=1e-5)
    Xq = jnp.asarray(X[:512], jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(kmeans_model.predict(dist, Xq)),
        np.asarray(kmeans_model.predict(single, Xq)),
    )


def test_distributed_gnb_fit_absent_class_matches_single_device(flow_dataset):
    """A batch missing one class must not NaN-poison the others: the
    smoothing term comes from the masked rows, matching train/gnb.fit."""
    from traffic_classifier_sdn_tpu.train import gnb as gnb_train
    from traffic_classifier_sdn_tpu.train.distributed import fit_gnb

    n_classes = len(flow_dataset.classes) + 1  # one class has no rows
    single = gnb_train.fit(flow_dataset.X, flow_dataset.y, n_classes)
    m = meshlib.make_mesh()
    dist = fit_gnb(m, flow_dataset.X, flow_dataset.y, n_classes)
    present = np.arange(n_classes - 1)
    assert np.all(np.isfinite(np.asarray(dist.inv_var)[present]))
    np.testing.assert_allclose(
        np.asarray(dist.inv_var)[present],
        np.asarray(single.inv_var)[present],
        rtol=1e-8,
    )


def test_knn_ring_merge_matches_single_device(reference_models_dir, X256):
    """The ppermute ring merge must equal both the all_gather merge and
    the single-device predict exactly, ties included."""
    d = ski.import_knn(f"{reference_models_dir}/KNeighbors")
    single = knn.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(knn.predict(single, X256))

    m = meshlib.make_mesh(n_data=1, n_state=8)
    dpad = knn_sharded.pad_corpus(d, 8)
    params = knn.from_numpy(dpad, dtype=jnp.float32)
    ring = knn_sharded.ring_predict(m, params, pad_mask=dpad.get("pad_mask"))
    got = np.asarray(ring(X256))
    np.testing.assert_array_equal(got, want)
    # the log-depth tournament merge must agree bit-for-bit too
    tour = knn_sharded.tournament_predict(
        m, params, pad_mask=dpad.get("pad_mask")
    )
    np.testing.assert_array_equal(np.asarray(tour(X256)), want)


def test_bench_sharded_smoke(tmp_path, reference_models_dir):
    """tools/bench_sharded.py runs end to end on the virtual mesh and
    emits the full scaling matrix (collective-shape regression canary).
    Needs the reference checkpoint tree (the bench loads the KNN/forest/
    SVC pickles); hosts without it skip — the multi-device scaling
    evidence is docs/artifacts/sharded_scaling_multidevice.json from the
    8-device dryrun."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "bench_sharded.py"),
         "--batch", "256", "--repeats", "1"],
        capture_output=True, text=True, timeout=240, cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-500:]
    out = json.loads(r.stdout.splitlines()[-1])
    for shard in ("state_1", "state_2", "state_8"):
        for key in ("knn_allgather_ms", "knn_ring_ms", "forest_ms",
                    "svc_ms"):
            assert out["results"][shard][key] > 0
    assert out["results"]["data_8"]["forest_dp_ms"] > 0


def test_knn_sharded_merges_with_padding_heavy_shards():
    """A corpus so small that most shards hold only +inf-distance padding
    (local top-k emits -inf candidates) must still merge exactly: the
    rank merge's value reconstruction must not turn -inf into NaN."""
    from traffic_classifier_sdn_tpu.models import knn
    from traffic_classifier_sdn_tpu.parallel import knn_sharded

    rng = np.random.RandomState(3)
    d = {
        "fit_X": rng.rand(8, 12) * 100.0,  # 8 rows over 8 shards, k=5
        "y": rng.randint(0, 6, 8).astype(np.int32),
        "n_neighbors": 5,
        "classes": np.arange(6),
    }
    single = knn.from_numpy(dict(d), dtype=jnp.float32)
    Xq = jnp.asarray(rng.rand(64, 12) * 100.0, jnp.float32)
    want = np.asarray(knn.predict(single, Xq))

    m = meshlib.make_mesh(n_data=1, n_state=8)
    dpad = knn_sharded.pad_corpus(dict(d), 8)
    params = knn.from_numpy(dpad, dtype=jnp.float32)
    for builder in (
        knn_sharded.sharded_predict,
        knn_sharded.ring_predict,
        knn_sharded.tournament_predict,
    ):
        fn = builder(m, params, pad_mask=dpad.get("pad_mask"))
        got = np.asarray(fn(Xq))
        np.testing.assert_array_equal(got, want)


def test_knn_merge_unpacked_fallback(reference_models_dir, X256, monkeypatch):
    """Corpora with rows × classes ≥ 2^31 can't pack labels into the int32
    index payload; the ring and tournament must fall back to a separate
    label payload and still merge exactly."""
    from traffic_classifier_sdn_tpu.parallel import knn_sharded

    monkeypatch.setattr(knn_sharded, "_packable", lambda params: False)
    d = ski.import_knn(f"{reference_models_dir}/KNeighbors")
    single = knn.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(knn.predict(single, X256))

    m = meshlib.make_mesh(n_data=1, n_state=8)
    dpad = knn_sharded.pad_corpus(d, 8)
    params = knn.from_numpy(dpad, dtype=jnp.float32)
    for builder in (knn_sharded.ring_predict,
                    knn_sharded.tournament_predict):
        fn = builder(m, params, pad_mask=dpad.get("pad_mask"))
        np.testing.assert_array_equal(np.asarray(fn(X256)), want)


def test_knn_ring_merge_non_power_of_two_shards(reference_models_dir, X256):
    """The ring merge must stay exact on shard counts with no power-of-two
    structure (the tournament rejects these; the ring must not)."""
    d = ski.import_knn(f"{reference_models_dir}/KNeighbors")
    single = knn.from_numpy(d, dtype=jnp.float32)
    want = np.asarray(knn.predict(single, X256))

    m = meshlib.make_mesh(n_data=1, n_state=5, devices=jax.devices()[:5])
    dpad = knn_sharded.pad_corpus(d, 5)
    params = knn.from_numpy(dpad, dtype=jnp.float32)
    ring = knn_sharded.ring_predict(m, params, pad_mask=dpad.get("pad_mask"))
    np.testing.assert_array_equal(np.asarray(ring(X256)), want)
    with pytest.raises(ValueError, match="power-of-two"):
        knn_sharded.tournament_predict(m, params)


def test_merge_topk_property_vs_numpy_sort():
    """Adversarial unit check of the sort-free rank merge: random blocks
    with heavy value ties (quantized values), -inf padding candidates,
    and unique indices must merge bit-identically to a NumPy
    lexicographic (value desc, index asc) sort of the union."""
    from traffic_classifier_sdn_tpu.parallel.knn_sharded import _merge_topk

    rng = np.random.RandomState(5)
    k = 5
    for trial in range(20):
        N = 7
        # quantized values force cross-block ties; some -inf padding
        av = np.round(rng.rand(N, k) * 4) / 4.0
        bv = np.round(rng.rand(N, k) * 4) / 4.0
        av[rng.rand(N, k) < 0.15] = -np.inf
        bv[rng.rand(N, k) < 0.15] = -np.inf
        # unique indices across the union; ints ride as the tie-break key
        perm = np.stack([rng.permutation(100)[: 2 * k] for _ in range(N)])
        ai, bi = perm[:, :k], perm[:, k:]

        def order(v, i):
            # each block must itself be sorted (value desc, index asc)
            o = np.lexsort((i, -v), axis=-1)
            return np.take_along_axis(v, o, 1), np.take_along_axis(i, o, 1)

        av, ai = order(av, ai)
        bv, bi = order(bv, bi)
        mv, mi, _ = _merge_topk(
            jnp.asarray(av, jnp.float32), jnp.asarray(ai, jnp.int32),
            jnp.asarray(bv, jnp.float32), jnp.asarray(bi, jnp.int32), k,
        )
        uv = np.concatenate([av, bv], axis=1)
        ui = np.concatenate([ai, bi], axis=1)
        o = np.lexsort((ui, -uv), axis=-1)[:, :k]
        np.testing.assert_array_equal(
            np.asarray(mv), np.take_along_axis(uv, o, 1).astype(np.float32),
            err_msg=f"values trial {trial}",
        )
        np.testing.assert_array_equal(
            np.asarray(mi), np.take_along_axis(ui, o, 1),
            err_msg=f"indices trial {trial}",
        )


def test_distributed_forest_fit_bit_identical_to_single_device(flow_dataset):
    """Row-sharded forest training (psum'd per-level histograms) must
    produce the EXACT same trees as the single-device fit: counts are
    integer-valued f32 and the randomness derives from the replicated
    key over the global row count."""
    from traffic_classifier_sdn_tpu.models import forest as forest_model
    from traffic_classifier_sdn_tpu.train import forest as forest_train
    from traffic_classifier_sdn_tpu.train.distributed import fit_forest

    X = flow_dataset.X[:1027]  # odd count: exercises sentinel padding
    y = flow_dataset.y[:1027]
    n_classes = len(flow_dataset.classes)
    kw = dict(n_trees=4, max_depth=5, n_bins=32, seed=3)
    single = forest_train.fit(X, y, n_classes, **kw)
    m = meshlib.make_mesh()  # 8-way data parallel
    dist = fit_forest(m, X, y, n_classes, **kw)
    for name in ("left", "right", "feature", "threshold", "values"):
        np.testing.assert_array_equal(
            np.asarray(getattr(dist, name)),
            np.asarray(getattr(single, name)),
            err_msg=name,
        )
    # and the trees actually classify
    Xq = jnp.asarray(X[:256], jnp.float32)
    acc = (np.asarray(forest_model.predict(dist, Xq)) == y[:256]).mean()
    assert acc > 0.9


def test_distributed_svc_fit_bit_identical_to_single_device(flow_dataset):
    """Pair-sharded SVC training (15 independent ovo QPs over the state
    axis) must produce the exact same Params as the single-device fit —
    same solver per pair, no cross-pair coupling."""
    from traffic_classifier_sdn_tpu.train import svc as svc_train
    from traffic_classifier_sdn_tpu.train.distributed import fit_svc

    rng = np.random.RandomState(0)
    idx = rng.choice(flow_dataset.n, size=512, replace=False)
    X, y = flow_dataset.X[idx], flow_dataset.y[idx]
    n_classes = len(flow_dataset.classes)
    kw = dict(n_iters=120, power_iters=12)
    single = svc_train.fit(X, y, n_classes, **kw)
    m = meshlib.make_mesh(n_data=1, n_state=8)
    dist = fit_svc(m, X, y, n_classes, **kw)
    for name in ("sv_hi", "sv_lo", "pair_coef", "intercept",
                 "vote_i", "vote_j", "gamma"):
        np.testing.assert_array_equal(
            np.asarray(getattr(dist, name)),
            np.asarray(getattr(single, name)),
            err_msg=name,
        )
