"""Fleet mode (serving/fleet.py + DriftController follow_rotation).

The fleet contract, each piece pinned here:

- ``partition_sources`` hands every member a contiguous balanced span
  covering all sources exactly once;
- promotion PROPAGATES through the shared rotation: a leader's
  drift-triggered promotion stages a seq-numbered member that a
  follower (``follow_rotation=True``) adopts as its own candidate and
  promotes only through its OWN parity-gated probes — end-to-end on an
  injectable (virtual) clock;
- a follower that REJECTS an adopted candidate never discards the
  shared rotation member (it may be the peer's promoted model) and
  never re-adopts the same seq;
- the ``/healthz`` roster-of-rosters aggregator folds N real member
  exposition servers into one scrape target: member health conjunction,
  per-source rosters annotated with the member index, drift state per
  member, 200/503 semantics.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from traffic_classifier_sdn_tpu.models import gnb
from traffic_classifier_sdn_tpu.obs.exposition import (
    ExpositionServer,
    HealthState,
)
from traffic_classifier_sdn_tpu.serving import fleet, retrain
from traffic_classifier_sdn_tpu.serving.drift import (
    CANDIDATE,
    PROMOTED,
    RETRAINING,
    STEADY,
    DriftController,
    DriftGate,
)
from traffic_classifier_sdn_tpu.utils.metrics import Metrics


# ---------------------------------------------------------------------------
# harness (the test_drift.py teacher/stream pair, fleet-sized)
# ---------------------------------------------------------------------------


def _teacher(params, X):
    return (np.asarray(X)[:, 0] > 500.0).astype(np.int32)


def _batch(lo, hi, n=16, seed=0):
    rng = np.random.RandomState(seed)
    X = np.zeros((n, 12), np.float32)
    X[: n // 2, 0] = lo * (1 + 0.01 * rng.rand(n // 2))
    X[n // 2:, 0] = hi * (1 + 0.01 * rng.rand(n - n // 2))
    X[:, 1] = 1.0
    return X


def _boot_params():
    return gnb.from_numpy({
        "theta": np.asarray(
            [[10.0] * 12, [1000.0] * 12], dtype=np.float64
        ),
        "var": np.ones((2, 12), np.float64),
        "class_prior": np.full(2, 0.5),
    })


class _Clock:
    """Injectable monotonic clock — the virtual time every controller
    in the fleet shares (retrain deadlines and status ages are exact,
    no wall-clock sleeps in the state machine)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _member(shared_dir, gate, clock, **kw):
    kw.setdefault("window", 3)
    kw.setdefault("threshold", 3.0)
    kw.setdefault("trips", 2)
    kw.setdefault("calibration_windows", 2)
    kw.setdefault("probe_successes", 2)
    kw.setdefault("min_retrain_rows", 16)
    kw.setdefault("boot_params", _boot_params())
    return DriftController(
        gate, family="gnb", classes=("ping", "voice"),
        directory=str(shared_dir), clock=clock, **kw,
    )


def _drive(gate, ctl, i, shifted):
    lo, hi = (100.0, 10000.0) if shifted else (10.0, 1000.0)
    labels = gate(None, _batch(lo, hi, seed=i))
    ctl.poll()
    return labels


def _wait_retrain(ctl, timeout=90.0):
    deadline = time.monotonic() + timeout
    while ctl._retrainer.poll() == retrain.RUNNING:
        if time.monotonic() > deadline:
            pytest.fail("background retrain never finished")
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# partition_sources
# ---------------------------------------------------------------------------


def test_partition_sources_balanced_and_covering():
    spans = fleet.partition_sources(10, 3)
    assert spans == [(0, 4), (4, 3), (7, 3)]
    # every source exactly once, in order
    covered = [s for start, n in spans for s in range(start, start + n)]
    assert covered == list(range(10))
    # balance: no member carries more than one extra source
    counts = [n for _, n in spans]
    assert max(counts) - min(counts) <= 1


def test_partition_sources_degenerate_shapes():
    assert fleet.partition_sources(2, 4) == [
        (0, 1), (1, 1), (2, 0), (2, 0)
    ]
    assert fleet.partition_sources(0, 2) == [(0, 0), (0, 0)]
    with pytest.raises(ValueError):
        fleet.partition_sources(4, 0)


# ---------------------------------------------------------------------------
# promotion propagation through the shared rotation (virtual clock)
# ---------------------------------------------------------------------------


def test_fleet_promotion_propagates_through_parity_gate(tmp_path):
    """THE fleet acceptance scenario: two members share one rotation;
    the leader's drift trip retrains and promotes seq 1; the follower
    adopts that member as its candidate and promotes it through its own
    parity probes — both gates end up swapped onto the SAME rotation
    member, with exactly one retrain run fleet-wide."""
    shared = tmp_path / "rotation"
    clock = _Clock()
    m_lead, m_follow = Metrics(), Metrics()
    lead_gate = DriftGate(_teacher)
    follow_gate = DriftGate(_teacher)
    leader = _member(shared, lead_gate, clock, metrics=m_lead)
    follower = _member(
        shared, follow_gate, clock, metrics=m_follow,
        follow_rotation=True,
    )
    try:
        # leader alone sees the shift and walks the full loop
        i = 0
        while leader.state != PROMOTED and i < 200:
            i += 1
            clock.advance(1.0)
            _drive(lead_gate, leader, i, shifted=i > 12)
            if leader.state == RETRAINING:
                _wait_retrain(leader)
        assert leader.state == PROMOTED
        assert m_lead.counters["promotions"] == 1
        members = retrain.list_candidates(str(shared))
        assert members[0][0] >= 1  # the retrained member, behind seq 0
        promoted_path = members[0][1]

        # follower: steady traffic so far, now polls on the SHIFTED
        # stream — it must adopt the leader's member (never retrain)
        # and promote only after its own probes agree
        seen = []
        j = 1000
        while follower.state != PROMOTED and j < 1200:
            j += 1
            clock.advance(1.0)
            _drive(follow_gate, follower, j, shifted=True)
            if not seen or seen[-1] != follower.state:
                seen.append(follower.state)
        assert follower.state == PROMOTED
        assert CANDIDATE in seen  # adopted, then probed — never skipped
        assert RETRAINING not in seen  # propagation, not a second fit
        assert "retrain_runs" not in m_follow.counters
        assert m_follow.counters["promotions"] == 1
        assert follow_gate.swapped and lead_gate.swapped
        # both serve the promoted member's labels on shifted traffic
        X = _batch(100.0, 10000.0, seed=9999)
        np.testing.assert_array_equal(
            np.asarray(follow_gate(None, X)), _teacher(None, X)
        )
        # the shared member survived both promotions
        assert os.path.isdir(promoted_path)
    finally:
        leader.close()
        follower.close()


def test_follower_rejection_keeps_shared_member(tmp_path):
    """A follower whose probes REJECT the adopted candidate must not
    discard the shared rotation member (it belongs to the peer — maybe
    as its promoted model) and must not re-adopt the same seq on later
    polls."""
    shared = tmp_path / "rotation"
    clock = _Clock()

    class Disagree:
        """A candidate build whose predict inverts the teacher —
        parity can never pass."""

        def __call__(self, params, X):
            return 1 - _teacher(params, X)

    gate = DriftGate(_teacher)
    # boot FIRST (seeds seq 0, so _promoted_seq anchors below the
    # member a peer stages next) ...
    follower = _member(
        shared, gate, clock, follow_rotation=True,
        candidate_max_failures=2,
        build_serving=lambda params: (Disagree(), None),
    )
    # ... THEN a peer stages seq 1 into the shared rotation
    staged = retrain.save_candidate(
        str(shared), 1, "gnb", _boot_params(), ("ping", "voice")
    )
    try:
        states = []
        for i in range(1, 40):
            clock.advance(1.0)
            _drive(gate, follower, i, shifted=False)
            states.append(follower.state)
            if follower.state == STEADY and CANDIDATE in states:
                break
        assert CANDIDATE in states  # it DID adopt seq 1
        assert follower.state == STEADY  # ...and rejected it
        assert not gate.swapped
        assert os.path.isdir(staged)  # the peer's member survives
        # no re-adoption of the judged seq: more polls stay STEADY
        for i in range(100, 110):
            clock.advance(1.0)
            _drive(gate, follower, i, shifted=False)
            assert follower.state == STEADY
    finally:
        follower.close()


# ---------------------------------------------------------------------------
# the /healthz roster-of-rosters aggregator
# ---------------------------------------------------------------------------


def _scrape(url):
    try:
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_aggregator_merges_member_rosters(tmp_path):
    """Two REAL member exposition servers → one aggregator scrape:
    member health conjunction, per-source roster rows annotated with
    the member index, drift states surfaced per member."""
    clock = _Clock()
    clock.t = 100.0
    h0 = HealthState(clock=clock, max_tick_age_s=30.0)
    h1 = HealthState(clock=clock, max_tick_age_s=30.0)
    h0.tick()
    h1.tick()
    h0.set_source_roster(lambda: [
        {"id": 0, "state": "HEALTHY"}, {"id": 1, "state": "HEALTHY"},
    ])
    h1.set_source_roster(lambda: [{"id": 2, "state": "DEAD"}])
    h0.set_drift(lambda: {"state": "STEADY", "swapped": False,
                          "promotions": 0})
    h1.set_drift(lambda: {"state": "PROMOTED", "swapped": True,
                          "promotions": 1})
    with ExpositionServer(Metrics(), health=h0) as s0, \
            ExpositionServer(Metrics(), health=h1) as s1:
        urls = [
            f"http://127.0.0.1:{s.port}/healthz" for s in (s0, s1)
        ]
        with fleet.FleetAggregator(urls) as agg:
            status, report = _scrape(
                f"http://127.0.0.1:{agg.port}/healthz"
            )
            assert status == 200 and report["healthy"]
            assert report["fleet_size"] == 2
            assert report["members_healthy"] == 2
            assert [s["member"] for s in report["sources"]] == [0, 0, 1]
            assert {s["id"] for s in report["sources"]} == {0, 1, 2}
            assert report["drift_states"] == ["STEADY", "PROMOTED"]
            assert report["swapped"] == [False, True]
            assert report["promotions_total"] == 1

            # one member goes tick-stale → fleet 503, the stale member
            # still REACHABLE with its own report carried through
            clock.advance(100.0)
            h0.tick()  # member 0 stays fresh
            status, report = _scrape(
                f"http://127.0.0.1:{agg.port}/healthz"
            )
            assert status == 503 and not report["healthy"]
            assert report["members_healthy"] == 1
            assert report["members_reachable"] == 2
            assert report["members"][1]["status"] == 503
            assert report["members"][1]["report"]["tick_stale"]


def test_aggregator_unreachable_member_is_unhealthy():
    """A silent member (nothing listening) must read unreachable AND
    make the fleet unhealthy — a fleet with a dead member probe-fails."""
    with ExpositionServer(Metrics(), health=None) as s0:
        # port from a server we immediately closed: nothing listens
        with ExpositionServer(Metrics(), health=None) as tmp:
            dead_port = tmp.port
        urls = [
            f"http://127.0.0.1:{s0.port}/healthz",
            f"http://127.0.0.1:{dead_port}/healthz",
        ]
        agg = fleet.FleetAggregator(urls, timeout=1.0)
        healthy, report = agg.check()
        assert not healthy
        assert report["members_reachable"] == 1
        assert report["members"][0]["healthy"]
        assert not report["members"][1]["reachable"]
        assert "error" in report["members"][1]


def test_aggregator_404_off_path():
    with fleet.FleetAggregator([]) as agg:
        status, body = _scrape(f"http://127.0.0.1:{agg.port}/nope")
        assert status == 404
