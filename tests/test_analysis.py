"""Tests for analysis/: sklearn parity for scaler/PCA/confusion matrix,
and reproduction of the reference notebook's analysis numbers
(1_log_Kmeans.ipynb cells 70-129, SURVEY.md §6)."""

import numpy as np
import pytest

import jax.numpy as jnp

from traffic_classifier_sdn_tpu.analysis import (
    PCA,
    StandardScaler,
    accuracy,
    confusion_matrix,
    match_clusters,
)
from traffic_classifier_sdn_tpu.analysis.eval import clustering_accuracy

sklearn = pytest.importorskip("sklearn")


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(42)


@pytest.fixture(scope="module")
def X(rng):
    # heteroscedastic, correlated columns — PCA actually has work to do
    base = rng.randn(500, 12)
    mix = rng.randn(12, 12) * np.linspace(0.1, 3.0, 12)
    return (base @ mix + rng.randn(12) * 5).astype(np.float64)


# ---------------------------------------------------------------------------
# sklearn parity


def test_scaler_matches_sklearn(X):
    from sklearn.preprocessing import StandardScaler as SkScaler

    sk = SkScaler().fit(X)
    p = StandardScaler.fit(jnp.asarray(X))
    np.testing.assert_allclose(np.asarray(p.mean), sk.mean_, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(p.scale), sk.scale_, rtol=1e-6)
    ours = np.asarray(StandardScaler.transform(p, jnp.asarray(X)))
    np.testing.assert_allclose(ours, sk.transform(X), rtol=1e-5, atol=1e-8)
    back = np.asarray(StandardScaler.inverse_transform(p, jnp.asarray(ours)))
    np.testing.assert_allclose(back, X, rtol=1e-5, atol=1e-6)


def test_scaler_zero_variance_column():
    Xc = np.ones((50, 3))
    Xc[:, 1] = np.arange(50)
    p = StandardScaler.fit(jnp.asarray(Xc))
    assert float(p.scale[0]) == 1.0  # zero-variance guard, like sklearn
    out = np.asarray(StandardScaler.transform(p, jnp.asarray(Xc)))
    assert np.all(out[:, 0] == 0)


def test_pca_matches_sklearn(X):
    from sklearn.decomposition import PCA as SkPCA

    sk = SkPCA(n_components=2).fit(X)
    p = PCA.fit(jnp.asarray(X), n_components=2)
    np.testing.assert_allclose(
        np.asarray(p.explained_variance), sk.explained_variance_, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(p.explained_variance_ratio),
        sk.explained_variance_ratio_,
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(p.components), sk.components_, rtol=1e-4, atol=1e-6
    )
    ours = np.asarray(PCA.transform(p, jnp.asarray(X)))
    np.testing.assert_allclose(ours, sk.transform(X), rtol=1e-4, atol=1e-5)


def test_pca_inverse_reconstructs_full_rank(X):
    p = PCA.fit(jnp.asarray(X), n_components=12)
    Z = PCA.transform(p, jnp.asarray(X))
    back = np.asarray(PCA.inverse_transform(p, Z))
    np.testing.assert_allclose(back, X, rtol=1e-4, atol=1e-5)


def test_confusion_matrix_matches_sklearn(rng):
    from sklearn.metrics import confusion_matrix as sk_cm

    y_true = rng.randint(0, 6, 300)
    y_pred = rng.randint(0, 6, 300)
    ours = np.asarray(
        confusion_matrix(jnp.asarray(y_true), jnp.asarray(y_pred), 6)
    )
    np.testing.assert_array_equal(ours, sk_cm(y_true, y_pred, labels=range(6)))
    assert float(accuracy(jnp.asarray(y_true), jnp.asarray(y_pred))) == (
        pytest.approx((y_true == y_pred).mean())
    )


def test_match_clusters_mode_and_ties():
    # cluster 0: labels [1,1,2] → 1; cluster 1: tie [0,2] → smallest = 0
    cids = jnp.asarray([0, 0, 0, 1, 1])
    y = jnp.asarray([1, 1, 2, 0, 2])
    remap = np.asarray(match_clusters(cids, y, k=3, n_classes=3))
    assert remap[0] == 1
    assert remap[1] == 0
    assert remap[2] == 0  # empty cluster → 0


# ---------------------------------------------------------------------------
# notebook-number reproduction on the reference datasets


@pytest.fixture(scope="module")
def ref_ds():
    import os

    if not os.path.isdir("/root/reference/datasets"):
        pytest.skip("reference datasets unavailable")
    from traffic_classifier_sdn_tpu.io.datasets import load_reference_datasets

    return load_reference_datasets("/root/reference/datasets")


def test_pca2_explained_variance_matches_notebook(ref_ds):
    """1_log_Kmeans.ipynb cell 82: scaled PCA-2 explains 81.11% of the
    variance (SURVEY.md §6)."""
    Xs = StandardScaler.transform(
        StandardScaler.fit(jnp.asarray(ref_ds.X)), jnp.asarray(ref_ds.X)
    )
    p = PCA.fit(Xs, n_components=2)
    ratio = float(jnp.sum(p.explained_variance_ratio))
    assert ratio == pytest.approx(0.8111, abs=0.02)


def test_pca2_logreg_matches_notebook(ref_ds):
    """1_log_Kmeans.ipynb cell 91: LogReg on PCA-2, 70/30 split → 83.03%.
    Our split PRNG differs from sklearn's, so a ±3% band."""
    from traffic_classifier_sdn_tpu.io.datasets import train_test_split
    from traffic_classifier_sdn_tpu.models import logreg
    from traffic_classifier_sdn_tpu.train import logreg as logreg_train

    tr, te = train_test_split(ref_ds, test_size=0.3, seed=101)
    sp = StandardScaler.fit(jnp.asarray(tr.X))
    pca = PCA.fit(StandardScaler.transform(sp, jnp.asarray(tr.X)), 2)
    Ztr = PCA.transform(pca, StandardScaler.transform(sp, jnp.asarray(tr.X)))
    Zte = PCA.transform(pca, StandardScaler.transform(sp, jnp.asarray(te.X)))
    params = logreg_train.fit(
        np.asarray(Ztr), tr.y, n_classes=len(tr.classes)
    )
    acc = float(
        accuracy(jnp.asarray(te.y), logreg.predict(params, Zte))
    )
    assert acc == pytest.approx(0.8303, abs=0.03)


def test_kmeans_mode_matching_matches_notebook(ref_ds):
    """1_log_Kmeans.ipynb cell 118: the 4-cluster KMeans checkpoint,
    mode-matched on the 4-class rows, scores 46.38%."""
    import os

    ckpt = "/root/reference/models/KMeans_Clustering"
    if not os.path.exists(ckpt):
        pytest.skip("reference KMeans checkpoint unavailable")
    from traffic_classifier_sdn_tpu.io import sklearn_import as ski
    from traffic_classifier_sdn_tpu.models import kmeans

    four = [c for c in ("dns", "ping", "telnet", "voice")]
    keep = np.isin(np.asarray(ref_ds.classes)[ref_ds.y], four)
    X4 = ref_ds.X[keep]
    # relabel to the 4-class alphabetical coding the notebook used
    names = np.asarray(ref_ds.classes)[ref_ds.y[keep]]
    y4 = np.searchsorted(np.asarray(four), names).astype(np.int32)

    params = kmeans.from_numpy(ski.import_kmeans(ckpt), dtype=jnp.float64)
    cids = kmeans.predict(params, jnp.asarray(X4))
    # the notebook's 46.38% is its cell-116 map, which is the identity on
    # the alphabetical coding (0=dns,1=ping,2=telnet,3=voice)
    notebook_acc = float(accuracy(jnp.asarray(y4), cids))
    assert notebook_acc == pytest.approx(0.4638, abs=0.005)
    # our data-driven mode matching must do at least as well (measured:
    # 61.0% — it fixes the reference's suboptimal cluster→label order)
    acc = float(
        clustering_accuracy(cids, jnp.asarray(y4), k=4, n_classes=4)
    )
    assert acc >= notebook_acc
    assert acc == pytest.approx(0.610, abs=0.02)


def test_cli_analyze_writes_figures(tmp_path, capsys, reference_datasets_dir):
    """`analyze` renders all four C13 notebook figures (1_log_Kmeans.ipynb
    cells 70-129) and prints the headline analysis numbers."""
    from traffic_classifier_sdn_tpu import cli

    cli.main([
        "analyze", "--data-dir", reference_datasets_dir,
        "--out", str(tmp_path),
    ])
    out = capsys.readouterr().out
    assert "PCA-2 explained variance" in out
    assert "logreg accuracy" in out
    for name in ("pca_scatter", "decision_boundary", "cluster_centers",
                 "cluster_scatter"):
        p = tmp_path / f"{name}.png"
        assert p.exists() and p.stat().st_size > 5000, name
