"""Sharded flow table (parallel/table_sharded.py) vs the single-device
spine: identical records through both must produce identical state,
render output, and eviction behavior — the flow partitioning across the
mesh must be invisible to everything above it."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from traffic_classifier_sdn_tpu.core import flow_table as ft
from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
from traffic_classifier_sdn_tpu.ingest.protocol import TelemetryRecord
from traffic_classifier_sdn_tpu.parallel import mesh as meshlib
from traffic_classifier_sdn_tpu.parallel import table_sharded as ts


def _rec(time, src, dst, pkts, bts, dp="1"):
    return TelemetryRecord(
        time=time, datapath=dp, in_port=1, eth_src=src, eth_dst=dst,
        out_port=2, packets=pkts, bytes=bts,
    )


def _label_fn(_params, X):
    # deterministic per-row pseudo-labels so render parity is meaningful
    return (jnp.sum(X, axis=1).astype(jnp.int32) % 6).astype(jnp.int32)


def _workload(n_flows, ticks, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for t in range(1, ticks + 1):
        recs = []
        for i in range(n_flows):
            growth = int(rng.randint(0, 1 << 16))
            recs.append(
                _rec(t, f"s{i:02x}", f"d{i:02x}", 10 * t, 1000 * t + growth)
            )
            if i % 3 == 0:  # reverse-direction telemetry for some flows
                recs.append(
                    _rec(t, f"d{i:02x}", f"s{i:02x}", 5 * t, 300 * t)
                )
        out.append(recs)
    return out


@pytest.fixture(scope="module")
def mesh():
    return meshlib.make_mesh()  # 8-way data axis on the virtual CPU mesh


def test_sharded_state_matches_single_device(mesh):
    cap = 128  # 16 slots per shard
    single = FlowStateEngine(capacity=cap)
    sharded = ts.ShardedFlowEngine(
        mesh, cap, predict_fn=_label_fn, params=None, table_rows=8
    )
    for recs in _workload(40, 3):
        single.mark_tick()
        sharded.mark_tick()
        single.ingest(recs)
        sharded.ingest(recs)
        single.step()
        sharded.step()
    # identical global feature state: global slot g lives on shard
    # g % n_shards at local row g // n_shards, so interleave per-shard rows
    shard_feats = np.stack(
        [
            np.asarray(
                ft.features12(jax.tree.map(lambda a: a[s], sharded.tables))
            )
            for s in range(sharded.n_shards)
        ]
    )
    Xs = shard_feats.transpose(1, 0, 2).reshape(-1, 12)
    X1 = np.asarray(ft.features12(single.table))
    np.testing.assert_array_equal(Xs, X1)
    assert sharded.num_flows() == single.num_flows() == 40


@pytest.mark.parametrize("native", [False, True])
def test_sharded_multi_update_tick_matches_single_device(mesh, native):
    """Three+ same-direction records for one flow in ONE tick: the
    batcher emits multiple flush batches whose concatenation would put
    two updates for one (slot, direction) into a single scatter — the
    coalesced sharded step must cut its apply groups at the conflict
    boundary (native: conflict-started generations; python: never within
    a drain) so state matches the single-device spine, which applies
    per flush batch. Regression for the round-4 review finding (silently
    dropped intermediate update -> wrong delta/rate features)."""
    if native:
        from traffic_classifier_sdn_tpu.native import engine as ne

        if not ne.available():
            pytest.skip("native engine unavailable")
    cap = 64
    single = FlowStateEngine(capacity=cap, native=native)
    sharded = ts.ShardedFlowEngine(
        mesh, cap, predict_fn=_label_fn, params=None, table_rows=8,
        native=native,
    )
    # flow A: create + 3 same-direction updates in tick 1 (three
    # generations / flush batches); flow B interleaved for routing noise
    recs = [
        _rec(1, "aa", "bb", 1, 100), _rec(1, "cc", "dd", 1, 50),
        _rec(1, "aa", "bb", 5, 500), _rec(1, "aa", "bb", 9, 800),
        _rec(1, "aa", "bb", 11, 1100), _rec(1, "cc", "dd", 3, 70),
    ]
    for eng in (single, sharded):
        eng.mark_tick()
        eng.ingest(recs)
        eng.step()
    # second tick: one more update so inst rates derive from tick-1 state
    recs2 = [_rec(3, "aa", "bb", 20, 2000), _rec(3, "cc", "dd", 6, 90)]
    for eng in (single, sharded):
        eng.mark_tick()
        eng.ingest(recs2)
        eng.step()
    shard_feats = np.stack(
        [
            np.asarray(
                ft.features12(jax.tree.map(lambda a: a[s], sharded.tables))
            )
            for s in range(sharded.n_shards)
        ]
    )
    Xs = shard_feats.transpose(1, 0, 2).reshape(-1, 12)
    X1 = np.asarray(ft.features12(single.table))
    np.testing.assert_array_equal(Xs, X1)


@pytest.mark.parametrize("native", [False, True])
def test_sharded_mixed_width_wire_matches_single_device(mesh, native):
    """A >2³¹-packet flow forces the full 24 B wire form while normal
    flows pack compact; when both land in one coalesced apply group the
    router must widen before concatenating (flow_table.widen_wire) and
    state must still match the single-device spine exactly."""
    if native:
        from traffic_classifier_sdn_tpu.native import engine as ne

        if not ne.available():
            pytest.skip("native engine unavailable")
    cap = 64
    single = FlowStateEngine(capacity=cap, native=native)
    sharded = ts.ShardedFlowEngine(
        mesh, cap, predict_fn=_label_fn, params=None, table_rows=8,
        native=native,
    )
    big = (1 << 33) + 7  # needs the full wire form (pkts_f >= 2^31)
    recs1 = [
        _rec(1, "aa", "bb", big, big * 100),
        _rec(1, "cc", "dd", 3, 300),
        _rec(1, "ee", "ff", 5, 500),
        # a same-tick second update for the big flow: its create goes in
        # one generation/batch and this update in another -> the step
        # coalesces batches of BOTH widths into apply groups
        _rec(1, "aa", "bb", big + 9, (big + 9) * 100),
        _rec(1, "aa", "bb", big + 11, (big + 11) * 100),
    ]
    recs2 = [_rec(4, "aa", "bb", big + 20, (big + 20) * 100),
             _rec(4, "cc", "dd", 9, 900)]
    for recs in (recs1, recs2):
        for eng in (single, sharded):
            eng.mark_tick()
            eng.ingest(recs)
            eng.step()
    shard_feats = np.stack(
        [
            np.asarray(
                ft.features12(jax.tree.map(lambda a: a[s], sharded.tables))
            )
            for s in range(sharded.n_shards)
        ]
    )
    Xs = shard_feats.transpose(1, 0, 2).reshape(-1, 12)
    X1 = np.asarray(ft.features12(single.table))
    np.testing.assert_array_equal(Xs, X1)


def test_sharded_render_matches_single_device(mesh):
    cap = 128
    single = FlowStateEngine(capacity=cap)
    sharded = ts.ShardedFlowEngine(
        mesh, cap, predict_fn=_label_fn, params=None, table_rows=8
    )
    for recs in _workload(40, 2, seed=7):
        single.mark_tick()
        sharded.mark_tick()
        single.ingest(recs)
        sharded.ingest(recs)
        single.step()
        sharded.step()
    labels = _label_fn(None, ft.features12(single.table))
    want = single.render_sample(labels, 8)
    got, evicted = sharded.tick_render(now=sharded.last_time, idle_seconds=3600)
    assert evicted == 0
    assert got == want
    # metadata resolves for every rendered global slot
    meta = sharded.slot_metadata([s for s, *_ in got])
    assert len(meta) == len(got)


def test_sharded_eviction_matches_single_device(mesh):
    cap = 64
    single = FlowStateEngine(capacity=cap)
    sharded = ts.ShardedFlowEngine(
        mesh, cap, predict_fn=_label_fn, params=None, table_rows=4
    )
    recs = _workload(24, 1)[0]
    for eng in (single, sharded):
        eng.mark_tick()
        eng.ingest(recs)
        eng.step()
    # refresh a third of the flows much later; the rest go idle
    fresh = [
        _rec(5000, f"s{i:02x}", f"d{i:02x}", 100, 10000)
        for i in range(0, 24, 3)
    ]
    for eng in (single, sharded):
        eng.mark_tick()
        eng.ingest(fresh)
        eng.step()
    want_evicted = single.evict_idle(now=5000, idle_seconds=1000)
    _rows, got_evicted = sharded.tick_render(now=5000, idle_seconds=1000)
    assert got_evicted == want_evicted == 16
    assert sharded.num_flows() == single.num_flows() == 8
    # evicted state is zeroed on every shard
    for s in range(sharded.n_shards):
        tbl = jax.tree.map(lambda a: a[s], sharded.tables)
        in_use = np.asarray(tbl.in_use)[:-1]
        X = np.asarray(ft.features12(tbl))
        assert not X[~in_use].any()
    # freed capacity is reusable through the same global index
    more = [_rec(6000, f"n{i}", f"m{i}", 1, 10) for i in range(16)]
    sharded.mark_tick()
    sharded.ingest(more)
    sharded.step()
    assert sharded.num_flows() == 24


def test_tick_outputs_replicated_across_shards(mesh):
    """make_tick_outputs declares its outputs replicated (out_specs=P())
    with the varying-axis checker disabled — so this guard asserts the
    replication REALLY holds: every output must be bitwise identical on
    every addressable shard. If a future edit drops an all_gather (or a
    predict_fn leaks a shard-varying value), out_specs=P() would silently
    publish one device's local value; this test is the tripwire."""
    eng = ts.ShardedFlowEngine(
        mesh, 64, predict_fn=_label_fn, params=None, table_rows=4
    )
    eng.mark_tick()
    eng.ingest(_workload(24, 2, seed=3)[0])
    eng.step()
    outs = eng._tick_outputs(eng.tables, None, 0, 2, 3600)
    for k, o in enumerate(outs):
        shards = o.addressable_shards
        base = np.asarray(shards[0].data)
        for sh in shards[1:]:
            np.testing.assert_array_equal(
                np.asarray(sh.data), base, err_msg=f"output {k} varies"
            )


@pytest.mark.parametrize("native", [False, True])
def test_sharded_churn_recycles_slots_without_drops(mesh, native):
    """Sustained churn through the sharded engine: cohorts retire and new
    ones mint every other tick; tick_render's folded eviction must recycle
    slots across ALL shards fast enough that the global table never fills,
    with the round-robin routing keeping every shard in play."""
    if native:
        from traffic_classifier_sdn_tpu.native import engine as ne

        if not ne.available():
            pytest.skip("native engine unavailable")
    from traffic_classifier_sdn_tpu.ingest.protocol import TelemetryRecord

    cap = 512
    stable_n, churn_n = cap // 2, cap // 8
    eng = ts.ShardedFlowEngine(
        mesh, cap, predict_fn=_label_fn, params=None, table_rows=8,
        native=native,
    )
    generation = 0
    evicted_total = 0
    for tick in range(1, 13):
        if tick % 2 == 0:
            generation += 1
        recs = [
            TelemetryRecord(
                time=tick, datapath="1", in_port="1",
                eth_src=f"st-{i:04x}", eth_dst="gw",
                out_port="2", packets=tick * 3, bytes=tick * 100,
            )
            for i in range(stable_n)
        ] + [
            TelemetryRecord(
                time=tick, datapath="1", in_port="1",
                eth_src=f"ch{generation}-{i:04x}", eth_dst="gw",
                out_port="2", packets=tick * 3, bytes=tick * 100,
            )
            for i in range(churn_n)
        ]
        eng.mark_tick()
        eng.ingest(recs)
        eng.step()
        rows, evicted = eng.tick_render(now=tick, idle_seconds=2)
        evicted_total += evicted
        assert len(rows) == 8  # the render stays full through churn
        assert eng.dropped == 0, f"tick {tick}: dropped flows"
        assert eng.num_flows() <= stable_n + 2 * churn_n
    assert evicted_total >= 4 * churn_n
