"""Open-set serving (serving/openset.py) + per-class drift attribution
(serving/drift.py) — the F12 guarantees, each pinned here:

- the OpenSetGate is byte-transparent while calibrating and on
  closed-world traffic after arming (CLI ``--openset auto`` output is
  byte-identical to ``--openset off``, serial + pipelined,
  ``--incremental auto/off``);
- armed, it relabels rows further than the calibrated threshold from
  EVERY known class with the explicit ``unknown`` index — host and
  device label paths agree exactly — and never rejects an inactive
  (zero-feature) row or an adversarially-perturbed KNOWN row;
- every scored drift window carries attribution (top z-shift features,
  top class-mix deltas incl. the ``unknown`` slot, score
  decomposition), exposed through ``DriftController.status`` → /healthz
  and the ``drift.transition``/``drift.window`` ring events;
- THE open-world acceptance loop: calibrate on closed-world traffic →
  inject a novel class → the openset gate rejects it → the drift
  monitor trips with the ``unknown`` class attributed → background
  retrain on KNOWN rows only → parity-gated promotion (unknown rows
  excluded from the probe) → the promoted model and re-based gate
  STILL reject the novel class — wrong-but-confident never serves;
- a rendered serve with novel traffic prints the explicit ``unknown``
  label (never "?" and never a fabricated known class).
"""

import contextlib
import io
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from traffic_classifier_sdn_tpu import cli
from traffic_classifier_sdn_tpu.ingest.protocol import format_line
from traffic_classifier_sdn_tpu.ingest.workload import (
    ClassWorkload,
    OpenWorldWorkload,
    novel_delta_pool,
    perturb_pools,
    synthetic_delta_pools,
)
from traffic_classifier_sdn_tpu.models import gnb
from traffic_classifier_sdn_tpu.obs import HealthState
from traffic_classifier_sdn_tpu.serving import retrain
from traffic_classifier_sdn_tpu.serving.drift import (
    PROMOTED,
    RETRAINING,
    STEADY,
    DriftController,
    DriftGate,
    DriftMonitor,
)
from traffic_classifier_sdn_tpu.serving.openset import (
    ARMED,
    CALIBRATING,
    OpenSetGate,
    class_reference,
    floored_std,
    openset_scores,
)
from traffic_classifier_sdn_tpu.utils.metrics import Metrics

# ---------------------------------------------------------------------------
# harness: a 2-class teacher over a 12-feature stream (test_drift.py's)
# ---------------------------------------------------------------------------


def _teacher(params, X):
    """Labels by thresholding feature 0 — class 0 below 500, class 1
    above. Stands in for the boot serving predict."""
    return (np.asarray(X)[:, 0] > 500.0).astype(np.int32)


def _batch(lo, hi, n=32, seed=0):
    rng = np.random.RandomState(seed)
    X = np.zeros((n, 12), np.float32)
    X[: n // 2, 0] = lo * (1 + 0.01 * rng.rand(n // 2))
    X[n // 2:, 0] = hi * (1 + 0.01 * rng.rand(n - n // 2))
    X[:, 1] = 1.0  # a constant column keeps every row "active"
    return X


def _novel_batch(n=16, seed=0):
    """Rows far outside both classes: feature 0 around 5e4 (50× class
    1), plus a feature-5 signature no known class has."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, 12), np.float32)
    X[:, 0] = 5e4 * (1 + 0.1 * rng.rand(n))
    X[:, 1] = 1.0
    X[:, 5] = 7e3 * (1 + 0.1 * rng.rand(n))
    return X


def _calibrated_gate(predict=_teacher, rows=64, margin=3.0, metrics=None,
                     recorder=None):
    gate = OpenSetGate(
        predict, n_classes=2, margin=margin, calibration_rows=rows,
        metrics=metrics, recorder=recorder,
    )
    i = 0
    while gate.state == CALIBRATING:
        i += 1
        assert i < 64, "gate never armed"
        gate(None, _batch(10.0, 1000.0, seed=i))
    return gate


# ---------------------------------------------------------------------------
# OpenSetGate
# ---------------------------------------------------------------------------


def test_gate_transparent_while_calibrating():
    gate = OpenSetGate(_teacher, n_classes=2, calibration_rows=10_000)
    X = _batch(10.0, 1000.0)
    np.testing.assert_array_equal(gate(None, X), _teacher(None, X))
    assert gate.state == CALIBRATING
    assert gate.threshold == float("inf")
    # even a wildly novel batch passes through untouched pre-arming
    Xn = _novel_batch()
    np.testing.assert_array_equal(gate(None, Xn), _teacher(None, Xn))


def test_gate_arms_after_calibration_rows_and_bumps_epoch():
    gate = OpenSetGate(_teacher, n_classes=2, calibration_rows=64)
    e0 = gate.label_epoch
    while gate.state == CALIBRATING:
        gate(None, _batch(10.0, 1000.0, seed=gate.status()[
            "calibration_rows"
        ] + 1))
    assert gate.state == ARMED
    assert np.isfinite(gate.threshold)
    assert gate.label_epoch != e0  # the incremental cache must flush


def test_gate_armed_closed_world_is_identity():
    gate = _calibrated_gate()
    for i in range(100, 110):
        X = _batch(10.0, 1000.0, seed=i)
        np.testing.assert_array_equal(gate(None, X), _teacher(None, X))
    assert gate.status()["rejections"] == 0


def test_gate_rejects_novel_rows_with_unknown_index():
    m = Metrics()
    gate = _calibrated_gate(metrics=m)
    X = np.concatenate(
        [_batch(10.0, 1000.0, seed=7), _novel_batch(seed=7)], axis=0
    )
    out = np.asarray(gate(None, X))
    known, novel = out[:32], out[32:]
    np.testing.assert_array_equal(known, _teacher(None, X[:32]))
    assert (novel == gate.unknown_index).all()
    assert gate.status()["rejections"] == 16
    assert m.counters["openset_rejections"] == 16


def test_gate_never_rejects_inactive_rows():
    gate = _calibrated_gate()
    X = np.zeros((8, 12), np.float32)  # all-zero = inactive slots
    out = np.asarray(gate(None, X))
    assert (out != gate.unknown_index).all()


def test_gate_device_and_host_paths_agree():
    """The jitted device relabel mirrors the numpy scorer exactly."""

    def device_teacher(params, X):
        return jnp.asarray(_teacher(params, X))

    host = _calibrated_gate(_teacher)
    dev = _calibrated_gate(device_teacher)
    X = np.concatenate(
        [_batch(10.0, 1000.0, seed=3), _novel_batch(seed=3)], axis=0
    )
    np.testing.assert_array_equal(
        np.asarray(host(None, X)), np.asarray(dev(None, jnp.asarray(X)))
    )
    # lazy device-count drain lands at the next call
    dev(None, jnp.asarray(_batch(10.0, 1000.0, seed=4)))
    assert dev.status()["rejections"] == host.status()["rejections"]


def test_gate_perturbed_known_traffic_not_rejected():
    """Adversarially-perturbed KNOWN pools (bounded epsilon moves
    toward the next class's mean — ingest/workload.perturb_pools)
    stay inside the calibrated threshold: boundary-hugging traffic
    must not be flushed out of the known world."""
    pools = synthetic_delta_pools(n_classes=3, seed=11)
    names = sorted(pools)

    def rows_from(pools_, seed):
        rng = np.random.RandomState(seed)
        X = np.zeros((96, 12), np.float32)
        y = np.zeros(96, np.int32)
        for i in range(96):
            c = i % 3
            pool = pools_[names[c]]
            X[i, :4] = pool[rng.randint(len(pool))]
            X[i, 4] = 1.0
            y[i] = c
        return X, y

    Xc, yc = rows_from(pools, 0)
    teacher = lambda params, X: yc[: np.asarray(X).shape[0]]  # noqa: E731
    gate = OpenSetGate(teacher, n_classes=3, calibration_rows=64)
    gate(None, Xc)
    gate(None, Xc)  # calibration pairs fold one tick deferred
    assert gate.state == ARMED
    pert = perturb_pools(pools, epsilon=0.2, seed=12)
    Xp, _ = rows_from(pert, 1)
    out = np.asarray(gate(None, Xp))
    # bounded moves INSIDE the known envelope: nothing rejected
    assert (out != gate.unknown_index).all()


def test_gate_rebase_keeps_rejecting_and_bumps_epoch():
    gate = _calibrated_gate()
    e0 = gate.label_epoch
    window = np.concatenate(
        [_batch(10.0, 1000.0, seed=i) for i in range(50, 54)]
    )
    y = _teacher(None, window)
    assert gate.rebase(window, y)
    assert gate.label_epoch != e0
    assert gate.state == ARMED
    out = np.asarray(gate(None, _novel_batch(seed=9)))
    assert (out == gate.unknown_index).all()


def test_gate_rebase_excludes_unknown_rows():
    """Rows labeled unknown never teach the stats: a rebase window
    polluted with rejected novel rows re-bases on the known rows only
    — and the novel class stays rejected."""
    gate = _calibrated_gate()
    known = np.concatenate(
        [_batch(10.0, 1000.0, seed=i) for i in range(60, 64)]
    )
    novel = _novel_batch(n=64, seed=60)
    window = np.concatenate([known, novel])
    y = np.concatenate([
        _teacher(None, known),
        np.full(64, gate.unknown_index, np.int32),
    ])
    assert gate.rebase(window, y)
    out = np.asarray(gate(None, _novel_batch(seed=61)))
    assert (out == gate.unknown_index).all()


def test_gate_score_surface_matches_reference_math():
    """openset_scores is the one home of the score expression: tiny
    hand-checked case."""
    mean = np.array([[0.0, 0.0]])
    inv_std = np.array([[1.0, 1.0]])
    s = openset_scores(np.array([[3.0, 4.0]]), mean, inv_std)
    np.testing.assert_allclose(s, [np.sqrt((9 + 16) / 2)])


def test_class_reference_excludes_unknown_and_floors_empty():
    X = np.array([[1.0, 1.0], [3.0, 3.0], [100.0, 100.0]])
    y = np.array([0, 0, 2])  # label 2 == unknown for n_classes=2
    ref = class_reference(X, y, 2)
    np.testing.assert_allclose(ref["class_mean"][0], [2.0, 2.0])
    assert ref["class_count"][1] == 0  # class 1 empty → inert
    np.testing.assert_allclose(ref["class_mean"][1], 0.0)
    floored = floored_std(ref["class_std"], X.std(axis=0))
    assert (floored > 0).all()


# ---------------------------------------------------------------------------
# drift attribution
# ---------------------------------------------------------------------------


def test_window_report_carries_feature_attribution():
    mon = DriftMonitor(window=2, threshold=3.0, trips=2,
                       calibration_windows=2)
    for i in range(1, 5):  # calibrate
        X = _batch(10.0, 1000.0, seed=i)
        mon.observe(X, _teacher(None, X))
    # shift ONLY feature 0 (scale ×40)
    report = None
    for i in range(5, 7):
        X = _batch(400.0, 40000.0, seed=i)
        r = mon.observe(X, _teacher(None, X))
        report = r if r is not None else report
    att = report["attribution"]
    assert att["features"][0][0] == 0  # feature 0 is the top mover
    assert att["features"][0][1] > 3.0
    assert att["dominant"] == "feature"
    assert report["over"]


def test_unknown_label_surge_attributes_class_mix():
    mon = DriftMonitor(window=2, threshold=3.0, trips=2,
                       calibration_windows=2, class_tolerance=0.1)
    for i in range(1, 5):
        X = _batch(10.0, 1000.0, seed=i)
        mon.observe(X, _teacher(None, X))
    # same features, but half the rows now carry the unknown index 2
    report = None
    for i in range(5, 7):
        X = _batch(10.0, 1000.0, seed=i)
        y = _teacher(None, X)
        y[: len(y) // 2] = 2
        r = mon.observe(X, y)
        report = r if r is not None else report
    att = report["attribution"]
    assert att["classes"][0][0] == 2  # the unknown slot moved most
    assert att["dominant"] == "class"
    assert report["over"]


def test_reference_roundtrip_carries_class_stats():
    mon = DriftMonitor(window=2, calibration_windows=2)
    for i in range(1, 5):
        X = _batch(10.0, 1000.0, seed=i)
        mon.observe(X, _teacher(None, X))
    ref = mon.reference_arrays()
    assert ref["class_mean"].shape == (2, 12)
    assert ref["class_std"].shape == (2, 12)
    assert ref["class_freq"].shape == (3,)  # 2 known + unknown slot
    # class 0 learned the low population, class 1 the high one
    assert ref["class_mean"][0][0] < 50
    assert ref["class_mean"][1][0] > 500
    # a fresh monitor seeded with it skips calibration, stats intact
    mon2 = DriftMonitor(reference=ref)
    assert mon2.calibrated
    np.testing.assert_allclose(
        mon2.reference_arrays()["class_mean"], ref["class_mean"]
    )


def test_controller_status_exposes_named_attribution(tmp_path):
    m = Metrics()
    gate = DriftGate(_teacher)
    ctl = DriftController(
        gate, family="gnb", classes=("ping", "voice"),
        directory=str(tmp_path / "drift"), window=2, threshold=3.0,
        trips=2, calibration_windows=2, metrics=m,
        boot_params=_boot_params(),
    )
    try:
        for i in range(1, 5):  # calibrate
            gate(None, _batch(10.0, 1000.0, seed=i))
            ctl.poll()
        for i in range(5, 7):  # feature-0 shift
            gate(None, _batch(400.0, 40000.0, seed=i))
            ctl.poll()
        att = ctl.status()["attribution"]
        assert att is not None
        # names resolved: the 12-feature layout maps to the reference
        # column names and the mover is the first feature column
        assert att["top_feature"] == "Delta Forward Packets"
        assert att["features"][0]["z"] > 3.0
        assert {c["class"] for c in att["classes"]} <= {
            "ping", "voice", "unknown",
        }
        # per-class attribution gauges live alongside drift_score
        assert any(
            k.startswith("drift_attribution_") for k in m.gauges
        )
    finally:
        ctl.close()


def test_healthz_drift_block_carries_attribution(tmp_path):
    gate = DriftGate(_teacher)
    ctl = DriftController(
        gate, family="gnb", classes=("ping", "voice"),
        directory=str(tmp_path / "drift"), window=2, threshold=3.0,
        trips=2, calibration_windows=2,
        boot_params=_boot_params(),
    )
    health = HealthState()
    health.set_drift(ctl.status)
    try:
        for i in range(1, 7):
            shifted = i > 4
            lo, hi = (400.0, 40000.0) if shifted else (10.0, 1000.0)
            gate(None, _batch(lo, hi, seed=i))
            ctl.poll()
        _healthy, report = health.check()
        att = report["drift"]["attribution"]
        assert att["top_feature"] == "Delta Forward Packets"
        assert "z_score" in att and "class_score" in att
    finally:
        ctl.close()


def _boot_params():
    return gnb.from_numpy({
        "theta": np.asarray(
            [[10.0] * 12, [1000.0] * 12], dtype=np.float64
        ),
        "var": np.ones((2, 12), np.float64),
        "class_prior": np.full(2, 0.5),
    })


# ---------------------------------------------------------------------------
# THE open-world acceptance loop
# ---------------------------------------------------------------------------


def _wait_retrain(ctl, timeout=90.0):
    deadline = time.monotonic() + timeout
    while ctl._retrainer.poll() == retrain.RUNNING:
        if time.monotonic() > deadline:
            pytest.fail("background retrain never finished")
        time.sleep(0.05)


def test_e2e_novel_class_trips_attributes_retrains_and_still_rejects(
    tmp_path,
):
    """THE acceptance scenario (ISSUE 12): closed-world calibration →
    novel-class injection → openset rejection → drift trip with the
    unknown class attributed → background retrain on KNOWN rows only →
    parity-gated promotion → the promoted model still rejects the
    novel class at the calibrated threshold."""
    m = Metrics()
    gate = DriftGate(_teacher)
    ctl = DriftController(
        gate, family="gnb", classes=("ping", "voice"),
        directory=str(tmp_path / "drift"), window=3, threshold=3.0,
        trips=2, calibration_windows=2, probe_successes=2,
        min_retrain_rows=16, metrics=m, boot_params=_boot_params(),
    )
    openset = OpenSetGate(gate, n_classes=2, calibration_rows=64,
                          metrics=m)
    ctl.set_openset(openset)
    tripped_att = None
    try:
        i = 0
        while ctl.state != PROMOTED and i < 300:
            i += 1
            X = _batch(10.0, 1000.0, seed=i)
            if i > 14:  # novel class arrives mid-stream
                X = np.concatenate([X, _novel_batch(seed=i)], axis=0)
            labels = np.asarray(openset(None, X))
            ctl.poll()
            if i > 14 and openset.state == ARMED:
                # the gate rejects exactly the novel rows, every tick
                np.testing.assert_array_equal(
                    labels[:32], _teacher(None, X[:32])
                )
                assert (labels[32:] == openset.unknown_index).all()
            if ctl.state == RETRAINING:
                if tripped_att is None:
                    tripped_att = ctl.status()["attribution"]
                _wait_retrain(ctl)
        assert ctl.state == PROMOTED
        assert openset.state == ARMED
        # the trip named the mover: the unknown surge tops the class
        # deltas (the z-shift may dominate the score — both name it)
        assert tripped_att is not None
        assert tripped_att["top_class"] == "unknown"
        assert m.counters["promotions"] == 1
        # the promoted model was fit on KNOWN rows only and the gate
        # re-based on the same window: the novel class is STILL
        # rejected at the calibrated threshold…
        out = np.asarray(openset(None, _novel_batch(seed=999)))
        assert (out == openset.unknown_index).all()
        # …while known traffic serves closed-world labels
        Xk = _batch(10.0, 1000.0, seed=998)
        np.testing.assert_array_equal(
            np.asarray(openset(None, Xk)), _teacher(None, Xk)
        )
        # and the re-based monitor no longer trips on the (continuing)
        # novel stream: the unknown fraction is the new baseline
        for j in range(12):
            X = np.concatenate([
                _batch(10.0, 1000.0, seed=1000 + j),
                _novel_batch(seed=1000 + j),
            ])
            openset(None, X)
            ctl.poll()
        assert ctl.state == STEADY
        assert ctl.status()["score"] < 3.0
    finally:
        ctl.close()


# ---------------------------------------------------------------------------
# CLI: byte-identity + explicit unknown rendering
# ---------------------------------------------------------------------------


def _native_checkpoint(tmp_path):
    from traffic_classifier_sdn_tpu.io import checkpoint as ck

    rng = np.random.RandomState(0)
    params = gnb.from_numpy({
        "theta": rng.gamma(2.0, 100.0, (2, 12)),
        "var": rng.gamma(2.0, 50.0, (2, 12)) + 1.0,
        "class_prior": np.full(2, 0.5),
    })
    path = str(tmp_path / "gnb_ckpt")
    ck.save_model(path, "gnb", params, classes=("ping", "voice"))
    return path


def _serve(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), \
            contextlib.redirect_stderr(io.StringIO()):
        cli.main(argv)
    return buf.getvalue()


def _common(ckpt):
    return [
        "gaussiannb", "--native-checkpoint", ckpt,
        "--source", "synthetic", "--synthetic-flows", "16",
        "--capacity", "64", "--print-every", "2", "--max-ticks", "10",
        "--idle-timeout", "0", "--table-rows", "8",
    ]


@pytest.mark.parametrize("incremental", ["off", "auto"])
@pytest.mark.parametrize("pipeline", ["off", "on"])
def test_openset_auto_closed_world_byte_identical(
    tmp_path, pipeline, incremental,
):
    """The transparency acceptance: --openset auto output is
    byte-identical to --openset off on closed-world traffic — with the
    gate actually ARMING mid-run (calibration-rows 32 < the ~160
    active rows a 10-tick 16-flow serve observes)."""
    common = _common(_native_checkpoint(tmp_path)) + [
        "--pipeline", pipeline, "--incremental", incremental,
    ]
    off = _serve(common + ["--openset", "off"])
    auto = _serve(common + [
        "--openset", "auto", "--openset-calibration-rows", "32",
    ])
    assert "Flow ID" in off
    assert auto == off
    assert "unknown" not in auto


def _openworld_capture(tmp_path, ticks=30, novel_start=16):
    """A deterministic open-world capture: closed-world class pools,
    then a novel class's records from ``novel_start`` on."""
    pools = synthetic_delta_pools(n_classes=2, seed=0)
    base = ClassWorkload(pools, flows_per_class=8, seed=1)
    novel = ClassWorkload(
        {"novel": novel_delta_pool(pools, seed=2, scale=200.0)},
        flows_per_class=8, seed=2, mac_base=1 << 24,
    )
    wl = OpenWorldWorkload(base, novel, novel_start_tick=novel_start)
    path = str(tmp_path / "openworld.capture")
    with open(path, "wb") as f:
        for _ in range(ticks):
            for r in wl.tick():
                f.write(format_line(r))
    return path, wl


def test_openset_serve_renders_explicit_unknown(tmp_path):
    """Novel traffic through a REAL serve renders rows with the
    explicit ``unknown`` label — never '?' and never only known
    classes."""
    path, _wl = _openworld_capture(tmp_path)
    out = _serve(_common(_native_checkpoint(tmp_path)) + [
        "--source", "replay", "--capture", path,
        "--capacity", "128", "--table-rows", "32",
        "--max-ticks", "30", "--print-every", "2",
        # serial: pipelined render coalescing under cold-compile
        # backpressure would make the render (and calibration) count
        # timing-dependent — the pipelined composition is pinned by
        # the byte-identity test above
        "--pipeline", "off",
        "--openset", "auto", "--openset-calibration-rows", "64",
    ])
    assert "unknown" in out
    assert "?" not in out.replace("...", "")


def test_openset_off_is_flagless_baseline(tmp_path):
    """--openset off never renders unknown even on novel traffic (the
    wrong-but-confident baseline this PR exists to fix) — pinning that
    the unknown label can ONLY come from the gate."""
    path, _wl = _openworld_capture(tmp_path)
    out = _serve(_common(_native_checkpoint(tmp_path)) + [
        "--source", "replay", "--capture", path,
        "--capacity", "128", "--table-rows", "32",
        "--max-ticks", "30", "--print-every", "2",
        "--openset", "off",
    ])
    assert "Flow ID" in out
    assert "unknown" not in out


def test_openset_sharded_auto_skips(tmp_path):
    """'auto' skips sharded serves (their predict binds at
    construction) — the flag must not error, just no-op."""
    n_dev = len(__import__("jax").devices())
    if n_dev < 8:
        pytest.skip("needs the 8-device CPU mesh")
    out = _serve(_common(_native_checkpoint(tmp_path)) + [
        "--shards", str(n_dev), "--openset", "auto", "--drift", "off",
        "--latency-provenance", "off",
    ])
    assert "Flow ID" in out


def test_healthz_carries_openset_block():
    gate = _calibrated_gate()
    health = HealthState()
    health.set_openset(gate.status)
    gate(None, np.concatenate(
        [_batch(10.0, 1000.0, seed=5), _novel_batch(seed=5)]
    ))
    _healthy, report = health.check()
    assert report["openset"]["state"] == ARMED
    assert report["openset"]["rejections"] == 16
    assert report["openset"]["threshold"] is not None


# ---------------------------------------------------------------------------
# workload generators
# ---------------------------------------------------------------------------


def test_synthetic_pools_are_separable_and_positive():
    pools = synthetic_delta_pools(n_classes=3, seed=3)
    assert set(pools) == {"class0", "class1", "class2"}
    for pool in pools.values():
        assert pool.shape[1] == 4
        assert (pool >= 0).all()
    # classes separated by rate scale
    means = [pools[f"class{i}"][:, 1].mean() for i in range(3)]
    assert means[0] < means[1] < means[2]


def test_novel_pool_is_outside_every_known_envelope():
    pools = synthetic_delta_pools(n_classes=3, seed=4)
    novel = novel_delta_pool(pools, seed=4)
    hi = max(float(p.max()) for p in pools.values())
    assert float(novel.max()) > 10 * hi
    # reverse-heavy signature: rev bytes dominate fwd bytes
    assert (novel[:, 3] > novel[:, 1]).all()


def test_perturb_pools_bounded_and_label_preserving():
    pools = synthetic_delta_pools(n_classes=2, seed=5)
    with pytest.raises(ValueError):
        perturb_pools(pools, epsilon=1.5)
    pert = perturb_pools(pools, epsilon=0.25, seed=5)
    assert set(pert) == set(pools)
    for c in pools:
        assert pert[c].shape == pools[c].shape
        assert (pert[c] >= 0).all()
        # bounded: no perturbed value leaves the [row, target-mean]
        # interpolation envelope by construction — spot-check scale
        assert float(np.abs(pert[c] - pools[c]).max()) <= 0.25 * max(
            float(np.abs(
                pools[o].mean(axis=0)[None, :] - pools[c]
            ).max())
            for o in pools
        ) + 1.0


def test_openworld_workload_injects_at_exact_tick():
    pools = synthetic_delta_pools(n_classes=2, seed=6)
    base = ClassWorkload(pools, flows_per_class=2, seed=6)
    novel = ClassWorkload(
        {"novel": novel_delta_pool(pools, seed=6)},
        flows_per_class=2, seed=6, mac_base=1 << 20,
    )
    wl = OpenWorldWorkload(base, novel, novel_start_tick=3)
    n_base = 2 * len(base.labels)
    assert len(wl.tick()) == n_base
    assert len(wl.tick()) == n_base
    batch3 = wl.tick()
    assert len(batch3) == n_base + 2 * len(novel.labels)
    macs = {r.eth_src for r in batch3}
    assert wl.novel_macs() & macs  # the novel hosts actually emit
    # disjoint host populations — no flow-key collisions
    assert not (wl.novel_macs() & {
        m for i in range(len(base.labels)) for m in base.flow_macs(i)
    })


def test_openworld_workload_rejects_colliding_mac_base():
    pools = synthetic_delta_pools(n_classes=2, seed=7)
    base = ClassWorkload(pools, flows_per_class=2, seed=7)
    novel = ClassWorkload(pools, flows_per_class=2, seed=7)  # mac_base 0
    with pytest.raises(ValueError, match="mac_base"):
        OpenWorldWorkload(base, novel)


def test_openset_with_drift_auto_closed_world_byte_identical(tmp_path):
    """Both loops armed (--openset auto + --drift auto) on closed-world
    traffic: output byte-identical to both off — the two gates compose
    transparently."""
    # serial: the drift poll + openset calibration add real host work
    # per tick, so under the pipelined flat-out synthetic source the
    # two runs coalesce renders at different ticks — a frame-schedule
    # (pacing) difference, not a label one. Each gate's pipelined
    # byte-identity is pinned on its own above / in test_drift.py.
    common = _common(_native_checkpoint(tmp_path)) + ["--pipeline", "off"]
    off = _serve(common + ["--openset", "off", "--drift", "off"])
    both = _serve(common + [
        "--openset", "auto", "--openset-calibration-rows", "32",
        "--drift", "auto", "--drift-dir", str(tmp_path / "drift"),
    ])
    assert "Flow ID" in off
    assert both == off


def test_bench_openset_smoke(tmp_path):
    """tools/bench_openset.py end-to-end on a trimmed family subset:
    valid JSON with the artifact's fields, accuracy delta ~0, and
    perfect unknown detection on the synthetic separable data (the
    committed openset_eval_cpu.json is the full six-family run)."""
    import json
    import subprocess
    import sys as _sys

    out_path = str(tmp_path / "openset_eval.json")
    proc = subprocess.run(
        [_sys.executable, "tools/bench_openset.py",
         "--families", "gnb,logreg", "--rows-per-class", "128",
         "--out", out_path],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.load(open(out_path))
    assert set(d["families"]) == {"gnb", "logreg"}
    for fam, r in d["families"].items():
        assert abs(r["accuracy_delta"]) <= 0.02, (fam, r)
        assert r["mahalanobis_auc"] >= 0.99, (fam, r)
        assert r["unknown_tpr_at_threshold"] >= 0.99, (fam, r)
        assert r["known_fpr_at_threshold"] <= 0.02, (fam, r)
        assert len(r["roc"]) == 21


def test_gate_empty_class_is_not_a_phantom_acceptance_basin():
    """A class the calibration window never saw (the live model simply
    never predicted it) must be DROPPED from the scoring matrices —
    floored into place it would sit at the origin with a wide std and
    silently accept exactly the low-rate novel traffic the gate exists
    to reject."""

    def one_class_teacher(params, X):
        return np.ones(np.asarray(X).shape[0], np.int32)

    gate = OpenSetGate(one_class_teacher, n_classes=2,
                       calibration_rows=64)
    i = 0
    while gate.state == CALIBRATING:
        i += 1
        assert i < 64
        # every calibration row lives at the ~1000 scale; class 0 is
        # never predicted
        gate(None, _batch(900.0, 1000.0, seed=i))
    # low-rate novel rows near the origin: far from the one real
    # class, and the never-seen class must not shelter them
    Xlow = np.zeros((8, 12), np.float32)
    Xlow[:, 0] = 3.0
    out = np.asarray(gate(None, Xlow))
    assert (out == gate.unknown_index).all()


def test_reference_matrices_drops_empty_classes():
    from traffic_classifier_sdn_tpu.serving.openset import (
        reference_matrices,
    )

    X = np.array([[10.0, 1.0], [12.0, 1.0], [11.0, 1.0]])
    ref = class_reference(X, np.array([1, 1, 1]), 3)
    out = reference_matrices(ref, X.std(axis=0))
    assert out is not None
    mean, inv_std = out
    assert mean.shape == (1, 2)  # only the present class survives
    np.testing.assert_allclose(mean[0], [11.0, 1.0])
    # nothing present at all -> None (the caller must not arm)
    ref_empty = class_reference(X, np.array([3, 3, 3]), 3)  # all unknown
    assert reference_matrices(ref_empty, X.std(axis=0)) is None


def test_attribution_gauges_refresh_for_recovered_classes(tmp_path):
    """A class that led the attribution and then recovered must read
    ~0 on its gauge at the next scored window — never its stale
    top-k value."""
    m = Metrics()
    gate = DriftGate(_teacher)
    ctl = DriftController(
        gate, family="gnb", classes=("ping", "voice"),
        directory=str(tmp_path / "drift"), window=2, threshold=50.0,
        trips=99, calibration_windows=2, class_tolerance=0.1,
        metrics=m, boot_params=_boot_params(),
    )
    try:
        for i in range(1, 5):  # calibrate on the balanced mix
            gate(None, _batch(10.0, 1000.0, seed=i))
            ctl.poll()
        # one window of pure class-1 traffic: ping's |delta| spikes
        for i in range(5, 7):
            gate(None, _batch(600.0, 1000.0, seed=i))
            ctl.poll()
        assert m.gauges["drift_attribution_ping"] > 1.0
        # the mix recovers: the gauge must come back down
        for i in range(7, 9):
            gate(None, _batch(10.0, 1000.0, seed=i))
            ctl.poll()
        assert m.gauges["drift_attribution_ping"] < 0.5
    finally:
        ctl.close()


def test_openworld_workload_guard_checks_real_mac_ranges():
    """The collision guard compares actual generated MAC ranges — a
    base population with its own nonzero mac_base must not slip past
    a zero-anchored check."""
    pools = synthetic_delta_pools(n_classes=2, seed=8)
    base = ClassWorkload(pools, flows_per_class=2, seed=8, mac_base=100)
    novel = ClassWorkload(
        {"novel": novel_delta_pool(pools, seed=8)},
        flows_per_class=16, seed=8, mac_base=90,
    )  # novel range [91, 123] overlaps base [101, 109]
    with pytest.raises(ValueError, match="mac_base"):
        OpenWorldWorkload(base, novel)


def test_openset_reference_survives_restart(tmp_path):
    """The review's restart hole, pinned: a serve restarted from its
    serving checkpoint mid-novel-episode boots the gate ARMED against
    the SAME persisted stats+threshold — it must NOT re-calibrate on
    the novel traffic and unlearn its rejection. Phase 2's calibration
    budget is deliberately unreachable, so any 'unknown' in its output
    can only come from the restored reference."""
    pools = synthetic_delta_pools(n_classes=2, seed=0)
    closed = str(tmp_path / "closed.capture")
    with open(closed, "wb") as f:
        wl = ClassWorkload(pools, flows_per_class=8, seed=1)
        for _ in range(12):
            for r in wl.tick():
                f.write(format_line(r))
    novel_only = str(tmp_path / "novel.capture")
    with open(novel_only, "wb") as f:
        nwl = ClassWorkload(
            {"novel": novel_delta_pool(pools, seed=2, scale=200.0)},
            flows_per_class=8, seed=2, mac_base=1 << 24,
        )
        for _ in range(6):
            for r in nwl.tick():
                f.write(format_line(r))
    state = str(tmp_path / "serve_state.npz")
    common = _common(_native_checkpoint(tmp_path)) + [
        "--capacity", "128", "--table-rows", "32", "--pipeline", "off",
    ]
    # phase 1: closed-world serve arms the gate; state saved on exit
    out1 = _serve(common + [
        "--source", "replay", "--capture", closed, "--max-ticks", "12",
        "--openset", "auto", "--openset-calibration-rows", "64",
        "--save-serve-state", state,
    ])
    assert "unknown" not in out1
    # phase 2: restore; ONLY novel traffic flows, and the calibration
    # budget (4096) is unreachable in 6 ticks — a fresh gate would
    # stay transparent and serve wrong-but-confident known labels
    out2 = _serve(common + [
        "--source", "replay", "--capture", novel_only,
        "--max-ticks", "6", "--restore-serve-state", state,
        "--openset", "auto", "--openset-calibration-rows", "4096",
    ])
    assert "unknown" in out2
