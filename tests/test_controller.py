"""Tests for the standalone OpenFlow 1.3 controller (controller/) — wire
format round-trips, learning-switch behavior, and the full controller ↔
fake-switch ↔ telemetry ↔ ingest pipeline, all in-process (no OVS/Ryu,
the test seam SURVEY.md §4 calls for)."""

import asyncio
import io
import struct
import sys

import pytest

sys.path.insert(0, "tools")

from traffic_classifier_sdn_tpu.controller import openflow as of
from traffic_classifier_sdn_tpu.controller.switch import Controller
from traffic_classifier_sdn_tpu.ingest.protocol import parse_line


# ---------------------------------------------------------------------------
# wire format


def test_match_roundtrip():
    raw = of.encode_match(in_port=7, eth_src="aa:bb:cc:dd:ee:ff",
                          eth_dst="11:22:33:44:55:66")
    assert len(raw) % 8 == 0
    fields, off = of.decode_match(raw, 0)
    assert off == len(raw)
    assert fields == {
        "in_port": 7,
        "eth_src": "aa:bb:cc:dd:ee:ff",
        "eth_dst": "11:22:33:44:55:66",
    }


def test_empty_match_roundtrip():
    raw = of.encode_match()
    fields, off = of.decode_match(raw, 0)
    assert fields == {} and off == len(raw) == 8


def test_flow_mod_roundtrip():
    match = of.encode_match(in_port=2, eth_src="aa:aa:aa:aa:aa:aa",
                            eth_dst="bb:bb:bb:bb:bb:bb")
    instr = of.instruction_apply_actions(of.action_output(5))
    msg = of.flow_mod(3, priority=1, match=match, instructions=instr)
    mtype, xid, body = of.MessageReader().feed(msg)[0]
    assert (mtype, xid) == (of.OFPT_FLOW_MOD, 3)
    fm = of.parse_flow_mod(body)
    assert fm["priority"] == 1
    assert fm["match"]["in_port"] == 2
    assert of.decode_output_port(fm["instructions"]) == 5


def test_flow_stats_roundtrip():
    stats = [
        of.FlowStat(1, 100, 5000,
                    {"in_port": 1, "eth_src": "aa:aa:aa:aa:aa:aa",
                     "eth_dst": "bb:bb:bb:bb:bb:bb"}, out_port=2),
        of.FlowStat(0, 7, 70, {}, out_port=None),
    ]
    msg = of.flow_stats_reply(9, stats)
    mtype, xid, body = of.MessageReader().feed(msg)[0]
    assert (mtype, xid) == (of.OFPT_MULTIPART_REPLY, 9)
    mp_type, parsed = of.parse_multipart_reply(body)
    assert mp_type == of.OFPMP_FLOW
    assert len(parsed) == 2
    assert parsed[0].packet_count == 100
    assert parsed[0].byte_count == 5000
    assert parsed[0].match["eth_dst"] == "bb:bb:bb:bb:bb:bb"
    assert parsed[0].out_port == 2
    assert parsed[1].priority == 0


def test_packet_in_roundtrip():
    from fake_switch import eth_frame

    frame = eth_frame("aa:aa:aa:aa:aa:aa", "bb:bb:bb:bb:bb:bb")
    msg = of.packet_in(4, of.OFP_NO_BUFFER, 0, of.encode_match(in_port=3),
                       frame)
    _, _, body = of.MessageReader().feed(msg)[0]
    pkt = of.parse_packet_in(body)
    assert pkt["match"]["in_port"] == 3
    assert pkt["eth_src"] == "aa:aa:aa:aa:aa:aa"
    assert pkt["eth_dst"] == "bb:bb:bb:bb:bb:bb"
    assert pkt["frame"] == frame


def test_message_reader_partial_frames():
    msg = of.hello(1) + of.features_request(2)
    mr = of.MessageReader()
    out = mr.feed(msg[:5])
    assert out == []
    out = mr.feed(msg[5:9])
    assert [m[0] for m in out] == [of.OFPT_HELLO]
    out = mr.feed(msg[9:])
    assert [m[0] for m in out] == [of.OFPT_FEATURES_REQUEST]


# ---------------------------------------------------------------------------
# controller ↔ fake switch


async def _run_session(n_polls=3, n_hosts=4):
    from fake_switch import FakeSwitch

    out = io.StringIO()
    ctl = Controller(host="127.0.0.1", port=0, poll_interval=0.05, out=out)
    await ctl.start()
    sw = FakeSwitch(dpid=42, n_hosts=n_hosts)
    await sw.connect("127.0.0.1", ctl.bound_port)
    await sw.pump(0.2)  # hello/features/table-miss handshake
    for a in range(0, n_hosts - 1, 2):
        sw.converse(a, a + 1)
    await sw.pump(0.05 * (n_polls + 4))
    registered = dict(ctl.datapaths)  # snapshot before stop unregisters
    await ctl.stop()
    return registered, sw, out.getvalue()


@pytest.fixture(scope="module")
def session():
    return asyncio.run(_run_session())


def test_controller_registers_datapath(session):
    registered, sw, _ = session
    assert 42 in registered
    assert registered[42].dpid == 42


def test_learning_switch_installs_flows(session):
    _, sw, _ = session
    prios = sorted(f["priority"] for f in sw.flows)
    # 1 table-miss + one priority-1 flow per direction per conversing pair
    assert prios[0] == 0
    p1 = [f for f in sw.flows if f["priority"] == 1]
    assert len(p1) == 4  # 2 pairs × 2 directions
    for f in p1:
        assert f["match"]["in_port"] == sw.port_of[f["match"]["eth_src"]]
        assert f["out_port"] == sw.port_of[f["match"]["eth_dst"]]


def test_monitor_emits_parseable_telemetry(session):
    _, sw, text = session
    records = [
        r
        for r in (parse_line(line.encode() + b"\n")
                  for line in text.splitlines())
        if r is not None
    ]
    assert len(records) >= 4  # ≥1 poll saw all four flows
    for r in records:
        assert r.datapath == "42"
        assert r.eth_src in sw.macs and r.eth_dst in sw.macs
        assert int(r.out_port) == sw.port_of[r.eth_dst]
        assert r.packets >= 0 and r.bytes >= 0
    # counters grow across polls for at least one flow
    by_flow = {}
    for r in records:
        by_flow.setdefault((r.eth_src, r.eth_dst), []).append(r.packets)
    assert any(v[-1] > v[0] for v in by_flow.values() if len(v) > 1)


def test_full_pipeline_controller_to_device_table(session):
    """Telemetry from our own controller drives the ingest spine and the
    device flow table ends up with the conversations, direction-folded."""
    import numpy as np

    from traffic_classifier_sdn_tpu.core import flow_table as ft
    from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine

    _, sw, text = session
    eng = FlowStateEngine(capacity=32)
    eng.ingest_bytes(text.encode())
    eng.step()
    in_use = np.asarray(eng.table.in_use)[:-1]
    # 4 unidirectional flows fold into 2 bidirectional conversations
    assert int(in_use.sum()) == 2
    f12 = np.asarray(ft.features12(eng.table))
    active = f12[in_use]
    # both directions saw traffic: fwd and rev cumulative-delta columns
    # can be zero on the last tick, but rates are recorded
    assert np.all(active[:, 3] >= 0)


def test_echo_and_junk_resilience():
    """Controller answers echo and survives unknown message types."""

    async def run():
        out = io.StringIO()
        ctl = Controller(host="127.0.0.1", port=0, poll_interval=10, out=out)
        await ctl.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", ctl.bound_port
        )
        mr = of.MessageReader()
        # swallow hello + features_request
        writer.write(of.message(of.OFPT_ECHO_REQUEST, 77, b"ping"))
        # unknown/unsupported type 25 (role request) — must not kill us
        writer.write(of.message(25, 78, b"\x00" * 8))
        writer.write(of.message(of.OFPT_ECHO_REQUEST, 79, b"pong"))
        await writer.drain()
        got = {}
        for _ in range(20):
            data = await asyncio.wait_for(reader.read(4096), timeout=2.0)
            if not data:
                break
            for mtype, xid, body in mr.feed(data):
                got[(mtype, xid)] = body
            if (of.OFPT_ECHO_REPLY, 79) in got:
                break
        writer.close()
        await ctl.stop()
        assert got[(of.OFPT_ECHO_REPLY, 77)] == b"ping"
        assert got[(of.OFPT_ECHO_REPLY, 79)] == b"pong"

    asyncio.run(run())


def test_malformed_bodies_do_not_kill_connection():
    """A buggy/hostile switch sending structurally valid frames with
    garbage BODIES (truncated packet-in, corrupt multipart) must not take
    the connection down: the controller drops the frame and keeps
    answering (the reference's Ryu stack tolerates the same)."""

    async def run():
        out = io.StringIO()
        ctl = Controller(host="127.0.0.1", port=0, poll_interval=10, out=out)
        await ctl.start()
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", ctl.bound_port
        )
        mr = of.MessageReader()
        # truncated PACKET_IN / MULTIPART / FEATURES bodies
        writer.write(of.message(of.OFPT_PACKET_IN, 11, b"\x01\x02"))
        writer.write(of.message(of.OFPT_MULTIPART_REPLY, 12, b"\x00"))
        writer.write(of.message(of.OFPT_FEATURES_REPLY, 13, b"\x00\x01"))
        # a syntactically valid echo afterwards proves the connection lives
        writer.write(of.message(of.OFPT_ECHO_REQUEST, 14, b"alive"))
        await writer.drain()
        got = {}
        for _ in range(20):
            data = await asyncio.wait_for(reader.read(4096), timeout=2.0)
            if not data:
                break
            for mtype, xid, body in mr.feed(data):
                got[(mtype, xid)] = body
            if (of.OFPT_ECHO_REPLY, 14) in got:
                break
        writer.close()
        await ctl.stop()
        assert got[(of.OFPT_ECHO_REPLY, 14)] == b"alive"

    asyncio.run(run())


def test_codec_fuzz_mutated_frames_raise_only_handled_types():
    """Byte-mutation fuzz over every parser: corrupt frames may be
    rejected (ValueError/struct.error/IndexError/KeyError — the types
    the connection handler drops) but must never raise anything else or
    hang. Seeded: failures reproduce."""
    import numpy as np

    rng = np.random.RandomState(123)
    stats = [
        of.FlowStat(1, 3, 5),
        of.FlowStat(
            1, 10, 20,
            match={"in_port": 2, "eth_src": "aa:bb:cc:dd:ee:01",
                   "eth_dst": "aa:bb:cc:dd:ee:02"},
            out_port=3,
        ),
    ]
    valid = [
        of.flow_stats_reply(5, stats),
        of.packet_in(6, 99, 0, of.encode_match(in_port=3),
                     b"\xff" * 20),
        of.flow_mod(7, 1, of.encode_match(1, "aa:bb:cc:dd:ee:01",
                                          "aa:bb:cc:dd:ee:02"),
                    of.instruction_apply_actions(of.action_output(2))),
    ]
    parsers = {
        of.OFPT_MULTIPART_REPLY: of.parse_multipart_reply,
        of.OFPT_PACKET_IN: of.parse_packet_in,
        of.OFPT_FLOW_MOD: of.parse_flow_mod,
    }
    for trial in range(300):
        frame = bytearray(valid[trial % len(valid)])
        for _ in range(rng.randint(1, 4)):
            op = rng.randint(3)
            if op == 0 and len(frame) > 9:  # mutate a body byte
                frame[rng.randint(8, len(frame))] = rng.randint(256)
            elif op == 1 and len(frame) > 9:  # truncate
                del frame[rng.randint(9, len(frame)):]
            else:  # append junk
                frame.extend(rng.bytes(rng.randint(1, 9)))
        if len(frame) < 8:
            continue
        version, mtype, length, xid = of.OFP_HEADER.unpack_from(frame)
        body = bytes(frame[8:])
        parser = parsers.get(mtype)
        if parser is None:
            continue
        try:
            parser(body)
        except of.PARSE_ERRORS:
            pass  # the connection loop's per-message guard (same tuple)
    # MessageReader on mutated streams: only ValueError (framing) allowed
    blob = b"".join(valid)
    for trial in range(100):
        stream = bytearray(blob)
        for _ in range(rng.randint(1, 6)):
            stream[rng.randint(len(stream))] = rng.randint(256)
        mr2 = of.MessageReader()
        try:
            mr2.feed(bytes(stream))
        except ValueError:
            pass
