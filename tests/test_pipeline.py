"""Pipelined serving (serving/pipeline.py + serving/warmup.py).

The load-bearing guarantees, each pinned here:

- the handoff is bounded with coalescing backpressure (never unbounded,
  never blocking the host stage);
- device-stage failures propagate to the host stage as the original
  exception (the serve loop's crash forensics depend on it);
- pipelined vs serial serve renders BYTE-IDENTICAL stdout for the same
  ticks — device-kernel ranked, full-table, host-native, and sharded
  paths;
- the flows_dropped gauge is fresh every tick, not every render
  (regression for the stale-gauge defect at the old cli.py:685);
- --warmup removes the first-tick compile stall: the serving programs
  are compiled before the loop, so tick one triggers zero new
  traces/compiles and runs at steady-state speed;
- the bench's --pipeline A/B mode executes the pipelined path
  end-to-end (the tier-1 smoke for the serve loop itself).
"""

import io
import contextlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from traffic_classifier_sdn_tpu import cli
from traffic_classifier_sdn_tpu.serving.pipeline import (
    Handoff,
    ServePipeline,
)
from traffic_classifier_sdn_tpu.utils.metrics import global_metrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Handoff / ServePipeline units
# ---------------------------------------------------------------------------


def test_handoff_bounded_with_coalescing_backpressure():
    h = Handoff(depth=2)
    assert h.put("t0") and h.put("t1")
    # full: the new tick coalesces into the NEWEST staged slot — the
    # queue never grows past depth and the host stage never blocks
    assert not h.put("t2")
    assert h.queued == 2 and h.coalesced == 1
    assert h.get(timeout=0) == "t0"
    assert h.get(timeout=0) == "t2"  # t1 was superseded
    assert h.get(timeout=0) is None  # empty → timeout, not blocking


def test_handoff_custom_merge():
    h = Handoff(depth=1, merge=lambda staged, new: staged + new)
    h.put([1])
    h.put([2])
    h.put([3])
    assert h.coalesced == 2
    assert h.get(timeout=0) == [1, 2, 3]


def test_handoff_join_waits_for_inflight():
    h = Handoff(depth=2)
    h.put("job")
    assert not h.join(timeout=0.05)  # still staged
    assert h.get(timeout=0) == "job"
    assert not h.join(timeout=0.05)  # in flight until done()
    h.done()
    assert h.join(timeout=1)


def test_pipeline_runs_jobs_in_order_and_drains():
    done = []
    # depth 32 >> item count: no coalescing, so every item must arrive,
    # in submission order
    pipe = ServePipeline(done.append, depth=32).start()
    try:
        for i in range(16):
            pipe.submit(i)
        assert pipe.drain(timeout=5)
    finally:
        pipe.shutdown(drain=False)
    assert done == list(range(16))


def test_pipeline_propagates_device_stage_exception():
    boom = ValueError("device stage died")

    def consume(job):
        raise boom

    pipe = ServePipeline(consume).start()
    try:
        pipe.submit("job")
        deadline = time.monotonic() + 5
        while not pipe.failed() and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(ValueError) as ei:
            pipe.submit("next")
        assert ei.value is boom  # the original exception, not a wrapper
        with pytest.raises(ValueError):
            pipe.drain(timeout=1)
    finally:
        pipe.shutdown(drain=False)


def test_pipeline_overlap_accounting():
    release = threading.Event()

    def consume(job):
        release.wait(timeout=5)  # device busy while the host works

    pipe = ServePipeline(consume).start()
    try:
        with pipe.host_stage():
            pipe.submit("job")
            time.sleep(0.05)  # host busy while the device job runs
        release.set()
        assert pipe.drain(timeout=5)
        s = pipe.stats()
        assert s["host_busy_s"] > 0
        assert s["device_busy_s"] > 0
        # the device job ran inside the host busy window → real overlap
        assert s["overlap_s"] > 0.02
    finally:
        release.set()
        pipe.shutdown(drain=False)


# ---------------------------------------------------------------------------
# Serial vs pipelined serve: byte-identical output
# ---------------------------------------------------------------------------


def _native_checkpoint(tmp_path, family):
    """Self-contained model checkpoints (no reference pickles needed)."""
    from traffic_classifier_sdn_tpu.io import checkpoint as ck

    rng = np.random.RandomState(0)
    if family == "gnb":
        from traffic_classifier_sdn_tpu.models import gnb

        params = gnb.from_numpy({
            "theta": rng.gamma(2.0, 100.0, (2, 12)),
            "var": rng.gamma(2.0, 50.0, (2, 12)) + 1.0,
            "class_prior": np.full(2, 0.5),
        })
    else:  # knn
        from traffic_classifier_sdn_tpu.train import knn as tknn

        X = rng.rand(64, 12).astype(np.float32) * 100
        y = rng.randint(0, 2, 64)
        params = tknn.fit(X, y, n_neighbors=3, n_classes=2)
    path = str(tmp_path / f"{family}_ckpt")
    ck.save_model(path, family, params, classes=("ping", "voice"))
    return path


def _serve(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(argv)
    return buf.getvalue()


def _common(ckpt, subcommand="gaussiannb"):
    return [
        subcommand,
        "--native-checkpoint", ckpt,
        "--source", "synthetic",
        "--synthetic-flows", "16",
        "--capacity", "64",
        "--print-every", "2",
        "--max-ticks", "6",
        "--idle-timeout", "0",
        "--table-rows", "8",
    ]


def test_pipelined_matches_serial_ranked(tmp_path):
    common = _common(_native_checkpoint(tmp_path, "gnb"))
    serial = _serve(common + ["--pipeline", "off"])
    pipelined = _serve(common + ["--pipeline", "on"])
    assert "Flow ID" in serial and "... showing 8 of 16" in serial
    assert pipelined == serial


def test_pipelined_matches_serial_full_table(tmp_path):
    common = _common(_native_checkpoint(tmp_path, "gnb"))
    common[common.index("--table-rows") + 1] = "0"
    serial = _serve(common + ["--pipeline", "off"])
    pipelined = _serve(common + ["--pipeline", "on"])
    assert serial.count("Flow ID") == 3  # 3 renders in 6 ticks
    assert pipelined == serial


def test_pipelined_matches_serial_host_native(tmp_path, monkeypatch):
    """Host-native kernels serve through a plain worker thread (the C++
    predict drops the GIL); the rendered rows must still be
    byte-identical to the serial host-native serve."""
    from traffic_classifier_sdn_tpu.native import knn as native_knn

    if not native_knn.available():
        pytest.skip("g++ unavailable — no host-native kernel to serve")
    monkeypatch.setenv("TCSDN_KNN_TOPK", "native")
    common = _common(
        _native_checkpoint(tmp_path, "knn"), subcommand="knearest"
    )
    serial = _serve(common + ["--pipeline", "off"])
    pipelined = _serve(common + ["--pipeline", "on"])
    assert "Flow ID" in serial
    assert pipelined == serial


def test_pipelined_matches_serial_sharded(tmp_path):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("sharded serve needs the conftest's 8-device mesh")
    common = _common(_native_checkpoint(tmp_path, "gnb"))
    common += ["--shards", "8"]
    serial = _serve(common + ["--pipeline", "off"])
    pipelined = _serve(common + ["--pipeline", "on"])
    assert "Flow ID" in serial
    assert pipelined == serial


# ---------------------------------------------------------------------------
# Satellite: flows_dropped gauge freshness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pipeline", ["off", "on"])
def test_flows_dropped_gauge_fresh_between_renders(tmp_path, pipeline):
    """m.set("flows_dropped", ...) used to run only inside the
    print_every gate, so a /metrics scrape between renders read a value
    up to N ticks stale. It must track the engine every tick — here the
    run drops flows from tick one but never reaches a render tick."""
    ckpt = _native_checkpoint(tmp_path, "gnb")
    cli.main([
        "gaussiannb",
        "--native-checkpoint", ckpt,
        "--source", "synthetic",
        "--synthetic-flows", "64",
        "--capacity", "4",
        "--print-every", "1000",  # never renders in 3 ticks
        "--max-ticks", "3",
        "--idle-timeout", "0",
        "--pipeline", pipeline,
    ])
    dropped = global_metrics.gauges.get("flows_dropped")
    assert dropped is not None and dropped > 0


# ---------------------------------------------------------------------------
# Warmup: AOT compile at startup, not at tick one
# ---------------------------------------------------------------------------


def _gnb_predict_and_params():
    from traffic_classifier_sdn_tpu.models import gnb, jit_serving_fn

    rng = np.random.RandomState(0)
    params = gnb.from_numpy({
        "theta": rng.gamma(2.0, 100.0, (6, 12)),
        "var": rng.gamma(2.0, 50.0, (6, 12)) + 1.0,
        "class_prior": np.full(6, 1 / 6),
    })
    return jit_serving_fn(gnb.predict), params


def test_warmup_first_tick_compiles_nothing(tmp_path):
    """After warmup_serving, one full serve tick's device programs are
    all cache hits: the jitted serving callables trace/compile zero new
    entries, and the persistent compilation cache (the tempdir) holds
    what warmup compiled — the restart-hot story."""
    import jax

    from traffic_classifier_sdn_tpu.core import flow_table as ft
    from traffic_classifier_sdn_tpu.ingest.batcher import (
        FlowStateEngine,
        apply_wire_jit,
    )
    from traffic_classifier_sdn_tpu.ingest.replay import SyntheticFlows
    from traffic_classifier_sdn_tpu.serving import warmup as wu

    cache_dir = str(tmp_path / "jit-cache")
    wu.enable_compilation_cache(cache_dir)
    try:
        predict, params = _gnb_predict_and_params()
        engine = FlowStateEngine(capacity=256)
        stats = wu.warmup_serving(
            engine, predict, params, table_rows=16, idle_timeout=60,
        )
        assert "predict" in stats["warmed"]
        assert any(w.startswith("apply_wire[") for w in stats["warmed"])
        assert os.listdir(cache_dir)  # compiles persisted to disk

        c_pred = predict._cache_size()
        c_apply = apply_wire_jit._cache_size()
        syn = SyntheticFlows(n_flows=64)
        engine.mark_tick()
        engine.ingest(syn.tick())
        engine.step()
        labels = predict(params, engine.features())
        outs = ft.top_active_render(
            engine.table, labels, 16, np.int32(engine.tick_floor)
        )
        jax.block_until_ready(outs)
        # tick one re-traced/compiled NOTHING — the stall is gone
        assert predict._cache_size() == c_pred
        assert apply_wire_jit._cache_size() == c_apply
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_warmup_removes_first_tick_stall_in_tick_span(tmp_path):
    """End-to-end: a cold serve's first `tick` span carries the compile
    stall; a warmed serve's does not. Compared within one process (the
    cold run is measured FIRST, while the jit caches are genuinely
    cold), using the stage_tick_s histogram the span tracer feeds."""
    ckpt = _native_checkpoint(tmp_path, "gnb")
    cache_dir = str(tmp_path / "jit-cache")
    argv = [
        "gaussiannb",
        "--native-checkpoint", ckpt,
        "--source", "synthetic",
        "--synthetic-flows", "32",
        "--capacity", "128",
        "--print-every", "1",
        "--max-ticks", "4",
        "--idle-timeout", "0",
        "--table-rows", "8",
        "--compilation-cache-dir", cache_dir,
    ]
    import jax

    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(buf):
            cli.main(argv)
        cold_first = global_metrics.histograms["stage_tick_s"]._samples[0]
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(buf):
            cli.main(argv + ["--warmup"])
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
    assert "warmup: compiled" in buf.getvalue()
    h = global_metrics.histograms["stage_tick_s"]
    warm_first, steady = h._samples[0], h._samples[1:]
    # the compile stall (hundreds of ms) dwarfs a warm tick (ms); a
    # generous 4x margin keeps CI scheduler noise out of the assertion
    assert warm_first < cold_first / 4
    # and the warmed first tick is steady-state-like: the acceptance
    # bound (first-tick p99 < 2x steady p50) with slack for CI jitter
    assert warm_first < max(4 * float(np.median(steady)), 0.25)


# ---------------------------------------------------------------------------
# Satellite: the bench's pipeline path runs end-to-end in tier-1
# ---------------------------------------------------------------------------


def test_bench_serve_pipeline_ab_smoke():
    """tools/bench_serve.py --pipeline both at toy scale: the pipelined
    serve path is EXECUTED (not just unit-tested) on every tier-1 run,
    and the A/B JSON tail carries the acceptance fields."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "bench_serve.py"),
         "--capacity", "1024", "--ticks", "3", "--table-rows", "16",
         "--pipeline", "both", "--warmup"],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    tail = json.loads(out.stdout.strip().splitlines()[-1])
    assert tail["metric"] == "serve_pipeline_ab"
    for mode in ("serial", "pipelined"):
        assert tail[mode]["serve_flows_per_sec"] > 0
        assert "first_tick_ms" in tail[mode]
    assert "speedup_flows_per_sec" in tail
    assert "overlap_ratio" in tail["pipelined"]
    assert tail["pipelined"]["ticks_coalesced"] >= 0
