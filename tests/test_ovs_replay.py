"""OpenFlow 1.3 interop beyond our own fake switch (VERDICT r3 item 7).

No Open vSwitch binary exists in this image, so a live OVS smoke test is
impossible; this is the capture-replay equivalent: a scripted peer speaks
HAND-ASSEMBLED golden bytes to the real asyncio controller over TCP —
every message packed field-by-field from the OpenFlow 1.3.5 wire layouts
with explicit struct formats and offsets, never via
``controller/openflow.py``'s encoder — so an encode/decode bug that is
symmetric in our codec (the failure mode a fake-switch test cannot see)
breaks these tests.

The byte streams replicate what a real OVS 2.x emits, including its
quirks our fake switch does not exercise:
  - OFPT_HELLO carrying an OFPHET_VERSIONBITMAP element (length 16, not
    a bare 8-byte header),
  - OFPT_FEATURES_REPLY with n_buffers=0 (modern OVS disables packet
    buffering) and capabilities 0x4f,
  - OFPT_ECHO_REQUEST with a payload that must be echoed verbatim,
  - OFPT_PACKET_IN with reason=OFPR_ACTION, a 16-byte OXM match
    (in_port + 4 pad) and the 2 alignment bytes before the frame,
  - OFPMP_FLOW reply whose entries carry nonzero duration/idle/flags
    fields, a priority-0 table-miss entry (empty match, CONTROLLER
    output) the monitor must filter out, and priority-1 entries with
    (in_port, eth_src, eth_dst) OXM matches and APPLY_ACTIONS/OUTPUT
    instructions.

Assertions run in both directions: the controller's replies are parsed
with the same hand-written framing (not our MessageReader), and the
monitor's TSV telemetry must carry exactly the golden counters.

Reference behavior being interoperated with: ``sudo ryu run
simple_monitor_13.py`` against a live OVS bridge
(/root/reference/README.md:26-35, simple_monitor_13.py:43-47).
"""

import asyncio
import io
import struct

from traffic_classifier_sdn_tpu.controller.switch import Controller
from traffic_classifier_sdn_tpu.ingest.protocol import parse_line

# -- hand framing (deliberately NOT of.MessageReader) -----------------------

HDR = struct.Struct("!BBHI")  # version, type, length, xid


async def read_msg(reader):
    hdr = await asyncio.wait_for(reader.readexactly(8), timeout=5.0)
    version, mtype, length, xid = HDR.unpack(hdr)
    assert version == 0x04, f"controller sent version {version}"
    body = await asyncio.wait_for(
        reader.readexactly(length - 8), timeout=5.0
    )
    return mtype, xid, body


def msg(mtype: int, xid: int, body: bytes = b"") -> bytes:
    return HDR.pack(0x04, mtype, 8 + len(body), xid) + body


# -- golden OVS-style messages, packed field by field -----------------------

DPID = 0x0000_1122_3344_5566


def ovs_hello(xid: int) -> bytes:
    # OFPHET_VERSIONBITMAP element: type=1 len=8, bitmap bit 4 (=0x10)
    elem = struct.pack("!HH", 1, 8) + struct.pack("!I", 0x10)
    return msg(0, xid, elem)  # OFPT_HELLO


def ovs_features_reply(xid: int) -> bytes:
    # datapath_id(8) n_buffers(4) n_tables(1) auxiliary_id(1) pad(2)
    # capabilities(4) reserved(4); OVS: n_buffers=0, n_tables=254
    body = struct.pack("!QIBB2xII", DPID, 0, 254, 0, 0x0000004F, 0)
    return msg(6, xid, body)  # OFPT_FEATURES_REPLY


def oxm_in_port(port: int) -> bytes:
    # class 0x8000, field 0 (IN_PORT), no mask, len 4
    return struct.pack("!I", 0x8000_0004) + struct.pack("!I", port)


def oxm_eth(field: int, mac: bytes) -> bytes:
    # field 3 = ETH_DST, 4 = ETH_SRC; header class<<16|field<<9|len
    return struct.pack("!I", (0x8000 << 16) | (field << 9) | 6) + mac


def match_in_port(port: int) -> bytes:
    # ofp_match: type=1 (OXM), length=4+8=12, then pad to 16
    return struct.pack("!HH", 1, 12) + oxm_in_port(port) + b"\x00" * 4


def match_learned(port: int, src: bytes, dst: bytes) -> bytes:
    # in_port(8) + eth_dst(10) + eth_src(10) OXMs: length 4+28=32,
    # already 8-aligned -> no pad
    fields = oxm_in_port(port) + oxm_eth(3, dst) + oxm_eth(4, src)
    return struct.pack("!HH", 1, 4 + len(fields)) + fields


def ovs_packet_in(xid: int, in_port: int, frame: bytes) -> bytes:
    # buffer_id(4) total_len(2) reason(1)=OFPR_ACTION table_id(1)
    # cookie(8), match, 2 pad bytes, frame
    head = struct.pack("!IHBBQ", 0xFFFFFFFF, len(frame), 1, 0, 0)
    return msg(10, xid, head + match_in_port(in_port) + b"\x00\x00" + frame)


def flow_entry(priority: int, match: bytes, instructions: bytes,
               packets: int, byts: int) -> bytes:
    # ofp_flow_stats: length(2) table_id(1) pad(1) duration_sec(4)
    # duration_nsec(4) priority(2) idle(2) hard(2) flags(2) pad(4)
    # cookie(8) packet_count(8) byte_count(8)
    length = 48 + len(match) + len(instructions)
    head = struct.pack(
        "!HBxIIHHHH4xQQQ",
        length, 0, 1234, 567000000, priority, 0, 0, 0x0001,
        0xDEADBEEF, packets, byts,
    )
    return head + match + instructions


def instr_output(port: int, max_len: int = 0xFFFF) -> bytes:
    # OFPIT_APPLY_ACTIONS(4) len 24, pad(4); OFPAT_OUTPUT(0) len 16,
    # port(4) max_len(2) pad(6)
    action = struct.pack("!HHIH6x", 0, 16, port, max_len)
    return struct.pack("!HH4x", 4, 8 + len(action)) + action


HOST_A = bytes.fromhex("0a0000000001")
HOST_B = bytes.fromhex("0a0000000002")


def ovs_flow_stats_reply(xid: int) -> bytes:
    # type(2)=OFPMP_FLOW flags(2)=0 pad(4), then entries: the priority-0
    # table-miss first (OVS dump order), then two learned flows
    miss_match = struct.pack("!HH", 1, 4) + b"\x00" * 4
    entries = (
        flow_entry(0, miss_match, instr_output(0xFFFFFFFD), 99, 9999)
        + flow_entry(
            1, match_learned(1, HOST_A, HOST_B), instr_output(2), 10, 1000
        )
        + flow_entry(
            1, match_learned(2, HOST_B, HOST_A), instr_output(1), 20, 2000
        )
    )
    return msg(19, xid, struct.pack("!HH4x", 1, 0) + entries)


def eth(dst: bytes, src: bytes, payload: bytes = b"x" * 46) -> bytes:
    return dst + src + struct.pack("!H", 0x0800) + payload


# -- the scripted session ---------------------------------------------------


async def _scripted_session():
    out = io.StringIO()
    ctl = Controller(host="127.0.0.1", port=0, poll_interval=0.05, out=out)
    await ctl.start()
    reader, writer = await asyncio.open_connection(
        "127.0.0.1", ctl.bound_port
    )
    seen: dict = {
        "flow_mods": [], "packet_outs": [], "echo": None, "hello": False
    }
    try:
        writer.write(ovs_hello(0x2A))
        await writer.drain()

        # controller greets with HELLO + FEATURES_REQUEST
        deadline = asyncio.get_event_loop().time() + 5.0
        features_xid = None
        while features_xid is None:
            mtype, xid, body = await read_msg(reader)
            if mtype == 0:
                seen["hello"] = True
            elif mtype == 5:
                features_xid = xid
        writer.write(ovs_features_reply(features_xid))
        # a keepalive echo with payload, mid-handshake
        writer.write(msg(2, 0x77, b"ovs-echo"))
        await writer.drain()

        # expect: echo reply (verbatim payload) + table-miss flow-mod;
        # then the 0.05 s poller starts asking for stats
        got_miss = False
        stats_xid = None
        while not (got_miss and seen["echo"] and stats_xid):
            mtype, xid, body = await read_msg(reader)
            if mtype == 3:
                seen["echo"] = body
            elif mtype == 14:
                seen["flow_mods"].append(body)
                prio = struct.unpack_from("!H", body, 22)[0]
                if prio == 0:
                    got_miss = True
            elif mtype == 18:
                if struct.unpack_from("!H", body, 0)[0] == 1:  # OFPMP_FLOW
                    stats_xid = xid

        # packet-in A->B (dst unknown: flood, no flow-mod), then B->A
        # (dst known: priority-1 flow-mod + packet-out)
        writer.write(ovs_packet_in(0x100, 1, eth(HOST_B, HOST_A)))
        writer.write(ovs_packet_in(0x101, 2, eth(HOST_A, HOST_B)))
        # answer the poller with the golden stats so the monitor renders
        writer.write(ovs_flow_stats_reply(stats_xid))
        await writer.drain()

        n_flow_mods = len(seen["flow_mods"])
        end = asyncio.get_event_loop().time() + 3.0
        while asyncio.get_event_loop().time() < end:
            try:
                mtype, xid, body = await asyncio.wait_for(
                    read_msg(reader), timeout=0.3
                )
            except asyncio.TimeoutError:
                if (
                    len(seen["packet_outs"]) >= 2
                    and len(seen["flow_mods"]) > n_flow_mods
                    and "data\t" in out.getvalue()
                ):
                    break
                continue
            if mtype == 13:
                seen["packet_outs"].append(body)
            elif mtype == 14:
                seen["flow_mods"].append(body)
            elif mtype == 18:
                if struct.unpack_from("!H", body, 0)[0] == 1:
                    writer.write(ovs_flow_stats_reply(xid))
                    await writer.drain()
    finally:
        writer.close()
        registered = dict(ctl.datapaths)
        await ctl.stop()
    return seen, registered, out.getvalue()


def _session():
    return asyncio.run(_scripted_session())


def test_ovs_style_handshake_and_learning():
    seen, registered, telemetry = _session()
    assert seen["hello"], "controller never sent HELLO"
    assert seen["echo"] == b"ovs-echo", "echo payload not returned verbatim"
    assert DPID in registered, "datapath with OVS-style features not registered"

    # table-miss flow-mod: priority 0, CONTROLLER output, decoded by hand
    miss = [
        b for b in seen["flow_mods"]
        if struct.unpack_from("!H", b, 22)[0] == 0
    ]
    assert miss, "no table-miss flow-mod installed"
    assert struct.pack("!I", 0xFFFFFFFD) in miss[0]  # OFPP_CONTROLLER

    # learned flow-mod for B->A (in_port=2, dst=HOST_A known): priority 1,
    # output port 1
    learned = [
        b for b in seen["flow_mods"]
        if struct.unpack_from("!H", b, 22)[0] == 1
    ]
    assert learned, "no priority-1 flow-mod after packet-in with known dst"
    body = learned[0]
    assert oxm_eth(3, HOST_A) in body, "learned match lacks eth_dst OXM"
    assert oxm_eth(4, HOST_B) in body, "learned match lacks eth_src OXM"
    # the OUTPUT action targets port 1 (where HOST_A was learned)
    assert struct.pack("!HHIH", 0, 16, 1, 0xFFFF) in body

    # both packet-ins were answered with packet-outs carrying the frame
    assert len(seen["packet_outs"]) >= 2
    assert any(eth(HOST_B, HOST_A) in b for b in seen["packet_outs"])


def test_ovs_style_stats_render_telemetry():
    _seen, _registered, telemetry = _session()
    rows = [
        parse_line((ln + "\n").encode())
        for ln in telemetry.splitlines()
        if ln.startswith("data\t")
    ]
    rows = [r for r in rows if r is not None]
    assert rows, f"no parseable telemetry rows in:\n{telemetry}"
    # the priority-0 table-miss entry (packets=99) must be filtered out
    assert all(r.packets != 99 for r in rows)
    # golden counters from the hand-packed multipart reply, sorted by
    # (in_port, eth_dst) exactly like simple_monitor_13.py:53-56
    a_to_b = [r for r in rows if r.packets == 10]
    b_to_a = [r for r in rows if r.packets == 20]
    assert a_to_b and a_to_b[0].bytes == 1000
    assert b_to_a and b_to_a[0].bytes == 2000
    first_pair = (rows[0].packets, rows[1].packets)
    assert first_pair == (10, 20), f"sort order wrong: {first_pair}"
