"""Native C++ forest evaluator (native/forest_eval.cpp) vs the oracles.

The evaluator claims BITWISE argmax parity with the numpy level-synchronous
oracle (bench._numpy_forest_labels): identical float64 addends accumulated
in identical tree order, first-max argmax. These tests assert that against
the reference checkpoint, against freshly-fit irregular sklearn forests
(variable leaf depths, padded node arrays — the shapes the DFS-preorder
re-layout must survive), and on adversarial exact ties.
"""

import os

import numpy as np
import pytest

from traffic_classifier_sdn_tpu.native import forest as native_forest

pytestmark = pytest.mark.skipif(
    not native_forest.available(),
    reason="g++ build unavailable",
)


@pytest.fixture(scope="module")
def forest_dict(reference_models_dir):
    from traffic_classifier_sdn_tpu.io import sklearn_import as ski

    return ski.import_forest(
        os.path.join(reference_models_dir, "RandomForestClassifier")
    )


def _oracle(d, X):
    import bench

    return bench._numpy_forest_labels(d, np.asarray(X, np.float64))


def _dict_from_sklearn(est):
    """The importer's OWN packing for a live estimator — fuzz exercises
    exactly the production (T, M) layout, not a test re-implementation."""
    from traffic_classifier_sdn_tpu.io import sklearn_import as ski

    return ski.forest_dict_from_estimator(est)


def test_parity_reference_rows(forest_dict, flow_dataset):
    f = native_forest.NativeForest(forest_dict)
    got = f.predict(flow_dataset.X.astype(np.float32))
    want = _oracle(forest_dict, flow_dataset.X)
    np.testing.assert_array_equal(got, want)


def test_parity_vs_xla_gather(forest_dict, flow_dataset):
    """Same labels as the XLA gather traversal (the semantic reference
    every TPU kernel is tested against) on the bench's own float
    distribution."""
    import jax
    import jax.numpy as jnp

    from traffic_classifier_sdn_tpu.models import forest as forest_mod

    rng = np.random.RandomState(0)
    X = np.abs(rng.gamma(1.5, 200.0, (2048, 12))).astype(np.float32)
    f = native_forest.NativeForest(forest_dict)
    p = forest_mod.from_numpy(forest_dict, dtype=jnp.float32)
    got = f.predict(X)
    want = np.asarray(jax.jit(forest_mod.predict)(p, jnp.asarray(X)))
    np.testing.assert_array_equal(got, want)


def test_fuzz_irregular_sklearn_forests():
    """Freshly-fit forests: variable leaf depths, (T, M) padding, tied
    duplicate rows — walked by both the C++ evaluator and the numpy
    oracle, including far-out-of-training-range queries."""
    import warnings

    warnings.filterwarnings("ignore")
    from sklearn.ensemble import RandomForestClassifier

    rng = np.random.RandomState(42)
    for trial in range(4):
        n = 300 + 50 * trial
        # few distinct feature values -> massively tied thresholds
        Xt = rng.randint(0, 5, (n, 12)).astype(np.float64)
        yt = rng.randint(0, 4, n)
        est = RandomForestClassifier(
            n_estimators=5 + trial * 3,
            max_depth=None if trial % 2 else 4,
            random_state=trial,
        ).fit(Xt, yt)
        d = _dict_from_sklearn(est)
        f = native_forest.NativeForest(d)
        Xq = np.concatenate([
            rng.randint(0, 5, (256, 12)).astype(np.float32),
            (rng.rand(64, 12) * 1e6).astype(np.float32),
            np.zeros((8, 12), np.float32),
        ])
        np.testing.assert_array_equal(
            f.predict(Xq), _oracle(d, Xq), err_msg=f"{trial=}"
        )


def test_argmax_first_max_on_exact_ties():
    """Two single-split trees whose leaf distributions sum to exact ties:
    np.argmax takes the first maximum, and so must the C++ walk."""
    # both trees: root splits feature 0 at 10.0; leaves vote classes
    # (1,2) and (2,1) with weight 1 -> summed dist ties classes 1 and 2
    left = np.array([[1, -1, -1]] * 2, np.int32)
    right = np.array([[2, -1, -1]] * 2, np.int32)
    feature = np.zeros((2, 3), np.int32)
    threshold = np.array([[10.0, 0.0, 0.0]] * 2)
    values = np.zeros((2, 3, 4))
    values[0, 1] = [0, 4, 0, 0]   # tree0 left leaf -> class 1
    values[0, 2] = [0, 0, 4, 0]   # tree0 right leaf -> class 2
    values[1, 1] = [0, 0, 4, 0]   # tree1 left leaf -> class 2
    values[1, 2] = [0, 4, 0, 0]   # tree1 right leaf -> class 1
    d = {
        "left": left, "right": right, "feature": feature,
        "threshold": threshold, "values": values, "max_depth": 1,
        "classes": np.arange(4), "n_features": 12,
    }
    f = native_forest.NativeForest(d)
    X = np.zeros((2, 12), np.float32)
    X[1, 0] = 99.0  # row 0 goes left+left, row 1 right+right: both tie
    got = f.predict(X)
    np.testing.assert_array_equal(got, _oracle(d, X))
    assert (got == 1).all()  # first maximum, never class 2


def test_midpoint_threshold_rounds_down_like_sklearn():
    """f32-unsafe midpoint regression (ADVICE r5 high): sklearn stores
    float64 midpoints of adjacent float32 feature values and compares
    ``f32(x) <= f64(thr)``. Pick adjacent f32 values a < b whose f64
    midpoint rounds UP to b under a plain f32 cast (ties-to-even with b
    the even mantissa): a query at exactly b must go RIGHT (b > thr in
    f64), but a plain-cast walk compares b <= f32(thr) == b and goes
    left. The fuzz suite cannot catch this — its small-integer features
    have f32-exact midpoints — so this pins the f32_safe_thresholds
    routing directly."""
    a = np.float32(np.nextafter(np.float32(1.0), np.float32(2.0)))
    b = np.float32(np.nextafter(a, np.float32(2.0)))
    thr = (np.float64(a) + np.float64(b)) / 2.0
    # the premise of the regression: the plain cast rounds up to b
    assert np.float32(thr) == b and np.float64(np.float32(thr)) > thr
    left = np.array([[1, -1, -1]], np.int32)
    right = np.array([[2, -1, -1]], np.int32)
    feature = np.zeros((1, 3), np.int32)
    threshold = np.array([[thr, 0.0, 0.0]], np.float64)
    values = np.zeros((1, 3, 2))
    values[0, 1] = [4, 0]  # left leaf -> class 0
    values[0, 2] = [0, 4]  # right leaf -> class 1
    d = {
        "left": left, "right": right, "feature": feature,
        "threshold": threshold, "values": values, "max_depth": 1,
        "classes": np.arange(2), "n_features": 12,
    }
    f = native_forest.NativeForest(d)
    X = np.zeros((2, 12), np.float32)
    X[0, 0] = b  # exactly the upper adjacent value: must go right
    X[1, 0] = a  # clearly below the midpoint: must go left
    got = f.predict(X)
    np.testing.assert_array_equal(got, _oracle(d, X))
    np.testing.assert_array_equal(got, [1, 0])


def test_nonfinite_features_match_oracle(forest_dict):
    """-inf / NaN / +inf feature values: numpy's `x <= thr` is True for
    -inf and False for NaN, and the walk must terminate at a real leaf
    either way — the leaf sentinel is a NaN threshold precisely so a
    -inf query cannot defeat the self-loop and march off the node array."""
    f = native_forest.NativeForest(forest_dict)
    X = np.zeros((6, 12), np.float32)
    X[0, :] = -np.inf
    X[1, :] = np.inf
    X[2, :] = np.nan
    X[3, 0] = -np.inf
    X[4, 5] = np.nan
    X[5, 11] = np.inf
    np.testing.assert_array_equal(f.predict(X), _oracle(forest_dict, X))


def test_narrow_feature_matrix_rejected(forest_dict):
    f = native_forest.NativeForest(forest_dict)
    with pytest.raises(ValueError, match="too narrow"):
        f.predict(np.zeros((4, 8), np.float32))
    with pytest.raises(ValueError, match="too narrow"):
        f.predict_proba(np.zeros((4, 3), np.float32))


def test_degenerate_single_node_trees():
    """Root-is-leaf trees (sklearn produces them on constant labels)."""
    d = {
        "left": np.full((3, 1), -1, np.int32),
        "right": np.full((3, 1), -1, np.int32),
        "feature": np.zeros((3, 1), np.int32),
        "threshold": np.zeros((3, 1)),
        "values": np.array([[[5.0, 1.0]], [[0.0, 3.0]], [[2.0, 2.0]]]),
        "max_depth": 0, "classes": np.arange(2), "n_features": 12,
    }
    f = native_forest.NativeForest(d)
    X = np.ones((7, 12), np.float32)
    np.testing.assert_array_equal(f.predict(X), _oracle(d, X))


def test_predict_proba_matches_oracle_distribution(forest_dict,
                                                   flow_dataset):
    """tcf_proba returns the oracle's mean normalized distribution
    bitwise (same addends, same order, same /T)."""
    import bench

    X = flow_dataset.X[:512]
    f = native_forest.NativeForest(forest_dict)
    got = f.predict_proba(X.astype(np.float32))
    d = forest_dict
    n_trees = d["left"].shape[0]
    probs = np.zeros((X.shape[0], d["values"].shape[2]))
    rows = np.arange(X.shape[0])
    for t in range(n_trees):
        left, right = d["left"][t], d["right"][t]
        feat, thr, vals = d["feature"][t], d["threshold"][t], d["values"][t]
        node = np.zeros(X.shape[0], np.int64)
        active = left[node] != -1
        while active.any():
            fi = feat[node]
            go_left = X[rows, fi] <= thr[node]
            node = np.where(
                active, np.where(go_left, left[node], right[node]), node
            )
            active = left[node] != -1
        v = vals[node]
        probs += v / v.sum(axis=1, keepdims=True)
    np.testing.assert_array_equal(got, probs / n_trees)
    # and the labels the bench gate asserts are argmax of exactly this
    np.testing.assert_array_equal(
        f.predict(X.astype(np.float32)),
        bench._numpy_forest_labels(d, X),
    )
