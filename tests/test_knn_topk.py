"""The --knn-topk flag and the serving byte-identity across KNN tiers.

Every EXACT top-k implementation must render byte-identical serve
output — serial and pipelined, --incremental auto and off — because
selection never changes semantics, only speed (models/__init__.py).
The corpus is integer-valued so even the native tier's exact-f64
ranking agrees with the f32 device ranking (every distance exactly
representable — the adversarial-tie-suite trick), putting `native`
inside the byte-identity matrix instead of behind its documented
near-tie divergence. The flag beats the env var, unknown values are a
clean usage error (exit 2, no traceback), and the approximate tier
stays behind its explicit opt-in.
"""

import contextlib
import io

import numpy as np
import pytest

from traffic_classifier_sdn_tpu import cli
from traffic_classifier_sdn_tpu.ingest.protocol import (
    TelemetryRecord,
    format_line,
)
from traffic_classifier_sdn_tpu.models import resolve_knn_topk


def _rec(t, i, pkts, bts):
    return TelemetryRecord(
        time=t, datapath="1", in_port=1, eth_src=f"f{i:03d}",
        eth_dst="gw", out_port=2, packets=pkts, bytes=bts,
    )


@pytest.fixture(scope="module")
def knn_serve(tmp_path_factory):
    """(checkpoint, capture) — a synthetic integer-valued KNN corpus
    checkpoint plus a varying-churn replay capture."""
    from traffic_classifier_sdn_tpu.io import checkpoint as ck
    from traffic_classifier_sdn_tpu.train import knn as tknn

    tmp = tmp_path_factory.mktemp("knn_topk")
    rng = np.random.RandomState(0)
    X = rng.randint(0, 50, (64, 12)).astype(np.float64)
    y = rng.randint(0, 2, 64)
    params = tknn.fit(X, y, n_neighbors=3, n_classes=2)
    ckpt = str(tmp / "knn_ckpt")
    ck.save_model(ckpt, "knn", params, classes=("ping", "voice"))
    cum = {}
    lines = []
    for t, flows in enumerate([range(24), range(4), range(16)], start=1):
        for i in flows:
            p, b = cum.get(i, (0, 0))
            p += 5 + i
            b += 900 + 17 * i
            cum[i] = (p, b)
            lines.append(format_line(_rec(t, i, p, b)))
    cap = tmp / "churn.capture"
    cap.write_bytes(b"".join(lines))
    return ckpt, str(cap)


def _serve(knn_serve, extra):
    ckpt, cap = knn_serve
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main([
            "knearest", "--native-checkpoint", ckpt,
            "--source", "replay", "--capture", cap,
            "--capacity", "64", "--print-every", "1",
            "--idle-timeout", "0", "--table-rows", "8",
        ] + extra)
    return buf.getvalue()


def test_exact_tiers_render_byte_identical(knn_serve):
    from traffic_classifier_sdn_tpu.native import knn as native_knn

    base = _serve(knn_serve, ["--knn-topk", "sort"])
    assert base.count("Flow ID") == 3
    impls = ["argmax", "hier", "screened", "screened16"]
    if native_knn.available():
        impls.append("native")
    for impl in impls:
        for pipeline in ("off", "on"):
            for inc in ("auto", "off"):
                out = _serve(knn_serve, [
                    "--knn-topk", impl, "--pipeline", pipeline,
                    "--incremental", inc,
                ])
                assert out == base, (impl, pipeline, inc)


def test_ivf_opt_in_serves(knn_serve, capsys):
    """The approximate tier serves behind the explicit flag — and says
    so on stderr (the opt-in NOTE; once per process, so reset the
    warn-once set — another suite may already have consumed it)."""
    import traffic_classifier_sdn_tpu.models as models

    models._KNN_TOPK_WARNED.discard("ivf")
    out = _serve(knn_serve, ["--knn-topk", "ivf"])
    assert "Flow ID" in out
    err = capsys.readouterr().err
    assert "APPROXIMATE" in err


def test_unknown_value_is_clean_usage_error(knn_serve, capsys):
    with pytest.raises(SystemExit) as ei:
        _serve(knn_serve, ["--knn-topk", "bogus"])
    assert ei.value.code == 2  # argparse usage error, not a traceback
    assert "unknown KNN top-k" in capsys.readouterr().err


def test_flag_wins_over_env(knn_serve, monkeypatch):
    base = _serve(knn_serve, ["--knn-topk", "sort"])
    # a poisoned env var loses to the flag...
    monkeypatch.setenv("TCSDN_KNN_TOPK", "native")
    assert _serve(knn_serve, ["--knn-topk", "sort"]) == base
    # ...and an INVALID env value without the flag still errors cleanly
    # at serving-path build (resolve_knn_topk owns validation)
    monkeypatch.setenv("TCSDN_KNN_TOPK", "wat")
    with pytest.raises(ValueError, match="unknown KNN top-k"):
        _serve(knn_serve, [])


def test_resolve_validates_names(monkeypatch):
    monkeypatch.delenv("TCSDN_KNN_TOPK", raising=False)
    assert resolve_knn_topk() == "sort"
    for ok in ("sort", "argmax", "hier", "hier512", "screened",
               "screened16", "pallas", "native", "ivf", "ivf4"):
        assert resolve_knn_topk(ok) == ok
    for bad in ("bogus", "hier512x", "screened-8", "ivf4.5", "IVF"):
        with pytest.raises(ValueError, match="unknown KNN top-k"):
            resolve_knn_topk(bad)
    # env fallback path
    monkeypatch.setenv("TCSDN_KNN_TOPK", "screened64")
    assert resolve_knn_topk() == "screened64"


def test_native_screen_counters_populate(knn_serve):
    from traffic_classifier_sdn_tpu.native import knn as native_knn
    from traffic_classifier_sdn_tpu.utils.metrics import global_metrics

    if not native_knn.available():
        pytest.skip("g++ build unavailable")
    _serve(knn_serve, ["--knn-topk", "native"])
    assert global_metrics.counters.get("knn_candidates_screened", 0) > 0
