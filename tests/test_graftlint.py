"""graftlint's own test suite.

Per rule: one minimal fixture that FIRES (positive) and one that is
CLEAN (negative), so a rule regression is caught by name rather than as
a silent coverage loss. Plus the suppression round-trip (a reasoned
disable comment hides the finding; a reasonless one is itself a
finding) and the tier-1 self-enforcement test: the whole installed
package must lint clean.

Fixture snippets are deliberately minimal — they isolate exactly the
pattern a rule keys on, nothing else.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import pytest

from traffic_classifier_sdn_tpu.analysis_static import lint_paths
from traffic_classifier_sdn_tpu.analysis_static.framework import (
    BAD_SUPPRESSION,
    LintRunner,
)
from traffic_classifier_sdn_tpu.analysis_static.rules import (
    ALL_RULES,
    AtomicIoRule,
    BlockingUnderLockRule,
    CtypesAbiRule,
    FaultSiteRegistryRule,
    JitPurityRule,
    LockDisciplineRule,
    LockOrderRule,
    RetraceHazardRule,
    ThreadLifecycleRule,
)

PACKAGE_DIR = os.path.dirname(
    os.path.dirname(os.path.abspath(lint_paths.__code__.co_filename))
)


def run_rule(tmp_path, rule_cls, source, filename="snippet.py"):
    path = tmp_path / filename
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    return LintRunner([rule_cls()]).run([str(path)])


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

JIT_PURITY_POSITIVE = """
    import time
    import jax

    @jax.jit
    def step(x):
        t = time.time()
        print(x)
        return float(x) + t
"""

JIT_PURITY_NEGATIVE = """
    import time
    import jax

    @jax.jit
    def step(x):
        return x * 2

    def host_loop(x):
        t = time.time()
        print(x)
        return float(x) + t
"""


def test_jit_purity_fires(tmp_path):
    findings = run_rule(tmp_path, JitPurityRule, JIT_PURITY_POSITIVE)
    assert len(findings) == 3  # time.time, print, float()
    assert {f.rule for f in findings} == {"jit-purity"}


def test_jit_purity_clean(tmp_path):
    assert run_rule(tmp_path, JitPurityRule, JIT_PURITY_NEGATIVE) == []


def test_jit_purity_sees_wrapped_function(tmp_path):
    src = """
        import jax
        import numpy as np

        def kernel(x):
            return np.random.rand() + x

        kernel_jit = jax.jit(kernel)
    """
    findings = run_rule(tmp_path, JitPurityRule, src)
    assert len(findings) == 1
    assert "np.random" in findings[0].message


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

RETRACE_POSITIVE = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    buf = np.zeros(16)

    def f(x):
        return x

    f_jit = jax.jit(f)
    y = f_jit(3.5)
"""

RETRACE_NEGATIVE = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    buf = np.zeros(16, dtype=np.float32)

    def f(x):
        return x

    f_jit = jax.jit(f, static_argnums=(0,))
    y = f_jit(3.5)
    z = f_jit(jnp.asarray(buf))
"""


def test_retrace_hazard_fires(tmp_path):
    findings = run_rule(tmp_path, RetraceHazardRule, RETRACE_POSITIVE)
    msgs = [f.message for f in findings]
    assert len(findings) == 2
    assert any("without an explicit dtype" in m for m in msgs)
    assert any("bare Python scalar" in m for m in msgs)


def test_retrace_hazard_clean(tmp_path):
    assert run_rule(tmp_path, RetraceHazardRule, RETRACE_NEGATIVE) == []


# ---------------------------------------------------------------------------
# ctypes-abi
# ---------------------------------------------------------------------------

CTYPES_POSITIVE = """
    import ctypes

    lib = ctypes.CDLL("libfoo.so")

    def evaluate(n):
        return lib.fe_eval(n)
"""

CTYPES_NEGATIVE = """
    import ctypes

    lib = ctypes.CDLL("libfoo.so")
    lib.fe_eval.argtypes = [ctypes.c_int64]
    lib.fe_eval.restype = ctypes.c_int64

    def evaluate(n):
        return lib.fe_eval(n)
"""


def test_ctypes_abi_fires(tmp_path):
    findings = run_rule(tmp_path, CtypesAbiRule, CTYPES_POSITIVE)
    assert len(findings) == 1
    assert "argtypes and restype" in findings[0].message


def test_ctypes_abi_clean(tmp_path):
    assert run_rule(tmp_path, CtypesAbiRule, CTYPES_NEGATIVE) == []


def test_ctypes_abi_partial_prototype_still_fires(tmp_path):
    src = CTYPES_NEGATIVE.replace(
        "    lib.fe_eval.restype = ctypes.c_int64\n", ""
    )
    findings = run_rule(tmp_path, CtypesAbiRule, src)
    assert len(findings) == 1
    assert "restype" in findings[0].message
    assert "argtypes" not in findings[0].message


def test_ctypes_abi_two_libs_need_per_handle_prototypes(tmp_path):
    # a prototype on one CDLL handle must not silence the check for a
    # same-named symbol on a DIFFERENT lib
    findings = run_rule(
        tmp_path, CtypesAbiRule,
        """
        import ctypes

        liba = ctypes.CDLL("a.so")
        libb = ctypes.CDLL("b.so")
        liba.fe_eval.argtypes = [ctypes.c_int64]
        liba.fe_eval.restype = ctypes.c_int64

        def evaluate(n):
            return liba.fe_eval(n) + libb.fe_eval(n)
        """,
    )
    assert len(findings) == 1
    assert "fe_eval" in findings[0].message


def test_ctypes_abi_tracks_nonconventional_handle_names(tmp_path):
    # a CDLL handle bound to a name other than lib/_lib must not
    # escape the rule
    findings = run_rule(
        tmp_path, CtypesAbiRule,
        CTYPES_POSITIVE.replace("lib", "engine"),
    )
    assert len(findings) == 1
    # ...including a handle obtained via LazyLib(...).load()
    findings = run_rule(
        tmp_path, CtypesAbiRule,
        """
        from engine import LazyLib

        _loader = LazyLib("src.cpp", "out.so", "demo")
        handle = _loader.load()

        def evaluate(n):
            return handle.fe_eval(n)
        """,
    )
    assert len(findings) == 1


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_POSITIVE = """
    import threading

    class Collector:
        def __init__(self):
            self._lock = threading.Lock()
            self._rows = 0
            self._t = threading.Thread(target=self._run)

        def _run(self):
            self._rows += 1

        def stats(self):
            return self._rows
"""

LOCK_NEGATIVE = """
    import threading

    class Collector:
        def __init__(self):
            self._lock = threading.Lock()
            self._rows = 0
            self._t = threading.Thread(target=self._run)

        def _run(self):
            with self._lock:
                self._rows += 1

        def stats(self):
            with self._lock:
                return self._rows
"""


def test_lock_discipline_fires(tmp_path):
    findings = run_rule(tmp_path, LockDisciplineRule, LOCK_POSITIVE)
    # unlocked write in the thread target AND unlocked read in stats()
    assert len(findings) == 2
    assert all("_rows" in f.message for f in findings)


def test_lock_discipline_clean(tmp_path):
    assert run_rule(tmp_path, LockDisciplineRule, LOCK_NEGATIVE) == []


# the obs flight-recorder shape: an event ring appended from a worker
# thread. Written WITHOUT the ring lock it is exactly the hazard
# lock-discipline exists for — this fixture pins that the rule covers
# the obs package's ring-writer pattern, not just counters.
LOCK_RING_POSITIVE = """
    import threading

    class BadRecorder:
        def __init__(self):
            self._ring_lock = threading.Lock()
            self._ring = []
            self._seq = 0

        def start(self):
            threading.Thread(target=self._writer).start()

        def _writer(self):
            self._seq = self._seq + 1
            self._ring.append(self._seq)

        def tail(self):
            return list(self._ring)
"""


def test_lock_discipline_covers_obs_style_ring_writers(tmp_path):
    findings = run_rule(tmp_path, LockDisciplineRule, LOCK_RING_POSITIVE)
    # _seq read+written and _ring read in _writer without the lock,
    # plus the unlocked _ring read in tail()
    assert findings
    flagged = {f.message.split("'")[1] for f in findings}
    assert {"self._seq", "self._ring"} <= flagged


def test_obs_package_is_clean():
    """The observability plane is held to the same static bar as the
    rest of the package (lock-discipline over its ring/health locks,
    atomic-io over its post-mortem dump, fault-site audit over its
    observer wiring) — a scoped scan so a violation names the obs file
    directly rather than drowning in a whole-package report."""
    findings = lint_paths([os.path.join(PACKAGE_DIR, "obs")])
    assert findings == [], "\n".join(f.render() for f in findings)


# the pipelined serve loop's shape: producer/consumer threads sharing
# rotating staging buffers (serving/pipeline.Handoff). Written WITHOUT
# the condition lock it is exactly the double-buffer handoff race the
# rule must catch: the device-stage thread pops staging slots and
# bumps the in-flight count while the host stage appends — every one
# of those accesses races unless it holds the owning *_lock.
LOCK_HANDOFF_POSITIVE = """
    import threading

    class BadPipeline:
        def __init__(self):
            self._lock = threading.Condition()
            self._slots = []
            self._inflight = 0
            self._t = threading.Thread(target=self._device_stage)

        def _device_stage(self):
            while True:
                job = self._slots.pop(0)
                self._inflight += 1
                job()
                self._done(job)

        def _done(self, job):
            self._inflight -= 1

        def put(self, job):
            self._slots.append(job)

        def idle(self):
            return not self._slots and not self._inflight
"""

LOCK_HANDOFF_NEGATIVE = """
    import threading

    class Pipeline:
        def __init__(self):
            self._lock = threading.Condition()
            self._slots = []
            self._inflight = 0
            self._t = threading.Thread(target=self._device_stage)

        def _device_stage(self):
            while True:
                with self._lock:
                    job = self._slots.pop(0)
                    self._inflight += 1
                job()
                self._done(job)

        def _done(self, job):
            with self._lock:
                self._inflight -= 1

        def put(self, job):
            with self._lock:
                self._slots.append(job)

        def idle(self):
            with self._lock:
                return not self._slots and not self._inflight
"""


def test_lock_discipline_covers_double_buffer_handoff(tmp_path):
    findings = run_rule(tmp_path, LockDisciplineRule,
                        LOCK_HANDOFF_POSITIVE)
    flagged = {f.message.split("'")[1] for f in findings}
    # _slots popped on the device-stage thread and appended/read by the
    # host side; _inflight written on BOTH sides (and through the
    # _done helper — the thread-target transitive closure must pull
    # helpers invoked from the target into the shared set)
    assert {"self._slots", "self._inflight"} <= flagged


def test_lock_discipline_clean_double_buffer_handoff(tmp_path):
    assert run_rule(tmp_path, LockDisciplineRule,
                    LOCK_HANDOFF_NEGATIVE) == []


def test_serving_package_is_clean():
    """The pipelined serve loop is new concurrency — producer/consumer
    threads sharing staging buffers — and must hold the same static bar
    (lock-discipline over the handoff's condition lock and the
    pipeline's accounting lock, fault-site audit over the
    pipeline.handoff/pipeline.coalesce seams, jit-purity over the
    donated feature projection). serving/degrade.py raises the bar
    again: its DeviceWatchdog worker thread and the ladder's shared
    state machine must hold lock-discipline, and the
    degrade.dispatch_stall/dispatch_error/probe seams must audit
    against the fault-site registry. The drift loop raises it once
    more: serving/retrain.py's background fit thread publishes a
    candidate checkpoint path to the serve thread, and
    serving/drift.py's controller state is read from the exposition
    thread — both must hold lock-discipline, and the drift.window/
    retrain.fit/promote.swap/promote.rollback seams must audit against
    the registry."""
    findings = lint_paths([os.path.join(PACKAGE_DIR, "serving")])
    assert findings == [], "\n".join(f.render() for f in findings)
    # scoped scans so a violation names the file directly when the
    # watchdog / retrainer-publication patterns regress
    for mod in ("degrade.py", "drift.py", "retrain.py"):
        findings = lint_paths(
            [os.path.join(PACKAGE_DIR, "serving", mod)]
        )
        assert findings == [], "\n".join(f.render() for f in findings)


# the degrade watchdog's shape: a worker thread executing handed-off
# jobs against a shared result slot plus a state machine read from
# other threads. Written WITHOUT the condition lock it is exactly the
# watchdog/shared-state-machine race lock-discipline must catch: the
# worker stores the job slot and results while call()/status() read
# and retract them.
LOCK_WATCHDOG_POSITIVE = """
    import threading

    class BadWatchdog:
        def __init__(self):
            self._lock = threading.Condition()
            self._job = None
            self._state = "HEALTHY"
            self._t = threading.Thread(target=self._run)

        def _run(self):
            while True:
                job = self._job
                self._job = None
                if job is not None:
                    self._state = job()

        def call(self, fn):
            self._job = fn

        def status(self):
            return (self._state, self._job)
"""

LOCK_WATCHDOG_NEGATIVE = """
    import threading

    class Watchdog:
        def __init__(self):
            self._lock = threading.Condition()
            self._job = None
            self._state = "HEALTHY"
            self._t = threading.Thread(target=self._run)

        def _run(self):
            while True:
                with self._lock:
                    job = self._job
                    self._job = None
                if job is not None:
                    result = job()
                    with self._lock:
                        self._state = result

        def call(self, fn):
            with self._lock:
                self._job = fn

        def status(self):
            with self._lock:
                return (self._state, self._job)
"""


def test_lock_discipline_covers_watchdog_state_machine(tmp_path):
    findings = run_rule(tmp_path, LockDisciplineRule,
                        LOCK_WATCHDOG_POSITIVE)
    flagged = {f.message.split("'")[1] for f in findings}
    # the worker stores _job and _state; call()/status() touch both
    # without the lock — every one of those accesses must be flagged
    assert {"self._job", "self._state"} <= flagged


def test_lock_discipline_clean_watchdog_state_machine(tmp_path):
    assert run_rule(tmp_path, LockDisciplineRule,
                    LOCK_WATCHDOG_NEGATIVE) == []


# the drift retrainer's shape: a background fit thread publishing its
# result — the candidate checkpoint path — back to the serve thread
# that polls for it. Written WITHOUT the lock it is exactly the
# publication race lock-discipline must catch: the worker stores the
# path/state while the serve thread's poll()/take() read and retract
# them, and a torn read hands the serve thread a half-published
# candidate.
LOCK_RETRAIN_POSITIVE = """
    import threading

    class BadRetrainer:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = "idle"
            self._candidate_path = None

        def submit(self, fn):
            self._state = "running"
            threading.Thread(target=self._run, args=(fn,)).start()

        def _run(self, fn):
            path = fn()
            self._candidate_path = path
            self._state = "done"

        def poll(self):
            return (self._state, self._candidate_path)
"""

LOCK_RETRAIN_NEGATIVE = """
    import threading

    class Retrainer:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = "idle"
            self._candidate_path = None

        def submit(self, fn):
            with self._lock:
                self._state = "running"
            threading.Thread(target=self._run, args=(fn,)).start()

        def _run(self, fn):
            path = fn()
            with self._lock:
                self._candidate_path = path
                self._state = "done"

        def poll(self):
            with self._lock:
                return (self._state, self._candidate_path)
"""


def test_lock_discipline_covers_retrainer_publication(tmp_path):
    findings = run_rule(tmp_path, LockDisciplineRule,
                        LOCK_RETRAIN_POSITIVE)
    flagged = {f.message.split("'")[1] for f in findings}
    # the fit thread stores both the candidate path and the state flag;
    # submit()/poll() touch them without the lock — all flagged
    assert {"self._candidate_path", "self._state"} <= flagged


def test_lock_discipline_clean_retrainer_publication(tmp_path):
    assert run_rule(tmp_path, LockDisciplineRule,
                    LOCK_RETRAIN_NEGATIVE) == []


# ---------------------------------------------------------------------------
# lock-order (graftlock)
# ---------------------------------------------------------------------------

# the AB/BA shape: two methods acquiring the same two locks in opposite
# orders — two threads interleaving them deadlock with both locks held.
# tests/test_locktrace.py runs THIS SAME source under the runtime
# witness and proves it trips there too (static + dynamic agreement).
LOCK_ORDER_ABBA = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def fwd(self):
            with self._a_lock:
                with self._b_lock:
                    return 1

        def rev(self):
            with self._b_lock:
                with self._a_lock:
                    return 2
"""

# same two locks, same order everywhere: consistent, clean
LOCK_ORDER_CONSISTENT = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def fwd(self):
            with self._a_lock:
                with self._b_lock:
                    return 1

        def rev(self):
            with self._a_lock:
                with self._b_lock:
                    return 2
"""


def test_lock_order_fires_on_abba(tmp_path):
    findings = run_rule(tmp_path, LockOrderRule, LOCK_ORDER_ABBA)
    assert len(findings) == 1
    assert findings[0].rule == "lock-order"
    assert "cycle" in findings[0].message
    assert "_a_lock" in findings[0].message
    assert "_b_lock" in findings[0].message


def test_lock_order_clean_when_consistent(tmp_path):
    assert run_rule(
        tmp_path, LockOrderRule, LOCK_ORDER_CONSISTENT
    ) == []


def test_lock_order_removing_either_edge_passes(tmp_path):
    # the acceptance contract: dropping EITHER acquisition edge of the
    # AB/BA pair makes the cycle (and the finding) disappear
    no_fwd_nesting = LOCK_ORDER_ABBA.replace(
        "with self._a_lock:\n                with self._b_lock:\n                    return 1",
        "with self._a_lock:\n                return 1",
    )
    assert run_rule(tmp_path, LockOrderRule, no_fwd_nesting) == []
    no_rev_nesting = LOCK_ORDER_ABBA.replace(
        "with self._b_lock:\n                with self._a_lock:\n                    return 2",
        "with self._b_lock:\n                return 2",
    )
    assert run_rule(tmp_path, LockOrderRule, no_rev_nesting) == []


def test_lock_order_sees_interprocedural_cycle(tmp_path):
    # the second half of the AB edge hides behind a helper call — the
    # propagation through the call graph must still close the cycle
    src = """
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def fwd(self):
                with self._a_lock:
                    self._grab_b()

            def _grab_b(self):
                with self._b_lock:
                    return 1

            def rev(self):
                with self._b_lock:
                    with self._a_lock:
                        return 2
    """
    findings = run_rule(tmp_path, LockOrderRule, src)
    assert len(findings) == 1
    assert "_grab_b" in findings[0].message  # the chain names the hop


def test_lock_order_flags_self_reacquire(tmp_path):
    # re-acquiring a held non-reentrant Lock on the same call path is
    # the single-thread deadlock variant
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self._read()

            def _read(self):
                with self._lock:
                    return self.n
    """
    findings = run_rule(tmp_path, LockOrderRule, src)
    assert len(findings) == 1
    assert "re-acquired" in findings[0].message


# ---------------------------------------------------------------------------
# blocking-under-lock (graftlock)
# ---------------------------------------------------------------------------

BLOCKING_POSITIVE = """
    import threading
    import queue

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            pass

        def drain(self):
            with self._lock:
                return self._q.get()

        def stop(self):
            with self._lock:
                self._t.join()
"""

BLOCKING_NEGATIVE = """
    import threading
    import queue

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            pass

        def drain(self):
            with self._lock:
                return self._q.get(timeout=1.0)

        def stop(self):
            t = self._t
            with self._lock:
                pass
            t.join(2.0)
"""


def test_blocking_under_lock_fires(tmp_path):
    findings = run_rule(tmp_path, BlockingUnderLockRule,
                        BLOCKING_POSITIVE)
    kinds = sorted(
        f.message.split("unbounded ")[1].split(" ")[0]
        for f in findings
    )
    assert kinds == ["join", "queue-get"]


def test_blocking_under_lock_clean_with_timeouts(tmp_path):
    assert run_rule(
        tmp_path, BlockingUnderLockRule, BLOCKING_NEGATIVE
    ) == []


def test_blocking_under_lock_condition_own_wait_exempt(tmp_path):
    # waiting on the condition you hold RELEASES it — only OTHER held
    # locks are blocked, so the bare wait alone is clean...
    src = """
        import threading

        class Stage:
            def __init__(self):
                self._lock = threading.Condition()

            def park(self):
                with self._lock:
                    self._lock.wait()
    """
    assert run_rule(tmp_path, BlockingUnderLockRule, src) == []
    # ...but the same wait under an ADDITIONAL outer lock blocks that
    # outer lock without bound and must fire
    src_nested = """
        import threading

        class Stage:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._lock = threading.Condition()

            def park(self):
                with self._outer_lock:
                    with self._lock:
                        self._lock.wait()
    """
    findings = run_rule(tmp_path, BlockingUnderLockRule, src_nested)
    assert len(findings) == 1
    assert "_outer_lock" in findings[0].message


def test_blocking_under_lock_explicit_unbounded_spellings(tmp_path):
    # join(None) / wait(timeout=None) / get(True) / communicate(data)
    # all block forever despite carrying an argument — none may read
    # as bounded
    src = """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(target=self._run, daemon=True)
                self._q = None
                self._ev = None
                self._proc = None

            def _run(self):
                pass

            def a(self):
                with self._lock:
                    self._t.join(None)

            def b(self):
                with self._lock:
                    self._ev.wait(timeout=None)

            def c(self):
                with self._lock:
                    return self._q.get(True)

            def d(self, data):
                with self._lock:
                    return self._proc.communicate(data)
    """
    findings = run_rule(tmp_path, BlockingUnderLockRule, src)
    assert len(findings) == 4
    # ...while real timeouts (and dict.get-ambiguous positionals)
    # still read as bounded
    bounded = (
        src.replace("self._t.join(None)", "self._t.join(2.0)")
        .replace("self._ev.wait(timeout=None)",
                 "self._ev.wait(timeout=1.0)")
        .replace("self._q.get(True)", "self._q.get('key')")
        .replace("self._proc.communicate(data)",
                 "self._proc.communicate(data, timeout=5)")
    )
    assert run_rule(tmp_path, BlockingUnderLockRule, bounded) == []


def test_blocking_under_lock_multi_item_with(tmp_path):
    # items of one `with` enter left-to-right: the open() in
    # `with self._lock, open(p) as f:` runs WITH the lock held and
    # must be flagged exactly like the nested two-statement form
    src = """
        import threading

        class Writer:
            def __init__(self):
                self._lock = threading.Lock()

            def dump(self, p):
                with self._lock, open(p) as f:
                    return f.name
    """
    findings = run_rule(tmp_path, BlockingUnderLockRule, src)
    assert len(findings) == 1
    assert "file-io" in findings[0].message
    # ...and the reverse item order opens BEFORE the lock: clean
    src_rev = src.replace("with self._lock, open(p) as f:",
                          "with open(p) as f, self._lock:")
    assert run_rule(tmp_path, BlockingUnderLockRule, src_rev) == []


def test_lock_order_multi_item_with_edge(tmp_path):
    # a two-item `with a, b:` is an a→b edge like the nested form —
    # reversed nesting elsewhere must close the cycle
    src = """
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def fwd(self):
                with self._a_lock, self._b_lock:
                    return 1

            def rev(self):
                with self._b_lock, self._a_lock:
                    return 2
    """
    findings = run_rule(tmp_path, LockOrderRule, src)
    assert len(findings) == 1
    assert "cycle" in findings[0].message


def test_analysis_scales_on_diamond_call_graphs(tmp_path):
    # a memo-at-top-only recursion is exponential in diamond depth
    # (measured: 37 s at depth 20) — the fixed-point closure must walk
    # a deep diamond chain in well under a second
    import time as _time

    depth = 40
    parts = ["import threading", "_lock = threading.Lock()"]
    parts.append(f"def f{depth}():\n    with _lock:\n        pass")
    for i in range(depth - 1, -1, -1):
        parts.append(
            f"def g{i}():\n    f{i + 1}()\n"
            f"def h{i}():\n    f{i + 1}()\n"
            f"def f{i}():\n    g{i}()\n    h{i}()"
        )
    src = "\n".join(parts)
    path = tmp_path / "diamond.py"
    path.write_text(src, encoding="utf-8")
    t0 = _time.perf_counter()
    findings = LintRunner(
        [LockOrderRule(), BlockingUnderLockRule()]
    ).run([str(path)])
    elapsed = _time.perf_counter() - t0
    assert findings == []
    assert elapsed < 5.0, f"diamond depth {depth} took {elapsed:.1f}s"


def test_lock_order_survives_call_cycles(tmp_path):
    # mutual recursion in the call graph must neither hang the
    # fixed-point nor hide the edge reachable through the cycle
    src = """
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def ping(self, n):
                if n:
                    self.pong(n - 1)
                with self._b_lock:
                    pass

            def pong(self, n):
                self.ping(n)

            def fwd(self):
                with self._a_lock:
                    self.ping(3)

            def rev(self):
                with self._b_lock:
                    with self._a_lock:
                        return 2
    """
    findings = run_rule(tmp_path, LockOrderRule, src)
    assert len(findings) == 1
    assert "cycle" in findings[0].message


def test_blocking_under_lock_sees_interprocedural_reach(tmp_path):
    # the blocking call hides behind a helper — call-graph propagation
    # must still flag the call site under the lock
    src = """
        import threading

        class Runner:
            def __init__(self):
                self._lock = threading.Lock()
                self._proc = None

            def shutdown(self):
                with self._lock:
                    self._drain()

            def _drain(self):
                self._proc.communicate()
    """
    findings = run_rule(tmp_path, BlockingUnderLockRule, src)
    assert len(findings) == 1
    assert "_drain" in findings[0].message  # the chain names the hop


# ---------------------------------------------------------------------------
# thread-lifecycle (graftlock)
# ---------------------------------------------------------------------------

THREAD_LIFECYCLE_POSITIVE = """
    import threading

    class Leaky:
        def start(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            pass
"""

THREAD_LIFECYCLE_NEGATIVE = """
    import threading

    class Daemonized:
        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            pass

    class Joined:
        def start(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            pass

        def stop(self):
            self._t.join(timeout=5.0)
"""


def test_thread_lifecycle_fires(tmp_path):
    findings = run_rule(tmp_path, ThreadLifecycleRule,
                        THREAD_LIFECYCLE_POSITIVE)
    assert len(findings) == 1
    assert "neither daemonized" in findings[0].message


def test_thread_lifecycle_clean(tmp_path):
    assert run_rule(
        tmp_path, ThreadLifecycleRule, THREAD_LIFECYCLE_NEGATIVE
    ) == []


def test_thread_lifecycle_accepts_alias_join(tmp_path):
    # the exposition-server idiom: the attribute is swapped into a
    # local under the teardown lock, and the LOCAL is joined
    src = """
        import threading

        class Server:
            def start(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def _run(self):
                pass

            def stop(self):
                thread, self._thread = self._thread, None
                if thread is not None:
                    thread.join(timeout=5.0)
    """
    assert run_rule(tmp_path, ThreadLifecycleRule, src) == []


def test_thread_lifecycle_flags_unbound_nondaemon(tmp_path):
    src = """
        import threading

        def fire_and_forget(fn):
            threading.Thread(target=fn).start()
    """
    findings = run_rule(tmp_path, ThreadLifecycleRule, src)
    assert len(findings) == 1
    assert "<unbound>" in findings[0].message


# ---------------------------------------------------------------------------
# fault-site-registry
# ---------------------------------------------------------------------------

FAULTS_REGISTRY = """
    SITES = {
        "demo.write": "demo seam",
    }

    def fault_point(site):
        pass
"""

FAULT_SITE_POSITIVE = """
    from faults import fault_point

    def save():
        fault_point("demo.unregistered")
"""

FAULT_SITE_NEGATIVE = """
    from faults import fault_point

    def save():
        fault_point("demo.write")
"""


def run_fault_rule(tmp_path, user_source):
    (tmp_path / "faults.py").write_text(
        textwrap.dedent(FAULTS_REGISTRY), encoding="utf-8"
    )
    (tmp_path / "user.py").write_text(
        textwrap.dedent(user_source), encoding="utf-8"
    )
    return LintRunner([FaultSiteRegistryRule()]).run([str(tmp_path)])


def test_fault_site_registry_fires(tmp_path):
    findings = run_fault_rule(tmp_path, FAULT_SITE_POSITIVE)
    msgs = [f.message for f in findings]
    assert any("demo.unregistered" in m and "not registered" in m
               for m in msgs)
    # the registered site is now also unused — both directions check
    assert any("demo.write" in m and "never used" in m for m in msgs)


def test_fault_site_registry_clean(tmp_path):
    assert run_fault_rule(tmp_path, FAULT_SITE_NEGATIVE) == []


def test_fault_site_registry_subtree_scan_uses_external_registry(tmp_path):
    # Registry outside the scanned paths (`tools/lint.sh some/subdir`
    # usage): the use→registry direction must still audit against the
    # nearest utils/faults.py, with no spurious missing-registry finding
    # and no false "never used" registry-side positives.
    (tmp_path / "utils").mkdir()
    (tmp_path / "utils" / "faults.py").write_text(
        textwrap.dedent(FAULTS_REGISTRY), encoding="utf-8"
    )
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "user.py").write_text(
        textwrap.dedent(FAULT_SITE_NEGATIVE), encoding="utf-8"
    )
    assert LintRunner([FaultSiteRegistryRule()]).run([str(sub)]) == []

    (sub / "user.py").write_text(
        textwrap.dedent(FAULT_SITE_POSITIVE), encoding="utf-8"
    )
    findings = LintRunner([FaultSiteRegistryRule()]).run([str(sub)])
    assert any(
        "demo.unregistered" in f.message and "not registered" in f.message
        for f in findings
    )


def test_fault_site_registry_side_checks_need_full_package_scan(tmp_path):
    # scanning ONLY the subtree holding the registry (lint.sh pkg/utils)
    # must not claim registered sites are "never used" — the users are
    # simply out of scope; the full-package scan still enforces it
    pkg = tmp_path / "pkg"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "utils" / "faults.py").write_text(
        textwrap.dedent(FAULTS_REGISTRY), encoding="utf-8"
    )
    (pkg / "user.py").write_text(
        textwrap.dedent(FAULT_SITE_NEGATIVE), encoding="utf-8"
    )
    partial = LintRunner([FaultSiteRegistryRule()]).run(
        [str(pkg / "utils")]
    )
    assert [f.message for f in partial] == []
    full = LintRunner([FaultSiteRegistryRule()]).run([str(pkg)])
    assert [f.message for f in full] == []  # demo.write used by user.py


def test_fault_site_registry_param_forwarding_is_scoped(tmp_path):
    # a *_site parameter in ONE function must not exempt a same-named
    # computed local in a DIFFERENT function from the literal check
    findings = run_fault_rule(
        tmp_path,
        """
        from faults import fault_point

        def forwards(write_site):
            fault_point(write_site)

        def computes(prefix):
            write_site = prefix + ".write"
            fault_point(write_site)
        """,
    )
    literal_msgs = [f for f in findings if "string literal" in f.message]
    assert len(literal_msgs) == 1  # only the computed one, line 9
    assert literal_msgs[0].line == 9


def test_fault_site_registry_rejects_computed_site(tmp_path):
    findings = run_fault_rule(
        tmp_path,
        """
        from faults import fault_point

        SITE = "demo" + ".write"

        def save():
            fault_point(SITE)
        """,
    )
    assert any("string literal" in f.message for f in findings)


# ---------------------------------------------------------------------------
# atomic-io
# ---------------------------------------------------------------------------

ATOMIC_POSITIVE = """
    import os

    def save(path, data):
        with open(path + ".tmp", "w") as f:
            f.write(data)
        os.replace(path + ".tmp", path)
"""

ATOMIC_MODULE_SCOPE = """
    import os

    with open("state.json.tmp", "w") as f:
        f.write("{}")
    os.replace("state.json.tmp", "state.json")
"""

ATOMIC_NEGATIVE = """
    from traffic_classifier_sdn_tpu.utils.atomicio import atomic_write_bytes

    def save(path, data):
        atomic_write_bytes(path, data.encode())

    def relocate(src, dst):
        import os
        os.replace(src, dst)  # rename without a write in scope: fine
"""


def test_atomic_io_fires(tmp_path):
    findings = run_rule(tmp_path, AtomicIoRule, ATOMIC_POSITIVE)
    assert len(findings) == 1
    assert "atomic_write_bytes" in findings[0].message


def test_atomic_io_clean(tmp_path):
    assert run_rule(tmp_path, AtomicIoRule, ATOMIC_NEGATIVE) == []


def test_atomic_io_fires_at_module_scope(tmp_path):
    # script-style write+rename with no enclosing def is a scope too
    findings = run_rule(tmp_path, AtomicIoRule, ATOMIC_MODULE_SCOPE)
    assert len(findings) == 1
    assert findings[0].rule == "atomic-io"


def test_atomic_io_function_scope_excludes_nested_defs(tmp_path):
    # a pure rename in the enclosing body must not pair with a write
    # inside a nested helper (the helper is its own scope)
    src = """
        import os

        def rotate(path):
            def write_log(p, d):
                with open(p, "w") as f:
                    f.write(d)
            os.replace(path, path + ".1")
    """
    assert run_rule(tmp_path, AtomicIoRule, src) == []


def test_atomic_io_module_scope_excludes_nested_defs(tmp_path):
    # a write inside a def nested under a module-level `if` must not
    # pair with an unrelated top-level rename
    src = """
        import os

        if True:
            def helper(p, d):
                with open(p, "w") as f:
                    f.write(d)

        os.replace("a.log", "b.log")
    """
    assert run_rule(tmp_path, AtomicIoRule, src) == []


def test_atomic_io_exempts_atomicio_module(tmp_path):
    d = tmp_path / "utils"
    d.mkdir()
    (d / "atomicio.py").write_text(
        textwrap.dedent(ATOMIC_POSITIVE), encoding="utf-8"
    )
    assert LintRunner([AtomicIoRule()]).run([str(d)]) == []


# ---------------------------------------------------------------------------
# graftsync: implicit-sync / transfer-discipline / donation-hazard /
# sync-under-lock (the device-boundary pass)
# ---------------------------------------------------------------------------

IMPLICIT_SYNC_INTERPROCEDURAL = """
    import numpy as np
    import jax


    def _render(x):
        return np.asarray(x)


    def serve_tick(x: jax.Array):
        return _render(x)
"""

IMPLICIT_SYNC_COLD = """
    import numpy as np
    import jax


    def _render(x):
        return np.asarray(x)


    def warmup(x: jax.Array):
        return _render(x)
"""

DONATION_HAZARD_ALIAS = """
    import jax


    def _step(b):
        return b + 1


    step_jit = jax.jit(_step, donate_argnums=(0,))


    def serve_tick(x: jax.Array):
        buf = x
        out = step_jit(buf)
        return out + buf
"""

DONATION_HAZARD_REBOUND = """
    import jax


    def _step(b):
        return b + 1


    step_jit = jax.jit(_step, donate_argnums=(0,))


    def serve_tick(x: jax.Array):
        buf = x
        buf = step_jit(buf)
        return buf + 1
"""

SYNC_UNDER_LOCK_COMPOSED = """
    import threading

    import numpy as np
    import jax


    class Table:
        def __init__(self):
            self._lock = threading.Lock()

        def serve_tick(self, x: jax.Array):
            with self._lock:
                return self._drain(x)

        def _drain(self, x):
            return int(np.asarray(x).sum())
"""

SYNC_OUTSIDE_LOCK = """
    import threading

    import numpy as np
    import jax


    class Table:
        def __init__(self):
            self._lock = threading.Lock()

        def serve_tick(self, x: jax.Array):
            host = np.asarray(x)  # graftlint: disable=implicit-sync -- render-sync: test seam
            with self._lock:
                return int(host.sum())
"""

TRANSFER_DISCIPLINE_MIXED = """
    import numpy as np
    import jax.numpy as jnp


    def serve_tick(vals):
        return jnp.asarray(np.float64(vals))


    def warmup(vals):
        return jnp.asarray(np.float64(vals))
"""


def _lint_file(tmp_path, source, filename="snippet.py"):
    path = tmp_path / filename
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    return lint_paths([str(path)])


def test_implicit_sync_fires_through_helper(tmp_path):
    # the sync lives in a helper the hot root reaches via a call edge:
    # the finding lands on the helper's np.asarray, with the hot chain
    # (serve_tick -> _render) in the message
    findings = _lint_file(tmp_path, IMPLICIT_SYNC_INTERPROCEDURAL)
    assert [f.rule for f in findings] == ["implicit-sync"]
    assert "np.asarray" in findings[0].message
    assert "serve_tick" in findings[0].message
    assert "_render" in findings[0].message


def test_implicit_sync_cold_path_is_free(tmp_path):
    # same sync, but only reachable from a cold function: no finding
    assert _lint_file(tmp_path, IMPLICIT_SYNC_COLD) == []


def test_implicit_sync_suppression_must_name_discipline(tmp_path):
    suppressed = IMPLICIT_SYNC_INTERPROCEDURAL.replace(
        "return np.asarray(x)",
        "return np.asarray(x)  # graftlint: disable=implicit-sync "
        "-- render-sync: test seam",
    )
    assert _lint_file(tmp_path, suppressed) == []
    # a reasoned suppression that names NO deferral discipline is a
    # bad-suppression finding — and bad-suppression cannot itself be
    # suppressed, so the allowlist can't be quietly watered down
    undisciplined = IMPLICIT_SYNC_INTERPROCEDURAL.replace(
        "return np.asarray(x)",
        "return np.asarray(x)  # graftlint: disable=implicit-sync "
        "-- reviewer said it's fine",
    )
    findings = _lint_file(tmp_path, undisciplined)
    assert [f.rule for f in findings] == [BAD_SUPPRESSION]
    assert "discipline" in findings[0].message


def test_donation_hazard_fires_through_alias(tmp_path):
    # x aliased to buf, buf donated, then buf referenced again
    findings = _lint_file(tmp_path, DONATION_HAZARD_ALIAS)
    assert [f.rule for f in findings] == ["donation-hazard"]
    assert "'buf'" in findings[0].message
    assert "step_jit" in findings[0].message


def test_donation_hazard_rebind_idiom_is_clean(tmp_path):
    # buf = donated_fn(buf) rebinds the name to the result: clean
    assert _lint_file(tmp_path, DONATION_HAZARD_REBOUND) == []


def test_sync_under_lock_composes_with_graftlock(tmp_path):
    # the lock is a real graftlock lock class (constructed in
    # __init__); the sync is one call edge away — the rule composes
    # graftlock's held-lock summaries with the sync summaries and
    # renders the full chain
    findings = _lint_file(tmp_path, SYNC_UNDER_LOCK_COMPOSED)
    by_rule = {f.rule for f in findings}
    assert "sync-under-lock" in by_rule
    sul = next(f for f in findings if f.rule == "sync-under-lock")
    assert "Table._lock" in sul.message
    assert "_drain" in sul.message


def test_sync_outside_lock_is_clean(tmp_path):
    # snapshot-outside-the-lock idiom: no sync-under-lock finding
    # (the sync itself carries its reasoned allowlist entry)
    assert _lint_file(tmp_path, SYNC_OUTSIDE_LOCK) == []


def test_transfer_discipline_hot_only(tmp_path):
    # identical upload in a hot root and a cold function: exactly one
    # finding, on the hot one
    findings = _lint_file(tmp_path, TRANSFER_DISCIPLINE_MIXED)
    assert [f.rule for f in findings] == ["transfer-discipline"]
    assert "serve_tick" in findings[0].message


# ---------------------------------------------------------------------------
# ctypes-abi: cross-language prototype checking
# ---------------------------------------------------------------------------

CROSS_LANG_CPP = """
    #include <cstdint>

    static int helper(int x) { return x; }

    extern "C" {

    void tc_fill(int32_t* dst, uint64_t n, float scale) {
        (void)dst; (void)n; (void)scale;
    }

    uint64_t tc_count(void* handle) {
        (void)handle;
        return 0;
    }

    }
"""

CROSS_LANG_PY_MISMATCH = """
    import ctypes as ct

    lib = ct.CDLL("libnative.so")
    lib.tc_fill.argtypes = [ct.POINTER(ct.c_int32), ct.c_uint64]
    lib.tc_fill.restype = None
    lib.tc_count.argtypes = [ct.c_void_p]
    lib.tc_count.restype = ct.c_uint32

    def go():
        lib.tc_fill(None, 0)
        return lib.tc_count(None)
"""

CROSS_LANG_PY_CLEAN = """
    import ctypes as ct

    lib = ct.CDLL("libnative.so")
    lib.tc_fill.argtypes = [ct.POINTER(ct.c_int32), ct.c_uint64,
                            ct.c_float]
    lib.tc_fill.restype = None
    lib.tc_count.argtypes = [ct.c_void_p]
    lib.tc_count.restype = ct.c_uint64

    def go():
        lib.tc_fill(None, 0, 1.0)
        return lib.tc_count(None)
"""


def _write_cross_lang(tmp_path, py_source):
    (tmp_path / "native.cpp").write_text(
        textwrap.dedent(CROSS_LANG_CPP), encoding="utf-8"
    )
    return run_rule(tmp_path, CtypesAbiRule, py_source,
                    filename="engine.py")


def test_ctypes_cross_language_mismatch(tmp_path):
    findings = _write_cross_lang(tmp_path, CROSS_LANG_PY_MISMATCH)
    msgs = "\n".join(f.message for f in findings)
    # arity drift (2 declared vs 3 defined) AND a restype width
    # mismatch (uint64_t returned, c_uint32 declared) both fire
    assert any("tc_fill" in f.message for f in findings), msgs
    assert any("tc_count" in f.message for f in findings), msgs
    assert len(findings) == 2, msgs


def test_ctypes_cross_language_clean(tmp_path):
    assert _write_cross_lang(tmp_path, CROSS_LANG_PY_CLEAN) == []


def test_ctypes_cross_language_absent_cpp_still_checks_python_side(
    tmp_path,
):
    # no sibling .cpp: the rule still enforces prototypes exist, but
    # makes no cross-language claims
    findings = run_rule(tmp_path, CtypesAbiRule, CROSS_LANG_PY_MISMATCH,
                        filename="engine.py")
    assert findings == []


# ---------------------------------------------------------------------------
# suppression round-trip
# ---------------------------------------------------------------------------


def test_suppression_with_reason_hides_finding(tmp_path):
    src = ATOMIC_POSITIVE.replace(
        "os.replace(path + \".tmp\", path)",
        "os.replace(path + \".tmp\", path)"
        "  # graftlint: disable=atomic-io -- fixture exercises raw rename",
    )
    assert run_rule(tmp_path, AtomicIoRule, src) == []


def test_suppression_without_reason_is_a_finding(tmp_path):
    src = ATOMIC_POSITIVE.replace(
        "os.replace(path + \".tmp\", path)",
        "os.replace(path + \".tmp\", path)"
        "  # graftlint: disable=atomic-io",
    )
    findings = run_rule(tmp_path, AtomicIoRule, src)
    rules = sorted(f.rule for f in findings)
    # the reasonless disable does NOT hide the finding, and is flagged
    assert rules == ["atomic-io", BAD_SUPPRESSION]


def test_suppression_unknown_rule_id_is_a_finding(tmp_path):
    src = "x = 1  # graftlint: disable=no-such-rule -- typo'd id\n"
    findings = run_rule(tmp_path, AtomicIoRule, src)
    assert [f.rule for f in findings] == [BAD_SUPPRESSION]
    assert "unknown rule id" in findings[0].message


def test_suppression_on_multiline_statement_closing_line(tmp_path):
    # the finding anchors at the statement's first line; a trailing
    # disable comment on the closing line must still suppress it
    src = ATOMIC_POSITIVE.replace(
        "os.replace(path + \".tmp\", path)",
        "os.replace(\n"
        "        path + \".tmp\",\n"
        "        path,\n"
        "    )  # graftlint: disable=atomic-io -- fixture exercises "
        "raw rename",
    )
    assert run_rule(tmp_path, AtomicIoRule, src) == []


def test_suppression_only_hides_named_rule(tmp_path):
    src = ATOMIC_POSITIVE.replace(
        "os.replace(path + \".tmp\", path)",
        "os.replace(path + \".tmp\", path)"
        "  # graftlint: disable=jit-purity -- wrong rule named",
    )
    path = tmp_path / "snippet.py"
    path.write_text(textwrap.dedent(src), encoding="utf-8")
    findings = lint_paths([str(path)])
    assert [f.rule for f in findings] == ["atomic-io"]


# ---------------------------------------------------------------------------
# self-enforcement + CLI contract
# ---------------------------------------------------------------------------


@pytest.mark.lint
def test_package_is_clean():
    findings = lint_paths([PACKAGE_DIR])
    assert findings == [], "\n".join(f.render() for f in findings)


@pytest.mark.lint
def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    clean = subprocess.run(
        [sys.executable, "-m",
         "traffic_classifier_sdn_tpu.analysis_static", PACKAGE_DIR],
        capture_output=True, text=True, env=env,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "clean" in clean.stdout

    dirty = tmp_path / "dirty.py"
    dirty.write_text(textwrap.dedent(ATOMIC_POSITIVE), encoding="utf-8")
    sarif_path = tmp_path / "findings.sarif"
    found = subprocess.run(
        [sys.executable, "-m",
         "traffic_classifier_sdn_tpu.analysis_static", "--json",
         "--sarif", str(sarif_path), str(dirty)],
        capture_output=True, text=True, env=env,
    )
    assert found.returncode == 1
    import json

    report = json.loads(found.stdout)
    assert report["schema_version"] == 2
    assert report["count"] == 1
    assert report["findings"][0]["rule"] == "atomic-io"
    # the SARIF copy carries the same finding in 2.1.0 shape, with the
    # rule catalog present so annotators can render descriptions
    sarif = json.loads(sarif_path.read_text(encoding="utf-8"))
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["results"][0]["ruleId"] == "atomic-io"
    assert (run["results"][0]["locations"][0]["physicalLocation"]
            ["region"]["startLine"]) == report["findings"][0]["line"]
    assert any(
        r["id"] == "atomic-io" for r in run["tool"]["driver"]["rules"]
    )

    # a --select scoped run must not flag valid suppressions of real
    # but unselected rule ids as bad-suppression
    suppressed = tmp_path / "suppressed.py"
    suppressed.write_text(
        "lib.fn()  # graftlint: disable=ctypes-abi -- prototype set "
        "elsewhere\n",
        encoding="utf-8",
    )
    scoped = subprocess.run(
        [sys.executable, "-m",
         "traffic_classifier_sdn_tpu.analysis_static",
         "--select=jit-purity", str(suppressed)],
        capture_output=True, text=True, env=env,
    )
    assert scoped.returncode == 0, scoped.stdout + scoped.stderr

    # --select that parses to zero rule ids must be a usage error, not
    # a run of zero rules reporting "clean"
    empty_select = subprocess.run(
        [sys.executable, "-m",
         "traffic_classifier_sdn_tpu.analysis_static",
         "--select=,", str(suppressed)],
        capture_output=True, text=True, env=env,
    )
    assert empty_select.returncode == 2
    assert "no rule ids" in empty_select.stderr

    # a non-.py target must be a usage error, not a silent "clean"
    not_py = tmp_path / "script.sh"
    not_py.write_text("echo hi\n", encoding="utf-8")
    usage = subprocess.run(
        [sys.executable, "-m",
         "traffic_classifier_sdn_tpu.analysis_static", str(not_py)],
        capture_output=True, text=True, env=env,
    )
    assert usage.returncode == 2
    assert "not a directory or .py file" in usage.stderr

    # a directory with zero .py files must be a usage error too — a
    # typo'd-but-existing data dir would otherwise pass a gate while
    # linting nothing
    empty_dir = tmp_path / "nodata"
    empty_dir.mkdir()
    (empty_dir / "notes.txt").write_text("no python here\n",
                                         encoding="utf-8")
    no_py = subprocess.run(
        [sys.executable, "-m",
         "traffic_classifier_sdn_tpu.analysis_static", str(empty_dir)],
        capture_output=True, text=True, env=env,
    )
    assert no_py.returncode == 2
    assert "no .py files" in no_py.stderr


def test_every_rule_has_fixture_coverage():
    """Adding a rule without fixture tests should fail loudly here."""
    covered = {
        "jit-purity", "retrace-hazard", "ctypes-abi", "lock-discipline",
        "fault-site-registry", "atomic-io",
        "lock-order", "blocking-under-lock", "thread-lifecycle",
        "implicit-sync", "transfer-discipline", "donation-hazard",
        "sync-under-lock",
    }
    assert {cls.id for cls in ALL_RULES} == covered
