"""Multi-host bring-up test: two OS processes, each with 2 virtual CPU
devices, rendezvous through ``parallel/mesh.py:init_distributed``
(jax.distributed over the loopback DCN analogue) and run a batch-sharded
predict plus a cross-process psum on the spanning mesh — the multi-host
path SURVEY.md §2.4 requires and VERDICT r1 found untested.

Runs in subprocesses because jax.distributed can only initialize once per
process (and the test session's jax is already single-process)."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_spanning_predict():
    import jax

    if jax.default_backend() == "cpu":
        # XLA's CPU backend rejects multiprocess computations outright
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"), so on a single-device CPU host this test can only
        # ever fail for environmental reasons. The multi-host spanning
        # path's covering evidence is the 8-device TPU dryrun
        # (MULTICHIP_r05.json: the same worker rendezvous + spanning
        # predict on real chips).
        pytest.skip(
            "multiprocess mesh needs a non-CPU backend; covered by the "
            "8-device TPU dryrun (MULTICHIP_r05.json)"
        )
    coordinator = f"127.0.0.1:{_free_port()}"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # fresh jax in the children, immune to the TPU sitecustomize
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, coordinator, str(i), "2"],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=repo_root,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MULTIHOST OK pid={i} devices=4" in out, out
