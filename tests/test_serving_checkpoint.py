"""Serving-state warm restart (io/serving_checkpoint.py): a restored
engine must CONTINUE bit-identically — same features, same slot
resolution for existing flows, same delta math against the stored
counters, same eviction clock — versus an engine that never stopped."""

import numpy as np
import pytest

from traffic_classifier_sdn_tpu.core import flow_table as ft
from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
from traffic_classifier_sdn_tpu.ingest.protocol import TelemetryRecord
from traffic_classifier_sdn_tpu.io import serving_checkpoint as sc


def _rec(time, src, dst, pkts, bts):
    return TelemetryRecord(
        time=time, datapath="1", in_port=1, eth_src=src, eth_dst=dst,
        out_port=2, packets=pkts, bytes=bts,
    )


def _tick(eng, t, n, base=0, prefix="f"):
    eng.mark_tick()
    eng.ingest([
        _rec(t, f"{prefix}{i:03d}", "gw", base + 7 * t + i,
             base + 1000 * t + 13 * i)
        for i in range(n)
    ])
    eng.step()


def _features(eng):
    return np.asarray(ft.features16(eng.table))


@pytest.mark.parametrize("native", [False, True])
def test_save_restore_continues_bitwise(tmp_path, native):
    if native:
        from traffic_classifier_sdn_tpu.native import engine as ne

        if not ne.available():
            pytest.skip("native engine unavailable")
    path = str(tmp_path / "serve_state.npz")

    # two engines run the same two ticks; one checkpoints + restores
    a = FlowStateEngine(capacity=64, native=native)
    b = FlowStateEngine(capacity=64, native=native)
    for eng in (a, b):
        _tick(eng, 1, 20)
        _tick(eng, 2, 20)
    sc.save(a, path)
    r = sc.restore(path)
    assert r.native == native
    np.testing.assert_array_equal(_features(r), _features(a))
    assert r.num_flows() == a.num_flows() == 20
    assert r.last_time == a.last_time

    # continuation: a third tick updates existing flows and adds new ones
    # — the restored engine must match the never-stopped engine exactly
    # (same slots, same mod-2^32 deltas vs the stored counters)
    for eng in (r, b):
        _tick(eng, 3, 24)
    np.testing.assert_array_equal(_features(r), _features(b))
    assert r.num_flows() == b.num_flows() == 24

    # eviction continuity: the restored clock ages flows identically, and
    # freed slots are reusable
    for eng in (r, b):
        assert eng.evict_idle(now=100, idle_seconds=50) == 24
        _tick(eng, 101, 5, prefix="n")
    np.testing.assert_array_equal(_features(r), _features(b))
    assert r.num_flows() == 5
    assert r.dropped == 0


def test_restore_after_partial_eviction_reuses_freed_slots(tmp_path):
    """A checkpoint taken AFTER evictions must restore the free list: new
    flows land in freed slots (below the frontier) instead of burning
    fresh capacity."""
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=16)
    _tick(eng, 1, 12)
    # refresh only even-numbered flows much later; odd ones go idle
    eng.mark_tick()
    eng.ingest([
        _rec(60, f"f{i:03d}", "gw", 1000 + i, 100000 + i)
        for i in range(0, 12, 2)
    ])
    eng.step()
    assert eng.evict_idle(now=60, idle_seconds=30) == 6
    sc.save(eng, path)
    r = sc.restore(path)
    assert r.num_flows() == 6
    _tick(r, 61, 6, prefix="x")  # six new flows -> must fit in freed slots
    assert r.num_flows() == 12
    assert r.dropped == 0
    # capacity frontier respected: nothing past what the original used
    in_use = np.nonzero(np.asarray(r.table.in_use)[:-1])[0]
    assert in_use.max() < 12


@pytest.mark.parametrize("native", [False, True])
def test_restore_preserves_lifo_free_order(tmp_path, native):
    """Allocation pops the END of the free stack, so a restore must keep
    the stack VERBATIM: two eviction rounds leave a non-ascending free
    list, and the restored engine's next assignments must land in the
    same slots a never-stopped engine uses."""
    if native:
        from traffic_classifier_sdn_tpu.native import engine as ne

        if not ne.available():
            pytest.skip("native engine unavailable")
    path = str(tmp_path / "s.npz")
    a = FlowStateEngine(capacity=16, native=native)
    b = FlowStateEngine(capacity=16, native=native)

    def drive(eng):
        _tick(eng, 1, 12)  # flows f000..f011 in slots 0..11
        # round 1: keep 0-3 and 8-11 fresh; 4-7 go idle -> free [4,5,6,7]
        eng.mark_tick()
        eng.ingest([
            _rec(60, f"f{i:03d}", "gw", 500 + i, 50000 + i)
            for i in (*range(4), *range(8, 12))
        ])
        eng.step()
        assert eng.evict_idle(now=60, idle_seconds=30) == 4
        # round 2: keep only 8-11; 0-3 go idle -> free [4,5,6,7,0,1,2,3]
        eng.mark_tick()
        eng.ingest([
            _rec(120, f"f{i:03d}", "gw", 900 + i, 90000 + i)
            for i in range(8, 12)
        ])
        eng.step()
        assert eng.evict_idle(now=120, idle_seconds=30) == 4

    drive(a)
    drive(b)
    sc.save(a, path)
    r = sc.restore(path)
    # the next four assignments must pop the same (non-ascending) stack
    for eng in (r, b):
        _tick(eng, 121, 4, prefix="z")
    np.testing.assert_array_equal(_features(r), _features(b))
    np.testing.assert_array_equal(
        np.asarray(r.table.in_use), np.asarray(b.table.in_use)
    )
    assert r.slot_metadata(slots=range(16)) == b.slot_metadata(
        slots=range(16)
    )


def test_restore_rejects_wrong_format(tmp_path):
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=8)
    _tick(eng, 1, 3)
    sc.save(eng, path)
    import numpy as np_

    z = dict(np_.load(path))
    z["format_version"] = np_.int64(99)
    np_.savez_compressed(path, **z)
    with pytest.raises(ValueError, match="format"):
        sc.restore(path)
