"""Serving-state warm restart (io/serving_checkpoint.py): a restored
engine must CONTINUE bit-identically — same features, same slot
resolution for existing flows, same delta math against the stored
counters, same eviction clock — versus an engine that never stopped."""

import os

import numpy as np
import pytest

from traffic_classifier_sdn_tpu.core import flow_table as ft
from traffic_classifier_sdn_tpu.ingest.batcher import FlowStateEngine
from traffic_classifier_sdn_tpu.ingest.protocol import TelemetryRecord
from traffic_classifier_sdn_tpu.io import serving_checkpoint as sc


def _rec(time, src, dst, pkts, bts):
    return TelemetryRecord(
        time=time, datapath="1", in_port=1, eth_src=src, eth_dst=dst,
        out_port=2, packets=pkts, bytes=bts,
    )


def _tick(eng, t, n, base=0, prefix="f"):
    eng.mark_tick()
    eng.ingest([
        _rec(t, f"{prefix}{i:03d}", "gw", base + 7 * t + i,
             base + 1000 * t + 13 * i)
        for i in range(n)
    ])
    eng.step()


def _features(eng):
    return np.asarray(ft.features16(eng.table))


@pytest.mark.parametrize("native", [False, True])
def test_save_restore_continues_bitwise(tmp_path, native):
    if native:
        from traffic_classifier_sdn_tpu.native import engine as ne

        if not ne.available():
            pytest.skip("native engine unavailable")
    path = str(tmp_path / "serve_state.npz")

    # two engines run the same two ticks; one checkpoints + restores
    a = FlowStateEngine(capacity=64, native=native)
    b = FlowStateEngine(capacity=64, native=native)
    for eng in (a, b):
        _tick(eng, 1, 20)
        _tick(eng, 2, 20)
    sc.save(a, path)
    r = sc.restore(path)
    assert r.native == native
    np.testing.assert_array_equal(_features(r), _features(a))
    assert r.num_flows() == a.num_flows() == 20
    assert r.last_time == a.last_time

    # continuation: a third tick updates existing flows and adds new ones
    # — the restored engine must match the never-stopped engine exactly
    # (same slots, same mod-2^32 deltas vs the stored counters)
    for eng in (r, b):
        _tick(eng, 3, 24)
    np.testing.assert_array_equal(_features(r), _features(b))
    assert r.num_flows() == b.num_flows() == 24

    # eviction continuity: the restored clock ages flows identically, and
    # freed slots are reusable
    for eng in (r, b):
        assert eng.evict_idle(now=100, idle_seconds=50) == 24
        _tick(eng, 101, 5, prefix="n")
    np.testing.assert_array_equal(_features(r), _features(b))
    assert r.num_flows() == 5
    assert r.dropped == 0


def test_restore_after_partial_eviction_reuses_freed_slots(tmp_path):
    """A checkpoint taken AFTER evictions must restore the free list: new
    flows land in freed slots (below the frontier) instead of burning
    fresh capacity."""
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=16)
    _tick(eng, 1, 12)
    # refresh only even-numbered flows much later; odd ones go idle
    eng.mark_tick()
    eng.ingest([
        _rec(60, f"f{i:03d}", "gw", 1000 + i, 100000 + i)
        for i in range(0, 12, 2)
    ])
    eng.step()
    assert eng.evict_idle(now=60, idle_seconds=30) == 6
    sc.save(eng, path)
    r = sc.restore(path)
    assert r.num_flows() == 6
    _tick(r, 61, 6, prefix="x")  # six new flows -> must fit in freed slots
    assert r.num_flows() == 12
    assert r.dropped == 0
    # capacity frontier respected: nothing past what the original used
    in_use = np.nonzero(np.asarray(r.table.in_use)[:-1])[0]
    assert in_use.max() < 12


@pytest.mark.parametrize("native", [False, True])
def test_restore_preserves_lifo_free_order(tmp_path, native):
    """Allocation pops the END of the free stack, so a restore must keep
    the stack VERBATIM: two eviction rounds leave a non-ascending free
    list, and the restored engine's next assignments must land in the
    same slots a never-stopped engine uses."""
    if native:
        from traffic_classifier_sdn_tpu.native import engine as ne

        if not ne.available():
            pytest.skip("native engine unavailable")
    path = str(tmp_path / "s.npz")
    a = FlowStateEngine(capacity=16, native=native)
    b = FlowStateEngine(capacity=16, native=native)

    def drive(eng):
        _tick(eng, 1, 12)  # flows f000..f011 in slots 0..11
        # round 1: keep 0-3 and 8-11 fresh; 4-7 go idle -> free [4,5,6,7]
        eng.mark_tick()
        eng.ingest([
            _rec(60, f"f{i:03d}", "gw", 500 + i, 50000 + i)
            for i in (*range(4), *range(8, 12))
        ])
        eng.step()
        assert eng.evict_idle(now=60, idle_seconds=30) == 4
        # round 2: keep only 8-11; 0-3 go idle -> free [4,5,6,7,0,1,2,3]
        eng.mark_tick()
        eng.ingest([
            _rec(120, f"f{i:03d}", "gw", 900 + i, 90000 + i)
            for i in range(8, 12)
        ])
        eng.step()
        assert eng.evict_idle(now=120, idle_seconds=30) == 4

    drive(a)
    drive(b)
    sc.save(a, path)
    r = sc.restore(path)
    # the next four assignments must pop the same (non-ascending) stack
    for eng in (r, b):
        _tick(eng, 121, 4, prefix="z")
    np.testing.assert_array_equal(_features(r), _features(b))
    np.testing.assert_array_equal(
        np.asarray(r.table.in_use), np.asarray(b.table.in_use)
    )
    assert r.slot_metadata(slots=range(16)) == b.slot_metadata(
        slots=range(16)
    )


def test_restore_rejects_wrong_format(tmp_path):
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=8)
    _tick(eng, 1, 3)
    sc.save(eng, path)
    import numpy as np_

    z = dict(np_.load(path))
    z["format_version"] = np_.int64(99)
    np_.savez_compressed(path, **z)
    with pytest.raises(ValueError, match="format"):
        sc.restore(path)


# durability layer: atomic writes, checksums, rotation, rollback


def test_save_is_atomic_and_leaves_no_temp(tmp_path):
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=8)
    _tick(eng, 1, 3)
    nbytes = sc.save(eng, path)
    assert nbytes == os.path.getsize(path)
    assert os.listdir(tmp_path) == ["s.npz"]  # temp cleaned up
    sc.validate(path)  # embedded checksum verifies


def test_restore_rejects_bit_flip_with_clear_error(tmp_path):
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=8)
    _tick(eng, 1, 3)
    sc.save(eng, path)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0x40  # one flipped bit mid-archive
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(sc.CorruptCheckpointError, match="s.npz"):
        sc.restore(path)


def test_content_crc_catches_tampered_member_with_stale_checksum(tmp_path):
    """Even an archive the zip layer accepts (re-compressed cleanly) is
    rejected when its content no longer matches the embedded CRC32."""
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=8)
    _tick(eng, 1, 3)
    sc.save(eng, path)
    z = dict(np.load(path))
    z["last_time"] = np.int64(int(z["last_time"]) + 1)  # stale crc32 kept
    np.savez_compressed(path, **z)
    with pytest.raises(sc.CorruptCheckpointError, match="CRC32"):
        sc.restore(path)


def test_restore_names_file_on_truncated_archive(tmp_path):
    path = str(tmp_path / "s.npz")
    eng = FlowStateEngine(capacity=8)
    _tick(eng, 1, 3)
    sc.save(eng, path)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])  # torn write
    with pytest.raises(sc.CorruptCheckpointError, match="s.npz"):
        sc.restore(path)


def test_rotation_keep_n_and_resolve_latest(tmp_path):
    d = str(tmp_path / "rot")
    eng = FlowStateEngine(capacity=16)
    paths = []
    for t in (1, 2, 3, 4, 5):
        _tick(eng, t, 4)
        paths.append(sc.save_rotating(eng, d, tick=t, keep=2)[0])
    names = sorted(os.listdir(d))
    assert names == ["ckpt-000000004.npz", "ckpt-000000005.npz"]
    assert sc.resolve_latest(d) == sc.checkpoint_path(d, 5)


def test_resolve_latest_rolls_back_past_corrupt_newest(tmp_path):
    d = str(tmp_path / "rot")
    eng = FlowStateEngine(capacity=16)
    _tick(eng, 1, 4)
    sc.save_rotating(eng, d, tick=1, keep=3)
    _tick(eng, 2, 4)
    newest, _ = sc.save_rotating(eng, d, tick=2, keep=3)
    blob = open(newest, "rb").read()
    with open(newest, "wb") as f:
        f.write(blob[: len(blob) // 3])  # torn newest
    assert sc.resolve_latest(d) == sc.checkpoint_path(d, 1)
    r = sc.restore(d)  # directory restore resolves + rolls back
    assert r.num_flows() == 4


def test_restore_missing_entries_clear_error(tmp_path):
    """A structurally valid npz that isn't a complete serving checkpoint
    must name the file and what's missing, not die on a bare KeyError."""
    path = str(tmp_path / "s.npz")
    data = {"format_version": np.int64(sc.FORMAT_VERSION)}
    data["crc32"] = np.uint32(sc._content_crc(data))
    np.savez_compressed(path, **data)
    with pytest.raises(sc.CorruptCheckpointError, match="missing"):
        sc.restore(path)


def test_save_rotating_sweeps_orphaned_temps(tmp_path):
    """A SIGKILL mid-write can't run the temp cleanup; the next rotation
    save collects the orphan (pruning only matches ckpt-*.npz)."""
    d = tmp_path / "rot"
    d.mkdir()
    orphan = d / ".ckpt-000000001.npz.tmp.12345"
    orphan.write_bytes(b"torn by a kill")
    eng = FlowStateEngine(capacity=8)
    _tick(eng, 1, 3)
    sc.save_rotating(eng, str(d), tick=2, keep=2)
    assert sorted(os.listdir(d)) == ["ckpt-000000002.npz"]


def test_rotation_serializes_concurrent_save_and_prune(tmp_path):
    """The rotation race the drift retrainer exposed: two in-process
    writers rotating the same directory could interleave — writer B's
    sweep_stale_tmp collecting writer A's in-flight temp as an
    'orphan', or B's keep-N prune (listed pre-commit) unlinking A's
    just-committed member. The per-directory rotation lock serializes
    whole passes: while A is mid-save, B's pass (sweep + save + prune)
    must BLOCK, and both checkpoints must commit."""
    import threading

    d = str(tmp_path / "rot")
    eng_a = FlowStateEngine(capacity=16)
    _tick(eng_a, 1, 4)
    eng_b = FlowStateEngine(capacity=16)
    _tick(eng_b, 1, 4)

    in_save = threading.Event()
    release = threading.Event()
    real_save = sc.save

    def slow_save(engine, path, feature_reference=None):
        # only writer A (tick 5) pauses mid-rotation; writer B's save
        # runs untouched so the test can't deadlock on the patch
        if path.endswith("ckpt-000000005.npz"):
            in_save.set()
            assert release.wait(timeout=30)
        return real_save(engine, path, feature_reference)

    done_b = threading.Event()
    results = {}

    def writer_a():
        results["a"] = sc.save_rotating(eng_a, d, tick=5, keep=2)

    def writer_b():
        results["b"] = sc.save_rotating(eng_b, d, tick=6, keep=2)
        done_b.set()

    orig = sc.save
    sc.save = slow_save
    try:
        ta = threading.Thread(target=writer_a, daemon=True)
        ta.start()
        assert in_save.wait(timeout=30)  # A is mid-rotation
        tb = threading.Thread(target=writer_b, daemon=True)
        tb.start()
        # B must be BLOCKED on the rotation lock while A is mid-save —
        # without the lock it would race straight through (and its
        # sweep would have collected A's temp)
        assert not done_b.wait(timeout=0.3)
        release.set()
        ta.join(timeout=30)
        assert done_b.wait(timeout=30)
        tb.join(timeout=30)
    finally:
        sc.save = orig
        release.set()
    # both passes committed; the interleaving lost nothing
    assert sorted(os.listdir(d)) == [
        "ckpt-000000005.npz", "ckpt-000000006.npz"
    ]
    assert sc.resolve_latest(d) == sc.checkpoint_path(d, 6)
    sc.validate(results["a"][0])
    sc.validate(results["b"][0])


def test_v1_checkpoint_reports_old_format_not_corruption(tmp_path):
    """A genuine pre-checksum (v1) file has no crc32 entry; it must be
    diagnosed as old-format, not accused of corruption."""
    path = str(tmp_path / "v1.npz")
    np.savez_compressed(path, format_version=np.int64(1),
                        capacity=np.int64(8))
    with pytest.raises(ValueError, match="format 1"):
        sc.validate(path)
    with pytest.raises(ValueError, match="format 1"):
        sc.restore(path)


def test_resolve_latest_empty_or_missing_dir(tmp_path):
    assert sc.resolve_latest(str(tmp_path)) is None
    assert sc.resolve_latest(str(tmp_path / "nope")) is None
    with pytest.raises(sc.CorruptCheckpointError, match="no valid"):
        sc.restore(str(tmp_path))
